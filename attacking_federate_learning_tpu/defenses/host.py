"""Host-BLAS defense kernels for the CPU backend.

Backend-aware kernel dispatch: on TPU the Krum/Bulyan distance engine is an
MXU Gram matmul (ops/distances.py, ops/pallas_distances.py), but XLA:CPU's
single-threaded gemm and sort are ~2x slower than the host's native BLAS on
this class of machine (measured: 433 ms XLA:CPU vs 226 ms OpenBLAS for the
(512, 79510) Gram).  So when the active backend is CPU the defense kernels
route the whole aggregation to these NumPy/BLAS implementations via
``jax.pure_callback`` (defenses/kernels.py ``distance_impl='host'``),
exactly like any production framework picks a different kernel per backend.

Semantics are identical to the reference variants (reference
defences.py:16-70, SURVEY.md §2.4 #4-6) and to the XLA kernels: Krum scores
sum the ``users_count - corrupted_count`` smallest distances (sum of a set,
so ``np.partition`` replaces the full row sort without changing the value);
ties resolve to the lowest index (first-occurrence ``np.argmin``, matching
reference defences.py:35); Bulyan's pool shrinks per selection while f stays
fixed.  Unlike defenses/oracle.py (a deliberately naive test oracle), this
module is a production path and is itself verified against the oracle in
tests/test_defenses.py.
"""

from __future__ import annotations

import numpy as np


def host_sq_distances(G: np.ndarray) -> np.ndarray:
    """(n, d) f32 -> (n, n) squared Euclidean distances, +inf diagonal.

    One BLAS Gram matmul + in-place epilogue — the same
    ``||g_i||^2 + ||g_j||^2 - 2 G G^T`` decomposition as the XLA kernel
    (ops/distances.py), so both paths compute identical values to f32
    tolerance.  The squared norms are read off the Gram diagonal (they ARE
    the diagonal), saving a full O(n d) pass, and the epilogue mutates the
    Gram buffer so no second n^2 array is allocated."""
    gram = G @ G.T
    sq = gram.diagonal().copy()
    gram *= -2.0
    gram += sq[:, None]
    gram += sq[None, :]
    np.maximum(gram, 0.0, out=gram)
    np.fill_diagonal(gram, np.inf)
    return gram


def host_pairwise_distances(G: np.ndarray) -> np.ndarray:
    """(n, d) f32 -> (n, n) Euclidean distances with +inf diagonal."""
    d2 = host_sq_distances(G)
    D = np.sqrt(d2, out=d2)
    np.fill_diagonal(D, np.inf)  # sqrt(inf) is inf, but keep it explicit
    return D


def _prefix_scores(sortedD, order, finite, alive, pool, f,
                   paper_scoring=False):
    """Sum of the k smallest alive distances per row, evaluated as an
    alive-masked rank prefix over presorted rows (same presort-once
    scheme as the XLA Bulyan, defenses/kernels.py); +inf for dead rows.
    k = pool - f, or pool - f - 2 under paper scoring (SURVEY.md §2.4
    #4)."""
    k = pool - f - (2 if paper_scoring else 0)
    alive_cols = alive[order]
    rank = np.cumsum(alive_cols, axis=1)
    take = alive_cols & (rank <= k) & finite
    scores = np.where(take, sortedD, 0.0).sum(axis=1)
    scores[~alive] = np.inf
    return scores


def host_krum_index(G, users_count, corrupted_count, paper_scoring=False):
    """Krum winner index (reference defences.py:23-42 semantics,
    ``return_index=True`` shape).

    Selection of the k nearest peers happens on *squared* distances
    (monotone in the true distance), so the sqrt runs only over the n*k
    selected entries instead of the full n^2 matrix; the score itself sums
    the square-rooted values, identical to the reference's norm sum."""
    G = np.asarray(G, np.float32)
    n = G.shape[0]
    d2 = host_sq_distances(G)
    k = users_count - corrupted_count - (2 if paper_scoring else 0)
    k = max(min(k, n - 1), 0)
    if k == 0:
        return 0
    part = np.partition(d2, k - 1, axis=1)[:, :k]
    scores = np.sqrt(part, out=part).sum(axis=1)
    return int(np.argmin(scores))


def host_krum(G, users_count, corrupted_count, paper_scoring=False):
    """Krum winner row."""
    G = np.asarray(G, np.float32)
    return G[host_krum_index(G, users_count, corrupted_count,
                             paper_scoring=paper_scoring)]


def _all_finite(a: np.ndarray) -> bool:
    """Full-finiteness check without materializing an (n, d) bool temp
    (420 MB at the 10k north-star tail): two scalar reductions — NaN
    propagates through min/max, ±inf is its own extremum."""
    return bool(np.isfinite(a.min()) and np.isfinite(a.max()))


def host_median(sel: np.ndarray):
    """Coordinate-wise median (defenses/median.py host path): the native
    column-blocked kernel when available AND the input is fully finite
    (std::nth_element on NaN is undefined behavior, and np.median's
    NaN-propagation must be preserved); np.median otherwise."""
    sel = np.asarray(sel, np.float32)
    if sel.size and _all_finite(sel):
        from attacking_federate_learning_tpu.native import native_median
        out = native_median(sel)
        if out is not None:
            return out
    return np.median(sel, axis=0).astype(np.float32)


def host_trimmed_mean_of(sel: np.ndarray, number_to_consider: int):
    """Median-anchored trimmed mean (reference defences.py:48-51), stable
    order on |deviation| to match Python's stable ``sorted``.

    Dispatches to the native column-blocked kernel
    (native/bulyan_select.cpp:fl_trimmed_mean) when available — the
    NumPy axis-0 formulation pays strided access across the whole (n, d)
    matrix for median/sort/masks, ~105 s at the exact-Bulyan 10k tail
    where the native kernel takes seconds.  Identical semantics
    (boundary ties keep the lowest row indices), pinned by
    tests/test_defenses.py::test_host_trimmed_mean_partition_matches_stable_sort."""
    sel = np.asarray(sel, np.float32)
    k = int(number_to_consider)
    if 0 < k <= sel.shape[0] and sel.size and _all_finite(sel):
        from attacking_federate_learning_tpu.native import (
            native_trimmed_mean
        )
        out = native_trimmed_mean(sel, k)
        if out is not None:
            return out
    med = np.median(sel, axis=0)
    dev = sel - med
    order = np.argsort(np.abs(dev), axis=0, kind="stable")
    kept = np.take_along_axis(dev, order[:k], axis=0)
    return (kept.mean(axis=0) + med).astype(np.float32)


def numpy_bulyan_selection(D, order, users_count, corrupted_count,
                           set_size, batch_select=1, paper_scoring=False):
    """Reference NumPy selection loop: presort-once, alive-masked rank
    prefixes, O(n^2) scoring per trip.  Kept as the semantic anchor and
    the fallback when the native kernel is unavailable."""
    n = D.shape[0]
    f = corrupted_count
    q = min(max(int(batch_select), 1), set_size)
    sortedD = np.take_along_axis(D, order, axis=1)
    finite = np.isfinite(sortedD)
    alive = np.ones(n, bool)
    selected = []
    while len(selected) < set_size:
        r = min(q, set_size - len(selected))
        scores = _prefix_scores(sortedD, order, finite, alive,
                                users_count - len(selected), f,
                                paper_scoring=paper_scoring)
        idxs = np.argsort(scores, kind="stable")[:r]
        selected.extend(int(i) for i in idxs)
        alive[idxs] = False
    return np.asarray(selected, np.int32)


def host_bulyan_selection(D, users_count, corrupted_count, set_size,
                          batch_select=1, paper_scoring=False):
    """Selected client indices, in selection order.

    Dispatches to the native incremental kernel
    (native/bulyan_select.cpp — O(n^2) total instead of O(n^2) *per
    selection*, which is what makes exact q=1 tractable at n=10,240)
    and falls back to :func:`numpy_bulyan_selection`.  Both produce the
    same selection: the scores are alive-prefix sums over each presorted
    row, invariant to tie order inside the sort (equal values are
    interchangeable within the prefix), and selection ties resolve to
    the lowest client index in both."""
    order = np.argsort(D, axis=1).astype(np.int32, copy=False)
    from attacking_federate_learning_tpu.native import (
        native_bulyan_selection
    )
    sel = native_bulyan_selection(D, order, users_count, corrupted_count,
                                  set_size, batch_select=batch_select,
                                  paper_scoring=paper_scoring)
    if sel is None:
        sel = numpy_bulyan_selection(D, order, users_count,
                                     corrupted_count, set_size,
                                     batch_select=batch_select,
                                     paper_scoring=paper_scoring)
    return sel


def host_bulyan(G, users_count, corrupted_count, paper_scoring=False,
                batch_select=1):
    """Bulyan (reference defences.py:55-70): iterative Krum selection with
    a shrinking pool, then trimmed mean with parameter 2f.

    ``batch_select=q`` mirrors the XLA kernel's flagged relaxation
    (defenses/kernels.py:bulyan): each trip takes the q lowest-scoring
    alive clients against the same scores (ties to the lowest index,
    matching both first-occurrence ``np.argmin`` and ``lax.top_k``),
    re-scoring between trips.  q=1 is reference-exact — and with the
    native incremental kernel it is also *fast* at 10k clients, so q=1
    stays the host default at every scale."""
    G = np.asarray(G, np.float32)
    f = corrupted_count
    set_size = users_count - 2 * f
    D = host_pairwise_distances(G)
    selected = host_bulyan_selection(D, users_count, f, set_size,
                                     batch_select=batch_select,
                                     paper_scoring=paper_scoring)
    sel = G[selected]
    return host_trimmed_mean_of(sel, set_size - 2 * f - 1)
