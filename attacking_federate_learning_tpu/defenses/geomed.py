"""Geometric-median aggregation (RFA: Pillutla, Kakade, Harchaoui,
IEEE TSP 2022) via the smoothed Weiszfeld iteration.

Beyond-reference addition: the geometric median minimizes
``sum_i ||z - g_i||`` and tolerates up to half the cohort arbitrarily
corrupted — a stronger estimator than the coordinate-wise median the
companion module implements.  The smoothed Weiszfeld update

    w_i = 1 / max(eps, ||z - g_i||);  z <- sum_i w_i g_i / sum_i w_i

runs a fixed number of iterations in a ``lax.fori_loop`` (static shapes,
one jit), entirely in matrix-vector ops that shard over the model axis.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from attacking_federate_learning_tpu.defenses.kernels import DEFENSES

_ITERS = 10
_EPS = 1e-6


@DEFENSES.register("GeoMedian")
def geometric_median(users_grads, users_count, corrupted_count,
                     iters: int = _ITERS, eps: float = _EPS,
                     telemetry=False):
    """``telemetry=True`` additionally returns ``{'dist_to_agg': (n,)}``
    — each client's distance to the geometric median (the Weiszfeld
    weights are 1/dist, so this is the influence view)."""
    G = users_grads.astype(jnp.float32)

    def step(_, z):
        dist = jnp.linalg.norm(G - z[None, :], axis=1)
        w = 1.0 / jnp.maximum(dist, eps)
        return (w @ G) / jnp.sum(w)

    z0 = jnp.mean(G, axis=0)
    z = lax.fori_loop(0, iters, step, z0)
    if not telemetry:
        return z
    return z, {"dist_to_agg": jnp.linalg.norm(G - z[None, :], axis=1)}
