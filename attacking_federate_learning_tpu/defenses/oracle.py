"""NumPy oracle implementations of the defenses, for testing only.

Independent array-based re-derivations of the reference semantics
(reference defences.py:13-70), used by tests/test_defenses.py to verify the
XLA kernels.  Written against the *behavior* documented in SURVEY.md §2.4
(n-f Krum scoring, median-anchored trim, shrinking-pool Bulyan); kept
deliberately simple and loop-free where possible so a bug here is unlikely
to coincide with a bug in the kernels.
"""

from __future__ import annotations

import numpy as np


def np_pairwise_distances(G):
    diffs = G[:, None, :] - G[None, :, :]
    return np.linalg.norm(diffs, axis=-1)


def np_no_defense(G, users_count, corrupted_count):
    return np.mean(G, axis=0)


def np_krum_select(G, users_count, corrupted_count, alive=None, D=None):
    """Index of the Krum winner among alive users."""
    n = G.shape[0]
    if D is None:
        D = np_pairwise_distances(G)
    if alive is None:
        alive = np.ones(n, bool)
    k = users_count - corrupted_count
    best_idx, best_err = -1, np.inf
    for i in range(n):
        if not alive[i]:
            continue
        others = [D[i, j] for j in range(n) if j != i and alive[j]]
        err = float(np.sum(np.sort(others)[:k]))
        if err < best_err:
            best_err, best_idx = err, i
    return best_idx


def np_krum(G, users_count, corrupted_count):
    return G[np_krum_select(G, users_count, corrupted_count)]


def np_trimmed_mean(G, users_count, corrupted_count):
    keep = G.shape[0] - corrupted_count - 1
    med = np.median(G, axis=0)
    dev = G - med
    order = np.argsort(np.abs(dev), axis=0, kind="stable")
    kept = np.take_along_axis(dev, order[:keep], axis=0)
    return np.mean(kept, axis=0) + med


def np_bulyan(G, users_count, corrupted_count):
    n = G.shape[0]
    f = corrupted_count
    set_size = users_count - 2 * f
    D = np_pairwise_distances(G)
    alive = np.ones(n, bool)
    selected = []
    while len(selected) < set_size:
        idx = np_krum_select(G, users_count - len(selected), f,
                             alive=alive, D=D)
        selected.append(idx)
        alive[idx] = False
    return np_trimmed_mean(G[selected], set_size, 2 * f)


NP_DEFENSES = {
    "NoDefense": np_no_defense,
    "Krum": np_krum,
    "TrimmedMean": np_trimmed_mean,
    "Bulyan": np_bulyan,
}
