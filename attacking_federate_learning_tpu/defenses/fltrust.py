"""FLTrust-style validation-data defense.

The reference ships a half-built hook for exactly this: every client
contributes a stratified ~11% metadata sample (reference user.py:63-66), the
server concatenates them (server.py:62-77) — and then never consumes the
result (SURVEY.md §2 C12).  This module completes the hook following the
FLTrust recipe (Cao et al., NDSS'21): the server computes its own gradient
g0 on the trusted metadata pool, scores each client gradient by clipped
cosine similarity

    ts_i = relu(cos(g_i, g0))

re-scales every client gradient to ||g0||, and returns the trust-weighted
average.  A gradient pointing away from the server's direction (e.g. an
ALIE drift) earns zero weight.

Unlike the statistical defenses, this one needs round context (the server
gradient); the engine provides it when a registered defense carries
``needs_server_grad = True``.
"""

from __future__ import annotations

import jax.numpy as jnp

from attacking_federate_learning_tpu.defenses.kernels import DEFENSES


def fltrust(users_grads, users_count, corrupted_count, server_grad=None,
            telemetry=False):
    """``telemetry=True`` additionally returns ``{'trust_scores': (n,)
    relu-clipped trust weights, 'cosine': (n,) raw cosine to the server
    gradient, 'server_grad_norm': ()}`` — the per-client trust the
    weighted average actually used."""
    assert server_grad is not None, "FLTrust requires the server gradient"
    g0 = server_grad
    g0_norm = jnp.linalg.norm(g0)
    gi_norm = jnp.linalg.norm(users_grads, axis=1)
    eps = 1e-12
    cos = (users_grads @ g0) / (gi_norm * g0_norm + eps)
    ts = jnp.maximum(cos, 0.0)                      # relu-clipped trust
    scaled = users_grads * (g0_norm / (gi_norm + eps))[:, None]
    agg = (ts @ scaled) / (jnp.sum(ts) + eps)
    if not telemetry:
        return agg
    return agg, {"trust_scores": ts, "cosine": cos,
                 "server_grad_norm": g0_norm}


fltrust.needs_server_grad = True
DEFENSES.register("FLTrust", fltrust)
