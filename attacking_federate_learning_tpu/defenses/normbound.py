"""Norm-bounding defense (Sun et al., "Can You Really Backdoor Federated
Learning?", 2019).

Beyond-reference addition targeted at the reference's own backdoor attack:
every client update is clipped to the cohort's median L2 norm before
averaging, so a crafted gradient cannot out-weigh honest ones however it
is scaled — the canonical mitigation for model-replacement/backdoor
submissions.  One norm per row + a broadcast scale: fully vectorized,
shards over both mesh axes.
"""

from __future__ import annotations

import jax.numpy as jnp

from attacking_federate_learning_tpu.defenses.kernels import DEFENSES


@DEFENSES.register("NormBound")
def norm_bounded_mean(users_grads, users_count, corrupted_count,
                      telemetry=False):
    """``telemetry=True`` additionally returns ``{'clip_scale': (n,),
    'clipped_count': () int32, 'norm_bound': () the cohort-median bound}``
    — which clients the norm clip actually touched this round."""
    G = users_grads.astype(jnp.float32)
    norms = jnp.linalg.norm(G, axis=1)
    bound = jnp.median(norms)
    scale = jnp.minimum(1.0, bound / jnp.maximum(norms, 1e-12))
    agg = jnp.mean(G * scale[:, None], axis=0)
    if not telemetry:
        return agg
    return agg, {"clip_scale": scale,
                 "clipped_count": jnp.sum(scale < 1.0).astype(jnp.int32),
                 "norm_bound": bound}
