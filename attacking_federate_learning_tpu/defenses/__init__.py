from attacking_federate_learning_tpu.defenses.kernels import (  # noqa: F401
    DEFENSES, bulyan, check_defense_args, krum, no_defense, trimmed_mean
)
from attacking_federate_learning_tpu.defenses.fltrust import fltrust  # noqa: F401
from attacking_federate_learning_tpu.defenses.median import median  # noqa: F401
from attacking_federate_learning_tpu.defenses.geomed import (  # noqa: F401
    geometric_median
)
from attacking_federate_learning_tpu.defenses.normbound import (  # noqa: F401
    norm_bounded_mean
)
from attacking_federate_learning_tpu.defenses.dnc import dnc  # noqa: F401,E402
from attacking_federate_learning_tpu.defenses.centeredclip import (  # noqa: F401,E402
    centered_clip
)
