from attacking_federate_learning_tpu.defenses.kernels import (  # noqa: F401
    DEFENSES, bulyan, check_defense_args, krum, no_defense, trimmed_mean
)
from attacking_federate_learning_tpu.defenses.fltrust import fltrust  # noqa: F401
from attacking_federate_learning_tpu.defenses.median import median  # noqa: F401
