"""Coordinate-wise median defense (Yin et al., ICML'18 — the companion
estimator to the trimmed mean the reference implements at
defences.py:44-52; the reference itself ships only the trimmed variant).

One jnp.median along the client axis: robust to up to half the clients per
coordinate, no selection state, fully shardable over the model axis.
"""

from __future__ import annotations

import jax.numpy as jnp

from attacking_federate_learning_tpu.defenses.kernels import DEFENSES


@DEFENSES.register("Median")
def median(users_grads, users_count, corrupted_count, impl="xla",
           telemetry=False, mask=None, weights=None, margins=False,
           numerics=False):
    """``impl='host'`` (opt-in, config ``median_impl``) routes to the
    native column-blocked kernel (native/bulyan_select.cpp:fl_median) —
    same rationale and same non-auto-dispatch rule as
    kernels.py:trimmed_mean.  ``impl='pallas'`` (config
    ``aggregation_impl='pallas'``) is the on-device tiled kernel
    (ops/pallas_defense.py) — the masked/weighted variants replicate
    kernels.masked_median bit for bit (pinned, tests/test_pallas.py).

    ``telemetry=True`` additionally returns ``{'dist_to_agg': (n,)}`` —
    each client's L2 distance to the aggregated median vector, the
    outlier view a coordinate-wise estimator admits (both impls: the
    distance is computed from the returned aggregate).

    ``mask`` (the quarantine seam, core/faults.py): the median of the
    alive rows only (kernels.py:masked_median — fixed shapes, traced
    alive count).

    ``weights`` (the staleness seam, core/async_rounds.py — requires
    ``mask``): the weighted lower median, the value where cumulative
    weight crosses half the mass (kernels.py:masked_median).

    ``margins=True`` (requires ``telemetry=True``; ISSUE 18)
    additionally returns ``margin_kept_frac``/``margin_boundary_dist``
    (utils/margins.py:median_pick_margins) — each row's pick mass
    from the exact rank membership of the median (so the picked values
    reconstruct the aggregate) and its inside-positive proximity to
    the rank-derived median.  Pure-XLA rank ops independent of
    ``impl``, so the pallas route gets bit-identical margins; the
    off-device host kernel raises.

    ``numerics=True`` (requires ``margins=True``; ISSUE 20)
    additionally returns ``num_tie_rows`` () int32 — boundary
    distances within TIE_BAND_ULPS ulp of the median pick, banded at
    the input's largest finite magnitude (utils/numerics.py)."""
    from attacking_federate_learning_tpu.defenses.kernels import (
        check_margin_seam, check_numerics_seam, check_weight_seam
    )
    check_weight_seam(mask, weights)
    check_margin_seam(margins, telemetry)
    check_numerics_seam(numerics, margins)
    if margins and impl == "host":
        raise ValueError(
            "Median margins need the on-device ranks; impl='host' "
            "returns only the aggregate (defenses/host.py)")

    def margin_fields():
        from attacking_federate_learning_tpu.utils.margins import (
            median_pick_margins
        )
        mf = median_pick_margins(users_grads, mask=mask, weights=weights)
        if numerics:
            from attacking_federate_learning_tpu.utils.numerics import (
                max_finite_abs, tie_proximity
            )
            key = users_grads if mask is None else jnp.where(
                mask[:, None], users_grads, jnp.inf)
            mf["num_tie_rows"] = tie_proximity(
                mf["margin_boundary_dist"], max_finite_abs(key))
        return mf

    if mask is not None:
        if impl == "host":
            raise ValueError(
                "mask-aware Median has no host kernel "
                "(defenses/host.py is maskless); use impl='xla'")
        if impl == "pallas":
            from attacking_federate_learning_tpu.ops.pallas_defense import (
                pallas_masked_median
            )
            agg = pallas_masked_median(users_grads, mask, weights=weights,
                                       weighted=weights is not None)
        else:
            from attacking_federate_learning_tpu.defenses.kernels import (
                masked_median
            )
            agg = masked_median(users_grads, mask, weights=weights)
        if not telemetry:
            return agg
        G = users_grads.astype(jnp.float32)
        dist = jnp.linalg.norm(G - agg.astype(jnp.float32)[None, :],
                               axis=1)
        diag = {"dist_to_agg": dist}
        if margins:
            diag.update(margin_fields())
        return agg, diag
    if impl == "host":
        from attacking_federate_learning_tpu.defenses.host import (
            host_median
        )
        from attacking_federate_learning_tpu.defenses.kernels import (
            host_coordwise
        )
        agg = host_coordwise(host_median, users_grads)
    elif impl == "pallas":
        from attacking_federate_learning_tpu.ops.pallas_defense import (
            pallas_median_of
        )
        agg = pallas_median_of(users_grads)
    else:
        agg = jnp.median(users_grads, axis=0)
    if not telemetry:
        return agg
    G = users_grads.astype(jnp.float32)
    dist = jnp.linalg.norm(G - agg.astype(jnp.float32)[None, :], axis=1)
    diag = {"dist_to_agg": dist}
    if margins:
        diag.update(margin_fields())
    return agg, diag
