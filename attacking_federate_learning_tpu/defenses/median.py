"""Coordinate-wise median defense (Yin et al., ICML'18 — the companion
estimator to the trimmed mean the reference implements at
defences.py:44-52; the reference itself ships only the trimmed variant).

One jnp.median along the client axis: robust to up to half the clients per
coordinate, no selection state, fully shardable over the model axis.
"""

from __future__ import annotations

import jax.numpy as jnp

from attacking_federate_learning_tpu.defenses.kernels import DEFENSES


@DEFENSES.register("Median")
def median(users_grads, users_count, corrupted_count, impl="xla"):
    """``impl='host'`` (opt-in, config ``median_impl``) routes to the
    native column-blocked kernel (native/bulyan_select.cpp:fl_median) —
    same rationale and same non-auto-dispatch rule as
    kernels.py:trimmed_mean."""
    if impl == "host":
        from attacking_federate_learning_tpu.defenses.host import (
            host_median
        )
        from attacking_federate_learning_tpu.defenses.kernels import (
            host_coordwise
        )
        return host_coordwise(host_median, users_grads)
    return jnp.median(users_grads, axis=0)
