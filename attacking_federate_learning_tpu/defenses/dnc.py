"""DnC — divide-and-conquer spectral defense (Shejwalkar & Houmansadr,
NDSS'21, the companion defense to the min-max/min-sum attacks in
attacks/minmax.py).

Beyond-reference addition.  Each of ``n_iters`` rounds: subsample a random
sketch of coordinates, center the cohort there, take the top singular
direction of the centered sketch (power iteration — cheap, static-shape,
jit-native), score every client by its squared projection, and mark the
``filter_frac * f`` highest-scoring clients as outliers.  A client survives
only if NO iteration marked it; the aggregate is the mean of survivors
(falling back to the overall mean if the intersection empties — possible
at small cohorts).

Sketch keys derive deterministically from (seed, round, iteration): the
engine feeds the round index through the ``needs_round`` seam (the same
attribute convention FLTrust uses for ``needs_server_grad``), so every
round sees FRESH coordinate subsets — the paper's subsampling assumption —
while runs still reproduce exactly (SURVEY.md §2.4 #13).  When the sketch
covers all of d, scores are permutation-invariant, so a single iteration
suffices and the others are skipped.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from attacking_federate_learning_tpu.defenses.kernels import DEFENSES

_N_ITERS = 5
_FILTER_FRAC = 1.5
_SKETCH_DIM = 2048
_POWER_STEPS = 10


def _top_direction(Sc, key):
    """Dominant right singular vector of the centered sketch via power
    iteration on Sc^T Sc (r-dim; never materializes the r x r Gram).

    The iterate starts from a key-derived random vector, not a constant:
    a fixed init lets a defense-aware adversary craft gradients whose
    dominant direction is orthogonal to it, stalling convergence toward
    a lesser direction; a random init has measure-zero overlap failure."""
    r = Sc.shape[1]
    v = jax.random.normal(key, (r,), Sc.dtype)
    v = v / jnp.maximum(jnp.linalg.norm(v), 1e-12)
    for _ in range(_POWER_STEPS):
        v = Sc.T @ (Sc @ v)
        v = v / jnp.maximum(jnp.linalg.norm(v), 1e-12)
    return v


@DEFENSES.register("DnC")
def dnc(users_grads, users_count, corrupted_count, n_iters: int = _N_ITERS,
        filter_frac: float = _FILTER_FRAC, sketch_dim: int = _SKETCH_DIM,
        seed: int = 0, round=0, telemetry=False):
    """``telemetry=True`` additionally returns ``{'survivor_mask': (n,)
    f32 0/1 — clients no iteration marked as outliers, 'survivor_count':
    () int32}``."""
    G = users_grads.astype(jnp.float32)
    n, d = G.shape
    # Outliers removed per iteration; capped so at least one client can
    # survive every iteration.
    remove = min(int(filter_frac * corrupted_count), n - 1)
    if remove == 0:
        agg = jnp.mean(G, axis=0)
        if not telemetry:
            return agg
        return agg, {"survivor_mask": jnp.ones((n,), jnp.float32),
                     "survivor_count": jnp.asarray(n, jnp.int32)}
    keep = n - remove
    r = min(sketch_dim, d)
    if r == d:
        # Full-coverage sketch: every iteration sees the same matrix, and
        # power iteration converges to the same dominant direction from
        # any (random) init — one iteration suffices.
        n_iters = 1
    base_key = jax.random.fold_in(jax.random.key(seed ^ 0xD0C),
                                  jnp.asarray(round, jnp.int32))

    good = jnp.ones((n,), bool)
    for i in range(n_iters):
        k_idx, k_pow = jax.random.split(jax.random.fold_in(base_key, i))
        if r == d:
            S = G
        else:
            idx = jax.random.choice(k_idx, d, (r,), replace=False)
            S = G[:, idx]
        Sc = S - jnp.mean(S, axis=0)[None, :]
        v = _top_direction(Sc, k_pow)
        scores = (Sc @ v) ** 2
        # Clients whose score ranks within the keep smallest survive
        # this iteration.
        _, keep_idx = lax.top_k(-scores, keep)
        good = good & jnp.zeros((n,), bool).at[keep_idx].set(True)

    w = good.astype(jnp.float32)
    survivors = jnp.sum(w)
    survivor_mean = (w @ G) / jnp.maximum(survivors, 1.0)
    # Empty intersection (possible at small n): overall mean, not zeros.
    agg = jnp.where(survivors > 0, survivor_mean, jnp.mean(G, axis=0))
    if not telemetry:
        return agg
    return agg, {"survivor_mask": w,
                 "survivor_count": survivors.astype(jnp.int32)}


# Engine seam: pass the round index so sketches refresh every round
# (core/engine.py:_aggregate_impl).
dnc.needs_round = True
