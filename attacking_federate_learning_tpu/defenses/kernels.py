"""Robust-aggregation kernels as compiled XLA.

Each defense is a pure function ``(users_grads (n, d), users_count,
corrupted_count) -> aggregated (d,)`` — the same contract as the reference's
registry (reference defences.py:73-75) — but vectorized over the client axis
instead of Python loops:

- Krum's O(n^2 * d) pairwise-distance dict (reference defences.py:16-21)
  becomes one Gram matmul (ops/distances.py) + a top_k reduction.
- TrimmedMean's per-coordinate Python loop (reference defences.py:44-52)
  becomes a stable argsort along the client axis + masked mean.
- Bulyan's destructive dict-popping selection loop (reference
  defences.py:55-70) becomes a fixed-trip ``lax.fori_loop`` over a static
  distance matrix with a boolean alive-mask, so shapes never change and jit
  compiles once.

Telemetry seam: every registered defense accepts ``telemetry=False``.
With it off (the default) the function returns the aggregated ``(d,)``
vector through the exact pre-telemetry code path — same compiled HLO, bit
for bit.  With it on it returns ``(aggregated, diagnostics)``, where the
diagnostics are a SMALL, FIXED-SHAPE pytree of device arrays (selection
masks and score vectors for Krum/Bulyan, per-client kept fractions for
the trimmed mean, clip scales/counts, trust scores, ...) that the engine
threads out of the fused round program as auxiliary jit outputs
(core/engine.py) — never via host callbacks.  ``telemetry`` is a Python
bool, so the branch resolves at trace time and the off path stays
untouched.  Host-engine variants that only return an aggregate (no
scores) fill their score slots with NaN — fixed shapes, explicit "not
measured".

Semantics match the reference's exact variants, quirks included
(SURVEY.md §2.4 #4-6): Krum scores sum the (users_count - corrupted_count)
*smallest* distances, not the paper's n-f-2 (reference defences.py:26,
33-34); TrimmedMean is the median-anchored variant keeping the
n-f-1 values closest to the median (defences.py:45, :50-51); Bulyan's
inner Krum runs with users_count shrinking per selection while
corrupted_count stays fixed (defences.py:62), and its final trim parameter
is 2f (defences.py:70).  Ties resolve to the lowest index, matching
``current_error < minimal_error`` (defences.py:35) and first-occurrence
``np.argmin``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from attacking_federate_learning_tpu.ops.distances import pairwise_distances
from attacking_federate_learning_tpu.utils.costs import stage_scope
from attacking_federate_learning_tpu.utils.margins import (
    krum_margins, rank_keep_margins
)
from attacking_federate_learning_tpu.utils.numerics import (
    cancellation_bits, gram_cancellation_bits, max_finite_abs,
    tie_proximity
)
from attacking_federate_learning_tpu.utils.plugins import Registry


DEFENSES = Registry("defense")


def stage_wrapped(fn, stage):
    """Defense-kernel dispatch seam of the stage ledger (utils/costs.py):
    every op a kernel traces carries ``stage`` in its op_name metadata,
    whatever call site invoked it (fused round, hier shard_fn, the
    standalone ``defense_<name>``/``tier2_<name>`` cost-report entries).
    Attribute-transparent: ``needs_round``/``needs_server_grad``/etc.
    survive the wrap — functools.wraps copies ``__dict__`` (where they
    live on both plain kernels and the engine's partials) and tolerates
    partials' missing ``__name__``."""
    @functools.wraps(fn)
    def scoped(*args, **kwargs):
        with stage_scope(stage):
            return fn(*args, **kwargs)

    # Partial introspection (tests reach exp.defense_fn.keywords to pin
    # config wiring) rides through: partial's C-level attrs are not in
    # __dict__, so wraps alone would drop them.
    for attr in ("func", "args", "keywords"):
        if hasattr(fn, attr) and not hasattr(scoped, attr):
            setattr(scoped, attr, getattr(fn, attr))
    return scoped

def check_margin_seam(margins, telemetry):
    """The ``margins=`` seam (ISSUE 18) rides the telemetry diagnostics
    pytree — margins without telemetry has no carrier and is a caller
    bug (core/engine.py always passes telemetry=True when margins are
    on, even with --telemetry off; the engine then filters the
    non-margin diagnostics back out)."""
    if margins and not telemetry:
        raise ValueError(
            "defense margins=True requires telemetry=True (margin "
            "fields ride the diagnostics pytree; utils/margins.py)")


def check_numerics_seam(numerics, margins):
    """The ``numerics=`` seam (ISSUE 20) rides the margin tensors — a
    kernel's tie-proximity counters band the PR 18 margins at k ulp of
    the decision boundary, so numerics without margins has nothing to
    band and is a caller bug (core/engine.py passes margins=True
    whenever kernel numerics are on, even with --margins off, and
    filters the margin fields back out of the event stream)."""
    if numerics and not margins:
        raise ValueError(
            "defense numerics=True requires margins=True (tie counters "
            "band the margin tensors; utils/numerics.py)")


_INF = jnp.inf
# topk cancellation guard: required ratio of a row's kept score mass to
# the complement subtraction's noise floor (eps * log2(n) * rowsum).
# 1e4 keeps the relative score error under ~1e-4 whenever topk is used;
# below that the evaluation falls back to the exact sort path.
_TOPK_GUARD = 1e4


def resolve_distance_impl(distance_impl, users_count=None, users_grads=None):
    """Resolve ``'auto'`` to a concrete distance engine for this backend.

    Backend-aware kernel dispatch (see defenses/host.py): XLA:CPU's
    single-thread gemm/sort lose ~2x to the host's native BLAS, so on an
    *eager* CPU-backend call 'auto' picks 'host' (a zero-copy view + BLAS),
    and 'xla' (MXU Gram matmul) everywhere else.  Traced operands stay on
    'xla': the host path would need a pure_callback whose (n, d) marshal
    costs more than the XLA kernel saves."""
    if distance_impl != "auto":
        return distance_impl
    if isinstance(users_count, jax.core.Tracer) or isinstance(
            users_grads, jax.core.Tracer):
        return "xla"
    return "host" if jax.default_backend() == "cpu" else "xla"


def _distances_for(users_grads, impl, distance_dtype=None):
    """Distance matrix (zero diagonal) via the selected engine.

    ``distance_dtype='bfloat16'``: cast the operand for the distance
    computation ONLY — the Gram rides the MXU at native bf16 throughput
    (vs the ~6-pass f32 HIGHEST emulation) with f32 accumulation and f32
    squared norms (ops/distances.py).  Training numerics are untouched;
    this is a flagged opt-in deviation like the other quirk knobs (off
    by default; the 'host' engine ignores it — host BLAS is f32)."""
    if distance_dtype is not None:
        users_grads = users_grads.astype(jnp.dtype(distance_dtype))
    if impl == "pallas":
        from attacking_federate_learning_tpu.ops.pallas_distances import (
            pallas_pairwise_distances
        )
        if distance_dtype is None:
            # Preserve pre-flag semantics: without an explicit
            # distance_dtype the pallas path always computed f32, even
            # for a bf16 wire matrix (the xla path, by documented
            # contract, rides the wire dtype — ops/distances.py).
            users_grads = users_grads.astype(jnp.float32)
        return pallas_pairwise_distances(users_grads)
    return pairwise_distances(users_grads)


def _host_defense(host_fn, users_grads, users_count, corrupted_count,
                  paper_scoring):
    """Run a row-returning defenses/host.py kernel (Bulyan; Krum goes
    through the scalar-index path in :func:`_host_krum_index`).  n/f must
    be static Python ints.  On a concrete (non-traced) gradient matrix
    this is a zero-copy ``np.asarray`` view plus the host BLAS kernel;
    inside a traced program it falls back to ``pure_callback`` (correct,
    but the callback marshals the full (n, d) operand — ~200 ms at n=512,
    d=79510 — so the engine keeps 'xla' for fused round programs and
    'host' for eager aggregation)."""
    import numpy as np

    n_static, f_static = int(users_count), int(corrupted_count)
    d = users_grads.shape[-1]

    def cb(g):
        return host_fn(np.asarray(g, np.float32), n_static, f_static,
                       paper_scoring=paper_scoring).astype(np.float32)

    if not isinstance(users_grads, jax.core.Tracer):
        return jnp.asarray(cb(users_grads))
    return jax.pure_callback(cb, jax.ShapeDtypeStruct((d,), jnp.float32),
                             users_grads.astype(jnp.float32))


def masked_median(users_grads, mask, weights=None):
    """Median along the client axis over the alive rows only.

    The alive count is data-dependent (traced), but shapes stay fixed:
    dead rows sort to the end (+inf sentinel) and the median gathers
    the middle one/two of the first ``e`` sorted entries with dynamic
    indices.  With an all-true mask this computes exactly
    ``jnp.median`` (same sort, same mean-of-two-middles).

    ``weights`` (the staleness seam, core/async_rounds.py): the
    WEIGHTED lower median — per coordinate, the smallest alive value
    whose cumulative weight reaches half the total weight mass.  With
    equal weights this is the classical lower median (NOT the
    mean-of-two-middles at even counts — the one documented deviation
    of the weighted path; it only runs under
    ``staleness_weight != 'none'``).
    """
    vals = jnp.where(mask[:, None], users_grads, _INF)
    srt = jnp.sort(vals, axis=0)
    if weights is not None:
        order = jnp.argsort(vals, axis=0)
        w = jnp.where(mask, weights, 0.0)
        w_srt = jnp.take_along_axis(
            jnp.broadcast_to(w[:, None], vals.shape), order, axis=0)
        cum = jnp.cumsum(w_srt, axis=0)
        half = jnp.sum(w) / 2.0
        # First sorted row whose cumulative weight reaches half; +inf
        # sentinels carry zero weight so the pick stays alive.
        pick = jnp.argmax(cum >= half, axis=0)
        return jnp.take_along_axis(srt, pick[None, :], axis=0)[0]
    e = jnp.sum(mask).astype(jnp.int32)
    lo = jnp.take(srt, (e - 1) // 2, axis=0)
    hi = jnp.take(srt, e // 2, axis=0)
    return (lo + hi) / 2


def masked_trimmed_mean_of(users_grads, mask, number_to_consider,
                           weights=None):
    """Mask-aware median-anchored trimmed mean (the quarantine seam).

    Same estimator as :func:`trimmed_mean_of` over the alive rows only:
    the anchor is the alive median, dead rows sort last (+inf deviation
    key), and the keep count ``number_to_consider`` may be traced
    (e - f - 1 with e the data-dependent alive count).  Fixed shapes
    throughout; the keep boundary is a rank comparison instead of a
    static slice.

    ``weights`` (the staleness seam, core/async_rounds.py): the TRIM
    stays rank-based and unweighted (robustness semantics — which
    values survive is a question of magnitude, not recency), but the
    kept deviations average with per-row weights, so a stale row's
    surviving coordinates contribute proportionally less.  The median
    anchor stays unweighted.  ``weights=None`` is byte-identical to
    the pre-seam path.
    """
    n = users_grads.shape[0]
    med = masked_median(users_grads, mask)
    dev = users_grads - med[None, :]
    key = jnp.where(mask[:, None], jnp.abs(dev), _INF)
    order = jnp.argsort(key, axis=0, stable=True)   # dead rows last
    sdev = jnp.take_along_axis(dev, order, axis=0)
    # Degenerate cohorts (too many quarantined rows for the trim) keep
    # at least one value instead of dividing by zero — the divergence
    # watchdog, not a NaN aggregate, is the recovery path.
    k = jnp.maximum(number_to_consider, 1)
    keep = jnp.arange(n)[:, None] < k
    if weights is not None:
        w = jnp.where(mask, weights, 0.0)
        w_s = jnp.take_along_axis(
            jnp.broadcast_to(w[:, None], sdev.shape), order, axis=0)
        wk = jnp.where(keep, w_s, 0.0)
        mass = jnp.maximum(jnp.sum(wk, axis=0), 1e-12)
        return jnp.sum(wk * sdev, axis=0) / mass + med
    return jnp.sum(jnp.where(keep, sdev, 0.0), axis=0) / k + med


def population_telemetry(users_grads):
    """Per-client update norms and cosine-to-mean — the population view
    the server can always observe (Bonawitz et al.: the update
    population is the server's only defense signal), independent of
    which defense runs.  Fixed shapes: two (n,) f32 vectors."""
    G = users_grads.astype(jnp.float32)
    norms = jnp.linalg.norm(G, axis=1)
    mean = jnp.mean(G, axis=0)
    cos = (G @ mean) / (norms * jnp.linalg.norm(mean) + 1e-12)
    return {"client_norms": norms, "cosine_to_mean": cos}


@DEFENSES.register("NoDefense")
def no_defense(users_grads, users_count, corrupted_count, telemetry=False,
               mask=None, weights=None, margins=False, numerics=False):
    """Plain FedAvg mean (reference defences.py:13-14).  ``mask`` (the
    quarantine seam, core/faults.py): mean over the alive rows only —
    a zeroed dropout row must not drag the average toward zero.
    ``weights`` (the staleness seam, core/async_rounds.py — requires
    ``mask``): the weighted alive mean ``sum(w_i g_i)/sum(w_i)`` —
    FedBuff's staleness-discounted aggregate.  ``margins=`` is
    accepted and ignored (a mean has no decision boundary to measure;
    config rejects --margins for a NoDefense tier-1, but the tier-2
    ``shard_mean`` wrapper forwards the flag here).  ``numerics=`` is
    likewise accepted and ignored (no decision boundary, no tie band;
    the engine-level health counters cover mean aggregation)."""
    check_weight_seam(mask, weights)
    check_margin_seam(margins, telemetry)
    check_numerics_seam(numerics, margins)
    if weights is not None:
        w = jnp.where(mask, weights, 0.0)
        agg = (w @ users_grads.astype(jnp.float32)) / jnp.maximum(
            jnp.sum(w), 1e-12)
    elif mask is None:
        agg = jnp.mean(users_grads, axis=0)
    else:
        e = jnp.maximum(jnp.sum(mask), 1)
        agg = jnp.sum(jnp.where(mask[:, None], users_grads, 0.0),
                      axis=0) / e
    if not telemetry:
        return agg
    return agg, {}


def _krum_scores(D, users_count, corrupted_count, alive=None,
                 paper_scoring=False, method="sort"):
    """Per-user Krum score: sum of the k smallest distances to other
    (alive) users.  Reference behavior sums k = users_count -
    corrupted_count (reference defences.py:26, 33-34; note the reference
    dict holds no self-distance, which the +inf diagonal reproduces);
    ``paper_scoring`` switches to the NIPS'17 paper's k = n - f - 2
    (SURVEY.md §2.4 #4).

    Two exact evaluation strategies:
    - 'sort': full ascending sort per row + masked prefix sum.
    - 'topk': complement identity.  A row always has exactly k + c
      participating entries where c = f - 1 (+2 under paper scoring) is
      *independent of Bulyan's shrinking pool*, so
      sum-of-k-smallest = rowsum - sum-of-c-largest, and ``lax.top_k`` of
      the small complement replaces the O(n log n)-per-row sort.
    - 'auto': 'topk' when the complement is small relative to n.

    Default is 'sort' — the oracle-verified path.  'topk' is numerically a
    subtraction, so it carries a runtime cancellation guard: with
    kept = rowsum - sum-of-complement, the subtraction's absolute error is
    ~eps * log2(n) * rowsum, so whenever any row's kept mass falls below
    ``_TOPK_GUARD * eps * log2(n) * rowsum`` (relative score error no
    longer <= 1/_TOPK_GUARD-ish) the evaluation falls back to the
    cancellation-free sort path via ``lax.cond`` — one branch executes at
    runtime, so the benign large-n/small-f regime keeps topk's cost while
    adversarial magnitudes (reference malicious.py-scale rows, which
    concentrate the rowsum in the complement) get sort's exactness
    automatically.  Inf/nan rowsums fail the guard explicitly
    (``isfinite(rowsum)`` is part of the reliability predicate), so
    overflow also lands on 'sort'.
    """
    n = D.shape[0]
    # entries per row = pool - 1, k = pool - f (- 2 paper) -> complement is
    # pool-independent: f - 1 (+ 2 under paper scoring).
    complement = corrupted_count - 1 + (2 if paper_scoring else 0)
    if method == "auto":
        method = "topk" if (0 <= complement <= max(n // 4, 1)) else "sort"

    def sort_scores():
        Dm = D + jnp.diag(jnp.full((n,), _INF, D.dtype))
        if alive is not None:
            row_dead = jnp.where(alive, 0.0, _INF)
            Dm = Dm + row_dead[None, :] + row_dead[:, None]
        k = users_count - corrupted_count - (2 if paper_scoring else 0)
        srt = jnp.sort(Dm, axis=1)  # ascending; masked entries land last
        prefix = (jnp.arange(n) < k) & jnp.isfinite(srt)
        return jnp.sum(jnp.where(prefix, srt, 0.0), axis=1)

    if method == "topk" and complement >= 0:
        pair_alive = None
        if alive is not None:
            pair_alive = alive[None, :] & alive[:, None]
        # Bool eye (n² i1, not f32 — 1/4 the bytes of the old distance-
        # diagonal eye) feeding straight into the select/reduce; XLA
        # fuses it into the masked rowsum (no standalone n² buffer in
        # the compiled program — checked via cost facts when the
        # distance-path eye was replaced, tests/test_distance_impl.py).
        mask = ~jnp.eye(n, dtype=bool) if pair_alive is None else (
            pair_alive & ~jnp.eye(n, dtype=bool))
        rowsum = jnp.sum(jnp.where(mask, D, 0.0), axis=1)
        if complement > 0:
            top, _ = lax.top_k(jnp.where(mask, D, -_INF), complement)
            kept = rowsum - jnp.sum(jnp.maximum(top, 0.0), axis=1)
            # Cancellation guard (see docstring): every row's kept mass
            # must clear the subtraction's noise floor, else re-evaluate
            # via the sort path.  Rows whose guard comparison is nan
            # (inf - inf) count as failing.
            eps = jnp.finfo(D.dtype).eps
            floor = (_TOPK_GUARD * eps * max(np.log2(max(n, 2)), 1.0)
                     * rowsum)
            # isfinite(rowsum): an overflowed rowsum gives kept = floor =
            # inf and inf >= inf would pass — overflow must fail the
            # guard, not just nan.
            reliable = jnp.all((kept >= floor) & jnp.isfinite(rowsum))
            scores = lax.cond(reliable, lambda: kept, sort_scores)
        else:
            scores = rowsum
    else:
        scores = sort_scores()
    if alive is not None:
        scores = jnp.where(alive, scores, _INF)
    return scores


def _pallas_krum_scores_guarded(users_grads, users_count, corrupted_count,
                                paper_scoring, distance_dtype):
    """Fused distance->score kernel (ops/pallas_defense.py) under the
    same cancellation guard as :func:`_krum_scores`'s 'topk' method:
    the fused evaluation is the complement identity (rowsum minus the
    c largest), so whenever any row's kept mass falls below the
    subtraction's noise floor the scores re-evaluate via the exact
    sort path over the pallas distance matrix (``lax.cond`` — one
    branch executes at runtime).  c == 0 degenerates to the pure
    rowsum: no subtraction, no guard."""
    from attacking_federate_learning_tpu.ops.pallas_defense import (
        pallas_krum_scores
    )

    op = users_grads
    if distance_dtype is not None:
        op = op.astype(jnp.dtype(distance_dtype))
    scores, rowsum = pallas_krum_scores(op, users_count, corrupted_count,
                                        paper_scoring=paper_scoring)
    comp = corrupted_count - 1 + (2 if paper_scoring else 0)
    if comp == 0:
        return scores
    n = users_grads.shape[0]
    eps = jnp.finfo(jnp.float32).eps
    floor = (_TOPK_GUARD * eps * max(np.log2(max(n, 2)), 1.0) * rowsum)

    def exact_sort():
        D = _distances_for(users_grads, "pallas", distance_dtype)
        return _krum_scores(D, users_count, corrupted_count,
                            paper_scoring=paper_scoring, method="sort")

    reliable = jnp.all((scores >= floor) & jnp.isfinite(rowsum))
    return lax.cond(reliable, lambda: scores, exact_sort)


def _host_krum_index(users_grads, users_count, corrupted_count,
                     paper_scoring):
    """Host-BLAS Krum index; pure_callback (scalar int out) under trace,
    zero-copy eager otherwise — same dispatch contract as _host_defense."""
    import numpy as np

    from attacking_federate_learning_tpu.defenses.host import (
        host_krum_index
    )

    n_static, f_static = int(users_count), int(corrupted_count)

    def cb(g):
        return np.int32(host_krum_index(np.asarray(g, np.float32),
                                        n_static, f_static,
                                        paper_scoring=paper_scoring))

    if not isinstance(users_grads, jax.core.Tracer):
        return jnp.asarray(cb(users_grads))
    return jax.pure_callback(cb, jax.ShapeDtypeStruct((), jnp.int32),
                             users_grads.astype(jnp.float32))


def _krum_scores_and_index(users_grads, users_count, corrupted_count,
                           paper_scoring, method, distance_impl, D,
                           distance_dtype, mask=None, scores_impl="xla"):
    """(scores-or-None, winner index) behind both :func:`krum_select`
    and the telemetry path.  Scores are ``None`` on the host engine —
    it returns only the scalar index (defenses/host.py), so telemetry
    fills that slot with NaN instead of paying a second (n,) marshal.

    ``scores_impl='pallas'`` (config ``aggregation_impl='pallas'``):
    the fused distance->score kernel — scores in one sweep, no (n, n)
    matrix (ops/pallas_defense.py), guarded like the 'topk' method
    (:func:`_pallas_krum_scores_guarded`).  An explicit opt-in that
    outranks ``distance_impl`` resolution; the masked path keeps the
    exact sort evaluator, fed by the pallas distance kernel.

    ``mask`` (the quarantine seam, core/faults.py): dead rows are
    excluded from every score (their distance entries mask to +inf, the
    per-row keep count k follows the data-dependent alive pool e - f)
    and can never win — fixed shapes, scoring forced onto the exact
    'sort' evaluator (the topk complement identity assumes the static
    pool)."""
    if D is None and scores_impl == "pallas":
        if mask is None:
            scores = _pallas_krum_scores_guarded(
                users_grads, users_count, corrupted_count, paper_scoring,
                distance_dtype)
            return scores, jnp.argmin(scores)
        # Masked pool: exact sort scoring over the pallas-computed
        # distance matrix (the fused kernel assumes the static pool).
        D = _distances_for(users_grads, "pallas", distance_dtype)
    if D is None:
        impl = resolve_distance_impl(distance_impl, users_count,
                                     users_grads)
        if impl == "host":
            if mask is not None:
                raise ValueError(
                    "mask-aware Krum needs a score-returning engine; "
                    "the host engine returns only the winner index "
                    "(defenses/host.py)")
            return None, _host_krum_index(users_grads, users_count,
                                          corrupted_count, paper_scoring)
        D = _distances_for(users_grads, impl, distance_dtype)
    if mask is not None:
        scores = _krum_scores(D, jnp.sum(mask), corrupted_count,
                              alive=mask, paper_scoring=paper_scoring,
                              method="sort")
    else:
        scores = _krum_scores(D, users_count, corrupted_count,
                              paper_scoring=paper_scoring, method=method)
    return scores, jnp.argmin(scores)


def krum_select(users_grads, users_count, corrupted_count,
                paper_scoring=False, method="sort", distance_impl="xla",
                D=None, distance_dtype=None, mask=None,
                scores_impl="xla"):
    """Index of the Krum winner (reference ``krum(..., return_index=True)``,
    defences.py:39-40).  :func:`krum` is defined through this, so the
    selection the engine's round diagnostics report is — by construction —
    the client the defense aggregated, for every distance engine."""
    return _krum_scores_and_index(users_grads, users_count, corrupted_count,
                                  paper_scoring, method, distance_impl, D,
                                  distance_dtype, mask=mask,
                                  scores_impl=scores_impl)[1]


@DEFENSES.register("Krum")
def krum(users_grads, users_count, corrupted_count, paper_scoring=False,
         method="sort", distance_impl="xla", D=None, distance_dtype=None,
         telemetry=False, mask=None, weights=None, scores_impl="xla",
         margins=False, numerics=False):
    """Krum selection (reference defences.py:23-42): the single gradient
    whose summed distance to its k nearest peers is minimal.

    ``distance_impl``: 'xla' (Gram matmul, ops/distances.py), 'pallas'
    (fused-epilogue TPU kernel, ops/pallas_distances.py), 'host' (NumPy/BLAS
    via pure_callback — the CPU-backend path, defenses/host.py), or 'auto'
    (host on CPU, xla elsewhere).  ``D``: precomputed (n, n) distance matrix
    with zero diagonal — the engine passes one from the blockwise shard_map
    kernels (parallel/distances.py) for distance_impl in {ring, allgather}.
    ``distance_dtype``: see :func:`_distances_for` (bf16 MXU mode).

    ``telemetry=True`` additionally returns ``{'selection_mask': (n,)
    one-hot f32, 'scores': (n,) f32 Krum scores}`` — the same single
    distance computation, so the mask provably marks the aggregated row
    (NaN scores on the scalar-index host engine).

    ``mask`` (the quarantine seam, core/faults.py): quarantined rows
    can never win selection and are excluded from every row's score;
    the winner is the Krum choice of the alive sub-cohort.

    ``weights`` (the staleness seam, core/async_rounds.py — requires
    ``mask``): selection stays unweighted (distances don't age), but
    the winning row's contribution is scaled by ITS weight — a stale
    Krum winner moves the server proportionally less.

    ``scores_impl='pallas'`` (config ``aggregation_impl='pallas'``):
    the fused distance->score route — see
    :func:`_krum_scores_and_index`.  The winner is an input row, so
    the aggregate is bit-exact whenever the (ulp-class) score
    difference between evaluations doesn't flip a near-tie — the
    measured-band contract (tests/test_pallas.py).

    ``margins=True`` (requires ``telemetry=True``; ISSUE 18)
    additionally returns ``margin_selection`` (n,) — each row's signed
    score distance to the selection threshold (selected iff > 0, one-
    sided at exact f32 score ties) — and ``margin_gap`` () — the
    winner/runner-up score gap (utils/margins.py:krum_margins).  Needs
    a score-returning engine: the scalar-index host path has no score
    vector to measure and raises.

    ``numerics=True`` (requires ``margins=True``; ISSUE 20)
    additionally returns ``num_tie_rows`` () int32 — rows whose
    selection margin sits within TIE_BAND_ULPS ulp (at the winner
    score's magnitude) of the boundary — and ``num_cancel_bits`` ()
    f32 — a documented cancellation-depth ESTIMATE: 2*max||g||^2 (the
    largest possible ||a||^2+||b||^2-2ab accumuland) against the
    winner's mean kept distance, since the (n, n) Gram is not in scope
    here and recomputing it would double the distance work
    (utils/numerics.py).
    """
    check_margin_seam(margins, telemetry)
    check_numerics_seam(numerics, margins)
    if not telemetry:
        idx = krum_select(users_grads, users_count, corrupted_count,
                          paper_scoring=paper_scoring, method=method,
                          distance_impl=distance_impl, D=D,
                          distance_dtype=distance_dtype, mask=mask,
                          scores_impl=scores_impl)
        if weights is not None:
            return users_grads[idx] * weights[idx]
        return users_grads[idx]
    scores, idx = _krum_scores_and_index(
        users_grads, users_count, corrupted_count, paper_scoring, method,
        distance_impl, D, distance_dtype, mask=mask,
        scores_impl=scores_impl)
    n = users_grads.shape[0]
    scores_out = (jnp.full((n,), jnp.nan, jnp.float32) if scores is None
                  else scores.astype(jnp.float32))
    sel = jnp.zeros((n,), jnp.float32).at[idx].set(1.0)
    agg = (users_grads[idx] * weights[idx] if weights is not None
           else users_grads[idx])
    diag = {"selection_mask": sel, "scores": scores_out}
    if margins:
        if scores is None:
            raise ValueError(
                "Krum margins need a score-returning engine; "
                "distance_impl='host' returns only the winner index "
                "(defenses/host.py)")
        diag.update(krum_margins(scores, idx, mask=mask))
        if numerics:
            win = scores_out[idx]
            diag["num_tie_rows"] = tie_proximity(
                diag["margin_selection"], win)
            k_kept = jnp.maximum(
                (jnp.sum(mask) if mask is not None else users_count)
                - corrupted_count, 1).astype(jnp.float32)
            g32 = users_grads.astype(jnp.float32)
            sq = jnp.sum(g32 * g32, axis=1)
            if mask is not None:
                sq = jnp.where(mask, sq, 0.0)
            diag["num_cancel_bits"] = cancellation_bits(
                2.0 * jnp.max(sq), win / k_kept)
    return agg, diag


def trimmed_mean_of(users_grads, number_to_consider, impl="xla",
                    telemetry=False, margins=False, numerics=False):
    """Median-anchored trimmed mean along the client axis.

    Per coordinate (reference defences.py:48-51): subtract the median, keep
    the ``number_to_consider`` values of smallest magnitude (stable order,
    matching Python's stable ``sorted`` on key=abs), and return their mean
    plus the median.

    ``impl='host'`` is the single dispatch site for the native
    column-blocked kernel — shared by :func:`trimmed_mean` and Bulyan's
    ``trim_impl`` tail so the two can never diverge.  ``impl='pallas'``
    (config ``aggregation_impl='pallas'``) is the on-device equivalent:
    the tiled per-d-block selection kernel
    (ops/pallas_defense.py:pallas_trimmed_mean_of) — same summation-
    order-ulps contract as the host kernel, and like it the kernel
    returns only the aggregate, so telemetry fills the NaN slots.

    ``telemetry=True`` additionally returns ``{'kept_fraction': (n,) —
    per client, the fraction of coordinates where its value survived the
    trim (NaN on the host/pallas kernels, which return only the
    aggregate) — 'trim_fraction': () — the per-round fraction of
    clients trimmed per coordinate}``.

    ``margins=True`` (requires ``telemetry=True``; ISSUE 18)
    additionally returns ``margin_kept_frac``/``margin_boundary_dist``
    (utils/margins.py:rank_keep_margins) — the kept fraction from rank
    membership (bit-equal to the scatter-based ``kept_fraction``) and
    the inside-positive mean distance to the trim boundary.  The
    reductions are pure-XLA rank ops over the same key the estimator
    sorts by, so the pallas impl gets REAL margins (its aggregate
    kernel still reports NaN ``kept_fraction``) and the two impls'
    margins are bit-identical by construction; the host kernel runs
    off-device and raises.

    ``numerics=True`` (requires ``margins=True``; ISSUE 20)
    additionally returns ``num_tie_rows`` () int32 — per-coordinate
    boundary distances within TIE_BAND_ULPS ulp of the trim cut,
    banded at the deviation key's largest finite magnitude
    (utils/numerics.py).
    """
    check_margin_seam(margins, telemetry)
    check_numerics_seam(numerics, margins)
    n = users_grads.shape[0]
    trim_frac = jnp.float32(1.0 - number_to_consider / n)

    def margin_fields():
        med = jnp.median(users_grads, axis=0)
        key = jnp.abs(users_grads - med[None, :])
        mf = rank_keep_margins(key, number_to_consider)
        if numerics:
            mf["num_tie_rows"] = tie_proximity(
                mf["margin_boundary_dist"], max_finite_abs(key))
        return mf

    if impl == "pallas":
        from attacking_federate_learning_tpu.ops.pallas_defense import (
            pallas_trimmed_mean_of
        )
        agg = pallas_trimmed_mean_of(users_grads, int(number_to_consider))
        if not telemetry:
            return agg
        diag = {"kept_fraction": jnp.full((n,), jnp.nan, jnp.float32),
                "trim_fraction": trim_frac}
        if margins:
            diag.update(margin_fields())
        return agg, diag
    if impl == "host":
        if margins:
            raise ValueError(
                "trimmed-mean margins need the on-device ranks; "
                "impl='host' returns only the aggregate "
                "(defenses/host.py)")
        from attacking_federate_learning_tpu.defenses.host import (
            host_trimmed_mean_of
        )
        k_static = int(number_to_consider)
        agg = host_coordwise(
            lambda g: host_trimmed_mean_of(g, k_static), users_grads)
        if not telemetry:
            return agg
        return agg, {"kept_fraction": jnp.full((n,), jnp.nan, jnp.float32),
                     "trim_fraction": trim_frac}
    med = jnp.median(users_grads, axis=0)
    dev = users_grads - med[None, :]
    order = jnp.argsort(jnp.abs(dev), axis=0, stable=True)
    kept_rows = order[:number_to_consider]
    kept = jnp.take_along_axis(dev, kept_rows, axis=0)
    agg = jnp.mean(kept, axis=0) + med
    if not telemetry:
        return agg
    d = users_grads.shape[1]
    kept_frac = (jnp.zeros((n,), jnp.float32)
                 .at[kept_rows.reshape(-1)].add(1.0) / d)
    diag = {"kept_fraction": kept_frac, "trim_fraction": trim_frac}
    if margins:
        key = jnp.abs(dev)
        mf = rank_keep_margins(key, number_to_consider, order=order)
        if numerics:
            mf["num_tie_rows"] = tie_proximity(
                mf["margin_boundary_dist"], max_finite_abs(key))
        diag.update(mf)
    return agg, diag


@DEFENSES.register("TrimmedMean")
def trimmed_mean(users_grads, users_count, corrupted_count, impl="xla",
                 telemetry=False, mask=None, weights=None,
                 margins=False, numerics=False):
    """Reference defences.py:44-52; keeps n - f - 1 coordinates.

    ``impl='host'`` (opt-in, config ``trimmed_mean_impl``) routes to the
    native column-blocked kernel (defenses/host.py ->
    native/bulyan_select.cpp:fl_trimmed_mean): at n=10,240, d=79,510 the
    XLA:CPU per-coordinate stable sort is minutes while the native
    kernel is ~25 s.  Unlike Krum's host path (which returns an exact
    input row, so dispatch cannot change results), the host trimmed
    mean differs from XLA by summation-order ulps — which is why it is
    NOT auto-dispatched: the staged/fused bit-identity invariant
    (tests/test_engine.py::test_backdoor_fused_equals_staged) holds
    only when both modes run the same kernel.

    ``mask`` (the quarantine seam, core/faults.py): the estimator runs
    over the alive rows only — alive median anchor, keep count
    e - f - 1 with e the data-dependent alive count (the trim budget
    shrinks with the cohort, it is not spent on quarantined rows).

    ``weights`` (the staleness seam, core/async_rounds.py — requires
    ``mask``): the trim stays rank-based; the kept deviations average
    weighted (see :func:`masked_trimmed_mean_of`).

    ``margins=True``: see :func:`trimmed_mean_of`; the masked variant
    ranks by the same alive-anchored key as
    :func:`masked_trimmed_mean_of` (dead rows +inf -> -inf boundary
    distance, zero kept fraction).  ``numerics=True``: see
    :func:`trimmed_mean_of` (the masked tie band is measured on the
    same alive-anchored key, whose dead-row +inf sentinels the
    finite-magnitude scale excludes)."""
    check_margin_seam(margins, telemetry)
    check_numerics_seam(numerics, margins)
    if mask is not None:
        if impl == "host":
            raise ValueError(
                "mask-aware TrimmedMean has no host kernel "
                "(defenses/host.py is maskless); use impl='xla'")
        n = users_grads.shape[0]
        e = jnp.sum(mask)
        if impl == "pallas":
            # Mask/weights seam on the pallas route: the tiled kernel
            # replicates masked_trimmed_mean_of op for op (pinned
            # bit-exact, tests/test_pallas.py); k = e - f - 1 derives
            # from the mask inside the kernel.
            from attacking_federate_learning_tpu.ops.pallas_defense import (
                pallas_masked_trimmed_mean
            )
            agg = pallas_masked_trimmed_mean(
                users_grads, mask, corrupted_count + 1, weights=weights,
                weighted=weights is not None)
        else:
            agg = masked_trimmed_mean_of(users_grads, mask,
                                         e - corrupted_count - 1,
                                         weights=weights)
        if not telemetry:
            return agg
        diag = {"kept_fraction": jnp.full((n,), jnp.nan, jnp.float32),
                "trim_fraction":
                (1.0 - (e - corrupted_count - 1) / jnp.maximum(e, 1)
                 ).astype(jnp.float32)}
        if margins:
            # Same alive-anchored key masked_trimmed_mean_of ranks by
            # (and the pallas tiles replicate op for op), so the
            # margins are impl-independent pure-XLA rank ops.
            med = masked_median(users_grads, mask)
            key = jnp.where(mask[:, None],
                            jnp.abs(users_grads - med[None, :]), _INF)
            k = jnp.maximum(e - corrupted_count - 1, 1)
            mf = rank_keep_margins(key, k)
            if numerics:
                mf["num_tie_rows"] = tie_proximity(
                    mf["margin_boundary_dist"], max_finite_abs(key))
            diag.update(mf)
        return agg, diag
    number_to_consider = users_grads.shape[0] - corrupted_count - 1
    return trimmed_mean_of(users_grads, number_to_consider, impl=impl,
                           telemetry=telemetry, margins=margins,
                           numerics=numerics)


def host_coordwise(host_fn, users_grads):
    """Dispatch a coordinate-wise defenses/host.py kernel
    (``(n, d) f32 -> (d,) f32``): zero-copy eager call on concrete
    operands, ``pure_callback`` inside traced programs — the shared
    scaffold for the opt-in 'host' impls of TrimmedMean and Median."""
    import numpy as np

    d = users_grads.shape[-1]

    def cb(g):
        return host_fn(np.asarray(g, np.float32)).astype(np.float32)

    if not isinstance(users_grads, jax.core.Tracer):
        return jnp.asarray(cb(users_grads))
    return jax.pure_callback(cb, jax.ShapeDtypeStruct((d,), jnp.float32),
                             users_grads.astype(jnp.float32))


def _host_bulyan_selection_of(D, users_count, corrupted_count, set_size,
                              batch_select, paper_scoring):
    """Host-side exact selection over a DEVICE-computed distance matrix —
    the hybrid's host half (VERDICT r3 #2).  ``pure_callback`` under
    trace (marshals the (n, n) D — ~420 MB at n=10,240, the hybrid's one
    data motion), zero-copy eager otherwise; returns (set_size,) int32
    selected indices.  The native incremental engine
    (native/bulyan_select.cpp) makes the selection itself O(n^2) total;
    D must already carry the +inf diagonal."""
    import numpy as np

    from attacking_federate_learning_tpu.defenses.host import (
        host_bulyan_selection
    )

    n_static = int(users_count)
    f_static = int(corrupted_count)
    k_static = int(set_size)
    q_static = int(batch_select)

    def cb(Dh):
        return host_bulyan_selection(
            np.asarray(Dh, np.float32), n_static, f_static, k_static,
            batch_select=q_static,
            paper_scoring=paper_scoring).astype(np.int32)

    if not isinstance(D, jax.core.Tracer):
        return jnp.asarray(cb(D))
    return jax.pure_callback(cb,
                             jax.ShapeDtypeStruct((k_static,), jnp.int32),
                             D.astype(jnp.float32))


def _bulyan_diag(n, selected, Dm, users_count, corrupted_count,
                 paper_scoring, method):
    """Bulyan telemetry pytree: the (n,) multi-hot selection mask plus
    the INITIAL-pool Krum scores (the scores the first selection ranked;
    later trips re-score over the shrinking pool, which would be an
    (n, set_size) matrix — deliberately not carried).  ``Dm`` None (the
    full-host engine, which only returns the aggregate) fills NaN."""
    mask = jnp.zeros((n,), jnp.float32).at[selected].set(1.0)
    if Dm is None:
        scores = jnp.full((n,), jnp.nan, jnp.float32)
    else:
        scores = _krum_scores(Dm, users_count, corrupted_count,
                              paper_scoring=paper_scoring,
                              method=method).astype(jnp.float32)
    return {"selection_mask": mask, "scores": scores}


@DEFENSES.register("Bulyan")
def bulyan(users_grads, users_count, corrupted_count, paper_scoring=False,
           method="sort", distance_impl="xla", D=None, batch_select=1,
           distance_dtype=None, selection_impl="xla", trim_impl="xla",
           telemetry=False, mask=None, weights=None, margins=False,
           numerics=False):
    """Bulyan (reference defences.py:55-70): iteratively Krum-select
    n - 2f gradients (removing each winner from the pool, with the pool
    size — but not f — shrinking), then trim-mean the selection with
    parameter 2f.

    The selection loop sorts each distance row ONCE and evaluates every
    iteration's sum-of-k-smallest as an alive-masked prefix over the
    presorted rows — O(n^2) per selection instead of the O(n^2 log n)
    per-iteration re-sort, exactly the same scores (the k smallest form
    the same multiset whatever the tie order).  ``method`` therefore only
    affects top-level :func:`krum`; ``paper_scoring`` still selects the
    k = pool - f - 2 variant.  ``distance_impl`` / ``D``: same contract
    as :func:`krum`.

    ``batch_select=q`` is an explicit, flagged relaxation for the
    large-n regime on the *traced/XLA* path, where the reference's
    strictly sequential selection is O(n) iterations of O(n^2) scoring
    (BASELINE.md): each trip selects the q lowest-scoring alive clients
    against the SAME scores, re-scoring only between trips, so the loop
    runs ceil(set_size/q) trips instead of set_size.  q=1 IS the
    reference semantics (ties resolve to the lowest index either way:
    ``lax.top_k`` breaks ties toward lower indices, matching
    first-occurrence ``np.argmin``) — the default, and what every
    oracle/reference-parity test pins.  On the ``host`` impl, exact q=1
    no longer needs the relaxation at scale: the native incremental
    kernel (native/bulyan_select.cpp) maintains every row's prefix score
    in O(1) amortized per selection, making the whole exact selection
    O(n^2) total instead of O(n^2) per step.

    ``selection_impl='host'`` is the HYBRID exact path for the
    accelerator backend at large n (VERDICT r3 #2): the O(n^2 d)
    distance work stays on the device (MXU Gram via ``distance_impl``),
    only the (n, n) D ships to the host — once — for the native O(n^2)
    incremental selection, and the selected rows are gathered and
    trim-meaned back on the device.  That replaces the traced path's
    set_size sequential O(n^2) scoring trips (~5,300 dependent
    (10240, 10240) passes per aggregation at the north star) with one
    D transfer + seconds of host selection, while keeping exact q=1
    reference semantics.  Composes with ``batch_select`` and the
    ``D=`` seam; opt-in (config ``bulyan_selection_impl``), not
    auto-dispatched, because host selection resolves f32 score ties by
    the native engine's comparator (see native/bulyan_select.cpp) while
    the traced loop uses f32 throughout — identical outside ulp-band
    ties (tests/test_defenses.py pins hybrid==xla on plain inputs).

    ``selection_impl='pallas'`` / ``trim_impl='pallas'`` (config
    ``bulyan_selection_impl='pallas'`` / ``aggregation_impl='pallas'``)
    is the ALL-ON-DEVICE exact route (ISSUE 11): the (n, n) D comes
    from the fused-epilogue pallas kernel (one HBM write, no Gram
    round-trip), the selection is the same oracle-verified traced loop
    as 'xla', and the trim tail runs the tiled pallas kernel — exact
    q=1 reference semantics with NO pure_callback marshal, the
    accelerator-resident alternative to the host hybrid above.  Same
    ulp-band caveat as every cross-engine distance comparison.

    ``trim_impl='host'`` routes the final trimmed-mean tail through the
    native column-blocked kernel (same opt-in standard — and the same
    ulps-not-bits caveat — as ``trimmed_mean_impl``): at the 10k north
    star the XLA:CPU stable argsort over the (n-2f, d) selection is
    minutes per aggregation while the native kernel is seconds, and on
    the CPU backend that tail, not the selection, is what dominates the
    hybrid.

    ``telemetry=True`` additionally returns the :func:`_bulyan_diag`
    pytree (multi-hot selection mask + initial-pool Krum scores).

    ``mask`` (the quarantine seam, core/faults.py): the selection pool
    starts from the alive rows; the SELECTED set keeps its static
    ``set_size`` shape (fixed shapes everywhere), with quarantined rows
    admitted only after every alive row (finite below-+inf sentinel) and
    excluded again from the final trimmed mean by an alive sub-mask —
    so a quarantined row can pad the selection buffer but never touches
    the aggregate.

    ``weights`` (the staleness seam, core/async_rounds.py — requires
    ``mask``): selection stays unweighted; the final masked trimmed
    mean over the selected rows averages with their per-row weights
    (:func:`masked_trimmed_mean_of`).

    ``margins=True`` (requires ``telemetry=True``; ISSUE 18) threads
    margin carries through the traced selection loop and additionally
    returns: ``margin_selection`` (n,) — per row, the signed score
    distance to its trip's selection cut (picks measure against the
    first unselected score, losers against the final trip's last pick;
    selected iff > 0, one-sided at exact f32 ties and on the masked
    variant, whose dead rows are forced to -inf); ``margin_gap`` () —
    the final trip's pick/runner-up slack; ``margin_slack`` (trips,) —
    that slack per selection trip; ``margin_trim_kept`` (n,) — the
    trim-stage kept fraction of each selected row scattered back to
    its client slot (zero for unselected rows).  Both off-device
    selection engines raise: the full-host path returns only the
    aggregate and the hybrid's native selection never ships per-trip
    scores back.

    ``numerics=True`` (requires ``margins=True``; ISSUE 20)
    additionally returns ``num_tie_rows`` () int32 — rows whose
    selection margin sits within TIE_BAND_ULPS ulp of the final trip's
    cut (the PR 18 tie-lock counter: the IID collapse pins this > 0
    every round) — and ``num_cancel_bits`` () f32 — the measured
    cancellation depth of the (n, n) distance Gram, the tie-band
    driver (utils/numerics.py:gram_cancellation_bits)."""
    check_margin_seam(margins, telemetry)
    check_numerics_seam(numerics, margins)
    n, _ = users_grads.shape
    f = corrupted_count
    set_size = users_count - 2 * f
    q = int(batch_select)
    if not (1 <= q):
        raise ValueError(f"batch_select must be >= 1, got {batch_select}")
    if selection_impl not in ("xla", "host", "pallas"):
        raise ValueError(f"selection_impl must be 'xla', 'host' or "
                         f"'pallas', got {selection_impl!r}")
    if trim_impl not in ("xla", "host", "pallas"):
        raise ValueError(f"trim_impl must be 'xla', 'host' or 'pallas', "
                         f"got {trim_impl!r}")

    def trim_tail(selection, number_to_consider):
        return trimmed_mean_of(selection, number_to_consider,
                               impl=trim_impl)
    q = min(q, set_size)
    if mask is not None and selection_impl == "host":
        raise ValueError(
            "mask-aware Bulyan is incompatible with "
            "selection_impl='host': the native selection engine has no "
            "mask seam (native/bulyan_select.cpp)")
    if D is None:
        impl = resolve_distance_impl(distance_impl, users_count,
                                     users_grads)
        if selection_impl == "pallas":
            # The all-on-device exact route (ISSUE 11): distances from
            # the fused-epilogue pallas kernel (no Gram round-trip),
            # then the SAME oracle-verified traced selection loop as
            # 'xla' below — the (n, n) matrix exists once, on device,
            # and no pure_callback marshal ever runs.  Identical
            # selection math on a ulp-different D: flips only inside
            # the measured tie band (tests/test_pallas.py).
            impl = "pallas"
        if impl == "host":
            if mask is not None:
                raise ValueError(
                    "mask-aware Bulyan has no full-host engine "
                    "(defenses/host.py is maskless)")
            if margins:
                raise ValueError(
                    "Bulyan margins need the traced selection loop; "
                    "the full-host engine returns only the aggregate "
                    "(defenses/host.py)")
            from attacking_federate_learning_tpu.defenses.host import (
                host_bulyan
            )
            host_fn = host_bulyan
            if q > 1:
                host_fn = functools.partial(host_bulyan, batch_select=q)
            agg = _host_defense(host_fn, users_grads, users_count,
                                corrupted_count, paper_scoring)
            if not telemetry:
                return agg
            # The full-host engine returns only the (d,) aggregate; the
            # selection never crosses back.  NaN mask/scores keep the
            # pytree shape fixed and say "not measured" explicitly.
            nan = jnp.full((n,), jnp.nan, jnp.float32)
            return agg, {"selection_mask": nan, "scores": nan}
        D = _distances_for(users_grads, impl, distance_dtype)

    # +inf diagonal reproduces the reference's no-self-distance dict
    # (defences.py:16-21).
    Dm = D + jnp.diag(jnp.full((n,), _INF, D.dtype))

    if selection_impl == "host":
        if margins:
            raise ValueError(
                "Bulyan margins are incompatible with "
                "selection_impl='host': the native selection engine "
                "returns only the selected indices, never the per-trip "
                "scores the margins measure (native/bulyan_select.cpp)")
        # Hybrid: device distances above, host-native exact selection,
        # device gather + trimmed mean below.
        selected = _host_bulyan_selection_of(
            Dm, users_count, corrupted_count, set_size, q, paper_scoring)
        selection = users_grads[selected]
        agg = trim_tail(selection, set_size - 2 * f - 1)
        if not telemetry:
            return agg
        return agg, _bulyan_diag(n, selected, Dm, users_count,
                                 corrupted_count, paper_scoring, method)

    if mask is not None:
        # Mask-aware selection, fixed shapes: the ``selected`` buffer
        # stays (set_size,) whatever the alive count.  Three-level
        # eligibility ladder per trip — alive & unselected rows compete
        # on real scores; dead unselected rows carry a finite
        # below-+inf sentinel (picked only once the alive pool is
        # exhausted, deterministically by lowest index); already-
        # selected rows sit at +inf and can never be re-picked.  Dead
        # rows that do pad the selection are excluded from the final
        # trimmed mean by the alive sub-mask, so they never touch the
        # aggregate.  (A real score above the 3e38 sentinel would
        # misorder a pick; finite f32 sums sit well below it outside
        # deliberately overflowed inputs, which quarantine already
        # removed.)
        order_m = jnp.argsort(Dm, axis=1)
        sortedD_m = jnp.take_along_axis(Dm, order_m, axis=1)
        finite_m = jnp.isfinite(sortedD_m)
        trips_m = -(-set_size // q)
        dead_sentinel = jnp.float32(3e38)

        def body_m(t, carry):
            if margins:
                (remaining, selected, margin, slack, cut,
                 last_scores) = carry
            else:
                remaining, selected = carry
            alive_pool = remaining & mask
            # Reference shrinking-pool k, over the ALIVE pool (clamped:
            # a degenerate cohort keeps at least the nearest neighbor).
            k = jnp.maximum(jnp.sum(alive_pool) - f
                            - (2 if paper_scoring else 0), 1)
            alive_cols = alive_pool[order_m]
            rank = jnp.cumsum(alive_cols, axis=1)
            take = alive_cols & (rank <= k) & finite_m
            scores = jnp.sum(jnp.where(take, sortedD_m, 0.0), axis=1)
            scores = jnp.where(alive_pool, scores, dead_sentinel)
            scores = jnp.where(remaining, scores, _INF)
            if margins:
                # One extra score (the first unselected, ascending) is
                # this trip's selection cut — the margin carries ride
                # the SAME top_k evaluation (its first q entries are
                # the margins-off picks, ties and all).
                kk = min(q + 1, n)
                neg_vals, idxs_all = lax.top_k(-scores, kk)
                idxs = idxs_all[:q]
            else:
                _, idxs = lax.top_k(-scores, q)
            r = jnp.minimum(q, set_size - t * q)
            live = jnp.arange(q) < r
            kill = jnp.zeros((n,), bool).at[idxs].set(live)
            selected = lax.dynamic_update_slice(
                selected, jnp.where(live, idxs, 0).astype(jnp.int32),
                (t * q,))
            if not margins:
                return remaining & ~kill, selected
            vals = -neg_vals          # ascending kk smallest scores
            runner = jnp.take(vals, jnp.minimum(r, kk - 1), mode="clip")
            last_pick = jnp.take(vals, jnp.maximum(r - 1, 0),
                                 mode="clip")
            margin = margin.at[jnp.where(live, idxs, n)].set(
                runner - vals[:q], mode="drop")
            slack = slack.at[t].set(runner - last_pick)
            return (remaining & ~kill, selected, margin, slack,
                    last_pick, scores)

        if margins:
            (rem_f, selected, margin_sel, slack, cut,
             last_scores) = lax.fori_loop(
                0, trips_m, body_m,
                (jnp.ones((n,), bool),
                 jnp.zeros((trips_m * q,), jnp.int32),
                 jnp.zeros((n,), jnp.float32),
                 jnp.zeros((trips_m,), jnp.float32),
                 jnp.float32(0.0), jnp.zeros((n,), jnp.float32)))
        else:
            _, selected = lax.fori_loop(
                0, trips_m, body_m,
                (jnp.ones((n,), bool),
                 jnp.zeros((trips_m * q,), jnp.int32)))
        selected = selected[:set_size]
        selection = users_grads[selected]
        # Effective-cohort Bulyan selects e - 2f of the e alive rows.
        # Alive rows enter ``selected`` first and in exactly the order a
        # run over the alive sub-matrix would pick them (dead rows only
        # pad the tail), so clipping to the first e - 2f alive picks
        # reproduces the shrunk-cohort selection SET inside the static
        # (set_size,) buffer; the rest is excluded from the trim below.
        sel_alive = mask[selected]
        e_set = jnp.sum(mask) - 2 * f
        sel_mask = sel_alive & (jnp.cumsum(sel_alive) <= e_set)
        w_sel = None if weights is None else weights[selected]
        if trim_impl == "pallas":
            from attacking_federate_learning_tpu.ops.pallas_defense import (
                pallas_masked_trimmed_mean
            )
            agg = pallas_masked_trimmed_mean(
                selection, sel_mask, 2 * f + 1, weights=w_sel,
                weighted=w_sel is not None)
        else:
            agg = masked_trimmed_mean_of(
                selection, sel_mask, jnp.sum(sel_mask) - 2 * f - 1,
                weights=w_sel)
        if not telemetry:
            return agg
        dm = jnp.zeros((n,), jnp.float32).at[selected].set(
            sel_mask.astype(jnp.float32))
        scores0 = _krum_scores(Dm, jnp.sum(mask), corrupted_count,
                               alive=mask, paper_scoring=paper_scoring,
                               method="sort").astype(jnp.float32)
        diag = {"selection_mask": dm, "scores": scores0}
        if margins:
            # Losers measure against the final trip's last pick (the
            # PADDED loop's cut — a lower bound on their distance to
            # the effective boundary when the cohort is degraded).
            # Picks the effective-cohort cumsum clipped out of the
            # selection are rejected rows whose trip-local margins
            # don't measure against the effective boundary — explicit
            # -inf ("rejected, unmeasured"), like dead rows, so the
            # selected-iff-margin>0 identity holds for every alive
            # row.  Trim-stage survival mirrors the
            # masked_trimmed_mean_of key over the selected rows.
            margin_sel = jnp.where(rem_f, cut - last_scores, margin_sel)
            clipped = jnp.zeros((n,), bool).at[selected].set(~sel_mask)
            margin_sel = jnp.where(clipped, -_INF, margin_sel)
            margin_sel = jnp.where(mask, margin_sel, -_INF)
            med_s = masked_median(selection, sel_mask)
            key_s = jnp.where(sel_mask[:, None],
                              jnp.abs(selection - med_s[None, :]), _INF)
            k_t = jnp.maximum(jnp.sum(sel_mask) - 2 * f - 1, 1)
            tm = rank_keep_margins(key_s, k_t)
            diag["margin_selection"] = margin_sel.astype(jnp.float32)
            diag["margin_gap"] = slack[trips_m - 1]
            diag["margin_slack"] = slack
            diag["margin_trim_kept"] = jnp.zeros(
                (n,), jnp.float32).at[selected].set(
                jnp.where(sel_mask, tm["margin_kept_frac"], 0.0))
            if numerics:
                diag["num_tie_rows"] = tie_proximity(
                    diag["margin_selection"], cut)
                diag["num_cancel_bits"] = gram_cancellation_bits(
                    Dm, mask=mask)
        return agg, diag

    # Presort once for the traced selection loop.
    order = jnp.argsort(Dm, axis=1)
    sortedD = jnp.take_along_axis(Dm, order, axis=1)
    finite = jnp.isfinite(sortedD)
    trips = -(-set_size // q)

    def body(t, carry):
        if margins:
            alive, selected, margin, slack, cut, last_scores = carry
        else:
            alive, selected = carry
        # Pool at trip start: everyone minus the t*q already selected.
        k = users_count - t * q - f - (2 if paper_scoring else 0)
        alive_cols = alive[order]                       # (n, n) gather
        rank = jnp.cumsum(alive_cols, axis=1)           # 1-based among alive
        take = alive_cols & (rank <= k) & finite
        scores = jnp.sum(jnp.where(take, sortedD, 0.0), axis=1)
        scores = jnp.where(alive, scores, _INF)
        # q lowest scores, ascending (ties -> lower index, like argmin);
        # only the first r count on the (possibly short) final trip.
        if margins:
            # One extra score — the first unselected, this trip's
            # selection cut; the first q entries of the widened top_k
            # are exactly the margins-off picks (same evaluation,
            # same tie resolution).
            kk = min(q + 1, n)
            neg_vals, idxs_all = lax.top_k(-scores, kk)
            idxs = idxs_all[:q]
        else:
            _, idxs = lax.top_k(-scores, q)
        r = jnp.minimum(q, set_size - t * q)
        live = jnp.arange(q) < r
        kill = jnp.zeros((n,), bool).at[idxs].set(live)
        selected = lax.dynamic_update_slice(
            selected, jnp.where(live, idxs, 0).astype(jnp.int32), (t * q,))
        if not margins:
            return alive & ~kill, selected
        vals = -neg_vals              # ascending kk smallest scores
        runner = jnp.take(vals, jnp.minimum(r, kk - 1), mode="clip")
        last_pick = jnp.take(vals, jnp.maximum(r - 1, 0), mode="clip")
        margin = margin.at[jnp.where(live, idxs, n)].set(
            runner - vals[:q], mode="drop")
        slack = slack.at[t].set(runner - last_pick)
        return alive & ~kill, selected, margin, slack, last_pick, scores

    alive0 = jnp.ones((n,), bool)
    sel0 = jnp.zeros((trips * q,), jnp.int32)
    if margins:
        (alive_f, selected, margin_sel, slack, cut,
         last_scores) = lax.fori_loop(
            0, trips, body,
            (alive0, sel0, jnp.zeros((n,), jnp.float32),
             jnp.zeros((trips,), jnp.float32), jnp.float32(0.0),
             jnp.zeros((n,), jnp.float32)))
    else:
        _, selected = lax.fori_loop(0, trips, body, (alive0, sel0))
    selected = selected[:set_size]

    selection = users_grads[selected]  # (set_size, d), in selection order
    number_to_consider = set_size - 2 * f - 1
    agg = trim_tail(selection, number_to_consider)
    if not telemetry:
        return agg
    diag = _bulyan_diag(n, selected, Dm, users_count, corrupted_count,
                        paper_scoring, method)
    if margins:
        # Losers measure against the final trip's last-pick score; the
        # trim-stage survival re-ranks the selection by the same key
        # trimmed_mean_of sorts by and scatters each selected row's
        # kept fraction back to its client slot.
        margin_sel = jnp.where(alive_f, cut - last_scores, margin_sel)
        med_s = jnp.median(selection, axis=0)
        tm = rank_keep_margins(jnp.abs(selection - med_s[None, :]),
                               number_to_consider)
        diag["margin_selection"] = margin_sel.astype(jnp.float32)
        diag["margin_gap"] = slack[trips - 1]
        diag["margin_slack"] = slack
        diag["margin_trim_kept"] = jnp.zeros(
            (n,), jnp.float32).at[selected].set(tm["margin_kept_frac"])
        if numerics:
            diag["num_tie_rows"] = tie_proximity(
                diag["margin_selection"], cut)
            diag["num_cancel_bits"] = gram_cancellation_bits(Dm)
    return agg, diag


# --- tier-2 (cross-shard) entries for hierarchical aggregation ----------
#
# The two-tier engine (ops/federated.py, core/engine.py
# aggregation='hierarchical') reduces per-megabatch tier-1 estimates with
# a SECOND robust pass over the (n/m, d) shard-estimate matrix.  Each
# shard_* entry is the corresponding flat kernel re-surfaced on that
# matrix: rows are shard estimates, ``shard_count`` plays users_count,
# ``corrupted_shards`` is the assumed number of colluder-controlled
# shards, and ``alive_counts`` (S,) int — the per-shard effective cohort
# from PR 2's fault masks — maps onto the kernels' existing quarantine
# ``mask=`` seam (a fully-dead shard's estimate can never win selection
# or touch a trim).  No new estimator math: the mask-aware paths are
# reused unchanged, which is what keeps tier-2 oracle-verified for free.
#
# Telemetry seam (ISSUE 8): every shard_* entry accepts the same
# trace-time ``telemetry=`` flag as the flat kernels and forwards it —
# the returned diagnostics pytree is the flat kernel's, re-read over
# the SHARD axis: a (S,) ``selection_mask`` says which shards'
# estimates the tier-2 reduction selected/kept/rejected, which is the
# raw material of the colluder-localization forensics (report.py).
# With it off (the default) the call is byte-for-byte the
# pre-telemetry path, same as the flat kernels' contract.

def check_weight_seam(mask, weights):
    """The staleness-weight seam (core/async_rounds.py) rides the
    quarantine mask: a ``weights=`` without a ``mask=`` has no
    delivered-cohort to weight and is a caller bug, rejected loudly."""
    if weights is not None and mask is None:
        raise ValueError(
            "defense weights= requires mask= (staleness weights apply "
            "to the delivered cohort only; core/async_rounds.py)")


def _alive_to_mask(alive_counts):
    return None if alive_counts is None else alive_counts > 0


def shard_mean(shard_estimates, shard_count, corrupted_shards,
               alive_counts=None, telemetry=False, margins=False,
               numerics=False):
    """Tier-2 NoDefense: alive-count-weighted mean of the shard
    estimates — with equal megabatches and no faults this is exactly
    the flat FedAvg mean (each estimate already averages m clients);
    with faults the weights restore the flat masked mean's
    per-client weighting.  ``telemetry=True`` returns ``(agg, {})`` —
    a mean rejects nothing, so there is nothing to attribute (and
    ``margins=`` / ``numerics=`` are likewise accepted and ignored: no
    decision boundary, no margin fields, no tie band)."""
    del corrupted_shards
    check_margin_seam(margins, telemetry)
    check_numerics_seam(numerics, margins)
    if alive_counts is None:
        agg = jnp.mean(shard_estimates, axis=0)
    else:
        w = alive_counts.astype(jnp.float32)
        agg = (w @ shard_estimates) / jnp.maximum(jnp.sum(w), 1.0)
    if not telemetry:
        return agg
    return agg, {}


def shard_krum(shard_estimates, shard_count, corrupted_shards,
               alive_counts=None, **kw):
    """Tier-2 Krum over shard estimates (mask-aware via alive counts)."""
    return krum(shard_estimates, shard_count, corrupted_shards,
                mask=_alive_to_mask(alive_counts), **kw)


def shard_trimmed_mean(shard_estimates, shard_count, corrupted_shards,
                       alive_counts=None, **kw):
    """Tier-2 median-anchored trimmed mean over shard estimates."""
    return trimmed_mean(shard_estimates, shard_count, corrupted_shards,
                        mask=_alive_to_mask(alive_counts), **kw)


def shard_bulyan(shard_estimates, shard_count, corrupted_shards,
                 alive_counts=None, **kw):
    """Tier-2 Bulyan over shard estimates (mask-aware via alive
    counts); the (S, S) distance pass is tiny — S = n/m shards."""
    return bulyan(shard_estimates, shard_count, corrupted_shards,
                  mask=_alive_to_mask(alive_counts), **kw)


def shard_median(shard_estimates, shard_count, corrupted_shards,
                 alive_counts=None, **kw):
    """Tier-2 coordinate-wise median over shard estimates."""
    # Local import: defenses/median.py imports DEFENSES from this module.
    from attacking_federate_learning_tpu.defenses.median import median
    return median(shard_estimates, shard_count, corrupted_shards,
                  mask=_alive_to_mask(alive_counts), **kw)


# Tier-2 dispatch surface (config.tier2_defense); tier-1 for the
# hierarchical engine is restricted to the same names — the mask-aware,
# oracle-verified kernel set.
#
# Group-sum seam (protocols/secagg.py, cfg.secagg='groupwise'): under
# group-wise secure aggregation the rows these kernels see are the
# per-megabatch SUMS the protocol exposes, scaled to means (sum / m) so
# they remain the same (S, d) estimate matrix the plain hierarchical
# tier produces — selection (Krum/Bulyan) is scale-covariant and the
# coordinate trims are row-wise, so no kernel changes: the only
# difference between "tier-2 over tier-1 estimates" and "tier-2 over
# secagg group sums" is which tensor the server was ever allowed to
# see, which is exactly the NET-SA measurement surface.
TIER2_DEFENSES = {"NoDefense": shard_mean, "Krum": shard_krum,
                  "TrimmedMean": shard_trimmed_mean,
                  "Bulyan": shard_bulyan, "Median": shard_median}


def check_tier2_args(name, shard_count, corrupted_shards):
    """Fail-fast validity for the tier-2 reduction: the Krum/Bulyan
    bounds via :func:`check_defense_args`, plus the trimmed mean's
    keep-count floor (S - f2 - 1 >= 1) that the flat path never hits
    because n >> f."""
    check_defense_args(name, shard_count, corrupted_shards)
    if (name in ("TrimmedMean",)
            and shard_count - corrupted_shards - 1 < 1):
        raise ValueError(
            f"tier-2 TrimmedMean keeps shard_count - corrupted_shards - 1 "
            f"estimates; got S={shard_count}, f2={corrupted_shards}")


def check_defense_args(name, users_count, corrupted_count):
    """Host-side guards mirroring the reference asserts (defences.py:25
    n >= 2f+1 for Krum; defences.py:56 n >= 4f+3 for Bulyan)."""
    if name == "Krum" and users_count < 2 * corrupted_count + 1:
        raise ValueError(
            f"Krum requires users_count >= 2*corrupted_count + 1 "
            f"(got n={users_count}, f={corrupted_count})")
    if name == "Bulyan" and users_count < 4 * corrupted_count + 3:
        raise ValueError(
            f"Bulyan requires users_count >= 4*corrupted_count + 3 "
            f"(got n={users_count}, f={corrupted_count})")
