"""attacking_federate_learning_tpu — a TPU-native federated-learning
attack/defense simulation framework.

A ground-up JAX / XLA / pjit re-design of the capabilities of
``shaneson0/attacking_federate_learning`` (synchronous federated SGD under
Byzantine attack: ALIE drift + clipped backdoors vs. Krum / TrimmedMean /
Bulyan / plain averaging).  Unlike the reference's sequential single-process
simulator (reference server.py:54-56 — a Python ``for`` over client objects),
the client axis here is an array dimension: the local step is
``vmap(grad(loss))`` over stacked client batches, sharded across TPU devices
with ``jax.sharding``, and the defense kernels are compiled XLA (Krum's
O(n^2·d) pairwise distances as one matmul).

Layer map (mirrors SURVEY.md §1):

- ``cli``        — L6 experiment driver
- ``attacks``    — L5 attack plugins (pure ``craft`` functions)
- ``core``       — L4 server runtime / round loop
- ``defenses``   — L3 robust-aggregation kernels
- ``data``       — L2 client data feeding (partitioners, batch gathers)
- ``models``     — L1 model zoo (torch-parameter-order compatible pytrees)
- ``parallel``   — device mesh / sharding layouts (no reference analog:
  the reference has no distributed backend, SURVEY.md §2.3)
- ``ops``        — low-level kernels (pairwise distances, sorting helpers)
"""

__version__ = "0.1.0"

from attacking_federate_learning_tpu.config import ExperimentConfig  # noqa: F401
