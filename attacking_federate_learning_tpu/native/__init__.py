"""Native (C++) host-runtime kernels, built on demand.

The TPU compute path is JAX/XLA/pallas; this package holds the *host*
runtime's native kernels — currently the incremental exact Bulyan
selection (bulyan_select.cpp), which turns the reference's O(n^3)
sequential selection (reference defences.py:55-70) into O(n^2) total so
exact-semantics Bulyan is tractable at the 10k-client north star.

Build model: ``g++ -O3 -shared`` at first use, cached next to the source
keyed on the source hash (so edits rebuild, repeat runs don't).  Loading
is strictly best-effort — any failure (no compiler, read-only tree,
unsupported platform) returns None and callers fall back to the NumPy
implementations in defenses/host.py.  ``FL_NATIVE=0`` disables the
native path outright (used by tests to pin the fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "bulyan_select.cpp")
_lock = threading.Lock()
_lib = None
_loaded = False


def _build_and_load():
    with open(_SRC, "rb") as fh:
        src = fh.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    so = os.path.join(_DIR, f"_bulyan_{tag}.so")
    if not os.path.exists(so):
        tmp = f"{so}.tmp.{os.getpid()}"
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=300,
        )
        os.replace(tmp, so)  # atomic: concurrent builders race safely
    lib = ctypes.CDLL(so)
    fn = lib.fl_bulyan_select
    fn.restype = ctypes.c_int
    fn.argtypes = [
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
    ]
    tm = lib.fl_trimmed_mean
    tm.restype = ctypes.c_int
    tm.argtypes = [
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
    ]
    md = lib.fl_median
    md.restype = ctypes.c_int
    md.argtypes = [
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_int32, ctypes.c_int32,
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
    ]
    return lib


def get_lib():
    """The loaded native library, or None if unavailable."""
    global _lib, _loaded
    with _lock:
        if _loaded:
            return _lib
        _loaded = True
        if os.environ.get("FL_NATIVE", "1") == "0":
            return None
        try:
            _lib = _build_and_load()
        except Exception:
            _lib = None
        return _lib


def native_bulyan_selection(D, order, users_count, corrupted_count,
                            set_size, batch_select=1,
                            paper_scoring=False):
    """Run the incremental selection; returns the selected index array
    (np.int32, length set_size) or None if the native path is
    unavailable or declines (caller falls back to NumPy)."""
    lib = get_lib()
    if lib is None:
        return None
    n = D.shape[0]
    if not (0 < set_size <= n):
        return None
    D = np.ascontiguousarray(D, np.float32)
    order = np.ascontiguousarray(order, np.int32)
    out = np.empty(set_size, np.int32)
    rc = lib.fl_bulyan_select(
        D, order, n, int(users_count), int(corrupted_count),
        int(set_size), int(max(1, batch_select)),
        1 if paper_scoring else 0, out,
    )
    if rc != 0:
        return None
    return out


def native_median(sel):
    """Column-blocked native coordinate-wise median; (d,) f32 or None."""
    lib = get_lib()
    if lib is None:
        return None
    n, d = sel.shape
    if n == 0 or d == 0:
        return None
    sel = np.ascontiguousarray(sel, np.float32)
    out = np.empty(d, np.float32)
    rc = lib.fl_median(sel, n, d, out)
    if rc != 0:
        return None
    return out


def native_trimmed_mean(sel, number_to_consider):
    """Column-blocked native trimmed mean; returns the (d,) f32 result
    or None if the native path is unavailable/ineligible (caller falls
    back to NumPy)."""
    lib = get_lib()
    if lib is None:
        return None
    n, d = sel.shape
    k = int(number_to_consider)
    if not (0 < k <= n) or n == 0 or d == 0:
        return None
    sel = np.ascontiguousarray(sel, np.float32)
    out = np.empty(d, np.float32)
    rc = lib.fl_trimmed_mean(sel, n, d, k, out)
    if rc != 0:
        return None
    return out
