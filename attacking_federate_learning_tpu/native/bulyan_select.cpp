// Incremental exact Bulyan selection (native host runtime kernel).
//
// The reference's Bulyan (reference defences.py:55-70) runs set_size
// strictly sequential Krum selections over a shrinking pool.  Evaluated
// naively that is O(n^2) scoring per selection -> O(n^3) total; the
// presort-once NumPy path (defenses/host.py:_prefix_scores) keeps the
// per-selection cost at O(n^2), still ~multi-hour at the n=10,240 north
// star.  This kernel maintains every row's score *incrementally*:
//
//   score_i = sum of the finite values among the first min(k, a) alive
//             columns of row i's presorted distance row
//             (k = users_count - selected - f [- 2 under paper scoring],
//              a = number of alive columns)
//
// which is exactly defenses/host.py:_prefix_scores.  Per row we keep
//   - a doubly-linked list over the row's rank positions holding the
//     alive columns (unlink = O(1) via the inverse permutation),
//   - the inclusive rank `bnd` of the prefix's last alive element,
//   - the alive count `cnt` and the f64 prefix sum.
// A selection step then costs O(1) amortized per row (membership test +
// at most a few link hops), so the whole exact q=1 selection is
// O(n * set_size) after the O(n^2) init — seconds, not hours, at 10k.
//
// Semantics notes (all matching defenses/host.py, which is itself pinned
// against the literal reference in tests/test_reference_parity.py):
//   - non-finite values (the +inf self-distance diagonal, adversarial
//     overflow rows) occupy prefix slots but contribute 0 to the sum;
//   - ties in the per-trip selection resolve to the lowest client index
//     (comparator on (score, index) == stable argsort);
//   - batch_select q > 1 selects q lowest against the SAME scores and
//     rescores between trips; q=1 is the reference semantics;
//   - scores accumulate in f64 (f32 values are exact in f64, so there
//     is no incremental drift) but COMPARE at f32 resolution: the NumPy
//     path's scores are f32 pairwise sums, so rows whose true sums
//     differ below f32 eps usually land on the same f32 value there and
//     tie-break by index — quantizing the comparator reproduces that
//     tie-break instead of resolving gaps the f32 computation cannot
//     see.  The precise contract: the two paths agree whenever score
//     gaps exceed the f32 summation's rounding error (a few ulps,
//     ~log2(n) worst case); within that noise band either pick is
//     inside the reference's own numerical indeterminacy (its torch
//     f32 sums have the same-order error with yet another ordering).
//     Measured (tests/test_native.py::test_adversarial_tie_randomized_
//     sweep, checked in): 3/1000 adversarial 1e6-magnitude trials
//     diverge at set level, every one a <=1-ulp f32 tie at its first
//     diverging trip; the sweep asserts that bound.
//
// Built on demand by attacking_federate_learning_tpu/native/__init__.py.
//
// Error contract: every kernel returns nonzero on ANY failure — including
// std::bad_alloc from the O(n^2) scratch (~16 bytes/entry, ~1.7 GB at
// n=10,240).  An exception escaping the extern "C" boundary into the
// ctypes frame would std::terminate the whole process; catching it keeps
// the documented degrade-to-NumPy fallback reachable.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

// Shared column-blocked machinery for the coordinate-wise kernels: the
// (n, d) matrix is row-major, so per-coordinate work would stride the
// whole matrix; instead gather BLOCK columns at a time into an
// L2-resident column-major buffer and run O(n) selection per column.
static const int32_t kColBlock = 128;

static void gather_block(const float* sel, int32_t n, int32_t d,
                         int32_t c0, int32_t bw, float* buf) {
    for (int64_t i = 0; i < n; ++i) {
        const float* row = sel + i * static_cast<int64_t>(d) + c0;
        for (int32_t c = 0; c < bw; ++c)
            buf[static_cast<size_t>(c) * n + i] = row[c];
    }
}

// NumPy median semantics: mid element (odd n) / f32 mean of the two
// middles (even n).  Clobbers tmp.
static float column_median(const float* col, int32_t n,
                           std::vector<float>& tmp) {
    std::copy(col, col + n, tmp.begin());
    const int32_t h = n / 2;
    std::nth_element(tmp.begin(), tmp.begin() + h, tmp.end());
    float med = tmp[h];
    if ((n & 1) == 0) {
        const float lo = *std::max_element(tmp.begin(), tmp.begin() + h);
        med = (lo + med) / 2.0f;
    }
    return med;
}

// Median-anchored trimmed mean (reference defences.py:48-51), evaluated
// column-blocked so the per-coordinate work runs on L2-resident data —
// the NumPy axis-0 formulation pays strided access over the whole
// (n, d) matrix for median, partition, and masks (~105 s at the
// (5326, 79510) exact-Bulyan tail; this kernel is ~2 passes + O(n) per
// coordinate).  Semantics match defenses/host.py:host_trimmed_mean_of:
//   - median = NumPy semantics (mean of the two middles for even n);
//   - keep the k smallest |dev| with boundary ties resolved to the
//     LOWEST row index (Python's stable sorted());
//   - mean of kept deviations + median, accumulated in f64.
static int trimmed_mean_impl(
    const float* sel,  // (n, d) row-major
    int32_t n, int32_t d, int32_t k,
    float* out         // (d,)
) {
    if (n <= 0 || d <= 0 || k <= 0 || k > n) return 1;
    std::vector<float> buf(static_cast<size_t>(n) * kColBlock);
    std::vector<float> tmp(n), adev(n);
    for (int32_t c0 = 0; c0 < d; c0 += kColBlock) {
        const int32_t bw = std::min(kColBlock, d - c0);
        gather_block(sel, n, d, c0, bw, buf.data());
        for (int32_t c = 0; c < bw; ++c) {
            const float* col = buf.data() + static_cast<size_t>(c) * n;
            const float med = column_median(col, n, tmp);
            for (int32_t i = 0; i < n; ++i)
                adev[i] = std::fabs(col[i] - med);
            std::copy(adev.begin(), adev.end(), tmp.begin());
            std::nth_element(tmp.begin(), tmp.begin() + (k - 1),
                             tmp.end());
            const float kth = tmp[k - 1];
            int32_t strict = 0;
            double sum = 0.0;
            for (int32_t i = 0; i < n; ++i)
                if (adev[i] < kth) {
                    ++strict;
                    sum += static_cast<double>(col[i] - med);
                }
            int32_t need = k - strict;  // boundary ties, lowest rows
            for (int32_t i = 0; i < n && need > 0; ++i)
                if (adev[i] == kth) {
                    sum += static_cast<double>(col[i] - med);
                    --need;
                }
            out[c0 + c] = static_cast<float>(
                sum / static_cast<double>(k) +
                static_cast<double>(med));
        }
    }
    return 0;
}

// Coordinate-wise median (defenses/median.py host path).
static int median_impl(
    const float* sel,  // (n, d) row-major
    int32_t n, int32_t d,
    float* out         // (d,)
) {
    if (n <= 0 || d <= 0) return 1;
    std::vector<float> buf(static_cast<size_t>(n) * kColBlock);
    std::vector<float> tmp(n);
    for (int32_t c0 = 0; c0 < d; c0 += kColBlock) {
        const int32_t bw = std::min(kColBlock, d - c0);
        gather_block(sel, n, d, c0, bw, buf.data());
        for (int32_t c = 0; c < bw; ++c)
            out[c0 + c] = column_median(
                buf.data() + static_cast<size_t>(c) * n, n, tmp);
    }
    return 0;
}

static int bulyan_select_impl(
    const float* D,        // (n, n) row-major distances, +inf diagonal
    const int32_t* order,  // (n, n) per-row argsort (ascending) of D
    int32_t n,
    int32_t users_count,
    int32_t f,
    int32_t set_size,
    int32_t q,
    int32_t paper_scoring,
    int32_t* out_selected  // (set_size,)
) {
    if (n <= 0 || set_size <= 0 || set_size > n || q < 1 || f < 0)
        return 1;
    const int64_t nn = static_cast<int64_t>(n) * n;

    // Row-major scratch.  sd = presorted values (gathered once so the
    // hot loops read contiguously); pos = inverse permutation; nxt/prv =
    // alive linked list over rank positions; head = first alive rank.
    std::vector<float> sd(nn);
    std::vector<int32_t> pos(nn), nxt(nn), prv(nn), head(n, 0);
    for (int64_t i = 0; i < n; ++i) {
        const int64_t base = i * n;
        const float* drow = D + base;
        const int32_t* ord = order + base;
        for (int32_t r = 0; r < n; ++r) {
            const int32_t c = ord[r];
            if (c < 0 || c >= n) return 1;
            sd[base + r] = drow[c];
            pos[base + c] = r;
            nxt[base + r] = r + 1;
            prv[base + r] = r - 1;
        }
    }

    std::vector<double> sum(n, 0.0);
    std::vector<int32_t> bnd(n, -1), cnt(n, 0);
    std::vector<uint8_t> alive_row(n, 1);

    int32_t s = 0;  // selected so far
    int32_t a = n;  // alive columns (columns == clients, same per row)
    const int32_t extra = paper_scoring ? 2 : 0;
    auto desired = [&]() -> int32_t {
        int64_t k = static_cast<int64_t>(users_count) - s - f - extra;
        if (k < 0) k = 0;
        if (k > a) k = a;
        return static_cast<int32_t>(k);
    };

    // Initial prefixes: all columns alive, ranks 0..d0-1.
    const int32_t d0 = desired();
    for (int64_t i = 0; i < n; ++i) {
        const int64_t base = i * n;
        double sm = 0.0;
        for (int32_t r = 0; r < d0; ++r) {
            const float v = sd[base + r];
            if (std::isfinite(v)) sm += static_cast<double>(v);
        }
        sum[i] = sm;
        cnt[i] = d0;
        bnd[i] = d0 - 1;
    }

    std::vector<int32_t> cand(n);
    std::vector<int32_t> pick;
    pick.reserve(q);

    while (s < set_size) {
        const int32_t r = std::min(q, set_size - s);
        int32_t m = 0;
        for (int32_t i = 0; i < n; ++i)
            if (alive_row[i]) cand[m++] = i;
        if (m < r) return 2;
        const auto cmp = [&](int32_t x, int32_t y) {
            const float sx = static_cast<float>(sum[x]);
            const float sy = static_cast<float>(sum[y]);
            if (sx != sy) return sx < sy;
            return x < y;
        };
        if (r < m)
            std::nth_element(cand.begin(), cand.begin() + (r - 1),
                             cand.begin() + m, cmp);
        std::sort(cand.begin(), cand.begin() + r, cmp);
        pick.assign(cand.begin(), cand.begin() + r);
        for (const int32_t j : pick) {
            out_selected[s++] = j;
            alive_row[j] = 0;
        }
        a -= r;
        const int32_t d = desired();  // next trip's k, post-trip pool

        // Row-major update: unlink this trip's deaths from each row's
        // list, then re-balance the prefix to the new desired size.
        for (int64_t i = 0; i < n; ++i) {
            const int64_t base = i * n;
            int32_t b = bnd[i], c = cnt[i];
            double sm = sum[i];
            for (const int32_t j : pick) {
                const int32_t p = pos[base + j];
                if (p <= b) {  // inside the prefix (p was alive)
                    const float v = sd[base + p];
                    if (std::isfinite(v)) sm -= static_cast<double>(v);
                    --c;
                    if (p == b) b = prv[base + p];
                }
                const int32_t pn = nxt[base + p];
                const int32_t pp = prv[base + p];
                if (pp >= 0) nxt[base + pp] = pn; else head[i] = pn;
                if (pn < n) prv[base + pn] = pp;
            }
            while (c > d) {  // k shrank: drop the prefix's last alive
                const float v = sd[base + b];
                if (std::isfinite(v)) sm -= static_cast<double>(v);
                --c;
                b = prv[base + b];
            }
            while (c < d) {  // deaths inside the prefix: extend it
                const int32_t nb = (b < 0) ? head[i] : nxt[base + b];
                if (nb >= n) break;  // fewer than d alive columns left
                const float v = sd[base + nb];
                if (std::isfinite(v)) sm += static_cast<double>(v);
                ++c;
                b = nb;
            }
            bnd[i] = b;
            cnt[i] = c;
            sum[i] = sm;
        }
    }
    return 0;
}

// extern "C" surface (see error contract at the top of the file).
extern "C" int fl_trimmed_mean(const float* sel, int32_t n, int32_t d,
                               int32_t k, float* out) {
    try {
        return trimmed_mean_impl(sel, n, d, k, out);
    } catch (...) {
        return 1;
    }
}

extern "C" int fl_median(const float* sel, int32_t n, int32_t d,
                         float* out) {
    try {
        return median_impl(sel, n, d, out);
    } catch (...) {
        return 1;
    }
}

extern "C" int fl_bulyan_select(const float* D, const int32_t* order,
                                int32_t n, int32_t users_count, int32_t f,
                                int32_t set_size, int32_t q,
                                int32_t paper_scoring,
                                int32_t* out_selected) {
    try {
        return bulyan_select_impl(D, order, n, users_count, f, set_size,
                                  q, paper_scoring, out_selected);
    } catch (...) {
        return 1;
    }
}
