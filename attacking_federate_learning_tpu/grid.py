"""Attack x defense grid runner — a thin campaign-spec wrapper.

The reference explores its attack/defense matrix by hand, one
``python main.py`` at a time (readme.md:23-28).  This driver compiles
its flag surface into a :class:`CampaignSpec` (campaigns/spec.py) and
delegates to the campaign engine's inline executor (campaigns/
scheduler.py) — the same sweep code path the campaign CLI, the fault
matrix and ``runs campaign`` use — while preserving the historical
contract: cells run in spec order in ONE process (model/data/compile
caches shared), every cell appends one JSON line to the summary as it
finishes, and composition rejections record as skipped cells instead
of killing the sweep:

    python -m attacking_federate_learning_tpu.grid --epochs 100 -s MNIST

Cell ids are ``cell_id_for(cfg, attack)`` — the config-hash
``run_id_for`` join key extended with the attack name, because the
plain config hash collapses attacks that share a config (signflip vs
alie).  Under ``--journal`` the sweep becomes a persisted campaign:
exactly-once cell accounting under ``runs/campaigns/<id>/``, per-run
journals + registry stamps (so ``runs campaign <id>`` renders the
grid table straight from the registry), and a re-invoke completes
only the remaining cells.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.config import ExperimentConfig


def _all_defenses():
    # Derived from the registry so new defenses join the sweep on
    # registration (names() is sorted; keep NoDefense first as the
    # baseline column).
    from attacking_federate_learning_tpu.defenses import DEFENSES
    names = DEFENSES.names()
    return ["NoDefense"] + [n for n in names if n != "NoDefense"]


def _all_attacks():
    from attacking_federate_learning_tpu.attacks import ATTACKS
    names = ATTACKS.names()
    return ["none"] + [n for n in names if n != "none"]


def grid_spec(base: ExperimentConfig, defenses=None,
              attacks=None) -> "CampaignSpec":
    """The grid flag surface as a campaign spec (defense x attack axes
    over the base config)."""
    from attacking_federate_learning_tpu.campaigns.spec import (
        CampaignSpec
    )

    return CampaignSpec(
        name="grid",
        base=dataclasses.asdict(base),
        axes={"defense": list(defenses or _all_defenses()),
              "attack": list(attacks or _all_attacks())},
        order="spec")


def _grid_row(cell, row) -> dict:
    """One campaign cell record in the historical grid summary shape."""
    rec = {"defense": (cell.cfg.defense if cell.cfg is not None
                       else cell.overrides.get("defense")),
           "attack": cell.attack}
    state = row["state"]
    if state == "skipped":
        rec["skipped"] = row.get("reason")
        if cell.cfg is not None:  # config-level rejections have no
            rec["run_id"] = cell.cell_id  # config hash to join on
        return rec
    rec["run_id"] = cell.cell_id
    if state == "failed":
        rec["failed"] = row.get("reason")
        rec["wall_s"] = row.get("wall_s")
        return rec
    rec["final_accuracy"] = row.get("final_accuracy")
    rec["max_accuracy"] = row.get("max_accuracy")
    rec["rounds"] = row.get("rounds")
    rec["wall_s"] = row.get("wall_s")
    if "final_asr" in row:
        rec["final_asr"] = row["final_asr"]
    return rec


def run_grid(base: ExperimentConfig, defenses=None, attacks=None,
             out_path=None, journal=False, order="spec"):
    """Run the grid as an inline campaign; returns the summary rows.

    ``journal=False`` (the historical default) keeps the sweep
    ephemeral — no runs/ artifacts, just the summary JSONL;
    ``journal=True`` persists the campaign journal + per-run journals
    and makes the sweep resumable."""
    from attacking_federate_learning_tpu.campaigns.scheduler import (
        Campaign
    )

    spec = grid_spec(base, defenses, attacks)
    os.makedirs(base.log_dir, exist_ok=True)
    out_path = out_path or os.path.join(base.log_dir, "grid_summary.jsonl")
    results = []
    summary = open(out_path, "w")

    def on_cell(cell, row):
        # Append per cell so a failing cell can't discard finished
        # results (the historical incremental-summary contract).
        rec = _grid_row(cell, row)
        results.append(rec)
        summary.write(json.dumps(rec) + "\n")
        summary.flush()
        print(json.dumps(rec), flush=True)

    camp = Campaign(spec, executor="inline", order=order,
                    journal_runs=journal, persist=journal,
                    on_cell=on_cell)
    try:
        camp.run()
    finally:
        summary.close()
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description="attack x defense grid")
    p.add_argument("-s", "--dataset", default=C.SYNTH_MNIST)
    p.add_argument("-n", "--users-count", default=10, type=int)
    p.add_argument("-m", "--mal-prop", default=0.24, type=float)
    p.add_argument("-e", "--epochs", default=50, type=int)
    p.add_argument("-c", "--batch_size", default=128, type=int)
    p.add_argument("--defenses", nargs="*", default=None)
    p.add_argument("--attacks", nargs="*", default=None)
    p.add_argument("--secagg", default="off",
                   choices=["off", "vanilla", "groupwise"],
                   help="secure-aggregation visibility mode for every "
                        "cell (protocols/secagg.py); incompatible "
                        "defense cells record as skipped")
    p.add_argument("--aggregation", default="flat",
                   choices=["flat", "hierarchical"])
    p.add_argument("--megabatch", default=0, type=int)
    p.add_argument("--tier2-defense", default=None,
                   choices=["NoDefense", "Krum", "TrimmedMean", "Bulyan",
                            "Median"])
    p.add_argument("--mal-placement", default="spread",
                   choices=["spread", "concentrated"])
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--backend", default="auto",
                   choices=["auto", "cpu", "tpu"])
    p.add_argument("--synth-train", default=ExperimentConfig.synth_train,
                   type=int)
    p.add_argument("--synth-test", default=ExperimentConfig.synth_test,
                   type=int)
    p.add_argument("--log-dir", default="logs", type=str)
    p.add_argument("--run-dir", default="runs", type=str,
                   help="campaign + run journal root (used with "
                        "--journal)")
    p.add_argument("--out", default=None, type=str,
                   help="summary JSONL path (default <log-dir>/"
                        "grid_summary.jsonl)")
    p.add_argument("--journal", action="store_true",
                   help="persist the sweep as a campaign: exactly-once "
                        "cell accounting under runs/campaigns/<id>/, "
                        "per-run journals + registry stamps, resumable "
                        "re-invocation ('runs campaign <id>' renders "
                        "the table)")
    p.add_argument("--order", default="spec",
                   choices=["spec", "grouped", "shuffled"],
                   help="cell execution order (campaigns/scheduler.py; "
                        "'spec' preserves the historical product order)")
    args = p.parse_args(argv)

    from attacking_federate_learning_tpu.cli import apply_backend
    apply_backend(args.backend)

    base = ExperimentConfig(dataset=args.dataset,
                            users_count=args.users_count,
                            mal_prop=args.mal_prop, epochs=args.epochs,
                            batch_size=args.batch_size, seed=args.seed,
                            backend=args.backend, log_dir=args.log_dir,
                            run_dir=args.run_dir,
                            synth_train=args.synth_train,
                            synth_test=args.synth_test,
                            secagg=args.secagg,
                            aggregation=args.aggregation,
                            megabatch=args.megabatch,
                            tier2_defense=args.tier2_defense,
                            mal_placement=args.mal_placement)
    run_grid(base, args.defenses, args.attacks, out_path=args.out,
             journal=args.journal, order=args.order)


if __name__ == "__main__":
    main()
