"""Attack x defense grid runner.

The reference explores its attack/defense matrix by hand, one
``python main.py`` at a time (readme.md:23-28).  This driver runs the whole
grid in one process — model/data/compile caches shared across cells, one
JSONL summary — which is what makes the "full grid overnight" target
(BASELINE.md) a single command:

    python -m attacking_federate_learning_tpu.grid --epochs 100 -s MNIST
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools
import json
import os
import time

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.config import ExperimentConfig


def _all_defenses():
    # Derived from the registry so new defenses join the sweep on
    # registration (names() is sorted; keep NoDefense first as the
    # baseline column).
    from attacking_federate_learning_tpu.defenses import DEFENSES
    names = DEFENSES.names()
    return ["NoDefense"] + [n for n in names if n != "NoDefense"]


def _all_attacks():
    from attacking_federate_learning_tpu.attacks import ATTACKS
    names = ATTACKS.names()
    return ["none"] + [n for n in names if n != "none"]


def run_grid(base: ExperimentConfig, defenses=None, attacks=None,
             out_path=None):
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.utils.lifecycle import run_id_for
    from attacking_federate_learning_tpu.utils.metrics import RunLogger

    defenses = defenses or _all_defenses()
    attacks = attacks or _all_attacks()
    dataset = load_dataset(base.dataset, base.data_dir, base.seed,
                           synth_train=base.synth_train,
                           synth_test=base.synth_test)
    os.makedirs(base.log_dir, exist_ok=True)
    out_path = out_path or os.path.join(base.log_dir, "grid_summary.jsonl")
    results = []
    summary = open(out_path, "w")

    def emit(cell):
        # Append per cell so a failing cell can't discard finished results.
        results.append(cell)
        summary.write(json.dumps(cell) + "\n")
        summary.flush()
        print(json.dumps(cell), flush=True)

    for defense, attack in itertools.product(defenses, attacks):
        run_id = None
        try:
            # Construction inside the try: composition rejections
            # (defense validity bounds, and since PR 7 the secagg
            # visibility rules — a robust defense under --secagg is a
            # ValueError at config time) record as skipped cells
            # instead of killing the sweep.
            cfg = dataclasses.replace(
                base, defense=defense,
                backdoor="pattern" if attack == "backdoor" else False,
                num_std=0.0 if attack == "none" else base.num_std,
                mal_prop=0.0 if attack == "none" else base.mal_prop)
            # Config-hash identity (utils/lifecycle.py): the join key
            # between a GRID row and the run registry (runs/index.jsonl).
            run_id = run_id_for(cfg)
            attacker = make_attacker(cfg, dataset=dataset,
                                     name=attack)
            exp = FederatedExperiment(cfg, attacker=attacker,
                                      dataset=dataset)
        except ValueError as e:  # composition guard — record & skip
            cell = {"defense": defense, "attack": attack,
                    "skipped": str(e)}
            if run_id is not None:  # config-level rejections have no
                cell["run_id"] = run_id  # config hash to join on
            emit(cell)
            continue
        t0 = time.time()
        try:
            # Context-managed: a cell that dies still closes its JSONL
            # and flushes its accuracy CSV (utils/metrics.py:RunLogger).
            with RunLogger(cfg, cfg.output, cfg.log_dir,
                           jsonl_name=f"grid_{defense}_{attack}") as logger:
                out = exp.run(logger)
        except FloatingPointError as e:  # backdoor nan guard — record cell
            emit({"defense": defense, "attack": attack,
                  "run_id": run_id, "failed": str(e),
                  "wall_s": round(time.time() - t0, 2)})
            continue
        cell = {
            "defense": defense, "attack": attack, "run_id": run_id,
            "final_accuracy": out["accuracies"][-1],
            "max_accuracy": max(out["accuracies"]),
            "rounds": cfg.epochs,
            "wall_s": round(time.time() - t0, 2),
        }
        if attack == "backdoor":
            cell["final_asr"] = exp.attacker.test_asr(exp.state.weights)
        emit(cell)

    summary.close()
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description="attack x defense grid")
    p.add_argument("-s", "--dataset", default=C.SYNTH_MNIST)
    p.add_argument("-n", "--users-count", default=10, type=int)
    p.add_argument("-m", "--mal-prop", default=0.24, type=float)
    p.add_argument("-e", "--epochs", default=50, type=int)
    p.add_argument("-c", "--batch_size", default=128, type=int)
    p.add_argument("--defenses", nargs="*", default=None)
    p.add_argument("--attacks", nargs="*", default=None)
    p.add_argument("--secagg", default="off",
                   choices=["off", "vanilla", "groupwise"],
                   help="secure-aggregation visibility mode for every "
                        "cell (protocols/secagg.py); incompatible "
                        "defense cells record as skipped")
    p.add_argument("--aggregation", default="flat",
                   choices=["flat", "hierarchical"])
    p.add_argument("--megabatch", default=0, type=int)
    p.add_argument("--tier2-defense", default=None,
                   choices=["NoDefense", "Krum", "TrimmedMean", "Bulyan",
                            "Median"])
    p.add_argument("--mal-placement", default="spread",
                   choices=["spread", "concentrated"])
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--backend", default="auto",
                   choices=["auto", "cpu", "tpu"])
    p.add_argument("--synth-train", default=ExperimentConfig.synth_train,
                   type=int)
    p.add_argument("--synth-test", default=ExperimentConfig.synth_test,
                   type=int)
    p.add_argument("--log-dir", default="logs", type=str)
    p.add_argument("--out", default=None, type=str,
                   help="summary JSONL path (default <log-dir>/"
                        "grid_summary.jsonl)")
    args = p.parse_args(argv)

    from attacking_federate_learning_tpu.cli import apply_backend
    apply_backend(args.backend)

    base = ExperimentConfig(dataset=args.dataset,
                            users_count=args.users_count,
                            mal_prop=args.mal_prop, epochs=args.epochs,
                            batch_size=args.batch_size, seed=args.seed,
                            backend=args.backend, log_dir=args.log_dir,
                            synth_train=args.synth_train,
                            synth_test=args.synth_test,
                            secagg=args.secagg,
                            aggregation=args.aggregation,
                            megabatch=args.megabatch,
                            tier2_defense=args.tier2_defense,
                            mal_placement=args.mal_placement)
    run_grid(base, args.defenses, args.attacks, out_path=args.out)


if __name__ == "__main__":
    main()
