"""Campaign engine (ISSUE 10): spec expansion determinism, the
composition-rejection pre-validation matrix, SIGKILL-mid-campaign
resume with exactly-once accounting, cache-aware ordering, the
deadline seam, and the ``runs campaign`` table render.

The kill/resume leg runs real inline campaigns in SUBPROCESSES (the
injection seams ``FL_CAMPAIGN_KILL_*`` os._exit mid-campaign); a
module-scoped fixture runs the 2x2 campaign once and several tests
audit its artifacts.  The measured grouped-vs-shuffled cache proof is
``slow``-marked (three supervisor-mode campaigns, each cell a child
process — ~70 s) — GRID_RESULTS.md round 10 records a measured run.
"""

import dataclasses
import json
import os
import subprocess
import sys

import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.campaigns import (
    Campaign, CampaignJournal, CampaignSpec, cell_id_for,
    composition_reject_reason, hlo_signature, order_cells
)
from attacking_federate_learning_tpu.campaigns.scheduler import (
    EXIT_DEADLINE, adjacency, trim_cache
)
from attacking_federate_learning_tpu.campaigns.spec import (
    cfg_to_cli_args, verify_cli_round_trip
)
from attacking_federate_learning_tpu.config import ExperimentConfig


def _base(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 12)
    kw.setdefault("mal_prop", 0.25)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 2)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("log_dir", os.path.join(str(tmp_path), "logs"))
    kw.setdefault("run_dir", os.path.join(str(tmp_path), "runs"))
    return kw


class RecordingExecutor:
    """Fake executor: records which cells execute, returns canned
    results, and can advance an injected clock per cell."""

    def __init__(self, clock=None, step=0.0):
        self.cells = []
        self.clock = clock
        self.step = step

    def run(self, cell, camp):
        self.cells.append(cell.cell_id)
        if self.clock is not None:
            self.clock.t += self.step
        return {"state": "done", "rc": 0, "final_accuracy": 50.0,
                "max_accuracy": 50.0, "rounds": cell.cfg.epochs,
                "wall_s": 0.0}


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# expansion determinism + identity

def test_spec_expansion_deterministic(tmp_path):
    spec = CampaignSpec(
        name="det", base=_base(tmp_path),
        axes={"defense": ["NoDefense", "Krum"],
              "attack": ["none", "alie"], "seed": [0, 1]})
    a = spec.expand()
    b = spec.expand()
    assert [c.cell_id for c in a] == [c.cell_id for c in b]
    assert [c.group for c in a] == [c.group for c in b]
    assert len(a) == 8 and len({c.cell_id for c in a}) == 8
    # JSON round trip preserves identity and expansion.
    spec2 = CampaignSpec.from_json(spec.to_json())
    assert spec2.campaign_id == spec.campaign_id
    assert [c.cell_id for c in spec2.expand()] == [c.cell_id for c in a]
    # The attack name is part of cell identity: two attacks sharing a
    # config (alie vs signflip) must not share a journal.
    cfg = ExperimentConfig(**_base(tmp_path))
    assert cell_id_for(cfg, "alie") != cell_id_for(cfg, "signflip")
    assert cell_id_for(cfg, "auto") != cell_id_for(cfg, "alie")


def test_spec_duplicate_cells_rejected(tmp_path):
    spec = CampaignSpec(name="dup", base=_base(tmp_path),
                        axes={"defense": ["Krum", "Krum"]})
    with pytest.raises(ValueError, match="duplicate cell id"):
        spec.expand()


def test_hlo_signature_groups(tmp_path):
    """The grouping heuristic measured on this engine: epochs and the
    io/cadence fields are program-inert, seed and the defense are not
    (the training set is baked into the fused span as constants)."""
    cfg = ExperimentConfig(**_base(tmp_path))
    same = dataclasses.replace(cfg, epochs=8, checkpoint_every=5,
                               log_dir="elsewhere")
    assert hlo_signature(cfg) == hlo_signature(same)
    assert hlo_signature(cfg) != hlo_signature(
        dataclasses.replace(cfg, seed=1))
    assert hlo_signature(cfg) != hlo_signature(
        dataclasses.replace(cfg, defense="Krum"))
    assert hlo_signature(cfg, "alie") != hlo_signature(cfg, "signflip")


# ---------------------------------------------------------------------------
# the composition-rejection matrix, pre-validated

# (overrides, attack, message fragment) — every known-invalid combo the
# pre-check must skip.  Spans config-level rejections (ExperimentConfig
# __post_init__) and engine-level ones (the pure init checks).
_INVALID = [
    (dict(defense="Bulyan", users_count=10, mal_prop=0.24), "alie",
     "4*corrupted_count"),
    (dict(defense="Krum", users_count=8, mal_prop=0.5), "alie",
     "2*corrupted_count"),
    (dict(secagg="vanilla", defense="Krum"), "auto",
     "server never sees per-client"),
    (dict(secagg="groupwise", aggregation="flat"), "auto",
     "requires --aggregation hierarchical"),
    (dict(secagg="vanilla", telemetry=True), "auto",
     "nothing per-client OR per-group"),
    (dict(aggregation="hierarchical", megabatch=5, users_count=12),
     "auto", "must divide users_count"),
    # ISSUE 19: hierarchical ⊕ faults is now a VALID composition; the
    # rejections that remain are the real structural ones — correlated
    # shard-domain death needs shard domains to kill, and the straggler
    # ring buffer is a cross-round carry the SPMD client_map can't
    # thread.
    (dict(faults=dict(shard_dropout=0.3), defense="Median"), "auto",
     "shard-DOMAIN"),
    (dict(aggregation="hierarchical", megabatch=4, users_count=32,
          mesh_shape=[8, 1], faults=dict(straggler=0.1),
          defense="TrimmedMean"), "auto", "SPMD client_map"),
    (dict(aggregation="hierarchical", megabatch=4,
          defense="GeoMedian"), "auto", "tier-1 defense"),
    (dict(aggregation="async", async_buffer=0), "auto",
     "--async-buffer >= 1"),
    (dict(aggregation="async", async_buffer=20, users_count=12,
          mal_prop=0.25), "auto", "exceeds the cohort"),
    (dict(aggregation="async", async_buffer=4, defense="TrimmedMean",
          users_count=12, mal_prop=0.25), "auto", "k - f - 1"),
    (dict(backdoor="pattern"), "backdoor_timed",
     "requires aggregation='async'"),
    (dict(faults=dict(dropout=0.2), defense="DnC"), "auto",
     "mask-aware defense"),
    (dict(participation=0.25, users_count=12, mal_prop=0.1), "alie",
     "malicious cohort to 0"),
]


@pytest.mark.parametrize("overrides,attack,fragment", _INVALID)
def test_rejection_matrix_precheck(tmp_path, overrides, attack,
                                   fragment):
    merged = _base(tmp_path, **overrides)
    reason = composition_reject_reason(merged, attack)
    assert reason is not None and fragment in reason, (reason, fragment)


def test_precheck_agrees_with_real_construction(tmp_path):
    """The pre-check must not drift from what the engine actually
    rejects: for engine-level combos, FederatedExperiment construction
    raises the SAME message the pre-check returned."""
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cases = [
        dict(defense="Bulyan", users_count=10, mal_prop=0.24),
        dict(faults=dict(shard_dropout=0.3), defense="Median"),
        dict(aggregation="async", async_buffer=20, users_count=12,
             mal_prop=0.25),
    ]
    ds = load_dataset(C.SYNTH_MNIST, seed=0, synth_train=256,
                      synth_test=64)
    for overrides in cases:
        merged = _base(tmp_path, **overrides)
        reason = composition_reject_reason(merged, "alie")
        assert reason
        cfg = ExperimentConfig(**merged)       # config itself is fine
        with pytest.raises(ValueError) as ei:
            FederatedExperiment(
                cfg, attacker=make_attacker(cfg, dataset=ds,
                                            name="alie"), dataset=ds)
        assert str(ei.value) == reason


def test_skipped_cells_never_reach_the_executor(tmp_path):
    spec = CampaignSpec(
        name="rej", base=_base(tmp_path),
        axes={"defense": ["NoDefense", "Bulyan"],
              "attack": ["none", "alie"]})
    rec = RecordingExecutor()
    camp = Campaign(spec, executor=rec, journal_runs=False,
                    persist=False)
    assert camp.run() == 0
    cells = spec.expand()
    skipped = [c for c in cells if c.skip]
    assert {(c.overrides["defense"], c.attack) for c in skipped} == {
        ("Bulyan", "alie")}
    executed = set(rec.cells)
    assert all(c.cell_id not in executed for c in skipped)
    assert len(executed) == 3
    # The skip carried the rejection message into the journal record.
    rec_j = camp.journal.cells[skipped[0].cell_id]
    assert rec_j["state"] == "skipped"
    assert "4*corrupted_count" in rec_j["reason"]


# ---------------------------------------------------------------------------
# ordering

def _cells_two_groups(tmp_path):
    spec = CampaignSpec(
        name="ord", base=_base(tmp_path),
        axes={"defense": ["Krum", "TrimmedMean"],
              "epochs": [2, 4, 6, 8]})
    return spec, spec.expand()


def test_grouped_ordering_is_adjacent_and_deterministic(tmp_path):
    spec, cells = _cells_two_groups(tmp_path)
    assert len({c.group for c in cells}) == 2       # 2 HLO groups
    g = order_cells(cells, "grouped", spec.campaign_id)
    assert adjacency(g) == len(cells) - 2           # fully contiguous
    assert [c.cell_id for c in g] == [
        c.cell_id for c in order_cells(cells, "grouped",
                                       spec.campaign_id)]
    # spec order interleaves the groups (defense is the outer axis...
    # epochs inner, so spec order is already grouped here); shuffled
    # must be deterministic and is the measured control arm.
    s1 = order_cells(cells, "shuffled", spec.campaign_id)
    s2 = order_cells(cells, "shuffled", spec.campaign_id)
    assert [c.cell_id for c in s1] == [c.cell_id for c in s2]
    assert adjacency(s1) <= adjacency(g)


def test_priority_bands_override_grouping(tmp_path):
    spec = CampaignSpec(
        name="prio", base=_base(tmp_path),
        axes={"defense": ["Krum", "TrimmedMean"], "epochs": [2, 4]},
        priorities={"defense=TrimmedMean": 10})
    cells = spec.expand()
    ordered = order_cells(cells, "grouped", spec.campaign_id)
    # The high-priority band runs first, grouping applies inside it.
    assert [c.overrides["defense"] for c in ordered] == [
        "TrimmedMean", "TrimmedMean", "Krum", "Krum"]


def test_trim_cache_evicts_oldest(tmp_path):
    d = tmp_path / "cache"
    os.makedirs(d)
    for i, name in enumerate(["a-cache", "b-cache", "c-cache"]):
        p = d / name
        p.write_bytes(b"x" * 100)
        os.utime(p, (i, i))                    # a oldest, c newest
        (d / (name + "-atime")).write_bytes(b"")
    evicted = trim_cache(str(d), 250)
    assert evicted == 1
    left = {f for f in os.listdir(d) if not f.endswith("-atime")}
    assert left == {"b-cache", "c-cache"}      # a (oldest) evicted
    assert not os.path.exists(d / "a-cache-atime")


# ---------------------------------------------------------------------------
# deadline stop + resume (injected clock, fake executor)

def test_deadline_stop_then_resume(tmp_path):
    spec = CampaignSpec(name="dl", base=_base(tmp_path),
                        axes={"defense": ["NoDefense", "Krum",
                                          "Median", "TrimmedMean"]})
    clock = FakeClock()
    rec = RecordingExecutor(clock=clock, step=10.0)
    camp = Campaign(spec, executor=rec, journal_runs=False,
                    deadline_s=25.0, clock=clock)
    rc = camp.run()
    assert rc == EXIT_DEADLINE
    assert len(rec.cells) == 3          # 0s, 10s, 20s; 30s > deadline
    man = camp.journal.read_manifest()
    assert man["status"] == "deadline"
    pending = [cid for cid, row in man["cells"].items()
               if row["state"] == "pending"]
    assert len(pending) == 1
    # Resume with a fresh window: only the remaining cell executes.
    clock2 = FakeClock()
    rec2 = RecordingExecutor(clock=clock2, step=10.0)
    camp2 = Campaign(spec, executor=rec2, journal_runs=False,
                     deadline_s=25.0, clock=clock2)
    assert camp2.run() == 0
    assert rec2.cells == pending
    j = CampaignJournal(camp2.run_dir, spec.campaign_id)
    assert j.verify([c.cell_id for c in spec.expand()]) == []
    assert j.read_manifest()["status"] == "done"
    assert j.attempt == 2


def test_journal_recommit_refused_and_torn_tail_sealed(tmp_path):
    j = CampaignJournal(str(tmp_path), "c1")
    j.start_attempt()
    j.commit_cell("cell_a", "done", rc=0)
    with pytest.raises(ValueError, match="exactly-once"):
        j.commit_cell("cell_a", "failed")
    with pytest.raises(ValueError, match="state must be"):
        j.commit_cell("cell_b", "running")
    j.close()
    # A SIGKILL mid-append leaves a torn tail; the next attempt seals
    # and skips it without losing committed records.
    with open(j.journal_path, "a") as f:
        f.write('{"kind": "cell", "cell": "torn')
    j2 = CampaignJournal(str(tmp_path), "c1")
    assert j2.torn_lines == 1
    assert j2.state_of("cell_a") == "done"
    j2.commit_cell("cell_b", "skipped", reason="x")
    j3 = CampaignJournal(str(tmp_path), "c1")
    assert j3.state_of("cell_b") == "skipped"
    assert j3.verify() == []


# ---------------------------------------------------------------------------
# kill mid-campaign -> resume (real subprocesses, inline executor)

CLI_ENV = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")


def _invoke_campaign(spec_path, env=None, expect=0):
    r = subprocess.run(
        [sys.executable, "-m", "attacking_federate_learning_tpu.campaigns",
         str(spec_path), "--executor", "inline"],
        env=env or CLI_ENV, capture_output=True, text=True)
    assert r.returncode == expect, (r.returncode, r.stderr[-2000:])
    return r


@pytest.fixture(scope="module")
def killed_campaign(tmp_path_factory):
    """One real 2x2 campaign, SIGKILLed (os._exit injection) after two
    cells, then resumed to completion; several tests audit it."""
    work = tmp_path_factory.mktemp("campaign_kill")
    base = _base(work)
    spec = dict(name="kr", base=base,
                axes={"defense": ["Krum", "TrimmedMean"],
                      "attack": ["none", "alie"]})
    spec_path = work / "spec.json"
    spec_path.write_text(json.dumps(spec))
    env = dict(CLI_ENV, FL_CAMPAIGN_KILL_AFTER_CELLS="2")
    _invoke_campaign(spec_path, env=env, expect=137)
    # Mid-campaign state: exactly 2 terminal cells, the rest pending.
    camp_id = os.listdir(os.path.join(base["run_dir"], "campaigns"))[0]
    j = CampaignJournal(base["run_dir"], camp_id)
    assert len(j.cells) == 2
    _invoke_campaign(spec_path)
    return {"work": work, "base": base, "camp_id": camp_id,
            "spec": CampaignSpec.from_json(json.dumps(spec))}


def test_kill_resume_exactly_once(killed_campaign):
    base = killed_campaign["base"]
    camp_id = killed_campaign["camp_id"]
    spec = killed_campaign["spec"]
    j = CampaignJournal(base["run_dir"], camp_id)
    expected = [c.cell_id for c in spec.expand()]
    assert j.verify(expected) == []
    man = j.read_manifest()
    assert man["status"] == "done"
    assert man["counts"] == {"done": 4}
    assert j.attempt == 2
    # Commits split across the two attempts — the resume executed only
    # the remaining cells.
    by_attempt = {}
    for rec in j.records():
        if rec.get("kind") == "cell":
            by_attempt.setdefault(rec["attempt"], []).append(rec["cell"])
    assert len(by_attempt[1]) == 2 and len(by_attempt[2]) == 2


def test_kill_resume_zero_duplicate_registry_stamps(killed_campaign):
    base = killed_campaign["base"]
    idx = os.path.join(base["run_dir"], "index.jsonl")
    ids = [json.loads(line)["run_id"] for line in open(idx)]
    assert len(ids) == 4
    assert len(ids) == len(set(ids))


def test_campaign_event_stream_validates_v8(killed_campaign):
    import importlib.util

    from attacking_federate_learning_tpu.utils.metrics import iter_events

    base = killed_campaign["base"]
    camp_id = killed_campaign["camp_id"]
    events_path = os.path.join(base["run_dir"], "campaigns", camp_id,
                               "events.jsonl")
    events = list(iter_events(events_path))       # emitter validation
    assert all(e["kind"] == "campaign" and e["v"] >= 8 for e in events)
    phases = [e["phase"] for e in events]
    assert phases.count("campaign_start") == 2    # two attempts
    assert phases.count("cell_done") == 4
    assert phases.count("campaign_done") == 1     # only the resume ends
    # The standalone validator (CI's view) agrees.
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "check_events.py")
    s = importlib.util.spec_from_file_location("check_events", path)
    ce = importlib.util.module_from_spec(s)
    s.loader.exec_module(ce)
    counts, _, errors = ce.check_file(events_path)
    assert errors == [] and counts == {"campaign": len(events)}


def test_runs_campaign_table_matches_manifests_bit_exactly(
        killed_campaign, capsys):
    """Acceptance: the rendered table's values come from the registry
    and match the per-run manifest values bit-exactly; skipped cells
    show their rejection reason."""
    from attacking_federate_learning_tpu.report import campaign_table
    from attacking_federate_learning_tpu.runs_cli import main as runs_main

    base = killed_campaign["base"]
    camp_id = killed_campaign["camp_id"]
    rc = runs_main(["--run-dir", base["run_dir"], "--bench", "",
                    "--progress", "", "--json", "campaign", camp_id])
    assert rc == 0
    blob = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    table = blob["table"]
    assert table["rows"] == ["Krum", "TrimmedMean"]
    assert table["cols"] == ["none", "alie"]
    for cid, row in blob["manifest"]["cells"].items():
        (rec,) = table["cells"][f"{row['defense']}|{row['attack']}"]
        assert rec["source"] == "registry"
        run_man = json.load(open(os.path.join(
            base["run_dir"], cid, "manifest.json")))
        assert rec["final_accuracy"] == run_man["final_accuracy"]
        assert rec["max_accuracy"] == run_man["max_accuracy"]
    # Human render carries the skip column for a campaign with one.
    spec2 = CampaignSpec(
        name="skiprender", base=killed_campaign["base"],
        axes={"defense": ["Bulyan"], "attack": ["alie"]})
    spec2.base["mal_prop"] = 0.25
    man2 = {"campaign_id": "x", "status": "done",
            "cells": {c.cell_id: {**c.row(), "state": "skipped",
                                  "reason": c.skip}
                      for c in spec2.expand()}}
    t2 = campaign_table(man2, {})
    (rec2,) = t2["cells"]["Bulyan|alie"]
    assert rec2["state"] == "skipped"
    assert "4*corrupted_count" in rec2["reason"]


def test_kill_before_commit_adopts_without_rerun(tmp_path):
    """The harsher kill point: the cell's run FINISHED (journal 'done',
    registry stamped) but the campaign commit never happened.  Resume
    must adopt the finished run instead of re-executing — zero
    duplicate registry stamps is the observable contract."""
    base = _base(tmp_path)
    spec = dict(name="kb", base=base, axes={"defense": ["NoDefense",
                                                        "Krum"]})
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec))
    env = dict(CLI_ENV, FL_CAMPAIGN_KILL_BEFORE_COMMIT="1")
    _invoke_campaign(spec_path, env=env, expect=137)
    camp_id = os.listdir(os.path.join(base["run_dir"], "campaigns"))[0]
    j = CampaignJournal(base["run_dir"], camp_id)
    assert j.cells == {}                     # nothing committed...
    idx = os.path.join(base["run_dir"], "index.jsonl")
    assert len(open(idx).readlines()) == 1   # ...but the run stamped
    _invoke_campaign(spec_path)
    j2 = CampaignJournal(base["run_dir"], camp_id)
    assert j2.read_manifest()["counts"] == {"done": 2}
    adopted = [rec for rec in j2.cells.values() if rec.get("adopted")]
    assert len(adopted) == 1                 # the killed cell, adopted
    ids = [json.loads(line)["run_id"] for line in open(idx)]
    assert len(ids) == 2 and len(set(ids)) == 2   # still no duplicates


# ---------------------------------------------------------------------------
# stale-index footgun

def test_runs_list_no_refresh_warns_when_stale(tmp_path, capsys):
    from attacking_federate_learning_tpu.runs_cli import main as runs_main
    from attacking_federate_learning_tpu.utils.registry import RunRegistry

    run_dir = tmp_path / "runs"
    d = run_dir / "r1"
    os.makedirs(d)
    (d / "manifest.json").write_text(json.dumps(
        {"run_id": "r1", "status": "done"}))
    reg = RunRegistry(str(run_dir))
    reg.refresh()
    assert reg.stale_run_ids() == []
    capsys.readouterr()
    # The store moves under the index (backdate the index rather than
    # future-date the manifest, so the refresh below really clears it).
    os.utime(reg.index_path,
             (os.path.getmtime(d / "manifest.json") - 5,) * 2)
    assert reg.stale_run_ids() == ["r1"]
    rc = runs_main(["--run-dir", str(run_dir), "--bench", "",
                    "--progress", "", "list", "--no-refresh"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "WARNING" in out and "stale" in out
    # A refreshing list clears the staleness (and the warning).
    rc = runs_main(["--run-dir", str(run_dir), "--bench", "",
                    "--progress", "", "list"])
    assert rc == 0
    assert "WARNING" not in capsys.readouterr().out
    assert reg.stale_run_ids() == []


# ---------------------------------------------------------------------------
# the CLI round trip (supervisor executor's child surface)

def test_cfg_to_cli_args_round_trip(tmp_path):
    cases = [
        _base(tmp_path),
        _base(tmp_path, defense="Krum", seed=3, partition="dirichlet",
              dirichlet_alpha=0.3, participation=0.5, mal_prop=0.5),
        _base(tmp_path, aggregation="hierarchical", megabatch=4,
              tier2_defense="Krum", mal_placement="concentrated",
              telemetry=True),
        _base(tmp_path, aggregation="async", async_buffer=8,
              staleness_weight="poly", defense="Krum"),
        _base(tmp_path, faults=dict(dropout=0.1, corrupt=0.05,
                                    corrupt_mode="scale"),
              defense="Median", checkpoint_every=2),
        # ISSUE 19: faults ⊕ hierarchical round-trips, shard-domain
        # flags included.
        _base(tmp_path, aggregation="hierarchical", megabatch=4,
              defense="TrimmedMean",
              faults=dict(dropout=0.1, shard_dropout=0.25,
                          shard_dropout_dwell=2)),
        _base(tmp_path, secagg="vanilla", defense="NoDefense",
              backdoor="pattern"),
    ]
    for kw in cases:
        cfg = ExperimentConfig(**kw)
        for attack in ("auto", "alie"):
            from attacking_federate_learning_tpu.campaigns.spec import (
                Cell
            )
            cell = Cell(cell_id=cell_id_for(cfg, attack), overrides=kw,
                        attack=attack, cfg=cfg)
            assert verify_cli_round_trip(cell) is None, kw
    # An inexpressible field fails LOUDLY instead of silently running
    # a drifted config.
    cfg = ExperimentConfig(**_base(tmp_path, test_step=3))
    from attacking_federate_learning_tpu.campaigns.spec import Cell
    cell = Cell(cell_id=cell_id_for(cfg, "auto"), overrides={},
                attack="auto", cfg=cfg)
    problem = verify_cli_round_trip(cell)
    assert problem is not None and "not expressible" in problem


def test_grid_spec_delegation_matches_historical_rows(tmp_path):
    """grid.py is now a campaign wrapper: the summary keeps the
    historical row shape and the skip semantics (tests/test_grid.py
    pins the behavioral contract; this pins the spec plumbing)."""
    from attacking_federate_learning_tpu.grid import grid_spec

    base = ExperimentConfig(**_base(tmp_path))
    spec = grid_spec(base, ["NoDefense", "Krum"], ["none", "alie"])
    cells = spec.expand()
    assert [(c.overrides["defense"], c.attack) for c in cells] == [
        ("NoDefense", "none"), ("NoDefense", "alie"),
        ("Krum", "none"), ("Krum", "alie")]
    # 'none' zeroes the malicious cohort (the historical mapping).
    assert cells[0].cfg.mal_prop == 0.0 and cells[0].cfg.num_std == 0.0
    assert cells[1].cfg.mal_prop == base.mal_prop


# ---------------------------------------------------------------------------
# measured cache-ordering proof (slow: 3 supervisor campaigns, each
# cell a fresh child process — the in-memory compile cache would mask
# eviction inside a single process)

@pytest.mark.slow
def test_cache_ordering_grouped_beats_shuffled_measured(tmp_path):
    def make_spec(arm_dir):
        return dict(
            name="proof",
            base=dict(dataset=C.SYNTH_MNIST, users_count=10,
                      mal_prop=0.2, batch_size=16, synth_train=256,
                      synth_test=64, backend="cpu",
                      log_dir=os.path.join(arm_dir, "logs"),
                      run_dir=os.path.join(arm_dir, "runs")),
            axes={"defense": ["Krum", "TrimmedMean"],
                  "epochs": [5, 10, 15, 20]})

    def run_arm(name, order, budget_mb):
        arm_dir = os.path.join(str(tmp_path), f"{name}_{order}")
        spec_path = os.path.join(str(tmp_path), f"{name}_{order}.json")
        with open(spec_path, "w") as f:
            json.dump(make_spec(arm_dir), f)
        r = subprocess.run(
            [sys.executable, "-m",
             "attacking_federate_learning_tpu.campaigns", spec_path,
             "--executor", "supervisor", "--order", order,
             "--cache-dir", os.path.join(arm_dir, "cache"),
             "--cache-budget-mb", str(budget_mb)],
            env=CLI_ENV, capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-2000:]
        camp_root = os.path.join(arm_dir, "runs", "campaigns")
        (cid,) = os.listdir(camp_root)
        with open(os.path.join(camp_root, cid, "manifest.json")) as f:
            return json.load(f)

    # The two orderings must actually differ (>=8 cells, 2 groups).
    spec = CampaignSpec.from_json(json.dumps(make_spec("x")))
    cells = spec.expand()
    assert len(cells) == 8 and len({c.group for c in cells}) == 2
    g = order_cells(cells, "grouped", spec.campaign_id)
    s = order_cells(cells, "shuffled", spec.campaign_id)
    assert adjacency(s) < adjacency(g)

    # Probe: grouped, unbounded — measures the per-group cache size.
    man_p = run_arm("probe", "grouped", 0.0)
    exec_ids = [c.cell_id for c in g]
    bytes_after = [man_p["cells"][cid]["cache_bytes"]
                   for cid in exec_ids]
    size_a, total = bytes_after[3], bytes_after[-1]
    size_b = total - size_a
    budget_mb = max(size_a, size_b) * 1.15 / 1e6
    assert budget_mb * 1e6 < total      # one group fits, both don't

    man_g = run_arm("meas", "grouped", budget_mb)
    man_s = run_arm("meas", "shuffled", budget_mb)
    # Acceptance: the manifests record a higher persistent-cache hit
    # count under grouped ordering, measured by the PR 3 counters.
    assert man_g["cache"]["hits"] > man_s["cache"]["hits"]
    assert man_g["cache"]["misses"] < man_s["cache"]["misses"]
    per_cell = [man_g["cells"][cid].get("cache_hits", 0)
                for cid in exec_ids]
    assert sum(per_cell) == man_g["cache"]["hits"]
