"""Multi-host wrapper: single-host no-op semantics."""

from attacking_federate_learning_tpu.parallel import multihost


def test_single_host_is_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert multihost.initialize() is False


def test_is_primary_single_host():
    assert multihost.is_primary() is True
