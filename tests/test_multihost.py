"""Multi-host wrapper: single-host no-op semantics, plus a real 2-process
exercise of ``jax.distributed.initialize`` over localhost (VERDICT item #8:
the only module whose happy path had never executed)."""

import os
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

from attacking_federate_learning_tpu.parallel import multihost


def test_single_host_is_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert multihost.initialize() is False


def test_is_primary_single_host():
    assert multihost.is_primary() is True


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_ring_round(tmp_path):
    """Two real processes join one distributed runtime; the global mesh
    spans both; the ring distance kernel's ppermute hops cross the process
    boundary; the Krum aggregate must match the single-process kernel.

    Infra flakiness (port races, slow coordinator) skips; a wrong answer
    fails."""
    worker = pathlib.Path(__file__).parent / "_multihost_worker.py"
    coord = f"127.0.0.1:{_free_port()}"
    out_path = tmp_path / "result.npz"
    repo_root = worker.parent.parent
    env = {**os.environ, "PALLAS_AXON_POOL_IPS": "",
           "JAX_PLATFORMS": "cpu",
           # Script-mode python puts tests/ (not the repo root) on
           # sys.path; prepend the root so the package imports.
           "PYTHONPATH": f"{repo_root}:{os.environ.get('PYTHONPATH', '')}"}
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    procs = [subprocess.Popen(
        [sys.executable, str(worker), coord, "2", str(i), str(out_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(worker.parent.parent))
        for i in range(2)]
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process runtime timed out (infra)")
    failing = [(p, o) for p, o in zip(procs, outs) if p.returncode != 0]
    if failing:
        # Capability skip, distinct from flake-skip: this box's jaxlib
        # (0.4.37) cannot run multiprocess collectives on the CPU
        # backend at all ("Multiprocess computations aren't implemented
        # on the CPU backend") — the test needs either a newer jaxlib
        # or real multi-host devices.  A permanent local gap, not a
        # wrong answer; the kernel itself is still covered by the
        # 8-virtual-device single-process ring/allgather parity tests
        # (tests/test_parallel.py, tests/test_distance_impl.py).
        cap = "Multiprocess computations aren't implemented"
        if all(cap in o for _, o in failing):
            pytest.skip("jaxlib CPU backend lacks multiprocess "
                        "collectives on this box (capability gap, "
                        "see ARCHITECTURE.md 'Known local failures')")
        # Skip only when every failing process's OWN output shows an
        # infra signature; a genuine assertion in one worker must fail
        # even if its peer finished cleanly.
        infra = ("UNAVAILABLE", "DEADLINE", "failed to connect",
                 "Connection re", "Barrier timed out")
        if all(any(sig in o for sig in infra) for _, o in failing):
            pytest.skip("distributed infra flake:\n"
                        + "\n---\n".join(o[-1000:] for _, o in failing))
        raise AssertionError("worker failed:\n"
                             + "\n---\n".join(o[-4000:] for _, o in failing))
    assert all("WORKER_OK" in o for o in outs)

    data = np.load(out_path)
    # Single-process reference: same kernel, same inputs, local mesh.
    from attacking_federate_learning_tpu.defenses.kernels import krum
    import jax.numpy as jnp

    want = np.asarray(krum(jnp.asarray(data["G"]), 16, 3))
    np.testing.assert_allclose(data["agg"], want, atol=2e-5, rtol=1e-5)
