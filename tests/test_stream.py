"""Host-streaming data pipeline (SURVEY.md §7.3 #5).

cfg.data_placement='host_stream' keeps the training set in host RAM and
double-buffers per-round batches; the resulting training run must be
bit-identical to the device-resident path in every mode (fused ALIE,
staged backdoor, sharded mesh, augmentation).
"""

import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import make_attacker
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.data.stream import HostStream


def _weights(placement, rounds=3, **overrides):
    kw = dict(dataset=C.SYNTH_MNIST, users_count=8, mal_prop=0.25,
              batch_size=16, epochs=rounds, defense="TrimmedMean",
              num_std=1.0, synth_train=512, synth_test=64,
              data_placement=placement)
    kw.update(overrides)
    cfg = ExperimentConfig(**kw)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=kw["synth_train"],
                      synth_test=64)
    exp = FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                              dataset=ds)
    exp.run_span(0, rounds)
    return np.asarray(exp.state.weights)


def test_streamed_equals_device_resident():
    np.testing.assert_array_equal(_weights("host_stream"),
                                  _weights("device"))


def test_streamed_backdoor_staged_equals_device():
    kw = dict(backdoor="pattern", backdoor_fused=False, defense="Krum")
    np.testing.assert_array_equal(_weights("host_stream", **kw),
                                  _weights("device", **kw))


def test_streamed_sharded_equals_device(hard_ds=None):
    kw = dict(users_count=16, mesh_shape=(8, 1))
    np.testing.assert_allclose(_weights("host_stream", **kw),
                               _weights("device", **kw),
                               atol=2e-6, rtol=1e-6)


def test_femnist_style_changes_training_and_zero_strength_is_iid():
    w_iid = _weights("device")
    w_sty = _weights("device", partition="femnist_style")
    assert np.isfinite(w_sty).all()
    assert not np.array_equal(w_iid, w_sty)   # the shift is real
    np.testing.assert_array_equal(            # and strength 0 is IID
        _weights("device", partition="femnist_style",
                 style_strength=0.0), w_iid)


def test_femnist_style_sharded_equals_unsharded():
    # The style params are (n,) host constants indexed inside the round
    # program; under a (8,1) mesh the broadcast multiply-add must not
    # perturb results beyond GSPMD reduction reordering.
    kw = dict(users_count=16, partition="femnist_style")
    np.testing.assert_allclose(
        _weights("device", mesh_shape=(8, 1), **kw),
        _weights("device", **kw), atol=2e-6, rtol=1e-6)


def test_streamed_femnist_style_with_participation_equals_device():
    # Pins the style-row/cohort alignment: the streamed path re-derives
    # the cohort ids host-side, and the style transform must index the
    # same rows (core/engine.py _compute_grads_impl).
    kw = dict(users_count=8, participation=0.5,
              partition="femnist_style")
    np.testing.assert_array_equal(_weights("host_stream", **kw),
                                  _weights("device", **kw))


def test_streamed_augmented_cifar_equals_device():
    # allclose, not equal: the device path runs rounds as one fused span
    # while streaming runs per-round programs, and XLA's conv fusions
    # differ at the ~1e-8 level between those two compilations (measured
    # identical per-round-vs-per-round; the augmentation itself is
    # bit-deterministic).
    kw = dict(dataset=C.SYNTH_CIFAR10, data_augment=True, users_count=4,
              batch_size=8, synth_train=256, defense="NoDefense",
              mal_prop=0.0)
    np.testing.assert_allclose(_weights("host_stream", rounds=2, **kw),
                               _weights("device", rounds=2, **kw),
                               atol=1e-6, rtol=1e-6)


def test_host_stream_batches_match_device_gather():
    import jax.numpy as jnp
    from attacking_federate_learning_tpu.data.partition import (
        iid_shards, round_batch_indices
    )

    rng = np.random.default_rng(0)
    x = rng.standard_normal((100, 3)).astype(np.float32)
    y = rng.integers(0, 5, 100).astype(np.int32)
    shards = iid_shards(100, 4, seed=1)
    stream = HostStream(x, y, shards, batch_size=8)
    for t in (0, 1, 5, 2):  # includes a backwards jump (resume-style)
        xs, ys = stream.get(t)
        idx = np.asarray(round_batch_indices(jnp.asarray(shards), t, 8))
        np.testing.assert_array_equal(np.asarray(xs), x[idx])
        np.testing.assert_array_equal(np.asarray(ys), y[idx])


def test_host_stream_prefetch_cache_bounded():
    x = np.zeros((50, 2), np.float32)
    y = np.zeros(50, np.int32)
    from attacking_federate_learning_tpu.data.partition import iid_shards

    stream = HostStream(x, y, iid_shards(50, 2, 0), batch_size=4)
    for t in range(5):
        stream.get(t)
        assert set(stream._cache) == {t + 1}  # exactly one slot in flight


def test_invalid_placement_rejected():
    with pytest.raises(ValueError, match="data_placement"):
        ExperimentConfig(dataset=C.SYNTH_MNIST, data_placement="hbm")


def test_prefetch_horizon_stops_at_last_round():
    x = np.zeros((50, 2), np.float32)
    y = np.zeros(50, np.int32)
    from attacking_federate_learning_tpu.data.partition import iid_shards

    stream = HostStream(x, y, iid_shards(50, 2, 0), batch_size=4,
                        n_rounds=3)
    stream.get(0)
    stream.get(1)
    stream.get(2)                 # last round: no prefetch past horizon
    assert stream._cache == {}


def test_threaded_deep_prefetch_equals_inline():
    """VERDICT r2 weak #4: --stream-workers 1 moves gather+transfer onto a
    background thread and --stream-prefetch deepens the pipeline; both
    must leave the training trajectory bit-identical (the cohort
    derivation is deterministic, so prefetched rounds see exactly the
    cohort the round uses)."""
    base = _weights("host_stream", rounds=4)
    deep = _weights("host_stream", rounds=4, stream_prefetch=3,
                    stream_workers=1)
    np.testing.assert_array_equal(base, deep)
    # With participation sampling (the deterministic-cohort contract).
    kw = dict(users_count=16, participation=0.5, rounds=4)
    np.testing.assert_array_equal(
        _weights("host_stream", **kw),
        _weights("host_stream", stream_prefetch=2, stream_workers=1, **kw))


def test_deep_prefetch_cache_bound_and_order():
    import jax.numpy as jnp
    from attacking_federate_learning_tpu.data.partition import (
        iid_shards, round_batch_indices
    )

    rng = np.random.default_rng(3)
    x = rng.standard_normal((60, 2)).astype(np.float32)
    y = rng.integers(0, 5, 60).astype(np.int32)
    shards = iid_shards(60, 3, 0)
    stream = HostStream(x, y, shards, batch_size=4, prefetch=3, workers=1)
    try:
        for t in (0, 1, 2, 7, 3):     # includes jumps both ways
            xs, ys = stream.get(t)
            idx = np.asarray(round_batch_indices(jnp.asarray(shards), t, 4))
            np.testing.assert_array_equal(np.asarray(xs), x[idx])
            assert set(stream._cache) <= {t + 1, t + 2, t + 3}
            assert len(stream._cache) == 3
    finally:
        stream._pool.shutdown(wait=True)
    with pytest.raises(ValueError, match="stream_prefetch"):
        ExperimentConfig(stream_prefetch=0)


def test_stall_stats_recorded():
    """get() accumulates stall wall-time and cold-miss counts, and a
    streamed run writes one 'stream' record to the JSONL log."""
    import json

    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.utils.metrics import RunLogger

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                           mal_prop=0.0, batch_size=8, epochs=3,
                           defense="NoDefense",
                           data_placement="host_stream",
                           synth_train=512, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=512, synth_test=64)
    exp = FederatedExperiment(cfg, dataset=ds)
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        logger = RunLogger(cfg, None, td)
        exp.run(logger=logger)
        stats = exp.stream.stall_stats()
        assert stats["stream_gets"] == 3
        assert stats["stream_cold_misses"] >= 1    # round 0 is always cold
        assert stats["stream_stall_s"] >= 0.0
        recs = []
        import glob
        for p in glob.glob(td + "/*.jsonl"):
            with open(p) as fh:
                recs += [json.loads(line) for line in fh]
        assert any(r.get("kind") == "stream" for r in recs)
