"""Robustness-margin observatory (ISSUE 18).

Acceptance contract: every margin carries an exactness identity — a
row is Krum/Bulyan-selected iff its selection margin > 0 (one-sided at
exact f32 score ties), a row's trim survival mass is bit-equal to the
telemetry kept-fraction, the median pick masses reconstruct the
aggregate; margins-off programs stay HLO byte-identical (the kernel
seam here, all 62 perf_gate entry points in CI); the pallas
composition threads (trim/median margins bit-exact, Krum/Bulyan
within the documented distance-kernel ulp band) while every off-device
impl is rejected at config AND kernel level with a clear error; the
engine emits one schema-v12 ``margin`` event per round (flat,
hierarchical, async), joining traffic's ``f_eff`` when present; the
30-round Bulyan z=1.5 collapse shows its tie-locked margin signature;
and the rollup/series/drift helpers behind ``runs margins``,
``tools/check_events.py --stats`` and the trace counter track hold
their units.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import (
    ExperimentConfig, TrafficConfig
)
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses.kernels import (
    bulyan, krum, trimmed_mean, trimmed_mean_of
)
from attacking_federate_learning_tpu.defenses.median import median
from attacking_federate_learning_tpu.utils import margins as M
from attacking_federate_learning_tpu.utils.metrics import RunLogger


def _grads(n=12, d=40, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .normal(size=(n, d)).astype(np.float32))


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 12)
    kw.setdefault("mal_prop", 0.2)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 6)
    kw.setdefault("test_step", 3)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("defense", "Krum")
    kw.setdefault("margins", True)
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    return ExperimentConfig(**kw)


def _run(cfg, name):
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name=name) as logger:
        exp.run(logger)
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    return exp, events


def _margin_events(events):
    return [e for e in events if e.get("kind") == "margin"]


# ---------------------------------------------------------------------------
# tentpole: per-kernel exactness identities

def test_krum_margin_identity():
    """Selected iff margin > 0 (continuous inputs don't tie), and the
    winner's margin IS the winner/runner-up gap."""
    G = _grads(11, 30)
    agg, diag = krum(G, 11, 2, telemetry=True, margins=True)
    sel = np.asarray(diag["selection_mask"])
    m = np.asarray(diag["margin_selection"])
    scores = np.sort(np.asarray(diag["scores"]))
    np.testing.assert_array_equal(m > 0, sel == 1.0)
    assert float(diag["margin_gap"]) == pytest.approx(
        float(scores[1] - scores[0]))
    assert float(m[np.argmax(sel)]) == pytest.approx(
        float(diag["margin_gap"]))


def test_krum_margin_identity_masked_weighted():
    """Dead rows report -inf margins and can't carry the identity;
    weights scale the aggregate but never the margins (selection is
    unweighted)."""
    G = _grads(11, 30, seed=3)
    mask = jnp.asarray(np.array([True] * 8 + [False] * 3))
    w = jnp.asarray(np.linspace(0.5, 1.5, 11).astype(np.float32))
    agg, diag = krum(G, 11, 2, telemetry=True, margins=True, mask=mask)
    aggw, diagw = krum(G, 11, 2, telemetry=True, margins=True, mask=mask,
                       weights=w)
    for d in (diag, diagw):
        m = np.asarray(d["margin_selection"])
        sel = np.asarray(d["selection_mask"])
        assert np.all(m[8:] == -np.inf)
        np.testing.assert_array_equal(m > 0, sel == 1.0)
    np.testing.assert_array_equal(np.asarray(diag["margin_selection"]),
                                  np.asarray(diagw["margin_selection"]))
    winner = int(np.argmax(np.asarray(diag["selection_mask"])))
    np.testing.assert_allclose(np.asarray(aggw),
                               np.asarray(agg) * float(w[winner]),
                               rtol=1e-6)


def test_trimmed_mean_margin_kept_frac_bit_equal():
    """margin_kept_frac (rank membership) is BIT-equal to the
    scatter-based telemetry kept_fraction — same keep set, same sum/d
    reduction."""
    G = _grads(13, 50, seed=1)
    _, diag = trimmed_mean(G, 13, 3, telemetry=True, margins=True)
    np.testing.assert_array_equal(np.asarray(diag["margin_kept_frac"]),
                                  np.asarray(diag["kept_fraction"]))
    # Boundary distance is inside-positive: fully-kept rows cannot sit
    # strictly outside the envelope everywhere.
    bd = np.asarray(diag["margin_boundary_dist"])
    assert np.isfinite(bd).all()


def test_trimmed_mean_margin_masked():
    """Dead rows: zero kept fraction, -inf boundary distance; alive
    rows keep e - f - 1 of the alive count."""
    G = _grads(12, 40, seed=2)
    mask = jnp.asarray(np.array([True] * 9 + [False] * 3))
    _, diag = trimmed_mean(G, 12, 2, telemetry=True, margins=True,
                           mask=mask)
    kf = np.asarray(diag["margin_kept_frac"])
    bd = np.asarray(diag["margin_boundary_dist"])
    assert np.all(kf[9:] == 0.0)
    assert np.all(bd[9:] == -np.inf)
    # 9 alive, keep 9 - 2 - 1 = 6 rows per coordinate.
    assert np.sum(kf) == pytest.approx(6.0, rel=1e-6)


def test_median_margin_reconstructs_aggregate():
    """The pick masses ARE the aggregate's rank membership: summing
    pick_mass * value per coordinate reproduces the median, unmasked
    and masked+weighted."""
    G = _grads(12, 40, seed=4)
    agg, diag = median(G, 12, 2, telemetry=True, margins=True)
    picks = M.median_pick_margins(G)
    np.testing.assert_array_equal(
        np.asarray(diag["margin_kept_frac"]),
        np.asarray(picks["margin_kept_frac"]))
    mask = jnp.asarray(np.array([True] * 9 + [False] * 3))
    w = jnp.asarray(np.linspace(0.5, 1.5, 12).astype(np.float32))
    aggw, diagw = median(G, 12, 2, telemetry=True, margins=True,
                         mask=mask, weights=w)
    # The weighted lower median picks exactly one row per coordinate
    # (mass 1.0), so the reconstruction is exact.
    alive = np.array([True] * 9 + [False] * 3)
    pick = M.median_pick_margins(G, mask=mask, weights=w)
    kf = np.asarray(pick["margin_kept_frac"])
    assert np.all(kf[~alive] == 0.0)
    recon = np.zeros(G.shape[1], np.float32)
    ranks_picked = 0
    vals = np.where(alive[:, None], np.asarray(G), np.inf)
    order = np.argsort(vals, axis=0)
    ranks = np.argsort(order, axis=0)
    wv = np.where(alive, np.asarray(w), 0.0)
    for j in range(G.shape[1]):
        col_w = wv[order[:, j]]
        cum = np.cumsum(col_w)
        pr = int(np.argmax(cum >= wv.sum() / 2.0))
        row = int(order[pr, j])
        recon[j] = vals[row, j]
        ranks_picked += 1
    np.testing.assert_array_equal(recon, np.asarray(aggw))
    assert np.all(np.asarray(diagw["margin_boundary_dist"])[~alive]
                  == -np.inf)


def test_bulyan_margin_identity():
    """Strictly positive margin implies selected; alive unselected
    rows sit at margin <= 0; trim survival lives only on selected
    rows."""
    G = _grads(15, 40, seed=5)
    _, diag = bulyan(G, 15, 2, telemetry=True, margins=True)
    m = np.asarray(diag["margin_selection"])
    sel = np.asarray(diag["selection_mask"])
    tk = np.asarray(diag["margin_trim_kept"])
    assert np.all(sel[m > 0] == 1.0)
    assert np.all(m[sel == 0.0] <= 0.0)
    assert np.all(tk[sel == 0.0] == 0.0)
    assert np.all(tk[sel == 1.0] > 0.0)
    # Trip slack vector covers every selection trip (q=1 -> set_size).
    assert np.asarray(diag["margin_slack"]).shape == (15 - 4,)


def test_bulyan_margin_identity_masked():
    G = _grads(15, 40, seed=6)
    mask = jnp.asarray(np.array([True] * 11 + [False] * 4))
    _, diag = bulyan(G, 15, 2, telemetry=True, margins=True, mask=mask)
    m = np.asarray(diag["margin_selection"])
    sel = np.asarray(diag["selection_mask"])
    assert np.all(m[11:] == -np.inf)
    assert np.all(sel[m > 0] == 1.0)
    alive_unsel = (np.arange(15) < 11) & (sel == 0.0)
    assert np.all(m[alive_unsel] <= 0.0)


# ---------------------------------------------------------------------------
# seam contracts: margins-off HLO identity, config + kernel rejections

def test_margins_off_is_hlo_identical():
    """margins=False must be a trace-time no-op: the lowered program
    is byte-identical to one that never mentions the kwarg (the
    engine-level twin is tools/perf_gate.py's 62-entry pin)."""
    n, d, f = 12, 40, 2
    spec = jax.ShapeDtypeStruct((n, d), jnp.float32)
    for fn in (
        lambda kw: jax.jit(lambda g: krum(g, n, f, telemetry=True, **kw)),
        lambda kw: jax.jit(lambda g: trimmed_mean(g, n, f, telemetry=True,
                                                  **kw)),
        lambda kw: jax.jit(lambda g: median(g, n, f, telemetry=True,
                                            **kw)),
        lambda kw: jax.jit(lambda g: bulyan(g, n, f, telemetry=True,
                                            **kw)),
    ):
        base = fn({}).lower(spec).as_text()
        off = fn({"margins": False}).lower(spec).as_text()
        assert base == off


def test_margins_require_telemetry():
    G = _grads()
    for call in (
        lambda: krum(G, 12, 2, margins=True),
        lambda: trimmed_mean(G, 12, 2, margins=True),
        lambda: median(G, 12, 2, margins=True),
        lambda: bulyan(G, 12, 2, margins=True),
    ):
        with pytest.raises(ValueError, match="requires telemetry"):
            call()


def test_host_impls_reject_margins():
    """Every off-device impl raises at the kernel: it returns only its
    aggregate, never the per-row tensors the margins read."""
    G = _grads()
    with pytest.raises(ValueError, match="on-device ranks"):
        trimmed_mean_of(G, 9, impl="host", telemetry=True, margins=True)
    with pytest.raises(ValueError, match="on-device ranks"):
        median(G, 12, 2, impl="host", telemetry=True, margins=True)
    with pytest.raises(ValueError, match="score-returning engine"):
        krum(G, 12, 2, distance_impl="host", telemetry=True, margins=True)
    with pytest.raises(ValueError, match="full-host engine"):
        bulyan(G, 12, 2, distance_impl="host", telemetry=True,
               margins=True)
    with pytest.raises(ValueError, match="selection_impl='host'"):
        bulyan(G, 12, 2, selection_impl="host", telemetry=True,
               margins=True)


def test_config_rejects_host_impls_and_non_margin_defenses():
    """--margins composition errors surface at config time, naming the
    offending knob."""
    with pytest.raises(ValueError, match="no selection/trim decision"):
        ExperimentConfig(margins=True, defense="NoDefense")
    for knob, defense in (
        ("trimmed_mean_impl", "TrimmedMean"),
        ("median_impl", "Median"),
        ("bulyan_trim_impl", "Bulyan"),
        ("distance_impl", "Krum"),
        ("bulyan_selection_impl", "Bulyan"),
    ):
        with pytest.raises(ValueError, match=knob):
            ExperimentConfig(margins=True, defense=defense,
                             **{knob: "host"})
    # The on-device impls compose.
    ExperimentConfig(margins=True, defense="Krum")
    ExperimentConfig(margins=True, defense="Bulyan",
                     bulyan_selection_impl="pallas")


def test_pallas_margin_composition():
    """aggregation_impl='pallas' x margins: trim/median margins are
    pure-XLA rank ops over the same key, so they are BIT-identical
    across impls; Krum margins ride the pallas score kernel and sit
    inside the documented ulp band with the same winner."""
    G = _grads(16, 128, seed=7)
    _, d_x = trimmed_mean(G, 16, 3, impl="xla", telemetry=True,
                          margins=True)
    _, d_p = trimmed_mean(G, 16, 3, impl="pallas", telemetry=True,
                          margins=True)
    np.testing.assert_array_equal(np.asarray(d_x["margin_kept_frac"]),
                                  np.asarray(d_p["margin_kept_frac"]))
    np.testing.assert_array_equal(
        np.asarray(d_x["margin_boundary_dist"]),
        np.asarray(d_p["margin_boundary_dist"]))
    _, m_x = median(G, 16, 3, impl="xla", telemetry=True, margins=True)
    _, m_p = median(G, 16, 3, impl="pallas", telemetry=True, margins=True)
    np.testing.assert_array_equal(np.asarray(m_x["margin_kept_frac"]),
                                  np.asarray(m_p["margin_kept_frac"]))
    _, k_x = krum(G, 16, 3, scores_impl="xla", telemetry=True,
                  margins=True)
    _, k_p = krum(G, 16, 3, scores_impl="pallas", telemetry=True,
                  margins=True)
    np.testing.assert_array_equal(np.asarray(k_x["selection_mask"]),
                                  np.asarray(k_p["selection_mask"]))
    np.testing.assert_allclose(np.asarray(k_x["margin_selection"]),
                               np.asarray(k_p["margin_selection"]),
                               rtol=1e-3, atol=1e-4)


# ---------------------------------------------------------------------------
# engine: the schema-v12 margin event, all three engines + traffic

def test_flat_margin_events_without_telemetry(tmp_path):
    """--margins alone emits one v12 margin event per round carrying
    the colluder ledger — and NO defense telemetry events (margins is
    not a telemetry superset on the wire)."""
    cfg = _cfg(tmp_path, defense="TrimmedMean")
    exp, events = _run(cfg, "margins_flat.jsonl")
    mev = _margin_events(events)
    assert len(mev) == cfg.epochs
    for e in mev:
        assert e["v"] >= 12
        assert e["defense"] == "TrimmedMean"
        assert e["malicious_count"] == exp.m_mal
        assert "colluder_kept_mass" in e and "honest_kept_mass" in e
        assert "margin_kept_frac" in e
    assert not [e for e in events if e.get("kind") == "defense"]


def test_flat_margin_events_with_telemetry(tmp_path):
    """margins + telemetry: margin fields live ONLY in the margin
    event; the defense telemetry event keeps its pre-v12 shape."""
    cfg = _cfg(tmp_path, defense="Krum", telemetry=True)
    _, events = _run(cfg, "margins_tele.jsonl")
    mev = _margin_events(events)
    dev = [e for e in events if e.get("kind") == "defense"]
    assert mev and dev
    for e in dev:
        assert not any(k.startswith("margin_") for k in e)
        assert "selection_mask" in e
    for e in mev:
        assert "colluder_margin" in e
        assert "attack_z_used" in e    # DriftAttack envelope utilization


def test_hier_margin_events(tmp_path):
    """Hierarchical rounds carry per-shard margin stacks plus shard_/
    tier2_ rollups in one margin event."""
    cfg = _cfg(tmp_path, defense="Krum", users_count=12,
               aggregation="hierarchical", megabatch=4,
               tier2_defense="Krum", epochs=4)
    _, events = _run(cfg, "margins_hier.jsonl")
    mev = _margin_events(events)
    assert len(mev) == cfg.epochs
    for e in mev:
        assert "shard_margin_selection" in e
        assert "tier2_margin_selection" in e
        assert "shard_colluder_margin" in e
        assert "tier2_colluder_margin" in e


def test_async_margin_events_tolerate_empty_rounds(tmp_path):
    """FedBuff rounds make no fabricated numbers: a round without a
    decision carries a NaN gap, and a round whose delivered buffer
    holds no colluder simply omits the colluder margin (every
    malicious row's selection margin is non-finite — dead under the
    delivery mask)."""
    cfg = _cfg(tmp_path, defense="Krum", aggregation="async",
               async_buffer=6, epochs=8)
    exp, events = _run(cfg, "margins_async.jsonl")
    mev = _margin_events(events)
    assert mev
    finite = [e for e in mev if e.get("colluder_margin") is not None
              and math.isfinite(e["colluder_margin"])]
    assert finite, "no round ever delivered a colluder decision"
    for e in mev:
        if e.get("colluder_margin") is None:
            gap = e.get("margin_gap")
            sel = e.get("margin_selection")
            assert (gap is None or math.isnan(gap)
                    or (sel is not None
                        and not any(v is not None and math.isfinite(v)
                                    for v in sel[:exp.m_mal])))


def test_margin_events_join_traffic_f_eff(tmp_path):
    """Under --traffic-population the margin event carries the round's
    effective-f, bit-matching the v11 traffic event it rode with."""
    cfg = _cfg(tmp_path, defense="Krum", epochs=8,
               traffic=TrafficConfig(population=64, min_cohort=4,
                                     fallback_defense="Median"))
    _, events = _run(cfg, "margins_traffic.jsonl")
    mev = {e["round"]: e for e in _margin_events(events)}
    tev = {e["round"]: e for e in events if e.get("kind") == "traffic"}
    assert mev and tev
    joined = 0
    for r, e in mev.items():
        if r in tev:
            assert e["f_eff"] == tev[r]["f_eff"]
            joined += 1
    assert joined


# ---------------------------------------------------------------------------
# behavior: the 30-round Bulyan z=1.5 tie-locked collapse signature

def test_bulyan_margin_collapse_signature():
    """The IID z=1.5 collapse through the margin observatory
    (BEHAVIOR_BASELINE bulyan_margin_collapse): the colluder margin
    never goes positive, and most rounds are tie-locked at EXACTLY
    zero — identical crafted rows are score-degenerate, so a selected
    colluder's runner-up is its own twin and equal f32 scores subtract
    to an exact 0."""
    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST_HARD, users_count=19, mal_prop=0.2,
        batch_size=64, epochs=30, test_step=30, seed=0,
        synth_train=4000, synth_test=1000, defense="Bulyan",
        num_std=1.5, margins=True)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=4000,
                      synth_test=1000)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds)
    cms = []
    for t in range(30):
        exp.run_round(t)
        mf = {k[len("defense_"):]: np.asarray(v)
              for k, v in exp.last_round_telemetry.items()
              if k.startswith("defense_margin_")}
        cms.append(M.margin_rollups(mf, exp.m_mal)["colluder_margin"])
    assert all(v <= 0.0 for v in cms)
    assert sum(1 for v in cms if v == 0.0) >= 20


# ---------------------------------------------------------------------------
# rollups / series / drift units (the runs-margins backend)

def test_margin_rollups_units():
    fields = {"margin_selection": [0.5, -1.0, -2.0, 0.25],
              "margin_trim_kept": [0.2, 0.0, 0.4, 0.6],
              "margin_gap": 0.75}
    r = M.margin_rollups(fields, 2)
    assert r["colluder_margin"] == -0.5
    assert r["colluder_selected"] == 1
    assert r["colluder_kept_mass"] == pytest.approx(0.1)
    assert r["honest_kept_mass"] == pytest.approx(0.5)
    assert r["margin_gap"] == 0.75
    # -inf (dead/rejected) rows never poison the ledger.
    r = M.margin_rollups({"margin_selection": [-np.inf, 0.5]}, 2)
    assert r["colluder_margin"] == -0.5


def test_tier2_margin_rollups_units():
    r = M.tier2_margin_rollups(
        {"margin_selection": [0.3, -0.2, -0.7],
         "margin_trim_kept": [1.0, 0.5, 0.0]},
        [True, False, True])
    assert r["colluder_margin"] == pytest.approx(-0.3)
    assert r["colluder_selected"] == 1
    assert r["colluder_kept_mass"] == pytest.approx(0.5)


def test_margin_series_and_drift():
    events = []
    for t, cm in enumerate([-0.1, 0.2, 0.3]):
        events.append({"kind": "margin", "round": t, "defense": "Krum",
                       "colluder_margin": cm, "f_eff": 2})
    events.append({"kind": "eval", "round": 1})
    ser = M.margin_series(events)
    assert list(ser) == ["Krum"]
    assert ser["Krum"]["round"] == [0, 1, 2]
    assert ser["Krum"]["colluder_margin"] == [-0.1, 0.2, 0.3]
    other = {"round": [0, 1, 2, 3],
             "colluder_margin": [-0.2, -0.2, 0.4, 0.1]}
    dr = M.margin_drift(ser["Krum"], other)
    assert dr["rounds"] == [0, 1, 2]
    assert dr["sign_flips"] == [1]
    np.testing.assert_allclose(dr["delta"], [-0.1, -0.4, 0.1])


def test_runs_margins_backend_reads_engine_events(tmp_path):
    """runs_cli's series loader digests a real margin stream."""
    from attacking_federate_learning_tpu import runs_cli

    cfg = _cfg(tmp_path, defense="Median", epochs=4)
    _, events = _run(cfg, "margins_runscli.jsonl")
    ser = runs_cli._margin_series_data(events)
    assert ser and "Median" in ser
    assert len(ser["Median"]["round"]) == cfg.epochs
    assert runs_cli._margin_series_data(
        [e for e in events if e.get("kind") != "margin"]) is None


# ---------------------------------------------------------------------------
# satellites: check_events --stats, trace counter track

def _load_tool(name):
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_events_validates_and_stats_margin_stream(tmp_path):
    from attacking_federate_learning_tpu.utils.metrics import (
        SCHEMA_VERSION, validate_event
    )

    ce = _load_tool("check_events")
    p = tmp_path / "margins.jsonl"
    rows = [
        {"kind": "margin", "round": 0, "defense": "Krum",
         "malicious_count": 2, "colluder_margin": -0.5,
         "v": SCHEMA_VERSION, "t": 0.1},
        {"kind": "round", "round": 0, "v": 1, "t": 0.2},
        {"kind": "margin", "round": 1, "defense": "Krum",
         "malicious_count": 2, "colluder_margin": 0.25,
         "v": SCHEMA_VERSION, "t": 0.3},
    ]
    for r in rows:
        validate_event(r)
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    counts, legacy, errors = ce.check_file(str(p))
    assert not errors and counts == {"margin": 2, "round": 1}
    stats = ce.file_stats(str(p))
    assert stats["margin"] == {"count": 2,
                               "versions": {SCHEMA_VERSION: 2}}
    assert stats["round"] == {"count": 1, "versions": {1: 1}}
    # A margin kind stamped with a pre-v12 version is an emitter bug.
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"kind": "margin", "round": 0,
                               "defense": "Krum", "v": 11,
                               "t": 0.1}) + "\n")
    _, _, errors = ce.check_file(str(bad))
    assert errors


def test_trace_export_margin_counter_track():
    from attacking_federate_learning_tpu.utils.trace_export import (
        events_to_trace, validate_trace
    )

    events = [
        {"kind": "margin", "round": 0, "t": 0.1, "defense": "Bulyan",
         "colluder_margin": -0.0},
        {"kind": "margin", "round": 1, "t": 0.2, "defense": "Bulyan",
         "colluder_margin": 0.4},
        # No decision this round: no counter point, not a NaN.
        {"kind": "margin", "round": 2, "t": 0.3, "defense": "Bulyan",
         "margin_gap": float("nan")},
    ]
    trace = events_to_trace(events)
    assert validate_trace(trace) == []
    pts = [e for e in trace["traceEvents"]
           if e.get("ph") == "C" and e["name"] == "colluder_margin"]
    assert [p["args"]["colluder_margin"] for p in pts] == [-0.0, 0.4]
