"""Telemetry pipeline: kernel diagnostics, engine threading, event
schema round-trip, the report tool, and the check_events validator.

Acceptance contract (ISSUE 1): with telemetry OFF every aggregation is
bit-identical to the pre-telemetry kernels and the fused round loop still
compiles as one jit (the on/off trajectory test); with it ON a 30-round
SYNTH_MNIST_HARD Krum-vs-ALIE run emits per-round selection masks whose
top-1 concentration, computed by the report tool, reproduces the pinned
GRID_RESULTS femnist_style trend (IID diffuse -> styled concentrated).
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu import report
from attacking_federate_learning_tpu.attacks import DriftAttack, make_attacker
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses import DEFENSES
from attacking_federate_learning_tpu.defenses.kernels import (
    bulyan, krum, krum_select, population_telemetry, trimmed_mean
)
from attacking_federate_learning_tpu.utils.metrics import (
    EVENT_KINDS, RunLogger, validate_event
)


# ---------------------------------------------------------------------------
# kernel diagnostics (defenses/kernels.py and friends)

def _grads(n=15, d=40, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))


@pytest.mark.parametrize("name,extra", [
    ("NoDefense", {}), ("Krum", {}), ("TrimmedMean", {}), ("Bulyan", {}),
    ("Median", {}), ("GeoMedian", {}), ("CenteredClip", {}),
    ("NormBound", {}), ("DnC", {"seed": 3, "round": 1}),
])
def test_kernel_telemetry_bit_identical_and_fixed_shape(name, extra):
    """telemetry=True must not perturb the aggregate (bit-for-bit) and
    must return fixed-shape diagnostics."""
    G, n, f = _grads(), 15, 3
    fn = DEFENSES[name]
    plain = np.asarray(fn(G, n, f, **extra))
    agg, diag = fn(G, n, f, telemetry=True, **extra)
    np.testing.assert_array_equal(plain, np.asarray(agg))
    for k, v in diag.items():
        assert np.asarray(v).shape in ((), (n,)), (name, k)


def test_fltrust_telemetry_trust_scores():
    G, n, f = _grads(), 15, 3
    g0 = jnp.asarray(np.random.default_rng(1)
                     .standard_normal(40).astype(np.float32))
    fn = DEFENSES["FLTrust"]
    plain = np.asarray(fn(G, n, f, server_grad=g0))
    agg, diag = fn(G, n, f, server_grad=g0, telemetry=True)
    np.testing.assert_array_equal(plain, np.asarray(agg))
    ts = np.asarray(diag["trust_scores"])
    cos = np.asarray(diag["cosine"])
    assert ts.shape == (n,) and (ts >= 0).all()
    np.testing.assert_allclose(ts, np.maximum(cos, 0.0), atol=1e-7)


def test_krum_telemetry_mask_marks_aggregated_row():
    """The one-hot mask and the score argmin must both point at the row
    krum_select reports — same single distance computation."""
    G, n, f = _grads(seed=7), 15, 3
    want = int(krum_select(G, n, f))
    agg, diag = krum(G, n, f, telemetry=True)
    mask = np.asarray(diag["selection_mask"])
    assert mask.sum() == 1.0 and int(np.argmax(mask)) == want
    assert int(np.argmin(np.asarray(diag["scores"]))) == want
    np.testing.assert_array_equal(np.asarray(agg), np.asarray(G)[want])


def test_krum_telemetry_under_jit_matches_eager():
    G, n, f = _grads(seed=9), 15, 3
    fn = jax.jit(lambda g: krum(g, n, f, telemetry=True))
    agg_j, diag_j = fn(G)
    agg_e, diag_e = krum(G, n, f, telemetry=True)
    np.testing.assert_array_equal(np.asarray(agg_j), np.asarray(agg_e))
    np.testing.assert_array_equal(np.asarray(diag_j["selection_mask"]),
                                  np.asarray(diag_e["selection_mask"]))


def test_trimmed_mean_kept_fraction_accounting():
    """Each coordinate keeps exactly n-f-1 clients, so the per-client
    kept fractions must sum to n-f-1."""
    G, n, f = _grads(seed=3), 15, 3
    _, diag = trimmed_mean(G, n, f, telemetry=True)
    kept = np.asarray(diag["kept_fraction"])
    assert kept.shape == (n,)
    np.testing.assert_allclose(kept.sum(), n - f - 1, rtol=1e-5)
    np.testing.assert_allclose(float(diag["trim_fraction"]),
                               1.0 - (n - f - 1) / n, rtol=1e-6)


def test_bulyan_telemetry_mask_is_selection_set():
    G, n, f = _grads(seed=5), 15, 3
    _, diag = bulyan(G, n, f, telemetry=True)
    mask = np.asarray(diag["selection_mask"])
    assert mask.sum() == n - 2 * f
    # Hybrid exact selection must mark the same set on plain inputs
    # (tests/test_defenses.py pins hybrid==xla aggregation already).
    _, diag_h = bulyan(G, n, f, selection_impl="host", telemetry=True)
    np.testing.assert_array_equal(mask, np.asarray(diag_h["selection_mask"]))


def test_population_telemetry_shapes_and_values():
    G = _grads(seed=11)
    pt = population_telemetry(G)
    norms = np.asarray(pt["client_norms"])
    cos = np.asarray(pt["cosine_to_mean"])
    np.testing.assert_allclose(norms, np.linalg.norm(np.asarray(G), axis=1),
                               rtol=1e-6)
    assert (np.abs(cos) <= 1.0 + 1e-5).all()


def test_attack_envelope_stats():
    """ALIE envelope stats mirror the craft arithmetic on the malicious
    cohort; NoAttack/z=0 report nothing."""
    from attacking_federate_learning_tpu.attacks import NoAttack

    G, f = _grads(seed=13), 4
    atk = DriftAttack(num_std=1.5)
    stats = atk.envelope_stats(G, f)
    mal = np.asarray(G)[:f]
    np.testing.assert_allclose(float(stats["sigma_norm"]),
                               np.linalg.norm(mal.std(0)), rtol=1e-5)
    np.testing.assert_allclose(float(stats["drift_norm"]),
                               1.5 * np.linalg.norm(mal.std(0)), rtol=1e-5)
    assert float(stats["z"]) == 1.5
    assert DriftAttack(num_std=0.0).envelope_stats(G, f) == {}
    assert NoAttack().envelope_stats(G, f) == {}


# ---------------------------------------------------------------------------
# engine threading

def _tele_cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 9)
    kw.setdefault("mal_prop", 0.22)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 5)
    kw.setdefault("test_step", 5)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("log_dir", str(tmp_path))
    return ExperimentConfig(**kw)


def _run(cfg, tmp_path, name, timer=None, attacker=None):
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    if attacker is None:
        attacker = (make_attacker(cfg, dataset=ds) if cfg.backdoor
                    else DriftAttack(1.0))
    exp = FederatedExperiment(cfg, attacker=attacker, dataset=ds)
    with RunLogger(cfg, None, str(tmp_path), jsonl_name=name) as logger:
        result = exp.run(logger, timer=timer)
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    return result, events


def test_telemetry_off_trajectory_bit_identical(tmp_path):
    """Acceptance: telemetry must be a pure observer — the on/off
    trajectories agree bit for bit (spans fused either way)."""
    r_off, _ = _run(_tele_cfg(tmp_path, defense="Krum", telemetry=False),
                    tmp_path, "off")
    r_on, events = _run(_tele_cfg(tmp_path, defense="Krum", telemetry=True),
                        tmp_path, "on")
    np.testing.assert_array_equal(np.asarray(r_off["final_weights"]),
                                  np.asarray(r_on["final_weights"]))
    kinds = {e["kind"] for e in events}
    assert {"defense", "attack", "eval", "selection_hist"} <= kinds


def test_tele_span_matches_per_round_dispatch(tmp_path):
    """The scanned telemetry span (one device program per eval interval,
    stacked aux outputs) must emit the same per-round events as the
    per-round dispatch path (here forced by a PhaseTimer)."""
    from attacking_federate_learning_tpu.utils.profiling import PhaseTimer

    cfg = _tele_cfg(tmp_path, defense="Krum", telemetry=True)
    _, ev_span = _run(cfg, tmp_path, "span")
    _, ev_round = _run(cfg, tmp_path, "per_round", timer=PhaseTimer())
    d_span = [e for e in ev_span if e["kind"] == "defense"]
    d_round = [e for e in ev_round if e["kind"] == "defense"]
    assert [e["round"] for e in d_span] == [e["round"] for e in d_round]
    for a, b in zip(d_span, d_round):
        np.testing.assert_array_equal(a["selection_mask"],
                                      b["selection_mask"])
        np.testing.assert_allclose(a["scores"], b["scores"], rtol=1e-5)
        np.testing.assert_allclose(a["client_norms"], b["client_norms"],
                                   rtol=1e-5)


def test_telemetry_under_device_mesh(tmp_path):
    """Stacked telemetry aux outputs must survive the (clients, model)
    mesh: same events, valid masks, no resharding surprises."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device harness")
    cfg = _tele_cfg(tmp_path, users_count=16, mal_prop=0.2, epochs=3,
                    test_step=3, defense="Krum", telemetry=True,
                    mesh_shape=(8, 1))
    _, events = _run(cfg, tmp_path, "mesh")
    dfs = [e for e in events if e["kind"] == "defense"]
    assert len(dfs) == 3
    for e in dfs:
        assert sum(e["selection_mask"]) == 1.0
        assert len(e["client_norms"]) == 16


def test_staged_backdoor_telemetry_has_shadow_loss(tmp_path):
    """The staged dispatch path (reference per-round nan-guard seam)
    threads the same telemetry, including the backdoor's envelope stats
    via AttackContext."""
    cfg = _tele_cfg(tmp_path, users_count=8, mal_prop=0.25, epochs=2,
                    test_step=2, defense="TrimmedMean", backdoor="pattern",
                    backdoor_fused=False, telemetry=True,
                    synth_train=512)
    _, events = _run(cfg, tmp_path, "staged_bd")
    atk = [e for e in events if e["kind"] == "attack"]
    assert len(atk) == 2
    for e in atk:
        assert e["attack"] == "backdoor"
        assert "shadow_loss" in e and "clip_halfwidth_norm" in e
    dfs = [e for e in events if e["kind"] == "defense"]
    assert len(dfs) == 2 and "kept_fraction" in dfs[0]


# ---------------------------------------------------------------------------
# schema round-trip (satellite): every kind the engine can emit, parsed
# and validated from real CPU runs' JSONL

def test_schema_roundtrip_every_engine_kind(tmp_path):
    """5-round runs covering the full event surface: every record
    validates, and the union of kinds is exactly the schema's."""
    from attacking_federate_learning_tpu.utils.metrics import (
        SCHEMA_VERSION
    )

    seen = set()
    # Run 1: Krum + ALIE + telemetry + round stats + profile.
    from attacking_federate_learning_tpu.utils.profiling import PhaseTimer

    cfg1 = _tele_cfg(tmp_path, defense="Krum", telemetry=True,
                     log_round_stats=True, epochs=5, test_step=2)
    _, ev1 = _run(cfg1, tmp_path, "roundtrip1", timer=PhaseTimer())
    # Run 2: backdoor (asr) + host-streamed data (stream) + telemetry.
    cfg2 = _tele_cfg(tmp_path, users_count=8, mal_prop=0.25, epochs=5,
                     test_step=2, defense="NoDefense", backdoor="pattern",
                     data_placement="host_stream", telemetry=True,
                     synth_train=512)
    _, ev2 = _run(cfg2, tmp_path, "roundtrip2")
    # Run 3: fault injection (the 'fault' kind, core/faults.py).
    from attacking_federate_learning_tpu.config import FaultConfig

    cfg3 = _tele_cfg(tmp_path, defense="Median", epochs=3, test_step=3,
                     faults=FaultConfig(dropout=0.3))
    _, ev3 = _run(cfg3, tmp_path, "roundtrip3")
    # Run 4: the v2 kinds — cost report (compile/cost) + a heartbeat
    # (emitted synchronously via heartbeat_fields; the thread variant
    # is covered in tests/test_costs.py).
    cfg4 = _tele_cfg(tmp_path, defense="NoDefense", epochs=2, test_step=2)
    ds4 = load_dataset(cfg4.dataset, seed=0, synth_train=256, synth_test=64)
    exp4 = FederatedExperiment(cfg4, attacker=DriftAttack(1.0), dataset=ds4)
    from attacking_federate_learning_tpu.utils.lifecycle import RunJournal

    with RunLogger(cfg4, None, str(tmp_path),
                   jsonl_name="roundtrip4") as logger:
        exp4.cost_report(logger)
        logger.record(**logger.heartbeat_fields())
        # v4: the science gate's verdict kind (tools/science_gate.py
        # emits these; synthesized here like the heartbeat above).
        logger.record(kind="gate", cell="krum_alie05", status="pass")
        # v6: the forensics verdict kind (report.py forensics_main
        # emits these; synthesized like the gate record above — the
        # real emission path is covered in tests/test_hierarchy.py).
        logger.record(kind="forensics", verdict="localized",
                      isolated_shards=[0])
        # v8: the campaign-scheduler kind (campaigns/scheduler.py
        # writes these to its own runs/campaigns/<id>/events.jsonl;
        # synthesized here — the real emission path is covered in
        # tests/test_campaign.py).
        logger.record(kind="campaign", campaign="c_test",
                      phase="cell_done", cell="x", rc=0)
        # v10: the measured-wall kind (--profile-every runs emit these
        # from core/engine.py's fetch boundary; synthesized here — the
        # real emission path, both host and trace sources, is covered
        # in tests/test_walls.py).
        logger.record(kind="wall", name="fused_span", source="host",
                      wall_s=0.125, rounds=2)
        # v3: a journaled run emits the 'lifecycle' kind from the
        # engine itself (start/complete; utils/lifecycle.py) — and, as
        # of v4, the run-finish 'registry' stamp.
        exp4.run(logger,
                 journal=RunJournal(str(tmp_path / "runs"), "roundtrip4"))
        path4 = logger.jsonl_path
    with open(path4) as f:
        ev4 = [json.loads(line) for line in f]
    # Run 5: hierarchical + secagg — the v5 'secagg' and v6
    # 'shard_selection' kinds from a real engine run (groupwise
    # tier-2 Krum with telemetry, core/engine.py hier tele span).
    cfg5 = _tele_cfg(tmp_path, users_count=12, mal_prop=0.25,
                     defense="NoDefense", epochs=3, test_step=3,
                     secagg="groupwise", aggregation="hierarchical",
                     megabatch=4, tier2_defense="Krum", telemetry=True)
    _, ev5 = _run(cfg5, tmp_path, "roundtrip5")
    # Run 6: asynchronous buffered rounds — the v7 'async' kind from a
    # real engine run (core/async_rounds.py; staleness-weighted Krum).
    cfg6 = _tele_cfg(tmp_path, users_count=12, mal_prop=0.25,
                     defense="Krum", epochs=4, test_step=4,
                     aggregation="async", async_buffer=7,
                     async_max_staleness=2, staleness_weight="poly",
                     telemetry=True)
    _, ev6 = _run(cfg6, tmp_path, "roundtrip6")
    # Run 7: population traffic — the v11 'traffic' kind from a real
    # engine run (core/population.py schedule, one event per round).
    from attacking_federate_learning_tpu.config import TrafficConfig

    cfg7 = _tele_cfg(tmp_path, users_count=12, mal_prop=0.25,
                     defense="Krum", epochs=3, test_step=3,
                     traffic=TrafficConfig(population=48, rate=0.8,
                                           seed=3))
    _, ev7 = _run(cfg7, tmp_path, "roundtrip7")
    # Run 8: robustness margins — the v12 'margin' kind from a real
    # engine run (utils/margins.py rollups, one event per round).
    cfg8 = _tele_cfg(tmp_path, users_count=12, mal_prop=0.25,
                     defense="Krum", epochs=3, test_step=3,
                     margins=True)
    _, ev8 = _run(cfg8, tmp_path, "roundtrip8")
    # Run 9: numerics observatory — the v14 'numerics' kind from a
    # real engine run (utils/numerics.py health counters + rollups,
    # one event per round).
    cfg9 = _tele_cfg(tmp_path, users_count=12, mal_prop=0.25,
                     defense="Krum", epochs=3, test_step=3,
                     numerics=True)
    _, ev9 = _run(cfg9, tmp_path, "roundtrip9")
    for rec in ev1 + ev2 + ev3 + ev4 + ev5 + ev6 + ev7 + ev8 + ev9:
        validate_event(rec)
        assert rec["v"] == SCHEMA_VERSION
        seen.add(rec["kind"])
    assert seen == set(EVENT_KINDS)


def test_record_rejects_schema_drift(tmp_path):
    """Emitter-side validation (utils/metrics.py): unknown kinds and
    missing required fields fail the producing run."""
    cfg = _tele_cfg(tmp_path)
    with RunLogger(cfg, None, str(tmp_path), jsonl_name="drift") as logger:
        with pytest.raises(ValueError, match="unknown event kind"):
            logger.record(kind="not_a_kind", round=0)
        with pytest.raises(ValueError, match="missing required"):
            logger.record(kind="eval", round=0)
        logger.record(kind="round", round=0)  # minimal valid event


# ---------------------------------------------------------------------------
# tools/check_events.py (satellite: wired into CI)

def _load_check_events():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "check_events.py")
    spec = importlib.util.spec_from_file_location("check_events", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_events_validator(tmp_path):
    ce = _load_check_events()
    cfg = _tele_cfg(tmp_path, defense="TrimmedMean", telemetry=True,
                    epochs=3, test_step=3)
    _, _ = _run(cfg, tmp_path, "ce_ok")
    good = os.path.join(str(tmp_path), "ce_ok.jsonl")
    counts, legacy, errors = ce.check_file(good)
    assert not errors and counts["defense"] == 3
    assert ce.main([good]) == 0
    # Malformed emitters are caught: bad kind, missing field, bad JSON.
    bad = os.path.join(str(tmp_path), "ce_bad.jsonl")
    with open(bad, "w") as f:
        f.write(json.dumps({"kind": "defense", "round": 0,
                            "defense": "Krum"}) + "\n")
        f.write(json.dumps({"kind": "mystery"}) + "\n")
        f.write(json.dumps({"kind": "eval", "round": 1}) + "\n")
        f.write("{not json\n")
        f.write(json.dumps({"free": "form"}) + "\n")
    counts, legacy, errors = ce.check_file(bad)
    assert len(errors) == 3 and legacy == 1 and counts == {"defense": 1}
    assert ce.main([bad]) == 1
    # --strict flags the free-form row too.
    assert len(ce.check_file(bad, strict=True)[2]) == 4


# ---------------------------------------------------------------------------
# report tool + the pinned femnist_style selection-concentration trend

def test_report_summarize_and_json(tmp_path, capsys):
    from attacking_federate_learning_tpu import cli

    cfg = _tele_cfg(tmp_path, defense="Krum", telemetry=True)
    _, _ = _run(cfg, tmp_path, "rep")
    path = os.path.join(str(tmp_path), "rep.jsonl")
    capsys.readouterr()                   # drain the run's tee lines
    assert cli.main(["report", "--json", path]) == 0
    out = json.loads(capsys.readouterr().out)
    s = out[path]
    assert s["defense"] == "Krum" and s["attack"] == "alie"
    sel = s["selection"]
    assert sel["rounds"] == 5 and 0 < sel["top1_share"] <= 1
    assert sel["top1_share"] == s["selection_hist"]["top1_share"]
    # Human-readable mode renders the same numbers.
    assert cli.main(["report", path]) == 0
    text = capsys.readouterr().out
    assert "selection concentration" in text and "top-1 share" in text


def test_report_reproduces_femnist_style_concentration_trend(tmp_path):
    """Acceptance: 30-round SYNTH_MNIST_HARD Krum-vs-ALIE, iid vs
    femnist_style — the telemetry selection masks, aggregated by the
    report tool, must reproduce the pinned GRID_RESULTS trend: styled
    honest structure CONCENTRATES Krum's selection (top-1 share up,
    distinct winners down vs iid)."""
    shares = {}
    winners = {}
    for part in ("iid", "femnist_style"):
        cfg = ExperimentConfig(
            dataset=C.SYNTH_MNIST_HARD, users_count=19, mal_prop=0.2,
            batch_size=64, epochs=30, test_step=30, defense="Krum",
            partition=part, style_strength=0.5, telemetry=True,
            log_dir=str(tmp_path))
        ds = load_dataset(cfg.dataset, seed=0, synth_train=8000,
                          synth_test=2000)
        exp = FederatedExperiment(cfg, attacker=make_attacker(cfg,
                                                              dataset=ds),
                                  dataset=ds)
        name = f"femnist_{part}"
        with RunLogger(cfg, None, str(tmp_path), jsonl_name=name) as logger:
            exp.run(logger)
        sel = report.selection_concentration(
            report.load_events([logger.jsonl_path]))
        assert sel["rounds"] == 30          # a mask every round
        shares[part] = sel["top1_share"]
        winners[part] = sel["distinct_winners"]
    # GRID_RESULTS round-5 row: top-1 share 0.17 -> 0.40 at strength 0.5.
    assert shares["femnist_style"] > shares["iid"], (shares, winners)
    assert winners["femnist_style"] < winners["iid"], (shares, winners)
