"""Raw dataset-file loaders (IDX / CIFAR pickles) against generated files.

The box has no real datasets (zero egress), so these tests write miniature
files in the exact on-disk formats — MNIST IDX magic/dims/uint8 payload,
CIFAR python pickles with bytes keys — and check parsing, shapes and the
reference normalizations (data_sets.py:26-27, :56-57, :154-155).
"""

import gzip
import os
import pickle
import struct

import numpy as np

from attacking_federate_learning_tpu.data import datasets as D


def write_idx_images(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000803))
        f.write(struct.pack(">III", *arr.shape))
        f.write(arr.astype(np.uint8).tobytes())


def write_idx_labels(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000801))
        f.write(struct.pack(">I", arr.shape[0]))
        f.write(arr.astype(np.uint8).tobytes())


def test_mnist_idx_loader(tmp_path):
    rng = np.random.default_rng(0)
    tx = rng.integers(0, 256, (20, 28, 28))
    ty = rng.integers(0, 10, 20)
    vx = rng.integers(0, 256, (8, 28, 28))
    vy = rng.integers(0, 10, 8)
    d = tmp_path
    write_idx_images(d / "train-images-idx3-ubyte", tx)
    write_idx_labels(d / "train-labels-idx1-ubyte", ty)
    write_idx_images(d / "t10k-images-idx3-ubyte", vx)
    write_idx_labels(d / "t10k-labels-idx1-ubyte", vy)

    ds = D.load_mnist(str(d))
    assert ds.train_x.shape == (20, 1, 28, 28)
    assert ds.test_x.shape == (8, 1, 28, 28)
    np.testing.assert_array_equal(ds.train_y, ty.astype(np.int32))
    # Reference normalization (x/255 - 0.1307) / 0.3081.
    want = (tx[0].astype(np.float32) / 255.0 - 0.1307) / 0.3081
    np.testing.assert_allclose(ds.train_x[0, 0], want, atol=1e-6)


def test_mnist_idx_gzip_variant(tmp_path):
    rng = np.random.default_rng(1)
    for name, writer, arr in [
        ("train-images-idx3-ubyte", write_idx_images,
         rng.integers(0, 256, (4, 28, 28))),
        ("train-labels-idx1-ubyte", write_idx_labels,
         rng.integers(0, 10, 4)),
        ("t10k-images-idx3-ubyte", write_idx_images,
         rng.integers(0, 256, (2, 28, 28))),
        ("t10k-labels-idx1-ubyte", write_idx_labels,
         rng.integers(0, 10, 2)),
    ]:
        raw = tmp_path / (name + ".raw")
        writer(raw, arr)
        with open(raw, "rb") as f, gzip.open(
                str(tmp_path / (name + ".gz")), "wb") as g:
            g.write(f.read())
        os.remove(raw)

    ds = D.load_mnist(str(tmp_path))
    assert ds.train_x.shape == (4, 1, 28, 28)


def test_cifar10_pickle_loader(tmp_path):
    rng = np.random.default_rng(2)
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    for i in range(1, 6):
        batch = {b"data": rng.integers(0, 256, (10, 3072),
                                       dtype=np.uint8).astype(np.uint8),
                 b"labels": rng.integers(0, 10, 10).tolist()}
        with open(d / f"data_batch_{i}", "wb") as f:
            pickle.dump(batch, f)
    test_batch = {b"data": rng.integers(0, 256, (6, 3072), dtype=np.uint8),
                  b"labels": rng.integers(0, 10, 6).tolist()}
    with open(d / "test_batch", "wb") as f:
        pickle.dump(test_batch, f)

    ds = D.load_cifar10(str(tmp_path))
    assert ds.train_x.shape == (50, 3, 32, 32)
    assert ds.test_x.shape == (6, 3, 32, 32)
    # Reference normalization (x/255 - 0.5) / 0.5 in [-1, 1].
    assert ds.train_x.min() >= -1.0 and ds.train_x.max() <= 1.0


def test_cifar100_pickle_loader(tmp_path):
    rng = np.random.default_rng(3)
    d = tmp_path / "cifar-100-python"
    d.mkdir()
    for name, n in [("train", 12), ("test", 5)]:
        batch = {b"data": rng.integers(0, 256, (n, 3072), dtype=np.uint8),
                 b"fine_labels": rng.integers(0, 100, n).tolist()}
        with open(d / name, "wb") as f:
            pickle.dump(batch, f)

    ds = D.load_cifar100(str(tmp_path))
    assert ds.train_x.shape == (12, 3, 32, 32)
    assert ds.num_classes == 100


def test_load_dataset_prefers_real_files(tmp_path):
    """When raw files exist, MNIST loads them instead of falling back."""
    rng = np.random.default_rng(4)
    write_idx_images(tmp_path / "train-images-idx3-ubyte",
                     rng.integers(0, 256, (4, 28, 28)))
    write_idx_labels(tmp_path / "train-labels-idx1-ubyte",
                     rng.integers(0, 10, 4))
    write_idx_images(tmp_path / "t10k-images-idx3-ubyte",
                     rng.integers(0, 256, (2, 28, 28)))
    write_idx_labels(tmp_path / "t10k-labels-idx1-ubyte",
                     rng.integers(0, 10, 2))
    ds = D.load_dataset("MNIST", data_dir=str(tmp_path))
    assert ds.name == "MNIST"
    assert len(ds.train_y) == 4
