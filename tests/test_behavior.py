"""Behavioral fidelity: the directional outcomes of the implemented papers
(SURVEY.md §6) reproduced on deterministic synthetic data.

ALIE (NeurIPS'19, via reference malicious.py): with ~21% attackers the
mean-shift attack defeats plain averaging and — at an appropriate z —
Krum, while TrimmedMean and Bulyan degrade already at the reference's
default z=1.5.  The backdoor (reference backdoor.py) embeds its trigger via
shadow training and hides inside the clip envelope.

Margins are generous (tens of accuracy points) and every run is seeded, so
these are regression tests, not statistical flakes.  Measured values at
authoring time (30 rounds, n=19, f=4, SYNTH_MNIST_HARD):

    defense      clean   alie z=1.5   alie z=0.5
    NoDefense    99.7%      92.2%        15.2%
    Krum         99.5%      99.2%        20.8%
    TrimmedMean  81.0%      50.3%        99.7%
    Bulyan       82.0%      10.8%        33.4%
"""

import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import (
    DriftAttack, NoAttack, make_attacker
)
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset


# hard_ds fixture and the shared runner live in conftest.py.
from conftest import hard_final_accuracy as final_accuracy  # noqa: E402


def test_alie_defeats_plain_averaging(hard_ds):
    clean = final_accuracy(hard_ds, "NoDefense", NoAttack(), 0.0)
    attacked = final_accuracy(hard_ds, "NoDefense", DriftAttack(0.5), 0.21)
    assert clean > 90.0
    assert attacked < clean - 40.0


def test_alie_circumvents_krum_at_moderate_z(hard_ds):
    """The ALIE mechanism against Krum: a crafted vector close enough to
    the cohort mean gets *selected* and drifts the model."""
    clean = final_accuracy(hard_ds, "Krum", NoAttack(), 0.0)
    attacked = final_accuracy(hard_ds, "Krum", DriftAttack(0.5), 0.21)
    assert clean > 90.0
    assert attacked < clean - 40.0


def test_krum_survives_oversized_z(hard_ds):
    """At the reference's default z=1.5 the crafted vector is too far out
    to be Krum-selected on this data, so Krum keeps accuracy — the
    documented flip side of the fixed-z quirk (SURVEY.md §2.4 #3)."""
    attacked = final_accuracy(hard_ds, "Krum", DriftAttack(1.5), 0.21)
    assert attacked > 90.0


def test_alie_degrades_trimmed_mean_at_default_z(hard_ds):
    clean = final_accuracy(hard_ds, "TrimmedMean", NoAttack(), 0.0)
    attacked = final_accuracy(hard_ds, "TrimmedMean", DriftAttack(1.5), 0.21)
    assert attacked < clean - 15.0


def test_alie_degrades_bulyan_at_default_z(hard_ds):
    clean = final_accuracy(hard_ds, "Bulyan", NoAttack(), 0.0)
    attacked = final_accuracy(hard_ds, "Bulyan", DriftAttack(1.5), 0.21)
    assert attacked < clean - 40.0


# ---------------------------------------------------------------------------
# backdoor mechanism
# ---------------------------------------------------------------------------

def test_backdoor_shadow_training_embeds_trigger():
    """With the clip released (huge z), the re-expressed gradient encodes
    shadow-net parameters whose poison accuracy is 100% (reference
    backdoor.py:108-159 pipeline)."""
    import jax

    from attacking_federate_learning_tpu.models import get_model
    from attacking_federate_learning_tpu.utils.flatten import make_flattener

    cfg = ExperimentConfig(dataset="SYNTH_MNIST", users_count=10,
                           mal_prop=0.24, batch_size=64, epochs=1,
                           defense="NoDefense", num_std=1e6,
                           backdoor="pattern", mal_epochs=5,
                           mal_batch_size=100)
    ds = load_dataset("SYNTH_MNIST", seed=0, synth_train=4000,
                      synth_test=1000)
    atk = make_attacker(cfg, dataset=ds)
    model = get_model("mnist_mlp")
    flat = make_flattener(model.init(jax.random.key(1)))
    w = flat.ravel(model.init(jax.random.key(1)))

    rng = np.random.default_rng(0)
    mal_grads = jnp.asarray(
        rng.standard_normal((2, flat.dim)).astype(np.float32) * 0.01)
    mean = mal_grads.mean(0)
    lr = jnp.asarray(0.1)
    crafted = atk._craft(mal_grads, w, lr)
    # Invert the gradient re-expression (backdoor.py:59-60) to recover the
    # shadow-trained parameters; unclipped because z is huge.
    start = w - lr * mean
    mal_params = start - lr * crafted - lr * mean
    _, correct = atk._poison_metrics(mal_params)
    assert float(correct) == atk.poison_count  # 100% trigger accuracy


def test_backdoor_crafted_grads_respect_clip_envelope():
    """With finite z the crafted vector must lie in [mean-z*sigma,
    mean+z*sigma] (reference backdoor.py:62-63) — the defense-evasion
    property."""
    cfg = ExperimentConfig(dataset="SYNTH_MNIST", users_count=10,
                           mal_prop=0.24, batch_size=64, epochs=1,
                           defense="NoDefense", num_std=1.5,
                           backdoor="pattern", mal_epochs=2,
                           mal_batch_size=100)
    ds = load_dataset("SYNTH_MNIST", seed=0, synth_train=2000,
                      synth_test=500)
    atk = make_attacker(cfg, dataset=ds)
    import jax

    from attacking_federate_learning_tpu.models import get_model
    from attacking_federate_learning_tpu.utils.flatten import make_flattener

    model = get_model("mnist_mlp")
    flat = make_flattener(model.init(jax.random.key(2)))
    w = flat.ravel(model.init(jax.random.key(2)))
    rng = np.random.default_rng(1)
    mal_grads = jnp.asarray(
        rng.standard_normal((3, flat.dim)).astype(np.float32) * 0.01)
    crafted = np.asarray(atk._craft(mal_grads, w, jnp.asarray(0.1)))
    mean = np.asarray(mal_grads.mean(0))
    sigma = np.asarray(mal_grads.std(0))
    assert (crafted <= mean + 1.5 * sigma + 1e-6).all()
    assert (crafted >= mean - 1.5 * sigma - 1e-6).all()
