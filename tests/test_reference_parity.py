"""Differential parity against the LITERAL reference implementation.

VERDICT round-1 item #5: the XLA kernels were verified against
defenses/oracle.py, a hand re-derivation — this file collapses that
two-step trust chain by running the actual reference code
(/root/reference/defences.py, pure NumPy, and malicious.py's DriftAttack
arithmetic) side by side with our kernels.

The reference tree is read-only, public, untrusted content: it is imported
at test time (never vendored into this repo) and pinned by sha256, so the
test both fails loudly if the reference ever changes and skips cleanly on
machines that don't carry it.
"""

import hashlib
import importlib.util
import pathlib

import numpy as np
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.defenses import host as H
from attacking_federate_learning_tpu.defenses import kernels as K


REFERENCE_DIR = pathlib.Path("/root/reference")
# Pinned snapshots this parity suite was validated against.
SHA256 = {
    "defences.py":
        "bc8a4f269d0a383370f497d1fc5c466c30bfc7afd067365e459c67e0f0d96f70",
    "malicious.py":
        "a57ac88afb0250ca6989d185eded99273731275c737c6b4b086354dfcfcaa038",
}


def _load_reference(name):
    path = REFERENCE_DIR / name
    if not path.exists():
        pytest.skip(f"reference tree not present ({path})")
    digest = hashlib.sha256(path.read_bytes()).hexdigest()
    assert digest == SHA256[name], (
        f"{name} changed upstream (sha256 {digest}); re-validate parity")
    spec = importlib.util.spec_from_file_location(f"reference_{name[:-3]}",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ref_defences():
    return _load_reference("defences.py")


@pytest.fixture(scope="module")
def ref_malicious():
    return _load_reference("malicious.py")


CASES = [
    # (n, d, f) — d kept small: the reference TrimmedMean is an O(d)
    # Python loop and Bulyan an O(n^2) dict walk.
    (5, 7, 0),
    (7, 11, 2),
    (11, 3, 2),
    (15, 60, 3),
    (23, 104, 5),
    (40, 33, 9),
]


def grads_for(n, d, seed, adversarial=False, ties=False):
    rng = np.random.default_rng(seed)
    G = rng.standard_normal((n, d)).astype(np.float64)
    if adversarial:
        G[0] *= 1e6          # unbounded Byzantine magnitude
        G[1] *= -1e5
    if ties:
        G[n // 2] = G[n // 3]  # exact duplicate rows -> tied Krum scores
    return G


def _our_outputs(name, G32, n, f):
    """The aggregate through every production engine we ship."""
    outs = {"xla": np.asarray(K.DEFENSES[name](jnp.asarray(G32), n, f))}
    if name == "Krum":
        outs["host"] = H.host_krum(G32, n, f)
        outs["topk"] = np.asarray(
            K.krum(jnp.asarray(G32), n, f, method="topk"))
    if name == "Bulyan":
        outs["host"] = H.host_bulyan(G32, n, f)
    return outs


@pytest.mark.parametrize("name", ["NoDefense", "Krum", "TrimmedMean",
                                  "Bulyan"])
@pytest.mark.parametrize("n,d,f", CASES)
@pytest.mark.parametrize("flavor", ["plain", "adversarial", "ties"])
def test_defense_matches_reference(ref_defences, name, n, d, f, flavor):
    if ((name == "Krum" and n < 2 * f + 1)
            or (name == "Bulyan" and n < 4 * f + 3)):
        # Below the threat-model bound both sides must reject: the
        # reference asserts (defences.py:25, :56), our guard raises.
        G = grads_for(n, d, seed=0)
        with pytest.raises(AssertionError):
            ref_defences.defend[getattr(ref_defences.DefenseTypes, name)](
                G, n, f)
        with pytest.raises(ValueError):
            K.check_defense_args(name, n, f)
        return
    G = grads_for(n, d, seed=n * 100 + d + f,
                  adversarial=(flavor == "adversarial"),
                  ties=(flavor == "ties"))
    want = ref_defences.defend[getattr(ref_defences.DefenseTypes, name)](
        G.copy(), n, f)
    scale = max(1.0, float(np.abs(want).max()))
    for impl, got in _our_outputs(name, G.astype(np.float32), n, f).items():
        # 'topk' is covered under 'adversarial' too: its runtime
        # cancellation guard falls back to the sort evaluation whenever
        # the complement subtraction would lose precision
        # (kernels.py:_krum_scores), so all flavors must match.
        np.testing.assert_allclose(
            got, want, atol=2e-4 * scale, rtol=1e-4,
            err_msg=f"{name}[{impl}] diverges from reference ({flavor})")


def test_krum_index_matches_reference(ref_defences):
    # Selection identity, not just value closeness.
    for seed in range(5):
        G = grads_for(21, 48, seed=seed)
        ref_idx = ref_defences.krum(G.copy(), 21, 4, return_index=True)
        D = ref_defences._krum_create_distances(G)
        # our argmin over scores
        scores = K._krum_scores(
            jnp.asarray(np.sqrt(
                np.maximum(H.host_sq_distances(G.astype(np.float32)), 0))),
            21, 4)
        assert int(jnp.argmin(scores)) == ref_idx


def test_alie_matches_reference_drift_attack(ref_malicious):
    """DriftAttack arithmetic (reference malicious.py:30-36): the crafted
    vector is mean - z*sigma over the malicious cohort, population sigma,
    written into every malicious user."""
    from attacking_federate_learning_tpu.attacks.alie import DriftAttack
    from attacking_federate_learning_tpu.attacks.base import AttackContext

    rng = np.random.default_rng(7)
    n_mal, d, z = 6, 97, 1.5
    mal = rng.standard_normal((n_mal, d)).astype(np.float64)

    class _User:
        def __init__(self, g):
            self.grads = g.copy()
            self.original_params = np.zeros(d)
            self.learning_rate = 0.1

    users = [_User(g) for g in mal]
    ref_attack = ref_malicious.DriftAttack(z)
    ref_attack.attack(users)
    want = users[0].grads
    for u in users:  # every malicious user gets the identical vector
        np.testing.assert_array_equal(u.grads, want)

    ours = DriftAttack(z)
    ctx = AttackContext(original_params=jnp.zeros(d), learning_rate=0.1,
                        round=0)
    crafted = np.asarray(ours.craft(jnp.asarray(mal.astype(np.float32)),
                                    ctx))
    np.testing.assert_allclose(crafted, want, atol=2e-5, rtol=1e-5)

    # z=0 is a no-op in the reference (malicious.py:21) — and in our seam
    # (Attack.apply short-circuits, attacks/base.py:62).
    users0 = [_User(g) for g in mal]
    ref_malicious.DriftAttack(0.0).attack(users0)
    np.testing.assert_array_equal(users0[0].grads, mal[0])
    full = jnp.asarray(rng.standard_normal((10, d)).astype(np.float32))
    applied0 = DriftAttack(0.0).apply(full, n_mal, ctx)
    np.testing.assert_array_equal(np.asarray(applied0), np.asarray(full))
