"""Model zoo: shapes, wire-format dimensions, forward semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu.models import get_model
from attacking_federate_learning_tpu.utils.flatten import make_flattener


# Wire dims must match the reference nets parameter-for-parameter
# (reference data_sets.py:13-30 MnistNet, :33-61 Cifar10Net).
EXPECTED_DIMS = {
    "mnist_mlp": 784 * 100 + 100 + 100 * 10 + 10,               # 79,510
    "cifar10_cnn": (16 * 3 * 9 + 16) + (64 * 16 * 16 + 64)
                   + (384 * 64 + 384) + (192 * 384 + 192)
                   + (10 * 192 + 10),
}


@pytest.mark.parametrize("name", list(EXPECTED_DIMS))
def test_wire_dim(name):
    model = get_model(name)
    params = model.init(jax.random.key(0))
    flat = make_flattener(params)
    assert flat.dim == EXPECTED_DIMS[name]


@pytest.mark.parametrize("name", list(EXPECTED_DIMS))
def test_forward_is_log_softmax(name):
    model = get_model(name)
    params = model.init(jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (4,) + model.input_shape)
    out = model.apply(params, x)
    assert out.shape == (4, model.num_classes)
    # log-probs sum to 1 in prob space (log_softmax head,
    # reference data_sets.py:23, :51)
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), 1.0,
                               atol=1e-5)


def test_flatten_roundtrip():
    model = get_model("mnist_mlp")
    params = model.init(jax.random.key(3))
    flat = make_flattener(params)
    v = flat.ravel(params)
    back = flat.unravel(v)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_wire_order_matches_torch_parameters():
    """The flat vector must be fc1.w, fc1.b, fc2.w, fc2.b in torch layouts
    so reference-produced vectors load unchanged."""
    model = get_model("mnist_mlp")
    params = model.init(jax.random.key(4))
    flat = make_flattener(params)
    v = np.asarray(flat.ravel(params))
    w1 = np.asarray(params["fc1"]["weight"]).ravel()
    np.testing.assert_array_equal(v[: w1.size], w1)
    b1 = np.asarray(params["fc1"]["bias"])
    np.testing.assert_array_equal(v[w1.size: w1.size + b1.size], b1)


def test_mnist_init_distributions():
    """fc1 xavier (reference data_sets.py:17), fc2 torch-default bounds."""
    model = get_model("mnist_mlp")
    params = model.init(jax.random.key(5))
    w1 = np.asarray(params["fc1"]["weight"])
    bound1 = np.sqrt(6.0 / (784 + 100))
    assert np.abs(w1).max() <= bound1 + 1e-6
    assert np.abs(w1).max() > 0.8 * bound1   # actually fills the range
    w2 = np.asarray(params["fc2"]["weight"])
    assert np.abs(w2).max() <= 0.1 + 1e-6    # 1/sqrt(100)


def test_cifar10_spatial_trace():
    """32 -conv3-> 30 -pool3-> 10 -conv4-> 7 -pool4-> 1 (reference
    data_sets.py:36-43)."""
    model = get_model("cifar10_cnn")
    params = model.init(jax.random.key(6))
    x = jnp.zeros((2, 3, 32, 32))
    out = model.apply(params, x)   # would shape-error if the trace differed
    assert out.shape == (2, 10)
