"""Large-client-count paths in miniature: the 10k-client north star's code
shape (BASELINE.md) exercised at n=1024 on the 8-virtual-device CPU mesh —
client-sharded gradient matrix, bf16 storage, Gram-matmul distances at
n^2 = 1M entries, complement-top-k scoring, fused span."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses import DEFENSES
from attacking_federate_learning_tpu.parallel.mesh import make_plan


needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 (virtual) devices")


@needs_8
# slow tier: the 1024-client sharded span is the second most
# expensive tier-1 case (~100 s on a 1-core box); the n=2048
# sharded-vs-sort parity below keeps the scale contract in tier-1.
@pytest.mark.slow
def test_1024_client_sharded_round_with_krum():
    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=1024,
                           mal_prop=0.1, batch_size=4, epochs=1,
                           defense="Krum", grad_dtype="bfloat16",
                           synth_train=4096, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=4096, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds,
                              shardings=make_plan((8, 1)))
    state = exp.run_span(0, 2)
    assert int(state.round) == 2
    assert bool(np.isfinite(np.asarray(state.weights)).all())


@needs_8
def test_2048_client_krum_topk_sharded_matches_sort():
    """At n=2048 the distance matrix is 4M entries; the sharded top-k
    scoring must agree with the sort path."""
    rng = np.random.default_rng(0)
    G = jnp.asarray(rng.standard_normal((2048, 64)).astype(np.float32))
    from jax.sharding import NamedSharding, PartitionSpec as P
    plan = make_plan((8, 1))
    Gs = jax.device_put(G, NamedSharding(plan.mesh, P("clients", None)))
    a = np.asarray(jax.jit(DEFENSES["Krum"], static_argnums=(1, 2),
                           static_argnames=("method",))(
        Gs, 2048, 204, method="sort"))
    b = np.asarray(jax.jit(DEFENSES["Krum"], static_argnums=(1, 2),
                           static_argnames=("method",))(
        Gs, 2048, 204, method="topk"))
    np.testing.assert_allclose(a, b, atol=1e-4)
