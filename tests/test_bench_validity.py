"""The bench validity gate (VERDICT r3 #1): the mechanisms that make an
invalid TPU capture impossible to record — MFU ceiling, RTT floor,
once-guarded emission — pinned as unit behavior so a bench.py refactor
can't silently drop them before the next relay window.
"""

from __future__ import annotations

import importlib

import numpy as np
import pytest

import bench


@pytest.fixture(autouse=True)
def fresh_bench_state():
    """bench module state (RESULT/RECAP/_EMITTED) is global; isolate."""
    importlib.reload(bench)
    yield


def test_mfu_line_marks_invalid_above_bf16_peak():
    # 667 GFLOP in 0.09 ms = 7.4 PFLOP/s — the round-3 garbage number.
    frac = bench.mfu_line("krum_gram", 667e9, 0.09, "tpu")
    assert frac is not None and frac > 1.0
    assert bench.RESULT.get("valid") is False
    assert any("measurement broken" in r
               for r in bench.RESULT["invalid_reasons"])


def test_mfu_line_valid_below_peak_and_none_off_accel():
    frac = bench.mfu_line("krum_gram", 667e9, 40.0, "tpu")  # ~17 TFLOP/s
    assert frac is not None and frac < 1.0
    assert "valid" not in bench.RESULT          # nothing poisoned
    assert bench.mfu_line("x", 1e9, 1.0, "cpu") is None


def test_timed_ms_flags_wall_below_rtt():
    import jax.numpy as jnp

    x = jnp.zeros((4,))
    # A trivial op's wall is microseconds; an absurd RTT must flag it.
    ms, _, ok = bench.timed_ms(lambda: x + 1.0, iters=2, loops=1,
                               rtt=10_000.0)
    assert not ok
    assert ms >= 0.05                            # clamp held

    ms2, _, ok2 = bench.timed_ms(lambda: x + 1.0, iters=2, loops=1,
                                 rtt=0.0)
    assert ok2 and ms2 >= 0.05


def test_emit_result_json_is_once_guarded(capsys):
    bench.RESULT.update(metric="m", value=1.0, unit="ms",
                        vs_baseline=1.0, valid=True)
    bench.emit_result_json()
    bench.emit_result_json()                     # deadline-timer replay
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1 and '"metric": "m"' in out[0]


def test_mark_invalid_deduplicates_reasons():
    bench.RESULT.update(metric="m", value=1.0, valid=True)
    bench.mark_invalid("same reason")
    bench.mark_invalid("same reason")
    assert bench.RESULT["invalid_reasons"] == ["same reason"]
    assert bench.RESULT["valid"] is False
