"""The bench validity gate (VERDICT r3 #1): the mechanisms that make an
invalid TPU capture impossible to record — MFU ceiling, RTT floor,
once-guarded emission — pinned as unit behavior so a bench.py refactor
can't silently drop them before the next relay window.
"""

from __future__ import annotations

import importlib

import numpy as np
import pytest

import bench


@pytest.fixture(autouse=True)
def fresh_bench_state():
    """bench module state (RESULT/RECAP/_EMITTED) is global; isolate."""
    importlib.reload(bench)
    yield


def test_mfu_line_marks_invalid_above_bf16_peak():
    # 667 GFLOP in 0.09 ms = 7.4 PFLOP/s — the round-3 garbage number.
    frac = bench.mfu_line("krum_gram", 667e9, 0.09, "tpu")
    assert frac is not None and frac > 1.0
    assert bench.RESULT.get("valid") is False
    assert any("measurement broken" in r
               for r in bench.RESULT["invalid_reasons"])


def test_mfu_line_valid_below_peak_and_none_off_accel():
    frac = bench.mfu_line("krum_gram", 667e9, 40.0, "tpu")  # ~17 TFLOP/s
    assert frac is not None and frac < 1.0
    assert "valid" not in bench.RESULT          # nothing poisoned
    assert bench.mfu_line("x", 1e9, 1.0, "cpu") is None


def test_timed_ms_flags_wall_below_rtt():
    import jax.numpy as jnp

    x = jnp.zeros((4,))
    # A trivial op's wall is microseconds; an absurd RTT must flag it.
    ms, _, ok = bench.timed_ms(lambda: x + 1.0, iters=2, loops=1,
                               rtt=10_000.0)
    assert not ok
    assert ms >= 0.05                            # clamp held

    ms2, _, ok2 = bench.timed_ms(lambda: x + 1.0, iters=2, loops=1,
                                 rtt=0.0)
    assert ok2 and ms2 >= 0.05


def test_emit_result_json_is_once_guarded(capsys):
    bench.RESULT.update(metric="m", value=1.0, unit="ms",
                        vs_baseline=1.0, valid=True)
    bench.emit_result_json()
    bench.emit_result_json()                     # deadline-timer replay
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1 and '"metric": "m"' in out[0]


def test_mark_invalid_deduplicates_reasons():
    bench.RESULT.update(metric="m", value=1.0, valid=True)
    bench.mark_invalid("same reason")
    bench.mark_invalid("same reason")
    assert bench.RESULT["invalid_reasons"] == ["same reason"]
    assert bench.RESULT["valid"] is False


def test_phase_records_completion_only_on_success():
    with bench.phase("good", 30):
        pass
    with bench.phase("bad", 30):
        raise RuntimeError("boom")
    assert bench.RESULT["phases_completed"] == ["good"]


def test_relay_alive_stamps_window(monkeypatch):
    from attacking_federate_learning_tpu.utils import backend

    monkeypatch.setattr(backend, "relay_ports_listening",
                        lambda timeout=1.0: True)
    assert bench.relay_alive()
    assert bench.RESULT["window_s"] >= 0.0
    monkeypatch.setattr(backend, "relay_ports_listening",
                        lambda timeout=1.0: False)
    stamped = bench.RESULT["window_s"]
    assert not bench.relay_alive()
    assert bench.RESULT["window_s"] == stamped   # dead probe: no restamp


class TestF32FlipAdjudication:
    """ADVICE r4 #1: a legal near-tie between f32 engines must warn, not
    poison the capture; a decisive disagreement must still poison."""

    def test_exact_tie_is_exempt(self):
        rng = np.random.default_rng(3)
        G = rng.standard_normal((16, 32)).astype(np.float32)
        G[5] = G[11]            # identical rows: identical Krum scores
        is_tie, gap, band = bench.adjudicate_f32_flip(G, 3, [5, 11])
        assert is_tie and gap <= band

    def test_decisive_gap_poisons(self):
        rng = np.random.default_rng(4)
        G = rng.standard_normal((16, 32)).astype(np.float32)
        G[2] *= 40.0            # a far outlier: hugely worse score
        is_tie, gap, band = bench.adjudicate_f32_flip(G, 3, [0, 2])
        assert not is_tie and gap > band

    def test_gate_warns_on_tie_and_poisons_on_decisive_gap(self):
        # The gate bench_impl_table routes f32 disagreements through:
        # a legal tie must NOT poison validity; a decisive gap must.
        rng = np.random.default_rng(5)
        G = rng.standard_normal((12, 16)).astype(np.float32)
        G[1] = G[7]
        bench.gate_f32_disagreement(G, 2, {"xla": 1, "pallas": 7}, 12)
        assert "valid" not in bench.RESULT       # tie: warning only
        assert any("legal tie" in r for r in bench.RECAP)
        G[2] *= 40.0                             # decisive outlier
        bench.gate_f32_disagreement(G, 2, {"xla": 0, "pallas": 2}, 12)
        assert bench.RESULT["valid"] is False
        assert any("disagree" in r
                   for r in bench.RESULT["invalid_reasons"])


def test_host_cache_fingerprint_keys_the_cache_dir():
    """The persistent compile cache must be host-fingerprinted (VERDICT
    r4 weak #3: a foreign host's cached executable SIGILLing inside the
    TPU capture window) — deterministic per host, and the suite's own
    cache dir (conftest) must carry it."""
    import os

    from attacking_federate_learning_tpu.utils.backend import (
        host_cache_fingerprint
    )

    fp = host_cache_fingerprint()
    assert fp == host_cache_fingerprint()
    assert len(fp) == 12 and all(c in "0123456789abcdef" for c in fp)
    # conftest's setdefault respects an externally-set cache dir (a
    # user override wins verbatim, by design) — only the repo-default
    # path must carry the fingerprint.
    cache_dir = os.environ["JAX_COMPILATION_CACHE_DIR"].rstrip("/")
    if ".jax_cache" in cache_dir:
        assert cache_dir.endswith(fp)
    # The live config must match the env var either way (jax 0.9 reads
    # the env var at import time only; conftest applies it explicitly).
    import jax

    assert jax.config.jax_compilation_cache_dir == \
        os.environ["JAX_COMPILATION_CACHE_DIR"]


def test_classify_aot_warning_collapses_tuning_only_mismatch():
    """ISSUE 11 bench-hygiene satellite: the same-host cpu_aot_loader
    SIGILL false positive (only +prefer-no-scatter/+prefer-no-gather
    named — CLAUDE.md) collapses to one annotated line; a REAL
    cross-host mismatch (ISA features named) must pass through."""
    from attacking_federate_learning_tpu.utils.backend import (
        classify_aot_warning
    )

    benign = (
        "W0000 cpu_aot_loader.cc:55] executable was compiled with: "
        "[+aes,+avx,+sse4.1,+prefer-no-scatter,+prefer-no-gather,"
        "-amx-avx512,-fma4] vs host machine features: "
        "[aes,avx,sse4.1,fma]. This could lead to execution errors "
        "such as SIGILL.")
    is_warn, is_benign, note = classify_aot_warning(benign)
    assert is_warn and is_benign
    assert "prefer-no-scatter" in note and len(note) < 250
    assert "collapsed" in note

    real = benign.replace("+prefer-no-scatter,",
                          "+amx-fp16,+prefer-no-scatter,")
    is_warn, is_benign, note = classify_aot_warning(real)
    assert is_warn and not is_benign and note is None

    assert classify_aot_warning("ordinary line")[0] is False
    # a matching warning whose feature lists can't be parsed stays loud
    garbled = "foo SIGILL bar host machine features baz"
    is_warn, is_benign, _ = classify_aot_warning(garbled)
    assert is_warn and not is_benign


def test_aot_warning_collapse_pipe_roundtrip():
    """fd-level behavior: the benign dump collapses, the real mismatch
    and ordinary lines pass through, and python-side sys.stderr writes
    bypass the pump (the recap/deadline escape hatches must never
    depend on the filter thread)."""
    import os
    import subprocess
    import sys

    code = r"""
import os, sys, time
from attacking_federate_learning_tpu.utils.backend import (
    install_aot_warning_collapse)
install_aot_warning_collapse()
benign = ("W cpu_aot_loader] compiled with: [+aes,+prefer-no-scatter,"
          "+prefer-no-gather,-x] vs host machine features: [aes]. "
          "This could lead to execution errors such as SIGILL.")
real = benign.replace("+aes", "+amx-fp16,+aes")
os.write(2, (benign + "\n").encode())
os.write(2, (real + "\n").encode())
os.write(2, b"plain C-side line\n")
print("python-side line", file=sys.stderr, flush=True)
time.sleep(0.4)
"""
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          env={**os.environ, "JAX_PLATFORMS": "cpu",
                               "PALLAS_AXON_POOL_IPS": ""})
    err = proc.stderr
    assert proc.returncode == 0, err
    assert "false positive collapsed" in err
    # only the real mismatch's full dump survives (the collapsed note
    # mentions SIGILL too, so count the dump phrase)
    assert err.count("could lead to execution errors") == 1
    assert "amx-fp16" in err
    assert "plain C-side line" in err
    assert "python-side line" in err
