"""Hierarchical two-tier aggregation (ISSUE 6).

Acceptance contract: each tier-1 shard estimate bit-matches the flat
kernel applied to that shard's rows (masked-fault variants included);
``aggregation='flat'`` builds byte-identical HLO whatever the new knobs
hold; spread-vs-concentrated colluder placement produces the measured
tolerance flip on SYNTH_MNIST_HARD; and a SIGTERM-preempted
hierarchical run resumes bit-for-bit (same harness as test_faults.py's
lifecycle tests).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import (
    DriftAttack, make_attacker
)
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses.kernels import (
    TIER2_DEFENSES, bulyan, krum, shard_krum, shard_mean, trimmed_mean
)
from attacking_federate_learning_tpu.defenses.median import median
from attacking_federate_learning_tpu.ops.federated import (
    Placement, client_map, make_placement, tier1_assumed, tier2_assumed,
    two_tier_aggregate
)
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
from attacking_federate_learning_tpu.utils.metrics import RunLogger


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 12)
    kw.setdefault("mal_prop", 0.25)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 10)
    kw.setdefault("test_step", 5)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    return ExperimentConfig(**kw)


def _hier(tmp_path, **kw):
    kw.setdefault("aggregation", "hierarchical")
    kw.setdefault("megabatch", 4)
    return _cfg(tmp_path, **kw)


_DS = {}


def _dataset(name=C.SYNTH_MNIST):
    if name not in _DS:
        _DS[name] = load_dataset(name, seed=0, synth_train=256,
                                 synth_test=64)
    return _DS[name]


# ---------------------------------------------------------------------------
# placement (ops/federated.py)

def test_placement_spread_and_concentrated():
    for mode, want_counts in (("spread", (2, 2, 1)),
                              ("concentrated", (5, 0, 0))):
        pl = make_placement(24, 5, 8, mode)
        assert isinstance(pl, Placement)
        assert pl.mal_counts == want_counts
        # Every client exactly once, malicious-first within each shard.
        assert sorted(pl.grid.reshape(-1).tolist()) == list(range(24))
        for s in range(pl.num_shards):
            rows = pl.grid[s]
            c = pl.mal_counts[s]
            assert (rows[:c] < 5).all() and (rows[c:] >= 5).all()
        # Groups partition the shards and share one static count each.
        sids = [sid for _, group in pl.groups for sid in group]
        assert sorted(sids) == list(range(pl.num_shards))
        for count, group in pl.groups:
            assert all(pl.mal_counts[s] == count for s in group)


def test_placement_validation_and_assumed_bounds():
    with pytest.raises(ValueError, match="divide"):
        make_placement(10, 2, 3)
    with pytest.raises(ValueError, match="mal_placement"):
        make_placement(12, 2, 4, "clumped")
    assert tier1_assumed(13, 4) == 4        # ceil(13/4)
    assert tier1_assumed(0, 4) == 0
    assert tier2_assumed(13, 16) == 1       # ceil(13/16)
    assert tier2_assumed(33, 16) == 3


# ---------------------------------------------------------------------------
# acceptance (a): tier-1 estimates bit-match the flat kernels per shard

_T1 = {"Krum": krum, "TrimmedMean": trimmed_mean, "Bulyan": bulyan,
       "Median": median}


@pytest.mark.parametrize("name", sorted(_T1))
@pytest.mark.parametrize("masked", [False, True])
def test_tier1_shard_estimates_bit_match_flat_kernel(name, masked):
    """client_map's per-shard tier-1 pass IS the flat kernel on that
    shard's rows: under ``jax.disable_jit`` (op-identical dispatch) the
    two-tier composition is bit-for-bit the hand-built
    tier-2-over-per-shard-flat-kernels, masked-fault variants included
    (alive counts from the row mask).  The compiled scan is then
    allowed the usual XLA reassociation ulps on the coordinate-sum
    kernels (selection kernels stay bitwise — they return input rows)."""
    t1 = _T1[name]
    n, m, f = 32, 8, 3
    pl = make_placement(n, f, m, "spread")
    f1 = tier1_assumed(f, pl.num_shards)
    f2 = max(tier2_assumed(f, m), 1)
    rng = np.random.default_rng(7)
    G = jnp.asarray(rng.standard_normal((n, 40)).astype(np.float32))
    mask = jnp.asarray(rng.random(n) > 0.25) if masked else None
    t2 = TIER2_DEFENSES[name if name != "Bulyan" else "TrimmedMean"]

    def hand_built():
        ests, alive = [], []
        for s in range(pl.num_shards):
            ids = jnp.asarray(pl.grid[s])
            if masked:
                sm = mask[ids]
                ests.append(t1(G[ids], m, f1, mask=sm))
                alive.append(jnp.sum(sm).astype(jnp.int32))
            else:
                ests.append(t1(G[ids], m, f1))
        ests_m = jnp.stack(ests).astype(jnp.float32)
        return t2(ests_m, pl.num_shards, f2,
                  alive_counts=jnp.stack(alive) if masked else None)

    # Bit-for-bit under op-identical dispatch: the two-tier path calls
    # exactly the flat kernel per shard.
    with jax.disable_jit():
        exact = two_tier_aggregate(G, pl, t1, t2, f1, f2, mask=mask)
        ref_exact = hand_built()
    np.testing.assert_array_equal(np.asarray(exact),
                                  np.asarray(ref_exact))

    # Compiled regime: selection kernels stay bitwise; coordinate-sum
    # tails may reassociate inside the scan body (ulp band).
    agg = two_tier_aggregate(G, pl, t1, t2, f1, f2, mask=mask)
    ref = hand_built()
    if name in ("Krum", "Median"):
        np.testing.assert_array_equal(np.asarray(agg), np.asarray(ref))
    else:
        np.testing.assert_allclose(np.asarray(agg), np.asarray(ref),
                                   atol=5e-7, rtol=1e-6)


def test_shard_kernels_exclude_dead_shards():
    """alive_counts == 0 shards (every client quarantined) can never
    win tier-2 selection or weight the tier-2 mean — the shard_*
    entries map alive counts onto the kernels' quarantine mask seam."""
    rng = np.random.default_rng(3)
    E = jnp.asarray(rng.standard_normal((5, 16)).astype(np.float32))
    poisoned = E.at[0].set(1e4)             # dead shard with a wild row
    alive = jnp.asarray([0, 7, 8, 8, 6], jnp.int32)
    got = shard_krum(poisoned, 5, 1, alive_counts=alive)
    ref = krum(E[1:], 4, 1)                 # krum over the live shards
    # The winner must be a live shard's estimate (never row 0).
    assert np.isfinite(np.asarray(got)).all()
    assert not np.array_equal(np.asarray(got), np.asarray(poisoned[0]))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # Weighted tier-2 mean: dead shard contributes zero weight.
    wm = shard_mean(poisoned, 5, 0, alive_counts=alive)
    ref_m = (np.asarray(alive[1:], np.float32)
             @ np.asarray(E[1:])) / float(alive[1:].sum())
    np.testing.assert_allclose(np.asarray(wm), ref_m, rtol=1e-6)


def test_client_map_reorders_groups_to_shard_order():
    """Concentrated placement makes groups non-contiguous in shard id;
    the stacked output must still land in shard order."""
    pl = make_placement(24, 5, 8, "concentrated")   # counts (5, 0, 0)
    G = jnp.arange(24, dtype=jnp.float32)[:, None] * jnp.ones((1, 3))

    def shard_fn(ids, c_mal, G):
        return jnp.mean(G[ids], axis=0)

    out = np.asarray(client_map(shard_fn, pl, G))
    ref = np.stack([np.asarray(G)[pl.grid[s]].mean(0)
                    for s in range(3)])
    np.testing.assert_array_equal(out, ref)


# ---------------------------------------------------------------------------
# acceptance (b): the flat path is untouched

def test_flat_hlo_byte_identical_whatever_the_hier_knobs(tmp_path):
    """aggregation='flat' (the default) lowers byte-identical HLO with
    the hierarchical knobs at defaults or set — the new config surface
    must not leak into the flat trace (same methodology as the faults
    HLO pin, test_faults.py)."""
    ds = _dataset()

    def lowered(**kw):
        cfg = _cfg(tmp_path, defense="Krum", **kw)
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
        return exp._fused_round.lower(
            exp.state, jnp.asarray(0, jnp.int32)).as_text()

    base = lowered()
    knobbed = lowered(megabatch=4, tier2_defense="Median",
                      mal_placement="concentrated", tier1_corrupted=1,
                      tier2_corrupted=1)
    assert base == knobbed
    # Non-vacuous: the hierarchical build is a different program.
    hier = lowered(aggregation="hierarchical", megabatch=4)
    assert hier != base


# ---------------------------------------------------------------------------
# engine equivalences

def test_hier_nodefense_no_attack_matches_flat(tmp_path):
    """With NoDefense tiers and no attack, the two-tier mean-of-means
    over equal megabatches is the flat FedAvg mean — same trajectory to
    summation-order tolerance."""
    ds = _dataset()
    flat = FederatedExperiment(
        _cfg(tmp_path, mal_prop=0.0, defense="NoDefense", epochs=4),
        dataset=ds)
    flat.run_span(0, 4)
    hier = FederatedExperiment(
        _hier(tmp_path, mal_prop=0.0, defense="NoDefense", epochs=4),
        dataset=ds)
    hier.run_span(0, 4)
    np.testing.assert_allclose(np.asarray(hier.state.weights),
                               np.asarray(flat.state.weights),
                               atol=1e-6, rtol=1e-5)


def test_hier_round_equals_span_bitwise(tmp_path):
    """Per-round dispatch and the scanned span are the same program
    family (hier_core under jit vs fori_loop) — bit-identical states,
    like the flat engine's span pin."""
    ds = _dataset()
    a = FederatedExperiment(_hier(tmp_path, defense="Krum", epochs=4),
                            attacker=DriftAttack(1.0), dataset=ds)
    for t in range(4):
        a.run_round(t)
    b = FederatedExperiment(_hier(tmp_path, defense="Krum", epochs=4),
                            attacker=DriftAttack(1.0), dataset=ds)
    b.run_span(0, 4)
    np.testing.assert_array_equal(np.asarray(a.state.weights),
                                  np.asarray(b.state.weights))


def test_hier_cost_entries_and_megabatch_bound(tmp_path):
    """The cost ledger exposes hier_round/hier_span/tier2_* entry
    points, and the hierarchical round's temp bytes at the same cohort
    undercut the flat round's (the (n, d)/(n, n) buffers are gone —
    the small-scale shadow of the perf-gate memproof)."""
    ds = _dataset()
    hier = FederatedExperiment(
        _hier(tmp_path, users_count=48, megabatch=8, defense="Krum",
              tier2_defense="Krum"),
        attacker=DriftAttack(1.0), dataset=ds)
    led = hier.cost_report()
    names = [r.name for r in led.records]
    assert "hier_round" in names and "hier_span" in names
    assert "tier2_Krum" in names and not led.errors
    flat = FederatedExperiment(
        _cfg(tmp_path, users_count=48, defense="Krum"),
        attacker=DriftAttack(1.0), dataset=ds)
    led_f = flat.cost_report()
    temp = {r.name: r.temp_bytes for r in led.records}
    temp_f = {r.name: r.temp_bytes for r in led_f.records}
    assert temp["hier_round"] < temp_f["fused_round"]


# ---------------------------------------------------------------------------
# acceptance (c): the colluder-placement tolerance flip

def test_mal_placement_tolerance_flip(tmp_path):
    """SYNTH_MNIST_HARD, n=64, m=16, f=16, ALIE z=1.5 (behavioral-test
    batch 64): spread colluders put ~f/S identical crafted rows in
    EVERY megabatch — duplicates have zero mutual distance, so
    per-shard Krum selects the crafted vector everywhere and the run
    collapses like flat Krum does at this f.  Concentrated colluders
    saturate one megabatch but leave the other tier-1 estimates clean,
    and tier-2 Krum (f2=1) rejects the poisoned estimate — the
    defense is RESCUED (measured ~69% vs ~11%; GRID_RESULTS.md row).
    """
    ds = load_dataset(C.SYNTH_MNIST_HARD, seed=0)

    def acc(placement):
        cfg = ExperimentConfig(
            dataset=C.SYNTH_MNIST_HARD, users_count=64, mal_prop=0.25,
            batch_size=64, epochs=10, test_step=10, num_std=1.5,
            defense="Krum", seed=0, aggregation="hierarchical",
            megabatch=16, mal_placement=placement,
            log_dir=str(tmp_path / "logs"),
            run_dir=str(tmp_path / "runs"))
        exp = FederatedExperiment(
            cfg, attacker=make_attacker(cfg, dataset=ds), dataset=ds)
        exp.run_span(0, 10)
        _, correct = exp.evaluate(exp.state.weights)
        return 100.0 * float(correct) / len(ds.test_y)

    a_spread, a_conc = acc("spread"), acc("concentrated")
    assert a_conc - a_spread > 25.0, (a_spread, a_conc)
    assert a_spread < 35.0          # spread collapses
    assert a_conc > 50.0            # concentrated is rescued


# ---------------------------------------------------------------------------
# acceptance (d): SIGTERM preempt + resume mid-scan, bit-for-bit

def test_hier_preempt_resume_bit_for_bit(tmp_path):
    """Same harness as test_faults.py's SIGTERM test: a hierarchical
    run gracefully preempted at a seeded round and restarted finishes
    with final weights bit-for-bit equal to the uninterrupted run, and
    the journal audits exactly-once."""
    from attacking_federate_learning_tpu.utils.lifecycle import (
        GracefulShutdown, Preempted, RunJournal
    )

    kill_round = int(np.random.default_rng(23).integers(1, 9))
    ds = _dataset()

    def cfg_for(run_dir):
        return _hier(tmp_path, defense="Krum", epochs=10, test_step=5,
                     checkpoint_every=3, run_dir=str(tmp_path / run_dir))

    cfg_ref = cfg_for("runs_ref")
    full = FederatedExperiment(cfg_ref, attacker=DriftAttack(1.0),
                               dataset=ds)
    with RunLogger(cfg_ref, None, cfg_ref.log_dir,
                   jsonl_name="hier_full") as logger:
        full.run(logger, checkpointer=Checkpointer(cfg_ref))
    w_full = np.array(full.state.weights, copy=True)
    v_full = np.array(full.state.velocity, copy=True)

    cfg = cfg_for("runs_sup")
    ck = Checkpointer(cfg)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir,
                   jsonl_name="hier_sup") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "hier"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    resumed = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
    state, _extra = ck.resume(ck.latest(), with_extra=True)
    resumed.state = state
    with RunLogger(cfg, None, cfg.log_dir,
                   jsonl_name="hier_sup") as logger:
        resumed.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "hier"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    np.testing.assert_array_equal(np.asarray(resumed.state.weights),
                                  w_full)
    np.testing.assert_array_equal(np.asarray(resumed.state.velocity),
                                  v_full)
    assert RunJournal(cfg.run_dir, "hier").verify(
        epochs=10, test_step=5) == []
    with open(os.path.join(cfg.log_dir, "hier_sup.jsonl")) as f:
        events = [json.loads(line) for line in f]
    evals = [e["round"] for e in events if e["kind"] == "eval"]
    assert evals == sorted(set(evals))      # each eval exactly once


# ---------------------------------------------------------------------------
# config / CLI surface

def test_hier_config_validation(tmp_path):
    with pytest.raises(ValueError, match="megabatch"):
        _cfg(tmp_path, aggregation="hierarchical")          # no megabatch
    with pytest.raises(ValueError, match="divide"):
        _cfg(tmp_path, aggregation="hierarchical", megabatch=5)
    with pytest.raises(ValueError, match="shards"):
        _cfg(tmp_path, aggregation="hierarchical", megabatch=12)
    with pytest.raises(ValueError, match="aggregation"):
        _cfg(tmp_path, aggregation="tree")
    with pytest.raises(ValueError, match="tier2_defense"):
        _cfg(tmp_path, tier2_defense="FLTrust")


def test_hier_engine_rejects_unsupported_combos(tmp_path):
    # NOTE (ISSUE 8): telemetry/log_round_stats are no longer in this
    # matrix — they are supported hierarchical compositions now (the
    # per-shard diagnostics ride the scan as (S, m) stacks); ISSUE 19
    # likewise removed fault injection (per-shard quarantine masks
    # inside the scan step, tests/test_hier_faults.py); the remaining
    # rejections pin only the still-unsupported set.
    ds = _dataset()
    for kw, match in (
            (dict(participation=0.5), "participation"),
            (dict(data_placement="host_stream"), "device"),
            (dict(defense="GeoMedian"), "tier-1"),
            (dict(distance_impl="host"), "distance_impl"),
            (dict(trimmed_mean_impl="host"), "trimmed_mean_impl"),
    ):
        with pytest.raises(ValueError, match=match):
            FederatedExperiment(_hier(tmp_path, **kw),
                                attacker=DriftAttack(1.0), dataset=ds)
    # Tier validity bounds surface at init, not trace time.
    with pytest.raises(ValueError, match="Bulyan requires"):
        FederatedExperiment(
            _hier(tmp_path, defense="Bulyan", tier1_corrupted=2),
            attacker=DriftAttack(1.0), dataset=ds)


# ---------------------------------------------------------------------------
# ISSUE 8: per-shard telemetry, tier-2 forensics, colluder localization

def test_two_tier_telemetry_bit_matches_flat_kernels():
    """two_tier_aggregate(telemetry=True): each stacked tier-1
    diagnostics row is BIT-FOR-BIT the flat kernel's telemetry on that
    shard's sub-matrix (the ISSUE 8 acceptance contract), the tier-2
    diag is the shard_* entry's (S,) selection record, and the
    aggregate itself is bit-equal to the telemetry-off call."""
    n, m, f = 32, 8, 3
    pl = make_placement(n, f, m, "concentrated")
    f1 = tier1_assumed(f, pl.num_shards)
    f2 = max(tier2_assumed(f, m), 1)
    rng = np.random.default_rng(11)
    G = jnp.asarray(rng.standard_normal((n, 40)).astype(np.float32))
    t2 = TIER2_DEFENSES["Krum"]
    with jax.disable_jit():
        plain = two_tier_aggregate(G, pl, krum, t2, f1, f2)
        agg, t1d, t2d = two_tier_aggregate(G, pl, krum, t2, f1, f2,
                                           telemetry=True)
        # Per-shard rows == the flat kernel's telemetry on the same
        # sub-matrix (op-identical dispatch -> bitwise).
        for s in range(pl.num_shards):
            _, want = krum(G[jnp.asarray(pl.grid[s])], m, f1,
                           telemetry=True)
            for k in want:
                np.testing.assert_array_equal(
                    np.asarray(t1d[k][s]), np.asarray(want[k]), err_msg=k)
        # Tier-2 record: one-hot over the shard axis.
        _, want2 = krum(jnp.stack([
            krum(G[jnp.asarray(pl.grid[s])], m, f1)
            for s in range(pl.num_shards)]).astype(jnp.float32),
            pl.num_shards, f2, telemetry=True)
        np.testing.assert_array_equal(
            np.asarray(t2d["selection_mask"]),
            np.asarray(want2["selection_mask"]))
    np.testing.assert_array_equal(np.asarray(agg), np.asarray(plain))
    assert np.asarray(t1d["selection_mask"]).shape == (pl.num_shards, m)
    assert np.asarray(t2d["selection_mask"]).shape == (pl.num_shards,)


def test_shard_kernels_telemetry_passthrough():
    """Every TIER2_DEFENSES entry takes telemetry= and returns a
    bit-identical aggregate plus a fixed-shape (S,)/() diag."""
    rng = np.random.default_rng(5)
    E = jnp.asarray(rng.standard_normal((7, 24)).astype(np.float32))
    for name, fn in sorted(TIER2_DEFENSES.items()):
        plain = np.asarray(fn(E, 7, 1))
        agg, diag = fn(E, 7, 1, telemetry=True)
        np.testing.assert_array_equal(plain, np.asarray(agg),
                                      err_msg=name)
        for k, v in diag.items():
            assert np.asarray(v).shape in ((), (7,)), (name, k)
    assert TIER2_DEFENSES["NoDefense"](E, 7, 0, telemetry=True)[1] == {}


def test_hier_telemetry_on_off_bit_identical_and_hlo_clean(tmp_path):
    """Engine acceptance: telemetry must be a pure observer of the
    hierarchical round — on/off final weights bit-equal (span path),
    and the telemetry-OFF compiled round carries none of the stacked
    (S, m) diagnostics tensors (the structural half of the
    byte-identity pin; tools/perf_gate.py's hier cells staying
    byte-exact is the other half)."""
    ds = _dataset()
    off = FederatedExperiment(_hier(tmp_path, defense="Krum", epochs=4),
                              attacker=DriftAttack(1.0), dataset=ds)
    off.run_span(0, 4)
    on = FederatedExperiment(
        _hier(tmp_path, defense="Krum", epochs=4, telemetry=True),
        attacker=DriftAttack(1.0), dataset=ds)
    on.run_span(0, 4)
    np.testing.assert_array_equal(np.asarray(off.state.weights),
                                  np.asarray(on.state.weights))
    np.testing.assert_array_equal(np.asarray(off.state.velocity),
                                  np.asarray(on.state.velocity))
    # Structural HLO pin: S=3, m=4 — the stacked per-shard mask/score/
    # norm tensors are f32[3,4]; the off program must not contain one
    # (compiled-HLO text, the wire_hlo_facts convention).
    text_off = off._fused_round.lower(
        off.state, jnp.asarray(0, jnp.int32)).compile().as_text()
    text_on = on._fused_round.lower(
        on.state, jnp.asarray(0, jnp.int32)).compile().as_text()
    assert "f32[3,4]" not in text_off
    assert "f32[3,4]" in text_on          # non-vacuous
    # Stacked telemetry shapes: (rounds, S, m) tier-1, (rounds, S)
    # tier-2, from the span's one fetch.
    t0, stacked = on.last_span_telemetry
    host = jax.tree.map(np.asarray, stacked)
    assert host["shard_selection_mask"].shape == (4, 3, 4)
    assert host["tier2_selection_mask"].shape == (4, 3)
    # Per-round tier-1 masks are one-hot per shard (Krum), and the
    # tier-2 mask is one-hot over shards.
    assert (host["shard_selection_mask"].sum(axis=2) == 1.0).all()
    assert (host["tier2_selection_mask"].sum(axis=1) == 1.0).all()


def test_hier_round_stats(tmp_path):
    """--round-stats on a hierarchical run: per-round scalar diag with
    the flat keys, computed exactly from the (S, m) norm stack (same n
    values, different reduction shape)."""
    ds = _dataset()
    exp = FederatedExperiment(
        _hier(tmp_path, defense="Krum", log_round_stats=True),
        attacker=DriftAttack(1.0), dataset=ds)
    exp.run_round(0)
    diag = {k: float(v) for k, v in exp.last_round_stats.items()}
    assert set(diag) == {"grad_norm_mean", "grad_norm_max",
                         "grad_norm_min", "update_norm", "faded_lr"}
    assert diag["grad_norm_max"] >= diag["grad_norm_mean"] >= (
        diag["grad_norm_min"]) > 0


def test_hier_tele_cost_entry(tmp_path):
    """The telemetry engine ledgers its span under hier_tele_span —
    the perf-gate hier_krum_tele cell's entry point."""
    ds = _dataset()
    exp = FederatedExperiment(
        _hier(tmp_path, defense="Krum", telemetry=True),
        attacker=DriftAttack(1.0), dataset=ds)
    led = exp.cost_report()
    names = [r.name for r in led.records]
    assert "hier_tele_span" in names and not led.errors


def test_hier_telemetry_events_and_forensics_localization(tmp_path):
    """Satellite acceptance: a 10-round concentrated-placement Krum
    run emits one schema-v6 'shard_selection' event per round whose
    tier-2 mask rejects the colluder shard, and `report forensics`
    localizes it — the verdict NAMES the malicious shard(s)."""
    from attacking_federate_learning_tpu import report

    ds = load_dataset(C.SYNTH_MNIST_HARD, seed=0)
    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST_HARD, users_count=20, mal_prop=0.2,
        batch_size=64, epochs=10, test_step=10, num_std=1.5,
        defense="Krum", seed=0, aggregation="hierarchical",
        megabatch=5, mal_placement="concentrated", telemetry=True,
        log_dir=str(tmp_path / "logs"), run_dir=str(tmp_path / "runs"))
    exp = FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                              dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="fx") as logger:
        exp.run(logger)
    path = os.path.join(cfg.log_dir, "fx.jsonl")
    events = report.load_events([path])       # schema-validates v6
    ss = [e for e in events if e["kind"] == "shard_selection"]
    assert len(ss) == 10
    assert all(e["v"] >= 6 for e in ss)   # stamped with the writer version
    assert ss[0]["mal_counts"] == [4, 0, 0, 0]
    # Placement packs all 4 colluders into shard 0; tier-2 Krum must
    # reject its estimate (zero selection mass) every round — the
    # measured GRID round-6 rescue, now attributed.
    for e in ss:
        assert e["tier2_selection_mask"][0] == 0.0
    fx = report.forensics_summary(events)
    assert fx["malicious_shards"] == [0]
    assert fx["localization"]["verdict"] == "localized"
    assert fx["localization"]["isolated_shards"] == [0]
    assert fx["tier2"]["mal_rejected_rounds"] == 10
    assert fx["tier2"]["malicious_share"] == 0.0
    # Tier-1 concentration: the colluder shard's selection collapses
    # onto its own malicious rows (the duplicate-collapse mechanism).
    row0 = next(r for r in fx["tier1"] if r["shard"] == 0)
    assert row0["malicious_share"] > 0.9
    # The CLI surface agrees: `report forensics` exits 0 and the
    # emitted v6 'forensics' event validates.
    ev_path = str(tmp_path / "fx_verdict.jsonl")
    assert report.forensics_main([path, "--events", ev_path]) == 0
    rec = json.loads(open(ev_path).read().strip())
    assert rec["kind"] == "forensics" and rec["v"] >= 6
    assert rec["verdict"] == "localized"
    assert rec["isolated_shards"] == [0]
    # A flat log (no shard_selection events) is a named failure.
    flat = str(tmp_path / "flat.jsonl")
    with open(flat, "w") as f:
        f.write(json.dumps({"kind": "round", "round": 0, "v": 1}) + "\n")
    assert report.forensics_main([flat]) == 1


def test_trace_export_forensics_track(tmp_path):
    """Synthetic shard_selection/forensics events land as the tier-2
    rejection counter + forensics instants, and the exported trace
    validates."""
    from attacking_federate_learning_tpu.utils.trace_export import (
        events_to_trace, validate_trace
    )

    events = [
        {"kind": "shard_selection", "round": 0, "defense": "Krum",
         "tier2_selection_mask": [0.0, 1.0, 0.0], "v": 6, "t": 1.0},
        {"kind": "shard_selection", "round": 1, "defense": "Krum",
         "tier2_kept_fraction": [0.05, 0.9, 0.85], "v": 6, "t": 2.0},
        {"kind": "shard_selection", "round": 2, "defense": "NoDefense",
         "v": 6, "t": 3.0},                   # no attribution: no point
        {"kind": "forensics", "verdict": "localized",
         "isolated_shards": [0], "v": 6, "t": 4.0},
    ]
    trace = events_to_trace(events)
    assert validate_trace(trace) == []
    counters = [e for e in trace["traceEvents"]
                if e["name"] == "tier2_rejected"]
    assert [e["args"]["tier2_rejected"] for e in counters] == [2.0, 1.0]
    instants = [e for e in trace["traceEvents"]
                if e["name"].startswith("tier2 reject")]
    assert len(instants) == 2
    assert instants[0]["args"]["rejected_shards"] == "0,2"
    assert instants[1]["args"]["rejected_shards"] == "0"
    assert any(e["name"] == "forensics:localized"
               for e in trace["traceEvents"])


def test_cli_hier_flags_roundtrip():
    from attacking_federate_learning_tpu.cli import (
        build_parser, config_from_args
    )

    args = build_parser().parse_args(
        ["-d", "Krum", "-s", "SYNTH_MNIST", "-n", "12",
         "--aggregation", "hierarchical", "--megabatch", "4",
         "--tier2-defense", "TrimmedMean", "--mal-placement",
         "concentrated", "--tier1-corrupted", "2",
         "--tier2-corrupted", "1"])
    cfg = config_from_args(args)
    assert cfg.aggregation == "hierarchical" and cfg.megabatch == 4
    assert cfg.tier2_defense == "TrimmedMean"
    assert cfg.mal_placement == "concentrated"
    assert cfg.tier1_corrupted == 2 and cfg.tier2_corrupted == 1
