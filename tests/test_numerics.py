"""Numerics & determinism observatory (ISSUE 20).

Acceptance contract: the device health counters hold their units
(nonfinite by stage, norm dynamic range, tie proximity banded at k ulp
of the boundary's own scale, Gram cancellation depth); the host ulp
machinery is the shared f32 lattice (ordinals, NaN conventions, the
f64-adjudicated verdict taxonomy the divergence ledger persists into
NUMERICS_BASELINE.json); numerics-off programs stay HLO byte-identical
(the kernel seam here, all 62 perf_gate entry points plus the
bit-identity behavioral twin in CI via --numproof); numerics without
margins is rejected at the kernel and host impls at config time; every
engine (flat, hierarchical, async) emits one schema-v14 ``numerics``
event per round; same-seed twins are bit-deterministic while seeded
diverging twins get a stage-attributed first-divergence from
``runs diff --band``; and the reader stack (rollups, series, drift,
``check_events --stats`` over mixed-version logs, the trace counter
track, the numerics gate's banding rules) holds its contracts.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses.kernels import (
    bulyan, krum, trimmed_mean
)
from attacking_federate_learning_tpu.defenses.median import median
from attacking_federate_learning_tpu.utils import numerics as N
from attacking_federate_learning_tpu.utils.metrics import RunLogger


def _grads(n=12, d=40, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .normal(size=(n, d)).astype(np.float32))


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 12)
    kw.setdefault("mal_prop", 0.2)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 4)
    kw.setdefault("test_step", 2)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("defense", "Krum")
    kw.setdefault("numerics", True)
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    return ExperimentConfig(**kw)


def _run(cfg, name, z=1.5):
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(z), dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name=name) as logger:
        exp.run(logger)
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    return exp, events


def _numerics_events(events):
    return [e for e in events if e.get("kind") == "numerics"]


# ---------------------------------------------------------------------------
# tentpole: device health counters hold their units

def test_nonfinite_count_and_mask():
    x = jnp.asarray(np.array([[1.0, np.inf, 2.0],
                              [np.nan, 3.0, -np.inf]], np.float32))
    assert int(N.nonfinite_count(x)) == 3
    mask = jnp.asarray(np.array([True, False]))
    assert int(N.nonfinite_count(x, mask)) == 1


def test_norm_dynamic_range_units():
    G = jnp.asarray(np.array([[4.0, 0.0], [1.0, 0.0]], np.float32))
    # norms 4 and 1: log2(4/1) = 2 bits of dynamic range.
    assert float(N.norm_dynamic_range(G)) == pytest.approx(2.0)
    # Fewer than two usable rows is degenerate, not an error.
    assert float(N.norm_dynamic_range(
        G, jnp.asarray(np.array([True, False])))) == 0.0
    assert float(N.norm_dynamic_range(jnp.zeros((3, 2)))) == 0.0
    # Nonfinite rows are excluded from the range, not propagated.
    Gn = jnp.asarray(np.array([[np.inf, 0.0], [2.0, 0.0], [1.0, 0.0]],
                              np.float32))
    assert float(N.norm_dynamic_range(Gn)) == pytest.approx(1.0)


def test_tie_proximity_bands_at_boundary_scale():
    """A margin within k ulp AT THE BOUNDARY'S SCALE counts as a tie;
    the same absolute margin at a tiny scale does not."""
    scale = 1.0
    band = N.TIE_BAND_ULPS * (2.0 ** -23) * scale
    m = jnp.asarray(np.array([band * 0.5, -band * 0.5, band * 4.0,
                              np.inf, -np.inf], np.float32))
    assert int(N.tie_proximity(m, scale)) == 2
    # Shrinking the boundary scale shrinks the band with it.
    assert int(N.tie_proximity(m, scale * 1e-3)) == 0
    # ulp_at never underflows to a zero band.
    assert float(N.ulp_at(0.0)) > 0.0


def test_cancellation_bits_units():
    # 2^20 max term cancelling to a 2^-4 survivor: 24 bits gone.
    assert float(N.cancellation_bits(2.0 ** 20, 2.0 ** -4)) == \
        pytest.approx(24.0)
    # No cancellation (result at the term scale) reports 0, not noise.
    assert float(N.cancellation_bits(8.0, 8.0)) == 0.0


def test_gram_cancellation_bits():
    D = jnp.asarray(np.array([[np.inf, 4.0, 16.0],
                              [4.0, np.inf, 1.0],
                              [16.0, 1.0, np.inf]], np.float32))
    # max finite 16, min positive 1 -> 4 bits.
    assert float(N.gram_cancellation_bits(D)) == pytest.approx(4.0)
    # Masking row 2 removes both extremes -> 4/4 -> 0 bits.
    mask = jnp.asarray(np.array([True, True, False]))
    assert float(N.gram_cancellation_bits(D, mask)) == 0.0
    # An identical cohort (no positive distance) is 0, not -inf/NaN.
    assert float(N.gram_cancellation_bits(
        jnp.full((3, 3), jnp.inf) * 0.0)) == 0.0


# ---------------------------------------------------------------------------
# tentpole: host ulp machinery (the ledger's referee)

def test_f32_ords_and_ulp_diff_lattice():
    a = np.float32(1.0)
    b = np.nextafter(a, np.float32(2.0), dtype=np.float32)
    assert int(N.ulp_diff([a], [b])[0]) == 1
    # The ordinal is monotone across the sign change.
    vals = np.array([-1.0, -0.0, 0.0, 1e-30, 1.0], np.float32)
    ords = N.f32_ords(vals)
    assert list(np.argsort(ords)) == [0, 1, 2, 3, 4]
    # NaN-vs-NaN is the same non-value; NaN-vs-number is unbandable.
    assert int(N.ulp_diff([np.nan], [np.nan])[0]) == 0
    assert int(N.ulp_diff([np.nan], [1.0])[0]) == 2 ** 31


def test_max_ulp_argmax():
    a = np.zeros(4, np.float32)
    b = a.copy()
    b[2] = np.nextafter(np.float32(0.0), np.float32(1.0),
                        dtype=np.float32)
    u, i = N.max_ulp(a, b)
    assert (u, i) == (1, 2)
    assert N.max_ulp(a, a) == (0, -1)


def test_adjudicate_verdict_taxonomy():
    oracle = np.array([1.0, 2.0, 3.0], np.float64)
    o32 = oracle.astype(np.float32)
    # Bit-identical -> exact.
    assert N.adjudicate(o32, o32, oracle)["verdict"] == "exact"
    # 1-ulp wiggle around the oracle -> tie_band, inside the band.
    b = o32.copy()
    b[1] = np.nextafter(b[1], np.float32(10.0), dtype=np.float32)
    rec = N.adjudicate(o32, b, oracle)
    assert rec["verdict"] == "tie_band" and rec["in_tie_band"]
    assert rec["max_ulp"] == 1 and rec["argmax_coord"] == 1
    # One impl far off the oracle while the other sits on it: the
    # accuracy asymmetry is named, not averaged away.
    far = o32.copy()
    far[0] = o32[0] * np.float32(1.5)
    assert N.adjudicate(o32, far, oracle)["verdict"] == "a_closer"
    assert N.adjudicate(far, o32, oracle)["verdict"] == "b_closer"
    # Each impl wrong on a different coordinate -> split.
    a = o32.copy()
    a[2] = o32[2] * np.float32(1.5)
    assert N.adjudicate(a, far, oracle)["verdict"] == "split"


# ---------------------------------------------------------------------------
# seam contracts: numerics-off HLO identity, kernel + config rejections

def test_numerics_off_is_hlo_identical():
    """numerics=False must be a trace-time no-op: the lowered program
    is byte-identical to one that never mentions the kwarg (the
    engine-level twin is tools/perf_gate.py --numproof)."""
    n, d, f = 12, 40, 2
    spec = jax.ShapeDtypeStruct((n, d), jnp.float32)
    for fn in (
        lambda kw: jax.jit(lambda g: krum(g, n, f, telemetry=True, **kw)),
        lambda kw: jax.jit(lambda g: trimmed_mean(g, n, f, telemetry=True,
                                                  **kw)),
        lambda kw: jax.jit(lambda g: median(g, n, f, telemetry=True,
                                            **kw)),
        lambda kw: jax.jit(lambda g: bulyan(g, n, f, telemetry=True,
                                            **kw)),
    ):
        base = fn({}).lower(spec).as_text()
        off = fn({"margins": False, "numerics": False}).lower(
            spec).as_text()
        assert base == off


def test_kernel_numerics_require_margins():
    """Kernel tie counters band the PR 18 margin tensors; numerics
    without margins has nothing to band and is a caller bug."""
    G = _grads()
    for call in (
        lambda: krum(G, 12, 2, telemetry=True, numerics=True),
        lambda: trimmed_mean(G, 12, 2, telemetry=True, numerics=True),
        lambda: median(G, 12, 2, telemetry=True, numerics=True),
        lambda: bulyan(G, 12, 2, telemetry=True, numerics=True),
    ):
        with pytest.raises(ValueError, match="requires margins"):
            call()


def test_kernel_numerics_fields():
    """With margins on, each margin-bearing kernel returns its num_*
    counters next to (never inside) the margin fields."""
    G = _grads(12, 40, seed=9)
    _, diag = krum(G, 12, 2, telemetry=True, margins=True, numerics=True)
    for f in ("num_tie_rows", "num_cancel_bits"):
        assert f in diag
    assert float(diag["num_cancel_bits"]) >= 0.0
    _, diag = trimmed_mean(G, 12, 2, telemetry=True, margins=True,
                           numerics=True)
    assert "num_tie_rows" in diag


def test_config_rejects_host_impls_under_numerics():
    """--numerics on a margin-bearing defense shares --margins'
    on-device-impl requirement (the tie counters ride the margin
    tensors); on any other defense only the defense-agnostic stage
    counters run and no constraint applies."""
    for knob, defense in (
        ("distance_impl", "Krum"),
        ("median_impl", "Median"),
        ("bulyan_selection_impl", "Bulyan"),
    ):
        with pytest.raises(ValueError, match=knob):
            ExperimentConfig(numerics=True, defense=defense,
                             **{knob: "host"})
    # Stage counters compose with everything else.
    ExperimentConfig(numerics=True, defense="Krum")
    ExperimentConfig(numerics=True, defense="NoDefense")
    ExperimentConfig(numerics=True, defense="DnC")


# ---------------------------------------------------------------------------
# engine: the schema-v14 numerics event, all three engines

def test_flat_numerics_events(tmp_path):
    """--numerics alone emits one v14 numerics event per round with
    the full flat field set — and neither margin nor defense telemetry
    events ride along on the wire."""
    cfg = _cfg(tmp_path, defense="Krum")
    _, events = _run(cfg, "num_flat.jsonl")
    nev = _numerics_events(events)
    assert len(nev) == cfg.epochs
    for e in nev:
        assert e["v"] >= 14
        assert e["defense"] == "Krum"
        assert e["tie_band_ulps"] == N.TIE_BAND_ULPS
        for f in ("nonfinite_pre", "nonfinite_post", "nonfinite_agg",
                  "range_log2", "tie_rows", "cancel_bits",
                  "nonfinite_total", "tie_locked"):
            assert f in e, f
        assert e["nonfinite_total"] == 0
        assert e["tie_locked"] in (0, 1)
        assert e["range_log2"] >= 0.0
    assert not [e for e in events if e.get("kind") in
                ("margin", "defense")]


def test_flat_numerics_without_margin_defense(tmp_path):
    """On a defense with no margin tensors, only the defense-agnostic
    stage counters appear — no fabricated tie/cancellation numbers."""
    cfg = _cfg(tmp_path, defense="NoDefense")
    _, events = _run(cfg, "num_nodef.jsonl")
    nev = _numerics_events(events)
    assert len(nev) == cfg.epochs
    for e in nev:
        assert "nonfinite_pre" in e and "range_log2" in e
        assert "tie_rows" not in e and "cancel_bits" not in e


def test_hier_numerics_events(tmp_path):
    """Hierarchical rounds carry shard_/tier2_ tie and cancellation
    stacks on the same names, plus the defense-agnostic stage counters
    measured once at the engine level."""
    cfg = _cfg(tmp_path, defense="Krum", aggregation="hierarchical",
               megabatch=4, tier2_defense="Krum")
    _, events = _run(cfg, "num_hier.jsonl")
    nev = _numerics_events(events)
    assert len(nev) == cfg.epochs
    for e in nev:
        for f in ("shard_tie_rows", "shard_cancel_bits",
                  "tier2_tie_rows", "tier2_cancel_bits",
                  "nonfinite_post", "nonfinite_agg", "range_log2",
                  "nonfinite_total", "tie_locked"):
            assert f in e, f


def test_async_numerics_events(tmp_path):
    cfg = _cfg(tmp_path, defense="Krum", aggregation="async",
               async_buffer=6, epochs=6)
    _, events = _run(cfg, "num_async.jsonl")
    nev = _numerics_events(events)
    assert len(nev) == cfg.epochs
    for e in nev:
        assert "tie_rows" in e and "nonfinite_pre" in e


# ---------------------------------------------------------------------------
# determinism + runs diff stage attribution (satellite: runs diff --band)

def test_same_seed_twins_are_bit_deterministic(tmp_path):
    """Two same-seed runs reproduce their numerics trajectory to the
    bit — the determinism bar runs diff enforces at band 0."""
    from attacking_federate_learning_tpu import runs_cli

    cfg = _cfg(tmp_path, defense="Krum")
    _, ev_a = _run(cfg, "num_twin_a.jsonl")
    _, ev_b = _run(cfg, "num_twin_b.jsonl")
    d = runs_cli.diff_trajectories(_numerics_events(ev_a),
                                   _numerics_events(ev_b), band=0)
    assert d["bit_identical"] is True
    assert d["divergence_round"] is None


def test_runs_diff_attributes_divergence_stage(tmp_path):
    """Two seeded twins whose attacks differ diverge in their numerics
    records; runs diff names the round, the pipeline stage and the f32
    ulp size of the first mismatch."""
    from attacking_federate_learning_tpu import runs_cli

    cfg = _cfg(tmp_path, defense="Krum")
    _, ev_a = _run(cfg, "num_div_a.jsonl", z=1.5)
    _, ev_b = _run(cfg, "num_div_b.jsonl", z=0.5)
    d = runs_cli.diff_trajectories(_numerics_events(ev_a),
                                   _numerics_events(ev_b), band=0)
    assert d["divergence_round"] is not None
    assert d["divergence_kind"] == "numerics"
    assert d["divergence_stage"] in ("deliver", "quarantine",
                                     "tier1_aggregate", "apply")
    assert d["divergence_anchor"] in d["divergence_fields"]
    assert d["divergence_ulp"] is not None and d["divergence_ulp"] > 0
    # The anchored field observes the stage the report names.
    assert N.stage_of(d["divergence_anchor"]) == d["divergence_stage"]
    # A band wide enough to cover the envelope reports clean.
    wide = runs_cli.diff_trajectories(
        _numerics_events(ev_a), _numerics_events(ev_a), band=4)
    assert wide.get("identical_within_band") is True


def test_stage_attribution_units():
    assert N.stage_of("nonfinite_pre") == "deliver"
    assert N.stage_of("nonfinite_post") == "quarantine"
    assert N.stage_of("tie_rows") == "tier1_aggregate"
    assert N.stage_of("shard_cancel_bits") == "tier1_aggregate"
    assert N.stage_of("tier2_tie_rows") == "tier2_aggregate"
    assert N.stage_of("nonfinite_agg") == "apply"
    assert N.stage_of("attack_z_used", kind="margin") == "deliver"
    assert N.stage_of("margin_gap", kind="margin") == "tier1_aggregate"
    # Attribution picks the largest comparable ulp as its anchor.
    stage, ulp, anchor = N.divergence_attribution(
        {"nonfinite_pre": [0, 0.0],            # 0 ulp
         "cancel_bits": [1.0, 1.5],            # large
         "tie_rows": [None, 2]})               # not comparable
    assert anchor == "cancel_bits" and stage == "tier1_aggregate"
    assert ulp == N.field_ulp(1.0, 1.5)
    # Nothing comparable: stage still attributes, ulp stays None.
    stage, ulp, anchor = N.divergence_attribution(
        {"tier2_tie_rows": [None, [1]]})
    assert stage == "tier2_aggregate" and ulp is None


# ---------------------------------------------------------------------------
# reader stack: rollups, series, drift, report

def test_numerics_rollups_units():
    r = N.numerics_rollups({"nonfinite_pre": 2, "nonfinite_post": 1.0,
                            "shard_nonfinite_agg": [1, 0, 3],
                            "tie_rows": 0, "cancel_bits": 40.0})
    assert r == {"nonfinite_total": 7, "tie_locked": 0}
    r = N.numerics_rollups({"shard_tie_rows": [0, 2, 0],
                            "nonfinite_agg": float("nan")})
    assert r == {"nonfinite_total": 0, "tie_locked": 1}
    r = N.numerics_rollups({"tier2_tie_rows": 1})
    assert r["tie_locked"] == 1


def test_numerics_series_and_drift():
    events = []
    for t, (tr, cb) in enumerate([(0, 10.0), (2, 12.0), (1, 11.0)]):
        events.append({"kind": "numerics", "round": t, "defense": "Krum",
                       "tie_rows": tr, "cancel_bits": cb,
                       "shard_tie_rows": [tr, tr + 1]})
    events.append({"kind": "eval", "round": 1})
    ser = N.numerics_series(events)
    assert ser["tie_rows"] == [(0, 0), (1, 2), (2, 1)]
    # Hier stacks reduce to their max — the conservative health view.
    assert ser["shard_tie_rows"] == [(0, 1), (1, 3), (2, 2)]
    other = N.numerics_series(
        [{"kind": "numerics", "round": t, "tie_rows": v}
         for t, v in [(0, 0), (1, 2), (2, 5)]])
    assert N.numerics_drift(ser, other, "tie_rows") == (2, 1, 5)
    assert N.numerics_drift(ser, ser, "tie_rows") is None


def test_report_numerics_summary(tmp_path):
    from attacking_federate_learning_tpu.report import numerics_summary

    cfg = _cfg(tmp_path, defense="Krum")
    _, events = _run(cfg, "num_report.jsonl")
    nm = numerics_summary(events)
    assert nm is not None
    assert nm["rounds"] == cfg.epochs
    assert nm["nonfinite_total"] == 0
    assert 0 <= nm["tie_locked_rounds"] <= cfg.epochs
    assert numerics_summary(
        [e for e in events if e.get("kind") != "numerics"]) is None


def test_runs_numerics_backend_reads_engine_events(tmp_path):
    """The numerics series loader digests a real engine stream the way
    the ``runs numerics`` verb renders it."""
    cfg = _cfg(tmp_path, defense="Median", epochs=4)
    _, events = _run(cfg, "num_runscli.jsonl")
    ser = N.numerics_series(events)
    assert ser
    assert len(ser["tie_rows"]) == cfg.epochs
    assert all(f in N.SERIES_FIELDS or
               f.split("_", 1)[1] in N.SERIES_FIELDS
               for f in ser)


# ---------------------------------------------------------------------------
# satellites: check_events --stats over a mixed-version log, trace track,
# the numerics gate's banding rules

def _load_tool(name):
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_events_stats_mixed_version_log(tmp_path):
    """One log holding v12 margin, v13 fault and v14 numerics rows —
    the realistic resumed-run file — validates cleanly and the stats
    histogram keeps the versions apart; a numerics kind stamped v13 is
    an emitter bug."""
    from attacking_federate_learning_tpu.utils.metrics import (
        validate_event
    )

    ce = _load_tool("check_events")
    p = tmp_path / "mixed.jsonl"
    rows = [
        {"kind": "margin", "round": 0, "defense": "Krum",
         "malicious_count": 2, "colluder_margin": -0.5, "v": 12,
         "t": 0.1},
        {"kind": "fault", "round": 0, "injected": 1, "v": 13, "t": 0.2},
        {"kind": "numerics", "round": 0, "defense": "Krum",
         "tie_rows": 0, "nonfinite_total": 0, "tie_locked": 0,
         "v": 14, "t": 0.3},
        {"kind": "numerics", "round": 1, "defense": "Krum",
         "tie_rows": 2, "nonfinite_total": 0, "tie_locked": 1,
         "v": 14, "t": 0.4},
        {"kind": "round", "round": 0, "v": 1, "t": 0.5},
    ]
    for r in rows:
        validate_event(r)
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    counts, legacy, errors = ce.check_file(str(p))
    assert not errors
    assert counts == {"margin": 1, "fault": 1, "numerics": 2,
                      "round": 1}
    stats = ce.file_stats(str(p))
    assert stats["numerics"] == {"count": 2, "versions": {14: 2}}
    assert stats["margin"]["versions"] == {12: 1}
    assert stats["fault"]["versions"] == {13: 1}
    # A numerics kind stamped with a pre-v14 version is an emitter bug.
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"kind": "numerics", "round": 0,
                               "defense": "Krum", "v": 13,
                               "t": 0.1}) + "\n")
    _, _, errors = ce.check_file(str(bad))
    assert errors


def test_trace_export_numerics_counter_track():
    from attacking_federate_learning_tpu.utils.trace_export import (
        events_to_trace, validate_trace
    )

    events = [
        {"kind": "numerics", "round": 0, "t": 0.1, "defense": "Krum",
         "nonfinite_total": 0, "tie_rows": 0, "cancel_bits": 12.5},
        {"kind": "numerics", "round": 1, "t": 0.2, "defense": "Krum",
         "nonfinite_total": 3, "tie_rows": 1, "cancel_bits": 40.0},
        # Hier stacks are lists; no scalar to draw, no point emitted.
        {"kind": "numerics", "round": 2, "t": 0.3, "defense": "Krum",
         "shard_tie_rows": [0, 1], "nonfinite_total": float("nan")},
    ]
    trace = events_to_trace(events)
    assert validate_trace(trace) == []
    pts = [e for e in trace["traceEvents"]
           if e.get("ph") == "C" and e["name"] == "numerics"]
    assert len(pts) == 2
    assert pts[0]["args"] == {"nonfinite_total": 0.0, "tie_rows": 0.0,
                              "cancel_bits": 12.5}
    assert pts[1]["args"]["nonfinite_total"] == 3.0


def test_numerics_gate_banding_rules():
    """The drift gate's diff logic: growth past the pinned ulp
    envelope, a verdict flip and an availability flip all fail; a
    shrinking envelope and an unchanged ledger pass."""
    ng = _load_tool("numerics_gate")

    def cell(max_ulp=2, verdict="tie_band"):
        return {"cohorts": {"drift": {"max_ulp": max_ulp,
                                      "n_mismatch": 1,
                                      "argmax_coord": 0,
                                      "in_tie_band": True,
                                      "verdict": verdict,
                                      "band_ulps": 8}}}

    base = {"Krum/topk": cell(), "Median/pallas": cell(0, "exact")}
    ok = ng.diff(base, {"Krum/topk": cell(),
                        "Median/pallas": cell(0, "exact")})
    assert not ok
    # Envelope growth fails; shrink passes.
    assert ng.diff(base, {"Krum/topk": cell(5),
                          "Median/pallas": cell(0, "exact")})
    assert not ng.diff(base, {"Krum/topk": cell(1),
                              "Median/pallas": cell(0, "exact")})
    # Verdict flip fails even inside the band.
    assert ng.diff(base, {"Krum/topk": cell(),
                          "Median/pallas": cell(0, "b_closer")})
    # Availability flip (a cell vanishing or erroring) fails.
    assert ng.diff(base, {"Krum/topk": cell()})
    assert ng.diff(base, {"Krum/topk": cell(),
                          "Median/pallas": {"cohorts": {
                              "drift": {"skipped": "impl unavailable"}}}})


def test_numerics_baseline_is_fresh():
    """The checked-in ledger matches this module's constants and holds
    the measured envelope classes the docs cite."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "NUMERICS_BASELINE.json")
    with open(path) as f:
        base = json.load(f)
    assert base["tie_band_ulps"] == N.TIE_BAND_ULPS
    cells = base["cells"]
    assert len(cells) >= 15
    for name, cell in cells.items():
        assert "/" in name
        for rec in cell["cohorts"].values():
            if "skipped" in rec:
                continue
            assert rec["verdict"] in ("exact", "tie_band", "a_closer",
                                      "b_closer", "split")
    # The pinned anchor facts: Krum's top-k twin is exact, and the
    # trimmed-mean variants sit in a small tie band.
    assert all(r["verdict"] == "exact"
               for r in cells["Krum/topk"]["cohorts"].values()
               if "skipped" not in r)
