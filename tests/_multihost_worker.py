"""Worker for the 2-process multihost exercise (run by test_multihost).

Each process contributes 4 virtual CPU devices; after
``multihost.initialize`` the global mesh spans 8 devices across the two
processes, and the blockwise ring distance kernel's ``ppermute`` hops cross
the process boundary over the distributed runtime — the DCN path of
SURVEY.md §2.3, on localhost.

Usage: python _multihost_worker.py <coord_addr> <num_procs> <proc_id> <out>
"""

import os
import sys

# Must be set before jax backend init (conftest isn't in play here).
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def main():
    coord, num_procs, proc_id, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])

    from attacking_federate_learning_tpu.parallel import multihost

    assert multihost.initialize(coordinator_address=coord,
                                num_processes=num_procs,
                                process_id=proc_id) is True
    assert jax.process_count() == num_procs
    assert jax.device_count() == 4 * num_procs          # global devices
    assert len(jax.local_devices()) == 4

    from attacking_federate_learning_tpu.defenses.kernels import krum
    from attacking_federate_learning_tpu.parallel.distances import (
        pairwise_distances_ring
    )
    from attacking_federate_learning_tpu.parallel.mesh import (
        CLIENTS, make_mesh
    )

    mesh = make_mesh((jax.device_count(), 1))

    # Same full matrix on both processes (same seed); each contributes its
    # process-local rows to the globally sharded array.
    n, d, f = 16, 256, 3
    G_full = np.random.default_rng(0).standard_normal((n, d)).astype(
        np.float32)
    sharding = NamedSharding(mesh, P(CLIENTS, None))
    G = jax.make_array_from_process_local_data(sharding, G_full[
        proc_id * (n // num_procs):(proc_id + 1) * (n // num_procs)])
    assert not G.is_fully_addressable   # genuinely spans both processes

    @jax.jit
    def agg(G):
        D = pairwise_distances_ring(G, mesh, axis=CLIENTS)
        out = krum(G, n, f, D=D)
        # Replicate so every process holds the full aggregate.
        return jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P()))

    out = agg(G)
    result = np.asarray(out.addressable_data(0))
    if multihost.is_primary():
        np.savez(out_path, agg=result, G=G_full)
    # Clean shutdown so the coordinator exits 0.
    jax.distributed.shutdown()
    print("WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
