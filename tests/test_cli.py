"""CLI-level tests: invoke cli.main([...]) end to end (VERDICT item #7).

Covers the reference-verbatim flag surface (reference main.py:103-153)
including the typo'd ``-dispatch_weightsn`` alias, the backdoor trigger
flag, resume-with-checkpoint, profiling output, and the TPU-era knobs.
"""

import json
import os

import numpy as np
import pytest

from attacking_federate_learning_tpu import cli


def run_cli(tmp_path, extra, epochs=6):
    argv = ["-s", "SYNTH_MNIST", "-e", str(epochs), "-c", "16",
            "--synth-train", "256", "--synth-test", "64",
            "--log-dir", str(tmp_path / "logs"),
            "--run-dir", str(tmp_path / "runs")] + extra
    return argv, cli.main(argv)


def test_reference_verbatim_flags_and_csv(tmp_path):
    # The reference's own spelling, incl. the -dispatch_weightsn typo alias
    # for --users-count (reference main.py:118).
    argv, result = run_cli(tmp_path, ["-dispatch_weightsn", "10",
                                      "-m", "0.1", "-z", "1.5",
                                      "-d", "Krum", "-l", "0.1"])
    assert len(result["accuracies"]) >= 2
    assert result["accuracies"][-1] > 50.0  # synth MNIST converges fast
    # CSV trajectory with the reference's filename schema (main.py:100).
    csvs = os.listdir(tmp_path / "logs")
    assert any(c.startswith("SYNTH_MNIST_stdev_1.5_Krum") and
               c.endswith(".csv") for c in csvs)


def test_backdoor_pattern_flag(tmp_path, capsys):
    _, result = run_cli(tmp_path, ["-b", "pattern", "-n", "8",
                                   "-m", "0.25", "-d", "NoDefense"],
                        epochs=3)
    out = capsys.readouterr().out
    assert "BEFORE" in out            # pre-training line (main.py:45-51)
    assert "malicious net" in out     # ASR lines (backdoor.py:96-101)
    assert len(result["accuracies"]) >= 1


def test_backdoor_sample_index_flag_coerced(tmp_path):
    # Reference leaves '-b 1' as the string '1' and crashes (str - int,
    # backdoor.py:34, SURVEY.md §2.4 #10); we coerce and run.
    _, result = run_cli(tmp_path, ["-b", "1", "-n", "8", "-m", "0.25"],
                        epochs=2)
    assert len(result["accuracies"]) >= 1


def test_resume_roundtrip(tmp_path):
    # First run crosses the checkpoint threshold (synth MNIST hits 100%
    # by round 5), writing runs/<ds>/checkpoint.npz (reference
    # main.py:84-89); the resumed run continues from the saved round.
    run_cli(tmp_path, ["-n", "10", "-m", "0.1", "-d", "NoDefense"],
            epochs=6)
    ckpt = tmp_path / "runs" / "SYNTH_MNIST" / "checkpoint.npz"
    assert ckpt.exists()
    saved_round = int(np.load(ckpt)["round"])
    assert saved_round > 0

    argv, result = run_cli(tmp_path, ["-n", "10", "-m", "0.1",
                                      "-d", "NoDefense", "--resume"],
                           epochs=9)
    # Continued (round counter advanced past the snapshot), still accurate.
    assert result["accuracies"][-1] > 90.0
    assert result["epochs"][-1] == 8


def test_resume_missing_checkpoint_exits(tmp_path):
    with pytest.raises(SystemExit, match="no checkpoint"):
        run_cli(tmp_path, ["--resume"], epochs=2)


def test_profile_flag_writes_phase_timing(tmp_path):
    run_cli(tmp_path, ["-n", "6", "-m", "0.0", "--profile"], epochs=3)
    logs = tmp_path / "logs"
    jsonls = [f for f in os.listdir(logs) if f.endswith(".jsonl")]
    assert jsonls
    records = [json.loads(line)
               for line in (logs / jsonls[0]).read_text().splitlines()]
    prof = [r for r in records if r.get("kind") == "profile"]
    assert prof and "round" in prof[0]["phases"]
    assert prof[0]["phases"]["round"]["total_s"] > 0


def test_round_stats_flag_writes_diagnostics(tmp_path):
    run_cli(tmp_path, ["-n", "6", "-m", "0.0", "--round-stats"], epochs=2)
    logs = tmp_path / "logs"
    jsonls = [f for f in os.listdir(logs) if f.endswith(".jsonl")]
    records = [json.loads(line)
               for line in (logs / jsonls[0]).read_text().splitlines()]
    rounds = [r for r in records if r.get("kind") == "round"]
    assert rounds and "grad_norm_mean" in rounds[0]


def test_distance_impl_and_scoring_flags(tmp_path):
    _, result = run_cli(tmp_path, ["-n", "10", "-m", "0.1", "-d", "Krum",
                                   "--distance-impl", "xla",
                                   "--krum-scoring-method", "topk"],
                        epochs=3)
    assert result["accuracies"][-1] > 0.0


def test_geomed_flags(tmp_path):
    _, result = run_cli(tmp_path, ["-n", "8", "-m", "0.25",
                                   "-d", "GeoMedian",
                                   "--geomed-iters", "3",
                                   "--geomed-eps", "1e-4"],
                        epochs=2)
    assert result["accuracies"][-1] > 0.0


def test_augment_flag_parses(tmp_path):
    _, result = run_cli(tmp_path, ["-n", "4", "-m", "0.0",
                                   "--augment", "off"], epochs=2)
    assert len(result["accuracies"]) >= 1


def test_invalid_choices_error():
    with pytest.raises(SystemExit):
        cli.build_parser().parse_args(["-d", "NotADefense"])
    with pytest.raises(SystemExit):
        cli.build_parser().parse_args(["-s", "NotADataset"])


def test_bulyan_guard_via_cli(tmp_path):
    with pytest.raises(ValueError, match="Bulyan requires"):
        run_cli(tmp_path, ["-n", "10", "-m", "0.24", "-d", "Bulyan"],
                epochs=2)


def test_attack_backdoor_requires_trigger():
    with pytest.raises(SystemExit):
        cli.build_parser()  # parser itself fine
        cli.main(["--attack", "backdoor", "-s", "SYNTH_MNIST", "-e", "1"])


def test_model_override_flag(tmp_path):
    _, result = run_cli(tmp_path, ["-n", "6", "-m", "0.0",
                                   "--model", "mnist_cnn"], epochs=2)
    assert len(result["accuracies"]) >= 1


def test_telemetry_flag_and_report_subcommand(tmp_path, capsys):
    """--telemetry writes schema-valid defense/attack/selection_hist
    events; the report subcommand reads them back."""
    from attacking_federate_learning_tpu.utils.metrics import validate_event

    run_cli(tmp_path, ["-n", "9", "-m", "0.22", "-d", "Krum",
                       "--telemetry"], epochs=4)
    logs = tmp_path / "logs"
    jsonl = [f for f in os.listdir(logs) if f.endswith(".jsonl")][0]
    path = str(logs / jsonl)
    records = [json.loads(line)
               for line in open(path).read().splitlines()]
    for r in records:
        validate_event(r)
    defense = [r for r in records if r["kind"] == "defense"]
    assert len(defense) == 4
    assert all("selection_mask" in r and "client_norms" in r
               for r in defense)
    assert [r for r in records if r["kind"] == "selection_hist"]
    capsys.readouterr()
    from attacking_federate_learning_tpu import cli as cli_mod
    assert cli_mod.main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "selection concentration" in out


def test_crash_still_writes_csv(tmp_path):
    """RunLogger is context-managed in cli.main: a run that raises
    after the logger opens still exits cleanly through __exit__ (here:
    the Bulyan n >= 4f+3 guard), leaving the JSONL artifact behind."""
    with pytest.raises(ValueError, match="Bulyan requires"):
        run_cli(tmp_path, ["-n", "10", "-m", "0.24", "-d", "Bulyan"],
                epochs=2)
    logs = tmp_path / "logs"
    assert [f for f in os.listdir(logs) if f.endswith(".jsonl")]
