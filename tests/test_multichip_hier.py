"""SPMD hierarchical tier-1 on the 8-virtual-device mesh (ISSUE 12).

Acceptance contract: with a multi-device mesh ``clients`` axis the
hierarchical round runs as one shard_map program (each device scans
its own megabatches, tier-2 reads one explicit estimate all_gather)
and reproduces the sequential scan path inside the measured ulp band —
for every tier-1 defense, both placements (concentrated exercises the
group-padding schedule), masked (faulted) and weighted (async-style)
kernel variants, and telemetry; a shard count not divisible by the
clients axis is rejected loudly (engine, schedule and campaign
pre-check agreeing on the message); the compiled per-device program
holds no full (n, d)/(S, m, d) tensor and its collective traffic is
the O(S·d) gather; and a SIGTERM-preempted sharded run resumes
bit-for-bit (same harness as test_hierarchy.py's lifecycle test).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses.kernels import (
    TIER2_DEFENSES, bulyan, krum, trimmed_mean
)
from attacking_federate_learning_tpu.defenses.median import median
from attacking_federate_learning_tpu.ops.federated import (
    make_placement, spmd_schedule, tier1_assumed, tier2_assumed,
    two_tier_aggregate
)
from attacking_federate_learning_tpu.parallel.mesh import make_plan
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
from attacking_federate_learning_tpu.utils.metrics import RunLogger

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 (virtual) devices")

_DS = {}


def _dataset(name=C.SYNTH_MNIST):
    if name not in _DS:
        _DS[name] = load_dataset(name, seed=0, synth_train=256,
                                 synth_test=64)
    return _DS[name]


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 32)
    kw.setdefault("mal_prop", 0.25)
    kw.setdefault("batch_size", 8)
    kw.setdefault("epochs", 2)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("aggregation", "hierarchical")
    kw.setdefault("megabatch", 4)
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    return ExperimentConfig(**kw)


def _run(tmp_path, shardings, rounds=2, **kw):
    cfg = _cfg(tmp_path, **kw)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(cfg.num_std),
                              dataset=_dataset(), shardings=shardings)
    for t in range(rounds):
        exp.run_round(t)
    return exp, np.asarray(exp.state.weights)


# ---------------------------------------------------------------------------
# schedule invariants (pure host — no devices needed)

@pytest.mark.parametrize("mal_placement", ["spread", "concentrated"])
@pytest.mark.parametrize("n,f,m,parts", [
    (32, 8, 4, 8), (32, 8, 4, 4), (64, 15, 4, 8), (48, 5, 4, 6),
])
def test_spmd_schedule_invariants(n, f, m, parts, mal_placement):
    """Every megabatch is scheduled exactly once in gathered order,
    padding is bounded by < parts duplicate rows per group, and the
    per-group grids deal device-contiguous slices of the placement."""
    pl = make_placement(n, f, m, mal_placement)
    sched = spmd_schedule(pl, parts)
    S = pl.num_shards
    assert sorted(np.unique(sched.select)) == sorted(sched.select)
    assert sched.padded_shards >= S
    assert sched.padded_shards < S + parts * len(pl.groups)
    # Reconstruct the device-major gathered order and check select
    # lands every true megabatch on a row holding ITS client ids.
    k_per = [g.shape[0] // parts for g in sched.grids]
    gathered = []
    for q in range(parts):
        for gi, grid in enumerate(sched.grids):
            k = k_per[gi]
            gathered.extend(grid[q * k:(q + 1) * k].tolist())
    for sid in range(S):
        assert gathered[sched.select[sid]] == pl.grid[sid].tolist()
    # Static counts match the placement groups 1:1.
    assert sched.counts == tuple(c for c, _ in pl.groups)


def test_spmd_schedule_rejects_indivisible_shard_count():
    """S % clients axis != 0 is a loud error naming the knobs — never
    silent replication (ISSUE 12 satellite)."""
    pl = make_placement(24, 5, 4, "spread")        # S = 6
    with pytest.raises(ValueError, match="--megabatch"):
        spmd_schedule(pl, 8)
    with pytest.raises(ValueError, match="mesh clients"):
        spmd_schedule(pl, 4)
    # Divisible counts pass whatever the group layout.
    for parts in (1, 2, 3, 6):
        assert spmd_schedule(pl, parts).parts == parts


@needs_8
def test_engine_rejects_indivisible_shard_count_loudly(tmp_path):
    """The engine init (and the campaign pre-check, via the same
    function) rejects mesh ⊕ hierarchical when S is not divisible by
    the clients axis — message names the flags, cells become skips."""
    from attacking_federate_learning_tpu.campaigns.spec import (
        composition_reject_reason
    )

    with pytest.raises(ValueError, match="--mesh-shape"):
        FederatedExperiment(
            _cfg(tmp_path, users_count=24, megabatch=4,
                 mesh_shape=(8, 1)),
            attacker=DriftAttack(1.5), dataset=_dataset())
    overrides = dict(
        dataset=C.SYNTH_MNIST, users_count=24, mal_prop=0.25,
        batch_size=8, epochs=2, aggregation="hierarchical",
        megabatch=4, mesh_shape=[8, 1], synth_train=256, synth_test=64)
    reason = composition_reject_reason(overrides)
    assert reason is not None and "--megabatch" in reason
    assert "clients axis=8" in reason
    # The same cell on a compatible mesh pre-validates clean.
    overrides["mesh_shape"] = [2, 1]
    assert composition_reject_reason(overrides) is None


def test_config_validates_mesh_shape_and_normalizes():
    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, mesh_shape=[4, 2])
    assert cfg.mesh_shape == (4, 2)                 # list -> tuple
    for bad in ((0, 1), (4,), (2, 1, 1), ("4", "2")):
        with pytest.raises(ValueError, match="mesh_shape"):
            ExperimentConfig(dataset=C.SYNTH_MNIST, mesh_shape=bad)


# ---------------------------------------------------------------------------
# engine parity: sharded == unsharded per defense / placement / mesh

_T2 = {"Krum": "Krum", "TrimmedMean": "TrimmedMean",
       "Median": "Median", "Bulyan": "TrimmedMean"}


@needs_8
@pytest.mark.parametrize("defense", sorted(_T2))
def test_spmd_round_matches_scan_per_defense(tmp_path, defense):
    kw = dict(defense=defense, tier2_defense=_T2[defense])
    if defense == "Bulyan":
        kw.update(users_count=64, megabatch=8, mal_prop=0.125)
    exp_ref, w_ref = _run(tmp_path, None, **kw)
    exp_spmd, w_spmd = _run(tmp_path, make_plan((8, 1)), **kw)
    assert exp_spmd._hier_spmd and not exp_ref._hier_spmd
    np.testing.assert_allclose(w_spmd, w_ref, atol=2e-5, rtol=1e-5)


@needs_8
@pytest.mark.parametrize("mesh_shape", [(4, 2), (2, 4)])
def test_spmd_round_matches_scan_across_mesh_shapes(tmp_path,
                                                    mesh_shape):
    """Model-axis sharding composes: the SPMD client_map replicates
    over the model axis, the server update stays model-sharded."""
    exp_ref, w_ref = _run(tmp_path, None)
    _, w_spmd = _run(tmp_path, make_plan(mesh_shape))
    np.testing.assert_allclose(w_spmd, w_ref, atol=2e-5, rtol=1e-5)


@needs_8
def test_spmd_round_matches_scan_concentrated_padding(tmp_path):
    """Concentrated placement leaves uneven groups (2 full + 6 empty
    over a 4-way axis): the padded schedule must not change a bit."""
    kw = dict(mal_placement="concentrated")
    _, w_ref = _run(tmp_path, None, **kw)
    exp, w_spmd = _run(tmp_path, make_plan((4, 2)), **kw)
    sched = spmd_schedule(exp._placement, 4)
    assert sched.padded_shards > exp._placement.num_shards  # real padding
    np.testing.assert_allclose(w_spmd, w_ref, atol=2e-5, rtol=1e-5)


@needs_8
def test_spmd_telemetry_matches_scan(tmp_path):
    """The stacked per-shard diagnostics and tier-2 selection record
    ride the same gather+reorder as the estimates — telemetry under
    SPMD is the scan path's telemetry, leaf for leaf."""
    kw = dict(telemetry=True)
    exp_ref, w_ref = _run(tmp_path, None, **kw)
    exp_spmd, w_spmd = _run(tmp_path, make_plan((8, 1)), **kw)
    np.testing.assert_allclose(w_spmd, w_ref, atol=2e-5, rtol=1e-5)
    ref_t, spmd_t = (exp_ref.last_round_telemetry,
                     exp_spmd.last_round_telemetry)
    assert sorted(ref_t) == sorted(spmd_t)
    for k in ref_t:
        np.testing.assert_allclose(np.asarray(spmd_t[k]),
                                   np.asarray(ref_t[k]),
                                   atol=1e-5, rtol=1e-5,
                                   err_msg=f"telemetry leaf {k}")


# ---------------------------------------------------------------------------
# kernel-level parity: masked (faulted) and weighted (async-style)

_T1 = {"Krum": krum, "TrimmedMean": trimmed_mean, "Bulyan": bulyan,
       "Median": median}


@needs_8
@pytest.mark.parametrize("name", sorted(_T1))
@pytest.mark.parametrize("variant", ["masked", "weighted"])
def test_two_tier_spmd_masked_weighted_parity(name, variant):
    """two_tier_aggregate under the SPMD plan == the sequential path,
    with the quarantine mask (faulted rows) and the staleness-weight
    seam threaded through the per-shard tier-1 kernels."""
    n, m, f = 32, 8, 3
    pl = make_placement(n, f, m, "spread")
    f1 = tier1_assumed(f, pl.num_shards)
    f2 = max(tier2_assumed(f, m), 1)
    rng = np.random.default_rng(11)
    G = jnp.asarray(rng.standard_normal((n, 40)).astype(np.float32))
    mask = jnp.asarray(rng.random(n) > 0.25)
    weights = (jnp.asarray((1.0 / np.sqrt(
        1.0 + rng.integers(0, 3, n))).astype(np.float32))
        if variant == "weighted" else None)
    t1, t2 = _T1[name], TIER2_DEFENSES[_T2[name]]
    plan = make_plan((4, 2))

    ref = two_tier_aggregate(G, pl, t1, t2, f1, f2, mask=mask,
                             weights=weights)
    got = two_tier_aggregate(G, pl, t1, t2, f1, f2, mask=mask,
                             weights=weights, plan=plan)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=5e-7, rtol=1e-6)


def test_two_tier_weights_require_mask():
    pl = make_placement(16, 2, 4, "spread")
    G = jnp.zeros((16, 8), jnp.float32)
    with pytest.raises(ValueError, match="weights= requires mask="):
        two_tier_aggregate(G, pl, krum, TIER2_DEFENSES["Krum"], 1, 1,
                           weights=jnp.ones(16))


# ---------------------------------------------------------------------------
# structural facts: collectives + placement invariants under sharding

@needs_8
def test_spmd_hlo_truly_sharded_and_collective_pinned(tmp_path):
    """The compiled per-device hier round holds no full (n, d) /
    (S, m, d) / (n, n) tensor, and its only collective is the estimate
    all_gather at exactly S*d*4 bytes (uniform spread groups, 1-way
    model axis)."""
    from attacking_federate_learning_tpu.utils.costs import (
        collective_hlo_bytes, compiled_cost_facts
    )

    cfg = _cfg(tmp_path, users_count=64, megabatch=4)   # S=16, f=16
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5),
                              dataset=_dataset(),
                              shardings=make_plan((8, 1)))
    compiled = exp._fused_round.lower(
        exp.state, jnp.asarray(0, jnp.int32), None).compile()
    text = compiled.as_text()
    d, S = exp.flat.dim, 16
    for shape in (f"f32[64,{d}]", f"bf16[64,{d}]", f"f32[16,4,{d}]",
                  "f32[64,64]"):
        assert shape not in text, f"{shape} rematerialized"
    facts = compiled_cost_facts(compiled)
    assert facts["collective_bytes"] == S * d * 4
    per_op = collective_hlo_bytes(text)["per_op"]
    assert set(per_op) == {"all-gather"}


@needs_8
def test_one_device_clients_axis_keeps_scan_path(tmp_path):
    """A (1, 1) mesh must route through the sequential scan: no SPMD
    flag, no collective in the compiled program, and cost facts equal
    to the no-mesh scan path exactly (the shardproof (a) leg)."""
    from attacking_federate_learning_tpu.utils.costs import (
        compiled_cost_facts
    )

    def facts(shardings):
        exp = FederatedExperiment(
            _cfg(tmp_path), attacker=DriftAttack(1.5),
            dataset=_dataset(), shardings=shardings)
        return exp, compiled_cost_facts(exp._fused_round.lower(
            exp.state, jnp.asarray(0, jnp.int32), None).compile())

    plan1 = make_plan((1, 1), devices=jax.devices()[:1])
    exp1, f1 = facts(plan1)
    exp0, f0 = facts(None)
    assert not exp1._hier_spmd
    assert f1["collective_bytes"] == 0
    for k in ("flops", "bytes_accessed", "argument_bytes",
              "output_bytes", "temp_bytes"):
        assert f1[k] == f0[k], k


def test_collective_hlo_bytes_parser():
    from attacking_federate_learning_tpu.utils.costs import (
        collective_hlo_bytes
    )

    text = """
  %ag = f32[16,100]{1,0} all-gather(f32[2,100]{1,0} %x), dimensions={0}
  %ar = bf16[8]{0} all-reduce(bf16[8]{0} %y), to_apply=%sum
  %cp.1 = f32[4,4]{1,0} collective-permute-start(f32[4,4]{1,0} %z)
  %done = f32[4,4]{1,0} collective-permute-done(%cp.1)
  %plain = f32[9,9]{1,0} add(f32[9,9]{1,0} %a, f32[9,9]{1,0} %b)
"""
    out = collective_hlo_bytes(text)
    assert out["per_op"]["all-gather"] == 16 * 100 * 4
    assert out["per_op"]["all-reduce"] == 8 * 2
    assert out["per_op"]["collective-permute"] == 4 * 4 * 4
    assert out["total"] == sum(out["per_op"].values())
    assert collective_hlo_bytes("%r = f32[4] add(%a, %b)")["total"] == 0


# ---------------------------------------------------------------------------
# campaign surface: mesh knobs stamped, invalid meshes become skips

def test_campaign_cells_stamp_mesh_knobs_and_skip_bad_mesh():
    from attacking_federate_learning_tpu.campaigns.spec import (
        CampaignSpec
    )

    spec = CampaignSpec(
        name="spmd",
        base=dict(dataset=C.SYNTH_MNIST, users_count=32, mal_prop=0.25,
                  batch_size=8, epochs=2, aggregation="hierarchical",
                  megabatch=4, synth_train=256, synth_test=64),
        axes={"mesh_shape": [[2, 1], [8, 1], [5, 1]]})
    cells = spec.expand()
    assert [c.skip is None for c in cells] == [True, True, False]
    assert "--megabatch" in cells[2].skip        # S=8 % 5 != 0
    for c in cells:
        row = c.row()
        assert row["megabatch"] == 4
        assert row["mal_placement"] == "spread"
        assert isinstance(row["mesh_shape"], list)
    assert cells[1].row()["mesh_shape"] == [8, 1]
    assert json.dumps([c.row() for c in cells])  # JSONL-stable


# ---------------------------------------------------------------------------
# lifecycle: SIGTERM preempt -> resume bit-for-bit on a sharded mesh

@needs_8
def test_spmd_preempt_resume_bit_for_bit(tmp_path):
    """Same harness as test_hierarchy.py's lifecycle test, on the
    (8, 1) mesh: a gracefully preempted SPMD hierarchical run resumes
    to final weights bit-for-bit equal to the uninterrupted run."""
    from attacking_federate_learning_tpu.utils.lifecycle import (
        GracefulShutdown, Preempted, RunJournal
    )

    ds = _dataset()
    kill_round = 3

    def cfg_for(run_dir):
        return _cfg(tmp_path, defense="Krum", epochs=6, test_step=3,
                    checkpoint_every=2, mesh_shape=(8, 1),
                    run_dir=str(tmp_path / run_dir))

    cfg_ref = cfg_for("runs_ref")
    full = FederatedExperiment(cfg_ref, attacker=DriftAttack(1.0),
                               dataset=ds)
    assert full._hier_spmd
    with RunLogger(cfg_ref, None, cfg_ref.log_dir,
                   jsonl_name="spmd_full") as logger:
        full.run(logger, checkpointer=Checkpointer(cfg_ref))
    w_full = np.array(full.state.weights, copy=True)
    v_full = np.array(full.state.velocity, copy=True)

    cfg = cfg_for("runs_sup")
    ck = Checkpointer(cfg)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir,
                   jsonl_name="spmd_sup") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "spmd"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    resumed = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
    state, _extra = ck.resume(ck.latest(), with_extra=True)
    resumed.state = state
    with RunLogger(cfg, None, cfg.log_dir,
                   jsonl_name="spmd_sup") as logger:
        resumed.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "spmd"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    np.testing.assert_array_equal(np.asarray(resumed.state.weights),
                                  w_full)
    np.testing.assert_array_equal(np.asarray(resumed.state.velocity),
                                  v_full)
    assert RunJournal(cfg.run_dir, "spmd").verify(
        epochs=6, test_step=3) == []
    with open(os.path.join(cfg.log_dir, "spmd_sup.jsonl")) as f:
        events = [json.loads(line) for line in f]
    evals = [e["round"] for e in events if e["kind"] == "eval"]
    assert evals == sorted(set(evals))
