"""Train-time augmentation (reference data_sets.py:157-166 parity).

The reference augments CIFAR100 training batches with reflect-pad 4 +
RandomCrop(32) + RandomHorizontalFlip via torchvision; ours is a jittable
per-image op keyed from (seed, round).  Correctness is checked exactly: every
augmented image must BE one of the 2*(2p+1)^2 legal crop/flip views of the
reflect-padded original.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.data.augment import (
    reflect_crop_flip, round_augment_key
)


def _legal_views(img, pad):
    """All crop/flip views torchvision could produce for this image."""
    c, h, w = img.shape
    padded = np.pad(img, ((0, 0), (pad, pad), (pad, pad)), mode="reflect")
    views = []
    for oy in range(2 * pad + 1):
        for ox in range(2 * pad + 1):
            crop = padded[:, oy:oy + h, ox:ox + w]
            views.append(crop)
            views.append(crop[..., ::-1])
    return views


def test_every_output_is_a_legal_crop_flip_view():
    rng = np.random.default_rng(0)
    imgs = rng.standard_normal((6, 3, 8, 8)).astype(np.float32)
    out = np.asarray(reflect_crop_flip(jnp.asarray(imgs),
                                       jax.random.key(3), pad=2))
    for i in range(len(imgs)):
        views = _legal_views(imgs[i], pad=2)
        assert any(np.array_equal(out[i], v) for v in views), i


def test_deterministic_per_key_and_varies_per_round():
    rng = np.random.default_rng(1)
    xs = jnp.asarray(rng.standard_normal((4, 5, 3, 32, 32))
                     .astype(np.float32))
    k0 = round_augment_key(0, 7)
    a = reflect_crop_flip(xs, k0)
    b = reflect_crop_flip(xs, round_augment_key(0, 7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = reflect_crop_flip(xs, round_augment_key(0, 8))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_leading_axes_and_jit_traced_round():
    rng = np.random.default_rng(2)
    xs = jnp.asarray(rng.standard_normal((2, 3, 3, 8, 8)).astype(np.float32))

    @jax.jit
    def f(x, t):
        return reflect_crop_flip(x, round_augment_key(0, t), pad=2)

    out = f(xs, jnp.asarray(3, jnp.int32))
    assert out.shape == xs.shape
    # distinct images draw distinct offsets (overwhelmingly likely)
    flat_in = np.asarray(xs).reshape(-1, 3, 8, 8)
    flat_out = np.asarray(out).reshape(-1, 3, 8, 8)
    assert not all(np.array_equal(a, b)
                   for a, b in zip(flat_in, flat_out))


def test_engine_runs_augmented_round_and_differs():
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks.base import NoAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    def weights_after(data_augment):
        cfg = ExperimentConfig(dataset=C.SYNTH_CIFAR10, users_count=4,
                               mal_prop=0.0, batch_size=8, epochs=1,
                               defense="NoDefense",
                               data_augment=data_augment,
                               synth_train=256, synth_test=64)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=256,
                          synth_test=64)
        exp = FederatedExperiment(cfg, attacker=NoAttack(), dataset=ds)
        exp.run_round(0)
        return np.asarray(exp.state.weights)

    w_aug = weights_after(True)
    w_plain = weights_after(False)
    assert w_aug.shape == w_plain.shape
    assert not np.array_equal(w_aug, w_plain)  # augmentation reached training


def test_wrn_cifar100_smoke_round_with_augmentation():
    """A full WRN-40-4 training round on the CIFAR100 pipeline, with the
    reference's augmentation on by default (data_augment=None -> CIFAR100
    rule).  The reference never exposes this model from its CLI
    (reference main.py:114); we train it."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks.base import NoAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.CIFAR100, users_count=2, mal_prop=0.0,
                           batch_size=2, epochs=1, defense="NoDefense",
                           synth_train=64, synth_test=16)
    ds = load_dataset(cfg.dataset, "data", seed=0, synth_train=64,
                      synth_test=16)
    exp = FederatedExperiment(cfg, attacker=NoAttack(), dataset=ds)
    assert exp._augment  # auto rule: CIFAR100 augments (reference parity)
    w0 = np.asarray(exp.state.weights)
    exp.run_round(0)
    w1 = np.asarray(exp.state.weights)
    assert not np.array_equal(w0, w1)
    assert np.all(np.isfinite(w1))


def test_augment_rejects_flat_data():
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=4,
                           mal_prop=0.0, batch_size=8, epochs=1,
                           data_augment=True,
                           synth_train=128, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=128, synth_test=64)
    flat = ds._replace(train_x=ds.train_x.reshape(len(ds.train_y), -1))
    with pytest.raises(ValueError, match="data_augment"):
        FederatedExperiment(cfg, dataset=flat)
