"""Grid runner: incremental summary, guard skipping, cell structure."""

import json

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.grid import run_grid


def test_grid_cells_and_guard_skip(tmp_path):
    base = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=10,
                            mal_prop=0.24, batch_size=16, epochs=3,
                            synth_train=256, synth_test=64,
                            log_dir=str(tmp_path))
    out_path = tmp_path / "summary.jsonl"
    results = run_grid(base, defenses=["NoDefense", "Bulyan"],
                       attacks=["none", "alie"], out_path=str(out_path))
    assert len(results) == 4
    # Bulyan with n=10, f=2 violates n >= 4f+3 -> recorded skip, not crash.
    skipped = [r for r in results if "skipped" in r]
    assert {(r["defense"], r["attack"]) for r in skipped} == {
        ("Bulyan", "alie")}
    ran = [r for r in results if "final_accuracy" in r]
    assert all(0.0 <= r["final_accuracy"] <= 100.0 for r in ran)
    # Every cell (ran AND skipped) carries its config-hash run_id — the
    # join key against the cross-run registry (utils/registry.py).
    assert all("run_id" in r for r in results)
    assert len({r["run_id"] for r in results}) == 4   # distinct configs
    # Summary written incrementally, one JSON line per cell.
    lines = [json.loads(x) for x in out_path.read_text().splitlines()]
    assert len(lines) == 4
    assert all(x["run_id"] for x in lines)


def test_grid_none_attack_sets_zero_malicious(tmp_path):
    base = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                            mal_prop=0.25, batch_size=16, epochs=2,
                            synth_train=128, synth_test=32,
                            log_dir=str(tmp_path))
    results = run_grid(base, defenses=["Krum"], attacks=["none"],
                       out_path=str(tmp_path / "s.jsonl"))
    assert results[0]["final_accuracy"] >= 0.0
