"""The distance-engine dispatch layer (VERDICT round-1 items #3/#4).

Every selectable ``distance_impl`` — xla, host (CPU BLAS, defenses/host.py),
pallas (interpret off-TPU), ring / allgather (blockwise shard_map kernels,
parallel/distances.py) — must produce the same aggregate as the oracle, both
through the kernel API and wired through the engine's config knob.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.defenses import host as H
from attacking_federate_learning_tpu.defenses import kernels as K
from attacking_federate_learning_tpu.defenses import oracle as O


CASES = [
    # (n, d, f) — n divisible by 8 where the blockwise kernels need a mesh
    (16, 40, 3),
    (24, 104, 5),
    (40, 33, 9),
]


def grads_for(n, d, seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, d)).astype(np.float32)


# --------------------------------------------------------------------------
# host BLAS kernels (the CPU-backend production path) vs oracle
# --------------------------------------------------------------------------
@pytest.mark.parametrize("n,d,f", CASES)
def test_host_krum_matches_oracle(n, d, f):
    G = grads_for(n, d, seed=n + d + f)
    want = O.np_krum(G.astype(np.float64), n, f)
    got = H.host_krum(G, n, f)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


@pytest.mark.parametrize("n,d,f", CASES)
def test_host_bulyan_matches_oracle(n, d, f):
    if n < 4 * f + 3:
        pytest.skip("bulyan guard")
    G = grads_for(n, d, seed=n * 7 + f)
    want = O.np_bulyan(G.astype(np.float64), n, f)
    got = H.host_bulyan(G, n, f)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_host_krum_adversarial_magnitudes_and_ties():
    # Adversarial magnitudes (huge malicious rows) and exact duplicate rows
    # (ties) — the regimes where a complement/subtraction path would lose
    # precision and where tie-breaks must resolve to the lowest index.
    rng = np.random.default_rng(0)
    G = rng.standard_normal((12, 30)).astype(np.float32)
    G[0] = 1e6          # adversarial magnitude
    G[5] = G[3]         # exact tie pair
    for f in (2, 3):
        want = O.np_krum(G.astype(np.float64), 12, f)
        got = H.host_krum(G, 12, f)
        np.testing.assert_allclose(got, want, atol=1e-3, rtol=1e-4)
        xla = np.asarray(K.krum(jnp.asarray(G), 12, f))
        np.testing.assert_allclose(xla, want, atol=1e-3, rtol=1e-4)


# --------------------------------------------------------------------------
# kernel API dispatch
# --------------------------------------------------------------------------
@pytest.mark.parametrize("impl", ["xla", "host", "auto", "pallas"])
def test_krum_kernel_dispatch(impl):
    n, d, f = 24, 104, 5
    G = grads_for(n, d, seed=1)
    want = O.np_krum(G.astype(np.float64), n, f)
    got = np.asarray(K.krum(jnp.asarray(G), n, f, distance_impl=impl))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


@pytest.mark.parametrize("impl", ["xla", "host", "auto"])
def test_bulyan_kernel_dispatch(impl):
    n, d, f = 24, 40, 5
    G = grads_for(n, d, seed=2)
    want = O.np_bulyan(G.astype(np.float64), n, f)
    got = np.asarray(K.bulyan(jnp.asarray(G), n, f, distance_impl=impl))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_host_impl_inside_jit_uses_callback():
    # Static n/f closed over; traced G goes through pure_callback — slower,
    # but must stay correct (the engine only picks this when told to).
    n, d, f = 16, 40, 3
    G = grads_for(n, d, seed=3)
    fn = jax.jit(lambda g: K.krum(g, n, f, distance_impl="host"))
    want = O.np_krum(G.astype(np.float64), n, f)
    np.testing.assert_allclose(np.asarray(fn(jnp.asarray(G))), want,
                               atol=2e-4, rtol=1e-4)


def test_resolve_auto():
    # On this CPU test backend: eager calls resolve to host, traced to xla.
    assert K.resolve_distance_impl("auto", 10, np.zeros((4, 2))) == "host"
    assert K.resolve_distance_impl("xla", 10, None) == "xla"
    seen = {}

    def probe(g):
        seen["impl"] = K.resolve_distance_impl("auto", 10, g)
        return g.sum()

    jax.jit(probe)(jnp.zeros((4, 2)))
    assert seen["impl"] == "xla"


# --------------------------------------------------------------------------
# engine wiring: cfg.distance_impl reaches the defense, including the
# blockwise shard_map engines over the 8-virtual-device mesh
# --------------------------------------------------------------------------
def _one_round_weights(distance_impl, mesh_shape=None, defense="Krum",
                       distance_dtype="float32"):
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=16,
                           mal_prop=0.2, batch_size=16, epochs=2,
                           defense=defense, distance_impl=distance_impl,
                           distance_dtype=distance_dtype,
                           mesh_shape=mesh_shape,
                           synth_train=1024, synth_test=128)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=1024, synth_test=128)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    exp.run_round(0)
    exp.run_round(1)
    return np.asarray(exp.state.weights)


@pytest.mark.parametrize("impl,mesh", [
    ("xla", None),
    ("ring", (8, 1)),
    ("allgather", (8, 1)),
])
def test_engine_distance_impl_parity(impl, mesh):
    ref = _one_round_weights("auto")
    got = _one_round_weights(impl, mesh_shape=mesh)
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-5)


def test_engine_blockwise_requires_mesh():
    with pytest.raises(ValueError, match="needs a device mesh"):
        _one_round_weights("ring", mesh_shape=None)


def test_engine_bulyan_blockwise():
    ref = _one_round_weights("auto", defense="Bulyan")
    got = _one_round_weights("allgather", mesh_shape=(8, 1),
                             defense="Bulyan")
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-5)


def test_engine_blockwise_requires_divisible_cohort():
    with pytest.raises(ValueError, match="divisible"):
        from attacking_federate_learning_tpu import config as C
        from attacking_federate_learning_tpu.config import ExperimentConfig
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.data.datasets import (
            load_dataset
        )

        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=10,
                               mal_prop=0.2, batch_size=8, epochs=1,
                               defense="Krum", distance_impl="ring",
                               mesh_shape=(8, 1),
                               synth_train=256, synth_test=64)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=256,
                          synth_test=64)
        FederatedExperiment(cfg, dataset=ds)


def test_engine_ring_bf16_parity():
    """bf16 wire matrix through the ring engine matches the xla engine at
    bf16 tolerance (distances accumulate f32 in both)."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    def weights(impl, mesh):
        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=16,
                               mal_prop=0.2, batch_size=8, epochs=1,
                               defense="Krum", distance_impl=impl,
                               grad_dtype="bfloat16", mesh_shape=mesh,
                               synth_train=512, synth_test=64)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=512,
                          synth_test=64)
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
        exp.run_round(0)
        return np.asarray(exp.state.weights)

    np.testing.assert_allclose(weights("ring", (8, 1)),
                               weights("xla", None), atol=2e-5, rtol=1e-5)


# --------------------------------------------------------------------------
# distance_dtype='bfloat16': the bf16-Gram MXU mode (round-3 flag) — cast
# for the distance computation only, f32 accumulation + f32 norms
# --------------------------------------------------------------------------
def test_bf16_distances_close_to_f32():
    from attacking_federate_learning_tpu.ops.distances import (
        pairwise_distances
    )

    G = grads_for(32, 500, seed=5)
    want = np.asarray(pairwise_distances(jnp.asarray(G)))
    got = np.asarray(pairwise_distances(jnp.asarray(G, jnp.bfloat16)))
    assert got.dtype == np.float32  # accumulation/norms stay f32
    # bf16 multiplies: ~0.4% per-element relative error, averaged down by
    # the d-length accumulation.
    np.testing.assert_allclose(got, want, atol=0.05, rtol=2e-2)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_krum_select_bf16_agrees(impl):
    """On generic (non-tie) data the bf16-Gram selection matches f32 —
    eager and jitted — for both the XLA and pallas engines."""
    G = jnp.asarray(grads_for(24, 300, seed=9))
    want = int(K.krum_select(G, 24, 5))
    got = int(K.krum_select(G, 24, 5, distance_impl=impl,
                            distance_dtype="bfloat16"))
    assert got == want
    jit_sel = jax.jit(K.krum_select, static_argnums=(1, 2),
                      static_argnames=("distance_impl", "distance_dtype"))
    assert int(jit_sel(G, 24, 5, distance_impl=impl,
                       distance_dtype="bfloat16")) == want


def test_bulyan_bf16_close_to_f32():
    """On separated data (tight honest cluster, far malicious rows) the
    bf16-Gram selection picks the same set, so outputs match to bf16
    tolerance.  (On knife-edge iid data the discrete selection can
    legitimately differ between dtypes — that's inherent to any
    selection defense under a distance perturbation, not a bug.)"""
    rng = np.random.default_rng(11)
    base = rng.standard_normal(200).astype(np.float32)
    G = base + 0.05 * rng.standard_normal((31, 200)).astype(np.float32)
    G[:5] += 10.0  # malicious rows far from the honest cluster
    G = jnp.asarray(G)
    want = np.asarray(K.bulyan(G, 31, 5))
    got = np.asarray(K.bulyan(G, 31, 5, distance_dtype="bfloat16"))
    # Near-tied honest rows may swap a marginal selection between dtypes;
    # the bound is a fraction of the honest-cluster spread (0.05) — far
    # below the 10.0 malicious offset any contamination would show.
    np.testing.assert_allclose(got, want, atol=0.1, rtol=2e-2)
    assert float(np.max(np.abs(got - np.asarray(base)))) < 1.0


def test_engine_distance_dtype_bf16():
    """cfg.distance_dtype reaches the kernels through the engine wiring;
    the fused round runs and matches the f32 run closely (selection on
    well-separated synth gradients is dtype-robust)."""
    ref = _one_round_weights("xla")
    got = _one_round_weights("xla", distance_dtype="bfloat16")
    np.testing.assert_allclose(got, ref, atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("impl", ["allgather", "ring"])
def test_engine_distance_dtype_bf16_blockwise(impl):
    # ring regression: the scan carry must be f32 even for bf16 operands
    # (parallel/distances.py) — bf16 tiles never exist.
    ref = _one_round_weights(impl, mesh_shape=(8, 1))
    got = _one_round_weights(impl, mesh_shape=(8, 1),
                             distance_dtype="bfloat16")
    np.testing.assert_allclose(got, ref, atol=1e-3, rtol=1e-3)


def test_pallas_default_distance_dtype_stays_f32_for_bf16_wire():
    """grad_dtype=bfloat16 + distance_impl=pallas WITHOUT the flag must
    keep the pre-flag f32 distance math (change behavior only behind
    flags): pallas distances from a bf16 wire matrix equal those of its
    f32 upcast exactly."""
    from attacking_federate_learning_tpu.defenses.kernels import (
        _distances_for
    )

    G16 = jnp.asarray(grads_for(16, 128, seed=21), jnp.bfloat16)
    want = np.asarray(_distances_for(G16.astype(jnp.float32), "pallas"))
    got = np.asarray(_distances_for(G16, "pallas"))
    np.testing.assert_array_equal(got, want)


def test_distance_dtype_validation():
    from attacking_federate_learning_tpu.config import ExperimentConfig

    with pytest.raises(ValueError, match="distance_dtype"):
        ExperimentConfig(dataset="SYNTH_MNIST", users_count=8,
                         distance_dtype="float16")
