"""The distance-engine dispatch layer (VERDICT round-1 items #3/#4).

Every selectable ``distance_impl`` — xla, host (CPU BLAS, defenses/host.py),
pallas (interpret off-TPU), ring / allgather (blockwise shard_map kernels,
parallel/distances.py) — must produce the same aggregate as the oracle, both
through the kernel API and wired through the engine's config knob.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.defenses import host as H
from attacking_federate_learning_tpu.defenses import kernels as K
from attacking_federate_learning_tpu.defenses import oracle as O


CASES = [
    # (n, d, f) — n divisible by 8 where the blockwise kernels need a mesh
    (16, 40, 3),
    (24, 104, 5),
    (40, 33, 9),
]


def grads_for(n, d, seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, d)).astype(np.float32)


# --------------------------------------------------------------------------
# host BLAS kernels (the CPU-backend production path) vs oracle
# --------------------------------------------------------------------------
@pytest.mark.parametrize("n,d,f", CASES)
def test_host_krum_matches_oracle(n, d, f):
    G = grads_for(n, d, seed=n + d + f)
    want = O.np_krum(G.astype(np.float64), n, f)
    got = H.host_krum(G, n, f)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


@pytest.mark.parametrize("n,d,f", CASES)
def test_host_bulyan_matches_oracle(n, d, f):
    if n < 4 * f + 3:
        pytest.skip("bulyan guard")
    G = grads_for(n, d, seed=n * 7 + f)
    want = O.np_bulyan(G.astype(np.float64), n, f)
    got = H.host_bulyan(G, n, f)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_host_krum_adversarial_magnitudes_and_ties():
    # Adversarial magnitudes (huge malicious rows) and exact duplicate rows
    # (ties) — the regimes where a complement/subtraction path would lose
    # precision and where tie-breaks must resolve to the lowest index.
    rng = np.random.default_rng(0)
    G = rng.standard_normal((12, 30)).astype(np.float32)
    G[0] = 1e6          # adversarial magnitude
    G[5] = G[3]         # exact tie pair
    for f in (2, 3):
        want = O.np_krum(G.astype(np.float64), 12, f)
        got = H.host_krum(G, 12, f)
        np.testing.assert_allclose(got, want, atol=1e-3, rtol=1e-4)
        xla = np.asarray(K.krum(jnp.asarray(G), 12, f))
        np.testing.assert_allclose(xla, want, atol=1e-3, rtol=1e-4)


# --------------------------------------------------------------------------
# kernel API dispatch
# --------------------------------------------------------------------------
@pytest.mark.parametrize("impl", ["xla", "host", "auto", "pallas"])
def test_krum_kernel_dispatch(impl):
    n, d, f = 24, 104, 5
    G = grads_for(n, d, seed=1)
    want = O.np_krum(G.astype(np.float64), n, f)
    got = np.asarray(K.krum(jnp.asarray(G), n, f, distance_impl=impl))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


@pytest.mark.parametrize("impl", ["xla", "host", "auto"])
def test_bulyan_kernel_dispatch(impl):
    n, d, f = 24, 40, 5
    G = grads_for(n, d, seed=2)
    want = O.np_bulyan(G.astype(np.float64), n, f)
    got = np.asarray(K.bulyan(jnp.asarray(G), n, f, distance_impl=impl))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_host_impl_inside_jit_uses_callback():
    # Static n/f closed over; traced G goes through pure_callback — slower,
    # but must stay correct (the engine only picks this when told to).
    n, d, f = 16, 40, 3
    G = grads_for(n, d, seed=3)
    fn = jax.jit(lambda g: K.krum(g, n, f, distance_impl="host"))
    want = O.np_krum(G.astype(np.float64), n, f)
    np.testing.assert_allclose(np.asarray(fn(jnp.asarray(G))), want,
                               atol=2e-4, rtol=1e-4)


def test_resolve_auto():
    # On this CPU test backend: eager calls resolve to host, traced to xla.
    assert K.resolve_distance_impl("auto", 10, np.zeros((4, 2))) == "host"
    assert K.resolve_distance_impl("xla", 10, None) == "xla"
    seen = {}

    def probe(g):
        seen["impl"] = K.resolve_distance_impl("auto", 10, g)
        return g.sum()

    jax.jit(probe)(jnp.zeros((4, 2)))
    assert seen["impl"] == "xla"


# --------------------------------------------------------------------------
# engine wiring: cfg.distance_impl reaches the defense, including the
# blockwise shard_map engines over the 8-virtual-device mesh
# --------------------------------------------------------------------------
def _one_round_weights(distance_impl, mesh_shape=None, defense="Krum",
                       distance_dtype="float32"):
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=16,
                           mal_prop=0.2, batch_size=16, epochs=2,
                           defense=defense, distance_impl=distance_impl,
                           distance_dtype=distance_dtype,
                           mesh_shape=mesh_shape,
                           synth_train=1024, synth_test=128)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=1024, synth_test=128)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    exp.run_round(0)
    exp.run_round(1)
    return np.asarray(exp.state.weights)


@pytest.mark.parametrize("impl,mesh", [
    ("xla", None),
    ("ring", (8, 1)),
    ("allgather", (8, 1)),
])
def test_engine_distance_impl_parity(impl, mesh):
    ref = _one_round_weights("auto")
    got = _one_round_weights(impl, mesh_shape=mesh)
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-5)


def test_engine_blockwise_requires_mesh():
    with pytest.raises(ValueError, match="needs a device mesh"):
        _one_round_weights("ring", mesh_shape=None)


class _BulyanEngineProbe:
    """One engine, stepped round by round with its realized Bulyan
    selection observable (the telemetry seam's multi-hot mask) and the
    pre-defense gradient matrix recomputable on the host for the tie
    replay.  Telemetry does not perturb the trajectory (PR-1 pin)."""

    def __init__(self, distance_impl, mesh_shape=None):
        from attacking_federate_learning_tpu import config as C
        from attacking_federate_learning_tpu.attacks import DriftAttack
        from attacking_federate_learning_tpu.config import ExperimentConfig
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.data.datasets import (
            load_dataset
        )

        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=16,
                               mal_prop=0.2, batch_size=16, epochs=2,
                               defense="Bulyan",
                               distance_impl=distance_impl,
                               mesh_shape=mesh_shape, telemetry=True,
                               synth_train=1024, synth_test=128)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=1024,
                          synth_test=128)
        self.exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                       dataset=ds)

    def pre_defense_grads(self, t):
        exp = self.exp
        grads = exp._compute_grads_impl(exp.state, t)
        grads = exp.attacker.apply(grads, exp.m_mal,
                                   exp._ctx_for(exp.state, t))
        return np.asarray(grads, np.float64)

    def step(self, t):
        """Run round t; returns the frozen selection set."""
        self.exp.run_round(t)
        mask = np.asarray(
            self.exp.last_round_telemetry["defense_selection_mask"])
        return frozenset(np.flatnonzero(mask > 0).tolist())

    @property
    def weights(self):
        return np.asarray(self.exp.state.weights)


def _bulyan_selection_steps(G, n, f):
    """Host replay of the Bulyan selection loop with BOTH f32 distance
    formulations the engines use (direct difference vs Gram — the
    bench.py:adjudicate_f32_flip template): per selection step, the
    top-2 mid-score gap against the measured indeterminacy band
    (4x the |diff-form - Gram-form| spread on this very data, plus the
    analytic worst-case f32 summation term).  Scores sum in float64 so
    each formulation's own error is isolated.  Returns
    [(pick, runner_up, gap, band), ...] for the set_size steps."""
    G32 = np.asarray(G, np.float32)
    d_diff = np.sqrt(((G32[:, None, :] - G32[None, :, :]) ** 2)
                     .sum(-1, dtype=np.float32))
    sq = (G32 * G32).sum(1, dtype=np.float32)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (G32 @ G32.T)
    d_gram = np.sqrt(np.maximum(d2, 0.0, dtype=np.float32))
    eps32 = float(np.finfo(np.float32).eps)
    alive = np.ones(n, bool)
    steps = []
    for s in range(n - 2 * f):
        n_cur = n - s
        k = n_cur - f          # reference n-f scoring quirk, shrinking n
        mids, spreads, absmax = {}, [], 0.0
        for i in range(n):
            if not alive[i]:
                continue
            pair = []
            for D in (d_diff, d_gram):
                v = np.asarray([D[i, j] for j in range(n)
                                if j != i and alive[j]], np.float64)
                pair.append(float(np.sort(v)[:k].sum()))
            mids[i] = 0.5 * (pair[0] + pair[1])
            spreads.append(abs(pair[0] - pair[1]))
            absmax = max(absmax, abs(pair[0]), abs(pair[1]))
        order = sorted(mids, key=mids.__getitem__)
        gap = mids[order[1]] - mids[order[0]]
        band = 4.0 * max(spreads) + 0.5 * n_cur * eps32 * absmax
        steps.append((order[0], order[1], gap, band))
        alive[order[0]] = False
    return steps


def _adjudicate_trim_flips(G_ref, G_got, sel, f, w_ref, w_got, lr):
    """Adjudicate per-coordinate trimmed-mean keep-set flips (the
    second place two correct engines can legally diverge): the two
    engines' gradient matrices already differ at the ulp level (the
    mesh-sharded and single-device reductions order sums differently),
    and a coordinate whose trim boundary — the gap between the keep-th
    and (keep+1)-th smallest |deviation-from-median| — sits inside
    that measured perturbation band can legally keep DIFFERENT rows,
    moving the aggregate by up to the boundary pair's combined
    deviation over the keep count.  Same measured-band standard as
    bench.py:adjudicate_f32_flip.  Returns indices of coordinates
    whose weight difference is NOT attributable to a legal flip."""
    S = sorted(sel)
    rows_ref = G_ref[S]
    rows_got = G_got[S]
    f2 = 2 * f
    keep = len(S) - f2 - 1
    eps32 = float(np.finfo(np.float32).eps)
    med = np.median(rows_ref, axis=0)
    a = np.sort(np.abs(rows_ref - med), axis=0)
    gap = a[keep] - a[keep - 1]          # trim-boundary gap, per coord
    # Measured input indeterminacy (x16 safety, same spirit as the x4
    # on the measured score spread in adjudicate_f32_flip — the median
    # and every deviation shift with the perturbation).
    band = 16.0 * (np.abs(rows_ref - rows_got).max(axis=0)
                   + eps32 * np.abs(rows_ref).max(axis=0))
    dw = np.abs(w_ref.astype(np.float64) - w_got.astype(np.float64))
    strict = 2e-5 + 1e-5 * np.abs(w_ref)     # the summation-noise floor
    # One boundary swap changes the kept mean by at most the boundary
    # pair's combined |dev| / keep; the weight moves lr x that.
    envelope = lr * (a[keep] + a[keep - 1] + 2.0 * band) / keep + strict
    viol = dw > strict
    illegal = viol & ((gap > band) | (dw > envelope))
    return np.flatnonzero(illegal), int(viol.sum())


def test_engine_bulyan_blockwise():
    """Blockwise-allgather D vs the in-program xla D, wired through the
    engine under Bulyan.  Two correct f32 engines may legally disagree
    wherever a selection rests on a near-tie (ARCHITECTURE.md "Known
    local failures"; the ulp-band reality tests/test_native.py pins),
    and Bulyan selects twice: the shrinking-pool Krum selection, and
    the per-coordinate trimmed-mean keep set — on iid gaussian-ish
    gradients the trim boundary is near-tied on a sizable fraction of
    coordinates, so a blanket 2e-5 weight tolerance mis-adjudicates
    legal flips as kernel bugs.  Instead (bench.py:adjudicate_f32_flip
    is the template — measured indeterminacy bands, not guessed
    tolerances):

    1. the realized SELECTION SETS (telemetry masks) are compared per
       round; a set flip is legal only if the host replay of the
       selection (both f32 distance formulations, f64 score sums)
       shows a step whose top-2 score gap is inside its band;
    2. with identical selection sets, every coordinate whose weights
       differ beyond summation noise must sit on a trim boundary
       within the measured inter-engine perturbation band AND inside
       the single-swap envelope.

    A decisive-gap disagreement still fails either stage — that would
    be a wrong kernel, not a tie."""
    ref = _BulyanEngineProbe("auto")
    got = _BulyanEngineProbe("allgather", mesh_shape=(8, 1))
    n, f = 16, ref.exp.m_mal
    lr = ref.exp.cfg.learning_rate
    for t in range(2):
        G_ref = ref.pre_defense_grads(t)
        G_got = got.pre_defense_grads(t)
        sel_ref, sel_got = ref.step(t), got.step(t)
        if sel_ref != sel_got:
            steps = _bulyan_selection_steps(G_ref, n, f)
            tied = [(p, q, g, b) for p, q, g, b in steps if g <= b]
            assert tied, (
                f"round {t}: selection flip {sorted(sel_ref ^ sel_got)} "
                f"with every step's top-2 gap DECISIVE (no step inside "
                f"its indeterminacy band): {steps}")
            return     # states legally diverged; later rounds can't compare
        illegal, n_viol = _adjudicate_trim_flips(
            G_ref, G_got, sel_ref, f, ref.weights, got.weights, lr)
        assert illegal.size == 0, (
            f"round {t}: {illegal.size}/{n_viol} diverging coordinates "
            f"are NOT legal trim-boundary ties (first: "
            f"{illegal[:5].tolist()}) — decisive disagreement between "
            f"the distance engines")
        if n_viol:
            return     # legally diverged at the trim stage; stop comparing
    np.testing.assert_allclose(got.weights, ref.weights,
                               atol=2e-5, rtol=1e-5)


def test_engine_blockwise_requires_divisible_cohort():
    with pytest.raises(ValueError, match="divisible"):
        from attacking_federate_learning_tpu import config as C
        from attacking_federate_learning_tpu.config import ExperimentConfig
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.data.datasets import (
            load_dataset
        )

        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=10,
                               mal_prop=0.2, batch_size=8, epochs=1,
                               defense="Krum", distance_impl="ring",
                               mesh_shape=(8, 1),
                               synth_train=256, synth_test=64)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=256,
                          synth_test=64)
        FederatedExperiment(cfg, dataset=ds)


def test_engine_ring_bf16_parity():
    """bf16 wire matrix through the ring engine matches the xla engine at
    bf16 tolerance (distances accumulate f32 in both)."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    def weights(impl, mesh):
        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=16,
                               mal_prop=0.2, batch_size=8, epochs=1,
                               defense="Krum", distance_impl=impl,
                               grad_dtype="bfloat16", mesh_shape=mesh,
                               synth_train=512, synth_test=64)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=512,
                          synth_test=64)
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
        exp.run_round(0)
        return np.asarray(exp.state.weights)

    np.testing.assert_allclose(weights("ring", (8, 1)),
                               weights("xla", None), atol=2e-5, rtol=1e-5)


# --------------------------------------------------------------------------
# distance_dtype='bfloat16': the bf16-Gram MXU mode (round-3 flag) — cast
# for the distance computation only, f32 accumulation + f32 norms
# --------------------------------------------------------------------------
def test_bf16_distances_close_to_f32():
    from attacking_federate_learning_tpu.ops.distances import (
        pairwise_distances
    )

    G = grads_for(32, 500, seed=5)
    want = np.asarray(pairwise_distances(jnp.asarray(G)))
    got = np.asarray(pairwise_distances(jnp.asarray(G, jnp.bfloat16)))
    assert got.dtype == np.float32  # accumulation/norms stay f32
    # bf16 multiplies: ~0.4% per-element relative error, averaged down by
    # the d-length accumulation.
    np.testing.assert_allclose(got, want, atol=0.05, rtol=2e-2)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_krum_select_bf16_agrees(impl):
    """On generic (non-tie) data the bf16-Gram selection matches f32 —
    eager and jitted — for both the XLA and pallas engines."""
    G = jnp.asarray(grads_for(24, 300, seed=9))
    want = int(K.krum_select(G, 24, 5))
    got = int(K.krum_select(G, 24, 5, distance_impl=impl,
                            distance_dtype="bfloat16"))
    assert got == want
    jit_sel = jax.jit(K.krum_select, static_argnums=(1, 2),
                      static_argnames=("distance_impl", "distance_dtype"))
    assert int(jit_sel(G, 24, 5, distance_impl=impl,
                       distance_dtype="bfloat16")) == want


def test_bulyan_bf16_close_to_f32():
    """On separated data (tight honest cluster, far malicious rows) the
    bf16-Gram selection picks the same set, so outputs match to bf16
    tolerance.  (On knife-edge iid data the discrete selection can
    legitimately differ between dtypes — that's inherent to any
    selection defense under a distance perturbation, not a bug.)"""
    rng = np.random.default_rng(11)
    base = rng.standard_normal(200).astype(np.float32)
    G = base + 0.05 * rng.standard_normal((31, 200)).astype(np.float32)
    G[:5] += 10.0  # malicious rows far from the honest cluster
    G = jnp.asarray(G)
    want = np.asarray(K.bulyan(G, 31, 5))
    got = np.asarray(K.bulyan(G, 31, 5, distance_dtype="bfloat16"))
    # Near-tied honest rows may swap a marginal selection between dtypes;
    # the bound is a fraction of the honest-cluster spread (0.05) — far
    # below the 10.0 malicious offset any contamination would show.
    np.testing.assert_allclose(got, want, atol=0.1, rtol=2e-2)
    assert float(np.max(np.abs(got - np.asarray(base)))) < 1.0


def test_engine_distance_dtype_bf16():
    """cfg.distance_dtype reaches the kernels through the engine wiring;
    the fused round runs and matches the f32 run closely (selection on
    well-separated synth gradients is dtype-robust)."""
    ref = _one_round_weights("xla")
    got = _one_round_weights("xla", distance_dtype="bfloat16")
    np.testing.assert_allclose(got, ref, atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("impl", ["allgather", "ring"])
def test_engine_distance_dtype_bf16_blockwise(impl):
    # ring regression: the scan carry must be f32 even for bf16 operands
    # (parallel/distances.py) — bf16 tiles never exist.
    ref = _one_round_weights(impl, mesh_shape=(8, 1))
    got = _one_round_weights(impl, mesh_shape=(8, 1),
                             distance_dtype="bfloat16")
    np.testing.assert_allclose(got, ref, atol=1e-3, rtol=1e-3)


def test_pallas_default_distance_dtype_stays_f32_for_bf16_wire():
    """grad_dtype=bfloat16 + distance_impl=pallas WITHOUT the flag must
    keep the pre-flag f32 distance math (change behavior only behind
    flags): pallas distances from a bf16 wire matrix equal those of its
    f32 upcast exactly."""
    from attacking_federate_learning_tpu.defenses.kernels import (
        _distances_for
    )

    G16 = jnp.asarray(grads_for(16, 128, seed=21), jnp.bfloat16)
    want = np.asarray(_distances_for(G16.astype(jnp.float32), "pallas"))
    got = np.asarray(_distances_for(G16, "pallas"))
    np.testing.assert_array_equal(got, want)


def test_distance_dtype_validation():
    from attacking_federate_learning_tpu.config import ExperimentConfig

    with pytest.raises(ValueError, match="distance_dtype"):
        ExperimentConfig(dataset="SYNTH_MNIST", users_count=8,
                         distance_dtype="float16")


# --------------------------------------------------------------------------
# ISSUE 6 satellites: diagonal zeroing + pallas norm hoist, pinned via
# static cost facts (utils/costs.py — deterministic per (HLO, XLA,
# platform), no stopwatch)
# --------------------------------------------------------------------------
def _facts(lowered):
    from attacking_federate_learning_tpu.utils.costs import (
        compiled_cost_facts
    )
    return compiled_cost_facts(lowered.compile())


def test_zero_diagonal_matches_eye_formula_bitwise():
    """The iota-select diagonal zeroing computes exactly what the old
    ``D * (1 - eye(n))`` spelling computed: off-diagonal D*1.0 is D, the
    diagonal is exactly zero either way."""
    from attacking_federate_learning_tpu.ops.distances import (
        pairwise_distances, pairwise_sq_distances
    )

    G = jnp.asarray(grads_for(64, 32, seed=3))
    D_eye = jnp.sqrt(pairwise_sq_distances(G)) * (
        1.0 - jnp.eye(64, dtype=jnp.float32))
    np.testing.assert_array_equal(np.asarray(pairwise_distances(G)),
                                  np.asarray(D_eye))


def test_zero_diagonal_costs_no_more_than_eye():
    """The eye spelling pays an extra n^2-shaped construct+multiply on
    the hot path (~420 MB f32 materialized at n=10,240 before fusion
    gets a say); the iota select must be strictly cheaper in FLOPs and
    never worse in bytes/temp on the same shape."""
    from attacking_federate_learning_tpu.ops.distances import (
        pairwise_distances, pairwise_sq_distances
    )

    n, d = 512, 1024
    sds = jax.ShapeDtypeStruct((n, d), jnp.float32)

    def eye_style(G):
        D = jnp.sqrt(pairwise_sq_distances(G))
        return D * (1.0 - jnp.eye(n, dtype=D.dtype))

    new = _facts(jax.jit(pairwise_distances).lower(sds))
    old = _facts(jax.jit(eye_style).lower(sds))
    assert new["flops"] < old["flops"]
    assert new["bytes_accessed"] <= old["bytes_accessed"]
    assert new["temp_bytes"] <= old["temp_bytes"]


def test_pallas_single_f32_materialization_of_padded_matrix():
    """pallas_pairwise_distances hoists ONE f32 view of the padded
    matrix for the squared norms; the matmul operand stays the wire
    dtype.  A second materialization of Gp.astype(f32) would cost
    ~np*dp*4 extra temp bytes — pin the bf16 path under that
    threshold (shape-exact facts; the perf-gate env guard covers
    toolchain bumps, and this box's tests always run on one env)."""
    from attacking_federate_learning_tpu.ops.pallas_distances import (
        pallas_pairwise_distances
    )

    n, d = 300, 700
    np_, dp = 384, 1024          # padded to lcm(128,128) x 512-multiple
    extra_cast = np_ * dp * 4    # a second f32 copy of Gp
    sds16 = jax.ShapeDtypeStruct((n, d), jnp.bfloat16)
    sds32 = jax.ShapeDtypeStruct((n, d), jnp.float32)
    f16 = _facts(jax.jit(lambda g: pallas_pairwise_distances(g))
                 .lower(sds16))
    f32 = _facts(jax.jit(lambda g: pallas_pairwise_distances(g))
                 .lower(sds32))
    # Measured 4.18 MB on this env; one duplicated cast would add
    # +1.57 MB.  The bound sits between the two.
    assert f16["temp_bytes"] < 4.18e6 + 0.5 * extra_cast
    # And the bf16 path must stay cheaper than the all-f32 path (whose
    # padded matrix alone is twice the bytes).
    assert f16["temp_bytes"] < f32["temp_bytes"]
