"""Per-round client participation sampling (beyond-reference; the
reference uses every client every round, server.py:54-56)."""

import numpy as np
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import make_attacker
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset


def _exp(**overrides):
    kw = dict(dataset=C.SYNTH_MNIST, users_count=20, mal_prop=0.25,
              batch_size=16, epochs=4, defense="TrimmedMean", num_std=1.0,
              participation=0.5, synth_train=512, synth_test=64)
    kw.update(overrides)
    cfg = ExperimentConfig(**kw)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=kw["synth_train"],
                      synth_test=64)
    return FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                               dataset=ds)


def test_cohort_sizes_static_and_scaled():
    exp = _exp()                      # n=20 f=5 p=0.5
    assert (exp.m, exp.m_mal) == (10, 2)  # round(0.5*5)=2
    full = _exp(participation=1.0)
    assert (full.m, full.m_mal) == (20, 5)


def test_participants_structure_and_variation():
    exp = _exp()
    p0 = np.asarray(exp._participants(0))
    p1 = np.asarray(exp._participants(1))
    assert len(p0) == exp.m
    assert len(set(p0.tolist())) == exp.m          # no duplicates
    assert np.all(p0[: exp.m_mal] < exp.f)         # malicious first
    assert np.all(p0[exp.m_mal:] >= exp.f)         # honest rest
    assert not np.array_equal(p0, p1)              # resampled per round
    # deterministic per (seed, round)
    np.testing.assert_array_equal(p0, np.asarray(exp._participants(0)))


def test_training_runs_and_defense_sees_cohort():
    exp = _exp(defense="Krum")        # guard: m=10 >= 2*2+1
    exp.run_span(0, 4)
    w = np.asarray(exp.state.weights)
    assert np.all(np.isfinite(w))
    assert int(exp.state.round) == 4


def test_guard_checks_cohort_not_population():
    # Bulyan needs (cohort) m >= 4*m_mal + 3.  With n=22, f=5 the full
    # population fails (22 < 23) — but the p=0.5 cohort (m=11,
    # m_mal=round(2.5)=2, bound 11) passes: the guard must judge what the
    # defense actually sees.
    kw = dict(users_count=22, mal_prop=0.23, defense="Bulyan")
    with pytest.raises(ValueError, match="Bulyan"):
        _exp(participation=1.0, **kw)
    exp = _exp(participation=0.5, **kw)
    assert (exp.m, exp.m_mal) == (11, 2)
    exp.run_round(0)  # and it trains


def test_streaming_matches_device_under_participation():
    a = _exp(data_placement="host_stream")
    b = _exp(data_placement="device")
    a.run_span(0, 3)
    b.run_span(0, 3)
    np.testing.assert_array_equal(np.asarray(a.state.weights),
                                  np.asarray(b.state.weights))


def test_partial_participation_differs_from_full():
    a = _exp(participation=0.5)
    b = _exp(participation=1.0)
    a.run_span(0, 2)
    b.run_span(0, 2)
    assert not np.array_equal(np.asarray(a.state.weights),
                              np.asarray(b.state.weights))


def test_validation():
    with pytest.raises(ValueError, match="participation"):
        ExperimentConfig(dataset=C.SYNTH_MNIST, participation=0.0)
    with pytest.raises(ValueError, match="participation"):
        ExperimentConfig(dataset=C.SYNTH_MNIST, participation=1.5)


def test_zero_malicious_cohort_rejected():
    # round(0.5 * 1) == 0 (banker's rounding): a silent attack-free "attack
    # run" must be refused up front.
    with pytest.raises(ValueError, match="malicious cohort to 0"):
        _exp(users_count=20, mal_prop=0.05, participation=0.5)


def test_all_malicious_tiny_cohort_rejected():
    # All-malicious population with a tiny cohort (the empty-honest-pool
    # crash scenario): refused at construction by the zero-malicious-cohort
    # guard (once m_mal >= 1, rounding can't demand more honest clients
    # than exist, so that second guard is a defensive backstop).
    with pytest.raises(ValueError):
        _exp(users_count=3, mal_prop=1.0, participation=0.1,
             defense="NoDefense")


def test_blockwise_guard_uses_cohort_rows():
    # n=20 divides 4 but the m=10 cohort doesn't divide... 10 % 4 != 0:
    # must raise cleanly at construction, not inside shard_map.
    with pytest.raises(ValueError, match="round cohort"):
        _exp(defense="Krum", distance_impl="ring", mesh_shape=(4, 2),
             participation=0.5)
