"""FLTrust validation-data defense (completes the reference's vestigial
metadata hook, SURVEY.md §2 C12)."""

import jax.numpy as jnp
import numpy as np

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack, NoAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses import DEFENSES


def test_opposed_gradient_gets_zero_trust():
    g0 = jnp.asarray([1.0, 0.0, 0.0])
    G = jnp.stack([g0, -g0, jnp.asarray([0.0, 1.0, 0.0])])
    out = np.asarray(DEFENSES["FLTrust"](G, 3, 1, server_grad=g0))
    # Row 1 (opposed) has trust 0; rows 0 and 2 have trust 1 and 0 resp.
    # (orthogonal → cos 0), so the result is row 0 rescaled to ||g0||.
    np.testing.assert_allclose(out, np.asarray(g0), atol=1e-5)


def test_trust_weighted_average_rescales_to_server_norm():
    g0 = jnp.asarray([2.0, 0.0])
    gi = jnp.asarray([[4.0, 0.0]])  # same direction, double norm
    out = np.asarray(DEFENSES["FLTrust"](gi, 1, 0, server_grad=g0))
    np.testing.assert_allclose(out, [2.0, 0.0], atol=1e-5)  # rescaled


def test_fltrust_resists_alie_that_breaks_no_defense(hard_ds):
    """ALIE z=0.5 collapses plain averaging (tests/test_behavior.py) but
    FLTrust's cosine gate keeps accuracy high."""
    from conftest import hard_final_accuracy

    # NoDefense under the same attack collapses to ~15% (test_behavior.py);
    # FLTrust holds ~81% at authoring time.
    attacked = hard_final_accuracy(hard_ds, "FLTrust", DriftAttack(0.5),
                                   0.21)
    assert attacked > 70.0


def test_metadata_pool_carries_contributor_style():
    """Under femnist_style the contributed samples are the client's OWN
    (styled) view — the trust reference must live on the distribution
    honest clients actually train on (core/engine.py collect_metadata)."""
    def meta(partition, strength=0.5):
        cfg = ExperimentConfig(
            dataset=C.SYNTH_MNIST, users_count=6, mal_prop=0.0,
            batch_size=16, epochs=1, defense="FLTrust",
            collect_metadata=True, partition=partition,
            style_strength=strength, synth_train=256, synth_test=64)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=256,
                          synth_test=64)
        exp = FederatedExperiment(cfg, attacker=NoAttack(), dataset=ds)
        return exp.metadata

    mx_iid, my_iid = meta("iid")
    mx_sty, my_sty = meta("femnist_style")
    np.testing.assert_array_equal(my_iid, my_sty)   # same picks
    assert not np.array_equal(mx_iid, mx_sty)       # styled inputs
    mx_s0, _ = meta("femnist_style", strength=0.0)
    np.testing.assert_array_equal(mx_iid, mx_s0)    # strength 0 == iid
