"""WRN-40-4 (reference Cifar100Net, data_sets.py:108-149) and ResNet-20."""

import jax
import numpy as np
import pytest

from attacking_federate_learning_tpu.models import get_model
from attacking_federate_learning_tpu.utils.flatten import make_flattener


def count_wrn_params(depth=40, widen=4, classes=100):
    """Analytic parameter count of the reference WRN-40-4 trainables."""
    n = (depth - 4) // 6
    ch = [16, 16 * widen, 32 * widen, 64 * widen]
    total = 3 * 3 * 3 * ch[0]  # stem conv
    for g in range(3):
        in_p = ch[g]
        out_p = ch[g + 1]
        for b in range(n):
            i = in_p if b == 0 else out_p
            total += 2 * i  # bn1
            total += 3 * 3 * i * out_p  # conv1
            total += 2 * out_p  # bn2
            total += 3 * 3 * out_p * out_p  # conv2
            if i != out_p:
                total += 1 * 1 * i * out_p  # shortcut
    total += 2 * ch[3]  # final bn
    total += ch[3] * classes + classes  # fc
    return total


def test_wrn_param_count_matches_reference_architecture():
    model = get_model("wideresnet40_4")
    params = model.init(jax.random.key(0))
    flat = make_flattener(params)
    assert flat.dim == count_wrn_params()


@pytest.mark.parametrize("name,classes", [("wideresnet40_4", 100),
                                          ("resnet20", 10)])
def test_forward_shapes_and_logprobs(name, classes):
    model = get_model(name)
    params = model.init(jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (2, 3, 32, 32))
    out = jax.jit(model.apply)(params, x)
    assert out.shape == (2, classes)
    np.testing.assert_allclose(np.exp(np.asarray(out, np.float64)).sum(-1),
                               1.0, atol=1e-4)


def test_wrn_grads_finite():
    """One wire-format gradient step must be finite (BN batch-stats path)."""
    import jax.numpy as jnp
    from attacking_federate_learning_tpu.models.layers import nll_loss

    model = get_model("resnet20")
    params = model.init(jax.random.key(3))
    flat = make_flattener(params)

    def loss(w, x, y):
        return nll_loss(model.apply(flat.unravel(w), x), y)

    w = flat.ravel(params)
    x = jax.random.normal(jax.random.key(4), (4, 3, 32, 32))
    y = jnp.asarray([0, 1, 2, 3])
    g = jax.jit(jax.grad(loss))(w, x, y)
    assert bool(jnp.isfinite(g).all())
