"""Pallas fused distance kernel vs the XLA reference (interpret mode on CPU)."""

import numpy as np
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.ops.distances import pairwise_distances
from attacking_federate_learning_tpu.ops.pallas_distances import (
    pallas_pairwise_distances
)


@pytest.mark.parametrize("n,d", [(16, 100), (40, 300), (64, 512)])
def test_pallas_matches_xla(n, d):
    rng = np.random.default_rng(n + d)
    G = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    want = np.asarray(pairwise_distances(G))
    got = np.asarray(pallas_pairwise_distances(G, bm=8, bn=8, bk=128,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_pallas_padding_is_harmless():
    # n and d far from the block multiples.
    rng = np.random.default_rng(0)
    G = jnp.asarray(rng.standard_normal((13, 77)).astype(np.float32))
    want = np.asarray(pairwise_distances(G))
    got = np.asarray(pallas_pairwise_distances(G, bm=8, bn=8, bk=128,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_pallas_unequal_tile_sizes():
    """bm != bn requires lcm padding — every output tile must be written."""
    rng = np.random.default_rng(7)
    G = jnp.asarray(rng.standard_normal((20, 64)).astype(np.float32))
    want = np.asarray(pairwise_distances(G))
    got = np.asarray(pallas_pairwise_distances(G, bm=8, bn=16, bk=64,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)
