"""Pallas kernel suite vs the XLA references (interpret mode on CPU;
Mosaic-compiled when the suite runs on a real TPU via FL_TEST_TPU=1).

Parity contract (ISSUE 11, mirrored in PARITY.md):

- masked/weighted trimmed mean + median kernels replicate
  defenses/kernels.py's masked estimators op for op — pinned
  BIT-EXACT;
- unmasked trimmed mean / median and the fused Krum scores are
  ulp-bounded (the whole-matrix XLA program fuses its arithmetic
  differently than the tiled one — the same summation-order contract
  as the native host kernels, tests/test_native.py);
- selection outputs (Krum winner, Bulyan selection set) are bit-exact
  whenever the f32 score gap clears the tie band; inside the band a
  flip is legal and adjudicated with an f64 re-score, exactly the
  test_native standard.
"""

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.defenses.kernels import (
    _krum_scores, bulyan, krum, krum_select, masked_median,
    masked_trimmed_mean_of, trimmed_mean, trimmed_mean_of
)
from attacking_federate_learning_tpu.defenses.median import median
from attacking_federate_learning_tpu.ops.distances import pairwise_distances
from attacking_federate_learning_tpu.ops.pallas_distances import (
    pallas_pairwise_distances
)
from attacking_federate_learning_tpu.ops.pallas_defense import (
    krum_scores_cost, pallas_krum_scores, pallas_masked_median,
    pallas_masked_trimmed_mean, pallas_median_of, pallas_trimmed_mean_of
)

# Env-var gate, NOT a jax.devices() probe: backend init at collection
# time would hang in the relay connect-retry loop if the relay died
# between the capture script's probe and pytest's start.
on_tpu = os.environ.get("FL_TEST_TPU") == "1"


@pytest.mark.parametrize("n,d", [(16, 100), (40, 300), (64, 512)])
def test_pallas_matches_xla(n, d):
    rng = np.random.default_rng(n + d)
    G = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    want = np.asarray(pairwise_distances(G))
    got = np.asarray(pallas_pairwise_distances(G, bm=8, bn=8, bk=128,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_pallas_padding_is_harmless():
    # n and d far from the block multiples.
    rng = np.random.default_rng(0)
    G = jnp.asarray(rng.standard_normal((13, 77)).astype(np.float32))
    want = np.asarray(pairwise_distances(G))
    got = np.asarray(pallas_pairwise_distances(G, bm=8, bn=8, bk=128,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_pallas_unequal_tile_sizes():
    """bm != bn requires lcm padding — every output tile must be written."""
    rng = np.random.default_rng(7)
    G = jnp.asarray(rng.standard_normal((20, 64)).astype(np.float32))
    want = np.asarray(pairwise_distances(G))
    got = np.asarray(pallas_pairwise_distances(G, bm=8, bn=16, bk=64,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


# ---------------------------------------------------------------------------
# attack-shaped cohort matrices: the pinned defense x attack configs'
# gradient geometry, built directly (identical ALIE colluder rows at the
# z-envelope, a boosted backdoor row, sign-flipped rows) so the parity
# suite exercises the tie structure real rounds produce.

def _cohort(n, d, f, attack, seed=0):
    rng = np.random.default_rng(seed)
    G = rng.standard_normal((n, d)).astype(np.float32)
    if attack == "alie":
        mu, sigma = G[f:].mean(0), G[f:].std(0)
        G[:f] = mu + 1.5 * sigma          # identical crafted rows: ties
    elif attack == "backdoor":
        G[:f] = 8.0 * rng.standard_normal(d).astype(np.float32)
    elif attack == "signflip":
        G[:f] = -G[f:2 * f] if f else G[:f]
    return jnp.asarray(G)


_CASES = [(19, 300, 4, "none"), (21, 777, 5, "alie"),
          (32, 512, 8, "backdoor"), (24, 100, 6, "signflip"),
          (13, 79, 3, "alie"), (64, 1024, 15, "alie")]


# ---------------------------------------------------------------------------
# fused distance -> Krum score kernel

def _degenerate_pair_band(f, G):
    """Identical crafted rows have zero distances evaluated by Gram
    cancellation: |d2_err| ~ eps·||g||², so each such pair's distance
    carries ~||g||·sqrt(2·eps) of engine-dependent noise and a crafted
    row's score up to f times that (measured to match within 2x; 4x
    safety).  Honest decisive rows stay at relative-ulp level."""
    max_norm = float(np.max(np.linalg.norm(np.asarray(G), axis=1)))
    return 4.0 * f * max_norm * float(
        np.sqrt(2.0 * np.finfo(np.float32).eps))


@pytest.mark.parametrize("n,d,f,attack", _CASES)
@pytest.mark.parametrize("paper_scoring", [False, True])
def test_fused_krum_scores_match_sort_path(n, d, f, attack,
                                           paper_scoring):
    G = _cohort(n, d, f, attack)
    want = np.asarray(_krum_scores(pairwise_distances(G), n, f,
                                   paper_scoring=paper_scoring))
    got, rowsum = pallas_krum_scores(G, n, f,
                                     paper_scoring=paper_scoring,
                                     bm=8, bn=8, bk=128, interpret=True)
    band = _degenerate_pair_band(f, G)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-6,
                               atol=band)
    # The winner is the defense output: it must agree outside the tie
    # band (crafted cohorts hold EXACT-duplicate rows whose scores
    # differ only by degenerate-pair noise — a flip among those is a
    # legal tie, adjudicated against the reference's own score gap).
    ga, wa = int(np.argmin(np.asarray(got))), int(np.argmin(want))
    assert ga == wa or abs(want[ga] - want[wa]) <= band
    assert np.all(np.isfinite(np.asarray(rowsum)))


@pytest.mark.parametrize("bm,bn,bk", [(8, 8, 128), (8, 16, 64),
                                      (16, 8, 256)])
def test_fused_krum_scores_tile_boundaries(bm, bn, bk):
    """n, d far from every block multiple (incl. bm != bn lcm padding)."""
    G = _cohort(23, 333, 5, "alie", seed=3)
    want = np.asarray(_krum_scores(pairwise_distances(G), 23, 5))
    got, _ = pallas_krum_scores(G, 23, 5, bm=bm, bn=bn, bk=bk,
                                interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-6,
                               atol=1e-4)


def test_fused_krum_scores_wire_dim():
    """The production wire dim (d=79510, nothing divides cleanly)."""
    G = _cohort(12, 79_510, 3, "alie", seed=1)
    want = np.asarray(_krum_scores(pairwise_distances(G), 12, 3))
    got, _ = pallas_krum_scores(G, 12, 3, bm=8, bn=8, bk=512,
                                interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-6,
                               atol=2e-3)


def test_fused_krum_complement_zero():
    """f=1 (reference scoring) has an empty complement: scores ARE the
    rowsums — no subtraction, no guard, still the sort path's values."""
    G = _cohort(11, 200, 1, "none", seed=5)
    want = np.asarray(_krum_scores(pairwise_distances(G), 11, 1))
    got, rowsum = pallas_krum_scores(G, 11, 1, bm=8, bn=8, bk=128,
                                     interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(rowsum))


def test_pallas_krum_dispatch_guard_falls_back_to_sort():
    """Adversarial magnitudes (reference malicious.py scale) concentrate
    the rowsum in the complement; the dispatch's cancellation guard must
    re-evaluate via the exact sort path — the selected index must match
    the oracle-verified sort evaluation, not the cancelled subtraction."""
    n, d, f = 19, 300, 4
    G = np.array(_cohort(n, d, f, "none"), copy=True)
    G[:f] *= 1e18                       # cancellation regime
    G = jnp.asarray(G)
    want = int(krum_select(G, n, f, distance_impl="xla"))
    got = int(krum_select(G, n, f, scores_impl="pallas"))
    assert got == want


def test_pallas_krum_kernel_entry():
    """krum(scores_impl='pallas') returns an exact input row (selection
    defense: agreement on the winner == bit-exact aggregate)."""
    G = _cohort(21, 400, 5, "alie")
    want = np.asarray(krum(G, 21, 5))
    got = np.asarray(krum(G, 21, 5, scores_impl="pallas"))
    np.testing.assert_array_equal(got, want)
    # telemetry carries the fused scores (real values, not NaN slots)
    agg, diag = krum(G, 21, 5, scores_impl="pallas", telemetry=True)
    assert np.isfinite(np.asarray(diag["scores"])).all()
    assert int(np.argmax(np.asarray(diag["selection_mask"]))) == int(
        np.argmin(np.asarray(diag["scores"])))


def test_pallas_krum_masked_path_matches_xla():
    """Quarantine mask forces the exact sort evaluator over the pallas
    distance matrix; winners must match the xla masked path."""
    n, d, f = 21, 300, 5
    G = _cohort(n, d, f, "alie")
    mask = jnp.asarray(np.random.default_rng(0).random(n) > 0.25)
    want = np.asarray(krum(G, n, f, mask=mask))
    got = np.asarray(krum(G, n, f, mask=mask, scores_impl="pallas"))
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# tiled trimmed mean / median (masked bit-exact, unmasked ulp-bounded)

@pytest.mark.parametrize("n,d,f,attack", _CASES)
def test_pallas_trimmed_mean_ulp_bounded(n, d, f, attack):
    G = _cohort(n, d, f, attack)
    k = n - f - 1
    want = np.asarray(trimmed_mean_of(G, k))
    got = np.asarray(pallas_trimmed_mean_of(G, k, interpret=True))
    # Summation-order ulps only (the host-kernel contract): a few ulp
    # at these magnitudes.
    np.testing.assert_allclose(got, want, rtol=3e-6, atol=3e-6)


@pytest.mark.parametrize("n,d,f,attack", _CASES)
def test_pallas_masked_trimmed_mean_bit_exact(n, d, f, attack):
    G = _cohort(n, d, f, attack)
    rng = np.random.default_rng(n)
    mask = jnp.asarray(rng.random(n) > 0.25)
    want = np.asarray(masked_trimmed_mean_of(
        G, mask, jnp.sum(mask) - f - 1))
    got = np.asarray(pallas_masked_trimmed_mean(G, mask, f + 1,
                                                interpret=True))
    np.testing.assert_array_equal(got, want)
    # weighted (the async staleness seam)
    w = jnp.asarray(rng.random(n).astype(np.float32) + 0.1)
    want = np.asarray(masked_trimmed_mean_of(
        G, mask, jnp.sum(mask) - f - 1, weights=w))
    got = np.asarray(pallas_masked_trimmed_mean(
        G, mask, f + 1, weights=w, weighted=True, interpret=True))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("n,d", [(19, 777), (22, 256), (13, 79)])
def test_pallas_median_kernels(n, d):
    rng = np.random.default_rng(n * d)
    G = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(pallas_median_of(G, interpret=True)),
        np.asarray(jnp.median(G, axis=0)))
    mask = jnp.asarray(rng.random(n) > 0.3)
    np.testing.assert_array_equal(
        np.asarray(pallas_masked_median(G, mask, interpret=True)),
        np.asarray(masked_median(G, mask)))
    w = jnp.asarray(rng.random(n).astype(np.float32) + 0.1)
    np.testing.assert_array_equal(
        np.asarray(pallas_masked_median(G, mask, weights=w,
                                        weighted=True, interpret=True)),
        np.asarray(masked_median(G, mask, weights=w)))


def test_trimmed_mean_dispatch_pallas_impl():
    """The registry kernel's impl='pallas' branch: NaN telemetry slots
    (the kernel returns only the aggregate — the documented host-kernel
    convention) and the masked branch bit-matches the xla seam."""
    n, d, f = 19, 300, 4
    G = _cohort(n, d, f, "alie")
    agg, diag = trimmed_mean(G, n, f, impl="pallas", telemetry=True)
    np.testing.assert_allclose(np.asarray(agg),
                               np.asarray(trimmed_mean(G, n, f)),
                               rtol=3e-6, atol=3e-6)
    assert np.isnan(np.asarray(diag["kept_fraction"])).all()
    mask = jnp.asarray(np.random.default_rng(1).random(n) > 0.2)
    np.testing.assert_array_equal(
        np.asarray(trimmed_mean(G, n, f, impl="pallas", mask=mask)),
        np.asarray(trimmed_mean(G, n, f, mask=mask)))
    np.testing.assert_array_equal(
        np.asarray(median(G, n, f, impl="pallas", mask=mask)),
        np.asarray(median(G, n, f, mask=mask)))


# ---------------------------------------------------------------------------
# Bulyan: the all-on-device route

@pytest.mark.parametrize("n,d,f,attack", [(19, 300, 4, "alie"),
                                          (23, 512, 5, "backdoor"),
                                          (32, 200, 7, "signflip")])
def test_bulyan_pallas_route_matches_xla(n, d, f, attack):
    G = _cohort(n, d, f, attack)
    want_agg, want_diag = bulyan(G, n, f, telemetry=True)
    got_agg, got_diag = bulyan(G, n, f, selection_impl="pallas",
                               trim_impl="pallas", telemetry=True)
    # Identical selection math over a ulp-different D: on decisive
    # cohorts the selection SET must agree, and the trim tail is then
    # summation-order ulps.
    np.testing.assert_array_equal(
        np.asarray(got_diag["selection_mask"]),
        np.asarray(want_diag["selection_mask"]))
    np.testing.assert_allclose(np.asarray(got_agg),
                               np.asarray(want_agg), rtol=3e-6,
                               atol=3e-6)


def test_bulyan_pallas_route_masked():
    n, d, f = 23, 300, 4
    G = _cohort(n, d, f, "alie")
    mask = jnp.asarray(np.random.default_rng(2).random(n) > 0.2)
    want = np.asarray(bulyan(G, n, f, mask=mask))
    got = np.asarray(bulyan(G, n, f, mask=mask, selection_impl="pallas",
                            trim_impl="pallas"))
    np.testing.assert_allclose(got, want, rtol=3e-6, atol=3e-6)


def test_bulyan_pallas_route_never_marshals(monkeypatch):
    """The acceptance fact: no (n, n) pure_callback on the 'pallas'
    route — a callback firing inside the traced program would be the
    host marshal coming back."""
    import jax as jax_mod

    def boom(*a, **k):
        raise AssertionError("pure_callback on the pallas route")

    monkeypatch.setattr(jax_mod, "pure_callback", boom)
    G = _cohort(19, 200, 4, "alie")
    jax.jit(lambda g: bulyan(g, 19, 4, selection_impl="pallas",
                             trim_impl="pallas"))(G).block_until_ready()


# ---------------------------------------------------------------------------
# engine-level: the pallas route reproduces the xla trajectories

def _engine_weights(defense, rounds=3, **kw):
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    base = dict(dataset=C.SYNTH_MNIST, users_count=19, mal_prop=0.21,
                batch_size=16, epochs=rounds, test_step=5, seed=0,
                synth_train=256, synth_test=64, defense=defense)
    base.update(kw)
    cfg = ExperimentConfig(**base)
    ds = load_dataset(C.SYNTH_MNIST, seed=0, synth_train=256,
                      synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds)
    exp.run_span(0, rounds)
    return np.asarray(exp.state.weights)


@pytest.mark.parametrize("defense", ["Krum", "Bulyan"])
def test_engine_pallas_selection_trajectories_bit_equal(defense):
    """Selection defenses aggregate exact input rows: with decisive
    ALIE-regime data the pallas-route trajectory is bit-equal to xla."""
    np.testing.assert_array_equal(
        _engine_weights(defense, aggregation_impl="pallas"),
        _engine_weights(defense))


def test_engine_pallas_async_and_faulted_bit_equal():
    """The masked/weighted pallas kernels are bit-exact, so the async
    (weights seam) and faulted (quarantine seam) trajectories through
    the pallas route reproduce xla bit for bit."""
    kw = dict(aggregation="async", async_buffer=12,
              staleness_weight="poly")
    np.testing.assert_array_equal(
        _engine_weights("TrimmedMean", aggregation_impl="pallas", **kw),
        _engine_weights("TrimmedMean", **kw))
    np.testing.assert_array_equal(
        _engine_weights("Median", aggregation_impl="pallas",
                        faults=dict(dropout=0.2)),
        _engine_weights("Median", faults=dict(dropout=0.2)))


def test_engine_pallas_hierarchical_scan():
    """The pallas kernels inside the PR 6 per-shard scan: one
    hierarchical jit owns tier-1 end to end (ISSUE 11 tentpole)."""
    kw = dict(users_count=24, mal_prop=0.125, aggregation="hierarchical",
              megabatch=8, tier2_defense="TrimmedMean")
    np.testing.assert_array_equal(
        _engine_weights("Krum", aggregation_impl="pallas", **kw),
        _engine_weights("Krum", **kw))


# ---------------------------------------------------------------------------
# the f32 tie-break band contract (tests/test_native.py standard)

def test_duplicate_row_ties_resolve_identically():
    """Exact duplicate rows are exact score ties in BOTH engines (each
    computes the duplicates' scores from identical inputs), so the
    first-occurrence argmin must pick the same winner — the
    deterministic half of the tie contract."""
    n, d, f = 20, 128, 4
    G = np.array(_cohort(n, d, f, "none", seed=9), copy=True)
    G[7] = G[11]
    G[:f] = G[0]
    G = jnp.asarray(G)
    assert int(krum_select(G, n, f)) == int(
        krum_select(G, n, f, scores_impl="pallas"))


def test_fused_krum_tie_band_sweep():
    """Randomized sweep: any cross-engine winner flip must sit inside
    the f32 score-indeterminacy band, adjudicated with an exact f64
    re-score (the measured-band reality test_native.py pins for the
    native comparator; bench.py:adjudicate_f32_flip is the template)."""
    flips = 0
    for trial in range(120):
        rng = np.random.default_rng(10_000 + trial)
        n = int(rng.integers(10, 28))
        f = max(1, int(0.24 * n))
        d = int(rng.integers(32, 200))
        G = rng.standard_normal((n, d)).astype(np.float32)
        if trial % 3 == 0:
            G[:f] = G[f:].mean(0) + 0.5 * G[f:].std(0)  # near-tie regime
        Gj = jnp.asarray(G)
        a = int(krum_select(Gj, n, f))
        b = int(np.argmin(np.asarray(
            pallas_krum_scores(Gj, n, f, bm=8, bn=8, bk=64,
                               interpret=True)[0])))
        if a == b:
            continue
        flips += 1
        # f64 exact re-score of both candidates: the gap must be inside
        # the f32 indeterminacy at these magnitudes.
        D = np.sqrt(np.maximum(
            ((G[:, None, :] - G[None, :, :]) ** 2).sum(-1), 0.0)
        ).astype(np.float64)
        np.fill_diagonal(D, np.inf)
        k = n - f
        srt = np.sort(D, axis=1)[:, :min(k, n - 1)]
        scores64 = srt.sum(1)
        gap = abs(scores64[a] - scores64[b])
        band = (32 * np.finfo(np.float32).eps
                * max(scores64[a], scores64[b]))
        assert gap <= band, (
            f"trial {trial}: winners {a} vs {b} diverge outside the "
            f"f32 tie band (gap {gap:.3e} > band {band:.3e})")
    # The sweep must have exercised the comparison, not vacuously passed.
    assert flips < 30


# ---------------------------------------------------------------------------
# campaign integration: impl axes pre-validate like every other knob

def test_campaign_impl_axes_prevalidate():
    from attacking_federate_learning_tpu.campaigns.spec import (
        CampaignSpec
    )

    spec = CampaignSpec(
        name="impl-compare",
        base=dict(dataset="SYNTH_MNIST", users_count=19, mal_prop=0.21,
                  batch_size=16, epochs=2, synth_train=256,
                  synth_test=64, defense="Krum"),
        axes={"aggregation_impl": ["xla", "pallas"],
              "backdoor_fused": [True, False],
              "backdoor": ["pattern"]},
    )
    cells = spec.expand()
    assert len(cells) == 4
    skips = {(c.overrides["aggregation_impl"],
              c.overrides["backdoor_fused"]): c.skip for c in cells}
    assert skips[("xla", True)] is None
    assert skips[("pallas", True)] is None
    # the pallas ⊕ host-staged backdoor seam: skipped with the config's
    # own message, never a crashed run
    assert "backdoor-staged" in skips[("pallas", False)]
    for c in cells:
        assert c.row()["aggregation_impl"] == c.overrides[
            "aggregation_impl"]


def test_campaign_bulyan_selection_axis():
    from attacking_federate_learning_tpu.campaigns.spec import (
        composition_reject_reason
    )

    base = dict(dataset="SYNTH_MNIST", users_count=23, mal_prop=0.21,
                batch_size=16, epochs=2, synth_train=256, synth_test=64,
                defense="Bulyan")
    assert composition_reject_reason(
        dict(base, bulyan_selection_impl="pallas")) is None
    r = composition_reject_reason(
        dict(base, bulyan_selection_impl="pallas", distance_impl="host"))
    assert r and "distance_impl" in r
    r = composition_reject_reason(
        dict(base, aggregation_impl="pallas",
             bulyan_selection_impl="host"))
    assert r and "marshal" in r


# ---------------------------------------------------------------------------
# cost-ledger fusion pin (slow: the 10k north-star compile)

@pytest.mark.slow
def test_fused_kernel_cost_ledger_beats_xla_at_north_star():
    """ISSUE 11 acceptance: at n=10,240 the fused distance->score
    kernel reads strictly fewer HBM bytes (operands-once accounting)
    than the XLA Gram+epilogue path, and no (n, n) tensor exists in
    its compiled program — tools/perf_gate.py --pallasproof is the
    same check, CI-wired via smoke leg 4."""
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "perf_gate", _os.path.join(_os.path.dirname(__file__), "..",
                                   "tools", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.pallasproof() == 0


def test_krum_scores_cost_model_shapes():
    """The declared model is deterministic in the shapes and the
    operands-once view is tile-size-invariant (it counts logical
    operands, not the bm/bn re-reads the tile view counts)."""
    a = krum_scores_cost(1024, 4096, 200, bm=128, bn=128, bk=512)
    b = krum_scores_cost(1024, 4096, 200, bm=256, bn=256, bk=1024)
    assert a["bytes_accessed"] == b["bytes_accessed"]
    assert a["hbm_tile_bytes"] > b["hbm_tile_bytes"]
    assert a["bytes_accessed"] < a["hbm_tile_bytes"]


# ---------------------------------------------------------------------------
# hardware-gated Mosaic parity (the capture-window payload)

@pytest.mark.skipif(not on_tpu, reason="needs a real TPU (Mosaic compile)")
@pytest.mark.parametrize("n,d", [(512, 4096), (704, 2000)])
def test_pallas_mosaic_compiled_matches_xla_on_tpu(n, d):
    """The kernel's production configuration (default tiles, interpret
    resolved OFF on TPU) against the XLA Gram path, on the real chip —
    the on-chip parity VERDICT round-2 item #2 asks for.  The 704 case
    exercises the lcm/padding scheme under Mosaic, not just interpret."""
    G = jax.random.normal(jax.random.PRNGKey(n + d), (n, d), jnp.float32)
    want = np.asarray(jax.jit(pairwise_distances)(G))
    got = np.asarray(jax.jit(pallas_pairwise_distances)(G))
    np.testing.assert_allclose(got, want, atol=5e-3, rtol=1e-3)


@pytest.mark.skipif(not on_tpu, reason="needs a real TPU (Mosaic compile)")
@pytest.mark.parametrize("n,d", [(512, 4096), (704, 2000)])
def test_pallas_defense_mosaic_compiled_on_tpu(n, d):
    """Mosaic compile + on-chip parity for the defense suite: fused
    Krum scores, the trim tile and the median tile at production
    configuration (interpret resolved OFF)."""
    f = int(0.24 * n)
    G = jax.random.normal(jax.random.PRNGKey(n + d), (n, d), jnp.float32)
    want = np.asarray(jax.jit(
        lambda g: _krum_scores(pairwise_distances(g), n, f))(G))
    got = np.asarray(jax.jit(
        lambda g: pallas_krum_scores(g, n, f)[0])(G))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=5e-2)
    k = n - f - 1
    np.testing.assert_allclose(
        np.asarray(jax.jit(lambda g: pallas_trimmed_mean_of(g, k))(G)),
        np.asarray(jax.jit(lambda g: trimmed_mean_of(g, k))(G)),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(jax.jit(pallas_median_of)(G)),
        np.asarray(jax.jit(lambda g: jnp.median(g, axis=0))(G)),
        rtol=1e-6, atol=1e-6)
