"""Pallas fused distance kernel vs the XLA reference (interpret mode on
CPU; Mosaic-compiled when the suite runs on a real TPU via FL_TEST_TPU=1)."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.ops.distances import pairwise_distances
from attacking_federate_learning_tpu.ops.pallas_distances import (
    pallas_pairwise_distances
)

# Env-var gate, NOT a jax.devices() probe: backend init at collection
# time would hang in the relay connect-retry loop if the relay died
# between the capture script's probe and pytest's start.
on_tpu = os.environ.get("FL_TEST_TPU") == "1"


@pytest.mark.parametrize("n,d", [(16, 100), (40, 300), (64, 512)])
def test_pallas_matches_xla(n, d):
    rng = np.random.default_rng(n + d)
    G = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    want = np.asarray(pairwise_distances(G))
    got = np.asarray(pallas_pairwise_distances(G, bm=8, bn=8, bk=128,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_pallas_padding_is_harmless():
    # n and d far from the block multiples.
    rng = np.random.default_rng(0)
    G = jnp.asarray(rng.standard_normal((13, 77)).astype(np.float32))
    want = np.asarray(pairwise_distances(G))
    got = np.asarray(pallas_pairwise_distances(G, bm=8, bn=8, bk=128,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_pallas_unequal_tile_sizes():
    """bm != bn requires lcm padding — every output tile must be written."""
    rng = np.random.default_rng(7)
    G = jnp.asarray(rng.standard_normal((20, 64)).astype(np.float32))
    want = np.asarray(pairwise_distances(G))
    got = np.asarray(pallas_pairwise_distances(G, bm=8, bn=16, bk=64,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


@pytest.mark.skipif(not on_tpu, reason="needs a real TPU (Mosaic compile)")
@pytest.mark.parametrize("n,d", [(512, 4096), (704, 2000)])
def test_pallas_mosaic_compiled_matches_xla_on_tpu(n, d):
    """The kernel's production configuration (default tiles, interpret
    resolved OFF on TPU) against the XLA Gram path, on the real chip —
    the on-chip parity VERDICT round-2 item #2 asks for.  The 704 case
    exercises the lcm/padding scheme under Mosaic, not just interpret."""
    G = jax.random.normal(jax.random.PRNGKey(n + d), (n, d), jnp.float32)
    want = np.asarray(jax.jit(pairwise_distances)(G))
    got = np.asarray(jax.jit(pallas_pairwise_distances)(G))
    np.testing.assert_allclose(got, want, atol=5e-3, rtol=1e-3)
