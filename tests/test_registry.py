"""Cross-run observatory (ISSUE 5): the run registry, the ``runs``
CLI (list/show/diff/compare/selfcheck), the schema-v4 kinds, the
checkpoint-layout migration, Perfetto trace export, the behavioral
science gate's diff policy, and report.py over mixed-version logs.

Acceptance contract: the registry indexes journal dirs incrementally
and tolerates torn artifacts; ``runs diff`` on two same-config runs
reports the first divergent round (different seeds) or bit-identity
(identical seeds); trace export of a real run validates against the
Chrome trace-event schema; the science gate's diff names cell+metric
when a constant is perturbed and skips loudly on env mismatch.
"""

import json
import os

import pytest

from attacking_federate_learning_tpu import cli
from attacking_federate_learning_tpu.utils.metrics import validate_event
from attacking_federate_learning_tpu.utils.registry import RunRegistry


# ---------------------------------------------------------------------------
# shared run store: three journaled CLI runs (seed 0, seed 1, and an
# identical-config twin of seed 0 under its own run id)

@pytest.fixture(scope="module")
def store(tmp_path_factory, capfd_disabled=None):
    tmp = tmp_path_factory.mktemp("obs")
    base = ["-s", "SYNTH_MNIST", "-e", "6", "-c", "16",
            "--synth-train", "256", "--synth-test", "64",
            "--log-dir", str(tmp / "logs"), "--run-dir", str(tmp / "runs"),
            "-n", "10", "-m", "0.1", "-d", "Krum",
            "--round-stats", "--journal"]
    cli.main(base)
    cli.main(base + ["--seed", "1"])
    cli.main(base + ["--run-id", "twin"])
    return tmp


def _run_dir(store):
    return str(store / "runs")


def _reg(store):
    return RunRegistry(_run_dir(store))


# ---------------------------------------------------------------------------
# registry core

def test_refresh_indexes_journaled_runs(store, capsys):
    reg = _reg(store)
    summary = reg.refresh()
    ents = {e["run_id"]: e for e in reg.entries()}
    assert summary["entries"] == len(ents) >= 3
    assert "twin" in ents
    s0 = [e for e in ents.values()
          if e["run_id"].startswith("SYNTH_MNIST_Krum_s0")]
    assert len(s0) == 1
    e = s0[0]
    assert e["status"] == "done"
    assert e["rounds_committed"] == 6 and e["evals_committed"] == 2
    assert e["final_accuracy"] > 50.0
    assert e["dataset"] == "SYNTH_MNIST" and e["defense"] == "Krum"
    assert e["event_kinds"]["round"] == 6      # private per-run log
    assert os.path.exists(e["events"])


def test_refresh_is_incremental_and_idempotent(store):
    reg = _reg(store)
    reg.refresh()
    first = reg.entries()
    s2 = reg.refresh()
    assert s2["built"] == 0 and s2["reused"] == len(first)
    assert reg.entries() == first


def test_engine_stamp_makes_run_resolvable_without_refresh(store):
    """core/engine.py appends an index line at run finish, so a
    just-finished run resolves before any rescan."""
    reg = RunRegistry(_run_dir(store))
    e = reg.resolve("twin")
    assert e["status"] == "done"
    assert e["final_accuracy"] > 50.0


def test_registry_event_emitted_and_v4_schema(store):
    ev_path = RunRegistry(_run_dir(store)).resolve("twin")["events"]
    events = [json.loads(x) for x in open(ev_path).read().splitlines()]
    for e in events:
        validate_event(e)
    stamps = [e for e in events if e["kind"] == "registry"]
    assert len(stamps) == 1 and stamps[0]["run_id"] == "twin"
    assert stamps[0]["v"] >= 4
    # v4 rules: the new kinds reject an older stamp, older logs stay
    # valid.
    validate_event({"kind": "gate", "cell": "x", "status": "pass", "v": 4})
    with pytest.raises(ValueError, match="need schema v4"):
        validate_event({"kind": "registry", "run_id": "r", "v": 3})
    validate_event({"kind": "round", "round": 1, "v": 1})


def test_resolve_prefix_tag_filter_and_ambiguity(store):
    reg = _reg(store)
    reg.refresh()
    assert reg.resolve("twin")["run_id"] == "twin"
    assert reg.resolve("SYNTH_MNIST_Krum_s1")["run_id"].startswith(
        "SYNTH_MNIST_Krum_s1_")
    with pytest.raises(ValueError, match="ambiguous"):
        reg.resolve("SYNTH_MNIST_Krum_s")      # s0 and s1 both match
    with pytest.raises(ValueError, match="no run matching"):
        reg.resolve("nonexistent")
    assert [e["run_id"] for e in reg.entries(["seed=1"])] == [
        reg.resolve("SYNTH_MNIST_Krum_s1")["run_id"]]
    reg.tag("twin", "golden")
    assert reg.resolve("golden")["run_id"] == "twin"
    reg.refresh()                               # tag survives a rescan
    assert reg.resolve("golden")["run_id"] == "twin"


def test_torn_artifacts_tolerated(tmp_path):
    """A SIGKILL mid-write leaves a torn manifest/journal/index; the
    registry counts and indexes around it instead of dying."""
    d = tmp_path / "runs" / "torn_run"
    os.makedirs(d)
    with open(d / "journal.jsonl", "w") as f:
        f.write(json.dumps({"kind": "rounds", "start": 0, "end": 4}) + "\n")
        f.write('{"kind": "rounds", "start": 5, "e')       # torn tail
    with open(d / "manifest.json", "w") as f:
        f.write('{"run_id": "torn_run", "status"')          # torn
    reg = RunRegistry(str(tmp_path / "runs"))
    reg.refresh()
    e = reg.resolve("torn_run")
    assert e["journal_high"] == 4
    assert e["torn_lines"] == 1
    assert e["problems"] == ["manifest missing or torn"]
    # A torn INDEX line doesn't take the index down either.
    with open(reg.index_path, "a") as f:
        f.write('{"run_id": "half')
    assert reg.resolve("torn_run")["journal_high"] == 4


# ---------------------------------------------------------------------------
# checkpoint layout: private auto dirs + legacy migration

def test_journaled_autos_live_under_run_id_dir(tmp_path):
    out = cli.main(["-s", "SYNTH_MNIST", "-e", "4", "-c", "16",
                    "--synth-train", "128", "--synth-test", "32",
                    "--log-dir", str(tmp_path / "logs"),
                    "--run-dir", str(tmp_path / "runs"),
                    "-n", "8", "-m", "0.0", "-d", "NoDefense",
                    "--journal", "--run-id", "mine",
                    "--checkpoint-every", "2"])
    assert out["accuracies"]
    autos = [n for n in os.listdir(tmp_path / "runs" / "mine")
             if n.startswith("checkpoint-auto-")]
    assert autos    # private: no collision with runs/<dataset>/
    shared = tmp_path / "runs" / "SYNTH_MNIST"
    if shared.exists():
        assert not [n for n in os.listdir(shared)
                    if n.startswith("checkpoint-auto-")]


def test_refresh_migrates_legacy_auto_checkpoint(tmp_path):
    """Pre-PR-5 layout: the manifest references an auto-checkpoint in
    the shared runs/<dataset>/ dir; one refresh moves it (npz + json
    sidecar) under the owning runs/<run_id>/ and rewrites the
    manifest."""
    runs = tmp_path / "runs"
    legacy = runs / "SYNTH_MNIST"
    owned = runs / "legacy_run"
    os.makedirs(legacy)
    os.makedirs(owned)
    ck = legacy / "checkpoint-auto-00000004.npz"
    ck.write_bytes(b"npz-bytes")
    (legacy / "checkpoint-auto-00000004.json").write_text("{}")
    with open(owned / "manifest.json", "w") as f:
        json.dump({"run_id": "legacy_run", "status": "preempted",
                   "checkpoint": str(ck)}, f)
    reg = RunRegistry(str(runs))
    summary = reg.refresh()
    assert summary["migrated"] == 1
    moved = owned / "checkpoint-auto-00000004.npz"
    assert moved.exists() and not ck.exists()
    assert (owned / "checkpoint-auto-00000004.json").exists()
    assert json.load(open(owned / "manifest.json"))[
        "checkpoint"] == str(moved)
    # One-shot: the next refresh reuses the entry, no re-migration.
    assert reg.refresh()["migrated"] == 0
    assert reg.resolve("legacy_run")["migrated_checkpoint"] == str(moved)


def test_checkpointer_legacy_fallback(tmp_path):
    """A run-id Checkpointer with no private autos yet falls back to
    pre-migration autos in the shared dataset dir for --resume."""
    import numpy as np

    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.utils.checkpoint import (
        Checkpointer
    )

    cfg = ExperimentConfig(dataset="SYNTH_MNIST", users_count=4,
                           batch_size=8, epochs=2, synth_train=64,
                           synth_test=16,
                           run_dir=str(tmp_path / "runs"))
    shared = Checkpointer(cfg)
    from attacking_federate_learning_tpu.core.server import ServerState
    import jax.numpy as jnp

    st = ServerState(weights=jnp.zeros(4), velocity=jnp.zeros(4),
                     round=jnp.asarray(7))
    shared.save_auto(st)
    private = Checkpointer(cfg, auto_dir=str(tmp_path / "runs" / "rid"))
    assert private.latest() is not None
    assert int(np.load(private.latest())["round"]) == 7
    # Once the private dir has its own auto, it wins.
    private.save_auto(ServerState(weights=jnp.ones(4),
                                  velocity=jnp.zeros(4),
                                  round=jnp.asarray(9)))
    assert "rid" in private.latest()
    assert int(np.load(private.latest())["round"]) == 9


# ---------------------------------------------------------------------------
# the runs CLI

def test_runs_list_show_compare_selfcheck(store, capsys):
    rd = _run_dir(store)
    assert cli.main(["runs", "--run-dir", rd, "list"]) == 0
    out = capsys.readouterr().out
    assert "twin" in out and "defense=Krum" in out
    assert cli.main(["runs", "--run-dir", rd, "show", "twin"]) == 0
    out = capsys.readouterr().out
    assert "journal audit: clean" in out
    assert cli.main(["runs", "--run-dir", rd, "compare", "twin",
                     "SYNTH_MNIST_Krum_s1"]) == 0
    out = capsys.readouterr().out
    assert "final_accuracy" in out
    assert cli.main(["runs", "--run-dir", rd, "selfcheck"]) == 0
    out = capsys.readouterr().out
    assert "refresh idempotent" in out
    assert cli.main(["runs", "--run-dir", rd, "show", "nope"]) == 2


def test_runs_diff_reports_first_divergent_round(store, capsys):
    """Same config, different seed: the diff names the first round
    where the per-round records part ways (the acceptance criterion's
    'first divergent round')."""
    rd = _run_dir(store)
    assert cli.main(["runs", "--run-dir", rd, "--json", "diff",
                     "SYNTH_MNIST_Krum_s0", "SYNTH_MNIST_Krum_s1"]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["config_deltas"] == {"seed": [0, 1]}
    tr = d["trajectory"]
    assert tr["bit_identical"] is False
    assert tr["divergence_round"] == 0      # seeds differ from init
    assert tr["divergence_fields"]


def test_runs_diff_bit_identity_on_same_seed(store, capsys):
    """Identical config+seed under two run ids: every shared per-round
    record must match to the bit (the determinism witness)."""
    rd = _run_dir(store)
    assert cli.main(["runs", "--run-dir", rd, "--json", "diff",
                     "SYNTH_MNIST_Krum_s0", "twin"]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d.get("config_deltas") == {}
    tr = d["trajectory"]
    assert tr["bit_identical"] is True
    assert tr["divergence_round"] is None
    assert tr["rounds_compared"] == 6


def test_report_run_id_resolution(store, capsys):
    from attacking_federate_learning_tpu import report

    assert report.main(["--run-dir", _run_dir(store),
                        "--run-id", "twin", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    (summary,) = out.values()
    assert summary["accuracy"]["final"] > 50.0


# ---------------------------------------------------------------------------
# trace export

def test_trace_export_validates_against_schema(store, tmp_path):
    from attacking_federate_learning_tpu.utils.trace_export import (
        export_trace, validate_trace
    )

    entry = RunRegistry(_run_dir(store)).resolve("twin")
    out = export_trace(entry["events"], str(tmp_path / "t.json"),
                       name="twin")
    obj = json.load(open(out))
    assert validate_trace(obj) == []
    evs = obj["traceEvents"]
    rounds = [e for e in evs if e["ph"] == "X"
              and e["name"].startswith("round ")]
    assert len(rounds) == 6                 # one span per round
    assert all(e["dur"] >= 1 for e in rounds)
    names = {e["name"] for e in evs}
    assert "eval" in names                  # instants present
    assert any(n.startswith("lifecycle:") for n in names)
    metas = [e for e in evs if e["ph"] == "M"]
    assert any(e["args"]["name"] == "twin" for e in metas)


def test_trace_export_heartbeat_counters_and_compiles():
    from attacking_federate_learning_tpu.utils.trace_export import (
        events_to_trace, validate_trace
    )

    events = [
        {"kind": "compile", "name": "fused_round", "compile_s": 1.5,
         "cache": "miss", "t": 2.0, "v": 2},
        {"kind": "heartbeat", "rss_mb": 512.0, "last_event_age_s": 0.1,
         "rounds_per_s": 3.25, "t": 3.0, "v": 2},
        {"kind": "profile", "phases": {"round": {"total_s": 1.0,
                                                 "count": 5,
                                                 "mean_ms": 200.0}},
         "t": 4.0, "v": 1},
        {"kind": "gate", "cell": "krum_alie05", "status": "pass",
         "t": 5.0, "v": 4},
    ]
    obj = events_to_trace(events, name="synth")
    assert validate_trace(obj) == []
    evs = obj["traceEvents"]
    comp = [e for e in evs if e["name"] == "compile fused_round"]
    assert comp and comp[0]["dur"] == 1_500_000   # 1.5 s in us
    assert comp[0]["ts"] == 500_000               # tail-anchored
    counters = [e for e in evs if e["ph"] == "C"]
    assert {list(e["args"])[0] for e in counters} == {"rss_mb",
                                                      "rounds_per_s"}
    assert [e for e in evs if e["name"] == "round"
            and e["tid"] == 6] or True            # phases track exists
    assert any(e["name"] == "gate" for e in evs)


def test_validate_trace_names_problems():
    from attacking_federate_learning_tpu.utils.trace_export import (
        validate_trace
    )

    assert validate_trace({"nope": []})
    bad = {"traceEvents": [
        {"name": "x", "ph": "X", "pid": 1, "tid": 1, "ts": 0},   # no dur
        {"name": "", "ph": "i", "pid": 1, "tid": 1, "ts": 1},    # no name
        {"name": "c", "ph": "C", "pid": 1, "tid": 1, "ts": 1,
         "args": {"v": "high"}},                                 # non-num
    ]}
    problems = validate_trace(bad)
    assert len(problems) == 3
    assert any("dur" in p for p in problems)


def test_device_trace_noop_without_tpu_gate(tmp_path, monkeypatch):
    from attacking_federate_learning_tpu.utils.trace_export import (
        device_trace
    )

    monkeypatch.delenv("FL_TEST_TPU", raising=False)
    with device_trace(str(tmp_path / "prof")):
        pass
    assert not os.path.exists(tmp_path / "prof")   # no capture started


# ---------------------------------------------------------------------------
# science gate (diff policy; the cell replays are smoke.sh leg 5)

def _load_gate():
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "science_gate.py")
    spec = importlib.util.spec_from_file_location("science_gate", path)
    sg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sg)
    return sg


def test_science_gate_diff_names_cell_and_metric():
    """A perturbed attack/defense constant shows up as a named
    cell.metric drift — exact metrics at any delta, banded metrics only
    beyond their measured ulp-tie envelope."""
    sg = _load_gate()
    baseline = {
        "nodefense_clean": {
            "final_accuracy": {"value": 80.4, "band": 0.0}},
        "krum_alie05": {
            "final_accuracy": {"value": 48.2, "band": 3.0},
            "malicious_share": {"value": 1.0, "band": 0.1}},
    }
    clean = {
        "nodefense_clean": {
            "final_accuracy": {"value": 80.4, "band": 0.0}},
        "krum_alie05": {
            "final_accuracy": {"value": 49.0, "band": 3.0},   # in band
            "malicious_share": {"value": 1.0, "band": 0.1}},
    }
    assert sg.diff(baseline, clean) == []
    # z drifting (say 0.5 -> 0.9) moves the Krum capture cell beyond
    # its band and flips the exact NoDefense cell by a hair: BOTH are
    # named.
    perturbed = {
        "nodefense_clean": {
            "final_accuracy": {"value": 80.5, "band": 0.0}},
        "krum_alie05": {
            "final_accuracy": {"value": 40.1, "band": 3.0},
            "malicious_share": {"value": 0.4, "band": 0.1}},
    }
    problems = sg.diff(baseline, perturbed)
    assert any(p.startswith("nodefense_clean.final_accuracy")
               and "exact-match" in p for p in problems)
    assert any(p.startswith("krum_alie05.final_accuracy") for p in problems)
    assert any(p.startswith("krum_alie05.malicious_share")
               and "band" in p for p in problems)
    # Vanished cells/metrics are drifts, not silence.
    assert sg.diff(baseline, {"nodefense_clean": {}}) != []


def test_science_gate_real_constant_drift_is_named():
    """The real failure mode against the REAL baseline: the ALIE z
    constant drifting 0.5 -> 1.5 (the checked-in krum_alie15 cell's
    measurements presented as krum_alie05) trips every
    selection-concentration metric by far more than its band, each
    named cell.metric."""
    sg = _load_gate()
    base = json.load(open(sg.BASELINE))["cells"]
    problems = sg.diff({"krum_alie05": base["krum_alie05"]},
                       {"krum_alie05": base["krum_alie15"]})
    assert problems
    assert all(p.startswith("krum_alie05.") for p in problems)
    named = {p.split(":")[0] for p in problems}
    assert "krum_alie05.final_accuracy" in named
    assert "krum_alie05.malicious_share" in named


def test_science_gate_env_mismatch_skips_loudly(tmp_path, capsys):
    sg = _load_gate()
    baseline = {"env": {"jax": "9.9.9", "jaxlib": "9.9.9",
                        "platform": "cpu"},
                "rounds": 10, "cells": {}}
    path = tmp_path / "bb.json"
    path.write_text(json.dumps(baseline))
    assert sg.main(["--baseline", str(path),
                    "--cells", "nodefense_clean"]) == 0
    out = capsys.readouterr().out
    assert "SKIP science_gate" in out and "environment mismatch" in out
    assert sg.main(["--baseline", str(path), "--strict-env",
                    "--cells", "nodefense_clean"]) == 1
    out = capsys.readouterr().out
    assert "FAIL science_gate" in out


def test_science_gate_missing_baseline_exit_2(tmp_path):
    sg = _load_gate()
    assert sg.main(["--baseline", str(tmp_path / "none.json")]) == 2


def test_science_gate_checked_in_baseline_shape():
    """The checked-in baseline carries provenance + the pinned cells
    with per-metric bands (the measured-band policy is part of the
    artifact, not just the tool)."""
    sg = _load_gate()
    base = json.load(open(sg.BASELINE))
    assert {"env", "rounds", "generated", "policy", "cells"} <= set(base)
    assert set(base["cells"]) == set(sg.CELLS)
    for cell, metrics in base["cells"].items():
        for m, rec in metrics.items():
            assert {"value", "band"} <= set(rec), (cell, m)
    # The selection-mediated cells carry bands; the clean mean cell is
    # exact.
    assert base["cells"]["nodefense_clean"]["final_accuracy"]["band"] == 0.0
    assert base["cells"]["krum_alie05"]["final_accuracy"]["band"] > 0.0


# ---------------------------------------------------------------------------
# report.py over mixed-version + torn logs (one invocation)

def test_report_mixed_version_and_torn_logs(tmp_path, capsys):
    from attacking_federate_learning_tpu import report

    v1 = tmp_path / "v1.jsonl"
    with open(v1, "w") as f:
        f.write(json.dumps({"kind": "eval", "round": 0, "test_loss": 0.5,
                            "accuracy": 50.0, "correct": 32,
                            "test_size": 64, "v": 1}) + "\n")
        f.write(json.dumps({"kind": "round", "round": 0,
                            "grad_norm_mean": 1.0, "v": 1}) + "\n")
    v3 = tmp_path / "v3.jsonl"
    with open(v3, "w") as f:
        f.write(json.dumps({"kind": "lifecycle", "phase": "start",
                            "attempt": 1, "v": 3}) + "\n")
        f.write(json.dumps({"kind": "heartbeat", "rss_mb": 10.0,
                            "last_event_age_s": 0.5, "v": 2}) + "\n")
        f.write(json.dumps({"kind": "eval", "round": 5, "test_loss": 0.1,
                            "accuracy": 90.0, "correct": 58,
                            "test_size": 64, "v": 3}) + "\n")
    torn = tmp_path / "torn.jsonl"
    with open(torn, "w") as f:
        f.write(json.dumps({"kind": "eval", "round": 0, "test_loss": 0.2,
                            "accuracy": 75.0, "correct": 48,
                            "test_size": 64, "v": 4}) + "\n")
        f.write('{"kind": "eval", "round": 5, "acc')       # SIGKILL here
    rc = report.main([str(v1), str(v3), str(torn), "--skip-bad",
                      "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out[str(v1)]["accuracy"]["final"] == 50.0
    assert out[str(v3)]["lifecycle"]["last_phase"] == "start"
    assert out[str(v3)]["heartbeat"]["beats"] == 1
    assert out[str(torn)]["accuracy"]["final"] == 75.0
    assert out[str(torn)]["bad_lines"] == 1
    # Without --skip-bad the torn log still fails loudly (the default
    # contract is unchanged).
    with pytest.raises(ValueError, match="not JSON"):
        report.main([str(torn)])
    # Human-readable path mentions the skip.
    assert report.main([str(torn), "--skip-bad"]) == 0
    assert "torn/invalid line(s) skipped" in capsys.readouterr().out
