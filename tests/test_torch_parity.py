"""Cross-framework parity: the wire format and forward math must agree with
a torch reconstruction of the reference architectures.

The reference's entire data flow runs through flat parameter vectors of
torch nets (reference user.py:17-28, data_sets.py:13-61).  Here we build the
same architectures in torch (CPU), push ONE flat vector into both
frameworks, and require the forward outputs to agree — proving a vector
produced by the reference loads into this framework unchanged (and vice
versa).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from attacking_federate_learning_tpu.models import get_model  # noqa: E402
from attacking_federate_learning_tpu.utils.flatten import (  # noqa: E402
    make_flattener
)


def load_flat_into_torch(flat_vec, torch_params):
    """The reference's row_into_parameters semantics (user.py:21-28)."""
    offset = 0
    for p in torch_params:
        size = int(np.prod(p.shape))
        chunk = flat_vec[offset: offset + size].reshape(tuple(p.shape))
        with torch.no_grad():
            p.copy_(torch.from_numpy(np.ascontiguousarray(chunk)))
        offset += size
    assert offset == len(flat_vec)


def build_torch_mnist():
    import torch.nn as nn
    import torch.nn.functional as F

    class Net(nn.Module):
        # Same architecture as reference MnistNet (data_sets.py:13-23).
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(28 * 28, 100)
            self.fc2 = nn.Linear(100, 10)

        def forward(self, x):
            return F.log_softmax(self.fc2(F.relu(self.fc1(x))), dim=1)

    return Net()


def build_torch_cifar10():
    import torch.nn as nn
    import torch.nn.functional as F

    class Net(nn.Module):
        # Same architecture as reference Cifar10Net (data_sets.py:33-52).
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 16, 3)
            self.pool1 = nn.MaxPool2d(3)
            self.conv2 = nn.Conv2d(16, 64, 4)
            self.pool2 = nn.MaxPool2d(4)
            self.fc1 = nn.Linear(64, 384)
            self.fc2 = nn.Linear(384, 192)
            self.fc3 = nn.Linear(192, 10)

        def forward(self, x):
            x = self.pool1(F.relu(self.conv1(x)))
            x = self.pool2(F.relu(self.conv2(x)))
            x = x.view(x.size(0), -1)
            x = F.relu(self.fc1(x))
            x = F.relu(self.fc2(x))
            return F.log_softmax(self.fc3(x), dim=1)

    return Net()


@pytest.mark.parametrize("name,builder,in_shape", [
    ("mnist_mlp", build_torch_mnist, (4, 784)),
    ("cifar10_cnn", build_torch_cifar10, (4, 3, 32, 32)),
])
def test_same_flat_vector_same_forward(name, builder, in_shape):
    model = get_model(name)
    params = model.init(jax.random.key(0))
    flat = make_flattener(params)
    vec = np.asarray(flat.ravel(params))

    tnet = builder()
    load_flat_into_torch(vec, tnet.parameters())

    rng = np.random.default_rng(0)
    x = rng.standard_normal(in_shape).astype(np.float32)

    ours = np.asarray(model.apply(flat.unravel(jnp.asarray(vec)), jnp.asarray(x)))
    with torch.no_grad():
        theirs = tnet(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-5, rtol=1e-4)


def test_torch_flat_vector_roundtrips_through_wire():
    """A torch-initialized net's flat vector (reference flatten_params,
    user.py:17-18) loads into our model and returns identical params."""
    tnet = build_torch_mnist()
    vec = np.concatenate([p.detach().numpy().ravel()
                          for p in tnet.parameters()])
    model = get_model("mnist_mlp")
    flat = make_flattener(model.init(jax.random.key(1)))
    params = flat.unravel(jnp.asarray(vec))
    np.testing.assert_array_equal(
        np.asarray(params["fc1"]["weight"]),
        tnet.fc1.weight.detach().numpy())
    np.testing.assert_array_equal(
        np.asarray(params["fc2"]["bias"]),
        tnet.fc2.bias.detach().numpy())


def test_import_reference_checkpoint(tmp_path):
    """A reference-produced checkpoint.pth.tar (torch.save of
    {'epoch','state_dict','acc'}, reference server.py:40-48) imports into
    our ServerState with forward parity."""
    from attacking_federate_learning_tpu.utils.checkpoint import (
        import_reference_checkpoint
    )

    tnet = build_torch_mnist()
    path = tmp_path / "checkpoint.pth.tar"
    torch.save({"epoch": 42, "state_dict": tnet.state_dict(), "acc": 87.5},
               str(path))

    model = get_model("mnist_mlp")
    flat = make_flattener(model.init(jax.random.key(0)))
    state, acc = import_reference_checkpoint(str(path),
                                             expected_dim=flat.dim)
    assert acc == 87.5
    assert int(state.round) == 42
    assert np.all(np.asarray(state.velocity) == 0)  # reference never saves it

    rng = np.random.default_rng(5)
    x = rng.standard_normal((4, 784)).astype(np.float32)
    ours = np.asarray(model.apply(flat.unravel(state.weights),
                                  jnp.asarray(x)))
    with torch.no_grad():
        theirs = tnet(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-5, rtol=1e-4)


def test_import_reference_checkpoint_dim_mismatch(tmp_path):
    from attacking_federate_learning_tpu.utils.checkpoint import (
        import_reference_checkpoint
    )

    tnet = build_torch_mnist()
    path = tmp_path / "checkpoint.pth.tar"
    torch.save({"epoch": 1, "state_dict": tnet.state_dict(), "acc": 0.0},
               str(path))
    with pytest.raises(ValueError, match="parameters"):
        import_reference_checkpoint(str(path), expected_dim=123)


def test_cli_resume_from_reference_checkpoint(tmp_path):
    """--resume <checkpoint.pth.tar> routes through the importer and
    continues training from the imported round."""
    from attacking_federate_learning_tpu import cli

    tnet = build_torch_mnist()
    path = tmp_path / "checkpoint.pth.tar"
    torch.save({"epoch": 2, "state_dict": tnet.state_dict(), "acc": 10.0},
               str(path))
    result = cli.main(["-s", "SYNTH_MNIST", "-e", "4", "-c", "16", "-n", "6",
                       "-m", "0.0", "--synth-train", "256",
                       "--synth-test", "64",
                       "--log-dir", str(tmp_path / "logs"),
                       "--run-dir", str(tmp_path / "runs"),
                       "--resume", str(path)])
    assert result["epochs"][-1] == 3  # continued from round 2


def build_torch_mnist_cnn():
    import torch.nn as nn
    import torch.nn.functional as F

    class Net(nn.Module):
        # Same architecture as models/mnist_cnn.py (classic torch MNIST
        # example shape).
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(1, 10, 5)
            self.conv2 = nn.Conv2d(10, 20, 5)
            self.fc1 = nn.Linear(320, 50)
            self.fc2 = nn.Linear(50, 10)

        def forward(self, x):
            x = F.max_pool2d(F.relu(self.conv1(x)), 2)
            x = F.max_pool2d(F.relu(self.conv2(x)), 2)
            x = x.view(x.size(0), -1)
            x = F.relu(self.fc1(x))
            return F.log_softmax(self.fc2(x), dim=1)

    return Net()


def test_mnist_cnn_torch_parity():
    model = get_model("mnist_cnn")
    params = model.init(jax.random.key(0))
    flat = make_flattener(params)
    assert flat.dim == 21840
    vec = np.asarray(flat.ravel(params))

    tnet = build_torch_mnist_cnn()
    load_flat_into_torch(vec, tnet.parameters())

    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 1, 28, 28)).astype(np.float32)
    ours = np.asarray(model.apply(flat.unravel(jnp.asarray(vec)),
                                  jnp.asarray(x)))
    with torch.no_grad():
        theirs = tnet(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-5, rtol=1e-4)
