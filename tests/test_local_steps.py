"""FedAvg-style local steps (beyond-reference: the reference is strictly
FedSGD, its client optimizer never steps — reference user.py:80)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import make_attacker
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.client import (
    make_client_update_fn, make_loss_fn
)
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.models.base import get_model
from attacking_federate_learning_tpu.utils.flatten import make_flattener


def _weights(rounds=3, **overrides):
    kw = dict(dataset=C.SYNTH_MNIST, users_count=8, mal_prop=0.25,
              batch_size=16, epochs=rounds, defense="TrimmedMean",
              num_std=1.0, synth_train=512, synth_test=64)
    kw.update(overrides)
    cfg = ExperimentConfig(**kw)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=kw["synth_train"],
                      synth_test=64)
    exp = FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                              dataset=ds)
    exp.run_span(0, rounds)
    return np.asarray(exp.state.weights)


def test_local_steps_one_is_reference_fedsgd():
    # The k=1 wrapper must be bit-identical to make_client_grad_fn (the
    # pre-existing reference-semantics path), not merely self-consistent.
    from attacking_federate_learning_tpu.core.client import (
        make_client_grad_fn
    )

    model = get_model("mnist_mlp")
    params = model.init(jax.random.key(1))
    flat = make_flattener(params)
    w = flat.ravel(params)
    rng = np.random.default_rng(2)
    xs = jnp.asarray(rng.standard_normal((4, 1, 8, 784)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, (4, 1, 8)).astype(np.int32))
    got = make_client_update_fn(model, flat, 1)(w, xs, ys, 0.07, 0.1)
    want = make_client_grad_fn(model, flat)(w, xs[:, 0], ys[:, 0])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_local_update_matches_manual_sgd():
    model = get_model("mnist_mlp")
    params = model.init(jax.random.key(0))
    flat = make_flattener(params)
    w0 = np.asarray(flat.ravel(params))
    loss = make_loss_fn(model, flat)
    grad = jax.grad(loss)

    rng = np.random.default_rng(0)
    n, k, B = 3, 4, 8
    xs = rng.standard_normal((n, k, B, 784)).astype(np.float32)
    ys = rng.integers(0, 10, (n, k, B)).astype(np.int32)
    lr = 0.05

    lr_report = 0.1   # the server's multiplier (constant-lr quirk)
    fn = make_client_update_fn(model, flat, local_steps=k)
    out = np.asarray(fn(jnp.asarray(w0), jnp.asarray(xs), jnp.asarray(ys),
                        lr, lr_report))

    for i in range(n):
        w = jnp.asarray(w0)
        for s in range(k):
            w = w - lr * grad(w, jnp.asarray(xs[i, s]),
                              jnp.asarray(ys[i, s]))
        pseudo = (w0 - np.asarray(w)) / lr_report
        np.testing.assert_allclose(out[i], pseudo, atol=1e-5, rtol=1e-5)


def test_local_steps_trains_and_interops_with_attack_defense():
    w1 = _weights(local_steps=1)
    w4 = _weights(local_steps=4)
    assert w4.shape == w1.shape
    assert np.all(np.isfinite(w4))
    assert not np.array_equal(w4, w1)


def test_local_steps_streaming_parity():
    kw = dict(local_steps=3)
    a = _weights(data_placement="host_stream", **kw)
    b = _weights(data_placement="device", **kw)
    np.testing.assert_array_equal(a, b)


def test_local_steps_converges_faster_per_round():
    # On the easy synth task, 4 local steps reach higher accuracy than 1
    # in the same (small) number of rounds.
    def acc(local_steps):
        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                               mal_prop=0.0, batch_size=16, epochs=3,
                               defense="NoDefense", local_steps=local_steps,
                               synth_train=512, synth_test=256)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=512,
                          synth_test=256)
        exp = FederatedExperiment(cfg, dataset=ds)
        exp.run_span(0, 3)
        _, correct = exp.evaluate(exp.state.weights)
        return float(correct)

    assert acc(4) > acc(1)


def test_local_steps_validated():
    with pytest.raises(ValueError, match="local_steps"):
        ExperimentConfig(dataset=C.SYNTH_MNIST, local_steps=0)


def test_local_steps_reduction_is_exact_under_server_lr():
    """FedAvg-as-FedSGD exactness: with k local steps, one server round
    (momentum 0, constant server lr) must land exactly on the weights a
    client would reach by k plain SGD steps at the faded lr — i.e. the
    lr_report divisor matches the server's multiplier."""
    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=1,
                           mal_prop=0.0, batch_size=8, epochs=1,
                           defense="NoDefense", local_steps=3, momentum=0.0,
                           synth_train=64, synth_test=32)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=64, synth_test=32)
    exp = FederatedExperiment(cfg, dataset=ds)
    w0 = np.asarray(exp.state.weights)

    # Manual: the single client's 3 local SGD steps at the faded lr.
    from attacking_federate_learning_tpu.core.server import (
        faded_learning_rate
    )
    loss = make_loss_fn(exp.model, exp.flat)
    grad = jax.grad(loss)
    xs, ys = exp._gather_batches(jnp.asarray(0, jnp.int32))
    xs = np.asarray(xs).reshape(1, 3, 8, *np.asarray(xs).shape[2:])
    ys = np.asarray(ys).reshape(1, 3, 8)
    lr = float(faded_learning_rate(cfg.learning_rate, cfg.fading_rate, 0))
    w = jnp.asarray(w0)
    for s in range(3):
        w = w - lr * grad(w, jnp.asarray(xs[0, s]), jnp.asarray(ys[0, s]))

    exp.run_round(0)
    np.testing.assert_allclose(np.asarray(exp.state.weights), np.asarray(w),
                               atol=1e-6, rtol=1e-6)


def test_cli_choices_match_registries():
    """Drift guard: the CLI's curated choice lists must cover exactly the
    registered defenses and attacks (grid.py derives from the registries;
    cli.py stays literal for import-weight reasons — this test keeps them
    in sync)."""
    from attacking_federate_learning_tpu import cli
    from attacking_federate_learning_tpu.attacks import ATTACKS
    from attacking_federate_learning_tpu.defenses import DEFENSES

    from attacking_federate_learning_tpu.models.base import MODELS

    parser = cli.build_parser()
    actions = {a.dest: a for a in parser._actions}
    assert set(actions["defense"].choices) == set(DEFENSES.names())
    assert set(actions["attack"].choices) == {"auto"} | set(ATTACKS.names())
    assert set(actions["model"].choices) == set(MODELS.names())


def test_remat_grads_identical():
    """jax.checkpoint must not change values — only the backward's memory
    schedule."""
    a = _weights(rounds=2, remat=True)
    b = _weights(rounds=2, remat=False)
    np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)
