"""Beyond-reference attacks (min-max/min-sum, NDSS'21) and defenses
(geometric median / RFA, norm bounding) — property tests + engine/CLI
integration."""

import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.attacks.minmax import (
    MinMaxAttack, MinSumAttack
)
from attacking_federate_learning_tpu.defenses.geomed import geometric_median
from attacking_federate_learning_tpu.defenses.normbound import (
    norm_bounded_mean
)


def grads_for(n, d, seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, d)).astype(np.float32)


# --------------------------------------------------------------------------
# min-max / min-sum
# --------------------------------------------------------------------------
def _max_pairwise_sq(G):
    sq = np.sum(G * G, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (G @ G.T)
    return float(np.maximum(d2, 0).max())


def test_minmax_respects_its_constraint_and_is_aggressive():
    G = grads_for(9, 50, seed=0)
    crafted = np.asarray(MinMaxAttack().craft(jnp.asarray(G)))
    budget = _max_pairwise_sq(G)
    worst = float(np.max(np.sum((G - crafted) ** 2, axis=1)))
    assert worst <= budget * (1 + 1e-4)          # constraint holds
    # gamma was actually pushed: crafted sits away from the plain mean
    mean = G.mean(axis=0)
    assert np.linalg.norm(crafted - mean) > 0.5 * np.sqrt(budget) / 2


def test_minsum_respects_its_constraint():
    G = grads_for(11, 40, seed=1)
    crafted = np.asarray(MinSumAttack().craft(jnp.asarray(G)))
    sq = np.sum(G * G, axis=1)
    d2 = np.maximum(sq[:, None] + sq[None, :] - 2.0 * (G @ G.T), 0)
    budget = float(d2.sum(axis=1).max())
    total = float(np.sum(np.sum((G - crafted) ** 2, axis=1)))
    assert total <= budget * (1 + 1e-4)


@pytest.mark.parametrize("cls", [MinMaxAttack, MinSumAttack])
def test_minmax_family_is_fusable_and_jits(cls):
    G = grads_for(8, 30, seed=2)
    atk = cls()
    assert getattr(atk, "fusable", True)
    out = jax.jit(atk.craft)(jnp.asarray(G))
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(atk.craft(jnp.asarray(G))),
                               atol=1e-5)


def test_minmax_gamma_is_near_tight():
    # The bisection should land gamma at the constraint boundary: growing
    # it a few percent must violate the budget.
    G = grads_for(10, 60, seed=3)
    atk = MinMaxAttack()
    Gj = jnp.asarray(G)
    mean = G.mean(axis=0)
    crafted = np.asarray(atk.craft(Gj))
    gamma_dir = crafted - mean
    budget = _max_pairwise_sq(G)
    pushed = mean + 1.05 * gamma_dir
    worst = float(np.max(np.sum((G - pushed) ** 2, axis=1)))
    assert worst > budget


# --------------------------------------------------------------------------
# geometric median
# --------------------------------------------------------------------------
def test_geomed_beats_mean_under_outlier():
    G = grads_for(12, 40, seed=4)
    G[0] = 1e4  # one wild outlier
    gm = np.asarray(geometric_median(jnp.asarray(G), 12, 1))
    mean = G.mean(axis=0)
    honest_center = G[1:].mean(axis=0)
    assert (np.linalg.norm(gm - honest_center)
            < np.linalg.norm(mean - honest_center) / 100)


def test_geomed_reduces_objective_vs_mean():
    G = grads_for(15, 30, seed=5)
    gm = np.asarray(geometric_median(jnp.asarray(G), 15, 3))

    def obj(z):
        return float(np.sum(np.linalg.norm(G - z, axis=1)))

    assert obj(gm) <= obj(G.mean(axis=0)) + 1e-4


def test_geomed_exact_on_collinear_points():
    # 1-D geometric median == the (coordinate) median.
    G = np.zeros((5, 3), np.float32)
    G[:, 0] = [0.0, 1.0, 2.0, 3.0, 100.0]
    gm = np.asarray(geometric_median(jnp.asarray(G), 5, 1, iters=200))
    assert abs(gm[0] - 2.0) < 0.05


# --------------------------------------------------------------------------
# norm bounding
# --------------------------------------------------------------------------
def test_normbound_caps_scaled_rows():
    G = grads_for(10, 25, seed=6)
    big = G.copy()
    big[0] *= 1e6                      # model-replacement-style scaling
    out_small = np.asarray(norm_bounded_mean(jnp.asarray(G), 10, 1))
    out_big = np.asarray(norm_bounded_mean(jnp.asarray(big), 10, 1))
    # The scaled row contributes only a direction, not 1e6x magnitude.
    assert np.linalg.norm(out_big - out_small) < np.linalg.norm(out_small)


def test_normbound_identity_when_norms_equal():
    G = grads_for(8, 16, seed=7)
    G = G / np.linalg.norm(G, axis=1, keepdims=True)  # equal norms
    out = np.asarray(norm_bounded_mean(jnp.asarray(G), 8, 1))
    np.testing.assert_allclose(out, G.mean(axis=0), atol=1e-6)


# --------------------------------------------------------------------------
# integration: registries, engine rounds, CLI choices
# --------------------------------------------------------------------------
@pytest.mark.parametrize("attack", ["minmax", "minsum"])
@pytest.mark.parametrize("defense", ["GeoMedian", "NormBound"])
def test_engine_round_with_extensions(attack, defense):
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                           mal_prop=0.25, batch_size=16, epochs=2,
                           defense=defense, synth_train=256, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(
        cfg, attacker=make_attacker(cfg, dataset=ds, name=attack),
        dataset=ds)
    exp.run_span(0, 2)   # fused span: the attacks must trace cleanly
    assert np.all(np.isfinite(np.asarray(exp.state.weights)))


def test_cli_accepts_extension_choices(tmp_path):
    from attacking_federate_learning_tpu import cli

    result = cli.main(["-s", "SYNTH_MNIST", "-e", "2", "-c", "16",
                       "-n", "8", "-m", "0.25", "-d", "GeoMedian",
                       "--attack", "minmax",
                       "--synth-train", "256", "--synth-test", "64",
                       "--log-dir", str(tmp_path / "logs"),
                       "--run-dir", str(tmp_path / "runs")])
    assert len(result["accuracies"]) >= 1


# --------------------------------------------------------------------------
# DnC (spectral filtering, NDSS'21)
# --------------------------------------------------------------------------
def test_dnc_filters_spectral_outliers():
    from attacking_federate_learning_tpu.defenses.dnc import dnc

    rng = np.random.default_rng(0)
    n, d, f = 20, 4096, 4
    G = rng.standard_normal((n, d)).astype(np.float32)
    direction = rng.standard_normal(d).astype(np.float32)
    # The planted collusion must clear the random-matrix noise floor of
    # the sketch (top singular value ~ sqrt(r) ~ 45) to be spectrally
    # identifiable — same condition the DnC paper's threat model assumes.
    G[:f] += 100.0 * direction / np.linalg.norm(direction)
    agg, diag = dnc(jnp.asarray(G), n, f, telemetry=True)
    out = np.asarray(agg)
    w = np.asarray(diag["survivor_mask"])
    honest_mean = G[f:].mean(axis=0)
    # f64-adjudicated (ISSUE 20, utils/numerics.py): this is NOT a
    # floating-point near-tie — every per-iteration removal boundary
    # gap measures >= 4e5 f32 ulp on this cohort (decisively outside
    # TIE_BAND_ULPS) and the f32 aggregate matches the f64
    # recomputation of the same survivor mean to ulps.  The filtering
    # claim is therefore asserted directly: no colluder survives any
    # iteration's spectral cut.
    assert not w[:f].any(), "a colluder survived the spectral filter"
    assert w.sum() > 0
    # The residual against the full honest mean is honest-subset
    # jitter, not malicious mass: with k of (n - f) iid N(0,1) honest
    # survivors its expected norm is sqrt(d * (1/k - 1/(n-f)))
    # (~12.3 at the measured k=10), which the old 0.5 *
    # ||full - honest|| threshold (10.6) undershot.  1.5x the
    # predicted jitter bounds it with slack while still failing if any
    # malicious mass (norm ~100) leaks into the aggregate.
    k = int(w.sum())
    jitter = math.sqrt(d * max(1.0 / k - 1.0 / (n - f), 0.0))
    assert np.linalg.norm(out - honest_mean) <= 1.5 * jitter, (
        f"DnC residual {np.linalg.norm(out - honest_mean):.2f} exceeds "
        f"1.5x the k={k} honest-survivor jitter {jitter:.2f}")
    # And the aggregate IS the survivor mean: the f32 reduction sits
    # within the tie band of the f64 referee when banded at the
    # aggregate's own largest magnitude (the tie_proximity convention
    # — per-coordinate ulp counts are meaningless at the near-zero
    # coordinates of a centered mean; measured 1.07 ulp-at-scale
    # here).
    from attacking_federate_learning_tpu.utils.numerics import (
        TIE_BAND_ULPS
    )
    ref64 = G[w > 0].astype(np.float64).mean(axis=0)
    band = TIE_BAND_ULPS * (2.0 ** -23) * float(np.max(np.abs(ref64)))
    worst = float(np.max(np.abs(out - ref64)))
    assert worst <= band, (
        f"aggregate is {worst:.3e} from the f64 survivor mean — "
        f"outside the {TIE_BAND_ULPS}-ulp-at-scale band {band:.3e}")


def test_dnc_zero_f_is_exact_mean():
    from attacking_federate_learning_tpu.defenses.dnc import dnc

    rng = np.random.default_rng(1)
    G = rng.standard_normal((16, 1024)).astype(np.float32)
    out = np.asarray(dnc(jnp.asarray(G), 16, 0))   # remove = 0
    np.testing.assert_allclose(out, G.mean(axis=0), atol=1e-5)


def test_dnc_under_jit_and_engine():
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=12,
                           mal_prop=0.25, batch_size=16, epochs=2,
                           defense="DnC", synth_train=256, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(
        cfg, attacker=make_attacker(cfg, dataset=ds, name="minmax"),
        dataset=ds)
    exp.run_span(0, 2)
    assert np.all(np.isfinite(np.asarray(exp.state.weights)))


def test_dnc_config_knobs_reach_the_kernel():
    """dnc_iters/dnc_sketch_dim/dnc_filter_frac are config surface wired
    through the registry partial, and cfg.seed drives the sketch keys
    (VERDICT r2 #9 + advisor: no more hard-coded seed=0)."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    def agg_for(seed, **knobs):
        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=12,
                               mal_prop=0.25, batch_size=16, epochs=1,
                               defense="DnC", seed=seed, synth_train=256,
                               synth_test=64, **knobs)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=256,
                          synth_test=64)
        exp = FederatedExperiment(cfg, dataset=ds)
        kw = exp.defense_fn.keywords
        assert kw["n_iters"] == cfg.dnc_iters
        assert kw["sketch_dim"] == cfg.dnc_sketch_dim
        assert kw["filter_frac"] == cfg.dnc_filter_frac
        assert kw["seed"] == seed
        assert getattr(exp.defense_fn, "needs_round", False)
        rng = np.random.default_rng(7)
        G = jnp.asarray(rng.standard_normal((12, 4096)).astype(np.float32))
        return np.asarray(exp.defense_fn(G, 12, 3, round=0))

    base = agg_for(0, dnc_sketch_dim=512)
    # Same config, same seed -> reproducible; different seed -> different
    # sketch subsets (d > sketch_dim so the subsets actually differ).
    np.testing.assert_array_equal(base, agg_for(0, dnc_sketch_dim=512))
    assert not np.array_equal(base, agg_for(1, dnc_sketch_dim=512))
    # Non-default iteration count changes the keep-set intersection.
    agg_for(0, dnc_iters=2, dnc_sketch_dim=512, dnc_filter_frac=1.0)

    with pytest.raises(ValueError):
        from attacking_federate_learning_tpu.config import (
            ExperimentConfig as EC
        )
        EC(dnc_filter_frac=0.0)


def test_geomed_config_knobs_reach_the_kernel():
    """geomed_iters/geomed_eps are config surface wired through the
    registry partial (VERDICT r3 #7 — the DnC config-surface standard)."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    def agg_for(**knobs):
        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                               mal_prop=0.25, batch_size=16, epochs=1,
                               defense="GeoMedian", synth_train=256,
                               synth_test=64, **knobs)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=256,
                          synth_test=64)
        exp = FederatedExperiment(cfg, dataset=ds)
        assert exp.defense_fn.keywords["iters"] == cfg.geomed_iters
        assert exp.defense_fn.keywords["eps"] == cfg.geomed_eps
        rng = np.random.default_rng(3)
        G = jnp.asarray(rng.standard_normal((8, 64)).astype(np.float32))
        G = G.at[0].set(1e4)   # outlier so iteration count matters
        return np.asarray(exp.defense_fn(G, 8, 2))

    base = agg_for()
    np.testing.assert_array_equal(base, agg_for())
    # One Weiszfeld step from the mean is still outlier-dragged; the
    # default 10 steps must land measurably closer to the honest mass.
    assert not np.allclose(base, agg_for(geomed_iters=1))
    # eps large enough to flatten the weights degenerates toward the mean.
    assert not np.allclose(base, agg_for(geomed_eps=1e6))
    with pytest.raises(ValueError):
        ExperimentConfig(defense="GeoMedian", geomed_iters=0)


def test_attack_direction_is_reachable():
    """--attack-direction reaches MinMax/MinSum (advisor: previously dead
    surface)."""
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig

    cfg = ExperimentConfig(attack_direction="sign")
    atk = make_attacker(cfg, name="minmax")
    assert atk.direction == "sign"
    G = grads_for(9, 40, seed=8)
    crafted = np.asarray(atk.craft(jnp.asarray(G)))
    default = np.asarray(MinMaxAttack().craft(jnp.asarray(G)))
    assert not np.allclose(crafted, default)
    with pytest.raises(ValueError):
        ExperimentConfig(attack_direction="bogus")


def test_dnc_fresh_sketches_per_round_and_fallback():
    from attacking_federate_learning_tpu.defenses.dnc import dnc

    rng = np.random.default_rng(2)
    # d > sketch_dim so rounds actually draw different coordinate subsets.
    G = jnp.asarray(rng.standard_normal((10, 4096)).astype(np.float32))
    a = np.asarray(dnc(G, 10, 2, round=0))
    b = np.asarray(dnc(G, 10, 2, round=1))
    assert not np.array_equal(a, b)          # fresh sketch per round
    np.testing.assert_array_equal(a, np.asarray(dnc(G, 10, 2, round=0)))

    # Small cohorts can empty the intersection of keep sets; the
    # aggregate must fall back to the overall mean, never a zero update.
    for seed in range(6):
        H = jnp.asarray(np.random.default_rng(seed)
                        .standard_normal((8, 4096)).astype(np.float32))
        out = np.asarray(dnc(H, 8, 3, round=seed))
        assert np.isfinite(out).all()
        assert np.linalg.norm(out) > 0.01    # not the silent zero update


def test_trimmed_mean_host_impl_matches_xla():
    """trimmed_mean_impl='host' is opt-in config surface: the engine
    wires the partial, the host/native kernel agrees with the XLA kernel
    within summation-order tolerance, and the default stays 'xla' (the
    staged/fused bit-identity invariant depends on it)."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.defenses.kernels import (
        trimmed_mean
    )

    rng = np.random.default_rng(0)
    G = jnp.asarray(rng.standard_normal((24, 4096)).astype(np.float32))
    via_xla = np.asarray(trimmed_mean(G, 24, 5))
    via_host = np.asarray(trimmed_mean(G, 24, 5, impl="host"))
    np.testing.assert_allclose(via_host, via_xla, rtol=1e-5, atol=1e-6)
    # Inside a jit the host impl goes through pure_callback.
    via_host_jit = np.asarray(
        jax.jit(lambda g: trimmed_mean(g, 24, 5, impl="host"))(G))
    np.testing.assert_allclose(via_host_jit, via_host, rtol=0, atol=0)

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                           mal_prop=0.25, batch_size=16, epochs=1,
                           defense="TrimmedMean",
                           trimmed_mean_impl="host",
                           synth_train=256, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, dataset=ds)
    assert exp.defense_fn.keywords["impl"] == "host"
    exp.run_span(0, 1)
    assert np.isfinite(np.asarray(exp.state.weights)).all()

    default_cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                                   mal_prop=0.25, defense="TrimmedMean",
                                   synth_train=256, synth_test=64)
    assert default_cfg.trimmed_mean_impl == "xla"
    with pytest.raises(ValueError):
        ExperimentConfig(trimmed_mean_impl="native")


def test_median_host_impl_matches_xla():
    """median_impl='host' mirrors the TrimmedMean opt-in: native kernel
    parity with jnp.median, engine wiring, xla default."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.defenses.median import median

    rng = np.random.default_rng(1)
    for n in (7, 24):  # odd (middle element) and even (mean of mids)
        G = jnp.asarray(rng.standard_normal((n, 4096)).astype(np.float32))
        via_xla = np.asarray(median(G, n, 2))
        via_host = np.asarray(median(G, n, 2, impl="host"))
        np.testing.assert_allclose(via_host, via_xla, rtol=1e-6,
                                   atol=1e-7)
        via_host_jit = np.asarray(
            jax.jit(lambda g, n=n: median(g, n, 2, impl="host"))(G))
        np.testing.assert_allclose(via_host_jit, via_host, rtol=0, atol=0)

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                           mal_prop=0.25, batch_size=16, epochs=1,
                           defense="Median", median_impl="host",
                           synth_train=256, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, dataset=ds)
    assert exp.defense_fn.keywords["impl"] == "host"
    exp.run_span(0, 1)
    assert np.isfinite(np.asarray(exp.state.weights)).all()
    assert ExperimentConfig(defense="Median").median_impl == "xla"
    with pytest.raises(ValueError):
        ExperimentConfig(median_impl="blas")
    # NaN inputs must fall back to np.median semantics (propagate NaN),
    # never reach the native kernel (nth_element on NaN is UB).
    Gn = np.ones((6, 8), np.float32)
    Gn[2, 3] = np.nan
    out = np.asarray(median(jnp.asarray(Gn), 6, 1, impl="host"))
    assert np.isnan(out[3]) and np.isfinite(np.delete(out, 3)).all()


# --------------------------------------------------------------------------
# ALIE paper z_max (num_std='auto', round 4)
# --------------------------------------------------------------------------
def test_paper_z_formula_and_degenerates():
    from statistics import NormalDist

    from attacking_federate_learning_tpu.attacks.alie import paper_z

    # n=50, f=12: s = 26-12 = 14 supporters, p = 24/38 -> z ~ 0.336.
    assert abs(paper_z(50, 12) - NormalDist().inv_cdf(24 / 38)) < 1e-12
    assert 0.30 < paper_z(50, 12) < 0.37
    # Small cohorts give tiny/zero hiding room (the paper's own curve):
    # n=10, f=2 -> s=4 of 8 honest -> p=0.5 -> z=0 exactly.
    assert paper_z(10, 2) == 0.0
    # Half-malicious cohorts still get headroom (s=1 supporter):
    # n=8, f=4 -> p = 3/4 -> z ~ 0.674.
    assert abs(paper_z(8, 4) - NormalDist().inv_cdf(0.75)) < 1e-12
    assert paper_z(4, 4) == 0.0                  # no honest workers
    assert 3.5 < paper_z(10, 9) < 4.0            # majority, capped quantile
    # p < 0.5 (no positive hiding room) clamps to 0, never negative —
    # a negative z would invert the backdoor clip envelope.
    assert paper_z(10, 1) == 0.0                 # p = 4/9 < 0.5
    for n in range(4, 60):
        for f in range(0, n // 2 + 1):
            assert paper_z(n, f) >= 0.0, (n, f)


def test_num_std_auto_resolves_in_config():
    from attacking_federate_learning_tpu.attacks.alie import paper_z
    from attacking_federate_learning_tpu.config import ExperimentConfig

    cfg = ExperimentConfig(users_count=50, mal_prop=0.24, num_std="auto")
    assert isinstance(cfg.num_std, float)
    assert cfg.num_std == paper_z(50, 12)
    # The CSV schema sees the resolved number, not the string.
    assert "auto" not in cfg.csv_name()
    with pytest.raises(ValueError):
        ExperimentConfig(num_std="bogus")


def test_num_std_auto_cli_surface():
    from attacking_federate_learning_tpu import cli

    args = cli.build_parser().parse_args(["-z", "auto"])
    assert args.num_std == "auto"
    args = cli.build_parser().parse_args(["-z", "1.25"])
    assert args.num_std == 1.25


# --------------------------------------------------------------------------
# CenteredClip (Karimireddy et al., ICML'21)
# --------------------------------------------------------------------------
def test_cclip_large_tau_is_exact_mean():
    from attacking_federate_learning_tpu.defenses.centeredclip import (
        centered_clip
    )

    G = grads_for(10, 32, seed=8)
    out = np.asarray(centered_clip(jnp.asarray(G), 10, 2, tau=1e9))
    np.testing.assert_allclose(out, G.mean(axis=0), atol=1e-5)


def test_cclip_bounds_outlier_influence():
    from attacking_federate_learning_tpu.defenses.centeredclip import (
        centered_clip
    )

    G = grads_for(12, 40, seed=9)
    G[0] = 1e4                      # unbounded Byzantine row
    out = np.asarray(centered_clip(jnp.asarray(G), 12, 1, tau=10.0,
                                   iters=5))
    honest_center = G[1:].mean(axis=0)
    mean = G.mean(axis=0)
    # The outlier can move the estimate by <= iters*tau/n total, vs the
    # plain mean's ~1e4*sqrt(d)/n displacement.
    assert np.linalg.norm(out - honest_center) <= 5 * 10.0 / 12 + 1.0
    assert (np.linalg.norm(out - honest_center)
            < np.linalg.norm(mean - honest_center) / 50)


def test_cclip_under_jit_and_engine():
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=12,
                           mal_prop=0.25, batch_size=16, epochs=2,
                           defense="CenteredClip", cclip_tau=5.0,
                           synth_train=256, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(
        cfg, attacker=make_attacker(cfg, dataset=ds, name="signflip"),
        dataset=ds)
    exp.run_round(0)
    exp.run_round(1)
    assert np.isfinite(np.asarray(exp.state.weights)).all()
    assert int(exp.state.round) == 2
