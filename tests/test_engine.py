"""Round-loop semantics: server update parity, attack seam, e2e smoke."""

import numpy as np
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack, NoAttack
from attacking_federate_learning_tpu.attacks.base import (
    AttackContext, cohort_stats
)
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.core.server import (
    faded_learning_rate, init_server_state, momentum_update
)


def small_cfg(**kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 10)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 6)
    kw.setdefault("test_step", 5)
    return ExperimentConfig(**kw)


def test_momentum_update_matches_reference_semantics():
    """v = mu*v - lr*g; w += v with constant base lr (reference
    server.py:89-90)."""
    d = 7
    state = init_server_state(jnp.arange(d, dtype=jnp.float32))
    g = jnp.ones((d,)) * 2.0
    s1 = momentum_update(state, g, learning_rate=0.1, momentum=0.9)
    np.testing.assert_allclose(np.asarray(s1.velocity), -0.2, atol=1e-6)
    np.testing.assert_allclose(np.asarray(s1.weights),
                               np.arange(d) - 0.2, atol=1e-6)
    s2 = momentum_update(s1, g, learning_rate=0.1, momentum=0.9)
    np.testing.assert_allclose(np.asarray(s2.velocity),
                               0.9 * -0.2 - 0.2, atol=1e-6)
    assert int(s2.round) == 2


def test_faded_lr():
    # lr * fr / (epoch + fr), reference server.py:50-52.
    assert np.isclose(float(faded_learning_rate(0.1, 10000.0, 0)), 0.1)
    assert np.isclose(float(faded_learning_rate(0.1, 10000.0, 10000)), 0.05)


def test_alie_craft_is_mean_minus_z_sigma():
    rng = np.random.default_rng(0)
    mal = jnp.asarray(rng.standard_normal((4, 11)).astype(np.float32))
    atk = DriftAttack(num_std=1.5)
    crafted = np.asarray(atk.craft(mal))
    mean = np.asarray(mal).mean(0)
    sigma = np.asarray(mal).std(0)  # population std, reference malicious.py:19
    np.testing.assert_allclose(crafted, mean - 1.5 * sigma, atol=1e-5)


def test_alie_apply_overwrites_first_f_rows_identically():
    rng = np.random.default_rng(1)
    G = jnp.asarray(rng.standard_normal((10, 5)).astype(np.float32))
    atk = DriftAttack(num_std=1.5)
    out = np.asarray(atk.apply(G, 3))
    # All malicious rows carry the same crafted vector (reference
    # malicious.py:26-27); honest rows untouched.
    assert np.array_equal(out[0], out[1]) and np.array_equal(out[1], out[2])
    np.testing.assert_array_equal(out[3:], np.asarray(G)[3:])


def test_alie_z_zero_is_noop():
    G = jnp.ones((6, 4))
    out = np.asarray(DriftAttack(num_std=0.0).apply(G, 2))
    np.testing.assert_array_equal(out, np.ones((6, 4)))


def test_e2e_accuracy_improves():
    cfg = small_cfg(epochs=11, mal_prop=0.0)
    exp = FederatedExperiment(cfg, attacker=NoAttack())
    test_size = len(exp.dataset.test_y)
    _, correct0 = exp.evaluate(exp.state.weights)
    for t in range(cfg.epochs):
        exp.run_round(t)
    _, correct1 = exp.evaluate(exp.state.weights)
    assert float(correct1) / test_size > float(correct0) / test_size + 0.2


@pytest.mark.parametrize("defense", ["NoDefense", "Krum", "TrimmedMean",
                                     "Bulyan"])
def test_e2e_each_defense_runs_under_attack(defense):
    # f=1 with n=10 satisfies every guard (Bulyan needs n >= 4f+3).
    cfg = small_cfg(defense=defense, mal_prop=0.1, epochs=3)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(cfg.num_std))
    for t in range(cfg.epochs):
        state = exp.run_round(t)
    w = np.asarray(state.weights)
    assert np.isfinite(w).all()
    assert int(state.round) == 3


def test_round_determinism():
    cfg = small_cfg(epochs=4, seed=42)
    w = []
    for _ in range(2):
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
        for t in range(cfg.epochs):
            exp.run_round(t)
        w.append(np.asarray(exp.state.weights))
    np.testing.assert_array_equal(w[0], w[1])


def test_attack_context_carries_faded_lr():
    seen = {}

    class Probe(DriftAttack):
        fusable = False  # run on host so the probe sees concrete values

        def craft(self, mal_grads, ctx: AttackContext = None):
            seen["lr"] = ctx.learning_rate
            return super().craft(mal_grads, ctx)

    cfg = small_cfg(epochs=1, mal_prop=0.3, fading_rate=100.0)
    exp = FederatedExperiment(cfg, attacker=Probe(1.5))
    exp.run_round(5)
    # lr * fr / (epoch + fr) at epoch 5 (reference server.py:50-52 reaches
    # the attacker via user 0's stash, user.py:84-86).
    assert np.isclose(float(seen["lr"]), 0.1 * 100.0 / 105.0)


def test_cohort_stats_population_sigma():
    x = jnp.asarray([[1.0, 2.0], [3.0, 6.0]])
    mean, std = cohort_stats(x)
    np.testing.assert_allclose(np.asarray(mean), [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(std), [1.0, 2.0])  # ddof=0


def test_metadata_collection():
    """Metadata subsystem (reference C12 user.py:63-66, server.py:62-77):
    stratified ~11% of each client's first batch, concatenated."""
    cfg = small_cfg(collect_metadata=True, users_count=5, batch_size=32)
    exp = FederatedExperiment(cfg, attacker=NoAttack())
    meta_x, meta_y = exp.get_metadata()
    # ~11% of 32 ~= 4 per client (stratified rounding may add a little).
    assert 5 * 2 <= len(meta_y) <= 5 * 10
    assert meta_x.shape[0] == meta_y.shape[0]
    assert meta_x.shape[1:] == exp.dataset.train_x.shape[1:]


def test_bf16_grad_dtype_runs():
    cfg = small_cfg(grad_dtype="bfloat16", epochs=2, mal_prop=0.2)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    for t in range(2):
        state = exp.run_round(t)
    assert np.isfinite(np.asarray(state.weights)).all()
    assert state.weights.dtype == np.float32  # server state stays f32


def test_fused_span_matches_per_round():
    """run_span (one scanned device program) must produce exactly the
    per-round loop's weights."""
    cfg = small_cfg(epochs=7, mal_prop=0.2, defense="TrimmedMean")
    a = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    for t in range(7):
        a.run_round(t)
    b = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    b.run_span(0, 7)
    np.testing.assert_array_equal(np.asarray(a.state.weights),
                                  np.asarray(b.state.weights))
    assert int(b.state.round) == 7


def test_run_uses_spans_with_same_eval_cadence():
    """engine.run with spans evaluates at the same rounds as the reference
    cadence (epoch % TEST_STEP == 0 or last, main.py:73)."""
    cfg = small_cfg(epochs=12, test_step=5, mal_prop=0.0)
    exp = FederatedExperiment(cfg, attacker=NoAttack())
    out = exp.run()
    assert out["epochs"] == [0, 5, 10, 11]


def test_baseline_attacks_run():
    from attacking_federate_learning_tpu.attacks import ATTACKS
    for name in ["signflip", "noise"]:
        cfg = small_cfg(epochs=2, mal_prop=0.3, defense="Median")
        atk = ATTACKS[name](cfg)
        exp = FederatedExperiment(cfg, attacker=atk)
        for t in range(2):
            state = exp.run_round(t)
        assert np.isfinite(np.asarray(state.weights)).all()


def test_median_defense_matches_numpy():
    from attacking_federate_learning_tpu.defenses import DEFENSES
    rng = np.random.default_rng(5)
    G = rng.standard_normal((9, 17)).astype(np.float32)
    out = np.asarray(DEFENSES["Median"](jnp.asarray(G), 9, 2))
    np.testing.assert_allclose(out, np.median(G, axis=0), atol=1e-6)


def test_backdoor_fused_equals_staged():
    """cfg.backdoor_fused folds the (pure, jitted) shadow-train pipeline
    into the round program; it must be bit-identical to the staged path
    (which keeps the reference's per-round host nan guard,
    backdoor.py:145-152)."""
    import numpy as np
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    def weights(fused):
        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                               mal_prop=0.25, batch_size=16, epochs=3,
                               defense="TrimmedMean", backdoor="pattern",
                               backdoor_fused=fused,
                               synth_train=512, synth_test=64)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=512,
                          synth_test=64)
        exp = FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                                  dataset=ds)
        exp.run_span(0, 3)
        return np.asarray(exp.state.weights)

    np.testing.assert_array_equal(weights(True), weights(False))


def test_fused_backdoor_nan_guard_fires():
    """A shadow-train nan must raise the reference's exact error
    (backdoor.py:146) from the fused path too — via the in-program
    crafted-rows isnan flag, not a blanket weights check."""
    import numpy as np
    import pytest
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                           mal_prop=0.25, batch_size=16, epochs=2,
                           defense="NoDefense", backdoor="pattern",
                           # absurd shadow lr -> shadow train overflows
                           mal_learning_rate=1e30,
                           synth_train=512, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=512, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                              dataset=ds)
    with pytest.raises(FloatingPointError, match="backdoor shadow"):
        exp.run_span(0, 2)
        # belt & braces: some overflows surface one span later
        exp.run_span(2, 2)


def test_fused_span_nan_leaves_recoverable_state():
    """When the fused span's nan guard fires, the engine restores the
    pre-span state before raising (the span donates its input, so without
    the snapshot the post-nan state would be all that's left — unlike the
    staged/reference path whose per-round raise leaves the last good
    round).  Catch-and-continue callers (benchmarks.py) rely on this."""
    import numpy as np
    import pytest
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                           mal_prop=0.25, batch_size=16, epochs=4,
                           defense="NoDefense", backdoor="pattern",
                           mal_learning_rate=1e30,  # shadow train overflows
                           synth_train=512, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=512, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                              dataset=ds)
    pre = np.asarray(exp.state.weights).copy()
    pre_round = int(exp.state.round)
    with pytest.raises(FloatingPointError, match="backdoor shadow"):
        exp.run_span(0, 4)
    np.testing.assert_array_equal(np.asarray(exp.state.weights), pre)
    assert int(exp.state.round) == pre_round
    assert np.isfinite(np.asarray(exp.state.weights)).all()


def test_round_stats_report_krum_selection():
    """Under Krum with --round-stats, the diagnostics carry the selected
    client index and a malicious-selected flag (reference
    krum(return_index=True), defences.py:39-40, promoted to telemetry)."""
    import numpy as np
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.defenses.kernels import krum_select

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=9,
                           mal_prop=0.22, batch_size=16, epochs=2,
                           defense="Krum", log_round_stats=True,
                           synth_train=256, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    exp.run_round(0)
    stats = exp.last_round_stats
    sel = int(stats["krum_selected"])
    assert 0 <= sel < 9
    assert int(stats["malicious_selected"]) == (1 if sel < exp.f else 0)

    # The reported index must be the actual Krum winner of the round's
    # (post-attack) gradient matrix — checked on round 1, whose input
    # weights are the current state.
    g1 = exp._compute_grads_impl(exp.state, 1)
    g1 = exp.attacker.apply(g1, exp.f, exp._ctx_for(exp.state, 1))
    want = int(krum_select(g1, 9, exp.f))
    exp.run_round(1)
    assert int(exp.last_round_stats["krum_selected"]) == want


def test_krum_selection_telemetry_matches_defense_impl():
    """The telemetry must use the defense's own distance engine: under
    distance_impl='allgather' (blockwise shard_map) the reported winner
    still matches the aggregated row."""
    import numpy as np
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=16,
                           mal_prop=0.2, batch_size=16, epochs=1,
                           defense="Krum", log_round_stats=True,
                           distance_impl="allgather", mesh_shape=(8, 1),
                           synth_train=256, synth_test=64)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    exp.run_round(0)
    sel = int(exp.last_round_stats["krum_selected"])
    assert 0 <= sel < 16


def test_krum_select_host_under_jit():
    """Explicit distance_impl='host' on a traced operand must route
    through the scalar-index pure_callback, not crash on np.asarray."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from attacking_federate_learning_tpu.defenses.kernels import (
        krum, krum_select
    )

    rng = np.random.default_rng(0)
    G = jnp.asarray(rng.standard_normal((9, 30)).astype(np.float32))
    fn = jax.jit(lambda g: krum_select(g, 9, 2, distance_impl="host"))
    want = int(krum_select(G, 9, 2, distance_impl="xla"))
    assert int(fn(G)) == want
    row = jax.jit(lambda g: krum(g, 9, 2, distance_impl="host"))(G)
    np.testing.assert_allclose(np.asarray(row), np.asarray(G[want]),
                               atol=0)


def test_fused_guard_catches_inf_not_just_nan():
    """The fused crafted-rows guard matches the staged path's isfinite
    check: an inf (no nan) crafted gradient must abort too."""
    import jax.numpy as jnp
    import numpy as np
    import pytest
    from attacking_federate_learning_tpu.attacks.base import Attack

    class InfAttack(Attack):
        checks_finite = True
        fusable = True
        name = "inf"

        def __init__(self):
            super().__init__(num_std=1.5)

        def craft(self, mal_grads, ctx=None):
            return jnp.full((mal_grads.shape[1],), jnp.inf)

    cfg = small_cfg(epochs=1, mal_prop=0.3, defense="NoDefense")
    exp = FederatedExperiment(cfg, attacker=InfAttack())
    with pytest.raises(FloatingPointError, match="backdoor shadow"):
        exp.run_round(0)


def test_staged_cpu_aggregation_uses_host_blas():
    """VERDICT r2 #8: staged rounds on the CPU backend aggregate eagerly,
    so distance_impl='auto' resolves to the zero-copy host BLAS kernel
    (defenses/host.py) instead of paying XLA:CPU's gemm penalty inside a
    jitted aggregate.  The two engines must agree on the training
    trajectory."""
    import jax
    import numpy as np
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    if jax.default_backend() != "cpu":
        import pytest
        pytest.skip("CPU-backend dispatch test")

    def run(distance_impl):
        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=9,
                               mal_prop=0.22, batch_size=16, epochs=2,
                               defense="Krum", backdoor="pattern",
                               backdoor_fused=False,  # staged seam
                               distance_impl=distance_impl,
                               synth_train=512, synth_test=64)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=512,
                          synth_test=64)
        exp = FederatedExperiment(
            cfg, attacker=make_attacker(cfg, dataset=ds), dataset=ds)
        assert exp._staged
        if distance_impl == "auto":
            # Eager aggregate (not a jitted wrapper).
            assert exp._aggregate == exp._aggregate_impl
        exp.run_round(0)
        exp.run_round(1)
        return np.asarray(exp.state.weights)

    w_auto = run("auto")   # eager -> host BLAS
    w_xla = run("xla")     # jitted XLA kernels
    # Krum selects a row (identical index either way); trajectories agree
    # to fp tolerance across the two distance engines.
    np.testing.assert_allclose(w_auto, w_xla, atol=1e-6)
