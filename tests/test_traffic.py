"""Population & traffic engine (ISSUE 17).

Acceptance contract: the legacy ``--participation`` draw routes through
core/population.py bit-compatibly; the traffic schedule is a pure
function of (TrafficConfig, seed, round) — deterministic across process
restarts, replayable on host, resume-exact; the registry never
materializes a population-sized tensor (structural O(1) pin + no dim-P
shape in the lowered span HLO); a forced validity-bound violation
completes through the declared degradation ladder with every decision
emitted as a v11 'traffic' event that diffs clean against
``replay_traffic``; and a SIGTERM-preempted traffic run resumes
bit-for-bit.
"""

import hashlib
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import (
    ExperimentConfig, TrafficConfig
)
from attacking_federate_learning_tpu.core import population as P
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
from attacking_federate_learning_tpu.utils.metrics import RunLogger


def _tcfg(**kw):
    kw.setdefault("population", 256)
    return TrafficConfig(**kw)


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 12)
    kw.setdefault("mal_prop", 0.2)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 10)
    kw.setdefault("test_step", 5)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("defense", "Krum")
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    return ExperimentConfig(**kw)


def _run(cfg, name, checkpointer=None):
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name=name) as logger:
        exp.run(logger, checkpointer=checkpointer)
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    return exp, events


def _traffic_events(events):
    return [e for e in events if e.get("kind") == "traffic"]


EVENT_KEYS = ("round", "arrived", "f_eff", "cohort", "action", "defense")


def _payload(e):
    return tuple(e[k] for k in EVENT_KEYS)


# ---------------------------------------------------------------------------
# satellite 1: the legacy --participation draw, relocated verbatim

def test_legacy_cohort_bit_compat():
    """population.legacy_cohort IS the pre-population inline draw from
    engine._participants — pinned against the original formula so the
    relocation can never drift (every pre-PR partial-participation
    trajectory depends on these exact ids)."""
    key = jax.random.key(1234)
    n, f, m, m_mal = 20, 4, 10, 2
    for t in (0, 3, 17):
        k1, k2 = jax.random.split(jax.random.fold_in(key, t))
        mal = jax.random.choice(k1, f, (m_mal,), replace=False)
        hon = f + jax.random.choice(k2, n - f, (m - m_mal,),
                                    replace=False)
        want = jnp.concatenate([mal, hon]).astype(jnp.int32)
        got = P.legacy_cohort(key, t, n, f, m, m_mal)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_engine_participants_route_through_population(tmp_path):
    """engine._participants delegates to population.legacy_cohort with
    the engine's own participation key (the single code path both the
    traced round and the streaming prefetcher share)."""
    cfg = _cfg(tmp_path, participation=0.5)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    for t in (0, 2, 7):
        want = P.legacy_cohort(exp._part_key, t, exp.n, exp.f, exp.m,
                               exp.m_mal)
        np.testing.assert_array_equal(np.asarray(exp._participants(t)),
                                      np.asarray(want))


# ---------------------------------------------------------------------------
# the defense-validity watchdog (host, schedule time)

def test_plan_action_ladder_bounds():
    """The declared ladder order on the published validity bounds:
    remask while m_eff >= bound(defense), else fallback while m_eff >=
    bound(fallback), else hold.  f is the kernel's STATIC corrupted
    count — the masked kernels trim f rows whatever arrived."""
    # Krum f=3: 2f+3 = 9; TrimmedMean fallback: 2f+1 = 7.
    pa = P.plan_action
    assert pa("Krum", "TrimmedMean", 9, 3, 1) == P.TRAFFIC_REMASK
    assert pa("Krum", "TrimmedMean", 8, 3, 1) == P.TRAFFIC_FALLBACK
    assert pa("Krum", "TrimmedMean", 7, 3, 1) == P.TRAFFIC_FALLBACK
    assert pa("Krum", "TrimmedMean", 6, 3, 1) == P.TRAFFIC_HOLD
    # Bulyan f=1: 4f+3 = 7; Median fallback: 2f+1 = 3.
    assert pa("Bulyan", "Median", 7, 1, 1) == P.TRAFFIC_REMASK
    assert pa("Bulyan", "Median", 6, 1, 1) == P.TRAFFIC_FALLBACK
    assert pa("Bulyan", "Median", 2, 1, 1) == P.TRAFFIC_HOLD
    # min_cohort floors every rung, including NoDefense.
    assert pa("NoDefense", "NoDefense", 3, 0, 1) == P.TRAFFIC_REMASK
    assert pa("NoDefense", "NoDefense", 3, 0, 4) == P.TRAFFIC_HOLD
    assert pa("Krum", "TrimmedMean", 8, 3, 8) == P.TRAFFIC_FALLBACK


def test_sybil_burst_window_and_fixed_average_f():
    """With the burst knob on, colluders arrive ONLY inside the window,
    boosted by period/width so their AVERAGE arrival mass matches the
    uniform profile — participation becomes an attack axis at fixed
    average f."""
    t = _tcfg(population=10_000, rate=0.2, reliability_lo=1.0,
              reliability_hi=1.0, churn_dwell=1, sybil_burst_period=4,
              sybil_burst_width=1)
    reg = P.PopulationRegistry(t, n=10, f=5, seed=3)
    pids = np.arange(2000)                 # colluders: pids < F = 5000
    per_round = [reg.available(pids, tt).mean() for tt in range(8)]
    for tt, frac in enumerate(per_round):
        if tt % 4 == 0:
            assert frac > 0.5              # in-window: boosted ~0.8
        else:
            assert frac == 0.0             # outside: silent
    avg = float(np.mean(per_round))
    # Uniform profile would arrive at rate*reliability = 0.2 per round.
    assert abs(avg - 0.2) < 0.05
    # The honest population is untouched by the sybil knob.
    hon = reg.available(reg.F + pids, 1).mean()
    assert abs(hon - 0.2) < 0.05


# ---------------------------------------------------------------------------
# the registry: lazy, deterministic, structurally O(1) in P

def test_registry_lazy_deterministic_million_clients():
    """P = 1,000,000 clients: the registry object holds scalars only
    (no attribute scales with P), per-client state is a pure function
    of (seed, pid), and two same-seed registries sample identical
    cohorts while different seeds diverge."""
    t = _tcfg(population=1_000_000)
    a = P.PopulationRegistry(t, n=16, f=3, seed=11)
    b = P.PopulationRegistry(t, n=16, f=3, seed=11)
    c = P.PopulationRegistry(t, n=16, f=3, seed=12)
    # Structural O(1): nothing on the object is population-sized.
    for reg in (a, b, c):
        for name, val in vars(reg).items():
            if isinstance(val, np.ndarray):
                assert val.size < 1024, (name, val.size)
    assert a.F == round(1_000_000 * 3 / 16)   # population mirrors f/n
    pids = np.array([0, a.F - 1, 999_999, a.F])
    sa, sb = a.client_state(pids), b.client_state(pids)
    for k in sa:
        np.testing.assert_array_equal(np.asarray(sa[k]),
                                      np.asarray(sb[k]))
    assert sa["malicious"].tolist() == [True, True, False, False]
    # Shard archetypes respect the rows-[0, f) attack invariant.
    assert (sa["shard"][sa["malicious"]] < 3).all()
    assert (sa["shard"][~sa["malicious"]] >= 3).all()
    for tt in (0, 5):
        ids_a, arr_a, p_a = a.sample_cohort(tt, 16, 3)
        ids_b, arr_b, p_b = b.sample_cohort(tt, 16, 3)
        np.testing.assert_array_equal(ids_a, ids_b)
        np.testing.assert_array_equal(arr_a, arr_b)
        np.testing.assert_array_equal(p_a, p_b)
        assert ids_a.shape == (16,) and arr_a.dtype == bool
    assert not np.array_equal(a.sample_cohort(0, 16, 3)[2],
                              c.sample_cohort(0, 16, 3)[2])


def test_schedule_deterministic_across_process_restart(tmp_path):
    """The whole span schedule (ids, arrivals, ladder actions) hashes
    identically when regenerated in a FRESH interpreter — the property
    that makes preempt/resume and host replay exact with no carried
    traffic state."""
    code = (
        "import hashlib, numpy as np\n"
        "from attacking_federate_learning_tpu.config import TrafficConfig\n"
        "from attacking_federate_learning_tpu.core import population as P\n"
        "t = TrafficConfig(population=500, rate=0.6, diurnal_amp=0.3,\n"
        "                  churn_dwell=3, sybil_burst_period=5)\n"
        "reg = P.PopulationRegistry(t, n=12, f=2, seed=7)\n"
        "s = P.traffic_schedule(reg, 0, 12, 12, 2, 'Krum', 'Median', 1)\n"
        "h = hashlib.sha256()\n"
        "for arr in (s.shard_ids, s.arrived.astype(np.int8), s.action):\n"
        "    h.update(np.ascontiguousarray(arr).tobytes())\n"
        "print(h.hexdigest())\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    out = [subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, check=True,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
           .stdout.strip() for _ in range(2)]
    assert out[0] == out[1]
    # And it matches THIS process's regeneration.
    import hashlib as _hl
    t = TrafficConfig(population=500, rate=0.6, diurnal_amp=0.3,
                      churn_dwell=3, sybil_burst_period=5)
    reg = P.PopulationRegistry(t, n=12, f=2, seed=7)
    s = P.traffic_schedule(reg, 0, 12, 12, 2, "Krum", "Median", 1)
    h = _hl.sha256()
    for arr in (s.shard_ids, s.arrived.astype(np.int8), s.action):
        h.update(np.ascontiguousarray(arr).tobytes())
    assert h.hexdigest() == out[0]


# ---------------------------------------------------------------------------
# the flat engine under traffic: events, ladder, HLO structure

def test_traffic_events_match_replay(tmp_path):
    """A 10-round churn run emits one v11 'traffic' event per round
    whose payload diffs IDENTICAL against the independent host
    regeneration (population.replay_traffic) — the fault_matrix-style
    replay audit."""
    cfg = _cfg(tmp_path, traffic=_tcfg(population=96, rate=0.7,
                                       churn_dwell=2, seed=9))
    exp, events = _run(cfg, "traffic_replay")
    got = sorted(_traffic_events(events), key=lambda e: e["round"])
    assert len(got) == 10
    want = P.replay_traffic(cfg, cfg.epochs)
    assert [_payload(e) for e in got] == [_payload(e) for e in want]
    assert all(e["v"] >= 11 for e in got)


def test_forced_underfill_completes_via_ladder(tmp_path):
    """Acceptance: a run whose cohort persistently under-fills the Krum
    validity bound COMPLETES (no raise) by walking the declared ladder,
    every decision is emitted and replay-exact, and a hold round is a
    true no-op (an all-hold schedule freezes the weights bit-for-bit)."""
    # Unreliable tiny population: arrivals routinely miss 2f+3.
    cfg = _cfg(tmp_path, epochs=8, traffic=_tcfg(
        population=16, rate=0.35, reliability_lo=0.3, reliability_hi=0.6,
        churn_dwell=2, fallback_defense="TrimmedMean", seed=5))
    exp, events = _run(cfg, "traffic_underfill")
    got = sorted(_traffic_events(events), key=lambda e: e["round"])
    assert len(got) == 8
    want = P.replay_traffic(cfg, cfg.epochs)
    assert [_payload(e) for e in got] == [_payload(e) for e in want]
    acts = {e["action"] for e in got}
    assert acts & {"fallback", "hold"}, acts   # the bound WAS violated
    # Degraded rounds aggregate with the defense the event names.
    for e in got:
        assert e["defense"] == {"remask": "Krum",
                                "fallback": "TrimmedMean",
                                "hold": "none"}[e["action"]]
    # All-hold schedule: min_cohort above the cohort size means no
    # round can ever satisfy the floor -> weights frozen bit-for-bit.
    cfg2 = _cfg(tmp_path, epochs=4, test_step=10, traffic=_tcfg(
        population=32, min_cohort=64))
    ds = load_dataset(cfg2.dataset, seed=0, synth_train=cfg2.synth_train,
                      synth_test=cfg2.synth_test)
    exp2 = FederatedExperiment(cfg2, attacker=DriftAttack(1.0),
                               dataset=ds)
    w0 = np.array(exp2.state.weights, copy=True)
    with RunLogger(cfg2, None, cfg2.log_dir,
                   jsonl_name="traffic_allhold") as logger:
        exp2.run(logger)
    np.testing.assert_array_equal(np.asarray(exp2.state.weights), w0)
    assert all(e["action"] == "hold"
               for e in P.replay_traffic(cfg2, cfg2.epochs))


def test_no_population_tensor_in_program(tmp_path):
    """Structural memory pin (the perf_gate --memproof analogue): with
    P = 1,000,000 registered clients the lowered traffic-span HLO
    carries cohort-sized operands only — no dimension anywhere in the
    program scales with P, and the schedule plan stays host-side
    numpy."""
    cfg = _cfg(tmp_path, traffic=_tcfg(population=1_000_000, seed=3))
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    assert exp._span_entry_name() == "traffic_span"
    hlo = exp._span_hlo_text(4)
    assert "1000000" not in hlo            # no dim-P shape compiled
    assert f"{4},{exp.m}" in hlo.replace(" ", "") or "4,12" in hlo
    sched = exp._traffic_plan(0, 4)
    assert sched.shard_ids.shape == (4, exp.m)
    assert (sched.shard_ids < exp.n).all()
    # Traffic OFF: the engine builds none of the machinery (the
    # byte-identity of the compiled programs is pinned end to end by
    # tools/perf_gate.py stageproof against PERF_BASELINE).
    cfg_off = _cfg(tmp_path)
    exp_off = FederatedExperiment(cfg_off, attacker=DriftAttack(1.0),
                                  dataset=ds)
    assert exp_off.traffic is None and exp_off.registry is None
    assert exp_off._traffic_span is None
    assert exp_off._span_entry_name() == "fused_span"


# ---------------------------------------------------------------------------
# preempt/resume: the stateless schedule makes resume free

def test_sigterm_preempt_resume_bit_for_bit_traffic(tmp_path):
    """SIGTERM at an arbitrary round under traffic: the restarted run
    finishes with final weights bit-for-bit equal to the uninterrupted
    run, the journal audits clean, and the stitched event stream
    carries every round's traffic event exactly once — possible only
    because the schedule is pure in (config, t) with NO carried state."""
    from attacking_federate_learning_tpu.utils.lifecycle import (
        GracefulShutdown, Preempted, RunJournal
    )

    kill_round = int(np.random.default_rng(17).integers(1, 9))
    tr = _tcfg(population=96, rate=0.7, churn_dwell=2, seed=9)

    def cfg_for(run_dir):
        return _cfg(tmp_path, traffic=tr, checkpoint_every=3,
                    run_dir=str(tmp_path / run_dir))

    cfg_ref = cfg_for("runs_ref")
    ds = load_dataset(cfg_ref.dataset, seed=0,
                      synth_train=cfg_ref.synth_train,
                      synth_test=cfg_ref.synth_test)
    full = FederatedExperiment(cfg_ref, attacker=DriftAttack(1.0),
                               dataset=ds)
    with RunLogger(cfg_ref, None, cfg_ref.log_dir,
                   jsonl_name="traf_full") as logger:
        full.run(logger, checkpointer=Checkpointer(cfg_ref))
    w_full = np.array(full.state.weights, copy=True)

    cfg = cfg_for("runs_sup")
    ck = Checkpointer(cfg)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="traf_sup") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "traf"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    resumed = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
    state, extra = ck.resume(ck.latest(), with_extra=True)
    resumed.state = state
    resumed.restore_fault_state(extra)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="traf_sup") as logger:
        resumed.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "traf"))

    np.testing.assert_array_equal(np.asarray(resumed.state.weights),
                                  w_full)
    assert RunJournal(cfg.run_dir, "traf").verify(
        epochs=10, test_step=5) == []
    # Exactly-once traffic events across the two attempts, replay-exact.
    with open(os.path.join(cfg.log_dir, "traf_sup.jsonl")) as f:
        ev = [json.loads(line) for line in f]
    got = sorted(_traffic_events(ev), key=lambda e: e["round"])
    assert [e["round"] for e in got] == list(range(10))
    want = P.replay_traffic(cfg, cfg.epochs)
    assert [_payload(e) for e in got] == [_payload(e) for e in want]


# ---------------------------------------------------------------------------
# async latency profile + hierarchical slot resampling

def test_async_latency_profile_deterministic():
    """The heavy-tail delay draw is pure in (key, t), lands inside the
    delivery ring, and the per-client scales come off the lazy
    registry — same config, same scales."""
    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=12,
                           mal_prop=0.2,
                           traffic=_tcfg(population=200, latency_scale=2.0,
                                         latency_tail=1.2, seed=4))
    scales, tail = P.async_latency_for_cfg(cfg, 12)
    scales2, _ = P.async_latency_for_cfg(cfg, 12)
    np.testing.assert_array_equal(np.asarray(scales),
                                  np.asarray(scales2))
    assert scales.shape == (12,) and (np.asarray(scales) > 0).all()
    assert tail == 1.2
    key = jax.random.key(0)
    for t in (0, 3):
        d1 = np.asarray(P.traffic_delays(key, t, scales, tail, 6))
        d2 = np.asarray(P.traffic_delays(key, t, scales, tail, 6))
        np.testing.assert_array_equal(d1, d2)
        assert d1.dtype == np.int32
        assert (d1 >= 0).all() and (d1 <= 5).all()
    assert not np.array_equal(
        np.asarray(P.traffic_delays(key, 0, scales, tail, 6)),
        np.asarray(P.traffic_delays(key, 1, scales, tail, 6)))


def test_hier_resample_slots_deterministic_and_invariant():
    """Per-megabatch slot resampling: pure in (key, t, ids[0]),
    malicious slots draw archetypes from [0, f), honest from [f, n) —
    the per-megabatch mirror of the rows-[0, c_mal) invariant."""
    key = jax.random.key(2)
    ids = jnp.arange(100, 108, dtype=jnp.int32)
    a = np.asarray(P.resample_slots(key, 4, ids, 2, 3, 16))
    b = np.asarray(P.resample_slots(key, 4, ids, 2, 3, 16))
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.int32 and a.shape == (8,)
    assert (a[:2] < 3).all() and (a[2:] >= 3).all() and (a < 16).all()
    c = np.asarray(P.resample_slots(key, 5, ids, 2, 3, 16))
    assert not np.array_equal(a, c)


# ---------------------------------------------------------------------------
# loud rejections (campaigns/spec.py pre-validates the same way)

def test_check_traffic_support_rejections(tmp_path):
    def make(**kw):
        kw.setdefault("traffic", _tcfg())
        return _cfg(tmp_path, **kw)

    with pytest.raises(ValueError, match="cover the cohort"):
        P.check_traffic_support(make(traffic=_tcfg(population=4)))
    with pytest.raises(ValueError, match="secagg"):
        P.check_traffic_support(make(secagg="vanilla",
                                     defense="TrimmedMean"))
    with pytest.raises(ValueError, match="host_stream|device"):
        P.check_traffic_support(make(data_placement="host_stream"))
    with pytest.raises(ValueError, match="mask-aware"):
        P.check_traffic_support(make(defense="GeoMedian"))
    with pytest.raises(ValueError, match="fallback"):
        P.check_traffic_support(
            make(traffic=_tcfg(fallback_defense="GeoMedian")))
    with pytest.raises(ValueError, match="host"):
        P.check_traffic_support(make(trimmed_mean_impl="host",
                                     defense="TrimmedMean"))
    with pytest.raises(ValueError, match="shard_map|SPMD|clients"):
        P.check_traffic_support(make(aggregation="hierarchical",
                                     megabatch=4, mesh_shape=(2, 1)))
    # The staged backdoor path has no arrival seam.
    with pytest.raises(ValueError, match="fused backdoor"):
        P.check_traffic_support(make(backdoor="pattern",
                                     backdoor_fused=False))
