"""Measured-walls observatory (ISSUE 16): utils/walls.py booking,
engine --profile-every wiring, schema-v10 wall events, the runs-walls
verb and the noise-banded wall gate.

Acceptance contract: the trace-to-HLO booking partitions exactly
(stage sums + unattributed == total, same floats) on all three engines
x two defenses over REAL profiler captures; FL_STAGE_SCOPES=0 books
everything to unattributed; profiling off leaves the round program's
HLO fingerprint-identical; ``runs walls`` renders single/diff/--json
and exits 1 on a walls-less run; and a --profile-every run's log
round-trips through validate_event at schema v10.

The real-capture tests run in SUBPROCESSES: op-level CPU trace events
need ``--xla_cpu_enable_xprof_traceme=true`` in XLA_FLAGS before the
process's FIRST compile, and this warm pytest process compiled long
ago (utils/profiling.py:ensure_op_profiling documents the seam).
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.utils import walls
from attacking_federate_learning_tpu.utils.costs import (
    STAGES, hlo_fingerprint, set_stage_scopes
)
from attacking_federate_learning_tpu.utils.metrics import (
    SCHEMA_VERSION, iter_events, validate_event
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _subproc_env():
    """Child env with the xprof op-trace flag live from process start
    (the child's first compile sees it; this process's cannot)."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "--xla_cpu_enable_xprof_traceme=true" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_cpu_enable_xprof_traceme=true").strip()
    return env


def _exp(**kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 9)
    kw.setdefault("mal_prop", 0.22)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 4)
    kw.setdefault("test_step", 4)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    cfg = ExperimentConfig(**kw)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    return FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)


# ---------------------------------------------------------------------------
# booking primitives (synthetic, no trace needed)

_HLO = """\
HloModule jit_round
ENTRY main {
  %dot.4 = f32[8,8]{1,0} dot(a, b), metadata={op_name="jit(round)/deliver/tier1_aggregate/dot" source_file="x"}
  %add.1 = f32[8]{0} add(c, d), metadata={op_name="jit(round)/deliver/add"}
  ROOT %mul.2 = f32[8]{0} multiply(e, f)
}
"""


def test_hlo_stage_map_innermost_token_rule():
    m = walls.hlo_stage_map(_HLO)
    # Innermost (LAST) taxonomy token wins, not the outer scope.
    assert m["dot.4"] == "tier1_aggregate"
    assert m["add.1"] == "deliver"
    # ROOT-prefixed instruction parsed; no op_name -> unattributed.
    assert m["mul.2"] is None


def test_book_events_exact_partition_and_coverage():
    stage_map = {"dot.4": "tier1_aggregate", "add.1": "deliver",
                 "mul.2": None}
    events = [
        {"ph": "X", "name": "dot.4", "dur": 100.5},
        {"ph": "X", "name": "dot.4", "dur": 0.25},      # repeats sum
        {"ph": "X", "name": "add.1", "dur": 7.0},
        {"ph": "X", "name": "mul.2", "dur": 3.5},       # unattributed
        {"ph": "X", "name": "TfrtCpuExecutable::Execute", "dur": 900.0},
        {"ph": "X", "name": "some_python_frame", "dur": 50.0},
    ]
    rec = walls.book_events(events, stage_map, name="fused_span")
    assert rec.stages == {"tier1_aggregate": 100.75, "deliver": 7.0}
    assert rec.unattributed_us == 3.5
    # The partition identity: same floats, not a tolerance.
    assert sum(rec.stages.values()) + rec.unattributed_us == rec.total_us
    rec.check()
    cov = rec.coverage
    assert cov["op_events"] == 4
    assert cov["runtime_us"] == 900.0       # classified, never booked
    assert cov["unknown_us"] == 50.0
    assert cov["booked_us"] == 111.25
    assert cov["op_time_fraction"] == pytest.approx(
        111.25 / (111.25 + 50.0), abs=1e-4)


def test_wall_event_validates_at_v10():
    rec = walls.book_events(
        [{"ph": "X", "name": "dot.4", "dur": 10.0}],
        {"dot.4": "tier1_aggregate"}, name="fused_span",
        platform="cpu", rounds=3)
    ev = rec.wall_event()
    ev["v"] = SCHEMA_VERSION
    ev["t"] = 0.0
    assert validate_event(ev) is ev
    # A v10 kind stamped with an older writer version is an emitter bug.
    ev_old = dict(ev, v=9)
    with pytest.raises(ValueError):
        validate_event(ev_old)


def test_measured_vs_modeled_shares_and_ratios():
    wall = {"stages": {"deliver": 300.0, "tier1_aggregate": 100.0},
            "unattributed_us": 0.0}
    cost = {"stages": {"deliver": {"flops": 100.0},
                       "tier1_aggregate": {"flops": 100.0}},
            "unattributed": {"flops": 0.0}}
    out = walls.measured_vs_modeled(wall, cost)
    assert out["deliver"]["measured_share"] == 0.75
    assert out["deliver"]["modeled_share"] == 0.5
    assert out["deliver"]["ratio"] == 1.5
    assert out["tier1_aggregate"]["ratio"] == 0.5
    # A stage with measured time but no modeled mass gets None, not 0.
    wall2 = {"stages": {"protect": 10.0}, "unattributed_us": 0.0}
    out2 = walls.measured_vs_modeled(wall2, cost)
    assert out2["protect"]["ratio"] is None


# ---------------------------------------------------------------------------
# scopes-off + fingerprint invariants (compiled programs, no trace)

def test_scopes_off_span_text_books_all_to_unattributed():
    prev = set_stage_scopes(False)
    try:
        exp = _exp(defense="Krum")
        text = exp._span_hlo_text(2)
    finally:
        set_stage_scopes(prev)
    smap = walls.hlo_stage_map(text)
    assert smap, "span HLO parsed no instructions"
    assert all(v is None for v in smap.values())
    # Booking a synthetic capture over those instructions lands 100%
    # in unattributed — scopes off degrades loudly, never invents.
    names = list(smap)[:5]
    rec = walls.book_events(
        [{"ph": "X", "name": n, "dur": 1.0} for n in names], smap)
    assert rec.stages == {}
    assert rec.unattributed_us == float(len(names))
    rec.check()


def test_profile_every_leaves_hlo_fingerprint_identical():
    off = _exp(defense="Krum", profile_every=0)
    on = _exp(defense="Krum", profile_every=2)
    f_off = hlo_fingerprint(off._span_hlo_text(3))
    f_on = hlo_fingerprint(on._span_hlo_text(3))
    assert f_off == f_on
    t0 = jnp.asarray(0, jnp.int32)
    r_off = off._fused_round.lower(off.state, t0).as_text()
    r_on = on._fused_round.lower(on.state, t0).as_text()
    assert hlo_fingerprint(r_off) == hlo_fingerprint(r_on)


def test_span_entry_names_match_cost_report_ledger():
    assert _exp(defense="Krum")._span_entry_name() == "fused_span"
    assert _exp(defense="Krum", aggregation="hierarchical",
                users_count=12, mal_prop=0.25,
                megabatch=4)._span_entry_name() == "hier_span"
    assert _exp(defense="Krum", aggregation="async",
                async_buffer=8, users_count=12,
                mal_prop=0.25)._span_entry_name() == "async_span"
    assert _exp(defense="Krum",
                telemetry=True)._span_entry_name() == "tele_span"


# ---------------------------------------------------------------------------
# REAL captures: partition invariant across the three engines (subprocess —
# the xprof flag must precede the child's first compile)

_MATRIX_SCRIPT = r"""
import json, os, sys, tempfile
import jax

sys.path.insert(0, %(repo)r)
from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.utils import walls
from attacking_federate_learning_tpu.utils.profiling import device_trace

CELLS = []
for defense in ("Krum", "TrimmedMean"):
    CELLS.append(("flat", dict(defense=defense)))
    CELLS.append(("hier", dict(defense=defense,
                               aggregation="hierarchical",
                               users_count=12, mal_prop=0.25,
                               megabatch=4)))
    CELLS.append(("async", dict(defense=defense, aggregation="async",
                                async_buffer=8, users_count=12,
                                mal_prop=0.25)))

ds = load_dataset(C.SYNTH_MNIST, seed=0, synth_train=128, synth_test=64)
for tag, overrides in CELLS:
    base = dict(dataset=C.SYNTH_MNIST, users_count=9, mal_prop=0.22,
                batch_size=16, epochs=4, test_step=4,
                synth_train=128, synth_test=64)
    base.update(overrides)
    exp = FederatedExperiment(ExperimentConfig(**base),
                              attacker=DriftAttack(1.0), dataset=ds)
    exp.run_span(0, 2)                         # warm: compile untraced
    jax.block_until_ready(exp.state.weights)
    td = tempfile.mkdtemp(prefix="wallmat_")
    with device_trace(td):
        exp.run_span(2, 2)
        jax.block_until_ready(exp.state.weights)
    rec = walls.book_trace(td, exp._span_hlo_text(2),
                           name=exp._span_entry_name(), rounds=2)
    out = {"cell": f"{tag}/{base['defense']}",
           "entry": exp._span_entry_name()}
    if rec is None:
        out["error"] = "no trace file"
    else:
        try:
            rec.check()
        except AssertionError as e:
            out["error"] = str(e)
        out["op_events"] = rec.coverage["op_events"]
        out["stages"] = rec.stages
        out["unattributed_us"] = rec.unattributed_us
        out["exact"] = (sum(rec.stages.values()) + rec.unattributed_us
                        == rec.total_us)
    print(json.dumps(out), flush=True)
"""


def test_partition_exact_on_all_three_engines_real_traces():
    proc = subprocess.run(
        [sys.executable, "-c", _MATRIX_SCRIPT % {"repo": REPO}],
        env=_subproc_env(), capture_output=True, text=True, timeout=540,
        cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.startswith("{")]
    assert len(rows) == 6, proc.stdout
    entries = {r["cell"]: r["entry"] for r in rows}
    assert entries["flat/Krum"] == "fused_span"
    assert entries["hier/Krum"] == "hier_span"
    assert entries["async/Krum"] == "async_span"
    for r in rows:
        assert "error" not in r, r
        assert r["op_events"] > 0, f"{r['cell']}: no op events booked"
        assert r["exact"], f"{r['cell']}: partition not exact"
        # The aggregation stage must carry measured time in every cell
        # (the span executed real defense work under the scope).
        assert r["stages"].get("tier1_aggregate", 0.0) > 0.0, r
        assert set(r["stages"]) <= set(STAGES), r


# ---------------------------------------------------------------------------
# e2e: --profile-every run -> v10 log -> runs walls

@pytest.fixture(scope="module")
def profiled_runs(tmp_path_factory):
    """Three journaled CLI runs in one store: two profiled (a, b) and
    one without --profile-every (for the exit-1 path)."""
    root = tmp_path_factory.mktemp("walls_e2e")
    log_dir, run_dir = str(root / "logs"), str(root / "runs")
    base = ["-s", "SYNTH_MNIST", "-n", "9", "-m", "0.22", "-c", "16",
            "-e", "5", "--synth-train", "128", "--synth-test", "64",
            "--journal", "--no-checkpoint", "--log-dir", log_dir,
            "--run-dir", run_dir]
    runs = [
        ("walls-a", ["-d", "Krum", "--profile-every", "1",
                     "--cost-report"]),
        ("walls-b", ["-d", "Median", "--profile-every", "1",
                     "--cost-report"]),
        ("walls-none", ["-d", "Krum"]),
    ]
    for run_id, extra in runs:
        proc = subprocess.run(
            [sys.executable, "-m", "attacking_federate_learning_tpu.cli",
             *base, *extra, "--run-id", run_id],
            env=_subproc_env(), capture_output=True, text=True,
            timeout=420, cwd=REPO)
        assert proc.returncode == 0, (run_id, proc.stderr[-3000:])
    return log_dir, run_dir


def _runs(run_dir, *argv):
    from attacking_federate_learning_tpu import runs_cli
    return runs_cli.main(["--run-dir", run_dir, *argv])


def test_profiled_run_log_roundtrips_at_v10(profiled_runs):
    log_dir, _ = profiled_runs
    path = os.path.join(log_dir, "walls-a.jsonl")
    events = list(iter_events(path, validate=True))
    wall = [e for e in events if e["kind"] == "wall"]
    # 'wall' arrived at v10 (KIND_MIN_VERSION); records stamp whatever
    # the current schema version is (v11+ after the traffic kind).
    assert wall and all(e["v"] == SCHEMA_VERSION >= 10 for e in wall)
    by_source = {e["source"] for e in wall}
    assert by_source == {"host", "trace"}
    for e in wall:
        if e["source"] != "trace":
            continue
        booked = sum(e["stages"].values()) + e["unattributed_us"]
        # wall_s is rounded to the microsecond, stages to 1e-3 us.
        assert booked == pytest.approx(e["wall_s"] * 1e6, abs=1.0)
        assert e["coverage"]["op_events"] > 0
        assert e["name"] == "fused_span"


def test_runs_walls_single_and_diff(profiled_runs, capsys):
    _, run_dir = profiled_runs
    assert _runs(run_dir, "walls", "walls-a") == 0
    out = capsys.readouterr().out
    assert "entry fused_span" in out
    assert "tier1_aggregate" in out
    assert "host walls:" in out
    assert _runs(run_dir, "walls", "walls-a", "walls-b") == 0
    out = capsys.readouterr().out
    assert "walls diff: walls-a vs walls-b" in out
    assert "rounds/s:" in out


def test_runs_walls_json_and_exit1(profiled_runs, capsys):
    _, run_dir = profiled_runs
    assert _runs(run_dir, "--json", "walls", "walls-a") == 0
    payload = json.loads(capsys.readouterr().out)
    entry = payload["walls-a"]["entries"]["fused_span"]
    assert entry["captures"] >= 1
    assert "vs_modeled" in entry     # the --cost-report twin joined
    assert _runs(run_dir, "walls", "walls-none") == 1
    assert "no wall events" in capsys.readouterr().out


def test_campaign_cells_carry_rounds_per_s(profiled_runs):
    """The registry whitelists the engine's always-on rounds_per_s
    summary stamp (the campaign time column's source)."""
    from attacking_federate_learning_tpu.utils.registry import RunRegistry
    _, run_dir = profiled_runs
    reg = RunRegistry(run_dir)
    reg.refresh()
    ent = reg.resolve("walls-a")
    assert isinstance(ent.get("rounds_per_s"), (int, float))
    assert ent["rounds_per_s"] > 0
