"""Fault domains for the hierarchical/SPMD tree (ISSUE 19).

Acceptance contract: per-client faults run inside each megabatch scan
step and a correlated shard-DOMAIN axis (``FaultConfig.shard_dropout``)
kills whole megabatches, flowing into tier-2 as per-shard alive counts
— the tier-2 estimate under shard death is BIT-EQUAL to the
survivor-submatrix estimator (a fully-dead shard can never win
selection or touch a trim); with faults off the hierarchical round
program stays HLO byte-identical; the emitted per-round 'fault' events
(per-shard survivor vector and tier-2 ladder action included) match
the host replay (core/faults.py hier_fault_schedule) exactly — per
round, per span, and on the (8, 1) SPMD mesh; a gracefully preempted
faulted⊕telemetry SPMD run resumes bit-for-bit with an exactly-once
journal; and the remaining composition rejections (shard-dropout⊕flat,
straggler⊕SPMD) are loud, with the campaign pre-check and engine
construction agreeing on the message.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import (
    ExperimentConfig, FaultConfig
)
from attacking_federate_learning_tpu.core import faults as F
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.core.population import ACTION_NAMES
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses.kernels import (
    TIER2_DEFENSES, bulyan, krum, shard_bulyan, shard_mean, trimmed_mean
)
from attacking_federate_learning_tpu.defenses.median import median
from attacking_federate_learning_tpu.ops.federated import shard_reduce
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
from attacking_federate_learning_tpu.utils.metrics import RunLogger

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 (virtual) devices")

_DS = {}


def _dataset(name=C.SYNTH_MNIST):
    if name not in _DS:
        _DS[name] = load_dataset(name, seed=0, synth_train=256,
                                 synth_test=64)
    return _DS[name]


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 16)
    kw.setdefault("mal_prop", 0.25)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 6)
    kw.setdefault("test_step", 3)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("aggregation", "hierarchical")
    kw.setdefault("megabatch", 4)
    kw.setdefault("defense", "TrimmedMean")
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    return ExperimentConfig(**kw)


def _run(cfg, name):
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                              dataset=_dataset())
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name=name) as logger:
        exp.run(logger)
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    return exp, events


# ---------------------------------------------------------------------------
# the shard-domain schedule itself (core/faults.py)

def test_domain_alive_deterministic_and_dwell_windowed():
    """Domain death is pure in (key, t) and dwells: a shard whose
    onset fires at t stays dead through [t, t + dwell) — the alive row
    at t is the AND over the dwell window's onset draws."""
    fc = FaultConfig(shard_dropout=0.35, shard_dropout_dwell=3)
    cfg = ExperimentConfig(faults=fc, dataset=C.SYNTH_MNIST,
                           users_count=16, defense="TrimmedMean",
                           aggregation="hierarchical", megabatch=4)
    key = F.fault_key(cfg)
    S = 8
    rows = {t: np.asarray(F.domain_alive_row(key, t, S, fc))
            for t in range(12)}
    for t in (0, 5, 11):
        np.testing.assert_array_equal(
            rows[t], np.asarray(F.domain_alive_row(key, t, S, fc)))
    # Reconstruct the per-round onsets (dwell=1 <=> the raw draw) and
    # pin the window semantics against the dwell-3 rows.
    fc1 = FaultConfig(shard_dropout=0.35, shard_dropout_dwell=1)
    onset = {t: ~np.asarray(F.domain_alive_row(key, t, S, fc1))
             for t in range(12)}
    for t in range(12):
        want = ~(onset[t]
                 | (onset[t - 1] if t >= 1 else False)
                 | (onset[t - 2] if t >= 2 else False))
        np.testing.assert_array_equal(rows[t], want, err_msg=f"t={t}")
    assert any(not rows[t].all() for t in range(12))   # deaths fired
    # shard_dropout=0 is the all-alive constant row, never a draw.
    np.testing.assert_array_equal(
        np.asarray(F.domain_alive_row(key, 3, S, FaultConfig())),
        np.ones(S, bool))


# ---------------------------------------------------------------------------
# tier-2 under shard death: masked kernel == survivor submatrix,
# BIT-equal (the acceptance pin)

_T2_FLAT = {"Krum": krum, "TrimmedMean": trimmed_mean,
            "Bulyan": bulyan, "Median": median}


@pytest.mark.parametrize("name", sorted(_T2_FLAT))
def test_tier2_masked_matches_survivor_submatrix(name):
    """shard_reduce with alive_counts carrying zeros (dead domains)
    must reproduce the flat kernel over the surviving shards' estimate
    submatrix — dead shards are EXCLUDED, not averaged in.  The
    selection kernels and the median are bit-equal; the trimmed
    mean's masked accumulation sums in mask order and lands within
    the flat masked pin's 1e-6 band.  Identical under jit (the fused
    round traces this path)."""
    rng = np.random.default_rng(19)
    S, f2, d = 9, 1, 40
    ests = jnp.asarray(rng.standard_normal((S, d)).astype(np.float32))
    dead = [2, 6]
    alive = jnp.asarray([0 if s in dead else 4 - (s % 2)
                         for s in range(S)], jnp.int32)
    # The engine zeroes dead rows before tier-2 (a dead domain's
    # estimate can be NaN); the kernels must not read them anyway.
    ez = ests.at[jnp.asarray(dead)].set(0.0)
    keep = np.asarray([s for s in range(S) if s not in dead])
    fn = TIER2_DEFENSES[name]
    got = np.asarray(shard_reduce(fn, ez, S, f2, alive_counts=alive))
    want = np.asarray(_T2_FLAT[name](ests[keep], len(keep), f2))
    if name == "TrimmedMean":
        np.testing.assert_allclose(got, want, atol=1e-6)
    else:
        np.testing.assert_array_equal(got, want)
    got_j = np.asarray(jax.jit(
        lambda e, a: shard_reduce(fn, e, S, f2, alive_counts=a))(
            ez, alive))
    np.testing.assert_array_equal(got, got_j)


def test_tier2_nodefense_weights_by_alive_counts():
    """Tier-2 NoDefense restores the flat masked mean's per-client
    weighting: each surviving shard's estimate weighted by its
    effective cohort, dead shards at weight zero."""
    rng = np.random.default_rng(3)
    S, d = 4, 12
    ests = jnp.asarray(rng.standard_normal((S, d)).astype(np.float32))
    alive = jnp.asarray([4, 2, 0, 3], jnp.int32)
    got = np.asarray(shard_mean(ests, S, 0, alive_counts=alive))
    e = np.asarray(ests)
    want = (4 * e[0] + 2 * e[1] + 3 * e[3]) / 9.0
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_tier2_bulyan_selection_clipped_under_shard_death():
    """Bulyan's selection count is STATIC (S - 2f) but the effective
    cohort shrinks with dead domains: at S=9, f2=1 with two dead
    shards the masked pass must clip its picks to e - 2f = 5 of the
    static 7-slot buffer and still bit-match Bulyan over the 7
    survivors (exactly the 4f+3 validity floor)."""
    rng = np.random.default_rng(23)
    S, f2, d = 9, 1, 32
    ests = jnp.asarray(rng.standard_normal((S, d)).astype(np.float32))
    dead = [0, 5]
    alive = jnp.asarray([0 if s in dead else 4 for s in range(S)],
                        jnp.int32)
    ez = ests.at[jnp.asarray(dead)].set(0.0)
    keep = np.asarray([s for s in range(S) if s not in dead])
    got = np.asarray(shard_bulyan(ez, S, f2, alive_counts=alive))
    want = np.asarray(bulyan(ests[keep], len(keep), f2))
    np.testing.assert_array_equal(got, want)
    # The (S,) selection record marks exactly e - 2f = 5 survivors and
    # never a dead shard.
    _, diag = shard_bulyan(ez, S, f2, alive_counts=alive,
                           telemetry=True)
    sel = np.asarray(diag["selection_mask"])
    assert sel.shape == (S,) and sel[dead].sum() == 0
    assert int(sel.sum()) == len(keep) - 2 * f2


# ---------------------------------------------------------------------------
# the ladder plan (host): remask -> fallback -> hold vs surviving shards

def test_plan_tier2_actions_ladder_thresholds():
    """The plan degrades monotonically as domains die: full survival
    plans remask (normal masked kernel), a survivor count below the
    defense's validity bound falls back to Median, and a cohort too
    small even for that holds the round."""
    acts = F.plan_tier2_actions([8, 7, 6, 4, 0], "Krum", 2)
    names = [ACTION_NAMES[a] for a in acts]
    assert names[0] == names[1] == "remask"    # >= 2f + 3 = 7
    assert names[2] == "fallback"    # Krum invalid, Median (2f+1) ok
    assert names[3] == "hold"        # below even Median's floor
    assert names[4] == "hold"        # nothing alive at all
    # Median's own floor IS the fallback's floor: its ladder has no
    # fallback rung — remask until 2f + 1, then hold.
    assert [ACTION_NAMES[a]
            for a in F.plan_tier2_actions([8, 5, 4, 0], "Median", 2)] \
        == ["remask", "remask", "hold", "hold"]


# ---------------------------------------------------------------------------
# engine: faults-off hier HLO byte-identity

def test_no_fault_hier_round_hlo_bit_identical(tmp_path):
    """With all fault flags off the hierarchical round program is
    byte-identical — faults=None and an all-zero FaultConfig lower to
    the same HLO (the PERF_BASELINE pin's unit-level mirror), and the
    faulted build is a different program."""
    def lowered(faults):
        cfg = _cfg(tmp_path, epochs=2, faults=faults)
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=_dataset())
        if exp.faults is None:
            args = (exp.state, jnp.asarray(0, jnp.int32))
        else:
            args = (exp.state, jnp.asarray(0, jnp.int32),
                    jnp.asarray(0, jnp.int32), exp._fault_state)
        return exp._fused_round.lower(*args).as_text()

    none_text = lowered(None)
    zero_text = lowered(FaultConfig(dropout=0.0, straggler=0.0,
                                    corrupt=0.0, shard_dropout=0.0))
    assert none_text == zero_text
    assert lowered(FaultConfig(dropout=0.2,
                               shard_dropout=0.25)) != none_text


# ---------------------------------------------------------------------------
# engine: emitted events == host replay, per round and per span

def _replay(exp, t0, count):
    rows = F.hier_fault_schedule(exp._fault_key, t0, count,
                                 exp._placement, exp.faults)
    acts = F.plan_tier2_actions([r["shards_alive"] for r in rows],
                                exp._tier2_name, exp._tier2_f)
    return rows, acts


def test_hier_fault_events_match_host_replay(tmp_path):
    """A faulted 6-round hierarchical run (dropout + straggler +
    corrupt + shard-domain death) completes with finite weights and
    every 'fault' event — per-shard survivor vector and tier-2 ladder
    action included — equal to the host replay exactly."""
    cfg = _cfg(tmp_path,
               faults=FaultConfig(dropout=0.2, straggler=0.1,
                                  straggler_delay=2, corrupt=0.1,
                                  shard_dropout=0.3,
                                  shard_dropout_dwell=2))
    exp, events = _run(cfg, "hier_replay")
    assert int(exp.state.round) == 6
    assert np.isfinite(np.asarray(exp.state.weights)).all()
    flt = sorted((e for e in events if e["kind"] == "fault"),
                 key=lambda e: e["round"])
    assert [e["round"] for e in flt] == list(range(6))
    rows, acts = _replay(exp, 0, 6)
    for got, want, act in zip(flt, rows, acts):
        for k in ("injected_dropout", "injected_straggler",
                  "injected_corrupt", "quarantined", "shards_dead",
                  "shards_alive"):
            assert int(got[k]) == want[k], (got, want)
        assert [int(x) for x in got["shard_alive"]] == \
            want["shard_alive"]
        assert int(got["tier2_action"]) == int(act)
    assert any(r["shards_dead"] > 0 for r in rows)   # deaths fired


def test_hier_fault_span_matches_per_round(tmp_path):
    """The scanned faulted span (actions as a per-round operand) must
    produce exactly the per-round dispatch's weights and fault state,
    straggler ring included."""
    fc = FaultConfig(dropout=0.2, straggler=0.2, straggler_delay=2,
                     corrupt=0.1, shard_dropout=0.25,
                     shard_dropout_dwell=2)
    cfg = _cfg(tmp_path, users_count=12, epochs=7, faults=fc)
    a = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                            dataset=_dataset())
    for t in range(7):
        a.run_round(t)
    b = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                            dataset=_dataset())
    b.run_span(0, 7)
    np.testing.assert_array_equal(np.asarray(a.state.weights),
                                  np.asarray(b.state.weights))
    np.testing.assert_array_equal(np.asarray(a._fault_state["stale"]),
                                  np.asarray(b._fault_state["stale"]))


# ---------------------------------------------------------------------------
# composition rejections: loud, and pre-check == construction

def test_shard_dropout_requires_hierarchical(tmp_path):
    """Correlated shard-domain death has no domains to kill on the
    flat path — rejected naming the flags, and the campaign pre-check
    returns the construction message verbatim."""
    from attacking_federate_learning_tpu.campaigns.spec import (
        composition_reject_reason
    )

    overrides = dict(
        dataset=C.SYNTH_MNIST, users_count=16, mal_prop=0.25,
        batch_size=16, epochs=2, defense="Median",
        synth_train=256, synth_test=64,
        faults=dict(shard_dropout=0.3))
    reason = composition_reject_reason(overrides)
    assert reason is not None and "shard-DOMAIN" in reason
    assert "--aggregation hierarchical" in reason
    cfg = ExperimentConfig(**overrides)        # config itself is fine
    with pytest.raises(ValueError) as ei:
        FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                            dataset=_dataset())
    assert str(ei.value) == reason


def test_straggler_rejects_spmd_mesh(tmp_path):
    """The straggler ring buffer is a cross-round carry the SPMD
    client_map cannot thread: hier ⊕ mesh(clients>1) ⊕ straggler is
    loudly rejected (and the stateless fault axes are named as the
    composing alternative)."""
    from attacking_federate_learning_tpu.campaigns.spec import (
        composition_reject_reason
    )

    overrides = dict(
        dataset=C.SYNTH_MNIST, users_count=32, mal_prop=0.25,
        batch_size=8, epochs=2, aggregation="hierarchical",
        megabatch=4, mesh_shape=[8, 1], defense="TrimmedMean",
        synth_train=256, synth_test=64,
        faults=dict(straggler=0.1))
    reason = composition_reject_reason(overrides)
    assert reason is not None and "SPMD client_map" in reason
    assert "--fault-straggler" in reason
    # The same cell without the straggler axis pre-validates clean.
    overrides["faults"] = dict(dropout=0.2, shard_dropout=0.25)
    assert composition_reject_reason(overrides) is None


# ---------------------------------------------------------------------------
# SPMD: faulted sharded == unsharded, and preempt -> resume bit-for-bit

@needs_8
def test_spmd_faulted_round_matches_scan(tmp_path):
    """Faulted rounds on the (8, 1) mesh reproduce the sequential scan
    path — weights inside the measured ulp band, every integer fault
    count (per-shard survivor vector included) EXACTLY the host
    replay on both paths."""
    fc = FaultConfig(dropout=0.2, corrupt=0.1, shard_dropout=0.25,
                     shard_dropout_dwell=2)
    kw = dict(users_count=32, batch_size=8, epochs=2, faults=fc)
    ref = FederatedExperiment(_cfg(tmp_path, **kw),
                              attacker=DriftAttack(1.0),
                              dataset=_dataset())
    spmd = FederatedExperiment(_cfg(tmp_path, mesh_shape=(8, 1), **kw),
                               attacker=DriftAttack(1.0),
                               dataset=_dataset())
    assert spmd._hier_spmd and not ref._hier_spmd
    for t in range(2):
        ref.run_round(t)
        spmd.run_round(t)
        rt, st = ref.last_round_telemetry, spmd.last_round_telemetry
        row = F.hier_fault_schedule(ref._fault_key, t, 1,
                                    ref._placement, ref.faults)[0]
        for tele in (rt, st):
            for k in ("injected_dropout", "injected_corrupt",
                      "quarantined", "shards_dead", "shards_alive"):
                assert int(np.asarray(tele[f"fault_{k}"])) == row[k]
            np.testing.assert_array_equal(
                np.asarray(tele["fault_shard_alive"]),
                row["shard_alive"])
    np.testing.assert_allclose(np.asarray(spmd.state.weights),
                               np.asarray(ref.state.weights),
                               atol=2e-5, rtol=1e-5)


@needs_8
def test_spmd_faulted_preempt_resume_bit_for_bit(tmp_path):
    """faults ⊕ hierarchical ⊕ telemetry on the (8, 1) mesh: a
    SIGTERM-preempted run resumes to final weights bit-for-bit equal
    to the uninterrupted run, with the journal and shared event stream
    recording every round's fault event and every eval exactly once
    across the two attempts."""
    from attacking_federate_learning_tpu.utils.lifecycle import (
        GracefulShutdown, Preempted, RunJournal
    )

    fc = FaultConfig(dropout=0.2, corrupt=0.05, shard_dropout=0.25,
                     shard_dropout_dwell=2)
    kill_round = 3

    def cfg_for(run_dir):
        return _cfg(tmp_path, users_count=32, batch_size=8, epochs=6,
                    test_step=3, checkpoint_every=2, telemetry=True,
                    mesh_shape=(8, 1), faults=fc,
                    run_dir=str(tmp_path / run_dir))

    cfg_ref = cfg_for("runs_ref")
    full = FederatedExperiment(cfg_ref, attacker=DriftAttack(1.0),
                               dataset=_dataset())
    assert full._hier_spmd
    with RunLogger(cfg_ref, None, cfg_ref.log_dir,
                   jsonl_name="fsp_full") as logger:
        full.run(logger, checkpointer=Checkpointer(cfg_ref))
    w_full = np.array(full.state.weights, copy=True)
    v_full = np.array(full.state.velocity, copy=True)

    cfg = cfg_for("runs_sup")
    ck = Checkpointer(cfg)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                              dataset=_dataset())
    with RunLogger(cfg, None, cfg.log_dir,
                   jsonl_name="fsp_sup") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "fsp"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    resumed = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=_dataset())
    state, extra = ck.resume(ck.latest(), with_extra=True)
    resumed.state = state
    resumed.restore_fault_state(extra)
    with RunLogger(cfg, None, cfg.log_dir,
                   jsonl_name="fsp_sup") as logger:
        resumed.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "fsp"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    np.testing.assert_array_equal(np.asarray(resumed.state.weights),
                                  w_full)
    np.testing.assert_array_equal(np.asarray(resumed.state.velocity),
                                  v_full)
    assert RunJournal(cfg.run_dir, "fsp").verify(
        epochs=6, test_step=3) == []
    with open(os.path.join(cfg.log_dir, "fsp_sup.jsonl")) as f:
        events = [json.loads(line) for line in f]
    fault_rounds = [e["round"] for e in events if e["kind"] == "fault"]
    assert sorted(fault_rounds) == list(range(6))
    # And the stitched event stream still equals the host replay.
    flt = sorted((e for e in events if e["kind"] == "fault"),
                 key=lambda e: e["round"])
    rows, acts = _replay(resumed, 0, 6)
    for got, want, act in zip(flt, rows, acts):
        assert [int(x) for x in got["shard_alive"]] == \
            want["shard_alive"]
        assert int(got["tier2_action"]) == int(act)
