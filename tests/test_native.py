"""Native (C++) incremental Bulyan selection vs the NumPy anchor.

The native kernel (attacking_federate_learning_tpu/native/bulyan_select.cpp)
must produce the same selection as defenses/host.py's presort-once NumPy
loop — which is itself pinned against the literal reference defences.py in
tests/test_reference_parity.py — across plain, adversarial-magnitude,
duplicate-row, and f32-overflow inputs, every batch_select, and paper
scoring.

Known, accepted divergence: when score gaps fall inside the f32
summation's rounding error (a few ulps, ~log2(n) worst case — e.g.
adversarial 1e6-scale rows compress relative gaps under f32 eps), the
NumPy path's f32 pairwise sums land on arbitrary orders the
f32-quantized-f64 native comparator cannot always reproduce bit-for-bit
— the reference's own torch f32 sums would give yet another order, so
within that noise band no ordering is canonical.  The selected *set* and
the final aggregate still matched everywhere in a 1,000-trial randomized
sweep at build time; the adversarial near-tie case is asserted at
set/aggregate level here.
"""

from __future__ import annotations

import numpy as np
import pytest

from attacking_federate_learning_tpu.defenses.host import (
    host_bulyan,
    host_pairwise_distances,
    host_trimmed_mean_of,
    numpy_bulyan_selection,
)
from attacking_federate_learning_tpu.defenses.oracle import np_bulyan
from attacking_federate_learning_tpu.native import (
    get_lib,
    native_bulyan_selection,
)

pytestmark = pytest.mark.skipif(
    get_lib() is None, reason="native kernel unavailable (no g++?)"
)


def _both(G, users, f, q=1, paper=False):
    set_size = users - 2 * f
    D = host_pairwise_distances(np.asarray(G, np.float32))
    order = np.argsort(D, axis=1).astype(np.int32)
    nat = native_bulyan_selection(D, order, users, f, set_size,
                                  batch_select=q, paper_scoring=paper)
    ref = numpy_bulyan_selection(D, order, users, f, set_size,
                                 batch_select=q, paper_scoring=paper)
    return nat, ref, set_size


class TestNativeBulyanSelection:
    @pytest.mark.parametrize("q", [1, 2, 3])
    @pytest.mark.parametrize("paper", [False, True])
    def test_exact_match_on_plain_inputs(self, q, paper):
        rng = np.random.default_rng(42)
        for n, f in [(6, 1), (11, 2), (16, 3), (25, 4), (33, 7)]:
            if paper and n - f - 2 <= 0:
                continue
            G = rng.standard_normal((n, 10)).astype(np.float32)
            nat, ref, _ = _both(G, n, f, q=q, paper=paper)
            assert nat is not None
            np.testing.assert_array_equal(nat, ref)

    def test_exact_match_with_duplicates_and_overflow(self):
        rng = np.random.default_rng(7)
        for trial in range(20):
            n = int(rng.integers(6, 30))
            f = int(rng.integers(0, max(1, (n - 1) // 4)))
            G = rng.standard_normal((n, 8)).astype(np.float32)
            G[1] = G[2]                        # duplicate rows (tie case)
            if trial % 2 == 0:
                G[3] *= 1e25                   # f32 overflow -> inf dists
            nat, ref, _ = _both(G, n, f, q=int(rng.integers(1, 4)))
            assert nat is not None
            np.testing.assert_array_equal(nat, ref)

    def test_adversarial_magnitudes_set_and_aggregate(self):
        # 1e6-scale rows push score gaps below f32 eps; order may differ
        # (see module docstring) but the selected set and the resulting
        # trimmed mean must not.
        rng = np.random.default_rng(3)
        for _ in range(30):
            n = int(rng.integers(8, 40))
            f = int(rng.integers(1, max(2, (n - 1) // 4)))
            q = int(rng.integers(1, 4))
            G = rng.standard_normal((n, 8)).astype(np.float32)
            G[0] *= 1e6
            nat, ref, set_size = _both(G, n, f, q=q)
            assert nat is not None
            assert set(nat.tolist()) == set(ref.tolist())
            keep = set_size - 2 * f - 1
            np.testing.assert_allclose(
                host_trimmed_mean_of(G[nat], keep),
                host_trimmed_mean_of(G[ref], keep),
                rtol=1e-5, atol=1e-5)

    def test_oracle_parity_q1_through_host_bulyan(self):
        # host_bulyan now routes through the native kernel by default;
        # q=1 must still match the independent loop oracle.
        for seed in range(6):
            rng = np.random.default_rng(seed)
            G = rng.standard_normal((13, 6)).astype(np.float32)
            np.testing.assert_allclose(
                host_bulyan(G, 13, 2), np_bulyan(G, 13, 2), atol=1e-5)

    def test_fallback_matches_native(self, monkeypatch):
        # With FL_NATIVE=0 semantics (loader returns None) host_bulyan
        # falls back to the NumPy loop and produces the same aggregate.
        rng = np.random.default_rng(11)
        G = rng.standard_normal((14, 9)).astype(np.float32)
        via_native = host_bulyan(G, 14, 2, batch_select=2)
        import attacking_federate_learning_tpu.native as nat_mod
        monkeypatch.setattr(nat_mod, "_lib", None)
        monkeypatch.setattr(nat_mod, "_loaded", True)
        via_numpy = host_bulyan(G, 14, 2, batch_select=2)
        np.testing.assert_allclose(via_native, via_numpy, atol=1e-6)

    def test_degenerate_shapes(self):
        # f=0 (select everyone), n=4 minimum, q larger than set_size.
        rng = np.random.default_rng(5)
        for n, f, q in [(4, 0, 1), (5, 0, 9), (6, 1, 6), (9, 2, 4)]:
            G = rng.standard_normal((n, 5)).astype(np.float32)
            nat, ref, _ = _both(G, n, f, q=q)
            assert nat is not None
            np.testing.assert_array_equal(nat, ref)
