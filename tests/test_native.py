"""Native (C++) incremental Bulyan selection vs the NumPy anchor.

The native kernel (attacking_federate_learning_tpu/native/bulyan_select.cpp)
must produce the same selection as defenses/host.py's presort-once NumPy
loop — which is itself pinned against the literal reference defences.py in
tests/test_reference_parity.py — across plain, adversarial-magnitude,
duplicate-row, and f32-overflow inputs, every batch_select, and paper
scoring.

Known, accepted divergence: when score gaps fall inside the f32
summation's rounding error (a few ulps, ~log2(n) worst case — e.g.
adversarial 1e6-scale rows compress relative gaps under f32 eps), the
NumPy path's f32 pairwise sums land on arbitrary orders the
f32-quantized-f64 native comparator cannot always reproduce bit-for-bit
— the reference's own torch f32 sums would give yet another order, so
within that noise band no ordering is canonical.  The checked-in
1,000-trial sweep (test_adversarial_tie_randomized_sweep) measures the
contract precisely: 3/1000 adversarial trials diverge at set level,
every one a <=1-ulp f32 tie at its first diverging trip — and the sweep
asserts that any divergence stays inside that tie band (a swapped
tie-row can shift the trimmed mean by that row's contribution, which is
inside the reference's own f32 indeterminacy).
"""

from __future__ import annotations

import numpy as np
import pytest

from attacking_federate_learning_tpu.defenses.host import (
    host_bulyan,
    host_pairwise_distances,
    host_trimmed_mean_of,
    numpy_bulyan_selection,
)
from attacking_federate_learning_tpu.defenses.oracle import np_bulyan
from attacking_federate_learning_tpu.native import (
    get_lib,
    native_bulyan_selection,
)

pytestmark = pytest.mark.skipif(
    get_lib() is None, reason="native kernel unavailable (no g++?)"
)


def _both(G, users, f, q=1, paper=False):
    set_size = users - 2 * f
    D = host_pairwise_distances(np.asarray(G, np.float32))
    order = np.argsort(D, axis=1).astype(np.int32)
    nat = native_bulyan_selection(D, order, users, f, set_size,
                                  batch_select=q, paper_scoring=paper)
    ref = numpy_bulyan_selection(D, order, users, f, set_size,
                                 batch_select=q, paper_scoring=paper)
    return nat, ref, set_size


class TestNativeBulyanSelection:
    @pytest.mark.parametrize("q", [1, 2, 3])
    @pytest.mark.parametrize("paper", [False, True])
    def test_exact_match_on_plain_inputs(self, q, paper):
        rng = np.random.default_rng(42)
        for n, f in [(6, 1), (11, 2), (16, 3), (25, 4), (33, 7)]:
            if paper and n - f - 2 <= 0:
                continue
            G = rng.standard_normal((n, 10)).astype(np.float32)
            nat, ref, _ = _both(G, n, f, q=q, paper=paper)
            assert nat is not None
            np.testing.assert_array_equal(nat, ref)

    def test_exact_match_with_duplicates_and_overflow(self):
        rng = np.random.default_rng(7)
        for trial in range(20):
            n = int(rng.integers(6, 30))
            f = int(rng.integers(0, max(1, (n - 1) // 4)))
            G = rng.standard_normal((n, 8)).astype(np.float32)
            G[1] = G[2]                        # duplicate rows (tie case)
            if trial % 2 == 0:
                G[3] *= 1e25                   # f32 overflow -> inf dists
            nat, ref, _ = _both(G, n, f, q=int(rng.integers(1, 4)))
            assert nat is not None
            np.testing.assert_array_equal(nat, ref)

    def test_adversarial_magnitudes_set_and_aggregate(self):
        # 1e6-scale rows push score gaps below f32 eps; order may differ
        # (see module docstring) but the selected set and the resulting
        # trimmed mean must not.
        rng = np.random.default_rng(3)
        for _ in range(30):
            n = int(rng.integers(8, 40))
            f = int(rng.integers(1, max(2, (n - 1) // 4)))
            q = int(rng.integers(1, 4))
            G = rng.standard_normal((n, 8)).astype(np.float32)
            G[0] *= 1e6
            nat, ref, set_size = _both(G, n, f, q=q)
            assert nat is not None
            assert set(nat.tolist()) == set(ref.tolist())
            keep = set_size - 2 * f - 1
            np.testing.assert_allclose(
                host_trimmed_mean_of(G[nat], keep),
                host_trimmed_mean_of(G[ref], keep),
                rtol=1e-5, atol=1e-5)

    def test_oracle_parity_q1_through_host_bulyan(self):
        # host_bulyan now routes through the native kernel by default;
        # q=1 must still match the independent loop oracle.
        for seed in range(6):
            rng = np.random.default_rng(seed)
            G = rng.standard_normal((13, 6)).astype(np.float32)
            np.testing.assert_allclose(
                host_bulyan(G, 13, 2), np_bulyan(G, 13, 2), atol=1e-5)

    def test_fallback_matches_native(self, monkeypatch):
        # With FL_NATIVE=0 semantics (loader returns None) host_bulyan
        # falls back to the NumPy loop and produces the same aggregate.
        rng = np.random.default_rng(11)
        G = rng.standard_normal((14, 9)).astype(np.float32)
        via_native = host_bulyan(G, 14, 2, batch_select=2)
        import attacking_federate_learning_tpu.native as nat_mod
        monkeypatch.setattr(nat_mod, "_lib", None)
        monkeypatch.setattr(nat_mod, "_loaded", True)
        via_numpy = host_bulyan(G, 14, 2, batch_select=2)
        np.testing.assert_allclose(via_native, via_numpy, atol=1e-6)

    def test_adversarial_tie_randomized_sweep(self):
        # The checked-in 1,000-trial randomized sweep (VERDICT r3 weak
        # #2), asserting the PRECISE tie-band contract documented at
        # native/bulyan_select.cpp: under 1e6-magnitude adversarial rows
        # the native and NumPy selections are set-equal (and the trimmed
        # means allclose) on every trial whose decisive f32 score gaps
        # exceed summation noise, and any set divergence must be an
        # f32 ulp-level tie at its first diverging trip — a pick the
        # reference's own f32 summation order cannot canonicalize either.
        # Writing this sweep down found what the round-3 session sweep
        # missed: 3/1000 trials DO diverge at set level, every one a
        # <=1-ulp tie (the r3 "set never diverged" claim was too strong;
        # BASELINE.md/PARITY.md now state the measured contract).
        rng = np.random.default_rng(0xB1A5)
        divergences = []
        for trial in range(1000):
            n = int(rng.integers(6, 28))
            f = int(rng.integers(0, max(1, (n - 1) // 4)))
            q = int(rng.integers(1, 4))
            G = rng.standard_normal((n, 6)).astype(np.float32)
            G[0] *= 1e6                       # adversarial magnitude
            if trial % 3 == 0:
                G[1] = G[2]                   # duplicate rows
            if trial % 7 == 0:
                with np.errstate(over="ignore", invalid="ignore"):
                    G[3] *= 1e25              # f32 overflow -> inf dists
            with np.errstate(over="ignore", invalid="ignore"):
                nat, ref, set_size = _both(G, n, f, q=q)
            assert nat is not None
            if set(nat.tolist()) == set(ref.tolist()):
                keep = set_size - 2 * f - 1
                if keep > 0:
                    np.testing.assert_allclose(
                        host_trimmed_mean_of(G[nat], keep),
                        host_trimmed_mean_of(G[ref], keep),
                        rtol=1e-5, atol=1e-5,
                        err_msg=f"trial {trial} (n={n}, f={f}, q={q})")
                continue
            with np.errstate(over="ignore", invalid="ignore"):
                gap = self._ulp_gap_at_divergence(G, n, f, q, nat, ref)
            assert gap is not None and gap <= 2.0, (
                f"trial {trial}: set diverged OUTSIDE the f32 tie band "
                f"(n={n}, f={f}, q={q}, gap={gap} ulps)")
            divergences.append((trial, gap))
        # The divergence rate itself is part of the pinned contract: a
        # native-comparator regression that starts resolving real gaps
        # differently would blow well past this bound.
        assert len(divergences) <= 10, divergences

    @staticmethod
    def _ulp_gap_at_divergence(G, n, f, q, nat, ref):
        """Replay the NumPy scoring to the first diverging trip; return
        the two picks' f32 score gap in ulps at that magnitude (0.0 for
        non-finite ties, None if the selections never diverge)."""
        from attacking_federate_learning_tpu.defenses.host import (
            _prefix_scores
        )
        D = host_pairwise_distances(np.asarray(G, np.float32))
        order = np.argsort(D, axis=1).astype(np.int32)
        sortedD = np.take_along_axis(D, order, axis=1)
        finite = np.isfinite(sortedD)
        alive = np.ones(n, bool)
        s, set_size = 0, len(ref)
        while s < set_size:
            r = min(q, set_size - s)
            scores = _prefix_scores(sortedD, order, finite, alive,
                                    n - s, f)
            t_nat = set(nat[s:s + r].tolist())
            t_ref = set(ref[s:s + r].tolist())
            if t_nat != t_ref:
                vals = [scores[i] for i in t_nat ^ t_ref]
                lo, hi = min(vals), max(vals)
                if not np.isfinite(lo):
                    return 0.0
                ulp = float(np.spacing(np.float32(max(abs(lo),
                                                      abs(hi)))))
                return float(hi - lo) / ulp
            idxs = np.argsort(scores, kind="stable")[:r]
            alive[idxs] = False
            s += r
        return None

    def test_degenerate_shapes(self):
        # f=0 (select everyone), n=4 minimum, q larger than set_size.
        rng = np.random.default_rng(5)
        for n, f, q in [(4, 0, 1), (5, 0, 9), (6, 1, 6), (9, 2, 4)]:
            G = rng.standard_normal((n, 5)).astype(np.float32)
            nat, ref, _ = _both(G, n, f, q=q)
            assert nat is not None
            np.testing.assert_array_equal(nat, ref)
