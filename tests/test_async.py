"""Asynchronous buffered rounds (ISSUE 9, FedBuff-style).

Acceptance contract: sync paths untouched (the flat engine's lowered
program is byte-identical at any async-knob value — the knobs are inert
under flat/hierarchical, and tools/perf_gate.py pins the real HLO
cells); the arrival/buffer dynamics are a pure function of the config,
replayable on the host (core/async_rounds.py:replay_schedule) and
diffed against emitted v7 'async' events; the staleness-weight seam on
the mask-aware kernels degenerates exactly to the quarantine path at
unit weights; faults compose (dropout = no submission, straggler =
extra delay, corrupt = quarantined at delivery); a SIGTERM-preempted
async run resumes bit-for-bit with the ring + pending buffers riding
the checkpoint ``extra=`` arrays; and the timed backdoor's rows always
arrive fresh.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.attacks.base import (
    AttackContext, cohort_stats, masked_cohort_stats
)
from attacking_federate_learning_tpu.config import (
    ExperimentConfig, FaultConfig
)
from attacking_federate_learning_tpu.core import async_rounds as A
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses.kernels import (
    bulyan, krum, no_defense, trimmed_mean
)
from attacking_federate_learning_tpu.defenses.median import median
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
from attacking_federate_learning_tpu.utils.metrics import (
    RunLogger, validate_event
)


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 12)
    kw.setdefault("mal_prop", 0.2)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 8)
    kw.setdefault("test_step", 4)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    kw.setdefault("aggregation", "async")
    kw.setdefault("async_buffer", 8)
    kw.setdefault("async_max_staleness", 2)
    return ExperimentConfig(**kw)


def _engine(cfg, attacker=None):
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    return FederatedExperiment(cfg, attacker=attacker or DriftAttack(1.0),
                               dataset=ds)


def _run(cfg, name, attacker=None, **run_kw):
    exp = _engine(cfg, attacker)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name=name) as logger:
        exp.run(logger, **run_kw)
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    return exp, events


# ---------------------------------------------------------------------------
# delay model / schedule determinism

def test_delay_schedule_deterministic():
    cfg = ExperimentConfig(aggregation="async", async_buffer=4,
                           async_max_staleness=2)
    spec = A.AsyncSpec(buffer=4, max_staleness=2, weighting="none")
    key = A.async_key(cfg)
    d1, drop1, _ = A.draw_delays(key, 3, 10, 2, spec)
    d2, drop2, _ = A.draw_delays(key, 3, 10, 2, spec)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    assert np.asarray(d1).min() >= 0 and np.asarray(d1).max() < spec.depth
    assert not np.asarray(drop1).any()          # no faults configured
    # Different rounds draw different schedules (overwhelmingly).
    d3, _, _ = A.draw_delays(key, 4, 10, 2, spec)
    assert not np.array_equal(np.asarray(d1), np.asarray(d3))


def test_timed_attacker_rows_always_emit_fresh():
    cfg = ExperimentConfig(aggregation="async", async_buffer=4,
                           async_max_staleness=3)
    spec = A.AsyncSpec(buffer=4, max_staleness=3, weighting="none",
                       timed=True)
    key = A.async_key(cfg)
    for t in range(10):
        d, _, _ = A.draw_delays(key, t, 12, 3, spec)
        assert np.asarray(d)[:3].tolist() == [0, 0, 0]
    # Replay: every delivered malicious row has staleness 0 — a timed
    # row either rides this round's bus fresh or is superseded by the
    # next fresh emission before it can age.
    cfg = ExperimentConfig(aggregation="async", async_buffer=4,
                           async_max_staleness=3, users_count=12,
                           mal_prop=0.25)
    rows = A.replay_schedule(cfg, 12, 3, 12, timed=True)
    delivered_mal = 0
    for r in rows:
        for i in range(3):
            if r["delivered_mask"][i]:
                delivered_mal += 1
                assert r["staleness"][i] == 0
    assert delivered_mal > 0


def test_straggler_fault_becomes_extra_delay():
    faults = FaultConfig(straggler=0.5, straggler_delay=2)
    cfg = ExperimentConfig(aggregation="async", async_buffer=4,
                           async_max_staleness=4, faults=faults)
    spec = A.AsyncSpec(buffer=4, max_staleness=4, weighting="none")
    key = A.async_key(cfg)
    t = 6     # past the fault_masks cold-start suppression window
    base, _, _ = A.draw_delays(key, t, 16, 0, spec)
    with_faults, _, _ = A.draw_delays(key, t, 16, 0, spec, faults)
    from attacking_federate_learning_tpu.core.faults import fault_masks
    _, stale, _ = fault_masks(key, t, 16, 0, faults)
    stale = np.asarray(stale)
    assert stale.any()          # the seed draws some stragglers here
    base, with_faults = np.asarray(base), np.asarray(with_faults)
    np.testing.assert_array_equal(
        with_faults[~stale], base[~stale])
    np.testing.assert_array_equal(
        with_faults[stale],
        np.minimum(base[stale] + 2, spec.depth - 1))


# ---------------------------------------------------------------------------
# engine runs: events match the host replay, every mask-aware defense

@pytest.mark.parametrize("defense,weighting,buffer", [
    ("NoDefense", "none", 7), ("Krum", "poly", 7),
    ("TrimmedMean", "poly", 7), ("Median", "const", 7),
    # Bulyan's bound applies at n=k: k >= 4f+3 = 11 (n=12, f=2).
    ("Bulyan", "none", 11),
])
def test_async_run_events_match_replay(tmp_path, defense, weighting,
                                       buffer):
    cfg = _cfg(tmp_path, defense=defense, staleness_weight=weighting,
               async_buffer=buffer)
    exp, events = _run(cfg, f"async_{defense}")
    assert int(exp.state.round) == cfg.epochs
    assert np.isfinite(np.asarray(exp.state.weights)).all()
    av = sorted((e for e in events if e.get("kind") == "async"),
                key=lambda e: e["round"])
    for e in events:
        validate_event(e)
    assert [e["round"] for e in av] == list(range(cfg.epochs))
    assert all(e["v"] >= 7 for e in av)   # stamped with the writer version
    rows = A.replay_schedule(cfg, exp.m, exp.m_mal, cfg.epochs)
    for e, r in zip(av, rows):
        assert int(e["delivered"]) == r["delivered"]
        assert int(e["pending"]) == r["pending"]
        assert int(e["evicted"]) == r["evicted"]
        assert int(e["superseded"]) == r["superseded"]
        # FedBuff trigger: a delivered round aggregates exactly k rows.
        assert int(e["delivered"]) in (0, min(buffer, exp.m))
        assert [int(x) for x in e["staleness_hist"]] == r["staleness_hist"]
        # Weight mass: none -> the histogram itself; poly/const -> the
        # weight function applied to the histogram.
        mass = [float(x) for x in e["weight_mass"]]
        want = [h * {"none": 1.0,
                     "poly": 1.0 / np.sqrt(1.0 + s),
                     "const": 1.0 if s == 0 else 0.5}[weighting]
                for s, h in enumerate(r["staleness_hist"])]
        np.testing.assert_allclose(mass, want, rtol=1e-6)


def test_async_telemetry_and_round_stats(tmp_path):
    cfg = _cfg(tmp_path, defense="Krum", telemetry=True,
               log_round_stats=True, staleness_weight="poly")
    exp, events = _run(cfg, "async_tele")
    kinds = {e["kind"] for e in events}
    assert {"async", "defense", "attack", "round", "eval"} <= kinds
    # Defense diagnostics ride the mask path: the Krum selection mask
    # must mark a DELIVERED row every round.
    av = {e["round"]: e for e in events if e["kind"] == "async"}
    rows = A.replay_schedule(cfg, exp.m, exp.m_mal, cfg.epochs)
    for e in events:
        if e["kind"] != "defense":
            continue
        sel = int(np.argmax(e["selection_mask"]))
        r = rows[e["round"]]
        if av[e["round"]]["delivered"]:
            assert r["delivered_mask"][sel]


def test_empty_delivery_round_is_server_noop(tmp_path):
    """A round with no arrivals must hold weights and velocity (the
    round counter still advances).  Deterministically find a seed whose
    round 0 delivers nothing (all round-0 delays > 0), then check the
    engine state is bit-unchanged after that round."""
    seed = None
    for s in range(200):
        cfg = ExperimentConfig(aggregation="async", async_buffer=8,
                               async_max_staleness=2, users_count=10,
                               mal_prop=0.2, seed=s)
        if A.replay_schedule(cfg, 10, 2, 1)[0]["delivered"] == 0:
            seed = s
            break
    assert seed is not None
    cfg = _cfg(tmp_path, users_count=10, seed=seed, epochs=2,
               test_step=2)
    exp = _engine(cfg)
    w0 = np.array(np.asarray(exp.state.weights), copy=True)
    v0 = np.array(np.asarray(exp.state.velocity), copy=True)
    exp.run_round(0)
    np.testing.assert_array_equal(np.asarray(exp.state.weights), w0)
    np.testing.assert_array_equal(np.asarray(exp.state.velocity), v0)
    assert int(exp.state.round) == 1


# ---------------------------------------------------------------------------
# the staleness-weight seam on the mask-aware kernels

def _toy(n=9, d=7, seed=3):
    rng = np.random.default_rng(seed)
    G = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    mask = jnp.asarray(rng.random(n) > 0.3)
    if not bool(mask.any()):
        mask = mask.at[0].set(True)
    w = jnp.asarray(rng.uniform(0.3, 1.0, size=n).astype(np.float32))
    w = jnp.where(mask, w, 0.0)
    return G, mask, w


def test_weighted_nodefense_is_weighted_masked_mean():
    G, mask, w = _toy()
    got = no_defense(G, 9, 2, mask=mask, weights=w)
    want = (np.asarray(w) @ np.asarray(G)) / np.asarray(w).sum()
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_weighted_krum_scales_winner_only():
    G, mask, w = _toy()
    unweighted = krum(G, 9, 2, mask=mask)
    weighted = krum(G, 9, 2, mask=mask, weights=w)
    # The winner is unchanged (selection is unweighted); its update is
    # scaled by its own weight.
    rows = np.asarray(G)
    sel = int(np.argmin(np.linalg.norm(
        rows - np.asarray(unweighted)[None, :], axis=1)))
    np.testing.assert_allclose(np.asarray(weighted),
                               float(np.asarray(w)[sel])
                               * np.asarray(unweighted), rtol=1e-6)


@pytest.mark.parametrize("kernel,kw", [
    (no_defense, {}), (trimmed_mean, {}), (bulyan, {}),
    (krum, {}),
])
def test_unit_weights_degenerate_to_masked_path(kernel, kw):
    """weights == 1 on every alive row must reproduce the quarantine
    path exactly — the weighted estimators are strict generalizations."""
    G, mask, _ = _toy(n=11, d=6)
    ones = jnp.where(mask, 1.0, 0.0)
    base = kernel(G, 11, 2, mask=mask, **kw)
    weighted = kernel(G, 11, 2, mask=mask, weights=ones, **kw)
    np.testing.assert_allclose(np.asarray(weighted), np.asarray(base),
                               rtol=1e-6, atol=1e-7)


def test_weighted_median_crosses_half_mass():
    # 3 alive rows, one coordinate: values [0, 10, 20], weights
    # [0.2, 0.2, 0.6] -> cumulative 0.2/0.4/1.0, half-mass 0.5 -> 20.
    G = jnp.asarray([[0.0], [10.0], [20.0], [99.0]])
    mask = jnp.asarray([True, True, True, False])
    w = jnp.asarray([0.2, 0.2, 0.6, 0.0])
    got = median(G, 4, 0, mask=mask, weights=w)
    assert float(got[0]) == 20.0
    # Flip the heavy weight to the low value -> the weighted median
    # moves to 0 (cumulative 0.6 >= 0.5 at the first row).
    w2 = jnp.asarray([0.6, 0.2, 0.2, 0.0])
    assert float(median(G, 4, 0, mask=mask, weights=w2)[0]) == 0.0


def test_weights_without_mask_rejected():
    G = jnp.zeros((5, 3))
    w = jnp.ones((5,))
    with pytest.raises(ValueError, match="mask"):
        no_defense(G, 5, 1, weights=w)


# ---------------------------------------------------------------------------
# sync paths untouched

def test_flat_hlo_byte_identical_under_async_knobs(tmp_path):
    """The async knobs are inert outside aggregation='async': a flat
    engine built with them set lowers to the byte-identical program
    (the real perf cells are pinned by tools/perf_gate.py)."""
    def lowered(**kw):
        cfg = _cfg(tmp_path, aggregation="flat", async_buffer=0, **kw)
        exp = _engine(cfg)
        return exp._fused_round.lower(
            exp.state, jnp.asarray(0, jnp.int32)).as_text()

    base = lowered()
    knobbed = lowered(async_max_staleness=7, staleness_weight="poly")
    assert base == knobbed


# ---------------------------------------------------------------------------
# loud rejections (message contract, PR 6/7 style)

@pytest.mark.parametrize("kw,match", [
    (dict(defense="GeoMedian"), "mask-aware defense"),
    (dict(participation=0.5), "participation=1.0"),
    (dict(data_placement="host_stream"), "data_placement='device'"),
    (dict(trimmed_mean_impl="host", defense="TrimmedMean"),
     "trimmed_mean_impl='host'"),
    (dict(median_impl="host", defense="Median"), "median_impl='host'"),
    (dict(backdoor="pattern", backdoor_fused=False), "backdoor-staged"),
])
def test_async_rejections_name_the_flag(tmp_path, kw, match):
    with pytest.raises(ValueError, match=match):
        _engine(_cfg(tmp_path, **kw))


def test_async_needs_buffer_size(tmp_path):
    with pytest.raises(ValueError, match="async-buffer"):
        _cfg(tmp_path, async_buffer=0)


def test_timed_attack_requires_async(tmp_path):
    from attacking_federate_learning_tpu.attacks import make_attacker

    cfg = _cfg(tmp_path, aggregation="flat", async_buffer=0,
               backdoor="pattern")
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    attacker = make_attacker(cfg, dataset=ds, name="backdoor_timed")
    with pytest.raises(ValueError, match="async"):
        FederatedExperiment(cfg, attacker=attacker, dataset=ds)


def test_straggler_participation_rejection_names_async(tmp_path):
    """Satellite (ISSUE 9): the sync straggler ⊕ participation<1.0
    rejection must point at --aggregation async as the supported
    route."""
    with pytest.raises(ValueError, match="aggregation async"):
        _engine(_cfg(tmp_path, aggregation="flat", async_buffer=0,
                     participation=0.5,
                     faults=FaultConfig(straggler=0.1)))


# ---------------------------------------------------------------------------
# delivered-cohort attack seam

def test_alie_craft_uses_delivered_cohort_stats():
    rng = np.random.default_rng(0)
    mal = jnp.asarray(rng.normal(size=(4, 6)).astype(np.float32))
    stal = jnp.asarray([0, -1, 2, -1, 0, 0, 0, 0], jnp.int32)
    ctx = AttackContext(original_params=jnp.zeros(6),
                        learning_rate=jnp.float32(0.1),
                        staleness=stal)
    atk = DriftAttack(1.5)
    got = atk.craft(mal, ctx)
    delivered = np.asarray(stal)[:4] >= 0
    sub = np.asarray(mal)[delivered]
    want = sub.mean(0) - 1.5 * sub.std(0)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                               atol=1e-6)
    # Sync ctx (no staleness): the reference full-cohort stats.
    got_sync = atk.craft(mal, AttackContext(
        original_params=jnp.zeros(6), learning_rate=jnp.float32(0.1)))
    m, s = cohort_stats(mal)
    np.testing.assert_allclose(np.asarray(got_sync),
                               np.asarray(m - 1.5 * s), rtol=1e-5)


def test_masked_cohort_stats_full_mask_matches_cohort_stats():
    rng = np.random.default_rng(1)
    mal = jnp.asarray(rng.normal(size=(5, 8)).astype(np.float32))
    m1, s1 = cohort_stats(mal)
    m2, s2 = masked_cohort_stats(mal, jnp.ones((5,), bool))
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5,
                               atol=1e-6)


def test_timed_backdoor_run_and_asr(tmp_path):
    from attacking_federate_learning_tpu.attacks import make_attacker

    cfg = _cfg(tmp_path, users_count=10, mal_prop=0.2,
               defense="TrimmedMean", backdoor="pattern", epochs=6,
               test_step=3, async_buffer=6, staleness_weight="poly")
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    attacker = make_attacker(cfg, dataset=ds, name="backdoor_timed")
    assert attacker.timed and attacker.name == "backdoor_timed"
    exp = FederatedExperiment(cfg, attacker=attacker, dataset=ds)
    assert exp._async.timed
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="timed") as logger:
        exp.run(logger)
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    assert any(e.get("kind") == "asr" for e in events)
    assert np.isfinite(np.asarray(exp.state.weights)).all()


# ---------------------------------------------------------------------------
# fault composition

def test_async_faults_compose(tmp_path):
    faults = FaultConfig(dropout=0.2, straggler=0.2, corrupt=0.1,
                         straggler_delay=1, corrupt_mode="nan")
    cfg = _cfg(tmp_path, defense="Krum", async_max_staleness=3,
               faults=faults, epochs=10, test_step=5)
    exp, events = _run(cfg, "async_faults")
    assert int(exp.state.round) == 10
    assert np.isfinite(np.asarray(exp.state.weights)).all()
    av = [e for e in events if e.get("kind") == "async"]
    fv = sorted((e for e in events if e.get("kind") == "fault"),
                key=lambda e: e["round"])
    assert len(av) == 10 and len(fv) == 10
    # Injected counts match the shared fault_masks schedule.
    from attacking_federate_learning_tpu.core.faults import (
        fault_key, fault_masks
    )
    key = fault_key(cfg)
    for e in fv:
        drop, stale, corrupt = (np.asarray(x) for x in fault_masks(
            key, e["round"], exp.m, exp.m_mal, faults))
        assert int(e["injected_dropout"]) == int(drop.sum())
        assert int(e["injected_straggler"]) == int(stale.sum())
        assert int(e["injected_corrupt"]) == int(corrupt.sum())
    # Dropout + corruption reduce delivery: every nan-corrupted row
    # that reaches the pending pool must be quarantined, never
    # delivered (total quarantined == total corrupt arrivals that
    # survived supersession; at minimum the counter moves when
    # corruption fires).
    assert sum(int(e["quarantined"]) for e in av) >= 0
    total_corrupt = sum(int(e["injected_corrupt"]) for e in fv)
    if total_corrupt:
        # No corrupted row may be aggregated: a delivered nan would
        # have tripped the divergence watchdog / non-finite weights.
        assert np.isfinite(np.asarray(exp.state.weights)).all()


# ---------------------------------------------------------------------------
# preempt -> resume, buffers in the checkpoint extra arrays

def test_async_preempt_resume_bit_for_bit(tmp_path):
    """Acceptance (ISSUE 9): an async run preempted at a boundary and
    resumed from its auto-checkpoint — ring + pending buffers riding
    the ``extra=`` arrays — reaches the same final weights bit-for-bit
    as an uninterrupted run, with the journal exactly-once."""
    from attacking_federate_learning_tpu.utils.lifecycle import (
        GracefulShutdown, Preempted, RunJournal
    )

    cfg = _cfg(tmp_path, defense="TrimmedMean", epochs=10, test_step=5,
               staleness_weight="poly", checkpoint_every=3)

    # Uninterrupted reference run.
    ref, _ = _run(_cfg(tmp_path, defense="TrimmedMean", epochs=10,
                       test_step=5, staleness_weight="poly",
                       log_dir=str(tmp_path / "ref_logs"),
                       run_dir=str(tmp_path / "ref_runs")), "ref")

    exp = _engine(cfg)
    ck = Checkpointer(cfg)
    j = RunJournal(cfg.run_dir, "async_pr")
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="pr1") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, checkpointer=ck, journal=j,
                    shutdown=GracefulShutdown(preempt_at_round=4))
    # The auto-checkpoint carries the async buffers.
    state, extra = Checkpointer(cfg).resume(Checkpointer(cfg).latest(),
                                            with_extra=True)
    assert {"async_buf", "async_occ", "async_birth", "async_pbuf",
            "async_pocc", "async_pbirth"} <= set(extra)
    assert extra["async_occ"].dtype == np.bool_
    assert extra["async_birth"].dtype == np.int32

    resumed = _engine(cfg)
    resumed.state = state
    resumed.restore_carry_state(extra)
    j2 = RunJournal(cfg.run_dir, "async_pr")
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="pr2") as logger:
        resumed.run(logger, checkpointer=Checkpointer(cfg), journal=j2,
                    shutdown=GracefulShutdown(preempt_at_round=4))
    assert RunJournal(cfg.run_dir, "async_pr").verify(
        epochs=cfg.epochs, test_step=cfg.test_step) == []
    np.testing.assert_array_equal(np.asarray(resumed.state.weights),
                                  np.asarray(ref.state.weights))
    np.testing.assert_array_equal(np.asarray(resumed.state.velocity),
                                  np.asarray(ref.state.velocity))
    # The post-run async buffers agree bit-for-bit too.
    a, b = resumed.carry_state_host(), ref.carry_state_host()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


def test_async_span_equals_per_round_dispatch(tmp_path):
    """One scanned span and per-round dispatch reach identical state
    (the span is the same program scanned)."""
    cfg = _cfg(tmp_path, defense="Krum", epochs=6, test_step=6,
               staleness_weight="const")
    spanned = _engine(cfg)
    spanned.run_span(0, 6)
    stepped = _engine(cfg)
    for t in range(6):
        stepped.run_round(t)
    np.testing.assert_array_equal(np.asarray(spanned.state.weights),
                                  np.asarray(stepped.state.weights))
    a, b = spanned.carry_state_host(), stepped.carry_state_host()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])
