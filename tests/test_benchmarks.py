"""BASELINE-config benchmark runner smoke (benchmarks.py)."""

from attacking_federate_learning_tpu import benchmarks


def test_reference_default_cell_runs(tmp_path):
    results = benchmarks.main(["--rounds", "2", "--cells", "1",
                               "--scale", "0.4",
                               "--log-dir", str(tmp_path)])
    assert len(results) == 1
    cell = results[0]
    assert cell["cell"] == "ref_default"
    assert cell["rounds_per_sec"] > 0
    assert 0.0 <= cell["final_accuracy"] <= 100.0


def test_unknown_cell_selection_is_empty(tmp_path):
    assert benchmarks.main(["--cells", "9",
                            "--log-dir", str(tmp_path)]) == []


def test_model_dataset_family_validation():
    import pytest
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig

    with pytest.raises(ValueError, match="shaped"):
        ExperimentConfig(dataset=C.MNIST, model="resnet20")
    with pytest.raises(ValueError, match="shaped"):
        ExperimentConfig(dataset=C.CIFAR10, model="mnist_cnn")
    # compatible pairings construct fine
    ExperimentConfig(dataset=C.CIFAR10, model="resnet20")
    ExperimentConfig(dataset=C.SYNTH_MNIST, model="mnist_cnn")


def test_strict_exits_nonzero_on_failed_cell(tmp_path, monkeypatch):
    """VERDICT r2 #10: --strict (default) must distinguish 'cell failed'
    from 'cell not requested' with a nonzero exit."""
    import pytest

    def boom(*a, **k):
        raise RuntimeError("injected cell failure")

    monkeypatch.setattr(benchmarks, "run_cell", boom)
    with pytest.raises(SystemExit, match="ref_default"):
        benchmarks.main(["--rounds", "1", "--cells", "1",
                         "--log-dir", str(tmp_path)])
    # --no-strict keeps the record-and-continue behavior.
    results = benchmarks.main(["--rounds", "1", "--cells", "1",
                               "--no-strict", "--log-dir", str(tmp_path)])
    assert results[0]["failed"].startswith("RuntimeError")
