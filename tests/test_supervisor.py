"""tools/supervisor.py: classification, backoff, the degradation
ladder, resume gating, and the supervised crash-matrix smoke (one cell
end to end through real subprocesses).
"""

import argparse
import importlib.util
import json
import os

import numpy as np
import pytest


def _load(name):
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _opts(**kw):
    kw.setdefault("raw", False)
    kw.setdefault("max_retries", 3)
    kw.setdefault("backoff_base", 2.0)
    kw.setdefault("backoff_max", 60.0)
    kw.setdefault("checkpoint_every", 5)
    kw.setdefault("stall_timeout", 0.0)
    kw.setdefault("stall_grace", 30.0)
    kw.setdefault("poll_interval", 1.0)
    kw.setdefault("run_id", None)
    kw.setdefault("events", None)
    kw.setdefault("verify_journal", False)
    kw.setdefault("inject_preempt_round", None)
    return argparse.Namespace(**kw)


def _sup(sup_mod, child, **kw):
    return sup_mod.Supervisor(_opts(**kw), child)


CHILD = ["-s", "SYNTH_MNIST", "-e", "6", "-c", "32", "--backend", "cpu"]


def test_degradation_ladder_oom_mesh_then_batch(tmp_path):
    sup = _load("supervisor")
    s = _sup(sup, CHILD + ["--mesh-shape", "8,1"],
             events=str(tmp_path / "e.jsonl"))
    # OOM #1: relax the MeshPlan first (cheapest semantic change).
    assert s.degrade_for("oom") == "mesh_relaxed"
    assert s.degrade_flags[-2:] == ["--mesh-shape", "none"]
    # OOM #2+: halve the client-batch chunk, floor 1.
    assert s.degrade_for("oom") == "batch_halved_to_16"
    assert s.degrade_for("oom") == "batch_halved_to_8"
    ns = s._effective_ns()
    assert ns.batch_size == 8 and ns.mesh_shape == "none"


def test_degradation_ladder_batch_floor(tmp_path):
    sup = _load("supervisor")
    s = _sup(sup, CHILD + ["-c", "1"], events=str(tmp_path / "e.jsonl"))
    assert s.degrade_for("oom") is None        # floor: plain retry


def test_degradation_ladder_backend_cpu_once(tmp_path):
    sup = _load("supervisor")
    s = _sup(sup, ["-s", "SYNTH_MNIST", "--backend", "tpu"],
             events=str(tmp_path / "e.jsonl"))
    assert s.degrade_for("backend") == "cpu_fallback"
    assert s.degrade_flags[-2:] == ["--backend", "cpu"]
    assert s.degrade_for("backend") is None    # already on CPU


def test_degradation_ladder_stall_staged_on_repeat(tmp_path):
    sup = _load("supervisor")
    s = _sup(sup, CHILD, events=str(tmp_path / "e.jsonl"))
    s.class_counts["stall"] = 1
    assert s.degrade_for("stall") is None      # first stall: retry only
    s.class_counts["stall"] = 2
    assert s.degrade_for("stall") == "staged_fallback"
    assert "--backdoor-staged" in s.degrade_flags
    s.class_counts["stall"] = 3
    assert s.degrade_for("stall") is None      # applied once


def test_degradation_ladder_async_falls_back_to_sync_first(tmp_path):
    """ISSUE 9 satellite: an async-mode stall degrades to synchronous
    rounds (--aggregation flat) BEFORE the staged per-round fallback —
    the buffered span is the largest program the async engine
    compiles, and the sync path is the known-good baseline."""
    sup = _load("supervisor")
    s = _sup(sup, CHILD + ["--aggregation", "async",
                           "--async-buffer", "8"],
             events=str(tmp_path / "e.jsonl"))
    s.class_counts["stall"] = 1
    assert s.degrade_for("stall") is None      # first stall: retry only
    s.class_counts["stall"] = 2
    assert s.degrade_for("stall") == "async_sync_fallback"
    assert s.degrade_flags[-2:] == ["--aggregation", "flat"]
    assert s._effective_ns().aggregation == "flat"
    # A further stall takes the staged step — the last resort.
    s.class_counts["stall"] = 3
    assert s.degrade_for("stall") == "staged_fallback"
    assert "--backdoor-staged" in s.degrade_flags


def test_backoff_exponential_and_preempt_free(tmp_path):
    """Decorrelation jitter (ISSUE 17 satellite): every sleep lands in
    the upper half of the exponential envelope [env/2, env] with
    env = min(backoff_max, backoff_base * 2**(failures-1)); preempts
    stay free; two supervisors with different jitter streams draw
    DIFFERENT sleeps from the same envelope (no lockstep retry
    storms)."""
    import random

    sup = _load("supervisor")
    s = _sup(sup, CHILD, backoff_base=2.0, backoff_max=9.0,
             events=str(tmp_path / "e.jsonl"))
    assert s.backoff("preempted") == 0.0
    for failures, env in ((1, 2.0), (2, 4.0), (3, 8.0), (5, 9.0),
                          (9, 9.0)):
        s.failures = failures
        for _ in range(20):
            b = s.backoff("crash")
            assert env / 2.0 <= b <= env, (failures, b)
    # Decorrelation: identical configs, different streams -> different
    # sleeps (the seeded-injection test surface backoff() documents).
    s.rng = random.Random(1)
    s2 = _sup(sup, CHILD, backoff_base=2.0, backoff_max=9.0,
              events=str(tmp_path / "e2.jsonl"))
    s2.rng = random.Random(2)
    s.failures = s2.failures = 2
    assert s.backoff("crash") != s2.backoff("crash")
    # Injected identical streams reproduce exactly (tests/campaigns can
    # pin schedules).
    s.rng = random.Random(7)
    s2.rng = random.Random(7)
    assert s.backoff("crash") == s2.backoff("crash")


def test_resume_gated_on_own_progress(tmp_path):
    """The first attempt must NOT adopt a stale checkpoint from some
    other experiment in the shared runs/<dataset>/ dir; after this
    run-id has progress (manifest exists), resume kicks in."""
    sup = _load("supervisor")
    child = CHILD + ["--run-dir", str(tmp_path / "runs")]
    s = _sup(sup, child, events=str(tmp_path / "e.jsonl"))
    ckdir = tmp_path / "runs" / "SYNTH_MNIST"
    os.makedirs(ckdir)
    np.savez(ckdir / "checkpoint.npz", weights=np.zeros(3))  # a stranger's
    assert "--resume" not in s.build_cmd(attempt=1)
    assert "--resume" in s.build_cmd(attempt=2)
    # A prior manifest for THIS run-id makes even attempt 1 resume (the
    # supervisor itself was restarted mid-run).
    os.makedirs(tmp_path / "runs" / s.run_id, exist_ok=True)
    with open(tmp_path / "runs" / s.run_id / "manifest.json", "w") as f:
        json.dump({"status": "preempted"}, f)
    assert "--resume" in s.build_cmd(attempt=1)
    # Journal flags are always pinned.
    cmd = s.build_cmd(attempt=1)
    assert "--journal" in cmd and "--run-id" in cmd


def test_supervisor_emits_valid_v3_events(tmp_path):
    from attacking_federate_learning_tpu.utils.metrics import (
        SCHEMA_VERSION, iter_events
    )

    sup = _load("supervisor")
    s = _sup(sup, CHILD, events=str(tmp_path / "e.jsonl"))
    s.emit("supervise_start", max_retries=3)
    s.emit("degrade", failure="oom", step="batch_halved_to_16")
    events = list(iter_events(str(tmp_path / "e.jsonl")))
    assert [e["phase"] for e in events] == ["supervise_start", "degrade"]
    # 'lifecycle' needs >= v3 (KIND_MIN_VERSION); the writer stamps the
    # current schema version (v4 since the cross-run observatory).
    assert all(e["v"] == SCHEMA_VERSION and e["v"] >= 3 for e in events)


def test_event_age_heartbeat_aware(tmp_path):
    """Stall detection must read the last heartbeat's REAL-event age —
    the heartbeat keeps the file mtime fresh precisely while stalled,
    so mtime alone would mask the stall it exists to expose."""
    import time

    sup = _load("supervisor")
    s = _sup(sup, CHILD, events=str(tmp_path / "e.jsonl"))
    p = str(tmp_path / "run.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"kind": "round", "round": 3, "v": 1}) + "\n")
        f.write(json.dumps({"kind": "heartbeat", "rss_mb": 1.0,
                            "last_event_age_s": 612.5, "v": 2}) + "\n")
    assert s._event_age(p, time.time()) == 612.5
    # Real event last: fall back to file mtime (fresh file, tiny age).
    with open(p, "a") as f:
        f.write(json.dumps({"kind": "round", "round": 4, "v": 1}) + "\n")
    assert s._event_age(p, time.time()) < 5.0
    # Missing file: age since child start.
    assert s._event_age(str(tmp_path / "nope.jsonl"),
                        time.time() - 42.0) >= 42.0


def test_raw_mode_passthrough(tmp_path):
    sup = _load("supervisor")
    s = _sup(sup, ["echo", "hi"], raw=True,
             events=str(tmp_path / "e.jsonl"))
    assert s.build_cmd(attempt=1) == ["echo", "hi"]
    assert s.degrade_for("oom") is None


def test_main_requires_child_args():
    sup = _load("supervisor")
    with pytest.raises(SystemExit):
        sup.main(["--max-retries", "2"])


# ---------------------------------------------------------------------------
# end to end: one crash-matrix cell through real subprocesses (the full
# matrix runs in tools/smoke.sh; this pins the CI-visible contract)

def test_crash_matrix_single_cell(tmp_path):
    cm = _load("crash_matrix")
    rc = cm.main(["--modes", "fused", "--defenses", "Krum",
                  "--epochs", "6", "--workdir", str(tmp_path)])
    assert rc == 0
    # The audited artifacts exist where the matrix says they do.
    run_dir = tmp_path / "fused_Krum" / "runs"
    from attacking_federate_learning_tpu.utils.lifecycle import RunJournal
    j = RunJournal(str(run_dir), "crash_fused_Krum")
    assert j.verify(epochs=6, test_step=5) == []
    assert j.read_manifest()["status"] == "done"
