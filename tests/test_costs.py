"""Compile-and-cost observatory: static HLO accounting (utils/costs.py),
the engine's cost_report, schema-v2 events (compile/cost/heartbeat),
the RunLogger heartbeat thread, and the deterministic perf gate
(tools/perf_gate.py).

Acceptance contract (ISSUE 3): the gate passes against a freshly
generated baseline on CPU, fails loudly (nonzero exit, named metric)
when a defense kernel's FLOPs are inflated, cost/compile/heartbeat
events round-trip through check_events, and running the cost report
leaves the round program's HLO byte-identical.
"""

import importlib.util
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu import report
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import (
    ExperimentConfig, FaultConfig
)
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.utils import costs
from attacking_federate_learning_tpu.utils.metrics import (
    RunLogger, SCHEMA_VERSION, validate_event
)


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 9)
    kw.setdefault("mal_prop", 0.22)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 4)
    kw.setdefault("test_step", 4)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("log_dir", str(tmp_path))
    return ExperimentConfig(**kw)


def _exp(cfg, **kw):
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    kw.setdefault("attacker", DriftAttack(1.0))
    return FederatedExperiment(cfg, dataset=ds, **kw)


# ---------------------------------------------------------------------------
# utils/costs.py primitives

def test_analyze_lowered_facts_present_and_deterministic():
    """cost_analysis/memory_analysis land in the record, and two
    analyses of the same program agree exactly (the determinism the
    perf gate stands on)."""
    fn = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.ones((32, 64), jnp.float32)
    a = costs.analyze_lowered("gram", fn.lower(x))
    b = costs.analyze_lowered("gram", fn.lower(x))
    assert a.flops > 0 and a.bytes_accessed > 0
    assert a.argument_bytes == 32 * 64 * 4
    assert a.peak_bytes >= a.argument_bytes
    assert a.gate_facts() == b.gate_facts()
    # Event payloads validate against schema v2.
    validate_event({**a.cost_event(), "v": SCHEMA_VERSION})
    validate_event({**a.compile_event(), "v": SCHEMA_VERSION})


def test_cost_scales_with_problem_size():
    """More clients -> more distance FLOPs: the facts are real numbers,
    not placeholders (the O(n^2 d) Krum story becomes measurable)."""
    from attacking_federate_learning_tpu.defenses.kernels import krum

    d = 512
    recs = {}
    for n in (8, 16):
        G = jnp.zeros((n, d), jnp.float32)
        fn = jax.jit(krum, static_argnums=(1, 2))
        recs[n] = costs.analyze_lowered(f"krum{n}", fn.lower(G, n, 2))
    assert recs[16].flops > 2.5 * recs[8].flops


def test_cache_counters_install_idempotent():
    costs.install_cache_counters()
    costs.install_cache_counters()
    counts = costs.cache_counts()
    assert set(counts) == {"hits", "misses"}
    assert counts["hits"] >= 0 and counts["misses"] >= 0


# ---------------------------------------------------------------------------
# engine.cost_report

def test_cost_report_fused_entries_and_events(tmp_path):
    cfg = _cfg(tmp_path, defense="Krum")
    exp = _exp(cfg)
    with RunLogger(cfg, None, str(tmp_path), jsonl_name="cr") as logger:
        ledger = exp.cost_report(logger)
    assert not ledger.errors
    names = [r.name for r in ledger.records]
    assert names == ["fused_round", "fused_span", "defense_Krum", "eval"]
    for rec in ledger.records:
        assert rec.flops > 0, rec.name
        assert rec.peak_bytes > 0, rec.name
        assert rec.cache in ("hit", "miss", "uncached")
    # The defense kernel is strictly cheaper than the round containing it.
    by = {r.name: r for r in ledger.records}
    assert by["defense_Krum"].flops < by["fused_round"].flops
    with open(logger.jsonl_path) as f:
        evs = [json.loads(line) for line in f]
    assert sum(e["kind"] == "compile" for e in evs) == 4
    assert sum(e["kind"] == "cost" for e in evs) == 4
    # ISSUE 15: every analyzed entry carries its stage attribution, and
    # the run carries exactly one per-seam wire ledger.
    assert sum(e["kind"] == "stage_cost" for e in evs) == 4
    assert sum(e["kind"] == "wire_bytes" for e in evs) == 1
    for e in evs:
        validate_event(e)


def test_cost_report_mode_specific_entries(tmp_path):
    # Telemetry adds the tele_span program.
    exp = _exp(_cfg(tmp_path, defense="Krum", telemetry=True))
    names = [r.name for r in exp.cost_report().records]
    assert "tele_span" in names
    # Faults swap the span for the fault span.
    exp = _exp(_cfg(tmp_path, defense="Median",
                    faults=FaultConfig(dropout=0.2)))
    names = [r.name for r in exp.cost_report().records]
    assert "fault_span" in names and "fused_span" not in names
    # The staged path (backdoor_fused=False) analyzes its stages; on the
    # CPU backend a Krum/Bulyan aggregate runs eagerly (host BLAS), so
    # only compute_grads has a compiled program — use TrimmedMean, whose
    # aggregate stays jitted.
    cfg = _cfg(tmp_path, users_count=8, mal_prop=0.25, defense="TrimmedMean",
               backdoor="pattern", backdoor_fused=False, synth_train=512)
    from attacking_federate_learning_tpu.attacks import make_attacker

    ds = load_dataset(cfg.dataset, seed=0, synth_train=512, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                              dataset=ds)
    ledger = exp.cost_report()
    names = [r.name for r in ledger.records]
    assert "compute_grads" in names and "aggregate" in names
    assert not ledger.errors


def test_cost_report_leaves_round_hlo_byte_identical(tmp_path):
    """Acceptance: the observatory is an observer — running it must not
    change the compiled round program (same pin methodology as the
    telemetry/fault bit-identity tests)."""
    ds = load_dataset(C.SYNTH_MNIST, seed=0, synth_train=256, synth_test=64)

    def lowered_text(run_report):
        cfg = _cfg(tmp_path, defense="Krum")
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
        if run_report:
            exp.cost_report()
        return exp._fused_round.lower(
            exp.state, jnp.asarray(0, jnp.int32)).as_text()

    assert lowered_text(False) == lowered_text(True)


# ---------------------------------------------------------------------------
# heartbeat

def test_heartbeat_thread_emits_and_stops(tmp_path):
    cfg = _cfg(tmp_path)
    with RunLogger(cfg, None, str(tmp_path), jsonl_name="hb",
                   heartbeat_every=0.05) as logger:
        logger.record(kind="round", round=0)
        time.sleep(0.18)
        logger.record(kind="round", round=3)
        time.sleep(0.12)
        path = logger.jsonl_path
    # Thread stopped: no writes after close.
    time.sleep(0.15)
    with open(path) as f:
        evs = [json.loads(line) for line in f]
    beats = [e for e in evs if e["kind"] == "heartbeat"]
    assert len(beats) >= 3
    for e in beats:
        validate_event(e)
        assert e["rss_mb"] > 0 and e["last_event_age_s"] >= 0
    # Round progress rides along once seen; the EMA appears after two
    # distinct rounds.
    assert beats[-1]["round"] == 3
    assert any("rounds_per_s" in e for e in beats)
    # The age tracks REAL events only — a beat never resets the clock:
    # ages grow monotonically between the two round events.
    stall = [e["last_event_age_s"] for e in beats if e["t"] < 0.18]
    assert stall == sorted(stall)
    with pytest.raises(ValueError, match="finish"):
        logger.record(kind="round", round=4)


def test_heartbeat_off_by_default(tmp_path):
    cfg = _cfg(tmp_path)
    with RunLogger(cfg, None, str(tmp_path), jsonl_name="nohb") as logger:
        assert logger._hb_thread is None
        logger.record(kind="round", round=0)
        path = logger.jsonl_path
    with open(path) as f:
        assert all(json.loads(line)["kind"] != "heartbeat" for line in f)


# ---------------------------------------------------------------------------
# schema v2 / check_events

def _load_tool(name):
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_v2_kinds_and_version_rules():
    validate_event({"kind": "compile", "name": "x", "compile_s": 0.1,
                    "cache": "hit", "v": 2})
    validate_event({"kind": "cost", "name": "x", "flops": 1.0,
                    "bytes_accessed": 2.0, "peak_bytes": 3, "v": 2})
    validate_event({"kind": "heartbeat", "rss_mb": 1.0,
                    "last_event_age_s": 0.0, "v": 2})
    # v1 events stay valid (old logs readable by the new reader).
    validate_event({"kind": "round", "round": 1, "v": 1})
    # A v2-only kind stamped v1 is an emitter bug.
    with pytest.raises(ValueError, match="need schema v2"):
        validate_event({"kind": "heartbeat", "rss_mb": 1.0,
                        "last_event_age_s": 0.0, "v": 1})
    # Unknown versions name the version, not the kind (a newer writer's
    # kinds are unknowable here).
    with pytest.raises(ValueError, match="newer writer"):
        validate_event({"kind": "from_the_future", "v": 99})


def test_check_events_handles_v2_and_unknown_versions(tmp_path):
    ce = _load_tool("check_events")
    path = os.path.join(str(tmp_path), "v2.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "compile", "name": "a",
                            "compile_s": 0.5, "cache": "miss",
                            "v": 2}) + "\n")
        f.write(json.dumps({"kind": "cost", "name": "a", "flops": 1.0,
                            "bytes_accessed": 1.0, "peak_bytes": 1,
                            "v": 2}) + "\n")
        f.write(json.dumps({"kind": "heartbeat", "rss_mb": 5.0,
                            "last_event_age_s": 0.1, "v": 2}) + "\n")
    counts, legacy, errors = ce.check_file(path)
    assert not errors
    assert counts == {"compile": 1, "cost": 1, "heartbeat": 1}
    assert ce.main([path]) == 0
    from attacking_federate_learning_tpu.utils.metrics import (
        SUPPORTED_VERSIONS
    )

    bad = os.path.join(str(tmp_path), "future.jsonl")
    with open(bad, "w") as f:
        # One past the newest supported version — stays "the future"
        # across schema bumps instead of hard-coding a constant.
        f.write(json.dumps({"kind": "quantum_trace",
                            "v": max(SUPPORTED_VERSIONS) + 1}) + "\n")
    counts, legacy, errors = ce.check_file(bad)
    assert len(errors) == 1 and "newer writer" in errors[0][1]
    assert ce.main([bad]) == 1


# ---------------------------------------------------------------------------
# report: compile & cost table

def test_report_compile_cost_table(tmp_path, capsys):
    from attacking_federate_learning_tpu import cli

    cfg = _cfg(tmp_path, defense="Krum")
    exp = _exp(cfg)
    with RunLogger(cfg, None, str(tmp_path), jsonl_name="cctab") as logger:
        exp.cost_report(logger)
        logger.record(**logger.heartbeat_fields())
        path = logger.jsonl_path
    capsys.readouterr()
    assert cli.main(["report", "--json", path]) == 0
    out = json.loads(capsys.readouterr().out)[path]
    cc = out["compile_cost"]
    assert {r["name"] for r in cc["entries"]} == {
        "fused_round", "fused_span", "defense_Krum", "eval"}
    for r in cc["entries"]:
        assert r["flops"] > 0 and r["peak_bytes"] > 0
    assert out["heartbeat"]["beats"] == 1
    assert cli.main(["report", path]) == 0
    text = capsys.readouterr().out
    assert "compile & cost" in text and "defense_Krum" in text


# ---------------------------------------------------------------------------
# tools/perf_gate.py (satellite: CI smoke next to fault_matrix)

def test_perf_gate_roundtrip_and_inflation_detection(tmp_path, capsys):
    """Acceptance: the gate passes against a freshly generated baseline,
    and an artificially inflated defense-kernel FLOP count fails with a
    nonzero exit naming the metric."""
    pg = _load_tool("perf_gate")
    baseline = os.path.join(str(tmp_path), "base.json")
    # One distance cell keeps the test inside CI budget (the compiles
    # are persistent-cache-warmed after the first run).
    argv = ["--baseline", baseline, "--cells", "krum"]
    assert pg.main(argv + ["--update"]) == 0
    assert pg.main(argv) == 0
    capsys.readouterr()

    with open(baseline) as f:
        doc = json.load(f)
    doc["cells"]["krum"]["defense_Krum"]["flops"] *= 2
    with open(baseline, "w") as f:
        json.dump(doc, f)
    assert pg.main(argv) == 1
    out = capsys.readouterr().out
    assert "krum.defense_Krum.flops" in out


def test_perf_gate_env_mismatch_skips_unless_strict(tmp_path, capsys):
    pg = _load_tool("perf_gate")
    baseline = os.path.join(str(tmp_path), "base.json")
    argv = ["--baseline", baseline, "--cells", "nodefense"]
    assert pg.main(argv + ["--update"]) == 0
    with open(baseline) as f:
        doc = json.load(f)
    doc["env"]["jax"] = "9.9.9"
    with open(baseline, "w") as f:
        json.dump(doc, f)
    capsys.readouterr()
    assert pg.main(argv) == 0
    assert "SKIP" in capsys.readouterr().out
    assert pg.main(argv + ["--strict-env"]) == 1


def test_perf_gate_missing_baseline_is_exit_2(tmp_path):
    pg = _load_tool("perf_gate")
    assert pg.main(["--baseline",
                    os.path.join(str(tmp_path), "nope.json")]) == 2


def test_checked_in_baseline_matches_this_environment():
    """The repo's PERF_BASELINE.json was generated on this box; the
    gate must treat it as comparable (env match) — otherwise every CI
    run silently skips and the gate is dead weight."""
    pg = _load_tool("perf_gate")
    if not os.path.exists(pg.BASELINE):
        pytest.skip("no checked-in baseline")
    with open(pg.BASELINE) as f:
        doc = json.load(f)
    assert doc["env"] == pg.environment()
    # And the cheapest cell actually gates clean against it.
    assert pg.main(["--cells", "nodefense"]) == 0


# ---------------------------------------------------------------------------
# stage & wire ledger (ISSUE 15)

def _round_compiled(exp):
    """Lower + compile the engine's round entry (the program
    --stageproof gates; signature varies by topology)."""
    t0 = jnp.asarray(0, jnp.int32)
    if exp._async is not None:
        return exp._fused_round.lower(
            exp.state, t0, exp._async_state, None).compile()
    if exp.faults is not None:
        return exp._fused_round.lower(
            exp.state, t0, exp._fault_state, None).compile()
    return exp._fused_round.lower(exp.state, t0).compile()


# Topology overrides per defense family.  Bulyan's 4f+3 validity bound
# needs wider cohorts: n=11/f=2 flat (the perf-gate pinned base), the
# gate's hier_bulyan shape for two-tier (megabatch >= 4*f1+3), and a
# full-cohort buffer under async (k=11 >= 4f+3).
_TOPO = {
    "flat": dict(),
    "hierarchical": dict(aggregation="hierarchical", users_count=12,
                         mal_prop=0.25, megabatch=4),
    "async": dict(aggregation="async", async_buffer=8),
}
_TOPO_BULYAN = {
    "flat": dict(users_count=11, mal_prop=0.2),
    "hierarchical": dict(aggregation="hierarchical", users_count=24,
                         mal_prop=0.125, megabatch=8,
                         tier2_defense="TrimmedMean"),
    "async": dict(aggregation="async", users_count=11, mal_prop=0.2,
                  async_buffer=11),
}


@pytest.mark.parametrize("topology", ["flat", "hierarchical", "async"])
@pytest.mark.parametrize("defense",
                         ["Krum", "TrimmedMean", "Bulyan", "Median"])
def test_stage_attribution_partitions_round(tmp_path, defense, topology):
    """Acceptance (ISSUE 15): on every tier-1 defense x topology the
    stage partition sums to XLA's own whole-program totals exactly,
    coverage clears the --stageproof bar, and the stages that must be
    populated are (tier2_aggregate appears on the two-tier topology
    and ONLY there)."""
    import math

    over = (_TOPO_BULYAN if defense == "Bulyan" else _TOPO)[topology]
    exp = _exp(_cfg(tmp_path, defense=defense, **over))
    compiled = _round_compiled(exp)
    facts = costs.compiled_cost_facts(compiled)
    att = costs.stage_attribution(compiled.as_text(), facts)
    for metric in ("flops", "bytes_accessed", "temp_bytes"):
        parts = [att["stages"][s][metric] for s in costs.STAGES]
        parts.append(att["unattributed"][metric])
        assert math.isclose(math.fsum(parts), facts[metric],
                            rel_tol=1e-9, abs_tol=1e-6), metric
    assert att["coverage"]["flops"] >= 0.95
    assert att["stages"]["deliver"]["flops"] > 0
    assert att["stages"]["tier1_aggregate"]["flops"] > 0
    assert att["stages"]["apply"]["flops"] > 0
    if topology == "hierarchical":
        assert att["stages"]["tier2_aggregate"]["flops"] > 0
    else:
        assert att["stages"]["tier2_aggregate"]["flops"] == 0


def test_pallas_cell_attributes_to_tier1(tmp_path):
    """The pallas defense-kernel dispatch is scoped: its (interpret-
    mode, on CPU) compute books under tier1_aggregate, not
    unattributed."""
    exp = _exp(_cfg(tmp_path, defense="Krum", aggregation_impl="pallas"))
    compiled = _round_compiled(exp)
    att = costs.stage_attribution(compiled.as_text(),
                                  costs.compiled_cost_facts(compiled))
    assert att["stages"]["tier1_aggregate"]["flops"] > 0
    assert att["stages"]["tier1_aggregate"]["bytes_accessed"] > 0


def test_stage_scopes_are_metadata_only(tmp_path):
    """Scopes off must leave the compiled program identical up to
    metadata: the canonicalized fingerprint matches, while the
    annotated text itself differs (the scopes ARE there)."""
    ds = load_dataset(C.SYNTH_MNIST, seed=0, synth_train=256,
                      synth_test=64)

    def compiled_text(on):
        prev = costs.set_stage_scopes(on)
        try:
            cfg = _cfg(tmp_path, defense="Krum")
            exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                      dataset=ds)
            return _round_compiled(exp).as_text()
        finally:
            costs.set_stage_scopes(prev)

    on, off = compiled_text(True), compiled_text(False)
    assert costs.hlo_fingerprint(on) == costs.hlo_fingerprint(off)
    assert "tier1_aggregate" in on and "tier1_aggregate" not in off


def test_wire_ledger_seam_math():
    """Pure seam pricing: every seam the topology crosses, nothing it
    doesn't, totals additive, and the hierarchical seam is the PR 12
    S*d*4 collective identity."""
    flat = costs.wire_ledger(cohort=16, dim=100)
    assert set(flat["seams"]) == {"broadcast", "client_update"}
    assert flat["seams"]["broadcast"]["bytes"] == 16 * 100 * 4
    assert flat["total_bytes"] == 2 * 16 * 100 * 4

    hier = costs.wire_ledger(cohort=64, dim=79510,
                             topology="hierarchical", num_shards=8,
                             megabatch=8, spmd_parts=4)
    assert hier["seams"]["tier1_to_tier2"]["bytes"] == 8 * 79510 * 4
    assert hier["seams"]["tier1_to_tier2"]["collective"] is True

    sa = costs.wire_ledger(cohort=12, dim=100, secagg="vanilla",
                           dropped=2)
    assert sa["seams"]["secagg_mask_exchange"]["bytes"] == 66 * 32
    assert sa["seams"]["secagg_recovery"]["bytes"] == 2 * 11 * 32
    gw = costs.wire_ledger(cohort=12, dim=100, secagg="groupwise",
                           topology="hierarchical", num_shards=3,
                           megabatch=4)
    assert gw["seams"]["secagg_mask_exchange"]["bytes"] == 3 * 6 * 32

    asy = costs.wire_ledger(cohort=12, dim=100, topology="async",
                            async_buffer=8)
    assert asy["seams"]["async_delivery"]["bytes"] == 8 * 100 * 4
    for led in (flat, hier, sa, gw, asy):
        assert led["total_bytes"] == sum(
            s["bytes"] for s in led["seams"].values())


def test_engine_wire_ledger_matches_topology(tmp_path):
    """FederatedExperiment.wire_ledger() fills the seam parameters from
    the live engine: hierarchical carries the S*d*4 seam sized by ITS
    placement."""
    exp = _exp(_cfg(tmp_path, defense="Krum", aggregation="hierarchical",
                    users_count=12, mal_prop=0.25, megabatch=4))
    led = exp.wire_ledger()
    S = exp._placement.num_shards
    assert led["seams"]["tier1_to_tier2"]["bytes"] == S * exp.flat.dim * 4
    assert led["seams"]["broadcast"]["bytes"] == exp.m * exp.flat.dim * 4


def test_v9_kinds_and_version_rules():
    validate_event({"kind": "stage_cost", "name": "fused_round",
                    "stages": {"deliver": {"flops": 1.0}},
                    "unattributed": {"flops": 0.0},
                    "coverage": {"flops": 0.99}, "v": 9})
    validate_event({"kind": "wire_bytes", "topology": "flat",
                    "seams": {"broadcast": {"bytes": 4}},
                    "total_bytes": 4, "v": 9})
    # A v9-only kind stamped v8 is an emitter bug.
    with pytest.raises(ValueError, match="need schema v9"):
        validate_event({"kind": "wire_bytes", "topology": "flat",
                        "seams": {}, "total_bytes": 0, "v": 8})


def test_no_reporting_means_no_ledger_events(tmp_path):
    """The telemetry-off invariant: without --cost-report nothing emits
    stage_cost/wire_bytes (cost_report without a logger writes no file;
    a plain logged run carries neither kind)."""
    cfg = _cfg(tmp_path, defense="Krum")
    exp = _exp(cfg)
    ledger = exp.cost_report()         # no logger: analysis only
    assert ledger.wire is not None     # the facts exist...
    with RunLogger(cfg, None, str(tmp_path), jsonl_name="plain") as lg:
        lg.record(kind="round", round=0)
        path = lg.jsonl_path
    with open(path) as f:              # ...but never reached the log
        kinds = {json.loads(line)["kind"] for line in f}
    assert "stage_cost" not in kinds and "wire_bytes" not in kinds


# ---------------------------------------------------------------------------
# runs attribution (registry verb over the banked v9 events)

@pytest.fixture(scope="module")
def attr_store(tmp_path_factory):
    from attacking_federate_learning_tpu import cli

    tmp = tmp_path_factory.mktemp("attr")
    base = ["-s", "SYNTH_MNIST", "-e", "4", "-c", "16", "-n", "9",
            "-m", "0.22", "--synth-train", "256", "--synth-test", "64",
            "--log-dir", str(tmp / "logs"), "--run-dir", str(tmp / "runs"),
            "--journal", "--no-checkpoint"]
    cli.main(base + ["-d", "Krum", "--cost-report", "--run-id", "attrA"])
    cli.main(base + ["-d", "TrimmedMean", "--cost-report",
                     "--run-id", "attrB"])
    cli.main(base + ["-d", "Krum", "--run-id", "plain"])
    return tmp


def _runs(store, *verb):
    from attacking_federate_learning_tpu import cli

    return cli.main(["runs", "--run-dir", str(store / "runs"),
                     "--bench", "", "--progress", ""] + list(verb))


def test_runs_attribution_single_and_diff(attr_store, capsys):
    assert _runs(attr_store, "attribution", "attrA") == 0
    out = capsys.readouterr().out
    assert "tier1_aggregate" in out and "broadcast" in out
    assert "coverage" in out
    assert _runs(attr_store, "attribution", "attrA", "attrB") == 0
    out = capsys.readouterr().out
    assert "attrA" in out and "attrB" in out
    assert "tier1_aggregate" in out


def test_runs_attribution_json(attr_store, capsys):
    assert _runs(attr_store, "--json", "attribution", "attrA") == 0
    out = capsys.readouterr().out
    # The registry refresh banner precedes the payload; parse from the
    # first JSON line.
    doc = json.loads(out[out.index("{"):])
    att = doc["attrA"]
    assert "fused_round" in att["stages"]
    assert att["wire"]["total_bytes"] > 0


def test_runs_attribution_without_events_exits_1(attr_store, capsys):
    assert _runs(attr_store, "attribution", "plain") == 1
    assert "--cost-report" in capsys.readouterr().out


def test_cost_report_run_log_validates(attr_store):
    """The --cost-report run's private log round-trips check_events
    (v9 kinds included), and the plain run carries neither kind."""
    ce = _load_tool("check_events")
    counts, _, errors = ce.check_file(
        str(attr_store / "logs" / "attrA.jsonl"))
    assert not errors
    assert counts["stage_cost"] >= 4 and counts["wire_bytes"] == 1
    counts, _, errors = ce.check_file(
        str(attr_store / "logs" / "plain.jsonl"))
    assert not errors
    assert "stage_cost" not in counts and "wire_bytes" not in counts


# ---------------------------------------------------------------------------
# bench embedding (the RESULT fields, not a full bench run)

def test_bench_result_embeds_env_and_cache(tmp_path):
    """bench.py's emitted JSON carries env attribution and cache counts
    (satellite).  Emulated: emit_result_json on a seeded RESULT — a
    full bench run is minutes, the contract is the field set."""
    import bench

    bench.RESULT.clear()
    prev = bench._EMITTED
    bench._EMITTED = False
    try:
        bench.RESULT.update(metric="x", value=1.0, env={"jax": "0.0"})
        import contextlib
        import io
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            bench.emit_result_json()
        rec = json.loads(buf.getvalue())
        assert rec["env"] == {"jax": "0.0"}
        assert set(rec["compile_cache"]) == {"hits", "misses"}
    finally:
        bench.RESULT.clear()
        bench._EMITTED = prev
