"""RunLogger (tee, CSV schema, JSONL records, context manager), the
event schema, and the profiling hooks (PhaseTimer, xla_trace)."""

import json
import os
import time

import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.utils.metrics import (
    RunLogger, SCHEMA_VERSION, iter_events, validate_event
)
from attacking_federate_learning_tpu.utils.profiling import (
    PhaseTimer, xla_trace
)


def make_cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("log_dir", str(tmp_path))
    return ExperimentConfig(**kw)


def test_tee_to_output_file(tmp_path):
    """Reference my_print semantics (main.py:13-18): with --output, lines
    append to the file instead of stdout."""
    out = tmp_path / "run.log"
    cfg = make_cfg(tmp_path, output=str(out))
    logger = RunLogger(cfg, cfg.output, cfg.log_dir)
    logger.print("hello")
    logger.print("no newline", end="")
    assert out.read_text() == "hello\nno newline"


def test_record_eval_and_csv_schema(tmp_path):
    cfg = make_cfg(tmp_path, defense="Krum", num_std=1.5, mal_prop=0.24)
    logger = RunLogger(cfg, None, cfg.log_dir)
    acc = logger.record_eval(epoch=5, test_loss=0.01, correct=1800,
                             test_size=2000)
    assert np.isclose(acc, 90.0)
    logger.record_eval(epoch=10, test_loss=0.005, correct=1900,
                       test_size=2000)
    logger.finish()

    # CSV with the reference filename schema (main.py:100).
    csv = os.path.join(cfg.log_dir, cfg.csv_name())
    assert os.path.exists(csv)
    vals = np.loadtxt(csv, delimiter=",")
    np.testing.assert_allclose(vals, [90.0, 95.0])
    assert "Krum" in os.path.basename(csv)
    assert "stdev_1.5" in os.path.basename(csv)

    # Structured JSONL carries both evals.
    with open(logger.jsonl_path) as f:
        kinds = [json.loads(x)["kind"] for x in f]
    assert kinds.count("eval") == 2


def test_phase_timer_accumulates_and_syncs():
    timer = PhaseTimer()
    with timer.phase("a"):
        time.sleep(0.01)
    with timer.phase("a"):
        time.sleep(0.01)
    with timer.phase("b", sync_on=lambda: None):
        pass
    s = timer.summary()
    assert s["a"]["count"] == 2
    assert s["a"]["total_s"] >= 0.02
    assert s["b"]["count"] == 1


def test_tee_handle_opened_once(tmp_path):
    """The tee opens ONCE at construction (the reference — and the old
    RunLogger.print — reopened the file per call); finish() leaves it
    open for trailing summary lines, close() shuts it."""
    out = tmp_path / "tee.log"
    cfg = make_cfg(tmp_path, output=str(out))
    logger = RunLogger(cfg, cfg.output, cfg.log_dir)
    handle = logger._tee
    assert handle is not None
    logger.print("one")
    logger.print("two")
    assert logger._tee is handle          # never reopened
    logger.finish()
    assert not handle.closed              # tee survives finish()
    logger.print("after finish")          # trailing summary still tees
    logger.close()
    assert handle.closed
    assert out.read_text() == "one\ntwo\nafter finish\n"


def test_runlogger_context_manager_crash_safe(tmp_path):
    """Satellite: the JSONL handle is closed and the accuracy CSV is
    written even when the run raises inside the with block."""
    cfg = make_cfg(tmp_path, defense="Median")
    with pytest.raises(RuntimeError, match="boom"):
        with RunLogger(cfg, None, cfg.log_dir) as logger:
            logger.record_eval(epoch=0, test_loss=0.5, correct=1000,
                               test_size=2000)
            raise RuntimeError("boom")
    assert logger._jsonl.closed
    csv = os.path.join(cfg.log_dir, cfg.csv_name())
    assert os.path.exists(csv)
    np.testing.assert_allclose(np.loadtxt(csv, delimiter=","), 50.0)
    # finish/close are idempotent — a second exit must not explode.
    logger.close()


def test_event_schema_validation(tmp_path):
    validate_event({"kind": "round", "round": 3})
    validate_event({"kind": "eval", "round": 0, "test_loss": 0.1,
                    "accuracy": 50.0, "correct": 1, "test_size": 2})
    with pytest.raises(ValueError, match="unknown event kind"):
        validate_event({"kind": "nope"})
    with pytest.raises(ValueError, match="missing required"):
        validate_event({"kind": "asr", "round": 1})
    with pytest.raises(ValueError, match="schema version"):
        validate_event({"kind": "round", "round": 1, "v": 99})
    with pytest.raises(ValueError, match="must be numeric"):
        validate_event({"kind": "round", "round": "three"})


def test_record_stamps_version_and_iter_events_roundtrip(tmp_path):
    cfg = make_cfg(tmp_path)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="rt") as logger:
        logger.record(kind="round", round=0, extra_field=1.5)
        logger.record(freeform="no kind, no validation")
        path = logger.jsonl_path
    recs = list(iter_events(path, validate=False))
    assert recs[0]["v"] == SCHEMA_VERSION and recs[0]["extra_field"] == 1.5
    assert "v" not in recs[1]
    with pytest.raises(ValueError, match="unknown event kind"):
        list(iter_events(path))           # validating reader flags line 2


def test_phase_timer_sync_on_callable_is_deferred():
    """Satellite: sync_on=callable is evaluated AFTER the block, so it
    can reference state the block itself produces (engine.run's eval
    phase reads `correct` assigned inside the block)."""
    import jax.numpy as jnp

    timer = PhaseTimer()
    box = {}
    with timer.phase("p", sync_on=lambda: box["x"]):
        box["x"] = jnp.arange(4)   # KeyError if evaluated at entry
    assert timer.counts["p"] == 1
    # Non-callable arrays block directly.
    with timer.phase("q", sync_on=jnp.ones(3)):
        pass
    assert timer.counts["q"] == 1


def test_phase_timer_sync_failure_still_records():
    """The timer accounts the phase even when the sync target raises
    (the finally path)."""
    timer = PhaseTimer()
    with pytest.raises(KeyError):
        with timer.phase("r", sync_on=lambda: {}["missing"]):
            pass
    assert timer.counts["r"] == 1


def test_phase_timer_nesting_accounts_both_levels():
    """Satellite (ISSUE 3): nested phases each run their own clock —
    the outer phase's total includes the inner's wall, and both counts
    advance (bench.py nests timed sections under its phase() bound)."""
    timer = PhaseTimer()
    with timer.phase("outer"):
        with timer.phase("inner"):
            time.sleep(0.01)
    s = timer.summary()
    assert s["outer"]["count"] == 1 and s["inner"]["count"] == 1
    assert s["outer"]["total_s"] >= s["inner"]["total_s"] >= 0.01


def test_phase_timer_reentry_same_name_nested():
    """Re-entering the SAME phase name while it is open must not lose
    time or corrupt counts: each exit accounts its own span, so the
    total is at least the outer span and the count is 2."""
    timer = PhaseTimer()
    with timer.phase("p"):
        time.sleep(0.01)
        with timer.phase("p"):
            time.sleep(0.01)
    assert timer.counts["p"] == 2
    # outer span (>= 0.02) + inner span (>= 0.01)
    assert timer.totals["p"] >= 0.03


def test_phase_timer_raise_inside_nested_phase_accounts_all():
    timer = PhaseTimer()
    with pytest.raises(RuntimeError, match="inner boom"):
        with timer.phase("outer"):
            with timer.phase("inner"):
                raise RuntimeError("inner boom")
    assert timer.counts["outer"] == 1 and timer.counts["inner"] == 1


def test_xla_trace_noop_and_active(tmp_path, monkeypatch):
    """Satellite: no log_dir -> the profiler is never touched; with one,
    start/stop bracket the block."""
    import jax

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    with xla_trace(None):
        pass
    with xla_trace(""):
        pass
    assert calls == []                     # no-op branch
    with xla_trace(str(tmp_path)):
        pass
    assert calls == [("start", str(tmp_path)), ("stop",)]
