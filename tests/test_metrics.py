"""RunLogger (tee, CSV schema, JSONL records) and PhaseTimer."""

import json
import os
import time

import numpy as np

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.utils.metrics import RunLogger
from attacking_federate_learning_tpu.utils.profiling import PhaseTimer


def make_cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("log_dir", str(tmp_path))
    return ExperimentConfig(**kw)


def test_tee_to_output_file(tmp_path):
    """Reference my_print semantics (main.py:13-18): with --output, lines
    append to the file instead of stdout."""
    out = tmp_path / "run.log"
    cfg = make_cfg(tmp_path, output=str(out))
    logger = RunLogger(cfg, cfg.output, cfg.log_dir)
    logger.print("hello")
    logger.print("no newline", end="")
    assert out.read_text() == "hello\nno newline"


def test_record_eval_and_csv_schema(tmp_path):
    cfg = make_cfg(tmp_path, defense="Krum", num_std=1.5, mal_prop=0.24)
    logger = RunLogger(cfg, None, cfg.log_dir)
    acc = logger.record_eval(epoch=5, test_loss=0.01, correct=1800,
                             test_size=2000)
    assert np.isclose(acc, 90.0)
    logger.record_eval(epoch=10, test_loss=0.005, correct=1900,
                       test_size=2000)
    logger.finish()

    # CSV with the reference filename schema (main.py:100).
    csv = os.path.join(cfg.log_dir, cfg.csv_name())
    assert os.path.exists(csv)
    vals = np.loadtxt(csv, delimiter=",")
    np.testing.assert_allclose(vals, [90.0, 95.0])
    assert "Krum" in os.path.basename(csv)
    assert "stdev_1.5" in os.path.basename(csv)

    # Structured JSONL carries both evals.
    with open(logger.jsonl_path) as f:
        kinds = [json.loads(x)["kind"] for x in f]
    assert kinds.count("eval") == 2


def test_phase_timer_accumulates_and_syncs():
    timer = PhaseTimer()
    with timer.phase("a"):
        time.sleep(0.01)
    with timer.phase("a"):
        time.sleep(0.01)
    with timer.phase("b", sync_on=lambda: None):
        pass
    s = timer.summary()
    assert s["a"]["count"] == 2
    assert s["a"]["total_s"] >= 0.02
    assert s["b"]["count"] == 1
