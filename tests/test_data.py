"""Data layer: partitioners, batch cycling, synthetic datasets, triggers."""

import numpy as np
import jax.numpy as jnp

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.data import partition as P
from attacking_federate_learning_tpu.data import triggers
from attacking_federate_learning_tpu.data.datasets import load_dataset


def test_iid_shards_cover_and_balance():
    shards = P.iid_shards(103, 10, seed=0)
    assert shards.shape == (10, 11)  # ceil(103/10), padded by wrapping
    # Every example appears at least once (DistributedSampler semantics,
    # reference user.py:49-54).
    assert set(shards.ravel().tolist()) == set(range(103))


def test_iid_shards_disjoint_before_padding():
    shards = P.iid_shards(100, 10, seed=1)
    flat = shards.ravel()
    assert len(set(flat.tolist())) == 100  # exact partition when divisible


def test_round_batches_cycle():
    shards = P.iid_shards(40, 4, seed=2)  # shard_len 10
    b0 = np.asarray(P.round_batch_indices(jnp.asarray(shards), 0, 4))
    b_wrap = np.asarray(P.round_batch_indices(jnp.asarray(shards), 3, 4))
    assert b0.shape == (4, 4)
    # Round 3 offset 12 -> wraps to positions [2,3,4,5] of each shard.
    np.testing.assert_array_equal(b_wrap, shards[:, [2, 3, 4, 5]])


def test_dirichlet_shards_shape_and_skew():
    labels = np.random.default_rng(0).integers(0, 10, 5000).astype(np.int32)
    shards = P.dirichlet_shards(labels, 8, alpha=0.1, seed=3)
    assert shards.shape[0] == 8
    # Strong alpha=0.1 skew: some client's label histogram is dominated by
    # few classes.
    hist = np.bincount(labels[shards[0]], minlength=10)
    assert hist.max() > hist.sum() * 0.25


def test_femnist_style_partition_shards_and_params():
    # Index side: identical to IID (the non-IIDness is the input
    # transform, not example assignment).
    labels = np.repeat(np.arange(5), 20)
    np.testing.assert_array_equal(
        P.make_shards("femnist_style", labels, 4, seed=7),
        P.iid_shards(len(labels), 4, 7))
    # Style side: deterministic per seed, bounded by strength, distinct
    # across seeds, degenerate at strength 0.
    a, b = P.client_style_params(6, 0.25, seed=3)
    a2, b2 = P.client_style_params(6, 0.25, seed=3)
    np.testing.assert_array_equal(a, a2)
    np.testing.assert_array_equal(b, b2)
    assert a.shape == b.shape == (6,) and a.dtype == np.float32
    assert np.all(np.abs(a - 1.0) <= 0.25) and np.all(np.abs(b) <= 0.125)
    assert len(np.unique(a)) == 6          # clients actually differ
    a4, _ = P.client_style_params(6, 0.25, seed=4)
    assert not np.array_equal(a, a4)
    a0, b0 = P.client_style_params(6, 0.0, seed=3)
    np.testing.assert_array_equal(a0, np.ones(6, np.float32))
    np.testing.assert_array_equal(b0, np.zeros(6, np.float32))


def test_synthetic_dataset_properties():
    ds = load_dataset(C.SYNTH_MNIST, seed=0, synth_train=512, synth_test=128)
    assert ds.train_x.shape == (512, 1, 28, 28)
    assert ds.train_y.shape == (512,)
    assert ds.num_classes == 10
    # Deterministic across loads.
    ds2 = load_dataset(C.SYNTH_MNIST, seed=0, synth_train=512, synth_test=128)
    np.testing.assert_array_equal(ds.train_x, ds2.train_x)


def test_synth_cifar10_hard_is_cnn_learnable_by_construction():
    """SYNTH_CIFAR10_HARD (round 4): CIFAR-shaped, deterministic, and
    its class prototypes are spatially smooth — 4x4-blocky low-frequency
    patterns — because per-pixel i.i.d. prototypes are invisible to
    conv+pool nets (measured: cifar10_cnn stays at random accuracy on
    them).  The blockiness is observable as the class-conditional mean
    being ~constant within 4x4 cells."""
    ds = load_dataset(C.SYNTH_CIFAR10_HARD, seed=0, synth_train=2048,
                      synth_test=128)
    assert ds.train_x.shape == (2048, 3, 32, 32)
    assert ds.num_classes == 10
    ds2 = load_dataset(C.SYNTH_CIFAR10_HARD, seed=0, synth_train=2048,
                       synth_test=128)
    np.testing.assert_array_equal(ds.train_x, ds2.train_x)
    # Class-mean image ~ 0.5 + signal*proto (noise averages out):
    # within-4x4-block variance must be far below pixel variance across
    # blocks for the prototype term to be blocky-smooth.
    c = np.asarray(ds.train_y) == 0
    mean_img = np.asarray(ds.train_x)[c].mean(axis=0)      # (3, 32, 32)
    blocks = mean_img.reshape(3, 8, 4, 8, 4)
    within = blocks.std(axis=(2, 4)).mean()
    across = blocks.mean(axis=(2, 4)).std()
    assert within < 0.5 * across, (within, across)


def test_mnist_falls_back_to_synthetic_when_files_absent():
    ds = load_dataset(C.MNIST, data_dir="/nonexistent", seed=0,
                      synth_train=64, synth_test=32)
    assert ds.name == C.SYNTH_MNIST


def test_pattern_trigger():
    x = jnp.zeros((3, 1, 28, 28))
    t = np.asarray(triggers.add_pattern(x))
    # 5x5 corner at 2.8 post-normalization (reference backdoor.py:47-50).
    assert (t[:, :, :5, :5] == 2.8).all()
    assert (t[:, :, 5:, :] == 0).all() and (t[:, :, :, 5:] == 0).all()


def test_backdoor_targets():
    y = jnp.asarray([0, 1, 4, 7, 9])
    np.testing.assert_array_equal(
        np.asarray(triggers.backdoor_targets(y, "pattern")), 0)
    np.testing.assert_array_equal(
        np.asarray(triggers.backdoor_targets(y, 2)),
        np.asarray([1, 2, 0, 3, 0]))  # (y+1)%5, reference backdoor.py:83
