"""Defense kernels vs the NumPy oracle + algebraic properties.

Oracle equivalence (SURVEY.md §4(a)): the XLA kernels must reproduce the
reference's exact variants (reference defences.py:13-70) — verified against
an independent NumPy re-derivation (defenses/oracle.py), which was itself
cross-checked against the reference implementation.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.defenses import kernels as K
from attacking_federate_learning_tpu.defenses import oracle as O


CASES = [
    # (n, d, f)
    (5, 7, 0),
    (7, 11, 2),
    (10, 50, 2),
    (11, 3, 2),
    (23, 104, 5),
    (40, 33, 9),
]


def grads_for(n, d, seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, d)).astype(np.float32)


@pytest.mark.parametrize("name", ["NoDefense", "Krum", "TrimmedMean",
                                  "Bulyan"])
@pytest.mark.parametrize("n,d,f", CASES)
def test_matches_oracle(name, n, d, f):
    if ((name == "Krum" and n < 2 * f + 1)
            or (name == "Bulyan" and n < 4 * f + 3)):
        # Below the defense's threat-model bound the reference asserts out
        # (defences.py:25, :56); our host-side guard must reject too.
        with pytest.raises(ValueError):
            K.check_defense_args(name, n, f)
        return
    G = grads_for(n, d, seed=n * 1000 + d * 10 + f)
    want = O.NP_DEFENSES[name](G.astype(np.float64), n, f)
    got = np.asarray(K.DEFENSES[name](jnp.asarray(G), n, f))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_krum_output_is_an_input_row():
    G = grads_for(15, 33, seed=3)
    out = np.asarray(K.krum(jnp.asarray(G), 15, 3))
    assert any(np.allclose(out, row) for row in G)


def test_trimmed_mean_within_coordinate_bounds():
    G = grads_for(12, 40, seed=4)
    out = np.asarray(K.trimmed_mean(jnp.asarray(G), 12, 2))
    assert np.all(out >= G.min(axis=0) - 1e-6)
    assert np.all(out <= G.max(axis=0) + 1e-6)


def test_no_defense_is_mean():
    G = grads_for(9, 17, seed=5)
    np.testing.assert_allclose(np.asarray(K.no_defense(jnp.asarray(G), 9, 0)),
                               G.mean(axis=0), atol=1e-6)


def test_krum_permutation_covariant():
    """Permuting clients must not change the *value* Krum selects."""
    G = grads_for(13, 21, seed=6)
    perm = np.random.default_rng(0).permutation(13)
    a = np.asarray(K.krum(jnp.asarray(G), 13, 3))
    b = np.asarray(K.krum(jnp.asarray(G[perm]), 13, 3))
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_krum_rejects_obvious_outlier():
    G = grads_for(11, 8, seed=7)
    G[0] += 100.0  # gross outlier cannot be selected
    out = np.asarray(K.krum(jnp.asarray(G), 11, 2))
    assert not np.allclose(out, G[0])


def test_bulyan_excludes_outlier_influence():
    G = grads_for(11, 6, seed=8)
    clean = np.asarray(K.bulyan(jnp.asarray(G.copy()), 11, 2))
    G2 = G.copy()
    G2[0] += 1e6
    poisoned = np.asarray(K.bulyan(jnp.asarray(G2), 11, 2))
    # One gross outlier among f=2 must leave the output near the clean one.
    assert np.abs(clean - poisoned).max() < 1.0


def test_defense_guards():
    with pytest.raises(ValueError):
        K.check_defense_args("Krum", 4, 2)
    with pytest.raises(ValueError):
        K.check_defense_args("Bulyan", 10, 2)
    K.check_defense_args("Krum", 5, 2)
    K.check_defense_args("Bulyan", 11, 2)


def test_krum_paper_scoring_flag():
    """paper_scoring sums n-f-2 closest (NIPS'17) vs the reference's n-f;
    both must still select a row of the input."""
    G = grads_for(15, 20, seed=9)
    ref_out = np.asarray(K.krum(jnp.asarray(G), 15, 3))
    paper_out = np.asarray(K.krum(jnp.asarray(G), 15, 3, paper_scoring=True))
    assert any(np.allclose(ref_out, row) for row in G)
    assert any(np.allclose(paper_out, row) for row in G)
    # Hand-check the paper scoring on the oracle side.
    D = O.np_pairwise_distances(G.astype(np.float64))
    scores = []
    for i in range(15):
        others = np.sort(np.delete(D[i], i))
        scores.append(others[: 15 - 3 - 2].sum())
    want = G[int(np.argmin(scores))]
    np.testing.assert_allclose(paper_out, want, atol=2e-4)


@pytest.mark.parametrize("n,d,f", [(11, 30, 2), (23, 104, 5), (40, 33, 9)])
def test_topk_and_sort_scoring_agree(n, d, f):
    """The complement-top_k evaluation (sum-of-k-smallest = rowsum minus
    sum-of-(f-1)-largest) must match the full-sort path exactly.  (Krum
    only: Bulyan's selection loop now evaluates via the presorted prefix
    regardless of method — covered against the oracle/reference in
    test_matches_oracle and tests/test_reference_parity.py.)"""
    G = jnp.asarray(grads_for(n, d, seed=n + d + f))
    a = np.asarray(K.krum(G, n, f, method="sort"))
    b = np.asarray(K.krum(G, n, f, method="topk"))
    np.testing.assert_allclose(a, b, atol=1e-5)


@pytest.mark.parametrize("n,d,f", [(11, 30, 2), (23, 104, 5), (40, 33, 9)])
@pytest.mark.parametrize("paper", [False, True])
def test_bulyan_presorted_prefix_matches_per_iteration_scoring(n, d, f,
                                                               paper):
    """Bulyan's presort-once selection must reproduce the per-iteration
    _krum_scores loop exactly (same winners in the same order), ties and
    paper-scoring included."""
    import jax
    from jax import lax

    G = jnp.asarray(grads_for(n, d, seed=n * 3 + d + f))
    G = G.at[2].set(G[5])  # exact duplicate rows -> tied scores
    D = K.pairwise_distances(G)
    set_size = n - 2 * f

    def old_selection(D):
        def body(t, carry):
            alive, selected = carry
            scores = K._krum_scores(D, n - t, f, alive=alive,
                                    paper_scoring=paper)
            idx = jnp.argmin(scores)
            return alive.at[idx].set(False), selected.at[t].set(idx)

        _, selected = lax.fori_loop(
            0, set_size, body,
            (jnp.ones((n,), bool), jnp.zeros((set_size,), jnp.int32)))
        return selected

    want = np.asarray(old_selection(D))
    got = np.asarray(K.bulyan(G, n, f, paper_scoring=paper))
    ref = np.asarray(K.trimmed_mean_of(G[jnp.asarray(want)],
                                       set_size - 2 * f - 1))
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_bf16_grads_accepted():
    """bf16 gradient matrix rides the distance kernel with f32 accumulation
    and still selects a sensible Krum winner."""
    G = grads_for(15, 64, seed=11)
    G[0] += 50.0  # gross outlier
    out = np.asarray(K.krum(jnp.asarray(G, jnp.bfloat16), 15, 3))
    assert not np.allclose(out.astype(np.float32), G[0], atol=1.0)


def test_topk_scoring_with_adversarial_magnitudes():
    """Complement subtraction under huge-norm Byzantine rows must still
    select the same gradient as the sort path (documents the numerical
    envelope of method='topk')."""
    G = grads_for(21, 50, seed=13)
    G[:4] *= 1e4  # gross-magnitude attackers
    a = np.asarray(K.krum(jnp.asarray(G), 21, 4, method="sort"))
    b = np.asarray(K.krum(jnp.asarray(G), 21, 4, method="topk"))
    np.testing.assert_allclose(a, b, atol=1e-5)
