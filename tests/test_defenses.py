"""Defense kernels vs the NumPy oracle + algebraic properties.

Oracle equivalence (SURVEY.md §4(a)): the XLA kernels must reproduce the
reference's exact variants (reference defences.py:13-70) — verified against
an independent NumPy re-derivation (defenses/oracle.py), which was itself
cross-checked against the reference implementation.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from attacking_federate_learning_tpu.defenses import kernels as K
from attacking_federate_learning_tpu.defenses import oracle as O


CASES = [
    # (n, d, f)
    (5, 7, 0),
    (7, 11, 2),
    (10, 50, 2),
    (11, 3, 2),
    (23, 104, 5),
    (40, 33, 9),
]


def grads_for(n, d, seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, d)).astype(np.float32)


@pytest.mark.parametrize("name", ["NoDefense", "Krum", "TrimmedMean",
                                  "Bulyan"])
@pytest.mark.parametrize("n,d,f", CASES)
def test_matches_oracle(name, n, d, f):
    if ((name == "Krum" and n < 2 * f + 1)
            or (name == "Bulyan" and n < 4 * f + 3)):
        # Below the defense's threat-model bound the reference asserts out
        # (defences.py:25, :56); our host-side guard must reject too.
        with pytest.raises(ValueError):
            K.check_defense_args(name, n, f)
        return
    G = grads_for(n, d, seed=n * 1000 + d * 10 + f)
    want = O.NP_DEFENSES[name](G.astype(np.float64), n, f)
    got = np.asarray(K.DEFENSES[name](jnp.asarray(G), n, f))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_krum_output_is_an_input_row():
    G = grads_for(15, 33, seed=3)
    out = np.asarray(K.krum(jnp.asarray(G), 15, 3))
    assert any(np.allclose(out, row) for row in G)


def test_trimmed_mean_within_coordinate_bounds():
    G = grads_for(12, 40, seed=4)
    out = np.asarray(K.trimmed_mean(jnp.asarray(G), 12, 2))
    assert np.all(out >= G.min(axis=0) - 1e-6)
    assert np.all(out <= G.max(axis=0) + 1e-6)


def test_no_defense_is_mean():
    G = grads_for(9, 17, seed=5)
    np.testing.assert_allclose(np.asarray(K.no_defense(jnp.asarray(G), 9, 0)),
                               G.mean(axis=0), atol=1e-6)


def test_krum_permutation_covariant():
    """Permuting clients must not change the *value* Krum selects."""
    G = grads_for(13, 21, seed=6)
    perm = np.random.default_rng(0).permutation(13)
    a = np.asarray(K.krum(jnp.asarray(G), 13, 3))
    b = np.asarray(K.krum(jnp.asarray(G[perm]), 13, 3))
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_krum_rejects_obvious_outlier():
    G = grads_for(11, 8, seed=7)
    G[0] += 100.0  # gross outlier cannot be selected
    out = np.asarray(K.krum(jnp.asarray(G), 11, 2))
    assert not np.allclose(out, G[0])


def test_bulyan_excludes_outlier_influence():
    G = grads_for(11, 6, seed=8)
    clean = np.asarray(K.bulyan(jnp.asarray(G.copy()), 11, 2))
    G2 = G.copy()
    G2[0] += 1e6
    poisoned = np.asarray(K.bulyan(jnp.asarray(G2), 11, 2))
    # One gross outlier among f=2 must leave the output near the clean one.
    assert np.abs(clean - poisoned).max() < 1.0


def test_defense_guards():
    with pytest.raises(ValueError):
        K.check_defense_args("Krum", 4, 2)
    with pytest.raises(ValueError):
        K.check_defense_args("Bulyan", 10, 2)
    K.check_defense_args("Krum", 5, 2)
    K.check_defense_args("Bulyan", 11, 2)


def test_krum_paper_scoring_flag():
    """paper_scoring sums n-f-2 closest (NIPS'17) vs the reference's n-f;
    both must still select a row of the input."""
    G = grads_for(15, 20, seed=9)
    ref_out = np.asarray(K.krum(jnp.asarray(G), 15, 3))
    paper_out = np.asarray(K.krum(jnp.asarray(G), 15, 3, paper_scoring=True))
    assert any(np.allclose(ref_out, row) for row in G)
    assert any(np.allclose(paper_out, row) for row in G)
    # Hand-check the paper scoring on the oracle side.
    D = O.np_pairwise_distances(G.astype(np.float64))
    scores = []
    for i in range(15):
        others = np.sort(np.delete(D[i], i))
        scores.append(others[: 15 - 3 - 2].sum())
    want = G[int(np.argmin(scores))]
    np.testing.assert_allclose(paper_out, want, atol=2e-4)


@pytest.mark.parametrize("n,d,f", [(11, 30, 2), (23, 104, 5), (40, 33, 9)])
def test_topk_and_sort_scoring_agree(n, d, f):
    """The complement-top_k evaluation (sum-of-k-smallest = rowsum minus
    sum-of-(f-1)-largest) must match the full-sort path exactly.  (Krum
    only: Bulyan's selection loop now evaluates via the presorted prefix
    regardless of method — covered against the oracle/reference in
    test_matches_oracle and tests/test_reference_parity.py.)"""
    G = jnp.asarray(grads_for(n, d, seed=n + d + f))
    a = np.asarray(K.krum(G, n, f, method="sort"))
    b = np.asarray(K.krum(G, n, f, method="topk"))
    np.testing.assert_allclose(a, b, atol=1e-5)


@pytest.mark.parametrize("n,d,f", [(11, 30, 2), (23, 104, 5), (40, 33, 9)])
@pytest.mark.parametrize("paper", [False, True])
def test_bulyan_presorted_prefix_matches_per_iteration_scoring(n, d, f,
                                                               paper):
    """Bulyan's presort-once selection must reproduce the per-iteration
    _krum_scores loop exactly (same winners in the same order), ties and
    paper-scoring included."""
    import jax
    from jax import lax

    G = jnp.asarray(grads_for(n, d, seed=n * 3 + d + f))
    G = G.at[2].set(G[5])  # exact duplicate rows -> tied scores
    D = K.pairwise_distances(G)
    set_size = n - 2 * f

    def old_selection(D):
        def body(t, carry):
            alive, selected = carry
            scores = K._krum_scores(D, n - t, f, alive=alive,
                                    paper_scoring=paper)
            idx = jnp.argmin(scores)
            return alive.at[idx].set(False), selected.at[t].set(idx)

        _, selected = lax.fori_loop(
            0, set_size, body,
            (jnp.ones((n,), bool), jnp.zeros((set_size,), jnp.int32)))
        return selected

    want = np.asarray(old_selection(D))
    got = np.asarray(K.bulyan(G, n, f, paper_scoring=paper))
    ref = np.asarray(K.trimmed_mean_of(G[jnp.asarray(want)],
                                       set_size - 2 * f - 1))
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_bf16_grads_accepted():
    """bf16 gradient matrix rides the distance kernel with f32 accumulation
    and still selects a sensible Krum winner."""
    G = grads_for(15, 64, seed=11)
    G[0] += 50.0  # gross outlier
    out = np.asarray(K.krum(jnp.asarray(G, jnp.bfloat16), 15, 3))
    assert not np.allclose(out.astype(np.float32), G[0], atol=1.0)


def test_topk_scoring_with_adversarial_magnitudes():
    """Complement subtraction under huge-norm Byzantine rows must still
    select the same gradient as the sort path (documents the numerical
    envelope of method='topk')."""
    G = grads_for(21, 50, seed=13)
    G[:4] *= 1e4  # gross-magnitude attackers
    a = np.asarray(K.krum(jnp.asarray(G), 21, 4, method="sort"))
    b = np.asarray(K.krum(jnp.asarray(G), 21, 4, method="topk"))
    np.testing.assert_allclose(a, b, atol=1e-5)


# slow tier: the n=2048 guard sweep is the single most expensive
# tier-1 case (~3 min on a 1-core box, >20% of ROADMAP's 870 s
# tier-1 wall budget); the full suite (no -m filter) still runs it.
@pytest.mark.slow
def test_topk_guard_bounds_error_under_adversarial_rows():
    """VERDICT r2 #5: method='auto' selects topk exactly in the
    large-n/small-f regime where the threat model puts unbounded rows.
    The runtime cancellation guard must keep topk's scores within a
    bounded relative error of an f64 sort reference there — concretely,
    by detecting that the complement subtraction would cancel and
    re-evaluating via the exact sort path inside the same jitted call."""
    n, d, f = 2048, 256, 64          # complement 63 <= n//4 -> auto=topk
    rng = np.random.default_rng(2048)
    G = rng.standard_normal((n, d)).astype(np.float32)
    # ONE unbounded row with the defense still assuming f=64: the
    # complement then strips every huge entry from honest rows, so their
    # kept mass collapses to honest scale while the rowsum stays huge —
    # the catastrophic-cancellation regime for the subtraction.  (With a
    # full cohort of f huge rows, reference scoring k=n-f keeps exactly
    # one huge entry per honest row, so kept/rowsum >= ~1/f and topk
    # stays accurate — the guard correctly declines to fire there.)
    G[0] *= 1e6

    D64 = O.np_pairwise_distances(G.astype(np.float64))
    D32 = jnp.asarray(np.sqrt(np.maximum(
        (lambda g: (g * g).sum(1)[:, None] + (g * g).sum(1)[None, :]
         - 2 * g @ g.T)(G.astype(np.float64)), 0)).astype(np.float32))

    def ref_scores(D):
        Dm = D.copy()
        np.fill_diagonal(Dm, np.inf)
        return np.sort(Dm, axis=1)[:, : D.shape[0] - f].sum(axis=1)

    want = ref_scores(D64)
    sort_scores = np.asarray(K._krum_scores(D32, n, f, method="sort"))
    auto_scores = np.asarray(K._krum_scores(D32, n, f, method="auto"))
    topk_scores = np.asarray(K._krum_scores(D32, n, f, method="topk"))

    # Guard fired: the guarded topk/auto evaluation IS the sort path.
    np.testing.assert_array_equal(auto_scores, sort_scores)
    np.testing.assert_array_equal(topk_scores, sort_scores)
    # And the sort path tracks the f64 reference to f32 tolerance.
    np.testing.assert_allclose(sort_scores, want, rtol=2e-4)
    assert int(np.argmin(auto_scores)) == int(np.argmin(want))

    # Benign magnitudes: the guard must NOT fire (auto keeps topk's
    # different summation order -> near-equal but not bit-identical),
    # and topk still tracks the f64 reference.
    Gb = rng.standard_normal((n, d)).astype(np.float32)
    D64b = O.np_pairwise_distances(Gb.astype(np.float64))
    D32b = jnp.asarray(D64b.astype(np.float32))
    sort_b = np.asarray(K._krum_scores(D32b, n, f, method="sort"))
    auto_b = np.asarray(K._krum_scores(D32b, n, f, method="auto"))
    np.testing.assert_allclose(auto_b, sort_b, rtol=1e-4)
    assert not np.array_equal(auto_b, sort_b), (
        "benign-regime auto unexpectedly took the sort fallback")
    np.testing.assert_allclose(auto_b, ref_scores(D64b), rtol=2e-4)


class TestBulyanBatchSelect:
    """VERDICT r2 #6: opt-in batched Bulyan selection for the 10k regime.
    q=1 is the reference anchor (and the default every oracle/parity test
    pins — the generic loop itself runs q=1); q>1 relaxes only the
    within-trip re-scoring."""

    def test_q1_explicit_equals_default(self):
        G = jnp.asarray(grads_for(23, 40, seed=3))
        a = np.asarray(K.bulyan(G, 23, 5))
        b = np.asarray(K.bulyan(G, 23, 5, batch_select=1))
        np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("q", [2, 3, 7, 100])
    def test_xla_matches_host_at_q(self, q):
        from attacking_federate_learning_tpu.defenses import host as H
        G = grads_for(31, 48, seed=q)
        G[:6] *= 50.0
        a = np.asarray(K.bulyan(jnp.asarray(G), 31, 6, batch_select=q))
        b = H.host_bulyan(G, 31, 6, batch_select=q)
        np.testing.assert_allclose(a, b, atol=1e-5)

    def test_batched_still_excludes_outliers(self):
        rng = np.random.default_rng(9)
        G = rng.standard_normal((43, 64)).astype(np.float32)
        G[:9] += 100.0                      # colluding outlier block
        for q in (1, 4, 16):
            out = np.asarray(K.bulyan(jnp.asarray(G), 43, 9,
                                      batch_select=q))
            honest = G[9:].mean(axis=0)
            assert np.linalg.norm(out - honest) < 2.0, q

    def test_one_trip_is_plain_krum_topset(self):
        """q >= set_size: a single trip selects the set_size lowest
        initial Krum scores in one shot."""
        G = grads_for(27, 32, seed=5)
        n, f = 27, 5
        set_size = n - 2 * f
        D = np.sqrt(np.maximum(
            (lambda g: (g * g).sum(1)[:, None] + (g * g).sum(1)[None, :]
             - 2 * g @ g.T)(G.astype(np.float64)), 0))
        scores = np.asarray(K._krum_scores(
            jnp.asarray(D.astype(np.float32)), n, f))
        want_sel = np.argsort(scores, kind="stable")[:set_size]
        want = np.asarray(K.trimmed_mean_of(
            jnp.asarray(G[want_sel]), set_size - 2 * f - 1))
        got = np.asarray(K.bulyan(jnp.asarray(G), n, f,
                                  batch_select=set_size))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_engine_wires_the_flag(self):
        from attacking_federate_learning_tpu import config as C
        from attacking_federate_learning_tpu.attacks import DriftAttack
        from attacking_federate_learning_tpu.config import ExperimentConfig
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.data.datasets import (
            load_dataset
        )

        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=23,
                               mal_prop=0.22, batch_size=16, epochs=1,
                               defense="Bulyan", bulyan_batch_select=4,
                               synth_train=256, synth_test=64)
        assert cfg.corrupted_count == 5
        ds = load_dataset(cfg.dataset, seed=0, synth_train=256,
                          synth_test=64)
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
        assert exp.defense_fn.keywords["batch_select"] == 4
        exp.run_span(0, 1)
        assert np.isfinite(np.asarray(exp.state.weights)).all()
        with pytest.raises(ValueError):
            ExperimentConfig(bulyan_batch_select=0)


class TestBulyanHybridSelection:
    """VERDICT r3 #2: the hybrid exact path — device distances, one
    (n, n) host marshal, native incremental selection, device gather +
    trim-mean (``selection_impl='host'``).  Outside f32 ulp-band ties
    the hybrid must equal the traced XLA selection exactly."""

    def test_hybrid_equals_xla_eager(self):
        G = jnp.asarray(grads_for(23, 40, seed=13))
        a = np.asarray(K.bulyan(G, 23, 5))
        b = np.asarray(K.bulyan(G, 23, 5, selection_impl="host"))
        np.testing.assert_allclose(a, b, atol=1e-6)

    def test_hybrid_equals_xla_under_jit(self):
        import functools

        import jax
        G = jnp.asarray(grads_for(19, 32, seed=17))
        xla_fn = jax.jit(K.bulyan, static_argnums=(1, 2))
        hyb_fn = jax.jit(
            functools.partial(K.bulyan, selection_impl="host"),
            static_argnums=(1, 2))
        np.testing.assert_allclose(np.asarray(xla_fn(G, 19, 4)),
                                   np.asarray(hyb_fn(G, 19, 4)),
                                   atol=1e-6)

    @pytest.mark.parametrize("q", [2, 5])
    def test_hybrid_composes_with_batch_select(self, q):
        G = jnp.asarray(grads_for(31, 48, seed=q))
        a = np.asarray(K.bulyan(G, 31, 6, batch_select=q))
        b = np.asarray(K.bulyan(G, 31, 6, batch_select=q,
                                selection_impl="host"))
        np.testing.assert_allclose(a, b, atol=1e-6)

    def test_hybrid_excludes_outliers(self):
        rng = np.random.default_rng(21)
        G = rng.standard_normal((43, 64)).astype(np.float32)
        G[:9] += 100.0
        out = np.asarray(K.bulyan(jnp.asarray(G), 43, 9,
                                  selection_impl="host"))
        honest = G[9:].mean(axis=0)
        assert np.linalg.norm(out - honest) < 2.0

    def test_host_trim_tail_matches_xla_within_ulps(self):
        # trim_impl='host' (the CPU-backend 10k tail opt-in) differs
        # from XLA only by summation-order ulps, eager and jitted, and
        # composes with the hybrid selection.
        import functools

        import jax
        G = jnp.asarray(grads_for(23, 40, seed=29))
        a = np.asarray(K.bulyan(G, 23, 5))
        b = np.asarray(K.bulyan(G, 23, 5, trim_impl="host"))
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
        hyb = jax.jit(functools.partial(K.bulyan, selection_impl="host",
                                        trim_impl="host"),
                      static_argnums=(1, 2))
        np.testing.assert_allclose(np.asarray(hyb(G, 23, 5)), a,
                                   rtol=1e-6, atol=1e-6)
        with pytest.raises(ValueError, match="trim_impl"):
            K.bulyan(G, 23, 5, trim_impl="gpu")

    def test_invalid_selection_impl_raises(self):
        G = jnp.asarray(grads_for(11, 8, seed=0))
        with pytest.raises(ValueError, match="selection_impl"):
            K.bulyan(G, 11, 2, selection_impl="gpu")

    def test_engine_wires_the_flag_and_runs_fused(self):
        from attacking_federate_learning_tpu import config as C
        from attacking_federate_learning_tpu.attacks import DriftAttack
        from attacking_federate_learning_tpu.config import ExperimentConfig
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.data.datasets import (
            load_dataset
        )

        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=23,
                               mal_prop=0.22, batch_size=16, epochs=2,
                               defense="Bulyan",
                               bulyan_selection_impl="host",
                               synth_train=256, synth_test=64)
        ds = load_dataset(cfg.dataset, seed=0, synth_train=256,
                          synth_test=64)
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
        assert exp.defense_fn.keywords["selection_impl"] == "host"
        # The fused round program must trace through the pure_callback.
        exp.run_span(0, 2)
        assert np.all(np.isfinite(np.asarray(exp.state.weights)))


def test_topk_guard_fails_on_rowsum_overflow():
    """An f32 rowsum that overflows to inf must fail the guard (inf >= inf
    would otherwise pass and return all-inf topk scores, blinding the
    argmin); the sort path stays exact because its per-row prefix never
    sums the huge complement entries."""
    n, f = 5, 2                      # complement 1 -> auto picks topk
    # Off-diagonal 1.2e38: each row's k=3-smallest prefix (~2.4e38) stays
    # finite in f32, but the full rowsum (~3.6e38) overflows to inf.
    D = np.full((n, n), 1.2e38, np.float32)
    np.fill_diagonal(D, 0.0)
    D[4, :] = D[:, 4] = 1.0          # one honest-looking row
    D[4, 4] = 0.0
    Dj = jnp.asarray(D)
    sort_scores = np.asarray(K._krum_scores(Dj, n, f, method="sort"))
    auto_scores = np.asarray(K._krum_scores(Dj, n, f, method="auto"))
    topk_scores = np.asarray(K._krum_scores(Dj, n, f, method="topk"))
    assert np.isfinite(sort_scores).all()
    np.testing.assert_array_equal(auto_scores, sort_scores)
    np.testing.assert_array_equal(topk_scores, sort_scores)
    assert int(np.argmin(auto_scores)) == 4


def test_host_trimmed_mean_partition_matches_stable_sort():
    """host_trimmed_mean_of's native evaluation must equal the
    definitional stable-sort form — including at boundary ties, where the
    stable order keeps the LOWEST row indices (e.g. +x before -x when
    |dev| ties), which changes the kept *values*.  Skipped when the
    native kernel is unavailable: the fallback IS the stable-sort form,
    so the comparison would be vacuous."""
    from attacking_federate_learning_tpu.defenses.host import (
        host_trimmed_mean_of,
    )
    from attacking_federate_learning_tpu.native import get_lib

    if get_lib() is None:
        pytest.skip("native kernel unavailable (no g++?)")

    def stable_sort_form(sel, k):
        med = np.median(sel, axis=0)
        dev = sel - med
        order = np.argsort(np.abs(dev), axis=0, kind="stable")
        kept = np.take_along_axis(dev, order[:k], axis=0)
        return (kept.mean(axis=0) + med).astype(np.float32)

    rng = np.random.default_rng(0)
    for n, d in [(5, 7), (12, 31), (33, 10), (6, 1)]:
        for k in [1, 2, n // 2, n - 1, n]:
            sel = rng.standard_normal((n, d)).astype(np.float32)
            np.testing.assert_allclose(
                host_trimmed_mean_of(sel, k), stable_sort_form(sel, k),
                rtol=1e-6, atol=1e-6)
    # Engineered symmetric ties: rows at med±x have identical |dev|;
    # the stable order keeps the earlier ROW, so sign matters.
    sel = np.array([[1.0], [3.0], [2.0], [1.0], [3.0], [2.0]], np.float32)
    for k in range(1, 7):
        # rtol covers the native kernel's f64-accumulated mean (<=1 ulp
        # vs NumPy's f32 mean); a tie-handling bug would be O(x), not ulp.
        np.testing.assert_allclose(
            host_trimmed_mean_of(sel, k), stable_sort_form(sel, k),
            rtol=1e-6, atol=1e-7)
    # Duplicated boundary values across many rows.
    sel = np.tile(np.array([[2.0], [0.0], [4.0], [2.0]], np.float32),
                  (3, 5))
    for k in range(1, sel.shape[0] + 1):
        np.testing.assert_allclose(
            host_trimmed_mean_of(sel, k), stable_sort_form(sel, k),
            rtol=1e-6, atol=1e-7)
