"""Multi-device paths on the 8-virtual-CPU-device mesh (conftest.py).

SURVEY.md §4(e): shard_map/pjit paths must run in CI without a TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.ops.distances import pairwise_distances
from attacking_federate_learning_tpu.parallel import distances as pd
from attacking_federate_learning_tpu.parallel.mesh import make_mesh, make_plan


needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 (virtual) devices")


def grads(n, d, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))


@needs_8
def test_allgather_distances_match_single_device():
    G = grads(32, 200)
    mesh = make_mesh((8, 1))
    D_ref = np.asarray(pairwise_distances(G))
    D_ag = np.asarray(pd.pairwise_distances_allgather(G, mesh))
    np.testing.assert_allclose(D_ag, D_ref, atol=1e-4)


@needs_8
def test_ring_distances_match_single_device():
    G = grads(32, 200, seed=1)
    mesh = make_mesh((8, 1))
    D_ref = np.asarray(pairwise_distances(G))
    D_ring = np.asarray(pd.pairwise_distances_ring(G, mesh))
    np.testing.assert_allclose(D_ring, D_ref, atol=1e-4)


@needs_8
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4)])
def test_sharded_round_matches_unsharded(mesh_shape):
    """A fully sharded round must produce the same weights as the
    single-device round (same math, different layout)."""
    def run(shardings):
        cfg = ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                               mal_prop=0.25, batch_size=8, epochs=2,
                               defense="Krum")
        ds = load_dataset(cfg.dataset, seed=0, synth_train=256,
                          synth_test=64)
        exp = FederatedExperiment(cfg, attacker=DriftAttack(cfg.num_std),
                                  dataset=ds, shardings=shardings)
        for t in range(2):
            exp.run_round(t)
        return np.asarray(exp.state.weights)

    w_single = run(None)
    w_sharded = run(make_plan(mesh_shape))
    np.testing.assert_allclose(w_sharded, w_single, atol=2e-5, rtol=1e-5)


@needs_8
def test_graft_dryrun():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


@needs_8
@pytest.mark.parametrize("defense", ["TrimmedMean", "Bulyan"])
def test_sort_heavy_defenses_under_sharding(defense):
    """Sort-along-client-axis kernels must compile and agree under a
    client-sharded layout."""
    from attacking_federate_learning_tpu.defenses.kernels import DEFENSES
    from jax.sharding import NamedSharding, PartitionSpec as P

    G = grads(16, 100, seed=2)
    want = np.asarray(DEFENSES[defense](G, 16, 2))
    mesh = make_mesh((8, 1))
    Gs = jax.device_put(G, NamedSharding(mesh, P("clients", None)))
    got = np.asarray(jax.jit(DEFENSES[defense],
                             static_argnums=(1, 2))(Gs, 16, 2))
    np.testing.assert_allclose(got, want, atol=1e-5)


@needs_8
def test_hybrid_bulyan_selection_under_sharding():
    """The hybrid exact path (selection_impl='host', round 4) must work
    with a client-sharded operand: GSPMD gathers the (n, n) D for the
    pure_callback and the device gather + trim-mean stay sharded."""
    import functools

    from attacking_federate_learning_tpu.defenses.kernels import bulyan
    from jax.sharding import NamedSharding, PartitionSpec as P

    G = grads(16, 100, seed=3)
    want = np.asarray(bulyan(G, 16, 2))
    mesh = make_mesh((8, 1))
    Gs = jax.device_put(G, NamedSharding(mesh, P("clients", None)))
    got = np.asarray(jax.jit(
        functools.partial(bulyan, selection_impl="host"),
        static_argnums=(1, 2))(Gs, 16, 2))
    np.testing.assert_allclose(got, want, atol=1e-5)
