"""Fault-injection harness + graceful degradation (ISSUE 2).

Acceptance contract: with faults OFF the engine path is untouched (the
config defaults to ``faults=None`` and every pre-existing trajectory
test pins that); a seeded dropout+straggler+corrupt run under each
mask-aware distance defense completes 30 rounds without raising, with
per-round 'fault' events matching the injected schedule exactly; a
killed run resumes from the last auto-checkpoint bit-for-bit; and a
diverging run rolls back to the last good checkpoint instead of
aborting (bounded by max_rollbacks).
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import (
    ExperimentConfig, FaultConfig
)
from attacking_federate_learning_tpu.core import faults as F
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.defenses.kernels import (
    bulyan, krum, trimmed_mean
)
from attacking_federate_learning_tpu.defenses.median import median
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
from attacking_federate_learning_tpu.utils.metrics import RunLogger


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 10)
    kw.setdefault("mal_prop", 0.2)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 10)
    kw.setdefault("test_step", 5)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    return ExperimentConfig(**kw)


def _run(cfg, tmp_path, name, checkpointer=None):
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name=name) as logger:
        exp.run(logger, checkpointer=checkpointer)
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    return exp, events


# ---------------------------------------------------------------------------
# the fault model itself (core/faults.py)

def test_fault_masks_deterministic_and_honest_corruption():
    """The schedule is a pure function of (config, round): two draws
    agree, and corruption never touches the attacker's rows [0, f)."""
    fc = FaultConfig(dropout=0.3, straggler=0.2, corrupt=0.3)
    cfg = ExperimentConfig(faults=fc, dataset=C.SYNTH_MNIST)
    key = F.fault_key(cfg)
    for t in (0, 3, 17):
        a = [np.asarray(x) for x in F.fault_masks(key, t, 16, 4, fc)]
        b = [np.asarray(x) for x in F.fault_masks(key, t, 16, 4, fc)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        drop, stale, corrupt = a
        assert not corrupt[:4].any()          # honest rows only
        assert not (drop & stale).any()       # exclusive
        assert not (drop & corrupt).any()
        assert not (stale & corrupt).any()
    # Cold ring buffer: stragglers suppressed at t < delay.
    drop0, stale0, _ = (np.asarray(x)
                        for x in F.fault_masks(key, 0, 16, 4, fc))
    assert not stale0.any()


def test_apply_faults_straggler_ring_buffer():
    """A straggler at round t submits what it computed at t-delay; the
    buffer carries fresh (pre-fault) submissions."""
    fc = FaultConfig(straggler=0.999, straggler_delay=2)
    cfg = ExperimentConfig(faults=fc, dataset=C.SYNTH_MNIST)
    key = F.fault_key(cfg)
    m, d = 6, 5
    state = F.init_fault_state(fc, m, d)
    grads_at = {t: jnp.full((m, d), float(t + 1)) for t in range(5)}
    for t in range(5):
        out, dropped, state, stats = F.apply_faults(
            grads_at[t], t, key, state, fc, 0)
        out = np.asarray(out)
        stale = np.asarray(F.fault_masks(key, t, m, 0, fc)[1])
        if t < 2:
            assert not stale.any()
            np.testing.assert_array_equal(out, np.asarray(grads_at[t]))
        else:
            assert stale.any()                # p=0.999: virtually sure
            np.testing.assert_array_equal(out[stale],
                                          np.asarray(grads_at[t - 2])[stale])
            np.testing.assert_array_equal(out[~stale],
                                          np.asarray(grads_at[t])[~stale])
            assert int(stats["fault_injected_straggler"]) == stale.sum()


def test_quarantine_masks_nonfinite_and_dropped():
    G = jnp.asarray(np.ones((5, 4), np.float32))
    G = G.at[1].set(jnp.nan).at[3].set(jnp.inf)
    dropped = jnp.asarray([False, False, True, False, False])
    clean, mask, stats = F.quarantine(G, dropped)
    np.testing.assert_array_equal(np.asarray(mask),
                                  [True, False, False, False, True])
    assert np.isfinite(np.asarray(clean)).all()
    assert int(stats["fault_quarantined"]) == 3


# ---------------------------------------------------------------------------
# mask-aware kernels: the quarantine mask must reproduce the
# shrunk-cohort estimator exactly (defenses/kernels.py)

@pytest.mark.parametrize("name,fn", [
    ("Krum", krum), ("TrimmedMean", trimmed_mean), ("Bulyan", bulyan),
    ("Median", median),
])
def test_masked_kernel_matches_survivor_submatrix(name, fn):
    rng = np.random.default_rng(7)
    n, f, d = 13, 2, 40
    G = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    dead = [3, 8]
    mask = jnp.asarray([i not in dead for i in range(n)])
    Gz = G.at[jnp.asarray(dead)].set(0.0)     # quarantine zeroes dead rows
    keep = np.asarray([i for i in range(n) if i not in dead])
    got = np.asarray(fn(Gz, n, f, mask=mask))
    want = np.asarray(fn(G[keep], len(keep), f))
    np.testing.assert_allclose(got, want, atol=1e-6)
    # And identically under jit (the fused round traces this path).
    got_j = np.asarray(jax.jit(
        lambda g, m: fn(g, n, f, mask=m))(Gz, mask))
    np.testing.assert_array_equal(got, got_j)


@pytest.mark.parametrize("name,fn", [
    ("Krum", krum), ("TrimmedMean", trimmed_mean), ("Bulyan", bulyan),
    ("Median", median),
])
def test_masked_kernel_all_alive_matches_unmasked(name, fn):
    rng = np.random.default_rng(11)
    n, f = 12, 2
    G = jnp.asarray(rng.standard_normal((n, 30)).astype(np.float32))
    a = np.asarray(fn(G, n, f))
    b = np.asarray(fn(G, n, f, mask=jnp.ones((n,), bool)))
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_host_impls_reject_mask():
    G = jnp.zeros((9, 4))
    with pytest.raises(ValueError, match="mask"):
        trimmed_mean(G, 9, 2, impl="host", mask=jnp.ones((9,), bool))
    with pytest.raises(ValueError, match="mask"):
        median(G, 9, 2, impl="host", mask=jnp.ones((9,), bool))
    with pytest.raises(ValueError, match="mask"):
        bulyan(G, 9, 1, selection_impl="host", mask=jnp.ones((9,), bool))


# ---------------------------------------------------------------------------
# engine integration

def test_faults_disabled_is_reference_path(tmp_path):
    """faults=None and an all-zero FaultConfig both leave the engine on
    the reference path: no fault state, no fault events."""
    cfg = _cfg(tmp_path, epochs=2)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0))
    assert exp.faults is None and exp._fault_state is None
    cfg0 = _cfg(tmp_path, epochs=2,
                faults=FaultConfig(dropout=0.0, straggler=0.0, corrupt=0.0))
    exp0 = FederatedExperiment(cfg0, attacker=DriftAttack(1.0))
    assert exp0.faults is None


def test_no_fault_round_hlo_bit_identical(tmp_path):
    """Acceptance: with all fault flags off the compiled round program
    is bit-identical — faults=None and an all-zero FaultConfig lower to
    byte-identical HLO, and none of the fault machinery's ops appear in
    it (same methodology as PR 1's telemetry bit-identity pin)."""
    ds = load_dataset(C.SYNTH_MNIST, seed=0, synth_train=256,
                      synth_test=64)

    def lowered(faults):
        cfg = _cfg(tmp_path, epochs=2, defense="Krum", faults=faults)
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
        args = ((exp.state, jnp.asarray(0, jnp.int32))
                if exp.faults is None
                else (exp.state, jnp.asarray(0, jnp.int32),
                      exp._fault_state))
        return exp._fused_round.lower(*args).as_text()

    none_text = lowered(None)
    zero_text = lowered(FaultConfig(dropout=0.0, straggler=0.0,
                                    corrupt=0.0))
    assert none_text == zero_text
    # The faulted build is a different program (sanity that the pin
    # above is not vacuous) — but only when faults are actually on.
    faulted = lowered(FaultConfig(dropout=0.2))
    assert faulted != none_text


def test_fault_requires_mask_aware_defense(tmp_path):
    with pytest.raises(ValueError, match="mask-aware"):
        FederatedExperiment(
            _cfg(tmp_path, defense="GeoMedian",
                 faults=FaultConfig(dropout=0.1)),
            attacker=DriftAttack(1.0))


@pytest.mark.parametrize("match", [
    "participation",
    # ISSUE 9 satellite: the rejection must name --aggregation async
    # as the supported straggler route (stragglers become extra
    # arrival delay in the buffered round, core/async_rounds.py).
    "aggregation async",
    "extra arrival delay",
])
def test_straggler_requires_full_participation(tmp_path, match):
    with pytest.raises(ValueError, match=match):
        FederatedExperiment(
            _cfg(tmp_path, participation=0.5,
                 faults=FaultConfig(straggler=0.1)),
            attacker=DriftAttack(1.0))


def _load_fault_matrix():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "fault_matrix.py")
    spec = importlib.util.spec_from_file_location("fault_matrix", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("defense", ["Krum", "TrimmedMean", "Bulyan"])
def test_faulted_30round_run_counts_match_schedule(tmp_path, defense):
    """Acceptance: dropout=0.2/straggler=0.1/corrupt=0.05, 30 rounds,
    no raise, finite weights, and every per-round 'fault' event matches
    the host replay of the injected schedule exactly."""
    fm = _load_fault_matrix()
    cfg = _cfg(tmp_path, users_count=15, epochs=30, test_step=30,
               defense=defense,
               faults=FaultConfig(dropout=0.2, straggler=0.1,
                                  corrupt=0.05))
    exp, events = _run(cfg, tmp_path, f"acc30_{defense}")
    assert int(exp.state.round) == 30
    assert np.isfinite(np.asarray(exp.state.weights)).all()
    fault_events = sorted((e for e in events if e["kind"] == "fault"),
                          key=lambda e: e["round"])
    assert [e["round"] for e in fault_events] == list(range(30))
    want = fm.expected_schedule(cfg, exp.m, exp.m_mal, 30)
    for got, exp_row in zip(fault_events, want):
        for k, v in exp_row.items():
            assert int(got[k]) == v, (got, exp_row)


def test_fault_span_matches_per_round(tmp_path):
    """The scanned fault span (one program per interval) must produce
    exactly the per-round dispatch's weights and fault state."""
    fc = FaultConfig(dropout=0.2, straggler=0.2, corrupt=0.1)
    cfg = _cfg(tmp_path, users_count=12, epochs=7, defense="TrimmedMean",
               faults=fc)
    a = FederatedExperiment(cfg, attacker=DriftAttack(1.0))
    for t in range(7):
        a.run_round(t)
    b = FederatedExperiment(cfg, attacker=DriftAttack(1.0))
    b.run_span(0, 7)
    np.testing.assert_array_equal(np.asarray(a.state.weights),
                                  np.asarray(b.state.weights))
    np.testing.assert_array_equal(np.asarray(a._fault_state["stale"]),
                                  np.asarray(b._fault_state["stale"]))


def test_resume_after_kill_bit_for_bit(tmp_path):
    """A run killed mid-span resumes from the last auto-checkpoint
    bit-for-bit: same final weights as the uninterrupted run, straggler
    ring buffer included (Checkpointer ``extra``)."""
    fc = FaultConfig(dropout=0.2, straggler=0.15, corrupt=0.05)
    cfg = _cfg(tmp_path, users_count=12, epochs=10, test_step=5,
               defense="TrimmedMean", faults=fc, checkpoint_every=3)

    full = FederatedExperiment(cfg, attacker=DriftAttack(1.0))
    ck = Checkpointer(cfg)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="full") as logger:
        full.run(logger, checkpointer=ck)
    # np.array(copy=True): on this backend np.asarray can be a zero-copy
    # view whose buffer the allocator reuses once the next experiment
    # starts compiling (the engine's own snapshots copy for the same
    # reason, core/engine.py:_host_copy).
    w_full = np.array(full.state.weights, copy=True)
    v_full = np.array(full.state.velocity, copy=True)

    # "SIGKILL after round 7": everything after the round-7 auto
    # checkpoint is lost; a fresh process resumes from it.
    auto7 = os.path.join(ck.dir, "checkpoint-auto-00000007.npz")
    assert os.path.exists(auto7), sorted(os.listdir(ck.dir))
    resumed = FederatedExperiment(cfg, attacker=DriftAttack(1.0))
    state, extra = Checkpointer(cfg).resume(auto7, with_extra=True)
    resumed.state = state
    resumed.restore_fault_state(extra)
    assert "stale" in extra                   # the ring buffer traveled
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="resumed") as logger:
        resumed.run(logger)
    np.testing.assert_array_equal(np.asarray(resumed.state.weights),
                                  w_full)
    np.testing.assert_array_equal(np.asarray(resumed.state.velocity),
                                  v_full)


def test_sigterm_preempt_resume_bit_for_bit(tmp_path):
    """SIGTERM-at-arbitrary-round (ISSUE 4 acceptance): a faulted run
    gracefully preempted at a seeded-random round and restarted
    finishes with final weights bit-for-bit equal to the uninterrupted
    run, and its journal + event stream record every round and eval
    exactly once across the two attempts.  Extends the SIGKILL+resume
    test above: SIGKILL loses work back to the last auto-checkpoint;
    the graceful path (utils/lifecycle.py) loses nothing — the preempt
    boundary IS a checkpoint."""
    from attacking_federate_learning_tpu.utils.lifecycle import (
        GracefulShutdown, Preempted, RunJournal
    )

    kill_round = int(np.random.default_rng(11).integers(1, 9))
    fc = FaultConfig(dropout=0.2, straggler=0.15, corrupt=0.05)

    def cfg_for(run_dir):
        # Distinct run dirs: runs/<dataset>/ is shared, and the
        # reference run's checkpoints must not become the supervised
        # run's resume targets.
        return _cfg(tmp_path, users_count=12, epochs=10, test_step=5,
                    defense="TrimmedMean", faults=fc, checkpoint_every=3,
                    run_dir=str(tmp_path / run_dir))

    cfg_ref = cfg_for("runs_ref")
    full = FederatedExperiment(cfg_ref, attacker=DriftAttack(1.0))
    with RunLogger(cfg_ref, None, cfg_ref.log_dir,
                   jsonl_name="sig_full") as logger:
        full.run(logger, checkpointer=Checkpointer(cfg_ref))
    w_full = np.array(full.state.weights, copy=True)
    v_full = np.array(full.state.velocity, copy=True)

    cfg = cfg_for("runs_sup")
    ck = Checkpointer(cfg)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0))
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="sig_sup") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "sig"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    resumed = FederatedExperiment(cfg, attacker=DriftAttack(1.0))
    state, extra = ck.resume(ck.latest(), with_extra=True)
    resumed.state = state
    resumed.restore_fault_state(extra)
    assert "stale" in extra                  # the ring buffer traveled
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="sig_sup") as logger:
        resumed.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "sig"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    np.testing.assert_array_equal(np.asarray(resumed.state.weights),
                                  w_full)
    np.testing.assert_array_equal(np.asarray(resumed.state.velocity),
                                  v_full)
    # Exactly-once: the journal audits clean, and the shared event
    # stream (both attempts append to one JSONL) carries every round's
    # fault event and every eval exactly once.
    assert RunJournal(cfg.run_dir, "sig").verify(
        epochs=10, test_step=5) == []
    with open(os.path.join(cfg.log_dir, "sig_sup.jsonl")) as f:
        events = [json.loads(line) for line in f]
    fault_rounds = [e["round"] for e in events if e["kind"] == "fault"]
    assert sorted(fault_rounds) == list(range(10))
    eval_rounds = [e["round"] for e in events if e["kind"] == "eval"]
    assert sorted(eval_rounds) == [0, 5, 9]


def test_watchdog_rollback_then_abort(tmp_path):
    """Finite bit-scaled corruption under NoDefense explodes the server
    norm: the watchdog rolls back to the last good auto-checkpoint
    (emitting 'fault' rollback events, state restored) and only after
    max_rollbacks raises — with a finite state left behind."""
    fc = FaultConfig(dropout=0.0, straggler=0.0, corrupt=0.3,
                     corrupt_mode="scale", corrupt_scale=1e30,
                     watchdog_norm=1e6, max_rollbacks=1)
    cfg = _cfg(tmp_path, users_count=10, epochs=10, test_step=5,
               defense="NoDefense", mal_prop=0.0, faults=fc,
               checkpoint_every=2)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(0.0), dataset=ds)
    ck = Checkpointer(cfg)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="diverge") as logger:
        with pytest.raises(FloatingPointError, match="diverged"):
            exp.run(logger, checkpointer=ck)
    assert np.isfinite(np.asarray(exp.state.weights)).all()
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    rollbacks = [e for e in events
                 if e["kind"] == "fault" and e.get("rolled_back")]
    # max_rollbacks=1: one rollback-and-retry, then the aborting one.
    assert len(rollbacks) == 2
    assert rollbacks[0]["restored_round"] == rollbacks[1]["restored_round"]
    # The deterministic retry diverged at the same boundary: the
    # rollback-after-divergence trajectory reproduces the clean run
    # from that checkpoint.
    assert rollbacks[0]["round"] == rollbacks[1]["round"]
    # The on-failure auto-checkpoint persists the restored round.
    restored = rollbacks[0]["restored_round"]
    assert any(f"{restored:08d}" in p for p in os.listdir(ck.dir))


def test_rollback_retry_reproduces_clean_resume(tmp_path):
    """Rollback-after-divergence reproduces the same trajectory as a
    clean run resumed from that checkpoint: a fresh engine resumed from
    the on-failure auto-checkpoint diverges at the same boundary."""
    fc = FaultConfig(corrupt=0.3, corrupt_mode="scale", corrupt_scale=1e30,
                     watchdog_norm=1e6, max_rollbacks=0)
    cfg = _cfg(tmp_path, users_count=10, epochs=10, test_step=5,
               defense="NoDefense", mal_prop=0.0, faults=fc,
               checkpoint_every=2)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(0.0), dataset=ds)
    ck = Checkpointer(cfg)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="d0") as logger:
        with pytest.raises(FloatingPointError):
            exp.run(logger, checkpointer=ck)
    with open(logger.jsonl_path) as f:
        rb = [json.loads(line) for line in f]
    rb = [e for e in rb if e["kind"] == "fault" and e.get("rolled_back")]
    diverged_at, restored = rb[0]["round"], rb[0]["restored_round"]

    # Clean engine, resumed from the persisted rollback target.
    path = Checkpointer(cfg).latest_auto()
    state, extra = Checkpointer(cfg).resume(path, with_extra=True)
    assert int(state.round) == restored
    fresh = FederatedExperiment(cfg, attacker=DriftAttack(0.0), dataset=ds)
    fresh.state = state
    fresh.restore_fault_state(extra)
    fresh.run_span(restored, diverged_at - restored + 1)
    w = np.asarray(fresh.state.weights)
    assert (not np.isfinite(w).all()
            or float(np.linalg.norm(w)) > fc.watchdog_norm)


def test_staged_path_threads_faults(tmp_path):
    """The staged (per-round host) dispatch applies the same fault seam:
    a non-fusable attack + faults yields the identical schedule counts."""
    fm = _load_fault_matrix()

    class StagedDrift(DriftAttack):
        fusable = False

    fc = FaultConfig(dropout=0.25, corrupt=0.1)
    cfg = _cfg(tmp_path, users_count=12, epochs=4, test_step=4,
               defense="Krum", faults=fc)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=StagedDrift(1.0), dataset=ds)
    assert exp._staged
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="staged") as logger:
        exp.run(logger)
    with open(logger.jsonl_path) as f:
        events = [json.loads(line) for line in f]
    fault_events = sorted((e for e in events if e["kind"] == "fault"),
                          key=lambda e: e["round"])
    want = fm.expected_schedule(cfg, exp.m, exp.m_mal, 4)
    assert len(fault_events) == 4
    for got, exp_row in zip(fault_events, want):
        for k, v in exp_row.items():
            assert int(got[k]) == v


# ---------------------------------------------------------------------------
# CI hook: the fault_matrix smoke itself (next to the check_events hook)

def test_fault_matrix_smoke(tmp_path):
    fm = _load_fault_matrix()
    rc = fm.main(["--epochs", "3", "--users", "10",
                  "--defenses", "NoDefense,Median",
                  "--log-dir", str(tmp_path)])
    assert rc == 0


# ---------------------------------------------------------------------------
# report: the fault/recovery table

def test_report_fault_recovery_table(tmp_path, capsys):
    from attacking_federate_learning_tpu import report

    cfg = _cfg(tmp_path, users_count=12, epochs=5, test_step=5,
               defense="Median",
               faults=FaultConfig(dropout=0.3, corrupt=0.1))
    _, events = _run(cfg, tmp_path, "rep_fault")
    s = report.summarize_run(events)
    flt = s["faults"]
    assert flt["rounds"] == 5
    total_injected = sum(flt["injected"].values())
    assert total_injected >= flt["quarantined"] > 0
    report._print_run("x", s, print)
    out = capsys.readouterr().out
    assert "faults over 5 rounds" in out and "quarantined" in out
