"""Test harness: 8 virtual CPU devices so every shard_map / pjit path runs
in CI without a TPU (SURVEY.md §4(e)).  Must run before jax initializes."""

import os

# Force CPU and disable the axon TPU site hook: on this image a
# sitecustomize.py dials the (single-client) TPU relay at interpreter start,
# which serializes/hangs concurrent test runs.  Clearing PALLAS_AXON_POOL_IPS
# makes the hook a no-op; tests are CPU-only by design.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
