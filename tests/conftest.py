"""Test harness: CPU-only jax with 8 virtual devices so every shard_map /
pjit path runs in CI without a TPU (SURVEY.md §4(e))."""

import os

# Environment setup must precede backend initialization (XLA_FLAGS and the
# compile cache are read lazily at CPU-client creation).  Note that this
# image's sitecustomize imports jax at interpreter start — BEFORE this file
# runs — so env vars alone cannot change the already-frozen platform
# selection for this process; they still matter for subprocesses and for
# the lazily-read flags below.
# FL_TEST_TPU=1: run the suite on the real TPU backend instead of the
# 8-virtual-CPU-device harness (the VERDICT round-2 "first chip session"
# re-run: fused-backdoor bit-identity, Mosaic pallas, engine suites on
# real XLA:TPU).  Multi-device tests skip themselves via their own
# device-count guards.
TPU_MODE = os.environ.get("FL_TEST_TPU") == "1"
if not TPU_MODE:
    os.environ["PALLAS_AXON_POOL_IPS"] = ""      # keep child processes off
    os.environ["JAX_PLATFORMS"] = "cpu"          # the TPU relay
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
# Persistent compile cache: the suite compiles dozens of kernel variants and
# this box has one core — caching cuts re-runs from minutes to seconds.
# The path carries a host fingerprint (utils/backend.py) so executables
# cached by a host with a different CPU feature set are never loaded here
# (the SIGILL risk XLA warned about in BENCH_r04).  Imported by file path
# to keep the package __init__ (and its jax-touching imports) out of the
# env-setup phase.
import importlib.util as _ilu  # noqa: E402

_spec = _ilu.spec_from_file_location(
    "_fl_backend", os.path.join(os.path.dirname(__file__), os.pardir,
                                "attacking_federate_learning_tpu", "utils",
                                "backend.py"))
_backend = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_backend)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), os.pardir,
                                   ".jax_cache",
                                   _backend.host_cache_fingerprint()))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# Because jax is already imported (see above), the only effective platform
# override for THIS process is the live config.  Backend init is lazy, so
# doing it here — before any test touches a jax op — keeps the whole suite
# on CPU even under the default environment (and even when the TPU relay
# is unreachable, which otherwise blocks forever in a connect-retry loop).
import jax  # noqa: E402

if not TPU_MODE:
    jax.config.update("jax_platforms", "cpu")
# Same already-imported reality for the cache settings: jax 0.9 reads the
# cache env vars at import time only, and sitecustomize (or an import in
# the fingerprint path) may have imported jax before the setdefaults
# above — so apply them to the live config explicitly.
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs",
                  float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: outside the tier-1 budget (tier-1 runs -m 'not slow'); "
        "e.g. the measured campaign cache-ordering proof, which spawns "
        "a child process per cell")


@pytest.fixture(scope="session")
def hard_ds():
    """Shared low-SNR behavioral dataset (generated once per session)."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    return load_dataset(C.SYNTH_MNIST_HARD, seed=0, synth_train=8000,
                        synth_test=2000)


def hard_final_accuracy(ds, defense, attack, mal_prop, rounds=30):
    """Run the standard behavioral config and return final test accuracy."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )

    cfg = ExperimentConfig(dataset=C.SYNTH_MNIST_HARD, users_count=19,
                           mal_prop=mal_prop, batch_size=64, epochs=rounds,
                           defense=defense)
    exp = FederatedExperiment(cfg, attacker=attack, dataset=ds)
    for t in range(rounds):
        exp.run_round(t)
    _, correct = exp.evaluate(exp.state.weights)
    return 100.0 * float(correct) / len(ds.test_y)
