"""Secure-aggregation protocol layer (ISSUE 7, protocols/secagg.py).

Acceptance contract: pairwise masks cancel BIT-EXACTLY in the uint32
bitcast domain (``sum(masked) == sum(clear)`` bitwise, dropout-recovery
path included); a ``--secagg vanilla`` run's final weights are
bit-equal to the clear NoDefense run's (the protocol is behaviorally
invisible when nothing inspects individual updates); a SIGTERM-
preempted secagg run resumes bit-for-bit (masks are derived, never
stored); every unsupported composition raises at init with a message
naming the offending flag (the PR 6 hierarchical rejections included);
the compiled vanilla round carries the structural wire facts; and
``--secagg groupwise`` composes with the two-tier tree (tier-2 robust
kernels over per-group sums, v5 'secagg' events with group-sum norms).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import (
    ExperimentConfig, FaultConfig
)
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.protocols import secagg as sa
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
from attacking_federate_learning_tpu.utils.metrics import (
    RunLogger, validate_event
)


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 12)
    kw.setdefault("mal_prop", 0.25)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 6)
    kw.setdefault("test_step", 3)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("defense", "NoDefense")
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    return ExperimentConfig(**kw)


_DS = {}


def _dataset(name=C.SYNTH_MNIST):
    if name not in _DS:
        _DS[name] = load_dataset(name, seed=0, synth_train=256,
                                 synth_test=64)
    return _DS[name]


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# ---------------------------------------------------------------------------
# protocol core: bit-exact mask cancellation (satellite 1)

def _matrix(n, d=257, seed=None):
    """An adversarially-scaled f32 matrix: magnitudes spanning ~16
    decades, the regime where f32 ADDITIVE masking could never cancel
    (rounding) — the uint32 bitcast domain must not care."""
    rng = np.random.default_rng(seed if seed is not None else n)
    G = rng.standard_normal((n, d)) * 10.0 ** rng.integers(-8, 8, (n, d))
    return jnp.asarray(G.astype(np.float32))


@pytest.mark.parametrize("n", [3, 19, 32])
def test_pairwise_cancellation_bitexact(n):
    """sum(masked) == sum(clear) BITWISE in the mod-2^32 domain: the
    antisymmetric per-pair masks cancel exactly in the modular column
    sum, while each individual wire row is garbage."""
    G = _matrix(n)
    ids = jnp.arange(n, dtype=jnp.int32)
    key_t = jax.random.fold_in(jax.random.key(7), 3)
    deltas = sa.pairwise_deltas(key_t, ids, G.shape[1])
    wire = sa.mask_rows(G, deltas)
    bits = jax.lax.bitcast_convert_type(G, jnp.uint32)
    np.testing.assert_array_equal(np.asarray(sa.modular_sum(wire)),
                                  np.asarray(sa.modular_sum(bits)))
    # Masking is not a no-op (every row actually moved).
    assert not (np.asarray(wire) == np.asarray(bits)).all(axis=1).any()
    # Per-row unmask is the exact inverse, and the sum check passes.
    rec, stats = sa.unmask_sum(wire, deltas, G, None, key_t, ids)
    np.testing.assert_array_equal(np.asarray(rec), np.asarray(G))
    assert int(stats["secagg_sum_check_ok"]) == 1
    assert int(stats["secagg_recovery"]) == 0


@pytest.mark.parametrize("n", [3, 19, 32])
def test_dropout_recovery_exact(n):
    """The Bonawitz recovery identity, bitwise: with dropped clients
    the survivors' modular sum minus the pair-by-pair reconstructed
    residue equals the clear survivors' modular sum exactly, and the
    reconstruction count is |alive| * |dropped| revealed pairs."""
    G = _matrix(n)
    ids = jnp.arange(n, dtype=jnp.int32)
    key_t = jax.random.fold_in(jax.random.key(7), 5)
    deltas = sa.pairwise_deltas(key_t, ids, G.shape[1])
    wire = sa.mask_rows(G, deltas)
    rng = np.random.default_rng(n)
    alive = rng.random(n) > 0.3
    alive[:2] = [False, True]            # >= 1 dropped, >= 1 survivor
    alive = jnp.asarray(alive)
    rec, stats = sa.unmask_sum(wire, deltas, G, alive, key_t, ids)
    n_alive, n_drop = int(alive.sum()), int((~alive).sum())
    assert int(stats["secagg_sum_check_ok"]) == 1
    assert int(stats["secagg_dropped"]) == n_drop
    assert int(stats["secagg_recovery"]) == 1
    assert int(stats["secagg_masks_reconstructed"]) == n_alive * n_drop
    np.testing.assert_array_equal(
        np.asarray(rec),
        np.where(np.asarray(alive)[:, None], np.asarray(G), 0.0))
    # The residue really is the survivors' unpaired mask mass: the
    # explicit identity modsum(wire[alive]) - R == modsum(clear[alive]).
    R, pairs = sa.recovery_residue(key_t, ids, alive, G.shape[1])
    bits = jax.lax.bitcast_convert_type(G, jnp.uint32)
    np.testing.assert_array_equal(
        np.asarray(sa.modular_sum(wire, alive) - R),
        np.asarray(sa.modular_sum(bits, alive)))
    assert int(pairs) == n_alive * n_drop


def test_mask_roundtrip_preserves_every_bit_pattern():
    """NaN/Inf/denormal rows ride the wire bit-exactly: the bitcast
    domain is invariant to float semantics (np.array_equal on the BIT
    view — NaN != NaN in float compare, but its pattern must survive)."""
    G = jnp.asarray(np.array(
        [[np.nan, np.inf, -np.inf, 0.0, -0.0],
         [1e-44, -1e-44, 3.14, -2.5e38, 2.5e38],
         [1.0, 2.0, 3.0, 4.0, 5.0]], np.float32))
    ids = jnp.arange(3, dtype=jnp.int32)
    key_t = jax.random.fold_in(jax.random.key(0), 0)
    deltas = sa.pairwise_deltas(key_t, ids, 5)
    rec = sa.unmask_rows(sa.mask_rows(G, deltas), deltas)
    np.testing.assert_array_equal(
        np.asarray(jax.lax.bitcast_convert_type(rec, jnp.uint32)),
        np.asarray(jax.lax.bitcast_convert_type(G, jnp.uint32)))


def test_masks_are_derived_not_stored():
    """Two independent derivations from the same config produce the
    identical mask stream (the preempt/resume re-derivation witness),
    and different rounds/seeds produce different streams."""
    cfg_a = ExperimentConfig(seed=3)
    key_a, key_b = sa.secagg_key(cfg_a), sa.secagg_key(
        ExperimentConfig(seed=3))
    ids = jnp.arange(5, dtype=jnp.int32)
    d_a = sa.pairwise_deltas(jax.random.fold_in(key_a, 2), ids, 17)
    d_b = sa.pairwise_deltas(jax.random.fold_in(key_b, 2), ids, 17)
    np.testing.assert_array_equal(np.asarray(d_a), np.asarray(d_b))
    d_c = sa.pairwise_deltas(jax.random.fold_in(key_a, 3), ids, 17)
    assert not np.array_equal(np.asarray(d_a), np.asarray(d_c))
    d_d = sa.pairwise_deltas(
        jax.random.fold_in(sa.secagg_key(ExperimentConfig(seed=4)), 2),
        ids, 17)
    assert not np.array_equal(np.asarray(d_a), np.asarray(d_d))


# ---------------------------------------------------------------------------
# acceptance: the protocol is behaviorally invisible

def test_vanilla_run_bit_equal_clear_nodefense(tmp_path):
    """--secagg vanilla final weights are bit-equal to the clear
    NoDefense run under an active ALIE-style attack: nothing in the
    run inspects individual updates, so masking must change nothing."""
    ds = _dataset()
    clear = FederatedExperiment(_cfg(tmp_path),
                                attacker=DriftAttack(1.0), dataset=ds)
    clear.run_span(0, 6)
    masked = FederatedExperiment(_cfg(tmp_path, secagg="vanilla"),
                                 attacker=DriftAttack(1.0), dataset=ds)
    masked.run_span(0, 6)
    np.testing.assert_array_equal(np.asarray(masked.state.weights),
                                  np.asarray(clear.state.weights))
    np.testing.assert_array_equal(np.asarray(masked.state.velocity),
                                  np.asarray(clear.state.velocity))


def test_vanilla_dropout_recovery_run(tmp_path):
    """--fault-dropout under --secagg vanilla: every dropout round
    completes as a mask-reconstruction round (exact sum recovery,
    counted in v5 'secagg' events) and the run stays bit-equal to the
    clear faulted run — recovery is exact, not approximate."""
    ds = _dataset()

    def run(tag, **kw):
        cfg = _cfg(tmp_path, faults=FaultConfig(dropout=0.25), **kw)
        exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
        with RunLogger(cfg, None, cfg.log_dir, jsonl_name=tag) as logger:
            exp.run(logger)
        return exp

    clear = run("clear_faulted")
    masked = run("secagg_faulted", secagg="vanilla")
    np.testing.assert_array_equal(np.asarray(masked.state.weights),
                                  np.asarray(clear.state.weights))
    events = _events(tmp_path / "logs" / "secagg_faulted.jsonl")
    sec = [e for e in events if e.get("kind") == "secagg"]
    faults = [e for e in events if e.get("kind") == "fault"]
    assert len(sec) == 6 and len(faults) == 6    # one per round, both
    assert all(e["sum_check_ok"] == 1 for e in sec)
    # The seeded schedule drops clients (the clear twin's fault events
    # witness it); every such round must be a recovery round whose
    # reconstruction count matches alive * dropped.
    assert sum(e["recovery"] for e in sec) >= 1
    for e in sec:
        drop = e["dropped"]
        assert e["recovery"] == (1 if drop else 0)
        assert e["masks_reconstructed"] == (12 - drop) * drop
        fe = next(f for f in faults if f["round"] == e["round"])
        assert fe["injected_dropout"] == drop


def test_groupwise_composes_with_hierarchy(tmp_path):
    """--secagg groupwise x --aggregation hierarchical: tier-2 robust
    kernels run over per-group sums end-to-end, 'secagg' events carry
    the per-group sum norms, and with a NoDefense tier-2 the protocol
    is behaviorally invisible against the plain hierarchical run."""
    ds = _dataset()
    cfg = _cfg(tmp_path, secagg="groupwise", aggregation="hierarchical",
               megabatch=4, tier2_defense="Krum")
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="gw") as logger:
        exp.run(logger)
    sec = [e for e in _events(tmp_path / "logs" / "gw.jsonl")
           if e.get("kind") == "secagg"]
    assert len(sec) == 6
    for e in sec:
        assert e["sum_check_ok"] == 1 and e["groups"] == 3
        assert len(e["group_sum_norms"]) == 3
        assert all(x > 0 for x in e["group_sum_norms"])

    masked = FederatedExperiment(
        _cfg(tmp_path, secagg="groupwise", aggregation="hierarchical",
             megabatch=4),
        attacker=DriftAttack(1.0), dataset=ds)
    masked.run_span(0, 6)
    plain = FederatedExperiment(
        _cfg(tmp_path, aggregation="hierarchical", megabatch=4),
        attacker=DriftAttack(1.0), dataset=ds)
    plain.run_span(0, 6)
    np.testing.assert_array_equal(np.asarray(masked.state.weights),
                                  np.asarray(plain.state.weights))


# ---------------------------------------------------------------------------
# satellite 2: SIGTERM preempt -> resume bit-for-bit (masks re-derived)

def test_secagg_preempt_resume_bit_for_bit(tmp_path):
    """test_hierarchy.py's journal-audit harness under --secagg
    vanilla + dropout faults: the mask PRNG state is derived, not
    stored, so the resumed attempt re-derives identical masks — final
    weights bit-equal to the uninterrupted run, journal exactly-once,
    and the resumed attempt's 'secagg' events (recovery counts
    included) byte-match the uninterrupted run's for the same rounds."""
    from attacking_federate_learning_tpu.utils.lifecycle import (
        GracefulShutdown, Preempted, RunJournal
    )

    kill_round = int(np.random.default_rng(31).integers(1, 9))
    ds = _dataset()

    def cfg_for(run_dir):
        return _cfg(tmp_path, secagg="vanilla",
                    faults=FaultConfig(dropout=0.25), epochs=10,
                    test_step=5, checkpoint_every=3,
                    run_dir=str(tmp_path / run_dir))

    cfg_ref = cfg_for("runs_ref")
    full = FederatedExperiment(cfg_ref, attacker=DriftAttack(1.0),
                               dataset=ds)
    with RunLogger(cfg_ref, None, cfg_ref.log_dir,
                   jsonl_name="sa_full") as logger:
        full.run(logger, checkpointer=Checkpointer(cfg_ref))
    w_full = np.array(full.state.weights, copy=True)

    cfg = cfg_for("runs_sup")
    ck = Checkpointer(cfg)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    with RunLogger(cfg, None, cfg.log_dir,
                   jsonl_name="sa_sup") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "sa"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    resumed = FederatedExperiment(cfg, attacker=DriftAttack(1.0),
                                  dataset=ds)
    state, extra = ck.resume(ck.latest(), with_extra=True)
    resumed.state = state
    resumed.restore_fault_state(extra)
    with RunLogger(cfg, None, cfg.log_dir,
                   jsonl_name="sa_sup") as logger:
        resumed.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, "sa"),
                    shutdown=GracefulShutdown(
                        preempt_at_round=kill_round))

    np.testing.assert_array_equal(np.asarray(resumed.state.weights),
                                  w_full)
    assert RunJournal(cfg.run_dir, "sa").verify(
        epochs=10, test_step=5) == []
    sup = [e for e in _events(tmp_path / "logs" / "sa_sup.jsonl")
           if e.get("kind") == "secagg"]
    ref = {e["round"]: e for e in
           _events(tmp_path / "logs" / "sa_full.jsonl")
           if e.get("kind") == "secagg"}
    rounds = [e["round"] for e in sup]
    assert rounds == sorted(set(rounds))        # exactly once per round
    assert set(rounds) == set(ref)
    for e in sup:                               # identical re-derivation
        for k in ("sum_check_ok", "dropped", "masks_reconstructed",
                  "recovery"):
            assert e[k] == ref[e["round"]][k], (e["round"], k)


# ---------------------------------------------------------------------------
# satellite 3: the loud-rejection message contract

# (cfg_kwargs, message fragment naming the offending flag).  Config-level
# rejections raise at ExperimentConfig construction.  (ISSUE 8 relaxed
# the matrix: --telemetry/--round-stats now compose with groupwise —
# tier-2 selection over group sums is server-visible — so only the
# VANILLA rows stay pinned here: one masked cohort sum has nothing
# per-client or per-group to observe.)
_CONFIG_REJECTS = [
    (dict(secagg="vanilla", defense="Krum"), "--secagg vanilla"),
    (dict(secagg="vanilla", defense="Bulyan"), "--tier2-defense"),
    (dict(secagg="groupwise", aggregation="hierarchical", megabatch=4,
          defense="TrimmedMean"), "--tier2-defense"),
    (dict(secagg="vanilla", aggregation="hierarchical", megabatch=4),
     "--secagg groupwise"),
    (dict(secagg="groupwise"), "--aggregation hierarchical"),
    (dict(secagg="vanilla", telemetry=True), "--telemetry"),
    (dict(secagg="vanilla", log_round_stats=True), "--round-stats"),
    (dict(secagg="vanilla", backdoor="pattern", backdoor_fused=False),
     "--backdoor-staged"),
    (dict(secagg="vanilla", participation=0.5), "--participation"),
    (dict(secagg="vanilla", grad_dtype="bfloat16"), "grad_dtype"),
    (dict(secagg="vanilla", faults=FaultConfig(straggler=0.2)),
     "--fault-straggler"),
    (dict(secagg="vanilla", faults=FaultConfig(corrupt=0.2)),
     "--fault-corrupt"),
    (dict(secagg="sideways"), "--secagg"),
]

# PR 6's hierarchical rejections, pinned to flag-naming messages too
# (minus telemetry/round-stats — supported since ISSUE 8 — and fault
# injection — supported since ISSUE 19, tests/test_hier_faults.py).
_ENGINE_REJECTS = [
    (dict(aggregation="hierarchical", megabatch=4, participation=0.5),
     "participation"),
    (dict(aggregation="hierarchical", megabatch=4,
          data_placement="host_stream"), "device"),
    (dict(aggregation="hierarchical", megabatch=4, backdoor="pattern",
          backdoor_fused=False), "--backdoor-staged"),
    (dict(aggregation="hierarchical", megabatch=4,
          trimmed_mean_impl="host"), "trimmed_mean_impl"),
    (dict(aggregation="hierarchical", megabatch=4,
          distance_impl="host"), "distance_impl"),
]


@pytest.mark.parametrize("kw,match", _CONFIG_REJECTS)
def test_secagg_config_rejections_name_the_flag(tmp_path, kw, match):
    with pytest.raises(ValueError, match=match):
        _cfg(tmp_path, **kw)


@pytest.mark.parametrize("kw,match", _ENGINE_REJECTS)
def test_hier_engine_rejections_name_the_flag(tmp_path, kw, match):
    with pytest.raises(ValueError, match=match):
        FederatedExperiment(_cfg(tmp_path, defense="Krum", **kw),
                            attacker=DriftAttack(1.0),
                            dataset=_dataset())


def test_groupwise_telemetry_composition(tmp_path):
    """ISSUE 8: --telemetry now composes with --secagg groupwise.  The
    observable surface is the GROUP-SUM level only: 'shard_selection'
    events carry tier-2 fields, never per-client stacks (no
    shard_grad_norms, no shard_selection_mask — tier-1 is NoDefense
    over rows the threat model hides); 'secagg' events grow the
    per-group envelope (cosine-to-mean next to the sum norms); and the
    run's weights stay bit-equal to the telemetry-off twin."""
    ds = _dataset()

    def cfg(**kw):
        return _cfg(tmp_path, secagg="groupwise",
                    aggregation="hierarchical", megabatch=4,
                    tier2_defense="Krum", **kw)

    off = FederatedExperiment(cfg(), attacker=DriftAttack(1.0),
                              dataset=ds)
    off.run_span(0, 6)
    c_on = cfg(telemetry=True)
    on = FederatedExperiment(c_on, attacker=DriftAttack(1.0), dataset=ds)
    with RunLogger(c_on, None, c_on.log_dir,
                   jsonl_name="gw_tele") as logger:
        on.run(logger)
    np.testing.assert_array_equal(np.asarray(off.state.weights),
                                  np.asarray(on.state.weights))
    events = _events(tmp_path / "logs" / "gw_tele.jsonl")
    ss = [e for e in events if e.get("kind") == "shard_selection"]
    assert len(ss) == 6 and all(e["v"] >= 6 for e in ss)
    for e in ss:
        assert len(e["tier2_selection_mask"]) == 3   # S groups
        # Per-client stacks must NOT appear under secagg: the server
        # never holds the rows they would be computed from.
        assert not any(k.startswith("shard_") for k in e)
    sec = [e for e in events if e.get("kind") == "secagg"]
    assert len(sec) == 6
    for e in sec:
        assert len(e["group_cos_to_mean"]) == 3
        assert all(-1.0 - 1e-5 <= x <= 1.0 + 1e-5
                   for x in e["group_cos_to_mean"])
    # Forensics runs on the groupwise stream too (tier-2-only view).
    from attacking_federate_learning_tpu.report import forensics_summary
    fx = forensics_summary(events)
    assert fx is not None and fx["tier2"]["rounds"] == 6
    assert "tier1" not in fx


def test_groupwise_round_stats_composition(tmp_path):
    """--round-stats under groupwise reports group-sum norm stats (the
    server-visible quantity), not per-client gradient norms."""
    ds = _dataset()
    exp = FederatedExperiment(
        _cfg(tmp_path, secagg="groupwise", aggregation="hierarchical",
             megabatch=4, tier2_defense="Krum", log_round_stats=True),
        attacker=DriftAttack(1.0), dataset=ds)
    exp.run_round(0)
    diag = {k: float(v) for k, v in exp.last_round_stats.items()}
    assert set(diag) == {"group_sum_norm_mean", "group_sum_norm_max",
                         "group_sum_norm_min", "update_norm",
                         "faded_lr"}
    assert diag["group_sum_norm_max"] >= diag["group_sum_norm_mean"] > 0


def test_secagg_rejects_nonfusable_attacker(tmp_path):
    """The engine-level half of the contract: a non-fusable attacker
    handed in programmatically (the --backdoor-staged path arrives as
    one) is rejected before any tracing."""
    class Staged(DriftAttack):
        fusable = False

    with pytest.raises(ValueError, match="fusable"):
        FederatedExperiment(_cfg(tmp_path, secagg="vanilla"),
                            attacker=Staged(1.0), dataset=_dataset())


# ---------------------------------------------------------------------------
# acceptance: HLO structure (secagg off byte-identical; vanilla wire pin)

def test_secagg_off_hlo_has_no_protocol_trace(tmp_path):
    """cfg.secagg='off' (the default) compiles a round with no uint32
    wire tensor and no secagg events — PERF_BASELINE's byte-exact
    FLOPs/bytes pins the stronger no-drift claim; this is the direct
    witness that the off path never touches the protocol."""
    ds = _dataset()
    exp = FederatedExperiment(_cfg(tmp_path), attacker=DriftAttack(1.0),
                              dataset=ds)
    text = exp._fused_round.lower(
        exp.state, jnp.asarray(0, jnp.int32), None).compile().as_text()
    facts = sa.wire_hlo_facts(text, 12, exp.flat.dim)
    assert not facts["wire_present"]
    assert facts["unmask_instructions"] == 0
    assert exp._secagg is None


def test_vanilla_wire_hlo_pin(tmp_path):
    """The perf_gate-memproof-style structural pin on the compiled
    vanilla round (tools/perf_gate.py wireproof runs the same facts in
    CI): the masked u32 wire exists, the server's reconstruction of
    the per-client matrix feeds ONLY the cohort-sum reduce, and no
    (n, n) distance matrix exists."""
    ds = _dataset()
    exp = FederatedExperiment(_cfg(tmp_path, secagg="vanilla"),
                              attacker=DriftAttack(1.0), dataset=ds)
    text = exp._fused_round.lower(
        exp.state, jnp.asarray(0, jnp.int32), None).compile().as_text()
    facts = sa.wire_hlo_facts(text, 12, exp.flat.dim)
    assert facts["wire_present"]
    assert facts["unmask_instructions"] >= 1
    assert facts["unmask_reduce_only"]
    assert not facts["distance_matrix"]


# ---------------------------------------------------------------------------
# schema v5, validator, report rollup

def test_secagg_event_schema_v5(tmp_path):
    validate_event({"kind": "secagg", "round": 3, "sum_check_ok": 1,
                    "v": 5})
    with pytest.raises(ValueError, match="need schema v5"):
        validate_event({"kind": "secagg", "round": 3, "v": 4})
    with pytest.raises(ValueError, match="missing required"):
        validate_event({"kind": "secagg", "v": 5})
    # tools/check_events.py speaks v5.
    import importlib.util as ilu
    spec = ilu.spec_from_file_location(
        "check_events", os.path.join(os.path.dirname(__file__),
                                     os.pardir, "tools",
                                     "check_events.py"))
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    p = tmp_path / "sec.jsonl"
    p.write_text(json.dumps({"kind": "secagg", "round": 0,
                             "sum_check_ok": 1, "recovery": 1,
                             "masks_reconstructed": 11, "v": 5,
                             "t": 0.1}) + "\n"
                 + json.dumps({"kind": "secagg", "round": 1, "v": 3,
                               "t": 0.2}) + "\n")
    counts, legacy, errors = mod.check_file(str(p))
    assert counts == {"secagg": 1}
    assert len(errors) == 1 and "need schema v5" in errors[0][1]


def test_report_secagg_rollup(tmp_path):
    from attacking_federate_learning_tpu.report import summarize_run

    events = [
        {"kind": "secagg", "round": 0, "sum_check_ok": 1, "dropped": 0,
         "masks_reconstructed": 0, "recovery": 0, "v": 5},
        {"kind": "secagg", "round": 1, "sum_check_ok": 1, "dropped": 2,
         "masks_reconstructed": 20, "recovery": 1,
         "group_sum_norms": [1.5, 2.5, 3.5], "v": 5},
        {"kind": "eval", "round": 1, "test_loss": 0.1, "accuracy": 50.0,
         "correct": 32, "test_size": 64, "v": 5},
    ]
    s = summarize_run(events)
    assert s["secagg"] == {
        "rounds": 2, "recovery_rounds": 1, "masks_reconstructed": 20,
        "sum_check_failures": 0, "groups": 3,
        "group_sum_norms_last": [1.5, 2.5, 3.5]}


# ---------------------------------------------------------------------------
# satellite 4: runs diff --band

def test_runs_diff_band_ulp_tolerance():
    from attacking_federate_learning_tpu.runs_cli import (
        _f32_ord, diff_trajectories
    )

    x = 193.0
    x1 = float(np.nextafter(np.float32(x), np.float32(np.inf)))
    assert _f32_ord(x1) - _f32_ord(x) == 1
    a = [{"kind": "round", "round": 0, "grad_norm_mean": x, "v": 5},
         {"kind": "round", "round": 1, "grad_norm_mean": -x, "v": 5}]
    b = [{"kind": "round", "round": 0, "grad_norm_mean": x1, "v": 5},
         {"kind": "round", "round": 1, "grad_norm_mean": -x, "v": 5}]
    exact = diff_trajectories(a, b)
    assert exact["divergence_round"] == 0
    assert not exact["bit_identical"]
    banded = diff_trajectories(a, b, band=1)
    assert banded["divergence_round"] is None
    assert banded.get("identical_within_band")
    assert not banded["bit_identical"]          # banded != bit-exact
    # Identical streams under band 0 still report bit-identity.
    assert diff_trajectories(a, list(a))["bit_identical"]
    # A real drift (beyond the band) still diverges.
    c = [{"kind": "round", "round": 0, "grad_norm_mean": x + 1.0,
          "v": 5}]
    assert diff_trajectories(a, c, band=4)["divergence_round"] == 0
    # Negative floats band correctly across the sign-magnitude seam.
    d1 = [{"kind": "round", "round": 0, "g": -0.0, "v": 5}]
    d2 = [{"kind": "round", "round": 0, "g": 0.0, "v": 5}]
    assert diff_trajectories(d1, d2, band=1)["divergence_round"] is None


# ---------------------------------------------------------------------------
# CLI surface

def test_cli_secagg_flag_roundtrip():
    from attacking_federate_learning_tpu.cli import (
        build_parser, config_from_args
    )

    args = build_parser().parse_args(
        ["-d", "NoDefense", "-s", "SYNTH_MNIST", "-n", "12",
         "--secagg", "groupwise", "--aggregation", "hierarchical",
         "--megabatch", "4", "--tier2-defense", "Krum"])
    cfg = config_from_args(args)
    assert cfg.secagg == "groupwise"
    assert cfg.aggregation == "hierarchical" and cfg.megabatch == 4
    assert cfg.tier2_defense == "Krum"
    args = build_parser().parse_args(["-d", "NoDefense", "--secagg",
                                      "vanilla"])
    assert config_from_args(args).secagg == "vanilla"
