"""Preemption-safe run lifecycle (ISSUE 4): graceful shutdown, the
exactly-once run journal, schema-v3 lifecycle events, and the report
rollup.

Acceptance contract: SIGTERM/SIGINT at a span boundary checkpoints,
journals 'preempted' and raises Preempted (exit 75 via the CLI); the
journal gives exactly-once round/eval accounting across restarts and
survives torn writes; v1/v2 logs stay valid under the v3 schema; and a
'lifecycle'-bearing run log reports its transitions.
"""

import json
import os
import signal
import threading

import numpy as np
import pytest

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
from attacking_federate_learning_tpu.utils.lifecycle import (
    EXIT_DIVERGED, EXIT_OK, EXIT_PREEMPTED, GracefulShutdown, Preempted,
    RunJournal, classify_failure, run_id_for
)
from attacking_federate_learning_tpu.utils.metrics import (
    RunLogger, validate_event
)


def _cfg(tmp_path, **kw):
    kw.setdefault("dataset", C.SYNTH_MNIST)
    kw.setdefault("users_count", 10)
    kw.setdefault("mal_prop", 0.2)
    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 10)
    kw.setdefault("test_step", 5)
    kw.setdefault("synth_train", 256)
    kw.setdefault("synth_test", 64)
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    kw.setdefault("run_dir", str(tmp_path / "runs"))
    return ExperimentConfig(**kw)


# ---------------------------------------------------------------------------
# the journal

def test_journal_exactly_once_and_replay(tmp_path):
    """Commits are monotonic (re-executions clamp to the fresh suffix),
    and a reopened journal replays its high-water mark and eval set."""
    j = RunJournal(str(tmp_path), "r1")
    assert j.start_attempt(0) == 1
    j.commit_rounds(0, 3)
    j.commit_eval(0)
    # Re-execution (rollback or resume replay) below the mark: no-op.
    j.commit_rounds(0, 3)
    j.commit_rounds(2, 5)          # clamped to [4, 5]
    j.commit_eval(0)               # duplicate eval: no-op
    j.commit_eval(5)
    j.finish("done")
    j.close()

    j2 = RunJournal(str(tmp_path), "r1")
    assert j2.high == 5
    assert j2.evals == {0, 5}
    assert j2.attempt == 1
    assert not j2.fresh_round(5) and j2.fresh_round(6)
    assert not j2.fresh_eval(5) and j2.fresh_eval(9)
    assert j2.verify(epochs=6) == []
    # Coverage gaps and cadence mismatches are named.
    problems = j2.verify(epochs=8, test_step=5)
    assert any("never committed" in p for p in problems)
    assert any("eval set mismatch" in p for p in problems)


def test_journal_duplicate_detection_from_raw_file(tmp_path):
    """verify() audits the RAW file, so even a buggy writer (or two
    uncoordinated ones) is caught."""
    d = tmp_path / "dup"
    os.makedirs(d)
    with open(d / "journal.jsonl", "w") as f:
        f.write(json.dumps({"kind": "rounds", "start": 0, "end": 2}) + "\n")
        f.write(json.dumps({"kind": "rounds", "start": 2, "end": 3}) + "\n")
        f.write(json.dumps({"kind": "eval", "round": 0}) + "\n")
        f.write(json.dumps({"kind": "eval", "round": 0}) + "\n")
    j = RunJournal(str(tmp_path), "dup")
    problems = j.verify(epochs=4)
    assert any("more than once: [2]" in p for p in problems)
    assert any("evals committed more than once: [0]" in p for p in problems)


def test_journal_torn_line_sealed_and_skipped(tmp_path):
    """A SIGKILL mid-append leaves a torn last line: the next attempt
    seals it with a newline, the reader skips (and counts) it, and new
    records stay parseable."""
    d = tmp_path / "torn"
    os.makedirs(d)
    with open(d / "journal.jsonl", "w") as f:
        f.write(json.dumps({"kind": "rounds", "start": 0, "end": 4}) + "\n")
        f.write('{"kind": "rounds", "start": 5, "e')     # torn mid-write
    j = RunJournal(str(tmp_path), "torn")
    assert j.high == 4
    assert j.torn_lines == 1
    j.commit_rounds(5, 7)          # appends after sealing the tail
    j.close()
    j2 = RunJournal(str(tmp_path), "torn")
    assert j2.high == 7
    assert j2.verify(epochs=8) == []


def test_manifest_status_transitions(tmp_path):
    j = RunJournal(str(tmp_path), "m")
    j.start_attempt(0)
    assert j.read_manifest()["status"] == "running"
    j.commit_rounds(0, 9)
    j.finish("preempted", EXIT_PREEMPTED, checkpoint="x.npz")
    man = j.read_manifest()
    assert man["status"] == "preempted"
    assert man["exit_code"] == EXIT_PREEMPTED
    assert man["last_round"] == 9 and man["rounds_committed"] == 10
    j.close()
    j2 = RunJournal(str(tmp_path), "m")
    assert j2.start_attempt(10) == 2
    assert j2.read_manifest()["attempt"] == 2


def test_run_id_identity(tmp_path):
    """Stable across processes and across io-only differences; distinct
    across anything that shapes the trajectory."""
    a = _cfg(tmp_path)
    b = _cfg(tmp_path, log_dir=str(tmp_path / "elsewhere"),
             run_dir=str(tmp_path / "other"), output="tee.txt")
    c = _cfg(tmp_path, seed=1)
    d = _cfg(tmp_path, defense="Krum")
    assert run_id_for(a) == run_id_for(b)
    assert run_id_for(a) != run_id_for(c)
    assert run_id_for(a) != run_id_for(d)
    assert run_id_for(a).startswith("SYNTH_MNIST_NoDefense_s0_")


# ---------------------------------------------------------------------------
# graceful shutdown

def test_graceful_shutdown_flag_and_restore():
    sd = GracefulShutdown(signals=(signal.SIGUSR1,))
    before = signal.getsignal(signal.SIGUSR1)
    with sd:
        assert not sd.requested
        os.kill(os.getpid(), signal.SIGUSR1)
        assert sd.requested and sd.source == "SIGUSR1"
        assert sd.should_preempt(0, 0)
    assert signal.getsignal(signal.SIGUSR1) == before


def test_injected_preempt_fires_once_per_lifecycle():
    """preempt_at_round fires for the attempt that STARTED at or before
    the injection point; the resumed attempt (which starts past it)
    must run to completion instead of re-preempting forever."""
    sd = GracefulShutdown(preempt_at_round=4)
    assert not sd.should_preempt(0, 3)
    assert sd.should_preempt(0, 4)
    assert sd.should_preempt(0, 6)       # first boundary past the mark
    assert sd.source == "injected"
    resumed = GracefulShutdown(preempt_at_round=4)
    assert not resumed.should_preempt(5, 7)


# ---------------------------------------------------------------------------
# engine integration

def _engine(cfg, ds=None):
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    ds = ds or load_dataset(cfg.dataset, seed=0,
                            synth_train=cfg.synth_train,
                            synth_test=cfg.synth_test)
    return FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)


def test_engine_preempt_checkpoints_then_resumes_exactly_once(tmp_path):
    """The full lifecycle in-process: injected preempt at a boundary ->
    auto-checkpoint + 'preempted' manifest + Preempted raised; a fresh
    engine resumes, finishes, and the journal + event stream account
    for every round and eval exactly once."""
    cfg = _cfg(tmp_path, checkpoint_every=3)
    rid = run_id_for(cfg)

    exp = _engine(cfg)
    ck = Checkpointer(cfg)
    j = RunJournal(cfg.run_dir, rid)
    sd = GracefulShutdown(preempt_at_round=4)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="lc") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, checkpointer=ck, journal=j, shutdown=sd)
    man = RunJournal(cfg.run_dir, rid).read_manifest()
    assert man["status"] == "preempted"
    assert os.path.exists(man["checkpoint"])

    resumed = _engine(cfg)
    ck2 = Checkpointer(cfg)
    state, extra = ck2.resume(ck2.latest(), with_extra=True)
    resumed.state = state
    resumed.restore_fault_state(extra)
    j2 = RunJournal(cfg.run_dir, rid)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="lc") as logger:
        resumed.run(logger, checkpointer=ck2, journal=j2,
                    shutdown=GracefulShutdown(preempt_at_round=4))
    final = RunJournal(cfg.run_dir, rid)
    assert final.verify(epochs=cfg.epochs, test_step=cfg.test_step) == []
    assert final.read_manifest()["status"] == "done"

    with open(os.path.join(cfg.log_dir, "lc.jsonl")) as f:
        events = [json.loads(line) for line in f]
    for e in events:
        validate_event(e)
    evals = [e["round"] for e in events if e["kind"] == "eval"]
    assert sorted(evals) == [0, 5, 9] and len(set(evals)) == len(evals)
    phases = [e["phase"] for e in events if e["kind"] == "lifecycle"]
    assert phases == ["start", "preempt", "resume", "complete"]


def test_engine_real_sigterm_preempts_at_first_boundary(tmp_path):
    """An actual SIGTERM delivered to the process (not the injection
    seam) is honored at the next span boundary."""
    cfg = _cfg(tmp_path, epochs=6, checkpoint_every=2)
    exp = _engine(cfg)
    sd = GracefulShutdown(signals=(signal.SIGTERM,))
    with sd:
        # Deliver before the loop starts: the request must be honored
        # at the FIRST boundary (deterministic — a timer-thread kill
        # mid-run would race the tiny run's wall clock).
        os.kill(os.getpid(), signal.SIGTERM)
        with RunLogger(cfg, None, cfg.log_dir, jsonl_name="sig") as logger:
            with pytest.raises(Preempted) as ei:
                exp.run(logger, checkpointer=Checkpointer(cfg),
                        journal=RunJournal(cfg.run_dir, "sig"),
                        shutdown=sd)
    assert ei.value.source == "SIGTERM"
    assert int(exp.state.round) >= 1        # at least one round banked
    assert RunJournal(cfg.run_dir, "sig").read_manifest()[
        "status"] == "preempted"


def test_preempt_without_checkpointer_still_checkpoints(tmp_path):
    """--no-checkpoint callers still get a resume point on preempt (a
    preempt that loses the run would defeat the point)."""
    cfg = _cfg(tmp_path, epochs=6)
    exp = _engine(cfg)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="nock") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, journal=None,
                    shutdown=GracefulShutdown(preempt_at_round=2))
    autos = [n for n in os.listdir(os.path.join(cfg.run_dir, cfg.dataset))
             if n.startswith("checkpoint-auto-")]
    assert autos


# ---------------------------------------------------------------------------
# schema v3

def test_v3_lifecycle_schema_rules():
    validate_event({"kind": "lifecycle", "phase": "preempt", "v": 3})
    validate_event({"kind": "lifecycle", "phase": "retry", "round": 4,
                    "attempt": 2, "v": 3})
    # v1/v2 logs stay valid under the v3 reader.
    validate_event({"kind": "round", "round": 1, "v": 1})
    validate_event({"kind": "heartbeat", "rss_mb": 1.0,
                    "last_event_age_s": 0.0, "v": 2})
    # A v3-only kind stamped older is an emitter bug.
    with pytest.raises(ValueError, match="need schema v3"):
        validate_event({"kind": "lifecycle", "phase": "x", "v": 2})
    with pytest.raises(ValueError, match="missing required"):
        validate_event({"kind": "lifecycle", "v": 3})


def test_check_events_accepts_v3(tmp_path):
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "check_events.py")
    spec = importlib.util.spec_from_file_location("check_events", path)
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)

    good = str(tmp_path / "v3.jsonl")
    with open(good, "w") as f:
        f.write(json.dumps({"kind": "lifecycle", "phase": "start",
                            "attempt": 1, "v": 3}) + "\n")
        f.write(json.dumps({"kind": "eval", "round": 0, "test_loss": 0.1,
                            "accuracy": 50.0, "correct": 32,
                            "test_size": 64, "v": 1}) + "\n")
        f.write(json.dumps({"kind": "heartbeat", "rss_mb": 1.0,
                            "last_event_age_s": 0.1, "v": 2}) + "\n")
    assert ce.main([good]) == 0
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write(json.dumps({"kind": "lifecycle", "phase": "start",
                            "v": 2}) + "\n")
    assert ce.main([bad]) == 1


# ---------------------------------------------------------------------------
# failure taxonomy + exit codes

def test_classify_failure_taxonomy():
    assert classify_failure(EXIT_OK) == "done"
    assert classify_failure(EXIT_PREEMPTED) == "preempted"
    assert classify_failure(EXIT_DIVERGED) == "divergence"
    assert classify_failure(1, "RESOURCE_EXHAUSTED: out of memory") == "oom"
    assert classify_failure(-9, "std::bad_alloc") == "oom"
    assert classify_failure(1, "Unable to initialize backend") == "backend"
    assert classify_failure(1, "relay connect timed out") == "backend"
    assert classify_failure(
        1, "FloatingPointError: server state diverged") == "divergence"
    assert classify_failure(-9, "") == "crash"
    # A supervisor-detected stall wins over whatever the kill left.
    assert classify_failure(-15, "", stalled=True) == "stall"
    assert classify_failure(EXIT_PREEMPTED, "", stalled=True) == "stall"


# ---------------------------------------------------------------------------
# report rollup

def test_report_lifecycle_summary(capsys):
    from attacking_federate_learning_tpu import report

    events = [
        {"kind": "lifecycle", "phase": "start", "attempt": 1, "v": 3},
        {"kind": "lifecycle", "phase": "preempt", "round": 4,
         "attempt": 1, "v": 3},
        {"kind": "lifecycle", "phase": "retry", "failure": "preempted",
         "v": 3},
        {"kind": "lifecycle", "phase": "degrade", "failure": "oom",
         "step": "batch_halved_to_8", "v": 3},
        {"kind": "lifecycle", "phase": "resume", "round": 5,
         "attempt": 2, "v": 3},
        {"kind": "lifecycle", "phase": "complete", "round": 9,
         "attempt": 2, "v": 3},
    ]
    s = report.summarize_run(events)
    lc = s["lifecycle"]
    assert lc["attempts"] == 2
    assert lc["last_phase"] == "complete"
    assert lc["phases"]["preempt"] == 1
    assert lc["degradations"] == ["batch_halved_to_8"]
    assert lc["failures"] == {"preempted": 1, "oom": 1}
    report._print_run("x", s, print)
    out = capsys.readouterr().out
    assert "lifecycle:" in out and "degradations" in out


def test_threaded_sigterm_is_seen_by_main_thread(tmp_path):
    """Signals sent from a worker thread (the supervisor's SIGTERM
    arrives asynchronously in the real topology) still set the flag in
    the main thread's handler."""
    sd = GracefulShutdown(signals=(signal.SIGUSR2,))
    with sd:
        t = threading.Thread(
            target=lambda: os.kill(os.getpid(), signal.SIGUSR2))
        t.start()
        t.join()
        # The handler runs between bytecodes of the main thread; give
        # it one explicit chance.
        for _ in range(100):
            if sd.requested:
                break
        assert sd.requested


def test_exactly_once_faulted_replay_suppression(tmp_path):
    """With fault injection on (per-round 'fault' events with or
    without telemetry), a resume replays rounds below the journal mark
    WITHOUT re-emitting their events — the stream stays exactly-once
    even though the rounds re-execute."""
    from attacking_federate_learning_tpu.config import FaultConfig

    fc = FaultConfig(dropout=0.2, straggler=0.15)
    cfg = _cfg(tmp_path, users_count=12, epochs=8, test_step=4,
               defense="TrimmedMean", faults=fc, checkpoint_every=3)
    rid = "faulted_once"
    exp = _engine(cfg)
    ck = Checkpointer(cfg)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="f1") as logger:
        with pytest.raises(Preempted):
            exp.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, rid),
                    shutdown=GracefulShutdown(preempt_at_round=4))
    resumed = _engine(cfg)
    state, extra = ck.resume(ck.latest(), with_extra=True)
    resumed.state = state
    resumed.restore_fault_state(extra)
    with RunLogger(cfg, None, cfg.log_dir, jsonl_name="f1") as logger:
        resumed.run(logger, checkpointer=ck,
                    journal=RunJournal(cfg.run_dir, rid),
                    shutdown=GracefulShutdown(preempt_at_round=4))
    with open(os.path.join(cfg.log_dir, "f1.jsonl")) as f:
        events = [json.loads(line) for line in f]
    fault_rounds = [e["round"] for e in events if e["kind"] == "fault"]
    assert sorted(fault_rounds) == list(range(8))      # once each
    assert RunJournal(cfg.run_dir, rid).verify(
        epochs=8, test_step=4) == []
