"""Checkpoint/resume: bit-for-bit continuation.

The reference is save-only and omits the momentum velocity (reference
server.py:40-48; SURVEY.md §5), so resume there would be inexact.  Here we
verify a resumed run continues identically to an uninterrupted one.
"""

import numpy as np

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer


def cfg_for(tmp_path):
    return ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                            batch_size=16, epochs=6, mal_prop=0.25,
                            run_dir=str(tmp_path / "runs"),
                            log_dir=str(tmp_path / "logs"))


def test_save_resume_roundtrip(tmp_path):
    cfg = cfg_for(tmp_path)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    for t in range(3):
        exp.run_round(t)
    ckpt = Checkpointer(cfg)
    path = ckpt.save(exp.state, accuracy=55.5)

    restored = ckpt.resume(path)
    np.testing.assert_array_equal(np.asarray(restored.weights),
                                  np.asarray(exp.state.weights))
    np.testing.assert_array_equal(np.asarray(restored.velocity),
                                  np.asarray(exp.state.velocity))
    assert int(restored.round) == int(exp.state.round) == 3


def test_resume_continues_bit_for_bit(tmp_path):
    cfg = cfg_for(tmp_path)

    # Uninterrupted 6-round run.
    full = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    for t in range(6):
        full.run_round(t)

    # 3 rounds, checkpoint, fresh process-equivalent, resume, 3 more.
    first = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    for t in range(3):
        first.run_round(t)
    ckpt = Checkpointer(cfg)
    ckpt.save(first.state, accuracy=0.0)

    second = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    second.state = ckpt.resume()
    for t in range(3, 6):
        second.run_round(t)

    np.testing.assert_array_equal(np.asarray(second.state.weights),
                                  np.asarray(full.state.weights))
    np.testing.assert_array_equal(np.asarray(second.state.velocity),
                                  np.asarray(full.state.velocity))
