"""Checkpoint/resume: bit-for-bit continuation.

The reference is save-only and omits the momentum velocity (reference
server.py:40-48; SURVEY.md §5), so resume there would be inexact.  Here we
verify a resumed run continues identically to an uninterrupted one.
"""

import numpy as np

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.attacks import DriftAttack
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.engine import FederatedExperiment
from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer


def cfg_for(tmp_path):
    return ExperimentConfig(dataset=C.SYNTH_MNIST, users_count=8,
                            batch_size=16, epochs=6, mal_prop=0.25,
                            run_dir=str(tmp_path / "runs"),
                            log_dir=str(tmp_path / "logs"))


def test_save_resume_roundtrip(tmp_path):
    cfg = cfg_for(tmp_path)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    for t in range(3):
        exp.run_round(t)
    ckpt = Checkpointer(cfg)
    path = ckpt.save(exp.state, accuracy=55.5)

    restored = ckpt.resume(path)
    np.testing.assert_array_equal(np.asarray(restored.weights),
                                  np.asarray(exp.state.weights))
    np.testing.assert_array_equal(np.asarray(restored.velocity),
                                  np.asarray(exp.state.velocity))
    assert int(restored.round) == int(exp.state.round) == 3


def test_atomic_save_leaves_no_temp_files(tmp_path):
    """Atomic replace (satellite): .npz/.json land via os.replace, so
    the directory never holds a torn or temporary file after save."""
    import os

    cfg = cfg_for(tmp_path)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    ckpt = Checkpointer(cfg)
    ckpt.save(exp.state, accuracy=10.0)
    ckpt.save_auto(exp.state, extra={"stale": np.zeros((2, 3), np.float32)})
    names = os.listdir(ckpt.dir)
    assert not any(n.endswith(".tmp") for n in names)
    assert "checkpoint.npz" in names and "checkpoint.json" in names
    assert any(n.startswith("checkpoint-auto-") for n in names)


def test_auto_rotation_keeps_last_n(tmp_path):
    import os

    import jax.numpy as jnp

    from attacking_federate_learning_tpu.core.server import ServerState

    cfg = cfg_for(tmp_path)
    ckpt = Checkpointer(cfg, keep_last=2)
    for r in range(5):
        state = ServerState(weights=jnp.zeros(4), velocity=jnp.zeros(4),
                            round=jnp.asarray(r, jnp.int32))
        ckpt.save_auto(state)
    autos = [n for n in os.listdir(ckpt.dir)
             if n.startswith("checkpoint-auto-") and n.endswith(".npz")]
    assert sorted(autos) == ["checkpoint-auto-00000003.npz",
                             "checkpoint-auto-00000004.npz"]
    # Sidecars rotate with their npz.
    jsons = [n for n in os.listdir(ckpt.dir)
             if n.startswith("checkpoint-auto-") and n.endswith(".json")]
    assert len(jsons) == 2
    assert ckpt.latest_auto().endswith("checkpoint-auto-00000004.npz")


def test_latest_picks_newest_by_round(tmp_path):
    import jax.numpy as jnp

    from attacking_federate_learning_tpu.core.server import ServerState

    def st(r):
        return ServerState(weights=jnp.zeros(4), velocity=jnp.zeros(4),
                           round=jnp.asarray(r, jnp.int32))

    cfg = cfg_for(tmp_path)
    ckpt = Checkpointer(cfg)
    ckpt.save(st(9), accuracy=80.0)       # best checkpoint at round 9
    ckpt.save_auto(st(4))
    assert ckpt.latest() == ckpt.path     # round 9 beats auto round 4
    ckpt.save_auto(st(12))
    assert ckpt.latest().endswith("checkpoint-auto-00000012.npz")
    assert ckpt.load_best_acc() == 80.0


def test_resume_roundtrips_extra_state(tmp_path):
    import jax.numpy as jnp

    from attacking_federate_learning_tpu.core.server import ServerState

    cfg = cfg_for(tmp_path)
    ckpt = Checkpointer(cfg)
    buf = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    state = ServerState(weights=jnp.ones(5), velocity=jnp.zeros(5),
                        round=jnp.asarray(7, jnp.int32))
    path = ckpt.save_auto(state, extra={"stale": buf})
    restored, extra = ckpt.resume(path, with_extra=True)
    assert int(restored.round) == 7
    np.testing.assert_array_equal(extra["stale"], buf)
    # Plain resume keeps the historical single-value contract.
    assert int(ckpt.resume(path).round) == 7


def test_resume_roundtrips_multi_array_extra(tmp_path):
    """ISSUE 9 satellite: the async engine checkpoints a MULTI-ARRAY
    carry (f32 ring + pending buffers, bool occupancy masks, int32
    birth/staleness counters) through the same ``extra=`` seam — every
    array and every dtype must survive the npz round trip, not just
    the single fault ring the pre-async tests exercised."""
    import jax.numpy as jnp

    from attacking_federate_learning_tpu.core.server import ServerState

    cfg = cfg_for(tmp_path)
    ckpt = Checkpointer(cfg)
    rng = np.random.default_rng(0)
    extra_in = {
        "async_buf": rng.normal(size=(3, 4, 5)).astype(np.float32),
        "async_occ": rng.random((3, 4)) > 0.5,
        "async_birth": rng.integers(0, 9, (3, 4)).astype(np.int32),
        "async_pbuf": rng.normal(size=(4, 5)).astype(np.float32),
        "async_pocc": rng.random(4) > 0.5,
        "async_pbirth": rng.integers(0, 9, 4).astype(np.int32),
    }
    state = ServerState(weights=jnp.ones(5), velocity=jnp.zeros(5),
                        round=jnp.asarray(3, jnp.int32))
    path = ckpt.save_auto(state, extra=extra_in)
    _, extra = ckpt.resume(path, with_extra=True)
    assert set(extra) == set(extra_in)
    for k, v in extra_in.items():
        assert extra[k].dtype == v.dtype, k
        np.testing.assert_array_equal(extra[k], v)


def test_resume_continues_bit_for_bit(tmp_path):
    cfg = cfg_for(tmp_path)

    # Uninterrupted 6-round run.  np.array(copy=True): np.asarray of a
    # CPU-backend jax array can be a zero-copy view, and the donating
    # round programs the later experiments run recycle that buffer —
    # the comparison must read memory it owns (this exact read has
    # segfaulted; core/engine.py:_host_copy makes the same choice).
    full = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    for t in range(6):
        full.run_round(t)
    w_full = np.array(full.state.weights, copy=True)
    v_full = np.array(full.state.velocity, copy=True)

    # 3 rounds, checkpoint, fresh process-equivalent, resume, 3 more.
    first = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    for t in range(3):
        first.run_round(t)
    ckpt = Checkpointer(cfg)
    ckpt.save(first.state, accuracy=0.0)

    second = FederatedExperiment(cfg, attacker=DriftAttack(1.5))
    second.state = ckpt.resume()
    for t in range(3, 6):
        second.run_round(t)

    np.testing.assert_array_equal(np.asarray(second.state.weights),
                                  w_full)
    np.testing.assert_array_equal(np.asarray(second.state.velocity),
                                  v_full)
