#!/usr/bin/env python
"""Supervisor crash-matrix smoke: seeded kill x dispatch mode x defense.

For every cell of {fused span, staged per-round, faulted span} x two
distance defenses, a supervised run (tools/supervisor.py) is preempted
at a random-but-SEEDED round (the FL_PREEMPT_AT_ROUND injection seam —
deterministic, so a failing cell replays exactly), resumed by the
supervisor, and then audited:

1. the supervisor exits clean (0) with bounded attempts — exactly one
   preempt resume, zero retry-budget charges;
2. the per-run journal covers every round and eval exactly once across
   the two attempts (utils/lifecycle.py:RunJournal.verify — the
   supervisor's --verify-journal enforces it in-band, and the matrix
   re-audits out-of-band);
3. the supervisor's own lifecycle event stream validates against the
   v3 schema and records the expected transitions.

The 'staged' cells run the real staged dispatch (pattern backdoor +
--backdoor-staged: per-round host boundaries, the reference's nan-guard
seam), so the preempt/resume contract is exercised on both sides of
the fused/staged split; the 'faulted' cells thread the straggler ring
buffer through the kill (Checkpointer ``extra``).

Usage:
    python tools/crash_matrix.py                 # full matrix
    python tools/crash_matrix.py --seed 7 --epochs 6

Exit status 0 when every cell passes, 1 otherwise.  CPU-pinned (this
must never race a TPU capture); CI-wired via tools/smoke.sh and
tests/test_supervisor.py.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import tempfile

os.environ["PALLAS_AXON_POOL_IPS"] = ""     # children inherit: never
os.environ["JAX_PLATFORMS"] = "cpu"         # touch the TPU relay

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from attacking_federate_learning_tpu.utils.lifecycle import (  # noqa: E402
    RunJournal
)
from attacking_federate_learning_tpu.utils.metrics import (  # noqa: E402
    iter_events
)

MODES = {
    # mode -> extra child flags (the dispatch-path axis)
    "fused": [],
    "staged": ["-b", "pattern", "--backdoor-staged"],
    "faulted": ["--fault-dropout", "0.2", "--fault-straggler", "0.1"],
}


def _load_supervisor():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "supervisor.py")
    spec = importlib.util.spec_from_file_location("supervisor", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_cell(sup, mode, defense, kill_round, epochs, workdir):
    """One supervised preempt/resume cycle; returns a list of problem
    strings (empty = cell passed)."""
    cell = f"{mode}_{defense}"
    run_dir = os.path.join(workdir, cell, "runs")
    log_dir = os.path.join(workdir, cell, "logs")
    run_id = f"crash_{cell}"
    events = os.path.join(log_dir, "supervisor.jsonl")
    child = ["--backend", "cpu", "-s", "SYNTH_MNIST", "-e", str(epochs),
             "-c", "16", "--synth-train", "256", "--synth-test", "64",
             "-d", defense, "--run-dir", run_dir, "--log-dir", log_dir,
             ] + MODES[mode]
    rc = sup.main(["--inject-preempt-round", str(kill_round),
                   "--verify-journal", "--checkpoint-every", "2",
                   "--max-retries", "2", "--run-id", run_id,
                   "--events", events, "--"] + child)
    problems = []
    if rc != 0:
        problems.append(f"supervisor exit {rc} (want 0)")
    journal = RunJournal(run_dir, run_id)
    problems += journal.verify(epochs=epochs, test_step=5)
    man = journal.read_manifest() or {}
    if man.get("status") != "done":
        problems.append(f"manifest status {man.get('status')!r} "
                        f"(want 'done')")
    if man.get("attempt") != 2:
        problems.append(f"attempts {man.get('attempt')} (want exactly 2: "
                        f"one preempt + one resume)")
    # The supervisor's own stream: v3-valid, expected transitions only.
    sup_events = list(iter_events(events))
    phases = [e["phase"] for e in sup_events]
    if phases.count("retry") != 1:
        problems.append(f"supervisor retries {phases.count('retry')} "
                        f"(want exactly 1, the preempt resume)")
    retries = [e for e in sup_events if e["phase"] == "retry"]
    if retries and retries[0].get("failure") != "preempted":
        problems.append(f"retry classified {retries[0].get('failure')!r} "
                        f"(want 'preempted')")
    if "supervise_done" not in phases:
        problems.append("no supervise_done transition")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Supervised preempt/resume crash matrix "
                    "(seeded kill round x dispatch mode x defense).")
    p.add_argument("--seed", default=0, type=int,
                   help="kill-round seed (deterministic replay)")
    p.add_argument("--epochs", default=6, type=int)
    p.add_argument("--modes", default="fused,staged,faulted")
    p.add_argument("--defenses", default="Krum,TrimmedMean")
    p.add_argument("--workdir", default=None,
                   help="cell run/log root (default: a temp dir)")
    args = p.parse_args(argv)

    import numpy as np

    rng = np.random.default_rng(args.seed)
    sup = _load_supervisor()
    workdir = args.workdir or tempfile.mkdtemp(prefix="crash_matrix_")
    failed = 0
    for mode in args.modes.split(","):
        for defense in args.defenses.split(","):
            # Seeded-but-random kill point strictly inside the run, so
            # the preempt boundary is never the trivial first/last one.
            kill_round = int(rng.integers(1, args.epochs - 1))
            problems = run_cell(sup, mode, defense, kill_round,
                                args.epochs, workdir)
            tag = f"{mode:8s} {defense:12s} kill@{kill_round}"
            if problems:
                failed += 1
                print(f"FAIL {tag}")
                for msg in problems:
                    print(f"     - {msg}")
            else:
                print(f"ok   {tag}")
    print(json.dumps({"crash_matrix": "FAIL" if failed else "ok",
                      "cells_failed": failed, "seed": args.seed,
                      "workdir": workdir}))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
