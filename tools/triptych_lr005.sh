#!/bin/bash
# Backdoor persistence triptych at the stable optimizer point
# (VERDICT r4 #2): the round-4 cells ran at the reference's lr 0.1 and
# two of three died in the lr-0.1 dead basin (Krum @ ~r90, Bulyan @
# ~r50), confounding the saturation-phase channel comparison.  The
# lr 0.05 control already converges cleanly and holds through round
# 149 (BASELINE.md round 4) — this re-runs all four cells there.
#
#   PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu bash tools/triptych_lr005.sh
#
# Serial by design (one core); each cell ~40-60 min (the backdoor
# cells pay the per-defense shadow-train compile once, then 150
# rounds).  Logs: logs/triptych005_<cell>.log + the config-keyed JSONL
# the engine writes (lr 0.05 keys distinct files from the r4 runs).
set -u
cd "$(dirname "$0")/.."
mkdir -p logs
# Pin the CPU backend HERE, not in the caller's memory: a default-env
# python with a dead relay blocks forever in the connect-retry loop
# (CLAUDE.md), and cli.py never calls ensure_live_backend — run bare,
# each cell would burn its whole timeout producing nothing.
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
COMMON="-s SYNTH_CIFAR10_HARD -e 150 -n 16 -m 0.2 -c 64 -l 0.05"

run_cell() {  # name, extra args...
  local name=$1; shift
  echo "=== triptych lr0.05 cell: $name ($(date +%T)) ==="
  timeout 7200 python -m attacking_federate_learning_tpu.cli \
    $COMMON "$@" -o "logs/triptych005_${name}.log"
  echo "=== $name done rc=$? ($(date +%T)) ==="
}

# Most-valuable-first: each finished cell is a banked artifact even if
# the round ends mid-script.  Krum carries the "immunity" claim, Bulyan
# the "no re-embed" claim; the control has a round-4 fallback
# (logs/convergence_control_lr005_r4.log, n=12) if time runs out.
run_cell krum_backdoor -d Krum -b pattern
run_cell bulyan_backdoor -d Bulyan -b pattern
run_cell trimmedmean_backdoor -d TrimmedMean -b pattern
# Control matches the triptych cohort (n=16) with no malicious
# clients; argparse takes the last -m, overriding COMMON's 0.2.
run_cell control_noattack -d TrimmedMean -m 0.0
echo "triptych lr0.05 complete"
