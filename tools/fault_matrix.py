#!/usr/bin/env python
"""Fault x defense smoke sweep: inject, quarantine, validate, in CI time.

Runs a short (default 5-round) SYNTH_MNIST experiment for every
mask-aware defense under a dropout+straggler+corrupt fault schedule,
then closes the loop three ways:

1. every run completes without raising (graceful degradation),
2. the emitted JSONL validates against the event schema
   (tools/check_events.py — the same validator CI wires for telemetry),
3. the per-round 'fault' event counts match a HOST-SIDE REPLAY of the
   deterministic injection schedule (core/faults.py:fault_masks is pure
   in (key, round), so the expected counts are recomputable without
   touching the engine) — an emitted count that drifts from the
   schedule fails the sweep.

Two composition legs ride along: the dropout x async-buffer cell
(core/async_rounds.py) and the hierarchical shard-domain chaos cells
(ISSUE 19) — two-tier runs under per-client faults PLUS correlated
shard-DOMAIN death, whose per-round 'fault' events (per-shard survivor
vectors included) and tier-2 ladder actions are diffed against the
host replay (core/faults.py:hier_fault_schedule / plan_tier2_actions).

Usage:
    python tools/fault_matrix.py                        # full smoke
    python tools/fault_matrix.py --epochs 5 --defenses Krum,Median
    python tools/fault_matrix.py --no-async --no-hier   # flat only

Exit status 0 when every cell passes, 1 otherwise.  CI-wired via
tests/test_faults.py next to the check_events hook.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from attacking_federate_learning_tpu.core.faults import (  # noqa: E402
    MASK_AWARE_DEFENSES
)


def expected_schedule(cfg, m, m_mal, epochs):
    """Host replay of the deterministic injection schedule: per-round
    (dropout, straggler, corrupt, quarantined) counts recomputed from
    the same PRNG derivation the fused round program uses."""
    import numpy as np

    from attacking_federate_learning_tpu.core.faults import (
        fault_key, fault_masks
    )

    key = fault_key(cfg)
    rows = []
    for t in range(epochs):
        drop, stale, corrupt = (np.asarray(x) for x in
                                fault_masks(key, t, m, m_mal, cfg.faults))
        quarantined = int(drop.sum())
        if cfg.faults.corrupt_mode in ("nan", "inf"):
            quarantined += int(corrupt.sum())
        rows.append({"injected_dropout": int(drop.sum()),
                     "injected_straggler": int(stale.sum()),
                     "injected_corrupt": int(corrupt.sum()),
                     "quarantined": quarantined})
    return rows


def matrix_spec(defenses, faults_kw, epochs, users, log_dir):
    """The fault x defense sweep as a campaign spec (ISSUE 10
    satellite: the ad-hoc cell loop ported onto campaign cells —
    campaigns/spec.py; the host-replay event diff stays wired as the
    per-cell check through the scheduler's ``checks`` hook)."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.campaigns.spec import (
        CampaignSpec
    )

    return CampaignSpec(
        name="fault_matrix",
        base=dict(dataset=C.SYNTH_MNIST, users_count=users,
                  mal_prop=0.2 if users >= 15 else 0.1,
                  num_std=1.0,            # the historical DriftAttack z
                  batch_size=16, epochs=epochs, test_step=epochs,
                  synth_train=256, synth_test=64,
                  faults=dict(faults_kw), log_dir=log_dir,
                  attack="alie"),
        axes={"defense": list(defenses)},
        order="spec")


def check_cell(path, cfg, epochs):
    """Schema-validate the run log and diff its 'fault' events against
    the host replay; returns a list of error strings (empty = pass)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_events", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "check_events.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)

    errors = []
    counts, _, bad_lines = ce.check_file(path)
    errors += [f"line {ln}: {msg}" for ln, msg in bad_lines]
    faults = []
    from attacking_federate_learning_tpu.utils.metrics import iter_events
    for e in iter_events(path):
        if e["kind"] == "fault" and not e.get("rolled_back"):
            faults.append(e)
    if len(faults) != epochs:
        errors.append(f"expected {epochs} fault events, got {len(faults)}")
        return errors
    exp_cfg = cfg
    want = expected_schedule(exp_cfg, exp_cfg.users_count,
                             exp_cfg.corrupted_count, epochs)
    for t, (got, exp) in enumerate(zip(sorted(faults,
                                              key=lambda e: e["round"]),
                                       want)):
        for k, v in exp.items():
            if int(got.get(k, -1)) != v:
                errors.append(
                    f"round {t}: {k} emitted {got.get(k)} != scheduled {v}")
    return errors


def run_async_cell(defense, epochs, users, log_dir, dropout=0.2,
                   async_buffer=8):
    """ISSUE 9 satellite: the dropout × async-buffer smoke leg.  One
    short aggregation='async' run under dropout faults, then three
    closures: the log schema-validates, every round carries a v7
    'async' event whose delivery dynamics match the host replay
    (core/async_rounds.py:replay_schedule), and the emitted
    'fault' dropout counts match the shared fault_masks schedule.
    Returns a list of error strings (empty = pass)."""
    import importlib.util

    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import (
        ExperimentConfig, FaultConfig
    )
    from attacking_federate_learning_tpu.core.async_rounds import (
        replay_schedule
    )
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.utils.metrics import (
        RunLogger, iter_events
    )

    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST, users_count=users,
        mal_prop=0.2 if users >= 15 else 0.1,
        batch_size=16, epochs=epochs, test_step=epochs,
        defense=defense, synth_train=256, synth_test=64,
        aggregation="async", async_buffer=async_buffer,
        async_max_staleness=2, staleness_weight="poly",
        faults=FaultConfig(dropout=dropout), log_dir=log_dir)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    name = f"fault_matrix_async_{defense}"
    path = os.path.join(log_dir, name + ".jsonl")
    try:
        with RunLogger(cfg, None, log_dir, jsonl_name=name) as logger:
            exp.run(logger)
    except Exception as e:                        # noqa: BLE001
        return [f"raised: {e}"]

    spec = importlib.util.spec_from_file_location(
        "check_events", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "check_events.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    errors = []
    _, _, bad_lines = ce.check_file(path)
    errors += [f"line {ln}: {msg}" for ln, msg in bad_lines]

    asyncs, faults = [], []
    for e in iter_events(path):
        if e["kind"] == "async":
            asyncs.append(e)
        elif e["kind"] == "fault" and not e.get("rolled_back"):
            faults.append(e)
    if len(asyncs) != epochs:
        errors.append(f"expected {epochs} async events, got "
                      f"{len(asyncs)}")
        return errors
    rows = replay_schedule(cfg, exp.m, exp.m_mal, epochs)
    for e, r in zip(sorted(asyncs, key=lambda e: e["round"]), rows):
        for k in ("delivered", "pending", "evicted", "superseded"):
            if int(e[k]) != r[k]:
                errors.append(f"round {e['round']}: async {k} emitted "
                              f"{e[k]} != replayed {r[k]}")
        if [int(x) for x in e["staleness_hist"]] != r["staleness_hist"]:
            errors.append(f"round {e['round']}: staleness_hist "
                          f"{e['staleness_hist']} != "
                          f"{r['staleness_hist']}")
    want = expected_schedule(cfg, exp.m, exp.m_mal, epochs)
    for got, exp_row in zip(sorted(faults, key=lambda e: e["round"]),
                            want):
        if int(got.get("injected_dropout", -1)) != exp_row[
                "injected_dropout"]:
            errors.append(
                f"round {got['round']}: injected_dropout "
                f"{got.get('injected_dropout')} != scheduled "
                f"{exp_row['injected_dropout']}")
    return errors


# Hierarchical chaos cells (ISSUE 19): (defense, users, megabatch)
# triples sized so BOTH tiers clear their validity bounds at the
# spread-placement per-tier f (Krum needs n >= 2f+3 at each tier,
# Bulyan n >= 4f+3 — ops/federated.py tier1_assumed/tier2_assumed).
# The first cell adds stragglers: the (delay, S, m, d) ring only
# exists under the sequential scan, and the sweep should cover it.
HIER_CELLS = (
    ("TrimmedMean", 16, 4, True),
    ("Median", 16, 4, False),
    ("NoDefense", 16, 4, False),
    ("Krum", 25, 5, False),
    ("Bulyan", 49, 7, False),
)


def run_hier_cell(defense, epochs, users, megabatch, log_dir,
                  dropout=0.2, corrupt=0.05, shard_dropout=0.25,
                  with_straggler=False):
    """ISSUE 19 satellite: the hierarchical chaos leg.  One short
    aggregation='hierarchical' run under per-client faults AND the
    correlated shard-DOMAIN axis, then three closures: the run
    completes (graceful degradation through the tier-2 ladder), the
    log schema-validates, and every per-round 'fault' event — the
    per-shard survivor vector ``shard_alive`` included — matches the
    host replay (core/faults.py:hier_fault_schedule is pure in
    (fault key, round, shard id)), with the emitted ``tier2_action``
    diffed against the independently recomputed ladder plan
    (plan_tier2_actions).  Returns a list of error strings."""
    import importlib.util

    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import (
        ExperimentConfig, FaultConfig
    )
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.core.faults import (
        hier_fault_schedule, plan_tier2_actions
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.utils.metrics import (
        RunLogger, iter_events
    )

    faults = FaultConfig(
        dropout=dropout, corrupt=corrupt, shard_dropout=shard_dropout,
        shard_dropout_dwell=2,
        straggler=0.1 if with_straggler else 0.0, straggler_delay=2)
    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST, users_count=users,
        mal_prop=0.2 if defense != "Bulyan" else 1.0 / megabatch,
        batch_size=16, epochs=epochs, test_step=epochs,
        defense=defense, synth_train=256, synth_test=64,
        aggregation="hierarchical", megabatch=megabatch,
        faults=faults, log_dir=log_dir)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.0), dataset=ds)
    name = f"fault_matrix_hier_{defense}"
    path = os.path.join(log_dir, name + ".jsonl")
    try:
        with RunLogger(cfg, None, log_dir, jsonl_name=name) as logger:
            exp.run(logger)
    except Exception as e:                        # noqa: BLE001
        return [f"raised: {e}"]

    spec = importlib.util.spec_from_file_location(
        "check_events", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "check_events.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    errors = []
    _, _, bad_lines = ce.check_file(path)
    errors += [f"line {ln}: {msg}" for ln, msg in bad_lines]

    events = [e for e in iter_events(path)
              if e["kind"] == "fault" and not e.get("rolled_back")]
    if len(events) != epochs:
        errors.append(f"expected {epochs} fault events, got "
                      f"{len(events)}")
        return errors
    rows = hier_fault_schedule(exp._fault_key, 0, epochs,
                               exp._placement, exp.faults)
    plan = plan_tier2_actions([r["shards_alive"] for r in rows],
                              exp._tier2_name, exp._tier2_f)
    for got, want, act in zip(sorted(events, key=lambda e: e["round"]),
                              rows, plan):
        t = want["round"]
        for k in ("injected_dropout", "injected_straggler",
                  "injected_corrupt", "quarantined", "shards_dead",
                  "shards_alive"):
            if int(got.get(k, -1)) != want[k]:
                errors.append(f"round {t}: {k} emitted {got.get(k)} "
                              f"!= scheduled {want[k]}")
        if [int(x) for x in got.get("shard_alive", [])] != \
                want["shard_alive"]:
            errors.append(f"round {t}: shard_alive emitted "
                          f"{got.get('shard_alive')} != scheduled "
                          f"{want['shard_alive']}")
        if int(got.get("tier2_action", -1)) != int(act):
            errors.append(f"round {t}: tier2_action emitted "
                          f"{got.get('tier2_action')} != planned "
                          f"{int(act)}")
    return errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="5-round fault x defense smoke sweep with schedule "
                    "validation (core/faults.py), plus the dropout x "
                    "async-buffer leg (core/async_rounds.py) and the "
                    "hierarchical shard-domain chaos leg "
                    "(core/faults.py:hier_fault_schedule).")
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--users", type=int, default=15)
    p.add_argument("--defenses", default=",".join(MASK_AWARE_DEFENSES),
                   help="comma-separated subset of the mask-aware "
                        "defenses")
    p.add_argument("--dropout", type=float, default=0.2)
    p.add_argument("--straggler", type=float, default=0.1)
    p.add_argument("--corrupt", type=float, default=0.05)
    p.add_argument("--no-async", action="store_true",
                   help="skip the dropout x async-buffer smoke leg")
    p.add_argument("--no-hier", action="store_true",
                   help="skip the hierarchical shard-domain chaos leg")
    p.add_argument("--hier-shard-dropout", type=float, default=0.25,
                   help="per-round shard-DOMAIN failure onset "
                        "probability for the hier leg")
    p.add_argument("--log-dir", default=None,
                   help="where run JSONLs land (default: a temp dir)")
    args = p.parse_args(argv)

    log_dir = args.log_dir or tempfile.mkdtemp(prefix="fault_matrix_")
    faults_kw = dict(dropout=args.dropout, straggler=args.straggler,
                     corrupt=args.corrupt)
    defenses = [d.strip() for d in args.defenses.split(",")]
    spec = matrix_spec(defenses, faults_kw, args.epochs, args.users,
                       log_dir)

    from attacking_federate_learning_tpu.campaigns.scheduler import (
        Campaign
    )

    def checks(cell, result):
        # The host-replay event diff, per cell: a 'done' run whose
        # emitted fault counts drift from the schedule FAILS the cell.
        return check_cell(result["events"], cell.cfg, args.epochs)

    rows = []

    def on_cell(cell, row):
        rows.append((cell, row))

    rc = Campaign(spec, executor="inline", journal_runs=False,
                  persist=False, checks=checks, on_cell=on_cell).run()
    # A skipped cell means the caller named a defense the fault model
    # cannot run — an error here (the default set is mask-aware only).
    failed = rc != 0 or any(row["state"] != "done" for _, row in rows)
    for cell, row in rows:
        defense = cell.cfg.defense if cell.cfg else "?"
        if row["state"] == "done":
            print(f"ok   {defense}: {args.epochs} rounds, fault events "
                  f"match the injected schedule  ({row.get('events')})")
        else:
            print(f"FAIL {defense} ({row['state']}): "
                  f"{row.get('reason')}")
    if not args.no_async:
        errors = run_async_cell("Krum", args.epochs, args.users,
                                log_dir, dropout=args.dropout)
        if errors:
            failed = True
            print(f"FAIL async(Krum): {len(errors)} problem(s)")
            for e in errors[:10]:
                print(f"  {e}")
        else:
            print(f"ok   async(Krum): {args.epochs} rounds, dropout x "
                  f"async-buffer — async + fault events match the "
                  f"replayed schedule")
    if not args.no_hier:
        wanted = {d.strip() for d in args.defenses.split(",")}
        for defense, users, megabatch, stragglers in HIER_CELLS:
            if defense not in wanted:
                continue
            errors = run_hier_cell(
                defense, args.epochs, users, megabatch, log_dir,
                dropout=args.dropout, corrupt=args.corrupt,
                shard_dropout=args.hier_shard_dropout,
                with_straggler=stragglers)
            tag = (f"hier({defense}, n={users}, m={megabatch}"
                   f"{', stragglers' if stragglers else ''})")
            if errors:
                failed = True
                print(f"FAIL {tag}: {len(errors)} problem(s)")
                for e in errors[:10]:
                    print(f"  {e}")
            else:
                print(f"ok   {tag}: {args.epochs} rounds, shard-domain "
                      f"chaos — per-shard fault events + tier-2 ladder "
                      f"actions match the host replay")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
