#!/usr/bin/env python
"""Noise-banded wall-clock gate over measured stage walls.

tools/perf_gate.py deliberately refuses to gate wall time (static HLO
facts only) because raw stopwatch numbers on this box are noisy — one
shared core, background capture watchers, compile-cache state.  This
gate makes wall time gateable anyway by measuring it the way
utils/walls.py books it (per-stage op time from a profiler trace, not
one end-to-end stopwatch) and comparing MEDIANS over k repeats against
a checked-in ``WALL_BASELINE.json`` inside explicit noise bands:

    band_us(stage) = max(rel_band * base_median,
                         mad_mult * (base_MAD + cur_MAD),
                         floor_us)

- the k-repeat median discards scheduler hiccups in any single repeat;
- the MAD term widens the band when the stage is *measurably* noisy
  (either at baseline time or now) instead of guessing a tolerance;
- the relative band and the absolute floor keep tiny stages (sub-ms
  ``apply``) from failing on microsecond jitter.

Only regressions gate (current median above the band's upper edge);
getting faster prints a note.  Two absolute facts ride along, baseline
or not: the booked partition must be exact (WallRecord.check) and each
capture must actually contain op events — a capture with none means
the ``--xla_cpu_enable_xprof_traceme`` flag missed the first compile
and the "walls" would be vacuously green.

The baseline records its environment (jax/jaxlib version, platform,
cpu count) and provenance (k, rounds per repeat, cell set).  On a
mismatched environment wall numbers are meaningless, so the gate SKIPS
loudly with exit 0 unless ``--strict-env``; regenerate with
``--update`` after a toolchain or host change.

Usage:
    python tools/wall_gate.py                   # gate against baseline
    python tools/wall_gate.py --update          # (re)generate baseline
    python tools/wall_gate.py -k 5 --cells krum

Exit status: 0 clean (or env-skip), 1 on a regression / broken
partition / op-eventless capture, 2 when the baseline is missing.
tools/smoke.sh runs the self-consistency leg (fresh --update followed
by a gate against it in a temp dir).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "WALL_BASELINE.json")

# Pinned cells: one per engine family that owns a span entry point.
# Small enough that k repeats of ROUNDS rounds stay in CI time on CPU;
# the per-stage SHAPE (which stage dominates) is what the gate pins,
# not absolute throughput.
CELLS = {
    "krum": dict(defense="Krum"),
    "hier_krum": dict(defense="Krum", aggregation="hierarchical",
                      users_count=12, mal_prop=0.25, megabatch=4),
}

ROUNDS = 3          # rounds per traced repeat (one span call)
DEFAULT_K = 3

BAND = dict(rel_band=0.75, mad_mult=10.0, floor_us=25_000.0)

# An op-time fraction this low means the capture was mostly events the
# HLO join could not explain — the booking is untrustworthy, fail
# rather than gate noise against noise.
OP_TIME_FLOOR = 0.5


def environment() -> dict:
    import importlib.metadata as md

    import jax

    def _v(pkg):
        try:
            return md.version(pkg)
        except Exception:
            return "unknown"

    return {"jax": _v("jax"), "jaxlib": _v("jaxlib"),
            "platform": jax.devices()[0].platform,
            "cpus": os.cpu_count()}


def _median(vals):
    vals = sorted(vals)
    n = len(vals)
    if not n:
        return 0.0
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def _mad(vals):
    med = _median(vals)
    return _median([abs(v - med) for v in vals])


def _pinned_experiment(overrides: dict):
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    base = dict(
        dataset=C.SYNTH_MNIST, users_count=11, mal_prop=0.2,
        batch_size=16, epochs=5, test_step=5, seed=0,
        synth_train=256, synth_test=64)
    base.update(overrides)
    cfg = ExperimentConfig(**base)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    return FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds)


def measure_cell(name: str, overrides: dict, k: int,
                 problems: list) -> dict:
    """k traced repeats of one ROUNDS-round span; returns the
    per-stage sample lists (us) plus booking diagnostics.  The warmup
    span compiles the program OUTSIDE any trace so repeat 0 measures
    execution, not compilation."""
    import jax

    from attacking_federate_learning_tpu.utils import walls
    from attacking_federate_learning_tpu.utils.profiling import (
        device_trace
    )

    exp = _pinned_experiment(overrides)
    epoch = 0
    exp.run_span(epoch, ROUNDS)                       # warmup/compile
    jax.block_until_ready(exp.state.weights)
    epoch += ROUNDS
    samples: dict = {}
    fracs = []
    root = tempfile.mkdtemp(prefix=f"wallgate_{name}_")
    try:
        for rep in range(k):
            td = os.path.join(root, f"rep{rep}")
            with device_trace(td):
                exp.run_span(epoch, ROUNDS)
                jax.block_until_ready(exp.state.weights)
            epoch += ROUNDS
            rec = walls.book_trace(
                td, exp._span_hlo_text(ROUNDS),
                name=exp._span_entry_name(),
                platform=jax.default_backend(), rounds=ROUNDS)
            if rec is None:
                problems.append(f"{name}[rep{rep}]: capture produced "
                                f"no trace file")
                continue
            rec.check()                               # exact partition
            cov = rec.coverage
            if cov["op_events"] == 0:
                problems.append(
                    f"{name}[rep{rep}]: 0 op events in the capture — "
                    f"the xprof-traceme flag missed the first compile "
                    f"of this process; nothing to gate")
                continue
            if cov["op_time_fraction"] < OP_TIME_FLOOR:
                problems.append(
                    f"{name}[rep{rep}]: op-time fraction "
                    f"{cov['op_time_fraction']:.2f} below the "
                    f"{OP_TIME_FLOOR} floor — booking untrustworthy")
            fracs.append(cov["op_time_fraction"])
            rows = dict(rec.stages)
            rows["unattributed"] = rec.unattributed_us
            for stage, us in rows.items():
                samples.setdefault(stage, []).append(float(us))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    out = {"entry": exp._span_entry_name(), "rounds": ROUNDS,
           "op_time_fraction": round(_median(fracs), 4) if fracs
           else 0.0,
           "stages": {}}
    for stage, vals in sorted(samples.items()):
        out["stages"][stage] = {
            "median_us": round(_median(vals), 3),
            "mad_us": round(_mad(vals), 3),
            "k": len(vals)}
    return out


def measure(cells, k: int, problems: list) -> dict:
    out = {}
    for name in cells:
        out[name] = measure_cell(name, CELLS[name], k, problems)
        stages = out[name]["stages"]
        top = max(stages, key=lambda s: stages[s]["median_us"]) \
            if stages else "-"
        print(f"  measured {name} ({out[name]['entry']}, k={k}): "
              + "  ".join(
                  f"{s}={v['median_us'] / 1e3:.1f}ms"
                  for s, v in stages.items())
              + f"  [top: {top}]")
    return out


def band_us(base: dict, cur_mad: float, cfg: dict) -> float:
    return max(cfg["rel_band"] * base["median_us"],
               cfg["mad_mult"] * (base["mad_us"] + cur_mad),
               cfg["floor_us"])


def diff(baseline: dict, measured: dict, band_cfg: dict) -> list:
    """Regression strings (empty = clean).  Only slower-than-band
    gates; a vanished stage or entry point gates too (the program
    family changed under the baseline)."""
    problems = []
    for cell, base in baseline.items():
        got = measured.get(cell)
        if got is None:
            problems.append(f"{cell}: cell not measured")
            continue
        if got["entry"] != base["entry"]:
            problems.append(
                f"{cell}: span entry point {got['entry']} != "
                f"baseline {base['entry']} (regenerate with --update)")
            continue
        for stage, want in base["stages"].items():
            have = got["stages"].get(stage)
            if have is None:
                # A stage present at baseline vanishing entirely is a
                # program change, not noise.
                problems.append(
                    f"{cell}.{stage}: stage present in baseline "
                    f"({want['median_us'] / 1e3:.1f} ms) but absent "
                    f"from the fresh capture")
                continue
            band = band_us(want, have["mad_us"], band_cfg)
            excess = have["median_us"] - (want["median_us"] + band)
            if excess > 0:
                problems.append(
                    f"{cell}.{stage}: median {have['median_us'] / 1e3:.1f}"
                    f" ms above baseline {want['median_us'] / 1e3:.1f} ms"
                    f" + band {band / 1e3:.1f} ms "
                    f"(over by {excess / 1e3:.1f} ms)")
            elif have["median_us"] + band < want["median_us"]:
                print(f"note wall_gate {cell}.{stage}: faster than the "
                      f"baseline band "
                      f"({have['median_us'] / 1e3:.1f} ms vs "
                      f"{want['median_us'] / 1e3:.1f} ms) — consider "
                      f"--update to tighten")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Noise-banded measured-walls gate over pinned "
                    "small configs (utils/walls.py booking, k-repeat "
                    "median + MAD bands).")
    p.add_argument("--baseline", default=BASELINE)
    p.add_argument("--update", action="store_true",
                   help="write a fresh baseline instead of gating")
    p.add_argument("--cells", default=",".join(CELLS),
                   help="comma-separated subset of the pinned cells")
    p.add_argument("-k", "--repeats", type=int, default=DEFAULT_K,
                   help=f"traced repeats per cell (default "
                        f"{DEFAULT_K}; medians over these)")
    p.add_argument("--rel-band", type=float, default=BAND["rel_band"])
    p.add_argument("--mad-mult", type=float, default=BAND["mad_mult"])
    p.add_argument("--floor-us", type=float, default=BAND["floor_us"])
    p.add_argument("--strict-env", action="store_true",
                   help="treat a baseline/environment mismatch as a "
                        "failure instead of a skip")
    args = p.parse_args(argv)

    cells = [c.strip() for c in args.cells.split(",") if c.strip()]
    unknown = [c for c in cells if c not in CELLS]
    if unknown:
        print(f"unknown cells: {unknown} (known: {sorted(CELLS)})")
        return 2

    # Must land before the FIRST compile of this process — XLA parses
    # XLA_FLAGS exactly once.
    from attacking_federate_learning_tpu.utils.profiling import (
        ensure_op_profiling
    )
    ensure_op_profiling()

    band_cfg = dict(rel_band=args.rel_band, mad_mult=args.mad_mult,
                    floor_us=args.floor_us)
    env = environment()

    if args.update:
        problems: list = []
        measured = measure(cells, args.repeats, problems)
        if problems:
            print(f"FAIL wall_gate --update: {len(problems)} capture "
                  f"problem(s)")
            for prob in problems:
                print(f"  {prob}")
            return 1
        payload = {"env": env, "band": band_cfg, "k": args.repeats,
                   "rounds": ROUNDS, "cells": measured}
        with open(args.baseline, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.baseline} ({len(measured)} cells, "
              f"k={args.repeats}, jax {env['jax']}, {env['platform']})")
        return 0

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline}; run with --update first")
        return 2
    with open(args.baseline) as f:
        base = json.load(f)
    benv = base.get("env", {})
    if benv != env:
        msg = (f"environment mismatch: baseline {benv} vs current "
               f"{env} — wall medians are only comparable within one "
               f"(jax, platform, host) tuple; regenerate with --update")
        if args.strict_env:
            print(f"FAIL wall_gate: {msg}")
            return 1
        print(f"SKIP wall_gate: {msg}")
        return 0

    problems = []
    measured = measure(cells, args.repeats, problems)
    baseline_cells = {c: v for c, v in base["cells"].items()
                      if c in cells}
    problems += diff(baseline_cells, measured, band_cfg)
    if problems:
        print(f"FAIL wall_gate: {len(problems)} problem(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    nstages = sum(len(v["stages"]) for v in measured.values())
    print(f"ok   wall_gate: {len(cells)} cells, {nstages} stage "
          f"medians inside the noise bands (k={args.repeats}, "
          f"rel {args.rel_band:.0%} / MAD x{args.mad_mult:.0f} / "
          f"floor {args.floor_us / 1e3:.0f} ms)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
