#!/bin/bash
# One-command TPU capture (VERDICT round-2 item #1): run the moment the
# relay lives.  Ordered most-valuable-first so a short relay window still
# banks the headline story; every step tees into logs/tpu_capture/ and a
# step failure does not stop the next step (the relay may flap).
#
#   bash tools/tpu_capture.sh [--quick] [--rehearse]
#
# --quick:    bench only (for a window expected to be very short).
# --rehearse: full CPU-mode dress rehearsal (VERDICT r4 #1) — relay
#             probes stubbed out, env pinned to CPU, cells at the CPU
#             scale, cell-5 skipped (it has its own dedicated overnight
#             job).  Proves the mechanics + prints the same [budget]
#             lines the real window will, so the per-step ordering is
#             provably sane before a window opens.  Also runs one
#             injected preempt->resume lifecycle drill (step 0) through
#             tools/supervisor.py, exactly-once journal audited.
#
# Every step runs under tools/supervisor.py (--raw): a crash mid-step
# retries inside the SAME relay window instead of losing it; the
# supervisor's v3 lifecycle events land next to the step logs.
#
# Every step prints "[budget] <step>: <s>s (cum <s>s)" — in a real
# window this is the record of where the window went; the rehearsal's
# lines are the measured CPU floor of each step's startup+compute path.
#
# Serializes CAPTURES via a self-healing lock (exits 2 if a live holder
# exists; a SIGKILLed holder's stale lock is reclaimed via its pid).
# The lock does NOT cover a bare `python bench.py` — during a relay
# window, use this script (or take the lock) instead of raw bench runs:
# one TPU process at a time on this box.
set -u
cd "$(dirname "$0")/.."
. tools/relay_probe.sh
OUT=logs/tpu_capture
mkdir -p "$OUT"
STAMP=$(date +%H%M%S)
LOCK=/tmp/tpu_capture.lock

QUICK=0 REHEARSE=0
for a in "$@"; do
  case "$a" in
    --quick) QUICK=1 ;;
    --rehearse) REHEARSE=1 ;;
    *) echo "unknown arg: $a (expected --quick / --rehearse)" >&2
       # Fail fast: a misspelled --rehearse must not silently launch
       # the real multi-hour capture on a live relay window.
       exit 2 ;;
  esac
done

# The rehearse/real deltas are captured ONCE here so the two paths
# cannot drift: the env prefix for step 2 and the cell list for step 3.
if [ "$REHEARSE" = 1 ]; then
  export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
  unset FL_TEST_TPU
  STAMP="rehearse_$STAMP"
  STEP2_ENV=()            # CPU backend: same suites, no TPU gate
  # CPU defaults: scale 0.1, cells 1,2,4 (cell 3's ResNet shadow-train
  # compile is impractical on one CPU core); cell-5 has its own
  # dedicated overnight job.
  STEP3_CELLS=()
  MB_ARGS=(--rehearse)    # pallas micro-bench: tiny shapes, interpret
  MC_ARGS=(--rehearse)    # multichip hier: CPU + 8 virtual devices
  SP_ARGS=(--rehearse)    # stage profile: CPU backend, same steps
  probe() { return 0; }
else
  STEP2_ENV=(env FL_TEST_TPU=1)
  STEP3_CELLS=(--cells 1,2,3,4)
  MB_ARGS=()              # pallas micro-bench: Mosaic compile, 2048c
  MC_ARGS=()              # multichip hier: live devices (a 1-chip
                          # window banks a 'skipped' record + reason)
  SP_ARGS=()              # stage profile: live devices + device trace
  probe() { relay_probe; }
fi

T_START=$SECONDS
T_STEP=$SECONDS
budget() {
  echo "[budget] $1: $((SECONDS - T_STEP))s (cum $((SECONDS - T_START))s)"
  T_STEP=$SECONDS
}

acquire() {
  if mkdir "$LOCK" 2>/dev/null; then
    echo $$ >"$LOCK/pid"
    return 0
  fi
  local holder
  holder=$(cat "$LOCK/pid" 2>/dev/null)
  if [ -n "${holder:-}" ] && kill -0 "$holder" 2>/dev/null; then
    return 1                       # live holder
  fi
  # Stale (holder gone or pid unreadable): reclaim.
  rm -rf "$LOCK" 2>/dev/null
  mkdir "$LOCK" 2>/dev/null && echo $$ >"$LOCK/pid"
}

if ! acquire; then
  echo "TPU lock held by live pid $(cat "$LOCK/pid" 2>/dev/null); " \
       "refusing to double-run" >&2
  exit 2
fi
trap 'rm -rf "$LOCK" 2>/dev/null' EXIT

if ! probe; then echo "relay dead; aborting" >&2; exit 1; fi

# Every capture step runs under the supervisor (tools/supervisor.py,
# --raw: retry/backoff only): a crash mid-step retries INSIDE the same
# relay window instead of wasting it.  Supervisor chatter goes to
# stderr (stdout artifacts like bench JSON stay clean); its lifecycle
# events land in $OUT/supervisor_$STAMP.jsonl (schema v3).
SUP=(python tools/supervisor.py --raw --max-retries 1 --backoff-base 5
     --events "$OUT/supervisor_$STAMP.jsonl" --)

if [ "$REHEARSE" = 1 ]; then
  echo "== step 0: lifecycle drill (injected preempt -> resume) =="
  # One supervised preempt/resume cycle through the real machinery:
  # FL_PREEMPT_AT_ROUND fires at a span boundary, the child exits 75
  # with a checkpoint, the supervisor resumes it, and the journal must
  # audit exactly-once.  A failing drill aborts the rehearsal — the
  # mechanics it proves are exactly what a real window relies on.
  DRILL="$OUT/drill_$STAMP"
  python tools/supervisor.py --inject-preempt-round 2 --verify-journal \
    --checkpoint-every 2 --events "$OUT/supervisor_$STAMP.jsonl" -- \
    --backend cpu -s SYNTH_MNIST -e 5 -c 16 --synth-train 256 \
    --synth-test 64 --run-dir "$DRILL/runs" --log-dir "$DRILL/logs" \
    || { echo "lifecycle drill FAILED" >&2; exit 1; }
  budget "step0-drill"
fi

echo "== step 1: bench.py (headline + 10k north star + per-impl) =="
# Outer bound must exceed bench's internal 5700 s final deadline so the
# clean banked-results exit (not this SIGTERM) is what ends a slow run.
"${SUP[@]}" timeout 6000 python bench.py >"$OUT/bench_$STAMP.json" \
  2>"$OUT/bench_$STAMP.log"
echo "bench rc=$? json:"; cat "$OUT/bench_$STAMP.json"
tail -30 "$OUT/bench_$STAMP.log"
budget "step1-bench"

[ "$QUICK" = 1 ] && exit 0

probe || { echo "relay died after bench" >&2; exit 1; }
echo "== step 2: TPU-backend test re-run (fused backdoor, Mosaic pallas,"
echo "   engine, defense kernels incl. the hybrid Bulyan callback) =="
"${SUP[@]}" ${STEP2_ENV[@]+"${STEP2_ENV[@]}"} timeout 3600 python -m pytest \
  tests/test_pallas.py tests/test_engine.py tests/test_parallel.py \
  tests/test_defenses.py \
  -q --no-header 2>&1 | tee "$OUT/pytest_tpu_$STAMP.log" | tail -15
budget "step2-pytest"

probe || { echo "relay died after pytest" >&2; exit 1; }
echo "== step 2.5: pallas defense-kernel micro-bench (Mosaic compile) =="
# First hard evidence the ops/pallas_defense.py kernels lower through
# Mosaic + their on-chip walls vs the XLA references (ISSUE 11); a
# lowering failure banks the error JSON instead of killing the window.
"${SUP[@]}" timeout 1800 python tools/pallas_microbench.py \
  ${MB_ARGS[@]+"${MB_ARGS[@]}"} >"$OUT/pallas_$STAMP.jsonl" \
  2>>"$OUT/pallas_$STAMP.log" || true
cat "$OUT/pallas_$STAMP.jsonl"
budget "step2.5-pallas-microbench"

probe || { echo "relay died after pallas micro-bench" >&2; exit 1; }
echo "== step 2.6: multi-chip hier round (SPMD tier-1, ISSUE 12) =="
# First real multi-chip execution of the SPMD client_map: sharded vs
# scan parity + walls + collective bytes, one JSON line banked either
# way (a single-chip window records skipped+reason instead of dying).
"${SUP[@]}" timeout 900 python tools/multichip_hier.py \
  ${MC_ARGS[@]+"${MC_ARGS[@]}"} >"$OUT/multichip_$STAMP.jsonl" \
  2>>"$OUT/multichip_$STAMP.log" || true
cat "$OUT/multichip_$STAMP.jsonl"
budget "step2.6-multichip-hier"

probe || { echo "relay died after multichip hier" >&2; exit 1; }
echo "== step 2.7: stage-ledger profile (stage scopes live, ISSUE 15) =="
# One profiled flat + one hierarchical round with the stage taxonomy's
# named_scope annotations live: static per-stage attribution + wire
# ledger per cell, plus a jax.profiler device trace whose op breakdown
# carries the same stage tokens (the on-TPU face of --stageproof).
# Since ISSUE 16 each cell also books its own capture through
# utils/walls.py and banks a walls_verdict (partition exact, booked
# time inside the host wall) next to the static row.
"${SUP[@]}" timeout 900 python tools/stage_profile.py \
  ${SP_ARGS[@]+"${SP_ARGS[@]}"} --trace-dir "$OUT/stage_trace_$STAMP" \
  >"$OUT/stage_$STAMP.jsonl" 2>>"$OUT/stage_$STAMP.log" || true
cat "$OUT/stage_$STAMP.jsonl"
budget "step2.7-stage-profile"

probe || { echo "relay died after stage profile" >&2; exit 1; }
echo "== step 3: BASELINE cells =="
"${SUP[@]}" timeout 7200 python -m attacking_federate_learning_tpu.benchmarks \
  --rounds 10 ${STEP3_CELLS[@]+"${STEP3_CELLS[@]}"} 2>&1 \
  | tee "$OUT/cells_$STAMP.log" | grep -E '^\{' || true
budget "step3-cells"

if [ "$REHEARSE" = 1 ]; then
  echo "rehearsal complete (cell-5 skipped: dedicated overnight job);" \
       "budget lines above are the CPU floor"
  exit 0
fi

probe || { echo "relay died after cells 1-4" >&2; exit 1; }
echo "== step 4: 10k non-IID grid (cell 5, overnight north star) =="
"${SUP[@]}" timeout 14400 python -m attacking_federate_learning_tpu.benchmarks \
  --rounds 10 --cells 5 2>&1 \
  | tee "$OUT/cell5_$STAMP.log" | grep -E '^\{' || true
budget "step4-cell5"

echo "capture complete; logs in $OUT/"
