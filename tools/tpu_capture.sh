#!/bin/bash
# One-command TPU capture (VERDICT round-2 item #1): run the moment the
# relay lives.  Ordered most-valuable-first so a short relay window still
# banks the headline story; every step tees into logs/tpu_capture/ and a
# step failure does not stop the next step (the relay may flap).
#
#   bash tools/tpu_capture.sh [--quick]
#
# --quick: bench only (for a window expected to be very short).
#
# Serializes CAPTURES via a self-healing lock (exits 2 if a live holder
# exists; a SIGKILLed holder's stale lock is reclaimed via its pid).
# The lock does NOT cover a bare `python bench.py` — during a relay
# window, use this script (or take the lock) instead of raw bench runs:
# one TPU process at a time on this box.
set -u
cd "$(dirname "$0")/.."
. tools/relay_probe.sh
OUT=logs/tpu_capture
mkdir -p "$OUT"
STAMP=$(date +%H%M%S)
LOCK=/tmp/tpu_capture.lock

acquire() {
  if mkdir "$LOCK" 2>/dev/null; then
    echo $$ >"$LOCK/pid"
    return 0
  fi
  local holder
  holder=$(cat "$LOCK/pid" 2>/dev/null)
  if [ -n "${holder:-}" ] && kill -0 "$holder" 2>/dev/null; then
    return 1                       # live holder
  fi
  # Stale (holder gone or pid unreadable): reclaim.
  rm -rf "$LOCK" 2>/dev/null
  mkdir "$LOCK" 2>/dev/null && echo $$ >"$LOCK/pid"
}

if ! acquire; then
  echo "TPU lock held by live pid $(cat "$LOCK/pid" 2>/dev/null); " \
       "refusing to double-run" >&2
  exit 2
fi
trap 'rm -rf "$LOCK" 2>/dev/null' EXIT

if ! relay_probe; then echo "relay dead; aborting" >&2; exit 1; fi

echo "== step 1: bench.py (headline + 10k north star + per-impl) =="
# Outer bound must exceed bench's internal 5700 s final deadline so the
# clean banked-results exit (not this SIGTERM) is what ends a slow run.
timeout 6000 python bench.py >"$OUT/bench_$STAMP.json" \
  2>"$OUT/bench_$STAMP.log"
echo "bench rc=$? json:"; cat "$OUT/bench_$STAMP.json"
tail -30 "$OUT/bench_$STAMP.log"

[ "${1:-}" = "--quick" ] && exit 0

relay_probe || { echo "relay died after bench" >&2; exit 1; }
echo "== step 2: TPU-backend test re-run (fused backdoor, Mosaic pallas,"
echo "   engine, defense kernels incl. the hybrid Bulyan callback) =="
FL_TEST_TPU=1 timeout 3600 python -m pytest \
  tests/test_pallas.py tests/test_engine.py tests/test_parallel.py \
  tests/test_defenses.py \
  -q --no-header 2>&1 | tee "$OUT/pytest_tpu_$STAMP.log" | tail -15

relay_probe || { echo "relay died after pytest" >&2; exit 1; }
echo "== step 3: BASELINE cells 1-4 full scale =="
timeout 7200 python -m attacking_federate_learning_tpu.benchmarks \
  --rounds 10 --cells 1,2,3,4 2>&1 \
  | tee "$OUT/cells_$STAMP.log" | grep -E '^\{' || true

relay_probe || { echo "relay died after cells 1-4" >&2; exit 1; }
echo "== step 4: 10k non-IID grid (cell 5, overnight north star) =="
timeout 14400 python -m attacking_federate_learning_tpu.benchmarks \
  --rounds 10 --cells 5 2>&1 \
  | tee "$OUT/cell5_$STAMP.log" | grep -E '^\{' || true

echo "capture complete; logs in $OUT/"
