#!/usr/bin/env python
"""Multi-chip hierarchical tier-1: parity + static traffic facts.

The SPMD client_map (ISSUE 12, ops/federated.py:_client_map_spmd) maps
the megabatch axis onto the mesh ``clients`` axis — each device scans
its own megabatches, tier-2 reads one explicit all_gather.  This tool
is the capture/bench leg for that mapping:

- ``--aot``: compile-only facts at the given scale — temp bytes and
  collective bytes for the SHARDED round vs the sequential SCAN round,
  the ``sharded vs scan tier-1`` record bench.py's ``multichip-hier``
  phase stamps into BENCH/MULTICHIP JSON.  Deterministic static-HLO
  facts (utils/costs.py), no execution, no TPU needed.
- default (execute): run a short sharded span AND its unsharded twin,
  assert parity inside the ulp band, and report walls — the "first
  real multi-chip round" record for a live relay window
  (tools/tpu_capture.sh step 2.6).

``--rehearse`` pins CPU + 8 virtual devices before backend init (the
same lazily-read XLA_FLAGS seam as __graft_entry__.py) so the whole
step runs on this box with no relay.  Without it the live device set
is used; fewer than 2 devices emits a ``skipped`` record and exits 0
(a single-chip window cannot multichip — the record still lands so
the capture log says WHY the step banked nothing).

Always prints exactly one JSON line on stdout; diagnostics on stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _force_rehearse_env(n_devices: int = 8) -> None:
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0),
            f"--xla_force_host_platform_device_count={n_devices}")
    from attacking_federate_learning_tpu.cli import apply_backend

    apply_backend("cpu")


def _clients_axis(num_shards: int, n_devices: int) -> int:
    """Largest divisor of the shard count that fits the device set —
    the mesh shape the S % clients == 0 contract admits."""
    for p in range(min(num_shards, n_devices), 0, -1):
        if num_shards % p == 0:
            return p
    return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="SPMD hierarchical tier-1 parity + traffic facts")
    ap.add_argument("--rehearse", action="store_true",
                    help="CPU + 8 virtual devices (no relay needed)")
    ap.add_argument("--aot", action="store_true",
                    help="compile-only: temp/collective byte facts for "
                         "sharded vs scan, no execution")
    ap.add_argument("--clients", type=int, default=64)
    ap.add_argument("--megabatch", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=2)
    args = ap.parse_args(argv)

    if args.rehearse:
        _force_rehearse_env()
    import jax

    rec = {"tool": "multichip_hier", "rehearse": bool(args.rehearse),
           "aot": bool(args.aot), "clients": args.clients,
           "megabatch": args.megabatch}
    n_dev = len(jax.devices())
    rec["n_devices"] = n_dev
    rec["platform"] = jax.devices()[0].platform
    S = args.clients // args.megabatch
    parts = _clients_axis(S, n_dev)
    rec["num_shards"], rec["clients_axis"] = S, parts
    if parts < 2:
        rec["skipped"] = True
        rec["reason"] = (f"no multi-device clients axis: {n_dev} "
                         f"device(s), S={S} — a single chip cannot "
                         f"multichip; waiting for a wider window")
        print(json.dumps(rec))
        return 0
    rec["skipped"] = False

    import jax.numpy as jnp
    import numpy as np

    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.parallel.mesh import make_plan
    from attacking_federate_learning_tpu.utils.costs import (
        compiled_cost_facts
    )

    n, m = args.clients, args.megabatch
    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST, users_count=n, mal_prop=0.24,
        batch_size=1, epochs=max(args.rounds, 2), test_step=2, seed=0,
        synth_train=n, synth_test=64, defense="Krum",
        aggregation="hierarchical", megabatch=m, tier2_defense="Krum")
    ds = load_dataset(cfg.dataset, seed=0, synth_train=n, synth_test=64)

    def build(shardings):
        return FederatedExperiment(cfg, attacker=DriftAttack(1.5),
                                   dataset=ds, shardings=shardings)

    plan = make_plan((parts, 1), devices=jax.devices()[:parts])
    exp_spmd = build(plan)
    assert exp_spmd._hier_spmd, "mesh did not engage the SPMD path"
    d = exp_spmd.flat.dim
    rec["d"] = d

    for tag, exp in (("sharded", exp_spmd), ("scan", build(None))):
        t0 = time.perf_counter()
        facts = compiled_cost_facts(
            exp._fused_round.lower(exp.state, jnp.asarray(0, jnp.int32),
                                   None).compile())
        rec[tag] = {"compile_s": round(time.perf_counter() - t0, 2),
                    "temp_bytes": int(facts["temp_bytes"]),
                    "collective_bytes": int(facts["collective_bytes"]),
                    "flops": facts["flops"]}
        if not args.aot:
            t0 = time.perf_counter()
            for t in range(args.rounds):
                exp.run_round(t)
            jax.block_until_ready(exp.state.weights)
            rec[tag]["rounds"] = args.rounds
            rec[tag]["wall_s"] = round(time.perf_counter() - t0, 3)
            rec[tag]["weights"] = exp.state.weights
    rec["collective_bytes_bound_S_d_4"] = S * d * 4
    if not args.aot:
        w_s = np.asarray(rec["sharded"].pop("weights"))
        w_r = np.asarray(rec["scan"].pop("weights"))
        rec["max_abs_diff"] = float(np.max(np.abs(w_s - w_r)))
        rec["parity_ok"] = bool(
            rec["max_abs_diff"] <= 2e-5 + 2e-5 * float(
                np.max(np.abs(w_r))))
    print(json.dumps(rec))
    return 0 if rec.get("parity_ok", True) else 1


if __name__ == "__main__":
    raise SystemExit(main())
