#!/bin/bash
# Poll the TPU relay every 45 s; on the first live probe, run the full
# capture sequence (tools/tpu_capture.sh) exactly once per window.
# Locking lives in tpu_capture.sh itself (rc=2 when another holder has
# the TPU), so a manual capture and this watcher can never double-run.
# State lands in logs/tpu_capture/watch.log.
set -u
cd "$(dirname "$0")/.."
. tools/relay_probe.sh
OUT=logs/tpu_capture
mkdir -p "$OUT"
WLOG="$OUT/watch.log"

echo "$(date +%T) watcher start" >>"$WLOG"
while true; do
  if relay_probe; then
    # Defer to the driver's end-of-round bench if it is already running
    # — one TPU process at a time.  (CPU-pinned benchmark/test runs are
    # fine to overlap; TPU-bound pytest/benchmarks runs are launched by
    # tpu_capture.sh itself under the lock.)
    # Any interpreter spelling counts (python3, absolute path, flags
    # between interpreter and script, and the '-m bench' module form);
    # a live capture-lock holder also counts as busy even though
    # tpu_capture.sh would itself exit 2 — cheaper to wait here.
    if pgrep -f 'python[0-9.]*[^ ]* .*(bench\.py|-m bench( |$))' >/dev/null \
        || { holder=$(cat /tmp/tpu_capture.lock/pid 2>/dev/null) \
             && [ -n "$holder" ] && kill -0 "$holder" 2>/dev/null; }; then
      echo "$(date +%T) relay live but TPU busy; waiting" >>"$WLOG"
      sleep 120
      continue
    fi
    echo "$(date +%T) relay LIVE -> capture" >>"$WLOG"
    bash tools/tpu_capture.sh >>"$OUT/capture_run.log" 2>&1
    rc=$?
    echo "$(date +%T) capture done rc=$rc" >>"$WLOG"
    if [ "$rc" = 2 ]; then
      sleep 120   # someone else holds the TPU; let them finish
      continue
    fi
    # One capture per window: wait for the relay to go away before
    # re-arming, so we don't immediately re-run on the same window.
    while relay_probe; do sleep 60; done
    echo "$(date +%T) relay gone; re-armed" >>"$WLOG"
  fi
  sleep 45
done
