#!/usr/bin/env python
"""Standalone validator for structured run JSONLs.

Checks every line of the given files against the event schema
(attacking_federate_learning_tpu/utils/metrics.py: EVENT_KINDS /
validate_event) so a malformed emitter is caught by CI, not by a reader
weeks later.  No device work (validation is pure Python over parsed
JSON), so it runs in tier-1 time budget on any backend state.

Speaks every supported schema version (v1, plus v2's compile/cost/
heartbeat kinds, plus v3's lifecycle kind — the preempt/resume/retry/
degrade transitions of utils/lifecycle.py — plus v4's cross-run
observatory kinds: 'registry' run-finish stamps, utils/registry.py,
and 'gate' behavioral-drift verdicts, tools/science_gate.py — plus
v5's 'secagg' kind: one secure-aggregation protocol record per round,
protocols/secagg.py — plus v6's hierarchical-forensics kinds:
'shard_selection' per-round tier-1/tier-2 selection records from
hierarchical rounds under --telemetry, core/engine.py, and
'forensics' colluder-localization verdicts, report.py — plus v7's
'async' kind: one asynchronous-round record per round under
aggregation='async', core/async_rounds.py — plus v8's 'campaign'
kind: one campaign-scheduler transition per record — campaign
start/done, cell start/done/failed/skipped verdicts and deadline
checkpoints — written to runs/campaigns/<id>/events.jsonl,
campaigns/scheduler.py — plus v9's observability kinds:
'stage_cost' per-entry stage-taxonomy cost attributions and
'wire_bytes' per-seam wire ledgers, both emitted by --cost-report
runs via utils/costs.py:CompileLedger.emit; with telemetry/reporting
off neither kind may appear, the invariant
tests/test_costs.py pins — plus v10's 'wall' kind: measured wall
telemetry from --profile-every runs — source='host' per-span/per-eval
host-clock walls from core/engine.py's fetch boundary, and
source='trace' per-stage booked walls from a jax.profiler capture,
utils/walls.py, whose stages + unattributed_us partition the booked
total exactly — plus v11's 'traffic' kind: one population-traffic
record per round under --traffic-population runs, core/population.py
— arrived/f_eff cohort accounting and the defense-validity watchdog's
ladder action, replayable on host via replay_traffic — plus v12's
'margin' kind: one robustness-margin record per round under --margins
runs, core/engine.py + utils/margins.py — per-row defense decision
margins, the colluder-survival rollups and the attack-side envelope
utilization — plus v13's hierarchical shard-domain 'fault' fields:
the per-shard survivor-count vector (shard_alive), the correlated
shard-DOMAIN accounting (shards_dead / shards_alive) and the
host-planned tier-2 ladder decision (tier2_action), all replayable
from the fault key via core/faults.py:hier_fault_schedule — plus
v14's 'numerics' kind: one numeric-health record per round under
--numerics runs, core/engine.py + utils/numerics.py — per-stage
nonfinite counts, gradient-norm dynamic range, distance-Gram
cancellation depth and the tie-proximity counters banded at k ulp of
the PR 18 margin boundaries, with the nonfinite_total / tie_locked
rollups; the cross-implementation ulp envelopes these counters
explain live in NUMERICS_BASELINE.json, tools/numerics_gate.py).  An
event stamped with a
version this reader does not know is reported as "produced by a newer
writer" — a clear per-line error, never a KeyError — and a newer-only
kind stamped with an older version is flagged as an emitter bug
(utils/metrics.py:validate_event owns both rules via
KIND_MIN_VERSION; the v6-kind-stamped-v5 rule mirrors the v2
precedent).

Usage:
    python tools/check_events.py logs/*.jsonl
    python tools/check_events.py --strict run.jsonl   # free-form lines
                                                      # are errors too
    python tools/check_events.py --stats run.jsonl    # per-kind count +
                                                      # schema-version
                                                      # histogram

Lines that are valid JSON objects WITHOUT a 'kind' field are counted as
legacy/free-form rows and skipped by default (pre-schema logs — e.g. the
grid drivers' summary rows); --strict flags them.  Exit status: 0 when
every file is clean, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from attacking_federate_learning_tpu.utils.metrics import (  # noqa: E402
    SCHEMA_VERSION, SUPPORTED_VERSIONS, validate_event
)


def check_file(path, strict=False):
    """Returns (per-kind counts, legacy-row count, [(lineno, error)])."""
    counts: dict = {}
    legacy = 0
    errors = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append((lineno, f"not JSON: {e}"))
                continue
            if not isinstance(rec, dict) or "kind" not in rec:
                legacy += 1
                if strict:
                    errors.append((lineno, "no 'kind' field (free-form "
                                           "row; --strict forbids)"))
                continue
            try:
                validate_event(rec)
            except ValueError as e:
                errors.append((lineno, str(e)))
                continue
            counts[rec["kind"]] = counts.get(rec["kind"], 0) + 1
    return counts, legacy, errors


def file_stats(path):
    """Per-kind stats over one file's typed rows — ``{kind: {"count":
    n, "versions": {v: n}}}`` — without validating (the histogram of a
    malformed file is still informative).  Free-form rows carry no
    kind/version stamp and are excluded; a typed row without a 'v'
    stamp counts under version 1 (the pre-stamp writer)."""
    stats: dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or "kind" not in rec:
                continue
            row = stats.setdefault(str(rec["kind"]),
                                   {"count": 0, "versions": {}})
            row["count"] += 1
            v = rec.get("v", 1)
            row["versions"][v] = row["versions"].get(v, 0) + 1
    return stats


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=f"Validate run JSONLs against the event schema "
                    f"(v{min(SUPPORTED_VERSIONS)}-v{max(SUPPORTED_VERSIONS)}"
                    f"; writer stamps v{SCHEMA_VERSION}).")
    p.add_argument("paths", nargs="+", metavar="JSONL")
    p.add_argument("--strict", action="store_true",
                   help="rows without a 'kind' field are errors, not "
                        "legacy free-form lines")
    p.add_argument("--stats", action="store_true",
                   help="also print the per-kind count and "
                        "schema-version histogram for each file")
    args = p.parse_args(argv)

    failed = False
    for path in args.paths:
        counts, legacy, errors = check_file(path, strict=args.strict)
        kinds = "  ".join(f"{k}:{v}" for k, v in sorted(counts.items()))
        tail = f"  (+{legacy} free-form)" if legacy else ""
        if errors:
            failed = True
            print(f"FAIL {path}: {len(errors)} bad line(s)  "
                  f"[{kinds}]{tail}")
            for lineno, msg in errors[:20]:
                print(f"  line {lineno}: {msg}")
            if len(errors) > 20:
                print(f"  ... and {len(errors) - 20} more")
        else:
            print(f"ok   {path}: {sum(counts.values())} events  "
                  f"[{kinds}]{tail}")
        if args.stats:
            stats = file_stats(path)
            print(f"  kind              count  versions")
            for kind in sorted(stats):
                row = stats[kind]
                vs = " ".join(f"v{v}:{n}" for v, n in
                              sorted(row["versions"].items()))
                print(f"    {kind:<15} {row['count']:>6}  {vs}")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
