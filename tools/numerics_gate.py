#!/usr/bin/env python
"""Numeric-drift gate over the cross-implementation divergence ledger.

tools/impl_drift.py measures, for every shipped impl pair of every
defense (xla / pallas-interpret / native / host, masked / weighted
variants, the scan-vs-sharded hier traversal), the f32 ulp envelope
between the pair on identical seeded cohorts plus an f64-adjudicated
verdict (defenses/oracle.py in double as referee).  This gate persists
that matrix into a checked-in ``NUMERICS_BASELINE.json`` and fails
when the numerics MOVE:

- **band exceeded**: a cell-cohort's measured ``max_ulp`` grows past
  its baseline envelope — an impl pair drifted apart (the PR 4
  bulyan-blockwise class: a reduction-order change that widens a
  1-ulp band into a selection flip);
- **verdict flip**: the f64-adjudicated verdict changes (e.g.
  ``tie_band`` -> ``split``, or an accuracy asymmetry inverts) — the
  pair's relationship to the double-precision truth changed even if
  the raw envelope did not;
- **availability flip**: a cell measured at baseline is skipped now
  (or the reverse) — an impl route appeared or vanished, which is a
  ledger fact, not noise.

Shrinking envelopes print a note (consider ``--update`` to tighten)
but never gate — only regressions fail.

Ulp envelopes are only comparable within one (jax, jaxlib, numpy,
platform) tuple, so on a baseline/environment mismatch the gate SKIPS
loudly with exit 0 unless ``--strict-env``; regenerate with
``--update`` after a toolchain change (provenance rides the file).

Usage:
    python tools/numerics_gate.py             # gate against baseline
    python tools/numerics_gate.py --update    # (re)generate baseline

Exit status: 0 clean (or env-skip), 1 on drift, 2 when the baseline is
missing.  tools/smoke.sh runs the self-consistency leg (fresh --update
followed by a gate against it in a temp dir); tools/perf_gate.py
--numproof separately pins that the in-jit numerics counters stay off
the numerics-off HLO.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "NUMERICS_BASELINE.json")


def environment() -> dict:
    import importlib.metadata as md

    import jax

    def _v(pkg):
        try:
            return md.version(pkg)
        except Exception:
            return "unknown"

    return {"jax": _v("jax"), "jaxlib": _v("jaxlib"),
            "numpy": _v("numpy"),
            "platform": jax.devices()[0].platform}


def diff(baseline_cells: dict, measured: dict) -> list:
    """Drift strings (empty = clean): band-exceeded, verdict-flip, or
    availability-flip per cell-cohort; a vanished cell gates too."""
    problems = []
    for cell, base in sorted(baseline_cells.items()):
        got = measured.get(cell)
        if got is None:
            problems.append(f"{cell}: cell not measured (variant "
                            f"removed? regenerate with --update)")
            continue
        for cname, want in sorted(base["cohorts"].items()):
            have = got["cohorts"].get(cname)
            if have is None:
                problems.append(f"{cell}[{cname}]: cohort missing from "
                                f"the fresh measurement")
                continue
            b_skip, h_skip = "skipped" in want, "skipped" in have
            if b_skip != h_skip:
                what = ("now skipped: " + have["skipped"][:60]
                        if h_skip else "now measurable")
                problems.append(
                    f"{cell}[{cname}]: impl availability flipped "
                    f"({what}) — regenerate with --update if intended")
                continue
            if b_skip:
                continue
            if have["max_ulp"] > want["max_ulp"]:
                problems.append(
                    f"{cell}[{cname}]: band exceeded — max_ulp "
                    f"{have['max_ulp']} > baseline envelope "
                    f"{want['max_ulp']} (mismatch "
                    f"{want['n_mismatch']}->{have['n_mismatch']} "
                    f"coords)")
            elif have["max_ulp"] < want["max_ulp"]:
                print(f"note numerics_gate {cell}[{cname}]: envelope "
                      f"shrank ({want['max_ulp']} -> "
                      f"{have['max_ulp']} ulp) — consider --update "
                      f"to tighten")
            if have["verdict"] != want["verdict"]:
                problems.append(
                    f"{cell}[{cname}]: verdict flip — "
                    f"{want['verdict']} -> {have['verdict']} "
                    f"(f64-adjudicated relationship changed)")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Gate the cross-implementation ulp envelopes and "
                    "f64 verdicts against NUMERICS_BASELINE.json "
                    "(tools/impl_drift.py measurement).")
    p.add_argument("--baseline", default=BASELINE)
    p.add_argument("--update", action="store_true",
                   help="write a fresh baseline instead of gating")
    p.add_argument("--seed", type=int, default=None,
                   help="cohort seed (default: the baseline's; "
                        "impl_drift.SEED when updating)")
    p.add_argument("--strict-env", action="store_true",
                   help="treat a baseline/environment mismatch as a "
                        "failure instead of a skip")
    args = p.parse_args(argv)

    from tools import impl_drift
    from attacking_federate_learning_tpu.utils.numerics import (
        TIE_BAND_ULPS
    )

    env = environment()

    if args.update:
        seed = impl_drift.SEED if args.seed is None else args.seed
        cells = impl_drift.measure(seed=seed)
        payload = {
            "provenance": {**env, "seed": seed,
                           "cohort": {"n": impl_drift.N,
                                      "d": impl_drift.D,
                                      "f": impl_drift.F}},
            "tie_band_ulps": TIE_BAND_ULPS,
            "cells": cells,
        }
        with open(args.baseline, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        n_skip = sum(1 for c in cells.values()
                     for r in c["cohorts"].values() if "skipped" in r)
        print(f"wrote {args.baseline} ({len(cells)} cells, "
              f"{n_skip} skipped cell-cohorts, seed {seed}, "
              f"jax {env['jax']}, {env['platform']})")
        return 0

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline}; run with --update "
              f"first")
        return 2
    with open(args.baseline) as f:
        base = json.load(f)
    benv = {k: base.get("provenance", {}).get(k) for k in env}
    if benv != env:
        msg = (f"environment mismatch: baseline {benv} vs current "
               f"{env} — ulp envelopes are only comparable within one "
               f"(jax, numpy, platform) tuple; regenerate with "
               f"--update")
        if args.strict_env:
            print(f"FAIL numerics_gate: {msg}")
            return 1
        print(f"SKIP numerics_gate: {msg}")
        return 0

    seed = base.get("provenance", {}).get("seed", impl_drift.SEED) \
        if args.seed is None else args.seed
    measured = impl_drift.measure(seed=seed)
    problems = diff(base["cells"], measured)
    if problems:
        print(f"FAIL numerics_gate: {len(problems)} drift(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    n_pairs = sum(len(c["cohorts"]) for c in measured.values())
    print(f"ok   numerics_gate: {len(measured)} impl pairs, "
          f"{n_pairs} cell-cohorts inside their baseline envelopes "
          f"(tie band {base.get('tie_band_ulps', TIE_BAND_ULPS)} ulp, "
          f"seed {seed})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
