# Shared relay-liveness probe (sourced by tpu_capture.sh and
# relay_watch.sh).  Pure bash /dev/tcp — no Python interpreter (this
# image's sitecustomize imports jax at startup; booting one per probe
# would steal seconds of CPU per minute on an nproc=1 box).  Port list
# mirrors relay_ports_listening (utils/backend.py).
relay_probe() {
  local p
  for p in 8082 8083 8087; do
    if timeout 2 bash -c "echo -n >/dev/tcp/127.0.0.1/$p" 2>/dev/null; then
      return 0
    fi
  done
  return 1
}
