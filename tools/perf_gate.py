#!/usr/bin/env python
"""Deterministic perf-regression gate: static HLO facts, no stopwatch.

Wall-clock benchmarks on this box are unusable as a gate (one shared
core, rare TPU relay windows, BENCH_*.json noise), so this gate replays
a pinned set of small configs, extracts each compiled entry point's
STATIC cost facts (utils/costs.py: cost_analysis FLOPs / bytes
accessed, memory_analysis buffer sizes) and diffs them against the
checked-in ``PERF_BASELINE.json``:

- ``flops`` / ``bytes_accessed`` / ``argument_bytes`` / ``output_bytes``
  must match EXACTLY — they are pure functions of (HLO, XLA version,
  platform), so any drift is a real change to the compiled program
  (e.g. a defense kernel growing a second distance computation);
- ``temp_bytes`` / ``peak_bytes`` compare within ``--tolerance``
  (default 5%) — buffer assignment may legally wiggle with scheduling.

The baseline records the environment it was generated in (jax/jaxlib
version, platform).  On a mismatched environment the comparison is
meaningless (XLA's cost model changed under us), so the gate SKIPS with
a loud notice and exit 0 unless ``--strict-env`` — regenerate with
``--update`` after a toolchain bump.

Usage:
    python tools/perf_gate.py                  # gate against baseline
    python tools/perf_gate.py --update         # (re)generate baseline
    python tools/perf_gate.py --cells krum,bulyan --tolerance 0.1

Exit status: 0 clean (or env-skip), 1 on any named regression, 2 when
the baseline is missing (run --update first).  CI-wired via
tests/test_costs.py next to the fault_matrix/check_events hooks;
tools/smoke.sh runs all three.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "PERF_BASELINE.json")

# The pinned cells: small enough to compile in CI time on CPU, wide
# enough to cover the cost-relevant program families — the O(n^2 d)
# distance defenses, the coordinate-wise sorts, the fused-vs-telemetry
# round programs, the plain mean, and the hierarchical (two-tier)
# streaming rounds (entry points hier_round/hier_span/tier2_*;
# core/engine.py aggregation='hierarchical').  Hierarchical cells
# override the base topology so both placement groups and the tier
# validity bounds (Bulyan m >= 4*f1+3) are exercised.
CELLS = {
    "nodefense": dict(defense="NoDefense"),
    "krum": dict(defense="Krum"),
    "trimmed_mean": dict(defense="TrimmedMean"),
    "bulyan": dict(defense="Bulyan"),
    "median": dict(defense="Median"),
    "krum_telemetry": dict(defense="Krum", telemetry=True),
    "hier_krum": dict(defense="Krum", aggregation="hierarchical",
                      users_count=12, mal_prop=0.25, megabatch=4),
    "hier_bulyan": dict(defense="Bulyan", aggregation="hierarchical",
                        users_count=24, mal_prop=0.125, megabatch=8,
                        tier2_defense="TrimmedMean"),
    # ISSUE 8: the hierarchical TELEMETRY cost cell — the telemetry
    # engine's hier_round / hier_tele_span with the per-shard + tier-2
    # diagnostics stacked through the scan, so the telemetry COST
    # gates like everything else.  The telemetry-OFF hot path is
    # pinned by the hier_krum/hier_bulyan cells above staying
    # byte-exact (telemetry is a trace-time flag; any residue in the
    # off path moves their FLOPs/bytes and fails the gate).
    "hier_krum_tele": dict(defense="Krum", aggregation="hierarchical",
                           users_count=12, mal_prop=0.25, megabatch=4,
                           telemetry=True),
    # ISSUE 11: the Pallas defense-kernel suite (interpret-mode HLO on
    # CPU — the facts pin the emulation program's drift; the
    # fused-vs-XLA fusion WIN is pinned by --pallasproof below, which
    # compares accounting-compatible models, not emulation bytes).
    "krum_pallas": dict(defense="Krum", aggregation_impl="pallas"),
    "trimmed_mean_pallas": dict(defense="TrimmedMean",
                                aggregation_impl="pallas"),
    "median_pallas": dict(defense="Median", aggregation_impl="pallas"),
    "bulyan_pallas": dict(defense="Bulyan", aggregation_impl="pallas"),
    "hier_krum_pallas": dict(defense="Krum", aggregation="hierarchical",
                             users_count=12, mal_prop=0.25, megabatch=4,
                             aggregation_impl="pallas"),
}

EXACT = ("flops", "bytes_accessed", "argument_bytes", "output_bytes",
         "collective_bytes")
TOLERANT = ("temp_bytes", "peak_bytes")


def _ensure_virtual_devices(n: int = 8) -> None:
    """Raise the virtual CPU device count to n BEFORE backend init so
    the shardproof leg can build an 8-device mesh in a standalone run
    (same lazily-read XLA_FLAGS seam as __graft_entry__.py; a no-op
    when jax's backend already initialized — shardproof then checks
    the live device count and skips loudly if it is short)."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    elif int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}")


def environment() -> dict:
    import importlib.metadata as md

    import jax

    def _v(pkg):
        try:
            return md.version(pkg)
        except Exception:
            return "unknown"

    return {"jax": _v("jax"), "jaxlib": _v("jaxlib"),
            "platform": jax.devices()[0].platform}


def _pinned_experiment(overrides: dict):
    """The pinned small experiment every proof leg replays."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    base = dict(
        dataset=C.SYNTH_MNIST, users_count=11, mal_prop=0.2,
        batch_size=16, epochs=5, test_step=5, seed=0,
        synth_train=256, synth_test=64)
    base.update(overrides)   # hierarchical cells override the topology
    cfg = ExperimentConfig(**base)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    return FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds)


def measure_cell(name: str, overrides: dict) -> dict:
    """Build the pinned small experiment and return {entry: facts}."""
    exp = _pinned_experiment(overrides)
    ledger = exp.cost_report()
    if ledger.errors:
        msgs = "; ".join(f"{n}: {m}" for n, m in ledger.errors)
        raise RuntimeError(f"cell {name}: cost analysis failed ({msgs})")
    return ledger.summary()


# --- hierarchical memory proof (ISSUE 6 acceptance) --------------------
# Static, deterministic, baseline-free: at the 10k north star
# (n=10,240, d=79,510, m=512) the hierarchical round's peak-proxy bytes
# must be bounded by the MEGABATCH, not the cohort — the (n, d) gradient
# matrix (3.26 GB) and the (n, n) distance matrix (419 MB) must not
# exist in the program.  Two independent witnesses: the lowered HLO text
# contains no tensor of either shape, and memory_analysis' temp bytes
# stay under MEM_FACTOR * m * d * 4 (measured ~2.6x — scan double
# buffers + the per-megabatch distance/sort intermediates; 6x leaves
# scheduling slack while sitting 8x below the (n, d) wall).

MEMPROOF = dict(n=10_240, d=79_510, m=512, mem_factor=6.0)


def memproof() -> int:
    """Build the north-star hierarchical config, lower + compile ONE
    round, and gate its static memory facts.  Returns 0 clean, 1 on a
    violation.  No baseline: the bound is absolute (O(m*d)), so it
    cannot drift silently with --update."""
    import jax.numpy as jnp

    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.utils.costs import (
        compiled_cost_facts
    )

    n, m = MEMPROOF["n"], MEMPROOF["m"]
    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST, users_count=n, mal_prop=0.24,
        batch_size=1, epochs=5, test_step=5, seed=0, synth_train=n,
        synth_test=64, defense="Bulyan", aggregation="hierarchical",
        megabatch=m, tier2_defense="Bulyan", tier2_corrupted=4)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=n, synth_test=64)
    exp = FederatedExperiment(cfg, dataset=ds)
    d = exp.flat.dim
    assert d == MEMPROOF["d"], f"wire dim moved: {d}"
    lowered = exp._fused_round.lower(exp.state, jnp.asarray(0, jnp.int32),
                                     None)
    text = lowered.as_text()
    problems = []
    for shape in (f"f32[{n},{d}]", f"bf16[{n},{d}]", f"f32[{n},{n}]"):
        if shape in text:
            problems.append(f"memproof: {shape} tensor present in the "
                            f"hierarchical round HLO — the cohort-sized "
                            f"array is back")
    facts = compiled_cost_facts(lowered.compile())
    bound = MEMPROOF["mem_factor"] * m * d * 4
    for metric in ("temp_bytes",):
        got = facts[metric]
        if got > bound:
            problems.append(
                f"memproof: {metric}={got / 1e6:.0f} MB exceeds the "
                f"O(m*d) bound {bound / 1e6:.0f} MB "
                f"({MEMPROOF['mem_factor']}x megabatch)")
    if problems:
        print(f"FAIL perf_gate --memproof: {len(problems)} violation(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    print(f"ok   perf_gate memproof: hier_round @ n={n}, m={m}, d={d}: "
          f"temp={facts['temp_bytes'] / 1e6:.0f} MB <= "
          f"{bound / 1e6:.0f} MB (vs (n,d)={n * d * 4 / 1e6:.0f} MB); "
          f"no (n,d)/(n,n) tensor in the HLO; "
          f"flops={facts['flops']:.3e}")
    return wireproof()


# --- secagg structural proof (ISSUE 7 acceptance) ----------------------
# Baseline-free like the memproof: compile one --secagg vanilla round
# and gate its structural HLO facts (protocols/secagg.py
# wire_hlo_facts) — the masked u32 wire must exist (the optimization
# barrier kept the compiler from cancelling the protocol away), the
# server-side reconstruction of the per-client matrix may feed ONLY
# the cohort-sum reduce (no defense/sort/diagnostic reads per-client
# rows post-masking), and no (n, n) distance matrix may exist.

WIREPROOF = dict(n=19, batch=16)


def wireproof() -> int:
    import jax.numpy as jnp

    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.protocols.secagg import (
        wire_hlo_facts
    )

    n = WIREPROOF["n"]
    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST, users_count=n, mal_prop=0.21,
        batch_size=WIREPROOF["batch"], epochs=5, test_step=5, seed=0,
        synth_train=256, synth_test=64, defense="NoDefense",
        secagg="vanilla")
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds)
    text = exp._fused_round.lower(exp.state, jnp.asarray(0, jnp.int32),
                                  None).compile().as_text()
    facts = wire_hlo_facts(text, n, exp.flat.dim)
    problems = []
    if not facts["wire_present"]:
        problems.append("wireproof: no u32 (n, d) wire tensor in the "
                        "vanilla-secagg round HLO — the masking was "
                        "compiled away")
    if not facts["unmask_reduce_only"]:
        problems.append(
            f"wireproof: the reconstructed per-client matrix has "
            f"non-reduce consumers "
            f"({facts['unmask_instructions']} unmask instruction(s)) — "
            f"a server-side op reads per-client rows post-masking")
    if facts["distance_matrix"]:
        problems.append("wireproof: an (n, n) distance matrix exists "
                        "under secagg — a pairwise defense ran over "
                        "per-client rows")
    if problems:
        print(f"FAIL perf_gate --memproof (secagg wireproof): "
              f"{len(problems)} violation(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    print(f"ok   perf_gate wireproof: secagg-vanilla round @ n={n}: "
          f"u32 wire present, unmask feeds only the cohort-sum "
          f"reduce, no (n, n) distance matrix")
    return pallasproof()


# --- pallas fusion proof (ISSUE 11 acceptance) -------------------------
# Baseline-free like the memproof: at the 10k north star the fused
# distance->Krum-score kernel must beat the XLA Gram+epilogue path on
# HBO bytes in the SAME accounting convention — XLA's cost_analysis
# counts each logical operand/output once, so the kernel's comparison
# number is its exact operands-once model
# (ops/pallas_defense.py:krum_scores_cost; the interpret emulation's
# own cost_analysis counts the grid loop body once and is not
# comparable in either direction).  Two structural witnesses ride
# along: the compiled fused program contains NO f32[n,n] tensor while
# the compiled XLA path does — the (n, n) matrix, its second HBM pass
# and the hybrid's pure_callback marshal are all gone on the pallas
# route.

PALLASPROOF = dict(n=10_240, d=79_510, f_frac=0.24)


def pallasproof() -> int:
    import jax
    import jax.numpy as jnp

    from attacking_federate_learning_tpu.defenses.kernels import (
        _krum_scores
    )
    from attacking_federate_learning_tpu.ops.distances import (
        pairwise_distances
    )
    from attacking_federate_learning_tpu.ops.pallas_defense import (
        krum_scores_cost, pallas_krum_scores
    )
    from attacking_federate_learning_tpu.utils.costs import (
        compiled_cost_facts
    )

    n, d = PALLASPROOF["n"], PALLASPROOF["d"]
    f = int(PALLASPROOF["f_frac"] * n)
    sds = jax.ShapeDtypeStruct((n, d), jnp.float32)
    fused_c = jax.jit(
        lambda g: pallas_krum_scores(g, n, f)[0]).lower(sds).compile()
    xla_c = jax.jit(
        lambda g: _krum_scores(pairwise_distances(g), n, f,
                               method="sort")).lower(sds).compile()
    xla_facts = compiled_cost_facts(xla_c)
    model = krum_scores_cost(n, d, f)
    nn = f"f32[{n},{n}]"
    problems = []
    if nn in fused_c.as_text():
        problems.append(
            f"pallasproof: {nn} tensor present in the fused "
            f"distance->score program — the (n, n) matrix is back")
    if nn not in xla_c.as_text():
        problems.append(
            f"pallasproof: comparison baseline degenerate — the XLA "
            f"Gram+epilogue path no longer materializes {nn}")
    if not model["bytes_accessed"] < xla_facts["bytes_accessed"]:
        problems.append(
            f"pallasproof: fused-kernel operands-once bytes "
            f"{model['bytes_accessed']:.3e} not below the XLA "
            f"Gram+epilogue path's measured "
            f"{xla_facts['bytes_accessed']:.3e}")
    if problems:
        print(f"FAIL perf_gate --pallasproof: {len(problems)} "
              f"violation(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    ratio = model["bytes_accessed"] / xla_facts["bytes_accessed"]
    print(f"ok   perf_gate pallasproof: fused krum-score kernel @ "
          f"n={n}, d={d}: {model['bytes_accessed'] / 1e9:.1f} GB "
          f"(operands-once) vs XLA path "
          f"{xla_facts['bytes_accessed'] / 1e9:.1f} GB "
          f"({100 * ratio:.0f}%); no {nn} tensor on the pallas route "
          f"(tile traffic {model['hbm_tile_bytes'] / 1e9:.0f} GB at "
          f"CI blocks)")
    return shardproof()


# --- hierarchical SPMD proof (ISSUE 12 acceptance) ---------------------
# Baseline-free like the memproof.  Three structural facts about the
# SPMD tier-1 mapping (ops/federated.py:_client_map_spmd), all provable
# on the 8-virtual-CPU-device mesh with no hardware:
#
# (a) scan-path fidelity: for EVERY pinned hierarchical cell, the
#     engine built on a 1-device clients axis produces an entry ledger
#     whose exact facts (FLOPs/bytes/args/outputs, collective bytes=0)
#     EQUAL the no-mesh scan path's — the mesh knobs must not perturb
#     the sequential program (its HLO differs only in the sharding-
#     propagation header any MeshPlan has always stamped);
# (b) the 8-device hier round is truly sharded: the compiled per-
#     device program holds NO full (n, d) / (S, m, d) / (n, n) tensor
#     (the "involuntary full rematerialization" seam is gone), and its
#     collective traffic is pinned to the explicit estimate all_gather
#     — within [1.0, 1.25]x of S*d*4 bytes;
# (c) sharded == unsharded: a 2-round SPMD run reproduces the scan
#     path's weights inside the measured ulp band (bit-equal on this
#     box; the tolerance covers GSPMD reduction reordering on others).

SHARDPROOF = dict(n=64, m=4, mesh_clients=8, coll_slack=1.25,
                  atol=2e-5)


def _hier_experiment(shardings, **overrides):
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    base = dict(
        dataset=C.SYNTH_MNIST, users_count=11, mal_prop=0.2,
        batch_size=16, epochs=5, test_step=5, seed=0,
        synth_train=256, synth_test=64)
    base.update(overrides)
    cfg = ExperimentConfig(**base)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    return FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds,
                               shardings=shardings)


def shardproof() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from attacking_federate_learning_tpu.parallel.mesh import make_plan
    from attacking_federate_learning_tpu.utils.costs import (
        compiled_cost_facts
    )

    if len(jax.devices()) < 8:
        print(f"SKIP perf_gate shardproof: needs 8 (virtual) devices, "
              f"have {len(jax.devices())} — the backend initialized "
              f"before the device-count flag could apply; run "
              f"tools/perf_gate.py standalone (it raises the count "
              f"itself) or under the test harness")
        return 0

    problems = []

    # (a) scan-path fidelity on a 1-device clients axis, per hier cell.
    plan1 = make_plan((1, 1), devices=jax.devices()[:1])
    hier_cells = sorted(c for c in CELLS if c.startswith("hier_"))
    for cell in hier_cells:
        ref = _hier_experiment(None, **CELLS[cell]).cost_report()
        got = _hier_experiment(plan1, **CELLS[cell]).cost_report()
        if ref.errors or got.errors:
            problems.append(f"shardproof[{cell}]: cost analysis failed "
                            f"({ref.errors + got.errors})")
            continue
        want, have = ref.summary(), got.summary()
        if set(want) != set(have):
            problems.append(
                f"shardproof[{cell}]: 1-device-mesh entry points "
                f"{sorted(have)} != scan path's {sorted(want)}")
            continue
        for entry, facts in want.items():
            for metric in EXACT:
                if have[entry].get(metric) != facts.get(metric):
                    problems.append(
                        f"shardproof[{cell}].{entry}.{metric}: "
                        f"1-device mesh {have[entry].get(metric)} != "
                        f"scan path {facts.get(metric)} — the mesh "
                        f"knobs changed the sequential program")
            if have[entry].get("collective_bytes"):
                problems.append(
                    f"shardproof[{cell}].{entry}: collective ops on a "
                    f"1-device mesh (the scan path grew a collective)")

    # (b) structural facts of the 8-device SPMD round.
    n, m = SHARDPROOF["n"], SHARDPROOF["m"]
    plan8 = make_plan((SHARDPROOF["mesh_clients"], 1))
    exp8 = _hier_experiment(
        plan8, users_count=n, mal_prop=0.25, defense="Krum",
        aggregation="hierarchical", megabatch=m)
    d, S = exp8.flat.dim, n // m
    compiled = exp8._fused_round.lower(
        exp8.state, jnp.asarray(0, jnp.int32), None).compile()
    text = compiled.as_text()
    for shape in (f"f32[{n},{d}]", f"bf16[{n},{d}]",
                  f"f32[{S},{m},{d}]", f"f32[{n},{n}]"):
        if shape in text:
            problems.append(
                f"shardproof: {shape} tensor present in the 8-device "
                f"hier round — a full cohort-sized array was "
                f"rematerialized")
    coll = compiled_cost_facts(compiled)["collective_bytes"]
    lo, hi = S * d * 4, SHARDPROOF["coll_slack"] * S * d * 4
    if not lo <= coll <= hi:
        problems.append(
            f"shardproof: collective bytes {coll} outside the O(S*d) "
            f"pin [{lo}, {hi:.0f}] — the estimate all_gather is "
            f"missing or a resharding collective crept in")

    # (c) sharded == unsharded inside the ulp band.
    if not problems:
        exp_ref = _hier_experiment(
            None, users_count=n, mal_prop=0.25, defense="Krum",
            aggregation="hierarchical", megabatch=m)
        for t in range(2):
            exp8.run_round(t)
            exp_ref.run_round(t)
        w8 = np.asarray(exp8.state.weights)
        wr = np.asarray(exp_ref.state.weights)
        diff = float(np.max(np.abs(w8 - wr)))
        if diff > SHARDPROOF["atol"]:
            problems.append(
                f"shardproof: sharded round diverged from the scan "
                f"path: max|diff|={diff:.3e} > {SHARDPROOF['atol']}")
    else:
        diff = float("nan")

    if problems:
        print(f"FAIL perf_gate --shardproof: {len(problems)} "
              f"violation(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    print(f"ok   perf_gate shardproof: {len(hier_cells)} hier cells "
          f"1-device-mesh == scan path (exact facts, 0 collective "
          f"bytes); 8-device SPMD round @ n={n}, m={m}, d={d}: no "
          f"(n,d)/(S,m,d)/(n,n) tensor, collective bytes {coll} "
          f"~= S*d*4 ({S * d * 4}); sharded==unsharded to "
          f"max|diff|={diff:.1e}")
    return stageproof()


# --- stage-attribution proof (ISSUE 15 acceptance) ---------------------
# Baseline-free like the memproof.  The stage ledger (utils/costs.py:
# stage_attribution over the jax.named_scope taxonomy threaded through
# the engines) must hold three facts for EVERY pinned cell's compiled
# round program:
#
# (a) coverage: >= 95% of the modeled FLOP mass (and >= 85% of the
#     byte mass — the remainder is XLA-inserted layout copies that
#     carry no op metadata) books under a named taxonomy stage;
# (b) exact partition: per metric, the six stage shares plus
#     ``unattributed`` sum to the whole-program cost_analysis total
#     EXACTLY (the split is of actuals, not of the model);
# (c) the annotation is metadata-only: a scopes-off twin of the same
#     cell compiles to an hlo_fingerprint-identical program (the
#     canonicalized, metadata-stripped hash) — checked on one cell per
#     program family to bound gate time.
#
# The wire ledger rides along: every hierarchical cell's
# tier1_to_tier2 seam must equal S*d*4 — the same number PR 12's
# shardproof pins as the 8-device all_gather's measured
# collective_bytes, which the 8-device leg below re-derives FROM the
# ledger (ledger <= measured <= 1.25x ledger).

STAGEPROOF = dict(flops_floor=0.95, bytes_floor=0.85, coll_slack=1.25,
                  # The pallas cells compile the CPU interpret-mode
                  # EMULATION (the same stand-in --pallasproof declares
                  # non-comparable): its grid-loop marshaling copies
                  # and rewritten prefix-sum reduce-windows carry no op
                  # metadata at all, so their mass is unattributable by
                  # construction — on the TPU route the kernel is one
                  # custom-call traced inside the dispatch scope.  The
                  # relaxed floors still pin the emulation cells'
                  # attribution from drifting further.
                  emu_floors=(0.75, 0.50),
                  fingerprint_cells=("krum", "hier_krum",
                                     "trimmed_mean_pallas"))


def _round_compiled(exp):
    """Lower + compile the cell's round entry point (the program the
    gate pins as fused_round/hier_round/async_round)."""
    import jax.numpy as jnp

    t0 = jnp.asarray(0, jnp.int32)
    if exp._async is not None:
        return exp._fused_round.lower(
            exp.state, t0, exp._async_state, None).compile()
    if exp.faults is not None:
        return exp._fused_round.lower(
            exp.state, t0, exp._fault_state, None).compile()
    return exp._fused_round.lower(exp.state, t0).compile()


def stageproof(cells=None) -> int:
    """Gate the stage/wire ledger facts over the pinned cells.
    Returns 0 clean, 1 on a violation.  No baseline: coverage floors,
    exact partition and the S*d*4 seam identity are absolute."""
    import math

    from attacking_federate_learning_tpu.utils.costs import (
        compiled_cost_facts, hlo_fingerprint, set_stage_scopes,
        stage_attribution
    )

    names = [c for c in CELLS if cells is None or c in cells]
    problems = []
    covs = []
    for name in names:
        exp = _pinned_experiment(CELLS[name])
        compiled = _round_compiled(exp)
        facts = compiled_cost_facts(compiled)
        att = stage_attribution(compiled.as_text(), facts)
        cov_f = att["coverage"]["flops"]
        cov_b = att["coverage"]["bytes_accessed"]
        emu = CELLS[name].get("aggregation_impl") == "pallas"
        f_floor, b_floor = (STAGEPROOF["emu_floors"] if emu else
                            (STAGEPROOF["flops_floor"],
                             STAGEPROOF["bytes_floor"]))
        if not emu:
            covs.append(cov_f)
        if cov_f < f_floor:
            problems.append(
                f"stageproof[{name}]: named-stage FLOP coverage "
                f"{cov_f:.1%} below the {f_floor:.0%} floor"
                + (" (interpret-emulation floor)" if emu else ""))
        if cov_b < b_floor:
            problems.append(
                f"stageproof[{name}]: named-stage byte coverage "
                f"{cov_b:.1%} below the {b_floor:.0%} floor"
                + (" (interpret-emulation floor)" if emu else ""))
        for metric, total in (("flops", facts.get("flops")),
                              ("bytes_accessed",
                               facts.get("bytes_accessed")),
                              ("temp_bytes", facts.get("temp_bytes"))):
            if total is None or total < 0:
                continue
            parts = [v[metric] for v in att["stages"].values()]
            parts.append(att["unattributed"][metric])
            got = math.fsum(parts)
            if not math.isclose(got, total, rel_tol=1e-9, abs_tol=1e-6):
                problems.append(
                    f"stageproof[{name}].{metric}: stage shares sum to "
                    f"{got} != whole-program total {total} — the "
                    f"partition is no longer exact")
        if not att["stages"]["tier1_aggregate"]["flops"] > 0:
            problems.append(
                f"stageproof[{name}]: tier1_aggregate attributed 0 "
                f"FLOPs — the defense-dispatch scope came unwired")
        hier = CELLS[name].get("aggregation") == "hierarchical"
        if hier:
            if not att["stages"]["tier2_aggregate"]["flops"] > 0:
                problems.append(
                    f"stageproof[{name}]: tier2_aggregate attributed "
                    f"0 FLOPs in a hierarchical cell — the "
                    f"shard_reduce scope came unwired")
            wire = exp.wire_ledger()
            S = exp._placement.num_shards
            want = S * exp.flat.dim * 4
            got = wire["seams"]["tier1_to_tier2"]["bytes"]
            if got != want:
                problems.append(
                    f"stageproof[{name}]: wire ledger tier1_to_tier2 "
                    f"{got} != S*d*4 = {want} — the ledger lost the "
                    f"PR-12 collective identity")
        if name in STAGEPROOF["fingerprint_cells"]:
            prev = set_stage_scopes(False)
            try:
                twin = _round_compiled(_pinned_experiment(CELLS[name]))
            finally:
                set_stage_scopes(prev)
            if (hlo_fingerprint(compiled.as_text())
                    != hlo_fingerprint(twin.as_text())):
                problems.append(
                    f"stageproof[{name}]: scopes-on round fingerprint "
                    f"!= scopes-off twin — the stage annotation is no "
                    f"longer metadata-only")

    # The measured SPMD cross-check: the 8-device hier round's
    # collective bytes must land inside [1.0, 1.25]x of the WIRE
    # LEDGER's tier1_to_tier2 seam (the ledger predicts the wire, the
    # compiler realizes it).
    import jax
    coll = None
    if len(jax.devices()) >= 8:
        from attacking_federate_learning_tpu.parallel.mesh import (
            make_plan
        )
        n, m = SHARDPROOF["n"], SHARDPROOF["m"]
        exp8 = _hier_experiment(
            make_plan((SHARDPROOF["mesh_clients"], 1)), users_count=n,
            mal_prop=0.25, defense="Krum", aggregation="hierarchical",
            megabatch=m)
        ledger_bytes = (exp8.wire_ledger()["seams"]["tier1_to_tier2"]
                        ["bytes"])
        coll = compiled_cost_facts(_round_compiled(exp8))[
            "collective_bytes"]
        if not (ledger_bytes <= coll
                <= STAGEPROOF["coll_slack"] * ledger_bytes):
            problems.append(
                f"stageproof: 8-device measured collective bytes "
                f"{coll} outside [1.0, "
                f"{STAGEPROOF['coll_slack']}]x the wire ledger's "
                f"tier1_to_tier2 seam {ledger_bytes}")
    else:
        print(f"note perf_gate stageproof: <8 devices "
              f"({len(jax.devices())}) — skipping the measured SPMD "
              f"wire cross-check (the per-cell ledger identity above "
              f"still gates)")

    if problems:
        print(f"FAIL perf_gate --stageproof: {len(problems)} "
              f"violation(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    spmd = (f", 8-device collective {coll} within "
            f"{STAGEPROOF['coll_slack']}x the ledger seam"
            if coll is not None else "")
    print(f"ok   perf_gate stageproof: {len(names)} cells partition "
          f">= {STAGEPROOF['flops_floor']:.0%} of FLOPs into named "
          f"stages (min {min(covs):.1%} over the faithful programs)"
          if covs else
          f"ok   perf_gate stageproof: {len(names)} emulation cells "
          f"hold the interpret floors", end="")
    print(f", stage sums exact, "
          f"{len([c for c in names if c in STAGEPROOF['fingerprint_cells']])} "
          f"scopes-off twins fingerprint-identical, hier "
          f"tier1_to_tier2 == S*d*4{spmd}")
    return numproof()


# --- numerics-observatory proof (ISSUE 20 acceptance) ------------------
# Baseline-free like the memproof.  The numerics observatory
# (utils/numerics.py counters threaded via cfg.numerics) must be a
# pure trace-time observer:
#
# (a) kernel twin: each margin-bearing defense kernel jitted with NO
#     observatory kwargs lowers to HLO text byte-identical to the
#     explicit margins=False, numerics=False spelling — the kwargs
#     leave zero residue when off (the off-path COST identity across
#     all 62 baseline entry points is pinned by the main gate, which
#     chains into this proof);
# (b) behavioral twin: a numerics-ON pinned experiment reaches
#     bit-identical weights to its numerics-OFF twin — the counters
#     observe the round, they never steer it.

NUMPROOF = dict(rounds=3, cells=("krum", "hier_krum"))


def numproof() -> int:
    """Gate the numerics-observatory observer facts.  Returns 0
    clean, 1 on a violation.  No baseline: HLO-text identity and
    weight bit-identity are absolute."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from attacking_federate_learning_tpu.defenses.kernels import (
        bulyan, krum, trimmed_mean
    )
    from attacking_federate_learning_tpu.defenses.median import median

    problems = []
    G = jnp.zeros((12, 32), jnp.float32)
    kernels = {
        "krum": (lambda g: krum(g, 12, 2, telemetry=True),
                 lambda g: krum(g, 12, 2, telemetry=True,
                                margins=False, numerics=False)),
        "trimmed_mean": (
            lambda g: trimmed_mean(g, 12, 2, telemetry=True),
            lambda g: trimmed_mean(g, 12, 2, telemetry=True,
                                   margins=False, numerics=False)),
        "median": (lambda g: median(g, 12, 2, telemetry=True),
                   lambda g: median(g, 12, 2, telemetry=True,
                                    margins=False, numerics=False)),
        "bulyan": (lambda g: bulyan(g, 12, 2, telemetry=True),
                   lambda g: bulyan(g, 12, 2, telemetry=True,
                                    margins=False, numerics=False)),
    }
    for name, (bare, explicit) in kernels.items():
        t_bare = jax.jit(bare).lower(G).as_text()
        t_off = jax.jit(explicit).lower(G).as_text()
        if t_bare != t_off:
            problems.append(
                f"numproof[{name}]: margins=False, numerics=False "
                f"lowers to different HLO than the bare call — the "
                f"observatory kwargs leave residue when off")

    for cell in NUMPROOF["cells"]:
        exp_off = _pinned_experiment(CELLS[cell])
        exp_on = _pinned_experiment({**CELLS[cell], "numerics": True})
        for t in range(NUMPROOF["rounds"]):
            exp_off.run_round(t)
            exp_on.run_round(t)
        w_off = np.asarray(exp_off.state.weights)
        w_on = np.asarray(exp_on.state.weights)
        if not np.array_equal(w_off.view(np.uint32),
                              w_on.view(np.uint32)):
            bad = int(np.sum(w_off.view(np.uint32)
                             != w_on.view(np.uint32)))
            problems.append(
                f"numproof[{cell}]: numerics-ON weights diverged from "
                f"the OFF twin after {NUMPROOF['rounds']} rounds "
                f"({bad} coords differ) — the counters steered the "
                f"round")

    if problems:
        print(f"FAIL perf_gate --numproof: {len(problems)} "
              f"violation(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    print(f"ok   perf_gate numproof: {len(kernels)} kernel twins "
          f"HLO-text identical with the observatory kwargs off, "
          f"{len(NUMPROOF['cells'])} numerics-ON cells bit-identical "
          f"to their OFF twins over {NUMPROOF['rounds']} rounds")
    return 0


def measure(cells) -> dict:
    out = {}
    for name in cells:
        out[name] = measure_cell(name, CELLS[name])
        print(f"  measured {name}: "
              + "  ".join(f"{e}={f['flops']:.3e}f"
                          for e, f in out[name].items()))
    return out


def diff(baseline: dict, measured: dict, tolerance: float) -> list:
    """Returns a list of '<cell>.<entry>.<metric>: ...' regression
    strings (empty = clean).  Missing/extra entries are regressions
    too — a silently vanished entry point must not pass the gate."""
    problems = []
    for cell, entries in baseline.items():
        if cell not in measured:
            problems.append(f"{cell}: cell not measured")
            continue
        got_entries = measured[cell]
        for entry, want in entries.items():
            got = got_entries.get(entry)
            if got is None:
                problems.append(f"{cell}.{entry}: entry point missing "
                                f"from the measured ledger")
                continue
            for metric in EXACT:
                if got.get(metric) != want.get(metric):
                    problems.append(
                        f"{cell}.{entry}.{metric}: measured "
                        f"{got.get(metric)} != baseline "
                        f"{want.get(metric)} (exact-match metric)")
            for metric in TOLERANT:
                w, g = want.get(metric), got.get(metric)
                if w in (None, 0):
                    if g != w:
                        problems.append(
                            f"{cell}.{entry}.{metric}: measured {g} != "
                            f"baseline {w}")
                    continue
                rel = abs(g - w) / abs(w)
                if rel > tolerance:
                    problems.append(
                        f"{cell}.{entry}.{metric}: measured {g} vs "
                        f"baseline {w} ({100 * rel:.1f}% > "
                        f"{100 * tolerance:.0f}% tolerance)")
        for entry in got_entries:
            if entry not in entries:
                problems.append(f"{cell}.{entry}: new entry point not in "
                                f"baseline (regenerate with --update)")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Deterministic (static-HLO) perf-regression gate "
                    "over pinned small configs (utils/costs.py).")
    p.add_argument("--baseline", default=BASELINE)
    p.add_argument("--update", action="store_true",
                   help="write a fresh baseline instead of gating")
    p.add_argument("--cells", default=",".join(CELLS),
                   help="comma-separated subset of the pinned cells")
    p.add_argument("--tolerance", type=float, default=0.05,
                   help="relative tolerance for the memory metrics "
                        "(FLOPs/bytes are always exact)")
    p.add_argument("--strict-env", action="store_true",
                   help="treat a baseline/environment mismatch as a "
                        "failure instead of a skip")
    p.add_argument("--memproof", action="store_true",
                   help="additionally run the hierarchical O(m*d) "
                        "memory proof at the 10k north star, the "
                        "secagg-vanilla wire proof, the pallas "
                        "fusion proof, the hierarchical SPMD shard "
                        "proof and the stage/wire-ledger proof "
                        "(absolute structural facts, no baseline; "
                        "tools/smoke.sh leg 4 runs all five)")
    p.add_argument("--pallasproof", action="store_true",
                   help="run ONLY the pallas fusion proof (+ the "
                        "chained shard proof): the fused "
                        "distance->Krum-score kernel's operands-once "
                        "bytes must beat the XLA Gram+epilogue path "
                        "at the 10k north star and no (n, n) tensor "
                        "may exist on the pallas route (ISSUE 11)")
    p.add_argument("--shardproof", action="store_true",
                   help="run ONLY the hierarchical SPMD shard proof "
                        "(ISSUE 12): every pinned hier cell on a "
                        "1-device clients axis matches the scan "
                        "path's exact cost facts, the 8-virtual-"
                        "device SPMD round holds no full "
                        "(n,d)/(S,m,d)/(n,n) tensor, its collective "
                        "bytes pin to the O(S*d) estimate "
                        "all_gather, and sharded==unsharded inside "
                        "the ulp band")
    p.add_argument("--stageproof", action="store_true",
                   help="run ONLY the stage/wire-ledger proof "
                        "(ISSUE 15): every pinned cell's round "
                        "partitions >= 95% of FLOPs into the named "
                        "stage taxonomy with exact sums, the stage "
                        "annotation is metadata-only (scopes-off "
                        "twin fingerprints match), and the "
                        "hierarchical wire ledger's tier1_to_tier2 "
                        "seam equals S*d*4 (honors --cells)")
    p.add_argument("--numproof", action="store_true",
                   help="run ONLY the numerics-observatory proof "
                        "(ISSUE 20): every margin-bearing kernel's "
                        "bare call lowers to HLO text identical to "
                        "the explicit margins=False, numerics=False "
                        "spelling, and numerics-ON pinned cells "
                        "reach bit-identical weights to their OFF "
                        "twins (the counters observe, never steer)")
    args = p.parse_args(argv)

    # The shard proof needs an 8-device mesh; the flag must land
    # before the first jax.devices() in this process (lazy backend
    # init) — harmless for every other leg (single-device jits cost
    # the same whatever the visible device count; the checked-in
    # baseline is verified under both 1- and 8-device envs by
    # tools/smoke.sh and tests/test_costs.py).
    _ensure_virtual_devices()

    if args.shardproof and not args.memproof:
        return shardproof()
    if args.pallasproof and not args.memproof:
        return pallasproof()
    if args.numproof and not args.memproof:
        return numproof()

    cells = [c.strip() for c in args.cells.split(",") if c.strip()]
    unknown = [c for c in cells if c not in CELLS]
    if unknown:
        print(f"unknown cells: {unknown} (known: {sorted(CELLS)})")
        return 2

    if args.stageproof and not args.memproof:
        return stageproof(cells)

    env = environment()
    if args.update:
        measured = measure(cells)
        payload = {"env": env, "tolerance": args.tolerance,
                   "cells": measured}
        with open(args.baseline, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.baseline} "
              f"({sum(len(v) for v in measured.values())} entry points, "
              f"jax {env['jax']}, {env['platform']})")
        return memproof() if args.memproof else stageproof(cells)

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline}; run with --update first")
        return 2
    with open(args.baseline) as f:
        base = json.load(f)
    benv = base.get("env", {})
    if benv != env:
        msg = (f"environment mismatch: baseline {benv} vs current {env} "
               f"— static cost facts are only comparable within one "
               f"(jax, platform) pair; regenerate with --update")
        if args.strict_env:
            print(f"FAIL perf_gate: {msg}")
            return 1
        print(f"SKIP perf_gate: {msg}")
        return 0

    baseline_cells = {c: v for c, v in base["cells"].items() if c in cells}
    measured = measure(cells)
    problems = diff(baseline_cells, measured, args.tolerance)
    if problems:
        print(f"FAIL perf_gate: {len(problems)} regression(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    n = sum(len(v) for v in measured.values())
    print(f"ok   perf_gate: {len(cells)} cells, {n} entry points match "
          f"the baseline (FLOPs/bytes exact, memory within "
          f"{100 * args.tolerance:.0f}%)")
    return memproof() if args.memproof else stageproof(cells)


if __name__ == "__main__":
    raise SystemExit(main())
