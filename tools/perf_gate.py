#!/usr/bin/env python
"""Deterministic perf-regression gate: static HLO facts, no stopwatch.

Wall-clock benchmarks on this box are unusable as a gate (one shared
core, rare TPU relay windows, BENCH_*.json noise), so this gate replays
a pinned set of small configs, extracts each compiled entry point's
STATIC cost facts (utils/costs.py: cost_analysis FLOPs / bytes
accessed, memory_analysis buffer sizes) and diffs them against the
checked-in ``PERF_BASELINE.json``:

- ``flops`` / ``bytes_accessed`` / ``argument_bytes`` / ``output_bytes``
  must match EXACTLY — they are pure functions of (HLO, XLA version,
  platform), so any drift is a real change to the compiled program
  (e.g. a defense kernel growing a second distance computation);
- ``temp_bytes`` / ``peak_bytes`` compare within ``--tolerance``
  (default 5%) — buffer assignment may legally wiggle with scheduling.

The baseline records the environment it was generated in (jax/jaxlib
version, platform).  On a mismatched environment the comparison is
meaningless (XLA's cost model changed under us), so the gate SKIPS with
a loud notice and exit 0 unless ``--strict-env`` — regenerate with
``--update`` after a toolchain bump.

Usage:
    python tools/perf_gate.py                  # gate against baseline
    python tools/perf_gate.py --update         # (re)generate baseline
    python tools/perf_gate.py --cells krum,bulyan --tolerance 0.1

Exit status: 0 clean (or env-skip), 1 on any named regression, 2 when
the baseline is missing (run --update first).  CI-wired via
tests/test_costs.py next to the fault_matrix/check_events hooks;
tools/smoke.sh runs all three.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "PERF_BASELINE.json")

# The pinned cells: small enough to compile in CI time on CPU, wide
# enough to cover the cost-relevant program families — the O(n^2 d)
# distance defenses, the coordinate-wise sorts, the fused-vs-telemetry
# round programs, and the plain mean.
CELLS = {
    "nodefense": dict(defense="NoDefense"),
    "krum": dict(defense="Krum"),
    "trimmed_mean": dict(defense="TrimmedMean"),
    "bulyan": dict(defense="Bulyan"),
    "median": dict(defense="Median"),
    "krum_telemetry": dict(defense="Krum", telemetry=True),
}

EXACT = ("flops", "bytes_accessed", "argument_bytes", "output_bytes")
TOLERANT = ("temp_bytes", "peak_bytes")


def environment() -> dict:
    import importlib.metadata as md

    import jax

    def _v(pkg):
        try:
            return md.version(pkg)
        except Exception:
            return "unknown"

    return {"jax": _v("jax"), "jaxlib": _v("jaxlib"),
            "platform": jax.devices()[0].platform}


def measure_cell(name: str, overrides: dict) -> dict:
    """Build the pinned small experiment and return {entry: facts}."""
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST, users_count=11, mal_prop=0.2,
        batch_size=16, epochs=5, test_step=5, seed=0,
        synth_train=256, synth_test=64, **overrides)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=256, synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds)
    ledger = exp.cost_report()
    if ledger.errors:
        msgs = "; ".join(f"{n}: {m}" for n, m in ledger.errors)
        raise RuntimeError(f"cell {name}: cost analysis failed ({msgs})")
    return ledger.summary()


def measure(cells) -> dict:
    out = {}
    for name in cells:
        out[name] = measure_cell(name, CELLS[name])
        print(f"  measured {name}: "
              + "  ".join(f"{e}={f['flops']:.3e}f"
                          for e, f in out[name].items()))
    return out


def diff(baseline: dict, measured: dict, tolerance: float) -> list:
    """Returns a list of '<cell>.<entry>.<metric>: ...' regression
    strings (empty = clean).  Missing/extra entries are regressions
    too — a silently vanished entry point must not pass the gate."""
    problems = []
    for cell, entries in baseline.items():
        if cell not in measured:
            problems.append(f"{cell}: cell not measured")
            continue
        got_entries = measured[cell]
        for entry, want in entries.items():
            got = got_entries.get(entry)
            if got is None:
                problems.append(f"{cell}.{entry}: entry point missing "
                                f"from the measured ledger")
                continue
            for metric in EXACT:
                if got.get(metric) != want.get(metric):
                    problems.append(
                        f"{cell}.{entry}.{metric}: measured "
                        f"{got.get(metric)} != baseline "
                        f"{want.get(metric)} (exact-match metric)")
            for metric in TOLERANT:
                w, g = want.get(metric), got.get(metric)
                if w in (None, 0):
                    if g != w:
                        problems.append(
                            f"{cell}.{entry}.{metric}: measured {g} != "
                            f"baseline {w}")
                    continue
                rel = abs(g - w) / abs(w)
                if rel > tolerance:
                    problems.append(
                        f"{cell}.{entry}.{metric}: measured {g} vs "
                        f"baseline {w} ({100 * rel:.1f}% > "
                        f"{100 * tolerance:.0f}% tolerance)")
        for entry in got_entries:
            if entry not in entries:
                problems.append(f"{cell}.{entry}: new entry point not in "
                                f"baseline (regenerate with --update)")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Deterministic (static-HLO) perf-regression gate "
                    "over pinned small configs (utils/costs.py).")
    p.add_argument("--baseline", default=BASELINE)
    p.add_argument("--update", action="store_true",
                   help="write a fresh baseline instead of gating")
    p.add_argument("--cells", default=",".join(CELLS),
                   help="comma-separated subset of the pinned cells")
    p.add_argument("--tolerance", type=float, default=0.05,
                   help="relative tolerance for the memory metrics "
                        "(FLOPs/bytes are always exact)")
    p.add_argument("--strict-env", action="store_true",
                   help="treat a baseline/environment mismatch as a "
                        "failure instead of a skip")
    args = p.parse_args(argv)

    cells = [c.strip() for c in args.cells.split(",") if c.strip()]
    unknown = [c for c in cells if c not in CELLS]
    if unknown:
        print(f"unknown cells: {unknown} (known: {sorted(CELLS)})")
        return 2

    env = environment()
    if args.update:
        measured = measure(cells)
        payload = {"env": env, "tolerance": args.tolerance,
                   "cells": measured}
        with open(args.baseline, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.baseline} "
              f"({sum(len(v) for v in measured.values())} entry points, "
              f"jax {env['jax']}, {env['platform']})")
        return 0

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline}; run with --update first")
        return 2
    with open(args.baseline) as f:
        base = json.load(f)
    benv = base.get("env", {})
    if benv != env:
        msg = (f"environment mismatch: baseline {benv} vs current {env} "
               f"— static cost facts are only comparable within one "
               f"(jax, platform) pair; regenerate with --update")
        if args.strict_env:
            print(f"FAIL perf_gate: {msg}")
            return 1
        print(f"SKIP perf_gate: {msg}")
        return 0

    baseline_cells = {c: v for c, v in base["cells"].items() if c in cells}
    measured = measure(cells)
    problems = diff(baseline_cells, measured, args.tolerance)
    if problems:
        print(f"FAIL perf_gate: {len(problems)} regression(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    n = sum(len(v) for v in measured.values())
    print(f"ok   perf_gate: {len(cells)} cells, {n} entry points match "
          f"the baseline (FLOPs/bytes exact, memory within "
          f"{100 * args.tolerance:.0f}%)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
