#!/usr/bin/env python
"""Run supervisor: bounded retry + backoff + degradation around any run.

Wraps a CLI experiment run (default) or an arbitrary command (``--raw``)
with the run-lifecycle layer (utils/lifecycle.py): failures are
classified, retried with exponential backoff, resumed from the newest
checkpoint, and — when the failure class calls for it — the run is
*degraded* rather than merely retried.  This is what makes a TPU relay
window un-wastable: a crash mid-window retries inside the same window
instead of losing it (tools/tpu_capture.sh runs its steps through this).

Failure taxonomy (utils/lifecycle.py:classify_failure):

- ``preempted`` (exit 75) — the child checkpointed on SIGTERM/SIGINT;
  resume immediately, no backoff, no retry-budget charge.
- ``divergence`` (exit 76 / divergence markers) — deterministic
  (watchdog rollbacks exhausted, or the backdoor nan guard); retrying
  the identical config reproduces it, so supervision stops FATALLY.
- ``oom`` — degradation ladder step: first relax the MeshPlan
  (``--mesh-shape none``), then halve the client-batch chunk (``-c``),
  floor 1; each step is a loud 'degrade' lifecycle event.
- ``backend`` — the TPU relay/backend died; resume the device-agnostic
  checkpoint on CPU (``--backend cpu``), loudly.
- ``stall`` — no event progress for ``--stall-timeout`` seconds (read
  from the child's event JSONL: the last heartbeat's last-event age,
  or the file mtime); the supervisor SIGTERMs (graceful: the child
  checkpoints at the next boundary), escalates to SIGKILL after
  ``--stall-grace``.  A second stall degrades: an async-mode run
  falls back to synchronous rounds first (``--aggregation flat`` —
  the buffered span is the largest program that engine compiles),
  then the staged per-round path (``--backdoor-staged``) — the
  repeated-compile-timeout remedy of last resort.
- ``crash`` — anything else; plain retry with backoff.

Exactly-once accounting: the child always runs with ``--journal`` and a
supervisor-pinned ``--run-id`` (so degraded restarts share one
journal); ``--verify-journal`` audits the journal after completion and
fails supervision on any double- or never-counted round/eval.

Usage:
    python tools/supervisor.py [options] -- -d Krum -s SYNTH_MNIST -e 30
    python tools/supervisor.py --raw [options] -- python bench.py

Exit status: the child's final exit code (0 on success), 1 when the
retry budget is exhausted or the journal audit fails.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from attacking_federate_learning_tpu.utils.lifecycle import (  # noqa: E402
    EXIT_PREEMPTED, RunJournal, classify_failure, run_id_for
)
from attacking_federate_learning_tpu.utils.metrics import (  # noqa: E402
    SCHEMA_VERSION, validate_event
)

STDERR_TAIL_BYTES = 8192
MAX_PREEMPT_RESUMES = 100   # safety backstop, not a budget: preempts are
#                             externally caused and individually cheap

# Defaults for every supervisor option (the argparse surface below and
# build_opts share these, so programmatic callers can't drift).
OPTION_DEFAULTS = dict(raw=False, max_retries=3, backoff_base=2.0,
                       backoff_max=60.0, checkpoint_every=5,
                       stall_timeout=0.0, stall_grace=30.0,
                       poll_interval=1.0, run_id=None, events=None,
                       verify_journal=False, inject_preempt_round=None,
                       child_env=None)


def build_opts(**overrides):
    """Options namespace for programmatic supervision (the campaign
    scheduler drives Supervisor objects directly; campaigns/
    scheduler.py).  ``child_env`` is a dict of environment overrides
    merged into every child attempt — the campaign pins its
    persistent-cache dir there."""
    unknown = set(overrides) - set(OPTION_DEFAULTS)
    if unknown:
        raise ValueError(f"unknown supervisor options {sorted(unknown)}")
    return argparse.Namespace(**{**OPTION_DEFAULTS, **overrides})


class Supervisor:
    def __init__(self, opts, child_args):
        self.opts = opts
        self.raw = opts.raw
        self.child_args = list(child_args)
        # Backoff jitter stream: seeded per PROCESS (pid + clock), so k
        # children supervising identical configs draw different sleeps
        # (see backoff()); tests inject a seeded Random here.
        import random
        self.rng = random.Random((os.getpid() << 20)
                                 ^ time.time_ns())
        self.failures = 0          # counted against --max-retries
        self.preempts = 0
        self.class_counts = {}
        self.degrade_flags = []
        self._events_fh = None
        if self.raw:
            self.run_id = opts.run_id or f"raw_{int(time.time())}"
            self.cfg = None
            self.events_path = opts.events or os.path.join(
                "logs", f"supervisor_{self.run_id}.jsonl")
        else:
            # Parse the child's flag surface once: run/log dirs, the
            # journal identity and the event-stream path all derive
            # from it (cli.build_parser is argparse-only — no jax).
            from attacking_federate_learning_tpu.cli import (
                build_parser, config_from_args
            )
            self.parser = build_parser()
            self.config_from_args = config_from_args
            ns = self.parser.parse_args(self.child_args)
            self.cfg = config_from_args(ns)
            self.run_id = opts.run_id or ns.run_id or run_id_for(self.cfg)
            self.events_path = opts.events or os.path.join(
                self.cfg.log_dir, f"supervisor_{self.run_id}.jsonl")

    # --- supervisor's own lifecycle event stream -----------------------
    def emit(self, phase, **fields):
        rec = {"kind": "lifecycle", "phase": phase, "v": SCHEMA_VERSION,
               "t": round(time.time(), 3), "run_id": self.run_id,
               **fields}
        validate_event(rec)
        if self._events_fh is None:
            os.makedirs(os.path.dirname(self.events_path) or ".",
                        exist_ok=True)
            self._events_fh = open(self.events_path, "a")
        self._events_fh.write(json.dumps(rec) + "\n")
        self._events_fh.flush()
        line = "  ".join(f"{k}={v}" for k, v in fields.items())
        # stderr, deliberately: a wrapped step's stdout may be a data
        # artifact (bench.py's JSON) that supervisor chatter must not
        # corrupt.
        print(f"[supervisor] {phase}  {line}", file=sys.stderr,
              flush=True)

    # --- child command construction ------------------------------------
    def _effective_ns(self):
        return self.parser.parse_args(self.child_args + self.degrade_flags)

    def _checkpoint_exists(self) -> bool:
        # PR 5 layout: a journaled child's auto-checkpoints live under
        # its private runs/<run_id>/; the shared runs/<dataset>/ still
        # holds the best-accuracy save and pre-migration autos.
        for ckdir in (os.path.join(self.cfg.run_dir, self.run_id),
                      os.path.join(self.cfg.run_dir, self.cfg.dataset)):
            if glob.glob(os.path.join(ckdir, "*.npz")):
                return True
        return False

    def build_cmd(self, attempt):
        if self.raw:
            return list(self.child_args)
        cmd = [sys.executable, "-m", "attacking_federate_learning_tpu.cli"]
        cmd += self.child_args
        cmd += ["--journal", "--run-id", self.run_id]
        if "--checkpoint-every" not in self.child_args:
            cmd += ["--checkpoint-every", str(self.opts.checkpoint_every)]
        cmd += self.degrade_flags
        # Resume from the newest checkpoint (auto saves compete with the
        # best save by round — cli.py --resume 'auto') — but only when
        # THIS run-id has prior progress: runs/<dataset>/ is shared, and
        # a first attempt must not silently adopt some other
        # experiment's checkpoint.
        manifest = os.path.join(self.cfg.run_dir, self.run_id,
                                "manifest.json")
        if (self._checkpoint_exists()
                and (attempt > 1 or os.path.exists(manifest))):
            cmd += ["--resume"]
        return cmd

    # --- degradation ladder --------------------------------------------
    def degrade_for(self, cls):
        """Append degradation flags for one failure class; returns a
        description of the step taken (None = no degradation, plain
        retry).  Flags are APPENDED so argparse last-wins overrides the
        original value — the original command stays legible in ps."""
        if self.raw:
            return None
        if cls == "oom":
            ns = self._effective_ns()
            if ns.mesh_shape and ns.mesh_shape.lower() != "none":
                self.degrade_flags += ["--mesh-shape", "none"]
                return "mesh_relaxed"
            new_bs = max(1, ns.batch_size // 2)
            if new_bs == ns.batch_size:
                return None          # floor reached; plain retry
            self.degrade_flags += ["-c", str(new_bs)]
            return f"batch_halved_to_{new_bs}"
        if cls == "backend":
            ns = self._effective_ns()
            if ns.backend != "cpu":
                # Device-agnostic checkpoint resumes on CPU — loud, and
                # only because the accelerator is gone.
                self.degrade_flags += ["--backend", "cpu"]
                return "cpu_fallback"
            return None
        if cls == "stall" and self.class_counts.get("stall", 0) >= 2:
            ns = self._effective_ns()
            if (ns.aggregation == "async"
                    and "--aggregation" not in self.degrade_flags):
                # An async-mode stall falls back to synchronous rounds
                # FIRST (--aggregation flat; argparse last-wins): the
                # buffered span is the largest program the async
                # engine compiles, and the sync path is the known-good
                # baseline — the staged per-round fallback below stays
                # the last resort.  (The async knobs are inert under
                # flat, so no further flag surgery is needed.)
                self.degrade_flags += ["--aggregation", "flat"]
                return "async_sync_fallback"
            if "--backdoor-staged" not in self.degrade_flags:
                # Repeated compile timeout: fall back to the staged
                # per-round path (per-round host boundaries — smaller
                # programs, observable progress).
                self.degrade_flags += ["--backdoor-staged"]
                return "staged_fallback"
        return None

    # --- stall detection ------------------------------------------------
    def _jsonl_path(self):
        if self.raw or self.cfg is None:
            return None
        base = self.cfg.csv_name().replace(".csv", "")
        return os.path.join(self.cfg.log_dir, base + ".jsonl")

    def _event_age(self, path, started_at):
        """Seconds since the child last made observable progress: the
        last heartbeat's REAL-event age when one is present (heartbeats
        keep the file mtime fresh precisely while stalled — mtime alone
        would mask the stall), else the file mtime, else child start."""
        try:
            with open(path, "rb") as f:
                tail = f.read()[-4096:].decode(errors="replace")
            lines = [ln for ln in tail.splitlines() if ln.strip()]
            for ln in reversed(lines):
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "heartbeat":
                    return float(rec.get("last_event_age_s", 0.0))
                break                    # newest line is a real event
            return time.time() - os.path.getmtime(path)
        except OSError:
            return time.time() - started_at

    # --- one attempt -----------------------------------------------------
    def run_attempt(self, attempt):
        cmd = self.build_cmd(attempt)
        self.emit("attempt", attempt=attempt,
                  cmd=" ".join(cmd), degraded=" ".join(self.degrade_flags))
        stderr_f = tempfile.NamedTemporaryFile(
            prefix="supervisor_stderr_", suffix=".log", delete=False)
        started = time.time()
        env = dict(os.environ)
        env.update(getattr(self.opts, "child_env", None) or {})
        if self.opts.inject_preempt_round is not None:
            env["FL_PREEMPT_AT_ROUND"] = str(self.opts.inject_preempt_round)
        proc = subprocess.Popen(cmd, stderr=stderr_f, env=env)
        stalled = False
        jsonl = self._jsonl_path()
        while proc.poll() is None:
            time.sleep(self.opts.poll_interval)
            if not self.opts.stall_timeout:
                continue
            age = self._event_age(jsonl, started) if jsonl else (
                time.time() - started)
            if age > self.opts.stall_timeout:
                stalled = True
                self.emit("stall_kill", attempt=attempt,
                          event_age_s=round(age, 1))
                proc.send_signal(signal.SIGTERM)   # graceful first: the
                try:                               # child checkpoints at
                    proc.wait(self.opts.stall_grace)  # the next boundary
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                break
        rc = proc.wait()
        stderr_f.close()
        with open(stderr_f.name, "rb") as f:
            f.seek(max(0, os.path.getsize(stderr_f.name)
                       - STDERR_TAIL_BYTES))
            tail = f.read().decode(errors="replace")
        os.unlink(stderr_f.name)
        return rc, tail, stalled

    # --- main loop --------------------------------------------------------
    def backoff(self, cls):
        """Bounded exponential backoff with decorrelation jitter.

        k identical campaign children that crash on the same cause
        (a dead relay, a full disk) all compute the same exponential
        envelope — without jitter they wake in lockstep and re-collide
        every cycle.  The sleep is drawn uniformly from the upper half
        of the envelope, ``[env/2, env]`` with
        ``env = min(backoff_max, backoff_base * 2**(failures-1))``:
        still exponentially growing and still capped, but any two
        children decorrelate by up to half a cycle.  The draw comes
        from ``self.rng`` — a PROCESS-seeded stream (never the
        experiment seed: children sharing a config must not share
        sleeps), injectable for tests."""
        if cls == "preempted":
            return 0.0
        n = max(0, self.failures - 1)
        env = min(self.opts.backoff_max,
                  self.opts.backoff_base * (2 ** n))
        return env / 2.0 + self.rng.random() * (env / 2.0)

    def verify_journal(self):
        if self.raw or not self.opts.verify_journal:
            return []
        journal = RunJournal(self.cfg.run_dir, self.run_id)
        ns = self._effective_ns()
        return journal.verify(epochs=ns.epochs,
                              test_step=self.cfg.test_step)

    def supervise(self) -> int:
        attempt = 0
        self.emit("supervise_start", raw=int(self.raw),
                  max_retries=self.opts.max_retries)
        while True:
            attempt += 1
            rc, tail, stalled = self.run_attempt(attempt)
            cls = classify_failure(rc, tail, stalled)
            self.class_counts[cls] = self.class_counts.get(cls, 0) + 1
            if cls == "done":
                problems = self.verify_journal()
                if problems:
                    self.emit("fatal", attempt=attempt,
                              failure="journal_audit",
                              problems="; ".join(problems))
                    return 1
                self.emit("supervise_done", attempts=attempt,
                          failures=self.failures, preempts=self.preempts)
                return 0
            if cls == "divergence":
                self.emit("fatal", attempt=attempt, failure=cls,
                          returncode=rc)
                print(tail[-2000:], file=sys.stderr)
                return rc if rc else 1
            if cls == "preempted":
                self.preempts += 1
                if self.preempts > MAX_PREEMPT_RESUMES:
                    self.emit("exhausted", attempt=attempt,
                              failure="preempt_loop")
                    return 1
                self.emit("retry", attempt=attempt, failure=cls,
                          returncode=EXIT_PREEMPTED, backoff_s=0)
                continue
            # Retryable failure: charge the budget, maybe degrade.
            self.failures += 1
            if self.failures > self.opts.max_retries:
                self.emit("exhausted", attempt=attempt, failure=cls,
                          failures=self.failures)
                print(tail[-2000:], file=sys.stderr)
                return 1
            step = self.degrade_for(cls)
            if step:
                self.emit("degrade", attempt=attempt, failure=cls,
                          step=step, flags=" ".join(self.degrade_flags))
            wait = self.backoff(cls)
            self.emit("retry", attempt=attempt, failure=cls,
                      returncode=rc, backoff_s=round(wait, 2))
            if wait:
                time.sleep(wait)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Supervise a run: bounded retry + backoff, failure "
                    "classification, degradation ladder, checkpoint "
                    "resume, exactly-once journal audit.  Child args "
                    "follow '--' (CLI flags by default, a full command "
                    "with --raw).")
    p.add_argument("--raw", action="store_true",
                   help="treat child args as a complete command instead "
                        "of cli.py flags (retry/backoff only: no resume "
                        "flags, no journal, no degradation)")
    p.add_argument("--max-retries", default=3, type=int,
                   help="retryable-failure budget (preempt resumes are "
                        "not charged)")
    p.add_argument("--backoff-base", default=2.0, type=float)
    p.add_argument("--backoff-max", default=60.0, type=float)
    p.add_argument("--checkpoint-every", default=5, type=int,
                   help="auto-checkpoint cadence forced onto the child "
                        "when it doesn't set one (resume granularity)")
    p.add_argument("--stall-timeout", default=0.0, type=float,
                   metavar="SECS",
                   help="kill + retry when the child makes no event "
                        "progress for SECS (heartbeat-aware); 0 = off")
    p.add_argument("--stall-grace", default=30.0, type=float,
                   help="seconds between the graceful SIGTERM and the "
                        "SIGKILL escalation on a stalled child")
    p.add_argument("--poll-interval", default=1.0, type=float)
    p.add_argument("--run-id", default=None,
                   help="journal identity (default: derived from the "
                        "child config; pinned across degraded restarts)")
    p.add_argument("--events", default=None, metavar="JSONL",
                   help="supervisor lifecycle-event stream (default "
                        "<log_dir>/supervisor_<run_id>.jsonl)")
    p.add_argument("--verify-journal", action="store_true",
                   help="after completion, audit the journal for "
                        "exactly-once round/eval coverage; violations "
                        "fail supervision")
    p.add_argument("--inject-preempt-round", default=None, type=int,
                   metavar="N",
                   help="set FL_PREEMPT_AT_ROUND=N in the child env "
                        "(deterministic preempt/resume drill — tests, "
                        "crash matrix, capture rehearsal)")
    if argv is None:
        argv = sys.argv[1:]
    if "--" in argv:
        split = argv.index("--")
        opts, child = p.parse_args(argv[:split]), argv[split + 1:]
    else:
        opts, child = p.parse_known_args(argv)
    if not child:
        p.error("no child args given (separate them with '--')")
    return Supervisor(opts, child).supervise()


if __name__ == "__main__":
    raise SystemExit(main())
