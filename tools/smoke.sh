#!/usr/bin/env bash
# One-liner CI smoke: event-schema validation + fault matrix + crash
# matrix + perf gate.
#
#   bash tools/smoke.sh            # all four, CPU-pinned
#   bash tools/smoke.sh --fast     # skip the fault + crash matrices
#                                  # (the two slowest legs)
#
# Legs (each independently CI-wired through tests/ as well):
#   1. tools/check_events.py over every run JSONL in logs/ (schema
#      v1-v3: round/eval/.../fault, compile/cost/heartbeat, lifecycle)
#      — skipped when logs/ has no .jsonl yet;
#   2. tools/fault_matrix.py — 5-round fault x defense sweep, emitted
#      'fault' events diffed against the host replay of the schedule;
#   3. tools/crash_matrix.py — supervised preempt/resume at a seeded
#      round x {fused, staged, faulted} x 2 defenses: bounded retries,
#      exactly-once journal, clean exit (tools/supervisor.py);
#   4. tools/perf_gate.py — deterministic static-HLO perf gate against
#      PERF_BASELINE.json (FLOPs/bytes exact, memory within tolerance).
#
# Exit: nonzero if any leg fails.  Always CPU (the gate's baseline is a
# CPU artifact, and the matrices must not touch a TPU capture).
set -u
cd "$(dirname "$0")/.."

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu

fail=0

shopt -s nullglob
jsonls=(logs/*.jsonl)
if [ ${#jsonls[@]} -gt 0 ]; then
    echo "== smoke 1/4: check_events (${#jsonls[@]} logs) =="
    python tools/check_events.py "${jsonls[@]}" || fail=1
else
    echo "== smoke 1/4: check_events — no logs/*.jsonl yet, skipped =="
fi

if [ "${1:-}" != "--fast" ]; then
    echo "== smoke 2/4: fault_matrix =="
    python tools/fault_matrix.py || fail=1
    echo "== smoke 3/4: crash_matrix (supervised preempt/resume) =="
    python tools/crash_matrix.py || fail=1
else
    echo "== smoke 2/4: fault_matrix — skipped (--fast) =="
    echo "== smoke 3/4: crash_matrix — skipped (--fast) =="
fi

echo "== smoke 4/4: perf_gate =="
python tools/perf_gate.py || fail=1

if [ $fail -ne 0 ]; then
    echo "SMOKE FAILED"
else
    echo "smoke clean"
fi
exit $fail
