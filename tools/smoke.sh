#!/usr/bin/env bash
# One-liner CI smoke: event-schema validation + fault matrix + perf gate.
#
#   bash tools/smoke.sh            # all three, CPU-pinned
#   bash tools/smoke.sh --fast     # skip the fault matrix (slowest leg)
#
# Legs (each independently CI-wired through tests/ as well):
#   1. tools/check_events.py over every run JSONL in logs/ (schema v1+v2:
#      round/eval/.../fault plus compile/cost/heartbeat) — skipped when
#      logs/ has no .jsonl yet;
#   2. tools/fault_matrix.py — 5-round fault x defense sweep, emitted
#      'fault' events diffed against the host replay of the schedule;
#   3. tools/perf_gate.py — deterministic static-HLO perf gate against
#      PERF_BASELINE.json (FLOPs/bytes exact, memory within tolerance).
#
# Exit: nonzero if any leg fails.  Always CPU (the gate's baseline is a
# CPU artifact, and the fault matrix must not touch a TPU capture).
set -u
cd "$(dirname "$0")/.."

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu

fail=0

shopt -s nullglob
jsonls=(logs/*.jsonl)
if [ ${#jsonls[@]} -gt 0 ]; then
    echo "== smoke 1/3: check_events (${#jsonls[@]} logs) =="
    python tools/check_events.py "${jsonls[@]}" || fail=1
else
    echo "== smoke 1/3: check_events — no logs/*.jsonl yet, skipped =="
fi

if [ "${1:-}" != "--fast" ]; then
    echo "== smoke 2/3: fault_matrix =="
    python tools/fault_matrix.py || fail=1
else
    echo "== smoke 2/3: fault_matrix — skipped (--fast) =="
fi

echo "== smoke 3/3: perf_gate =="
python tools/perf_gate.py || fail=1

if [ $fail -ne 0 ]; then
    echo "SMOKE FAILED"
else
    echo "smoke clean"
fi
exit $fail
