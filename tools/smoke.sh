#!/usr/bin/env bash
# One-liner CI smoke: event-schema validation + fault matrix + crash
# matrix + perf gate (incl. hierarchical memproof) + science gate +
# registry selfcheck + hierarchical-aggregation smoke.
#
#   bash tools/smoke.sh            # all seven, CPU-pinned
#   bash tools/smoke.sh --fast     # skip the fault + crash matrices
#                                  # (the two slowest legs)
#
# Legs (each independently CI-wired through tests/ as well):
#   1. tools/check_events.py over every run JSONL in logs/ (schema
#      v1-v4: round/eval/.../fault, compile/cost/heartbeat, lifecycle,
#      registry/gate) — skipped when logs/ has no .jsonl yet;
#   2. tools/fault_matrix.py — 5-round fault x defense sweep, emitted
#      'fault' events diffed against the host replay of the schedule;
#   3. tools/crash_matrix.py — supervised preempt/resume at a seeded
#      round x {fused, staged, faulted} x 2 defenses: bounded retries,
#      exactly-once journal, clean exit (tools/supervisor.py);
#   4. tools/perf_gate.py — deterministic static-HLO perf gate against
#      PERF_BASELINE.json (FLOPs/bytes exact, memory within tolerance);
#   5. tools/science_gate.py — deterministic behavioral-drift gate:
#      pinned SYNTH_MNIST_HARD defense x attack cells against
#      BEHAVIOR_BASELINE.json (exact where bit-deterministic, measured
#      ulp-tie bands elsewhere);
#   6. 'runs selfcheck' — cross-run registry over runs/ (incl. the
#      supervised-run artifacts legs 2-3 leave behind): index refresh
#      idempotence + every entry resolvable (utils/registry.py);
#   7. hierarchical-aggregation smoke — a 5-round journaled
#      hierarchical x {Krum, TrimmedMean} run each (two-tier streaming
#      engine, ops/federated.py), then a journal audit: every round and
#      eval committed exactly once (utils/lifecycle.py RunJournal).
#
# Exit: nonzero if any leg fails.  Always CPU (the gates' baselines are
# CPU artifacts, and the matrices must not touch a TPU capture).
set -u
cd "$(dirname "$0")/.."

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu

fail=0

shopt -s nullglob
jsonls=(logs/*.jsonl)
if [ ${#jsonls[@]} -gt 0 ]; then
    echo "== smoke 1/7: check_events (${#jsonls[@]} logs) =="
    python tools/check_events.py "${jsonls[@]}" || fail=1
else
    echo "== smoke 1/7: check_events — no logs/*.jsonl yet, skipped =="
fi

crash_work=""
if [ "${1:-}" != "--fast" ]; then
    echo "== smoke 2/7: fault_matrix =="
    python tools/fault_matrix.py || fail=1
    echo "== smoke 3/7: crash_matrix (supervised preempt/resume) =="
    # Keep the matrix's run stores: leg 6 registry-checks them.
    crash_work="$(mktemp -d -t crash_matrix_XXXXXX)"
    python tools/crash_matrix.py --workdir "$crash_work" || fail=1
else
    echo "== smoke 2/7: fault_matrix — skipped (--fast) =="
    echo "== smoke 3/7: crash_matrix — skipped (--fast) =="
fi

echo "== smoke 4/7: perf_gate (+ hierarchical memproof) =="
python tools/perf_gate.py --memproof || fail=1

echo "== smoke 5/7: science_gate (behavioral drift) =="
python tools/science_gate.py || fail=1

echo "== smoke 6/7: runs selfcheck (registry) =="
python -m attacking_federate_learning_tpu.cli runs selfcheck || fail=1
if [ -n "$crash_work" ]; then
    # The registry over the crash matrix's preempt/resume artifacts:
    # every supervised cell's run store must index, list and selfcheck
    # (refresh idempotence + resolvability) like any other runs/.
    for d in "$crash_work"/*/runs; do
        [ -d "$d" ] || continue
        echo "-- registry over crash-matrix artifacts: $d --"
        python -m attacking_federate_learning_tpu.cli runs \
            --run-dir "$d" --bench '' --progress '' list || fail=1
        python -m attacking_federate_learning_tpu.cli runs \
            --run-dir "$d" --bench '' --progress '' selfcheck || fail=1
    done
    rm -rf "$crash_work"
fi

echo "== smoke 7/7: hierarchical aggregation (journaled, audited) =="
hier_work="$(mktemp -d -t hier_smoke_XXXXXX)"
for def in Krum TrimmedMean; do
    python -m attacking_federate_learning_tpu.cli \
        -d "$def" -s SYNTH_MNIST -n 12 -m 0.25 -c 16 -e 5 \
        --synth-train 256 --synth-test 64 \
        --aggregation hierarchical --megabatch 4 \
        --journal --run-id "hier_${def}_smoke" --no-checkpoint \
        --log-dir "$hier_work/logs" --run-dir "$hier_work/runs" \
        > /dev/null || fail=1
done
# Journal audit: every round and eval committed exactly once
# (utils/lifecycle.py RunJournal.verify returns [] when clean).
python - "$hier_work/runs" <<'PY' || fail=1
import sys
from attacking_federate_learning_tpu.utils.lifecycle import RunJournal
bad = 0
for rid in ("hier_Krum_smoke", "hier_TrimmedMean_smoke"):
    problems = RunJournal(sys.argv[1], rid).verify(epochs=5, test_step=5)
    status = "ok" if not problems else f"FAIL {problems}"
    print(f"  journal {rid}: {status}")
    bad |= bool(problems)
sys.exit(bad)
PY
rm -rf "$hier_work"

if [ $fail -ne 0 ]; then
    echo "SMOKE FAILED"
else
    echo "smoke clean"
fi
exit $fail
