#!/usr/bin/env bash
# One-liner CI smoke: event-schema validation + fault matrix + crash
# matrix + perf gate (incl. hierarchical memproof + secagg wireproof +
# pallas fusion proof + stage/wire-ledger stageproof) +
# science gate + registry selfcheck + hierarchical-aggregation smoke +
# secure-aggregation smoke + hierarchical-telemetry/forensics smoke +
# asynchronous-rounds smoke + campaign-engine kill/resume smoke +
# measured-walls smoke (profiled run, runs walls, wall gate) +
# population-traffic smoke (churn run, ladder audit, runs traffic) +
# robustness-margins smoke (margin run, v12 audit, runs margins drift).
#
#   bash tools/smoke.sh            # all fourteen, CPU-pinned
#   bash tools/smoke.sh --fast     # skip the fault + crash matrices
#                                  # (the two slowest legs)
#
# Legs (each independently CI-wired through tests/ as well):
#   1. tools/check_events.py over every run JSONL in logs/ (schema
#      v1-v10: round/eval/.../fault, compile/cost/heartbeat, lifecycle,
#      registry/gate, secagg, shard_selection/forensics, async,
#      campaign, stage_cost/wire_bytes, wall) — skipped when logs/ has
#      no .jsonl yet;
#   2. tools/fault_matrix.py — 5-round fault x defense sweep, emitted
#      'fault' events diffed against the host replay of the schedule,
#      plus the dropout x async-buffer leg (async + fault events
#      diffed against core/async_rounds.py:replay_schedule);
#   3. tools/crash_matrix.py — supervised preempt/resume at a seeded
#      round x {fused, staged, faulted} x 2 defenses: bounded retries,
#      exactly-once journal, clean exit (tools/supervisor.py);
#   4. tools/perf_gate.py — deterministic static-HLO perf gate against
#      PERF_BASELINE.json (FLOPs/bytes exact, memory within tolerance);
#   5. tools/science_gate.py — deterministic behavioral-drift gate:
#      pinned SYNTH_MNIST_HARD defense x attack cells against
#      BEHAVIOR_BASELINE.json (exact where bit-deterministic, measured
#      ulp-tie bands elsewhere);
#   6. 'runs selfcheck' — cross-run registry over runs/ (incl. the
#      supervised-run artifacts legs 2-3 leave behind): index refresh
#      idempotence + every entry resolvable (utils/registry.py);
#   7. hierarchical-aggregation smoke — a 5-round journaled
#      hierarchical x {Krum, TrimmedMean} run each (two-tier streaming
#      engine, ops/federated.py), then a journal audit: every round and
#      eval committed exactly once (utils/lifecycle.py RunJournal);
#   8. secure-aggregation smoke — a 5-round journaled --secagg vanilla
#      run with injected dropout (every dropout round must complete as
#      a mask-reconstruction round with the bitwise sum check passing)
#      and a 5-round journaled --secagg groupwise x tier-2 Krum run
#      (protocols/secagg.py), then the same journal audit plus a
#      'secagg'-event audit over the private run logs;
#   9. hierarchical-telemetry forensics smoke — a 5-round journaled
#      hierarchical x Krum run with --telemetry (schema-v6
#      'shard_selection' events), check_events over its private log,
#      'report forensics' exit-0, and a 'runs trace' export (the
#      exporter validates the trace before writing);
#  10. asynchronous-rounds smoke — a journaled 5-round
#      --aggregation async x {Krum, TrimmedMean} run each (FedBuff
#      buffered rounds, core/async_rounds.py), then RunJournal.verify
#      (every round and eval exactly once), check_events over the
#      private logs (v7 'async' events), and an async-event audit:
#      one per round, every delivered round exactly k rows;
#  11. campaign-engine smoke — a journaled 2x2 (defense x attack)
#      campaign on SYNTH_MNIST (campaigns/scheduler.py) with one
#      injected mid-campaign kill (FL_CAMPAIGN_KILL_AFTER_CELLS) +
#      resume: the re-invoke completes only the remaining cells, the
#      campaign journal audits exactly-once, runs/index.jsonl carries
#      zero duplicate run stamps, check_events validates the v8
#      'campaign' event stream, and 'runs campaign <id>' renders the
#      defense x attack table from the registry;
#  12. measured-walls smoke — a journaled 5-round flat x Krum run with
#      --profile-every 1 (schema-v10 'wall' events: host span/eval
#      walls + per-stage trace bookings, utils/walls.py), check_events
#      over its private log, 'runs walls' exit-0 on the run, and the
#      noise-banded wall gate's self-consistency: a fresh --update
#      baseline in a temp dir must gate clean at k=3
#      (tools/wall_gate.py);
#  13. population-traffic smoke — a journaled 10-round churn run from a
#      deliberately unreliable 16-client population (the cohort
#      routinely under-fills the Krum validity bound, forcing the
#      degradation ladder), check_events over its private log (schema
#      v11 'traffic' events), a replay audit (emitted events must
#      equal core/population.py:replay_traffic exactly, with at least
#      one degraded round), and 'runs traffic <id>' exit-0;
#  14. robustness-margins smoke — two journaled 6-round --margins x
#      Bulyan runs at different seeds (schema-v12 'margin' events:
#      per-row decision margins + colluder-survival rollups,
#      utils/margins.py), check_events --stats over the private logs
#      (v12 kind + per-kind histogram), a margin-event audit (one per
#      round, rollup fields present), 'runs margins <id>' exit-0 on
#      one run, and the cross-run drift render over both;
#  15. faulted-hierarchy smoke (ISSUE 19) — a journaled 6-round
#      hierarchical TrimmedMean run under per-client dropout/corrupt
#      PLUS correlated shard-DOMAIN death (--fault-shard-dropout),
#      check_events --stats over its private log (schema-v13 'fault'
#      events with per-shard survivor vectors), a host-replay audit
#      (emitted events must equal core/faults.py:hier_fault_schedule
#      exactly, tier-2 ladder action included), and 'report' exit-0
#      with the shard-domain fault table rendered.
#
# Exit: nonzero if any leg fails.  Always CPU (the gates' baselines are
# CPU artifacts, and the matrices must not touch a TPU capture).
set -u
cd "$(dirname "$0")/.."

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu

fail=0

shopt -s nullglob
jsonls=(logs/*.jsonl)
if [ ${#jsonls[@]} -gt 0 ]; then
    echo "== smoke 1/16: check_events (${#jsonls[@]} logs) =="
    python tools/check_events.py "${jsonls[@]}" || fail=1
else
    echo "== smoke 1/16: check_events — no logs/*.jsonl yet, skipped =="
fi

crash_work=""
if [ "${1:-}" != "--fast" ]; then
    echo "== smoke 2/16: fault_matrix =="
    python tools/fault_matrix.py || fail=1
    echo "== smoke 3/16: crash_matrix (supervised preempt/resume) =="
    # Keep the matrix's run stores: leg 6 registry-checks them.
    crash_work="$(mktemp -d -t crash_matrix_XXXXXX)"
    python tools/crash_matrix.py --workdir "$crash_work" || fail=1
else
    echo "== smoke 2/16: fault_matrix — skipped (--fast) =="
    echo "== smoke 3/16: crash_matrix — skipped (--fast) =="
fi

echo "== smoke 4/16: perf_gate (+ memproof + wireproof + pallasproof"
echo "   + shardproof + stageproof) =="
python tools/perf_gate.py --memproof || fail=1

echo "== smoke 5/16: science_gate (behavioral drift) =="
python tools/science_gate.py || fail=1

echo "== smoke 6/16: runs selfcheck (registry) =="
python -m attacking_federate_learning_tpu.cli runs selfcheck || fail=1
if [ -n "$crash_work" ]; then
    # The registry over the crash matrix's preempt/resume artifacts:
    # every supervised cell's run store must index, list and selfcheck
    # (refresh idempotence + resolvability) like any other runs/.
    for d in "$crash_work"/*/runs; do
        [ -d "$d" ] || continue
        echo "-- registry over crash-matrix artifacts: $d --"
        python -m attacking_federate_learning_tpu.cli runs \
            --run-dir "$d" --bench '' --progress '' list || fail=1
        python -m attacking_federate_learning_tpu.cli runs \
            --run-dir "$d" --bench '' --progress '' selfcheck || fail=1
    done
    rm -rf "$crash_work"
fi

echo "== smoke 7/16: hierarchical aggregation (journaled, audited) =="
hier_work="$(mktemp -d -t hier_smoke_XXXXXX)"
for def in Krum TrimmedMean; do
    python -m attacking_federate_learning_tpu.cli \
        -d "$def" -s SYNTH_MNIST -n 12 -m 0.25 -c 16 -e 5 \
        --synth-train 256 --synth-test 64 \
        --aggregation hierarchical --megabatch 4 \
        --journal --run-id "hier_${def}_smoke" --no-checkpoint \
        --log-dir "$hier_work/logs" --run-dir "$hier_work/runs" \
        > /dev/null || fail=1
done
# Journal audit: every round and eval committed exactly once
# (utils/lifecycle.py RunJournal.verify returns [] when clean).
python - "$hier_work/runs" <<'PY' || fail=1
import sys
from attacking_federate_learning_tpu.utils.lifecycle import RunJournal
bad = 0
for rid in ("hier_Krum_smoke", "hier_TrimmedMean_smoke"):
    problems = RunJournal(sys.argv[1], rid).verify(epochs=5, test_step=5)
    status = "ok" if not problems else f"FAIL {problems}"
    print(f"  journal {rid}: {status}")
    bad |= bool(problems)
sys.exit(bad)
PY
rm -rf "$hier_work"

echo "== smoke 8/16: secure aggregation (journaled, audited) =="
sa_work="$(mktemp -d -t secagg_smoke_XXXXXX)"
# vanilla: one dropout-rate high enough that the 5-round seeded run is
# guaranteed (and pinned by the audit below) to include at least one
# mask-reconstruction round.
python -m attacking_federate_learning_tpu.cli \
    -d NoDefense -s SYNTH_MNIST -n 12 -m 0.25 -c 16 -e 5 \
    --synth-train 256 --synth-test 64 \
    --secagg vanilla --fault-dropout 0.25 \
    --journal --run-id secagg_vanilla_smoke --no-checkpoint \
    --log-dir "$sa_work/logs" --run-dir "$sa_work/runs" \
    > /dev/null || fail=1
# groupwise x tier-2 Krum over per-group sums (the NET-SA composition
# with the two-tier tree).
python -m attacking_federate_learning_tpu.cli \
    -d NoDefense --tier2-defense Krum -s SYNTH_MNIST -n 12 -m 0.25 \
    -c 16 -e 5 --synth-train 256 --synth-test 64 \
    --secagg groupwise --aggregation hierarchical --megabatch 4 \
    --journal --run-id secagg_groupwise_smoke --no-checkpoint \
    --log-dir "$sa_work/logs" --run-dir "$sa_work/runs" \
    > /dev/null || fail=1
python - "$sa_work" <<'PY' || fail=1
import json, os, sys
from attacking_federate_learning_tpu.utils.lifecycle import RunJournal
work = sys.argv[1]
bad = 0
for rid in ("secagg_vanilla_smoke", "secagg_groupwise_smoke"):
    problems = RunJournal(os.path.join(work, "runs"), rid).verify(
        epochs=5, test_step=5)
    events = [json.loads(line) for line in
              open(os.path.join(work, "logs", rid + ".jsonl"))]
    sec = [e for e in events if e.get("kind") == "secagg"]
    if len(sec) != 5:
        problems.append(f"{len(sec)} secagg events, want one per round")
    if any(not e.get("sum_check_ok") for e in sec):
        problems.append("bitwise sum check failed")
    if rid == "secagg_vanilla_smoke":
        rec = sum(e.get("recovery", 0) for e in sec)
        masks = sum(e.get("masks_reconstructed", 0) for e in sec)
        if rec < 1 or masks < 1:
            problems.append(f"no dropout-recovery round fired "
                            f"(recovery={rec}, masks={masks})")
    status = "ok" if not problems else f"FAIL {problems}"
    print(f"  secagg {rid}: {status}")
    bad |= bool(problems)
sys.exit(bad)
PY
rm -rf "$sa_work"

echo "== smoke 9/16: hierarchical telemetry + forensics (journaled) =="
fx_work="$(mktemp -d -t hier_tele_smoke_XXXXXX)"
# 5-round journaled hierarchical x Krum run with --telemetry: the run
# must emit one schema-v6 'shard_selection' event per round.
python -m attacking_federate_learning_tpu.cli \
    -d Krum -s SYNTH_MNIST -n 12 -m 0.25 -c 16 -e 5 \
    --synth-train 256 --synth-test 64 \
    --aggregation hierarchical --megabatch 4 --telemetry \
    --journal --run-id hier_tele_smoke --no-checkpoint \
    --log-dir "$fx_work/logs" --run-dir "$fx_work/runs" \
    > /dev/null || fail=1
# Event audit: the private log validates (v6 'shard_selection' events
# included) and carries exactly one per round.
python tools/check_events.py "$fx_work/logs/hier_tele_smoke.jsonl" \
    || fail=1
python - "$fx_work" <<'PY' || fail=1
import json, os, sys
events = [json.loads(line) for line in
          open(os.path.join(sys.argv[1], "logs",
                            "hier_tele_smoke.jsonl"))]
ss = [e for e in events if e.get("kind") == "shard_selection"]
ok = (len(ss) == 5 and all(e.get("v") >= 6 for e in ss)
      and all("tier2_selection_mask" in e for e in ss))
print(f"  shard_selection events: {len(ss)}/5 "
      f"({'ok' if ok else 'FAIL'})")
sys.exit(0 if ok else 1)
PY
# 'report forensics' must produce a verdict (exit 0) on the run log.
python -m attacking_federate_learning_tpu.cli report forensics \
    "$fx_work/logs/hier_tele_smoke.jsonl" || fail=1
# 'runs trace' export over the same run — export_trace validates the
# trace-event JSON (tier-2 forensics track included) before writing.
python -m attacking_federate_learning_tpu.cli runs \
    --run-dir "$fx_work/runs" --bench '' --progress '' \
    trace hier_tele_smoke -o "$fx_work/trace.json" || fail=1
rm -rf "$fx_work"

echo "== smoke 10/16: asynchronous rounds (journaled, audited) =="
as_work="$(mktemp -d -t async_smoke_XXXXXX)"
# 5-round journaled FedBuff runs: k=8 of n=12 aggregated per applied
# round, staleness bound 2, poly weighting, Krum + TrimmedMean.
for def in Krum TrimmedMean; do
    python -m attacking_federate_learning_tpu.cli \
        -d "$def" -s SYNTH_MNIST -n 12 -m 0.25 -c 16 -e 5 \
        --synth-train 256 --synth-test 64 \
        --aggregation async --async-buffer 8 --async-max-staleness 2 \
        --staleness-weight poly \
        --journal --run-id "async_${def}_smoke" --no-checkpoint \
        --log-dir "$as_work/logs" --run-dir "$as_work/runs" \
        > /dev/null || fail=1
    # The private log must validate (v7 'async' events included).
    python tools/check_events.py \
        "$as_work/logs/async_${def}_smoke.jsonl" || fail=1
done
# Journal audit (exactly-once) + async-event audit: one v7 'async'
# event per round, and every delivered round aggregates exactly k.
python - "$as_work" <<'PY' || fail=1
import json, os, sys
from attacking_federate_learning_tpu.utils.lifecycle import RunJournal
work = sys.argv[1]
bad = 0
for rid in ("async_Krum_smoke", "async_TrimmedMean_smoke"):
    problems = RunJournal(os.path.join(work, "runs"), rid).verify(
        epochs=5, test_step=5)
    events = [json.loads(line) for line in
              open(os.path.join(work, "logs", rid + ".jsonl"))]
    av = [e for e in events if e.get("kind") == "async"]
    if len(av) != 5:
        problems.append(f"{len(av)} async events, want one per round")
    if any(e.get("v", 0) < 7 for e in av):
        problems.append("async event stamped below v7")
    if any(int(e.get("delivered", -1)) not in (0, 8) for e in av):
        problems.append("a delivered round did not aggregate "
                        "exactly k=8 rows")
    if not any(int(e.get("delivered", 0)) == 8 for e in av):
        problems.append("no round ever reached the FedBuff trigger")
    status = "ok" if not problems else f"FAIL {problems}"
    print(f"  async {rid}: {status}")
    bad |= bool(problems)
sys.exit(bad)
PY
# Registry-resolved staleness table must render (runs async verb).
python -m attacking_federate_learning_tpu.cli runs \
    --run-dir "$as_work/runs" --bench '' --progress '' \
    async async_Krum_smoke || fail=1
rm -rf "$as_work"

echo "== smoke 11/16: campaign engine (kill + resume, audited) =="
ce_work="$(mktemp -d -t campaign_smoke_XXXXXX)"
cat > "$ce_work/spec.json" <<SPEC
{"name": "smoke",
 "base": {"dataset": "SYNTH_MNIST", "users_count": 12, "mal_prop": 0.25,
          "batch_size": 16, "epochs": 5, "synth_train": 256,
          "synth_test": 64, "backend": "cpu",
          "log_dir": "$ce_work/logs", "run_dir": "$ce_work/runs"},
 "axes": {"defense": ["Krum", "TrimmedMean"],
          "attack": ["none", "alie"]}}
SPEC
# First invocation dies (injected SIGKILL-equivalent) after 2 cells...
FL_CAMPAIGN_KILL_AFTER_CELLS=2 \
python -m attacking_federate_learning_tpu.campaigns "$ce_work/spec.json" \
    --executor inline > /dev/null 2>&1
rc=$?
[ "$rc" -eq 137 ] || { echo "FAIL campaign: expected kill rc 137, got $rc"; fail=1; }
# ...the re-invoke completes only the remaining cells.
python -m attacking_federate_learning_tpu.campaigns "$ce_work/spec.json" \
    --executor inline || fail=1
camp_id="$(ls "$ce_work/runs/campaigns")"
# Exactly-once audits: campaign journal + zero duplicate run stamps.
python - "$ce_work" "$camp_id" <<'PY' || fail=1
import json, os, sys
from attacking_federate_learning_tpu.campaigns import CampaignJournal
work, camp_id = sys.argv[1], sys.argv[2]
j = CampaignJournal(os.path.join(work, "runs"), camp_id)
problems = j.verify()
man = j.read_manifest()
if man["status"] != "done" or man["counts"].get("done") != 4:
    problems.append(f"campaign not done: {man['status']} {man['counts']}")
attempts = [r for r in j.records() if r.get("kind") == "attempt"]
if len(attempts) != 2:
    problems.append(f"{len(attempts)} attempts recorded, want 2")
ids = [json.loads(line)["run_id"]
       for line in open(os.path.join(work, "runs", "index.jsonl"))]
if len(ids) != len(set(ids)):
    problems.append(f"duplicate run stamps in index.jsonl: {ids}")
print("  campaign journal: " + ("ok (exactly-once, resumed)"
                                if not problems else f"FAIL {problems}"))
sys.exit(bool(problems))
PY
# The v8 'campaign' event stream validates...
python tools/check_events.py \
    "$ce_work/runs/campaigns/$camp_id/events.jsonl" || fail=1
# ...and 'runs campaign <id>' renders the defense x attack table from
# the registry (values bit-exact against the per-run manifests).
python -m attacking_federate_learning_tpu.cli runs \
    --run-dir "$ce_work/runs" --bench '' --progress '' \
    campaign "$camp_id" || fail=1
rm -rf "$ce_work"

echo "== smoke 12/16: measured walls (profiled run + wall gate) =="
wl_work="$(mktemp -d -t walls_smoke_XXXXXX)"
# 5-round journaled flat x Krum with every eval interval profiled: the
# engine books each span capture onto the stage taxonomy and emits
# schema-v10 'wall' events next to the --cost-report stage_cost twins.
python -m attacking_federate_learning_tpu.cli \
    -d Krum -s SYNTH_MNIST -n 12 -m 0.25 -c 16 -e 5 \
    --synth-train 256 --synth-test 64 \
    --profile-every 1 --cost-report \
    --journal --run-id walls_smoke --no-checkpoint \
    --log-dir "$wl_work/logs" --run-dir "$wl_work/runs" \
    > /dev/null || fail=1
# The private log validates (v10 'wall' events included) and carries
# both wall sources (host span/eval clocks + trace bookings).
python tools/check_events.py "$wl_work/logs/walls_smoke.jsonl" || fail=1
python - "$wl_work" <<'PY' || fail=1
import json, os, sys
events = [json.loads(line) for line in
          open(os.path.join(sys.argv[1], "logs", "walls_smoke.jsonl"))]
wl = [e for e in events if e.get("kind") == "wall"]
src = {e.get("source") for e in wl}
traced = [e for e in wl if e.get("source") == "trace"]
exact = all(
    abs(sum(e["stages"].values()) + e["unattributed_us"]
        - e["wall_s"] * 1e6) <= 1.0 for e in traced)
ok = (bool(wl) and src == {"host", "trace"}
      and all(e.get("v") == 10 for e in wl)
      and all(e["coverage"]["op_events"] > 0 for e in traced) and exact)
print(f"  wall events: {len(wl)} ({len(traced)} trace-booked, "
      f"partition {'exact' if exact else 'BROKEN'}) "
      f"({'ok' if ok else 'FAIL'})")
sys.exit(0 if ok else 1)
PY
# The registry verb renders the measured/modeled tables (exit 0).
python -m attacking_federate_learning_tpu.cli runs \
    --run-dir "$wl_work/runs" --bench '' --progress '' \
    walls walls_smoke || fail=1
# Wall-gate self-consistency: a freshly generated baseline must gate
# clean at k=3 (median + MAD noise bands, tools/wall_gate.py) —
# checked in a temp dir so the checked-in WALL_BASELINE.json is never
# clobbered by the smoke.
python tools/wall_gate.py --update --baseline "$wl_work/WALL_BASELINE.json" \
    > /dev/null || fail=1
python tools/wall_gate.py --baseline "$wl_work/WALL_BASELINE.json" || fail=1
rm -rf "$wl_work"

echo "== smoke 13/16: population traffic (churn, ladder, audited) =="
tr_work="$(mktemp -d -t traffic_smoke_XXXXXX)"
# 10-round journaled churn run from an unreliable 16-client population:
# the sampled cohort routinely misses Krum's 2f+3 validity bound, so
# the run only completes by walking the declared degradation ladder
# (remask -> TrimmedMean fallback -> hold), every decision a v11
# 'traffic' event.
python -m attacking_federate_learning_tpu.cli \
    -d Krum -s SYNTH_MNIST -n 12 -m 0.25 -c 16 -e 10 \
    --synth-train 256 --synth-test 64 --seed 1 \
    --traffic-population 16 --traffic-rate 0.6 --traffic-churn-dwell 2 \
    --traffic-fallback TrimmedMean --traffic-seed 5 \
    --journal --run-id traffic_smoke --no-checkpoint \
    --log-dir "$tr_work/logs" --run-dir "$tr_work/runs" \
    > /dev/null || fail=1
# The private log must validate (v11 'traffic' events included).
python tools/check_events.py "$tr_work/logs/traffic_smoke.jsonl" || fail=1
# Journal audit (exactly-once) + the replay audit: the emitted traffic
# events must equal the independent host regeneration of the schedule,
# and the under-fill must actually have forced a degradation step.
python - "$tr_work" <<'PY' || fail=1
import json, os, sys
from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.core.population import replay_traffic
from attacking_federate_learning_tpu.utils.lifecycle import RunJournal
work = sys.argv[1]
problems = RunJournal(os.path.join(work, "runs"), "traffic_smoke").verify(
    epochs=10, test_step=5)
events = [json.loads(line) for line in
          open(os.path.join(work, "logs", "traffic_smoke.jsonl"))]
tr = sorted((e for e in events if e.get("kind") == "traffic"),
            key=lambda e: e["round"])
cfg = C.ExperimentConfig(
    dataset=C.SYNTH_MNIST, users_count=12, mal_prop=0.25, batch_size=16,
    epochs=10, synth_train=256, synth_test=64, seed=1, defense="Krum",
    traffic=C.TrafficConfig(population=16, rate=0.6, churn_dwell=2,
                            fallback_defense="TrimmedMean", seed=5))
want = replay_traffic(cfg, 10)
keys = ("round", "arrived", "f_eff", "cohort", "action", "defense")
if len(tr) != 10:
    problems.append(f"{len(tr)} traffic events, want one per round")
if any(e.get("v", 0) < 11 for e in tr):
    problems.append("traffic event stamped below v11")
if ([tuple(e[k] for k in keys) for e in tr]
        != [tuple(e[k] for k in keys) for e in want]):
    problems.append("emitted traffic events diverge from the host replay")
if not any(e["action"] in ("fallback", "hold") for e in tr):
    problems.append("under-fill never forced a degradation step")
degraded = sum(1 for e in tr if e["action"] != "remask")
status = "ok" if not problems else f"FAIL {problems}"
print(f"  traffic traffic_smoke: {len(tr)} events, "
      f"{degraded} degraded rounds ({status})")
sys.exit(bool(problems))
PY
# Registry-resolved traffic table must render (runs traffic verb).
python -m attacking_federate_learning_tpu.cli runs \
    --run-dir "$tr_work/runs" --bench '' --progress '' \
    traffic traffic_smoke || fail=1
rm -rf "$tr_work"

echo "== smoke 14/16: robustness margins (v12 audit + drift render) =="
mg_work="$(mktemp -d -t margins_smoke_XXXXXX)"
# Two short journaled Bulyan --margins runs at different seeds: the
# in-jit margin observatory emits one schema-v12 'margin' event per
# round (per-row decision margins + colluder-survival rollups).
for seed in 0 1; do
    python -m attacking_federate_learning_tpu.cli \
        -d Bulyan -z 1.5 -s SYNTH_MNIST -n 15 -m 0.2 -c 16 -e 6 \
        --synth-train 256 --synth-test 64 --seed "$seed" \
        --margins \
        --journal --run-id "margins_smoke_$seed" --no-checkpoint \
        --log-dir "$mg_work/logs" --run-dir "$mg_work/runs" \
        > /dev/null || fail=1
    # The private log validates (v12 'margin' events included) and the
    # --stats histogram renders.
    python tools/check_events.py --stats \
        "$mg_work/logs/margins_smoke_$seed.jsonl" || fail=1
done
# Margin-event audit: one per round, rollup fields riding along.
python - "$mg_work" <<'PY' || fail=1
import json, os, sys
bad = 0
for seed in (0, 1):
    events = [json.loads(line) for line in
              open(os.path.join(sys.argv[1], "logs",
                                f"margins_smoke_{seed}.jsonl"))]
    mg = [e for e in events if e.get("kind") == "margin"]
    problems = []
    if len(mg) != 6:
        problems.append(f"{len(mg)} margin events, want one per round")
    if any(e.get("v", 0) < 12 for e in mg):
        problems.append("margin event stamped below v12")
    if any("colluder_margin" not in e or "margin_gap" not in e
           for e in mg):
        problems.append("a margin event is missing its rollups")
    status = "ok" if not problems else f"FAIL {problems}"
    print(f"  margins margins_smoke_{seed}: {len(mg)} events ({status})")
    bad |= bool(problems)
sys.exit(bad)
PY
# Registry-resolved trajectory table (exit 0), then the cross-run
# colluder-margin drift with sign-flip marks over both seeds.
python -m attacking_federate_learning_tpu.cli runs \
    --run-dir "$mg_work/runs" --bench '' --progress '' \
    margins margins_smoke_0 || fail=1
python -m attacking_federate_learning_tpu.cli runs \
    --run-dir "$mg_work/runs" --bench '' --progress '' \
    margins margins_smoke_0 margins_smoke_1 || fail=1
rm -rf "$mg_work"

echo "== smoke 15/16: faulted hierarchy (shard domains, journaled) =="
fh_work="$(mktemp -d -t fault_hier_smoke_XXXXXX)"
# A journaled 6-round two-tier run under BOTH fault granularities:
# per-client dropout/corrupt inside each megabatch plus correlated
# shard-DOMAIN death; the shard-dropout rate is high enough that the
# seeded run includes dead-domain rounds (pinned by the audit below).
python -m attacking_federate_learning_tpu.cli \
    -d TrimmedMean -s SYNTH_MNIST -n 16 -m 0.25 -c 16 -e 6 \
    --synth-train 256 --synth-test 64 --seed 3 \
    --aggregation hierarchical --megabatch 4 \
    --fault-dropout 0.2 --fault-corrupt 0.1 \
    --fault-shard-dropout 0.3 --fault-shard-dropout-dwell 2 \
    --journal --run-id fault_hier_smoke --no-checkpoint \
    --log-dir "$fh_work/logs" --run-dir "$fh_work/runs" \
    > /dev/null || fail=1
# The private log validates (schema-v13 'fault' events with per-shard
# survivor vectors) and the --stats histogram renders.
python tools/check_events.py --stats \
    "$fh_work/logs/fault_hier_smoke.jsonl" || fail=1
# Host-replay audit: every emitted 'fault' event — per-shard
# shard_alive vector and tier-2 ladder action included — must equal
# the independent regeneration from the fault key.
python - "$fh_work" <<'PY' || fail=1
import json, os, sys
from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.core.faults import (
    fault_key, hier_fault_schedule, plan_tier2_actions
)
from attacking_federate_learning_tpu.ops.federated import (
    make_placement, tier2_assumed
)
from attacking_federate_learning_tpu.utils.lifecycle import RunJournal

work = sys.argv[1]
problems = RunJournal(os.path.join(work, "runs"),
                      "fault_hier_smoke").verify(epochs=6, test_step=5)
cfg = C.ExperimentConfig(
    dataset=C.SYNTH_MNIST, users_count=16, mal_prop=0.25, seed=3,
    aggregation="hierarchical", megabatch=4, defense="TrimmedMean",
    faults=C.FaultConfig(dropout=0.2, corrupt=0.1, shard_dropout=0.3,
                         shard_dropout_dwell=2))
place = make_placement(cfg.users_count, cfg.corrupted_count,
                       cfg.megabatch, cfg.mal_placement)
rows = hier_fault_schedule(fault_key(cfg), 0, 6, place, cfg.faults)
plan = plan_tier2_actions(
    [r["shards_alive"] for r in rows], cfg.defense,
    tier2_assumed(cfg.corrupted_count, cfg.megabatch))
events = [json.loads(line) for line in
          open(os.path.join(work, "logs", "fault_hier_smoke.jsonl"))]
flt = sorted((e for e in events if e.get("kind") == "fault"
              and not e.get("rolled_back")),
             key=lambda e: e["round"])
if len(flt) != 6:
    problems.append(f"{len(flt)} fault events, want one per round")
else:
    for got, want, act in zip(flt, rows, plan):
        for k in ("injected_dropout", "injected_corrupt", "quarantined",
                  "shards_dead", "shards_alive"):
            if int(got.get(k, -1)) != want[k]:
                problems.append(
                    f"round {want['round']}: {k} {got.get(k)} != "
                    f"replayed {want[k]}")
        if [int(x) for x in got.get("shard_alive", [])] != \
                want["shard_alive"]:
            problems.append(f"round {want['round']}: shard_alive "
                            f"{got.get('shard_alive')} != "
                            f"{want['shard_alive']}")
        if int(got.get("tier2_action", -1)) != int(act):
            problems.append(f"round {want['round']}: tier2_action "
                            f"{got.get('tier2_action')} != {int(act)}")
    if not any(r["shards_dead"] > 0 for r in rows):
        problems.append("no dead-domain round fired (raise "
                        "--fault-shard-dropout)")
status = "ok" if not problems else f"FAIL {problems}"
print(f"  fault_hier_smoke: {len(flt)} fault events, host replay "
      f"exact ({status})")
sys.exit(bool(problems))
PY
# 'report' must render the shard-domain fault table (exit 0).
python -m attacking_federate_learning_tpu.cli report \
    "$fh_work/logs/fault_hier_smoke.jsonl" || fail=1
rm -rf "$fh_work"

echo "== smoke 16/16: numerics observatory (v14 audit + drift gate) =="
nm_work="$(mktemp -d -t numerics_smoke_XXXXXX)"
# A short journaled --numerics run: the in-jit numeric-health
# observatory emits one schema-v14 'numerics' event per round
# (nonfinite by stage, norm dynamic range, tie proximity at the
# decision boundaries, Gram cancellation depth).
python -m attacking_federate_learning_tpu.cli \
    -d Krum -z 1.5 -s SYNTH_MNIST -n 12 -m 0.2 -c 16 -e 5 \
    --synth-train 256 --synth-test 64 --seed 0 \
    --numerics \
    --journal --run-id numerics_smoke --no-checkpoint \
    --log-dir "$nm_work/logs" --run-dir "$nm_work/runs" \
    > /dev/null || fail=1
# The private log validates (v14 'numerics' events included) and the
# --stats histogram renders.
python tools/check_events.py --stats \
    "$nm_work/logs/numerics_smoke.jsonl" || fail=1
# Numerics-event audit: one per round, stage counters + rollups along.
python - "$nm_work" <<'PY' || fail=1
import json, os, sys
events = [json.loads(line) for line in
          open(os.path.join(sys.argv[1], "logs",
                            "numerics_smoke.jsonl"))]
nm = [e for e in events if e.get("kind") == "numerics"]
problems = []
if len(nm) != 5:
    problems.append(f"{len(nm)} numerics events, want one per round")
if any(e.get("v", 0) < 14 for e in nm):
    problems.append("numerics event stamped below v14")
need = ("nonfinite_pre", "nonfinite_post", "nonfinite_agg",
        "range_log2", "tie_rows", "cancel_bits", "nonfinite_total",
        "tie_locked", "tie_band_ulps")
if any(k not in e for e in nm for k in need):
    problems.append("a numerics event is missing its counters")
if any(e.get("nonfinite_total", -1) != 0 for e in nm):
    problems.append("nonfinite gradients in a healthy seeded run")
status = "ok" if not problems else f"FAIL {problems}"
print(f"  numerics numerics_smoke: {len(nm)} events ({status})")
sys.exit(bool(problems))
PY
# Registry-resolved health-trajectory table must render (runs
# numerics verb, exit 0).
python -m attacking_federate_learning_tpu.cli runs \
    --run-dir "$nm_work/runs" --bench '' --progress '' \
    numerics numerics_smoke || fail=1
# Cross-impl divergence ledger round-trip: regenerate a baseline into
# the temp dir, then gate against it — a fresh ledger must gate clean
# on the same host (the checked-in NUMERICS_BASELINE.json is the
# cross-session pin; tools/numerics_gate.py).
python tools/numerics_gate.py --update \
    --baseline "$nm_work/NUMERICS_BASELINE.json" || fail=1
python tools/numerics_gate.py --strict-env \
    --baseline "$nm_work/NUMERICS_BASELINE.json" || fail=1
rm -rf "$nm_work"

if [ $fail -ne 0 ]; then
    echo "SMOKE FAILED"
else
    echo "smoke clean"
fi
exit $fail
