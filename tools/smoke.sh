#!/usr/bin/env bash
# One-liner CI smoke: event-schema validation + fault matrix + crash
# matrix + perf gate + science gate + registry selfcheck.
#
#   bash tools/smoke.sh            # all six, CPU-pinned
#   bash tools/smoke.sh --fast     # skip the fault + crash matrices
#                                  # (the two slowest legs)
#
# Legs (each independently CI-wired through tests/ as well):
#   1. tools/check_events.py over every run JSONL in logs/ (schema
#      v1-v4: round/eval/.../fault, compile/cost/heartbeat, lifecycle,
#      registry/gate) — skipped when logs/ has no .jsonl yet;
#   2. tools/fault_matrix.py — 5-round fault x defense sweep, emitted
#      'fault' events diffed against the host replay of the schedule;
#   3. tools/crash_matrix.py — supervised preempt/resume at a seeded
#      round x {fused, staged, faulted} x 2 defenses: bounded retries,
#      exactly-once journal, clean exit (tools/supervisor.py);
#   4. tools/perf_gate.py — deterministic static-HLO perf gate against
#      PERF_BASELINE.json (FLOPs/bytes exact, memory within tolerance);
#   5. tools/science_gate.py — deterministic behavioral-drift gate:
#      pinned SYNTH_MNIST_HARD defense x attack cells against
#      BEHAVIOR_BASELINE.json (exact where bit-deterministic, measured
#      ulp-tie bands elsewhere);
#   6. 'runs selfcheck' — cross-run registry over runs/ (incl. the
#      supervised-run artifacts legs 2-3 leave behind): index refresh
#      idempotence + every entry resolvable (utils/registry.py).
#
# Exit: nonzero if any leg fails.  Always CPU (the gates' baselines are
# CPU artifacts, and the matrices must not touch a TPU capture).
set -u
cd "$(dirname "$0")/.."

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu

fail=0

shopt -s nullglob
jsonls=(logs/*.jsonl)
if [ ${#jsonls[@]} -gt 0 ]; then
    echo "== smoke 1/6: check_events (${#jsonls[@]} logs) =="
    python tools/check_events.py "${jsonls[@]}" || fail=1
else
    echo "== smoke 1/6: check_events — no logs/*.jsonl yet, skipped =="
fi

crash_work=""
if [ "${1:-}" != "--fast" ]; then
    echo "== smoke 2/6: fault_matrix =="
    python tools/fault_matrix.py || fail=1
    echo "== smoke 3/6: crash_matrix (supervised preempt/resume) =="
    # Keep the matrix's run stores: leg 6 registry-checks them.
    crash_work="$(mktemp -d -t crash_matrix_XXXXXX)"
    python tools/crash_matrix.py --workdir "$crash_work" || fail=1
else
    echo "== smoke 2/6: fault_matrix — skipped (--fast) =="
    echo "== smoke 3/6: crash_matrix — skipped (--fast) =="
fi

echo "== smoke 4/6: perf_gate =="
python tools/perf_gate.py || fail=1

echo "== smoke 5/6: science_gate (behavioral drift) =="
python tools/science_gate.py || fail=1

echo "== smoke 6/6: runs selfcheck (registry) =="
python -m attacking_federate_learning_tpu.cli runs selfcheck || fail=1
if [ -n "$crash_work" ]; then
    # The registry over the crash matrix's preempt/resume artifacts:
    # every supervised cell's run store must index, list and selfcheck
    # (refresh idempotence + resolvability) like any other runs/.
    for d in "$crash_work"/*/runs; do
        [ -d "$d" ] || continue
        echo "-- registry over crash-matrix artifacts: $d --"
        python -m attacking_federate_learning_tpu.cli runs \
            --run-dir "$d" --bench '' --progress '' list || fail=1
        python -m attacking_federate_learning_tpu.cli runs \
            --run-dir "$d" --bench '' --progress '' selfcheck || fail=1
    done
    rm -rf "$crash_work"
fi

if [ $fail -ne 0 ]; then
    echo "SMOKE FAILED"
else
    echo "smoke clean"
fi
exit $fail
