"""Krum/Bulyan behavior under femnist_style feature shift vs IID.

The behavioral evidence row for the 'femnist_style' partitioner
(SURVEY §7.2 M4: FEMNIST-style non-IID): with per-client input style
transforms, HONEST clients' gradients acquire systematic structure —
their pairwise distances are no longer exchangeable noise — which is
the condition distance-based defenses are sensitive to.  Label-skew
(Dirichlet) alone never produces this on class-balanced synth data.

Measured: Krum's 30-round selection histogram (distinct honest winners,
top-1 share, malicious picks) and final accuracy, iid vs femnist_style,
for Krum and Bulyan.  Results land in GRID_RESULTS.md.

Run (CPU):  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
            python tools/femnist_style_study.py
"""

from __future__ import annotations

import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_cell(defense, part, strength=0.5, rounds=30):
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST_HARD, users_count=19, mal_prop=0.2,
        batch_size=64, epochs=rounds, defense=defense, partition=part,
        style_strength=strength, log_round_stats=True)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=8000,
                      synth_test=2000)
    exp = FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                              dataset=ds)
    sels: list[int] = []
    mal_picks = 0
    for t in range(rounds):
        exp.run_round(t)
        st = exp.last_round_stats
        if st and "krum_selected" in st:
            sels.append(int(st["krum_selected"]))
            mal_picks += int(st["malicious_selected"])
    _, correct = exp.evaluate(exp.state.weights)
    acc = 100.0 * float(correct) / len(ds.test_y)
    out = {"defense": defense, "partition": part, "final_acc": round(acc, 2)}
    if sels:
        counts = collections.Counter(sels)
        out.update(
            distinct_winners=len(counts),
            top1_share=round(counts.most_common(1)[0][1] / len(sels), 3),
            top1_client=counts.most_common(1)[0][0],
            malicious_picks=mal_picks,
            histogram={str(k): v for k, v in sorted(counts.items())})
    return out


def main():
    rows = []
    for defense in ("Krum", "Bulyan"):
        for part in ("iid", "femnist_style"):
            row = run_cell(defense, part)
            rows.append(row)
            print(json.dumps(row), flush=True)
    # Cross-row deltas the GRID_RESULTS row quotes.
    k_iid, k_sty = rows[0], rows[1]
    print(json.dumps({
        "summary": "krum_selection_shift",
        "distinct_winners_iid": k_iid.get("distinct_winners"),
        "distinct_winners_style": k_sty.get("distinct_winners"),
        "top1_share_iid": k_iid.get("top1_share"),
        "top1_share_style": k_sty.get("top1_share"),
    }), flush=True)


if __name__ == "__main__":
    main()
