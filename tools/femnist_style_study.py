"""Krum/Bulyan behavior under femnist_style feature shift vs IID.

The behavioral evidence row for the 'femnist_style' partitioner
(SURVEY §7.2 M4: FEMNIST-style non-IID): with per-client input style
transforms, HONEST clients' gradients acquire systematic structure —
their pairwise distances are no longer exchangeable noise — which is
the condition distance-based defenses are sensitive to.  Label-skew
(Dirichlet) alone never produces this on class-balanced synth data.

Measured: the 30-round selection histogram (distinct winners, top-1
share, malicious picks) and final accuracy, iid vs femnist_style, for
Krum and Bulyan.  Results land in GRID_RESULTS.md.

Instrumentation: this study used to hand-roll its selection histogram
from per-round ``last_round_stats``; it now IS one telemetry run
(cfg.telemetry) — the engine writes per-round 'defense' events + the
end-of-run 'selection_hist' to the run JSONL, and the concentration
numbers come from report.selection_concentration, the same code path as
``python -m attacking_federate_learning_tpu.cli report``.  Bulyan rows
gain a selection-mass concentration (multi-hot masks) the old
Krum-winner instrumentation could not see.

Run (CPU):  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
            python tools/femnist_style_study.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_cell(defense, part, strength=0.5, rounds=30, log_dir="logs"):
    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu import report
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.utils.metrics import RunLogger

    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST_HARD, users_count=19, mal_prop=0.2,
        batch_size=64, epochs=rounds, test_step=rounds, defense=defense,
        partition=part, style_strength=strength, telemetry=True,
        log_dir=log_dir)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=8000,
                      synth_test=2000)
    exp = FederatedExperiment(cfg, attacker=make_attacker(cfg, dataset=ds),
                              dataset=ds)
    jsonl_name = f"femnist_study_{defense}_{part}"
    jsonl_path = os.path.join(log_dir, jsonl_name + ".jsonl")
    if os.path.exists(jsonl_path):
        os.remove(jsonl_path)  # RunLogger appends; one study = one log
    with RunLogger(cfg, None, log_dir, jsonl_name=jsonl_name) as logger:
        result = exp.run(logger)

    out = {"defense": defense, "partition": part,
           "final_acc": round(result["accuracies"][-1], 2),
           "jsonl": jsonl_path}
    sel = report.selection_concentration(report.load_events([jsonl_path]))
    if sel:
        out.update(
            distinct_winners=sel["distinct_winners"],
            top1_share=round(sel["top1_share"], 3),
            top1_client=sel["top1_client"],
            malicious_share=sel["malicious_share"],
            histogram=sel["histogram"])
        if "malicious_picks" in sel:
            out["malicious_picks"] = sel["malicious_picks"]
    return out


def main():
    rows = []
    for defense in ("Krum", "Bulyan"):
        for part in ("iid", "femnist_style"):
            row = run_cell(defense, part)
            rows.append(row)
            print(json.dumps(row), flush=True)
    # Cross-row deltas the GRID_RESULTS row quotes.
    k_iid, k_sty = rows[0], rows[1]
    print(json.dumps({
        "summary": "krum_selection_shift",
        "distinct_winners_iid": k_iid.get("distinct_winners"),
        "distinct_winners_style": k_sty.get("distinct_winners"),
        "top1_share_iid": k_iid.get("top1_share"),
        "top1_share_style": k_sty.get("top1_share"),
    }), flush=True)


if __name__ == "__main__":
    main()
