#!/usr/bin/env python
"""Cross-implementation divergence ledger (ISSUE 20).

Every defense in this repo ships several implementations that are
supposed to agree — the XLA kernels, the pallas (Mosaic/interpret)
tiles, the native C++ selection engine, the host BLAS routes, the
masked/weighted fault- and staleness-seam variants, and two shipped
traversal orders for the hierarchical tier-1 sweep (vmap'd shards vs a
lax.scan over shards).  History says "supposed to agree" needs a
measured envelope, not faith: the PR 4 bulyan-blockwise cascade was a
1-ulp Gram cancellation, tests/test_native.py pins a 3/1000 <=1-ulp
tie-swap band, and tests/test_pallas.py documents reduction-order
bands for the fused distance kernels.

This tool runs every available impl pair over identical seeded
attack-shaped cohorts (a DriftAttack-shaped cohort plus a near-tie one
with an exact duplicate row and a 1-ulp twin) and records, per pair:

- ``max_ulp`` / ``n_mismatch`` / ``argmax_coord``: the raw divergence
  envelope in f32 ulp (utils/numerics.py:ulp_diff — NaN-vs-NaN is 0,
  NaN-vs-number is the 2**31 sentinel);
- ``in_tie_band``: whether every divergent coordinate sits within
  TIE_BAND_ULPS of both the other impl and the referee;
- ``verdict``: the f64-adjudicated call (defenses/oracle.py re-run in
  double as referee) — 'exact', 'tie_band', 'a_closer'/'b_closer'
  (one impl is strictly nearer the f64 truth: an accuracy asymmetry
  worth keeping), or 'split'.

Impl variants that cannot run in this environment (e.g. a native .so
that fails to build) are recorded as ``skipped`` cells with the error,
never silently dropped — availability is part of the ledger.

``tools/numerics_gate.py`` persists this matrix into
``NUMERICS_BASELINE.json`` and gates regressions (envelope growth or a
verdict flip).  Standalone:

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/impl_drift.py
    ... --json out.json      # dump the raw matrix
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SEED = 0
N, D, F = 16, 64, 3


def cohorts(seed: int = SEED) -> dict:
    """Identical attack-shaped inputs for every impl pair.

    ``drift``: honest rows N(0,1), colluders parked at mean - 1.5 sigma
    (the DriftAttack shape the behavioral tests use).  ``neartie``: the
    same cohort with an exact duplicate row and a 1-ulp perturbed twin
    — the inputs where evaluation-order differences are allowed to
    flip selections, so the ledger measures the flip instead of
    assuming it away."""
    import numpy as np

    rng = np.random.default_rng(seed)
    base = rng.normal(size=(N, D)).astype(np.float32)
    mu = base[F:].mean(axis=0)
    sd = base[F:].std(axis=0)
    drift = base.copy()
    drift[:F] = (mu - 1.5 * sd).astype(np.float32)
    tie = drift.copy()
    tie[6] = tie[5]
    tie[7] = np.nextafter(tie[5], np.float32(np.inf))
    return {"drift": drift, "neartie": tie}


def _variants() -> dict:
    """{defense: (oracle64, ref_fn, {variant: fn})} — each fn maps the
    (n, d) f32 cohort to the aggregated (d,) vector through one shipped
    implementation route."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from attacking_federate_learning_tpu.defenses.kernels import (
        bulyan, krum, trimmed_mean, trimmed_mean_of
    )
    from attacking_federate_learning_tpu.defenses.median import median
    from attacking_federate_learning_tpu.defenses.oracle import (
        np_bulyan, np_krum, np_trimmed_mean
    )

    def ones(n):
        return jnp.ones((n,), bool)

    def unit_w(n):
        return jnp.ones((n,), jnp.float32)

    def arr(fn):
        def run(G):
            return np.asarray(fn(jnp.asarray(G)), np.float32)
        return run

    # The two shipped hierarchical tier-1 traversal orders over the
    # SAME kernel: vmap'd shards (the sharded/groupwise route) vs a
    # lax.scan over shards (the sequential-megabatch route).  Both
    # reduce each 4-row shard with trimmed_mean_of(keep=2) and mean the
    # shard estimates — the scan-vs-sharded hier question at kernel
    # granularity.
    shards = 4

    def hier_vmap(G):
        Gs = G.reshape(shards, N // shards, D)
        ests = jax.vmap(lambda S: trimmed_mean_of(S, 2))(Gs)
        return jnp.mean(ests, axis=0)

    def hier_scan(G):
        Gs = G.reshape(shards, N // shards, D)

        def step(acc, S):
            return acc + trimmed_mean_of(S, 2), None

        tot, _ = jax.lax.scan(step, jnp.zeros((D,), jnp.float32), Gs)
        return tot / shards

    def hier_oracle(G64):
        ests = [np_trimmed_mean(S, N // shards, 1)
                for S in G64.reshape(shards, N // shards, D)]
        return np.mean(ests, axis=0)

    return {
        "Krum": (
            lambda G64: np_krum(G64, N, F),
            arr(lambda G: krum(G, N, F)),
            {
                "topk": arr(lambda G: krum(G, N, F, method="topk")),
                "dist_host": arr(
                    lambda G: krum(G, N, F, distance_impl="host")),
                "dist_pallas": arr(
                    lambda G: krum(G, N, F, distance_impl="pallas")),
                "scores_pallas": arr(
                    lambda G: krum(G, N, F, scores_impl="pallas")),
                "masked": arr(lambda G: krum(G, N, F, mask=ones(N))),
            }),
        "TrimmedMean": (
            lambda G64: np_trimmed_mean(G64, N, F),
            arr(lambda G: trimmed_mean(G, N, F)),
            {
                "native_host": arr(
                    lambda G: trimmed_mean(G, N, F, impl="host")),
                "pallas": arr(
                    lambda G: trimmed_mean(G, N, F, impl="pallas")),
                "masked": arr(
                    lambda G: trimmed_mean(G, N, F, mask=ones(N))),
                "weighted": arr(
                    lambda G: trimmed_mean(G, N, F, mask=ones(N),
                                           weights=unit_w(N))),
            }),
        "Median": (
            lambda G64: __import__("numpy").median(G64, axis=0),
            arr(lambda G: median(G, N, F)),
            {
                "native_host": arr(
                    lambda G: median(G, N, F, impl="host")),
                "pallas": arr(lambda G: median(G, N, F, impl="pallas")),
                "masked": arr(lambda G: median(G, N, F, mask=ones(N))),
                "weighted": arr(
                    lambda G: median(G, N, F, mask=ones(N),
                                     weights=unit_w(N))),
            }),
        "Bulyan": (
            lambda G64: np_bulyan(G64, N, F),
            arr(lambda G: bulyan(G, N, F)),
            {
                "sel_native": arr(
                    lambda G: bulyan(G, N, F, selection_impl="host")),
                "trim_native": arr(
                    lambda G: bulyan(G, N, F, trim_impl="host")),
                "masked": arr(lambda G: bulyan(G, N, F, mask=ones(N))),
            }),
        "HierTrim": (
            hier_oracle,
            arr(hier_vmap),
            {"scan": arr(hier_scan)}),
    }


def measure(seed: int = SEED, band_ulps: int | None = None) -> dict:
    """{"Defense/variant": {"cohorts": {name: adjudication-record or
    {"skipped": reason}}}} — the full ledger, deterministic for a
    (seed, environment) pair."""
    from attacking_federate_learning_tpu.utils.numerics import (
        TIE_BAND_ULPS, adjudicate
    )

    if band_ulps is None:
        band_ulps = TIE_BAND_ULPS
    cells: dict = {}
    data = cohorts(seed)
    for defense, (oracle, ref_fn, variants) in _variants().items():
        refs, oracles = {}, {}
        for cname, G in data.items():
            oracles[cname] = oracle(G.astype("float64"))
            try:
                refs[cname] = ref_fn(G)
            except Exception as e:  # ref unavailable: whole family skips
                refs[cname] = e
        for vname, fn in variants.items():
            rec: dict = {"cohorts": {}}
            for cname, G in data.items():
                if isinstance(refs[cname], Exception):
                    rec["cohorts"][cname] = {
                        "skipped": f"ref: {type(refs[cname]).__name__}: "
                                   f"{refs[cname]}"}
                    continue
                try:
                    got = fn(G)
                except Exception as e:
                    rec["cohorts"][cname] = {
                        "skipped": f"{type(e).__name__}: {e}"}
                    continue
                rec["cohorts"][cname] = adjudicate(
                    refs[cname], got, oracles[cname],
                    band_ulps=band_ulps)
            cells[f"{defense}/{vname}"] = rec
    return cells


def render(cells: dict) -> str:
    lines = [f"{'cell':<26} {'cohort':<8} {'max_ulp':>8} "
             f"{'mismatch':>8}  verdict"]
    for cell in sorted(cells):
        for cname, rec in sorted(cells[cell]["cohorts"].items()):
            if "skipped" in rec:
                lines.append(f"{cell:<26} {cname:<8} {'-':>8} {'-':>8}"
                             f"  skipped ({rec['skipped'][:40]})")
            else:
                lines.append(
                    f"{cell:<26} {cname:<8} {rec['max_ulp']:>8} "
                    f"{rec['n_mismatch']:>8}  {rec['verdict']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Cross-implementation divergence ledger: every "
                    "impl pair over identical seeded cohorts, "
                    "f64-adjudicated (utils/numerics.py).")
    p.add_argument("--seed", type=int, default=SEED)
    p.add_argument("--json", metavar="PATH",
                   help="also dump the raw matrix as JSON")
    args = p.parse_args(argv)

    cells = measure(seed=args.seed)
    print(render(cells))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(cells, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json} ({len(cells)} cells)")
    skipped = sum(1 for c in cells.values()
                  for r in c["cohorts"].values() if "skipped" in r)
    if skipped:
        print(f"note: {skipped} skipped cell-cohort(s) — availability "
              f"is recorded, not hidden")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
